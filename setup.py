"""Wheel build with prebuilt native engines.

Reference counterpart: setup.py:25-60 — the reference compiles its
OCaml engine (cpr_gym_engine.so) during build_ext and ships it inside
a platform abi3 wheel.  Here the two C++ engines (the discrete-event
oracle and the generic-MDP compiler) are g++-compiled by the same
build_lib used at runtime, so a wheel install needs no compiler on the
target machine; source installs still build on demand.

`python -m build --wheel` produces the binary wheel;
`python -m build --sdist` ships the .cpp sources only.
"""

import os

from setuptools import setup
from setuptools.command.build_py import build_py
from setuptools.dist import Distribution

HERE = os.path.dirname(os.path.abspath(__file__))


class BuildWithNative(build_py):
    """Compile both native libraries into the build tree so the wheel
    carries ready-to-load .so files next to their sources."""

    def run(self):
        super().run()
        # load the builder module directly: importing the cpr_tpu
        # package would pull jax/flax, which PEP 517 isolated build
        # envs (setuptools-only requires) don't have; native/__init__
        # itself needs only the stdlib
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "_cpr_native_build",
            os.path.join(HERE, "cpr_tpu", "native", "__init__.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        build_lib = mod.build_lib

        pkg = os.path.join(self.build_lib, "cpr_tpu", "native")
        # names and opt levels must match the runtime loaders
        # (cpr_tpu/native/__init__.py:19, cpr_tpu/mdp/generic/native.py:25)
        for src_name, so_name, opt in (
                ("oracle.cpp", "liboracle.so", "-O2"),
                ("generic_compiler.cpp", "libgeneric_compiler.so",
                 "-O3")):
            src = os.path.join(pkg, "src", src_name)
            build_lib(src, os.path.join(pkg, so_name), opt)


class BinaryDistribution(Distribution):
    """Force a platform wheel: the payload is compiled machine code
    even though there is no setuptools Extension object."""

    def has_ext_modules(self):
        return True


setup(
    cmdclass={"build_py": BuildWithNative},
    distclass=BinaryDistribution,
)
