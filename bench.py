"""Benchmark entry point — prints ONE JSON line.

Metric: Nakamoto selfish-mining env-steps/sec on one chip (BASELINE.md
target config 1/2: vmap-batched episodes, SM1 policy, episode_len=2016).
Baseline: the north-star target of 10M env-steps/sec for a full v5e-8
slice (BASELINE.json "north_star"); vs_baseline is the single-chip
measured rate over that whole-slice target, so vs_baseline > 1 means one
chip alone beats the 8-chip goal. The reference publishes no numbers
(BASELINE.md), so the north star is the only fixed point.

Robustness: a faulted axon backend can HANG rather than raise (observed
when a large kernel crashed the device), so the TPU attempt runs in a
watchdog subprocess; on timeout or failure the parent falls back to CPU
in-process — a number with a visible backend tag always gets printed.
"""

import json
import os
import subprocess
import sys
import time


def measure_nakamoto(n_envs: int, n_steps: int = 2200, reps: int = 3):
    """The headline workload: SM1 selfish mining over `n_envs` vmapped
    episode streams.  Returns (env-steps/sec, SM1 relative revenue) —
    the one definition shared by the bench and the perf-experiment
    tooling (tools/tpu_bench_experiments.py), so sweeps there measure
    exactly what the bench reports."""
    import jax
    import numpy as np

    from cpr_tpu.envs.nakamoto import NakamotoSSZ
    from cpr_tpu.params import make_params

    env = NakamotoSSZ()
    # scan n_steps past one full episode (max_steps=2016) so stats exist
    params = make_params(alpha=0.35, gamma=0.5, max_steps=2016)
    policy = env.policies["sapirshtein-2016-sm1"]
    keys = jax.random.split(jax.random.PRNGKey(0), n_envs)
    fn = jax.jit(jax.vmap(
        lambda k: env.episode_stats(k, params, policy, n_steps)))
    jax.block_until_ready(fn(keys))  # compile
    t0 = time.time()
    for _ in range(reps):
        stats = jax.block_until_ready(fn(keys))
    dt = (time.time() - t0) / reps
    atk = np.asarray(stats["episode_reward_attacker"]).mean()
    dfn = np.asarray(stats["episode_reward_defender"]).mean()
    return n_envs * n_steps / dt, atk / (atk + dfn)


# correctness guard bounds: SM1 revenue near the ES'14 closed form
# (alpha=.35, gamma=.5 -> 0.416)
SM1_GUARD = (0.38, 0.45)


def run_bench(platform_hint: str):
    """Measure and print the JSON line on whatever backend comes up."""
    import jax

    if platform_hint == "cpu":
        jax.config.update("jax_platforms", "cpu")
    devs = jax.devices()
    platform = devs[0].platform
    print(f"bench: backend={platform} devices={len(devs)}",
          file=sys.stderr)

    # batch sweep on v5e-1 (2026-07): 8192 -> 137M steps/s, 65536 ->
    # 281M, 131072 -> 306M, 262144 -> 312M (saturated); 131072 keeps
    # compile + memory comfortable at ~98% of peak
    n_envs = 131072 if platform != "cpu" else 512
    steps_per_sec, rel = measure_nakamoto(n_envs)
    assert SM1_GUARD[0] < rel < SM1_GUARD[1], \
        f"SM1 revenue {rel} off closed form 0.416"

    print(json.dumps({
        "metric": "nakamoto_selfish_mining_env_steps_per_sec_per_chip",
        "value": round(steps_per_sec),
        "unit": "env-steps/sec/chip",
        "vs_baseline": round(steps_per_sec / 10_000_000, 3),
        "backend": platform,
    }))


def _attempt(timeout: float):
    """One watchdog-bounded child run.  Returns ("ok", json_line),
    ("failed", rc), or ("hung", None).  Manual Popen because
    subprocess.run's post-kill wait() is untimed — a child stuck in
    uninterruptible device I/O would hang the parent forever."""
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--direct"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        out, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            out, err = proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            # unkillable (D-state on the device fd): abandon the child
            out, err = "", ""
        sys.stderr.write(err or "")
        return "hung", None
    sys.stderr.write(err or "")
    line = next((ln for ln in (out or "").splitlines()
                 if ln.startswith("{")), None)
    if proc.returncode == 0 and line:
        return "ok", line
    return "failed", proc.returncode


def main():
    if "--direct" in sys.argv:
        # child mode: let the default (TPU-preferring) backend come up;
        # on a host with no TPU this IS the CPU bench and its result is
        # relayed as-is (the 512-env CPU run finishes well inside the
        # watchdog timeout)
        run_bench("default")
        return
    if os.environ.get("CPR_BENCH_BACKEND") == "cpu":
        run_bench("cpu")
        return
    # watchdog: try the TPU in a subprocess so a hung backend cannot
    # stall this process past the driver's patience; a clean failure
    # (e.g. transiently claimed chip) gets one paused retry, a hang
    # (wedged device) goes straight to CPU
    timeout = float(os.environ.get("CPR_BENCH_TPU_TIMEOUT", "360"))
    for attempt in range(2):
        status, payload = _attempt(timeout)
        if status == "ok":
            print(payload)
            return
        if status == "hung":
            print(f"bench: TPU attempt hung past {timeout:.0f}s (wedged "
                  f"backend?), falling back to CPU", file=sys.stderr)
            break
        print(f"bench: TPU attempt {attempt + 1} rc={payload}",
              file=sys.stderr)
        if attempt == 0:
            time.sleep(15.0)  # transiently claimed chip may free up
    else:
        print("bench: TPU attempts failed, falling back to CPU",
              file=sys.stderr)
    run_bench("cpu")


if __name__ == "__main__":
    main()
