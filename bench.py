"""Benchmark entry point — prints ONE JSON line.

Metric: Nakamoto selfish-mining env-steps/sec on one chip (BASELINE.md
target config 1/2: vmap-batched episodes, SM1 policy, episode_len=2016).
Baseline: the north-star target of 10M env-steps/sec for a full v5e-8
slice (BASELINE.json "north_star"); vs_baseline is the single-chip
measured rate over that whole-slice target, so vs_baseline > 1 means one
chip alone beats the 8-chip goal. The reference publishes no numbers
(BASELINE.md), so the north star is the only fixed point.
"""

import json
import sys
import time

import numpy as np


def _init_backend():
    """Initialize a JAX backend, preferring TPU, with diagnostics + retry.

    Round-1 postmortem: the driver bench run died with rc=1 ("Unable to
    initialize backend 'axon': UNAVAILABLE") and recorded no number.  A
    transiently claimed chip must not zero out the round's evidence, so:
    try TPU, retry once after a pause, then fall back to CPU — a number on
    CPU with a visible backend tag beats no number at all.
    """
    import jax

    last_err = None
    for attempt in range(2):
        try:
            devs = jax.devices()
            print(f"bench: backend={devs[0].platform} devices={len(devs)}",
                  file=sys.stderr)
            return jax, devs[0].platform
        except Exception as e:  # backend init failure (e.g. chip claimed)
            last_err = e
            print(f"bench: backend init attempt {attempt + 1} failed: {e!r}",
                  file=sys.stderr)
            time.sleep(15.0)
    print("bench: TPU unavailable, falling back to CPU", file=sys.stderr)
    try:
        jax.config.update("jax_platforms", "cpu")
        devs = jax.devices()
        return jax, devs[0].platform
    except Exception as e:
        print(f"bench: CPU fallback also failed: {e!r}; "
              f"first error: {last_err!r}", file=sys.stderr)
        raise


def main():
    jax, platform = _init_backend()
    from cpr_tpu.envs.nakamoto import NakamotoSSZ
    from cpr_tpu.params import make_params

    env = NakamotoSSZ()
    params = make_params(alpha=0.35, gamma=0.5, max_steps=2016)
    policy = env.policies["sapirshtein-2016-sm1"]

    # scan past one full episode (max_steps=2016) so episode stats exist
    n_envs, n_steps = (8192, 2200) if platform != "cpu" else (512, 2200)
    keys = jax.random.split(jax.random.PRNGKey(0), n_envs)
    fn = jax.jit(jax.vmap(lambda k: env.episode_stats(k, params, policy, n_steps)))
    jax.block_until_ready(fn(keys))  # compile
    reps = 3
    t0 = time.time()
    for _ in range(reps):
        stats = jax.block_until_ready(fn(keys))
    dt = (time.time() - t0) / reps
    steps_per_sec = n_envs * n_steps / dt

    # correctness guard: SM1 revenue near the ES'14 closed form
    atk = np.asarray(stats["episode_reward_attacker"]).mean()
    dfn = np.asarray(stats["episode_reward_defender"]).mean()
    rel = atk / (atk + dfn)
    assert 0.38 < rel < 0.45, f"SM1 revenue {rel} off closed form 0.416"

    print(json.dumps({
        "metric": "nakamoto_selfish_mining_env_steps_per_sec_per_chip",
        "value": round(steps_per_sec),
        "unit": "env-steps/sec/chip",
        "vs_baseline": round(steps_per_sec / 10_000_000, 3),
        "backend": platform,
    }))


if __name__ == "__main__":
    main()
