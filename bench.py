"""Benchmark entry point — prints ONE JSON line.

Metric: Nakamoto selfish-mining env-steps/sec on one chip (BASELINE.md
target config 1/2: vmap-batched episodes, SM1 policy, episode_len=2016).
Baseline: the north-star target of 10M env-steps/sec for a full v5e-8
slice (BASELINE.json "north_star"); vs_baseline is the single-chip
measured rate over that whole-slice target, so vs_baseline > 1 means one
chip alone beats the 8-chip goal. The reference publishes no numbers
(BASELINE.md), so the north star is the only fixed point.

Robustness: a faulted axon backend can HANG rather than raise (observed
when a large kernel crashed the device), so every TPU attempt runs
under `cpr_tpu/supervisor` — heartbeat-watchdogged child, bounded
probe-before-run, probe-gated warm restart — and on escalation the
parent falls back to CPU in-process, so a number with a visible
backend tag always gets printed.
"""

import glob
import json
import os
import re
import sys
import time

from cpr_tpu import device_metrics, supervisor, telemetry
# GuardFailure moved to the shared resilience layer (same taxonomy as
# the training/VI retry paths); re-exported here so bench.GuardFailure
# keeps working for callers and the GUARD_RC child protocol
from cpr_tpu.resilience import GuardFailure, TransientFault


# v5e (TPU v5 lite) single-chip peaks for the roofline fields: bf16
# matmul throughput and HBM bandwidth (public spec; the MXU peak is
# what the nakamoto env's pure-compute path is measured against)
V5E_PEAK_FLOPS = 197e12
V5E_PEAK_BYTES = 819e9


def _roofline(fn, args, n_env_steps: int):
    """Compile-time cost model of one benchmark call: XLA's
    cost_analysis gives flops + HBM bytes accessed; divided by the
    env-steps one call consumes they become per-step intensities, and
    at the measured rate they attribute the gap to compute vs memory
    vs per-op overhead (VERDICT r4 #8 — '0.18x a CPU core' was
    unattributable without them).  Returns {} when the backend does
    not expose the analysis."""
    try:
        import jax

        # the analysis pass costs one extra XLA compile; skip it on CPU
        # (fallback rows + the test suite discard the fields, and the
        # peaks it would be compared against are the chip's)
        if (jax.devices()[0].platform == "cpu"
                and os.environ.get("CPR_BENCH_ROOFLINE") != "force"):
            return {}
        compiled = jax.jit(fn).lower(*args).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0))
        bts = float(ca.get("bytes accessed", 0.0))
        if flops <= 0 and bts <= 0:
            return {}
        return {
            "flops_per_step": round(flops / n_env_steps, 1),
            "bytes_per_step": round(bts / n_env_steps, 1),
        }
    except Exception:  # noqa: BLE001 — roofline is best-effort metadata
        return {}


def _roofline_utilization(row: dict, rate: float):
    """Fold measured rate into the cost model: fraction of the chip's
    MXU / HBM peaks actually sustained, and which wall the workload is
    against ('overhead' when both are <2% — per-op dispatch dominates,
    the regime the active-set redesign attacks)."""
    if "bytes_per_step" not in row:
        return {}
    mxu = rate * row["flops_per_step"] / V5E_PEAK_FLOPS
    hbm = rate * row["bytes_per_step"] / V5E_PEAK_BYTES
    bound = ("compute" if mxu >= 0.5 else
             "memory" if hbm >= 0.5 else
             "mixed" if max(mxu, hbm) >= 0.02 else "overhead")
    return {"mxu_frac": round(mxu, 4), "hbm_frac": round(hbm, 4),
            "bound": bound}


def _bench_devices() -> int:
    """CPR_BENCH_DEVICES: how many devices the hot loops span (1 =
    single-device, the default).  Rows stamp the value as `n_devices`
    so ledger-v4 fingerprints separate device counts."""
    return max(1, int(os.environ.get("CPR_BENCH_DEVICES", "1") or 1))


def _bench_mesh(axis: str = "d"):
    """The 1-D mesh the measured loops shard over when
    CPR_BENCH_DEVICES > 1 (first N visible devices; docs/SCALING.md),
    else None.  Asking for more devices than the host exposes is a
    deterministic config error — GuardFailure, so the supervisor
    neither retries nor papers over it with a CPU run."""
    n = _bench_devices()
    if n <= 1:
        return None
    import jax

    from cpr_tpu.parallel import default_mesh

    devs = jax.devices()
    if len(devs) < n:
        raise GuardFailure(
            f"CPR_BENCH_DEVICES={n} but only {len(devs)} device(s) "
            f"visible to JAX")
    return default_mesh(axis, devices=devs[:n])


def _measure_episodes(env, policy_name: str, n_envs: int, n_steps: int,
                      reps: int, max_steps: int, chunk: int | None = None,
                      label: str = "episodes"):
    """Shared episode-batch harness: warm one compile, time `reps`
    batched episode_stats kernels, return (env-steps/sec, attacker
    relative revenue).  Every episode config below measures through
    this one definition — also shared with the perf-experiment tooling
    (tools/tpu_bench_experiments.py), so sweeps there measure exactly
    what the bench reports.  `chunk` splits the episode scan across
    device calls (axon kills single executions past ~60-75 s; see
    JaxEnv.make_episode_stats_fn).  Phase spans (compile / warmup /
    measure) go to the telemetry stream; CPR_PROFILE_DIR additionally
    captures a jax.profiler trace of the warm measured reps."""
    import jax
    import numpy as np

    from cpr_tpu.params import make_params

    tele = telemetry.current()
    params = make_params(alpha=0.35, gamma=0.5, max_steps=max_steps)
    policy = env.policies[policy_name]
    keys = jax.random.split(jax.random.PRNGKey(0), n_envs)
    collect = device_metrics.enabled()
    # CPR_BENCH_DEVICES > 1: the episode batch shards over the mesh
    # (same driver, GSPMD-partitioned); the row's n_devices stamp keeps
    # the banked rate in its own per-device-count fingerprint
    fn = env.make_episode_stats_fn(params, policy, n_steps, chunk=chunk,
                                   collect_metrics=collect,
                                   mesh=_bench_mesh())
    spec = getattr(fn, "metrics_spec", None)
    # compile_watch emits one schema-v2 `compile` event per traced
    # program (fn name, arg shapes, trace/compile seconds) — so the
    # trace says WHAT compiled during this span, not just how long
    with telemetry.compile_watch(), tele.span("compile") as sp:
        sp.fence(fn(keys))  # compile + warmup in one first call
    acc_total = None
    with tele.span("measure", env_steps=reps * n_envs * n_steps) as sp, \
            telemetry.maybe_profile(label):
        for _ in range(reps):
            out = jax.block_until_ready(fn(keys))
            if collect:
                stats, acc = out
                acc_total = (acc if acc_total is None
                             else spec.merge(acc_total, acc))
            else:
                stats = out
    if collect:
        # the single host readback of the in-graph accumulator, after
        # the measured span closed
        device_metrics.emit(label, spec, acc_total, reps=reps)
    dt = sp.dur_s / reps
    atk = np.asarray(stats["episode_reward_attacker"]).mean()
    dfn = np.asarray(stats["episode_reward_defender"]).mean()

    # roofline model of one representative chunk (compile-only pass)
    steps_ana = min(chunk or n_steps, n_steps)

    def ana(k):
        return jax.vmap(lambda kk: env.episode_stats(
            kk, params, policy, steps_ana))(k)

    extras = dict(_roofline(ana, (keys,), n_envs * steps_ana),
                  n_devices=_bench_devices())
    return n_envs * n_steps / dt, atk / (atk + dfn), extras


def measure_nakamoto(n_envs: int, n_steps: int = 2200, reps: int = 3):
    """The headline workload (BASELINE config 1): SM1 selfish mining
    over `n_envs` vmapped episode streams; n_steps scans past one full
    episode (max_steps=2016) so stats exist."""
    from cpr_tpu.envs.nakamoto import NakamotoSSZ

    return _measure_episodes(NakamotoSSZ(), "sapirshtein-2016-sm1",
                             n_envs, n_steps, reps, max_steps=2016,
                             label="nakamoto_sm1")


def _chunk_scaled(n_envs: int, base_chunk: int, base_envs: int):
    """`base_chunk` at its measured-good `base_envs`, shrinking
    proportionally for LARGER batches so per-call device time stays
    inside the axon worker's ~60-75 s ceiling.  Only shrink — a first
    attempt at a time-budget formula also GREW bk's chunk 128→183 at
    its measured batch and halved throughput on chip (mechanism not
    chased; chunk length is empirical).  Smaller batches get longer
    chunks naturally via make_episode_stats_fn's chunk>=n_steps
    unchunked path."""
    return max(16, base_chunk * base_envs // max(n_envs, base_envs))


def measure_bk(n_envs: int, n_steps: int = 128, reps: int = 3):
    """BASELINE config 2: Bk k=8 vote-withholding (get-ahead), vmap'd
    episode batch.  Round-4 sweep (tools/tpu_dag_sweep.py): the rate
    peaks at 8192 envs x 128-step episodes (capacity 264; DAG capacity
    scales with episode length and every per-step op is O(capacity), so
    shorter episodes are structurally cheaper) — ~558k steps/s on chip,
    0.95x the single-core C++ oracle.  Revenue is episode-length
    invariant within +-0.003 down to 128 steps (the 120-step rel 0.302
    vs 248-step 0.300 here; 64-step episodes measure 612k but drift to
    0.307, so 128 is the honest floor).  4096/10240/12288/16384 envs
    measure 550k/552k/497k/496k."""
    from cpr_tpu.envs.bk import BkSSZ

    # active-set ring window (round-5 redesign): per-step cost is
    # O(window), not O(2 x episode_len); 128 slots cover a ~14-deep
    # fork with k=8 votes (bit-for-bit episode parity vs full capacity
    # on CPU, tests/test_dag_ring.py; the revenue guard re-checks on
    # chip).  CPR_BK_WINDOW=0 falls back to full capacity.
    window = int(os.environ.get("CPR_BK_WINDOW", "128")) or None
    env = BkSSZ(k=8, incentive_scheme="constant", max_steps_hint=n_steps,
                window=window)
    chunk = None if n_envs <= 8192 else _chunk_scaled(n_envs, 128, 8192)
    rate, rel, extras = _measure_episodes(
        env, "get-ahead", n_envs, n_steps, reps,
        max_steps=n_steps - 8, chunk=chunk, label="bk8_withholding")
    return rate, rel, dict(extras, window=window or 0)


def measure_ethereum(n_envs: int, n_steps: int = 4096, reps: int = 2):
    """BASELINE config 3: Ethereum byzantium uncle-mining attack (FN'19
    policy), 65k batched episodes.  The 65k figure is EPISODES, not
    envs: 4096 envs x 120-step episodes is the measured-fastest shape
    (round-4 sweep: 168k steps/s at capacity 136; 8192 envs 165k, the
    256-step/capacity-264 shape 120k, the old 16384-env shape 42k, and
    65536 envs killed the axon worker).  fn19 revenue is episode-length
    invariant here (0.379 at 120 steps vs 0.380 at 248).  The config
    runs 4096 auto-resetting streams for 4096 steps in 128-step chunks
    — 4096 * 4096 / 120 ~ 140k completed episodes per rep."""
    from cpr_tpu.envs.ethereum import EthereumSSZ

    # active-set ring window (see measure_bk): per-step cost is
    # O(window); 128 slots cover the fn19 fork plus the 6-generation
    # uncle lookback.  CPR_ETH_WINDOW=0 falls back to full capacity.
    window = int(os.environ.get("CPR_ETH_WINDOW", "128")) or None
    env = EthereumSSZ("byzantium", max_steps_hint=128, window=window)
    rate, rel, extras = _measure_episodes(
        env, "fn19", n_envs, n_steps, reps, max_steps=120, chunk=128,
        label="ethereum_uncle_attack")
    return rate, rel, dict(extras, window=window or 0)


def measure_tailstorm_ppo(n_envs: int, rollout_len: int = 128,
                          reps: int = 2):
    """BASELINE config 4: Tailstorm selfish-mining PPO — the training
    driver's actual train_step (rollout with policy-net inference +
    env.step + auto-reset, then GAE + minibatch updates), measured in
    env-steps/sec; one call consumes rollout_len * n_envs steps.
    120-step episodes (capacity 264) per the round-4 capacity sweep:
    93k steps/s vs 72k at the 248-step/capacity-520 shape, same
    entropy check."""
    import jax
    import numpy as np

    from cpr_tpu.envs.registry import get_sized
    from cpr_tpu.params import make_params
    from cpr_tpu.train.ppo import PPOConfig, make_train

    # active-set ring window (see measure_bk); CPR_TS_WINDOW=0 -> full.
    # get_sized forwards kwargs, so the bench measures exactly the
    # registered key's config (memo key includes the kwargs)
    window = int(os.environ.get("CPR_TS_WINDOW", "128")) or None
    env = get_sized("tailstorm-8-discount-heuristic", 128, window=window)
    params = make_params(alpha=0.35, gamma=0.5, max_steps=120)
    cfg = PPOConfig(n_envs=n_envs, n_steps=rollout_len)
    init_fn, train_step = make_train(env, params, cfg)
    tele = telemetry.current()
    carry = jax.jit(init_fn)(jax.random.PRNGKey(0))
    mesh = _bench_mesh("dp")
    if mesh is not None:
        # data-parallel sampling: env batch sharded over "dp" exactly
        # like train(mesh=...) does it (cpr_tpu/train/ppo.py)
        from cpr_tpu.parallel import shard_envs
        ts, env_state, obs, key = carry
        carry = (ts, shard_envs(mesh, env_state, "dp"),
                 shard_envs(mesh, obs, "dp"), key)
    step = jax.jit(train_step)
    with telemetry.compile_watch(), tele.span("compile") as sp:
        carry, _ = step(carry)  # compile + warm
        sp.fence(carry)
    with tele.span("measure", env_steps=reps * n_envs * rollout_len) as sp, \
            telemetry.maybe_profile("tailstorm_ppo_train"):
        for _ in range(reps):
            carry, metrics = step(carry)
            jax.block_until_ready(carry)
    acc = metrics.pop("device_metrics", None)
    if acc is not None:
        # last rep's update accumulator (per-train_step, not cumulative)
        device_metrics.emit("tailstorm_ppo_train",
                            train_step.metrics_spec, acc)
    dt = sp.dur_s / reps
    ent = float(np.asarray(metrics["entropy"]))
    extras = _roofline(train_step, (carry,), n_envs * rollout_len)
    return n_envs * rollout_len / dt, ent, dict(
        extras, window=window or 0, n_devices=_bench_devices())


def measure_netsim(n_envs: int, n_activations: int = 10_000,
                   reps: int = 3):
    """netsim honest-net sweep (cpr_tpu/netsim): `n_envs` vmapped lanes
    of the 10-node honest clique (nakamoto, activation_delay 30,
    propagation 1.0, independent seeds) execute as one device program.
    Rate counts activations/sec across lanes; the check is the mean
    orphan rate, guarded against the oracle's measured band at this
    grid point (PARITY.md: ~0.029).  The engine's own netsim:run spans
    and the `netsim` point event land in the telemetry artifact."""
    import numpy as np

    from cpr_tpu import netsim
    from cpr_tpu.network import symmetric_clique
    from cpr_tpu.telemetry import now

    net = symmetric_clique(10, activation_delay=30.0,
                           propagation_delay=1.0)
    eng = netsim.Engine(net, protocol="nakamoto",
                        activations=n_activations, mesh=_bench_mesh())
    seeds = list(range(n_envs))
    delays = [30.0] * n_envs
    t0 = now()
    out = eng.run(seeds, delays)            # compile + first run
    first_s = now() - t0
    best = first_s
    for _ in range(reps):
        t0 = now()
        out = eng.run(seeds, delays)
        best = min(best, now() - t0)
    orphan = float(np.mean(
        1.0 - out["progress"] / float(n_activations)))
    drops = int(out["drop_q"].sum() + out["drop_p"].sum()
                + out["drop_b"].sum() + out["win_miss"].sum())
    if drops:
        raise GuardFailure(f"netsim_sweep: {drops} capacity drops")
    return n_envs * n_activations / best, orphan, dict(
        lanes=n_envs, activations_per_lane=n_activations,
        compile_and_first_run_s=round(first_s, 3),
        best_rep_s=round(best, 4), n_devices=_bench_devices())


def measure_mdp_grid(n_envs: int, mfl: int = 12, horizon: int = 100,
                     stop_delta: float = 1e-6):
    """Grid-batched exact-MDP solving (cpr_tpu/mdp/grid.py): one
    parametric compile per protocol (fc16 + aft20 at fork-length
    `mfl`) and ONE vmapped/sharded VI program per protocol over an
    `n_envs`-point (alpha, gamma) grid, the batch seam the serial
    battery lacks (one compile + one solve per point).  Rate counts
    solved grid points/sec across both protocols (solve only — the
    host-side compile is amortized once per protocol and reported in
    extras); the check is the fc16 optimal revenue at the hardest
    grid corner (max alpha, max gamma), guarded against the exact
    solve's value at this shape."""
    import numpy as np

    from cpr_tpu.mdp.grid import (compile_protocol, grid_value_iteration,
                                  param_ptmdp)
    from cpr_tpu.telemetry import now

    gammas = (0.25, 0.75)
    n_alphas = max(2, n_envs // len(gammas))
    alphas = [round(float(a), 6)
              for a in np.linspace(0.15, 0.45, n_alphas)]
    mesh = _bench_mesh()
    points = solve_s = 0
    check = 0.0
    extras = dict(protocols="fc16+aft20", mfl=mfl,
                  grid=f"{n_alphas}x{len(gammas)}",
                  n_devices=_bench_devices())
    for proto in ("fc16", "aft20"):
        t0 = now()
        pm = param_ptmdp(compile_protocol(proto, cutoff=mfl),
                         horizon=horizon)
        extras[f"{proto}_compile_s"] = round(now() - t0, 3)
        vi = grid_value_iteration(pm, alphas, gammas,
                                  stop_delta=stop_delta, mesh=mesh,
                                  protocol=proto, cutoff=mfl)
        if not bool(vi["grid_converged"].all()):
            raise GuardFailure(
                f"mdp_grid: {proto} left "
                f"{int((~vi['grid_converged']).sum())} points "
                f"unconverged")
        points += len(vi["grid_points"])
        solve_s += vi["vi_time"]
        extras[f"{proto}_sweeps"] = int(vi["vi_iter"])
        if proto == "fc16":
            # hardest corner: alpha-major point list ends at
            # (max alpha, max gamma)
            check = float(vi["grid_revenue"][-1])
    extras["point_solve_s"] = round(solve_s / points, 4)
    return points / solve_s, check, extras


def measure_mdp_state_shard(n_envs: int, horizon: int = 100,
                            stop_delta: float = 1e-6):
    """State-sharded exact-MDP solving (cpr_tpu/parallel/
    state_shard.py): ONE fc16 solve at fork-length `n_envs`, its
    state space partitioned over the CPR_BENCH_DEVICES mesh
    (source-block COO shards, per-sweep value-halo all_gather) —
    the capacity seam for models whose working set exceeds one
    device.  Rate counts state backups/sec (n_states x sweeps /
    solve_s, the same `mdp_states_per_sec` the solve's v13 telemetry
    event banks); the check is the fc16 optimal revenue at the
    hardest grid corner (0.45, 0.75), same band as `mdp_grid`."""
    from cpr_tpu.mdp.explicit import MDP
    from cpr_tpu.mdp.grid import compile_protocol, param_ptmdp
    from cpr_tpu.parallel import (sharded_state_value_iteration,
                                  state_halo_bytes)

    alpha, gamma = 0.45, 0.75
    pm = param_ptmdp(compile_protocol("fc16", cutoff=n_envs),
                     horizon=horizon)
    m = pm.mdp
    sv = pm._monomial(pm.start_coef, pm.start_expo, alpha, gamma)
    tm = MDP(n_states=m.n_states, n_actions=m.n_actions,
             start={int(s): float(v)
                    for s, v in zip(pm.start_ids, sv)},
             src=m.src, act=m.act, dst=m.dst,
             prob=pm.revalue(alpha, gamma),
             reward=m.reward, progress=m.progress).tensor()
    mesh = _bench_mesh()
    n = _bench_devices()
    vi = sharded_state_value_iteration(
        tm, mesh, stop_delta=stop_delta, pad_states=True,
        protocol="fc16", cutoff=n_envs)
    rate = tm.n_states * vi["vi_iter"] / vi["vi_time"]
    check = (tm.start_value(vi["vi_value"])
             / tm.start_value(vi["vi_progress"]))
    extras = dict(protocol="fc16", mfl=n_envs, n_states=tm.n_states,
                  sweeps=vi["vi_iter"],
                  solve_s=round(vi["vi_time"], 4),
                  n_devices=n, state_shards=vi["vi_state_shards"],
                  halo_bytes=state_halo_bytes(
                      tm.n_states + (-tm.n_states % n), n,
                      tm.prob.dtype))
    return rate, check, extras


def measure_mdp_compile(n_envs: int):
    """Frontier-batched MDP compilation (cpr_tpu/mdp/frontier.py):
    one compile of the generic bitcoin model at dag_size_cutoff
    `n_envs` through whole-frontier rounds — columnar successor
    collect, vectorized per-round validation, and (when
    CPR_MDP_COMPILE_WORKERS > 1) multi-core frontier expansion.  Rate
    counts discovered states/sec; the check is the transitions-per-
    state ratio of the compiled MDP, which is exact per cutoff (any
    drift means the compile emitted a different state graph)."""
    from cpr_tpu.mdp.frontier import FrontierCompiler, resolve_workers
    from cpr_tpu.mdp.generic import SingleAgent, get_protocol
    from cpr_tpu.telemetry import now

    model = SingleAgent(get_protocol("bitcoin"), alpha=0.3, gamma=0.5,
                        collect_garbage="simple", merge_isomorphic=True,
                        truncate_common_chain=True,
                        dag_size_cutoff=n_envs)
    fc = FrontierCompiler(model, protocol="bitcoin", cutoff=n_envs)
    t0 = now()
    m = fc.mdp()
    dt = now() - t0
    extras = dict(protocol="bitcoin", cutoff=n_envs,
                  states=m.n_states, transitions=m.n_transitions,
                  n_workers=resolve_workers(), compile_s=round(dt, 4))
    return m.n_states / dt, m.n_transitions / m.n_states, extras


def measure_attack_sweep(n_envs: int, n_activations: int = 1500,
                         reps: int = 3):
    """Adversary-in-the-network sweep (cpr_tpu/netsim/attack.py):
    `n_envs` attack lanes — (seed, delay, alpha, policy) tuples over a
    4-node clique with the attacker at node 0 — execute as ONE
    vmapped/sharded device program per rep (alpha and policy are lane
    inputs, so the whole grid shares one executable).  Rate counts
    lanes/sec on the best rep; the check is the honest-policy
    attacker's relative revenue at alpha=1/3, which must track its
    compute share (orphans at propagation 1.0 cost well under the
    guard slack).  The engine's own attack:run spans and the v11
    `attack_sweep` typed event land in the telemetry artifact, where
    the perf ledger lifts them into attack_sweep_lanes_per_sec rows."""
    import numpy as np

    from cpr_tpu.netsim.attack import AttackEngine
    from cpr_tpu.network import symmetric_clique
    from cpr_tpu.telemetry import now

    net = symmetric_clique(4, activation_delay=30.0,
                           propagation_delay=1.0)
    policies = ("honest", "sapirshtein-2016-sm1")
    alpha_axis = (0.15, 0.25, 0.33, 0.45)
    eng = AttackEngine(net, activations=n_activations,
                       policies=policies, topology="clique-4",
                       mesh=_bench_mesh())
    # lane grid: alpha-major over alpha_axis x policies, cycled to
    # n_envs so every point gets n_envs/8 independent seeds
    grid = [(a, p) for a in alpha_axis for p in range(len(policies))]
    lanes = [grid[i % len(grid)] for i in range(n_envs)]
    seeds = list(range(n_envs))
    delays = [30.0] * n_envs
    al = [a for a, _ in lanes]
    pi = [p for _, p in lanes]
    t0 = now()
    out = eng.run(seeds, delays, al, pi)     # compile + first run
    first_s = now() - t0
    best = first_s
    for _ in range(reps):
        t0 = now()
        out = eng.run(seeds, delays, al, pi)
        best = min(best, now() - t0)
    drops = int(out["drop_q"].sum() + out["drop_p"].sum()
                + out["drop_b"].sum() + out["win_miss"].sum())
    if drops:
        raise GuardFailure(f"attack_sweep: {drops} capacity drops")
    atk = np.asarray(out["reward_attacker"], dtype=float)
    dfn = np.asarray(out["reward_defender"], dtype=float)
    rel = atk / np.maximum(atk + dfn, 1e-9)
    hon = [rel[i] for i, ln in enumerate(lanes) if ln == (0.33, 0)]
    check = float(np.mean(hon))
    return n_envs / best, check, dict(
        lanes=n_envs, activations_per_lane=n_activations,
        grid="4 alphas x 2 policies", topology="clique-4",
        compile_and_first_run_s=round(first_s, 3),
        best_rep_s=round(best, 4), n_devices=_bench_devices())


# correctness guard bounds: SM1 revenue near the ES'14 closed form
# (alpha=.35, gamma=.5 -> 0.416)
SM1_GUARD = (0.38, 0.45)

# child exit code distinguishing a correctness-guard failure from a
# device fault / infrastructure failure (any other nonzero rc)
GUARD_RC = 3


def _child_cmd(mode: str, extra=None) -> list:
    """Command line for one bench child (this file, child mode)."""
    return ([sys.executable, os.path.abspath(__file__), mode]
            + (extra or []))


def _supervisor_config(timeout: float, **kw) -> "supervisor.SupervisorConfig":
    """The bench's supervision policy, CPR_SUPERVISOR_* overridable:
    GUARD_RC children are GuardFailure (never retried, never masked —
    the invariant that device faults cannot masquerade as guard
    failures lives in the GUARD_RC exit path of run_one/main), a hang
    or heartbeat stall earns at most one probe-gated warm restart, any
    other child failure is a transient chip claim worth one paused
    re-attempt."""
    return supervisor.SupervisorConfig.from_env(
        wall_timeout_s=timeout, **kw)


def _cpu_baseline(name: str):
    """Single-core C++-oracle steps/s for `name` from BASELINE_CPU.json
    (tools/cpu_baseline.py), or None if not banked.  The divisor for
    every row's vs_cpu_baseline: the reference's execution model is one
    sim per core, so >1.0 means one chip beats the reference engine's
    core-for-core rate on that workload."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE_CPU.json")
    try:
        with open(path) as f:
            cfgs = json.load(f)["configs"]
        return float(cfgs[name]["single_core_steps_per_sec"])
    except (OSError, KeyError, ValueError, TypeError):
        return None


def _last_known_tpu(metric_prefix: str, root: str | None = None):
    """Most recent banked on-chip row whose metric starts with
    `metric_prefix`: scans the BENCH_*.json artifacts next to this file
    (driver rounds carry one parsed row; BENCH_CONFIGS* carry row
    lists), newest round wins.  The context a CPU-fallback row ships so
    it can never be misread as a regression (VERDICT weak #1).

    Rows tagged `outage` (or carrying a `fallback_reason`/`error`) are
    never candidates even if they claim backend "tpu": a row banked
    during a chip outage describes the outage, not the chip — the same
    exclusion the perf gate's baseline scan applies
    (cpr_tpu/perf/gate.baseline_rows)."""
    if root is None:
        root = os.path.dirname(os.path.abspath(__file__))
    best = None  # (round, row, source file)
    for path in sorted(glob.glob(os.path.join(root, "BENCH*.json"))):
        base = os.path.basename(path)
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(data, dict):
            rnd = int(data.get("n", -1))
            rows = [data.get("parsed")]
        else:
            m = re.search(r"r(\d+)", base)
            rnd = int(m.group(1)) if m else -1
            rows = data
        for row in rows:
            if (not isinstance(row, dict)
                    or row.get("backend") != "tpu"
                    or row.get("outage") or row.get("fallback_reason")
                    or row.get("error")
                    or not str(row.get("metric", "")).startswith(
                        metric_prefix)):
                continue
            if best is None or rnd > best[0]:
                best = (rnd, row, base)
    if best is None:
        return None
    rnd, row, base = best
    return {"value": row.get("value"), "unit": row.get("unit"),
            "source": base, "round": rnd}


def _outage_fields(reason: str, metric_prefix: str):
    """Machine-readable chip-outage tags for a CPU-fallback (or error)
    row: `outage` + `fallback_reason` say WHY the backend is not tpu,
    `last_known_tpu` says what the chip measured when it was last seen
    — so the artifact carries its own context (VERDICT weak #1: the
    r05 CPU row read cold as a 306x regression)."""
    # always present (null = never measured on chip) so outage-row
    # consumers need no key-existence special case
    fields = {"outage": True, "fallback_reason": reason,
              "last_known_tpu": _last_known_tpu(metric_prefix)}
    telemetry.current().event("tpu_outage", reason=reason,
                              metric_prefix=metric_prefix)
    return fields


_PRNG_IMPLS = ("threefry2x32", "rbg")


def _prng_choice() -> str:
    """Validated CPR_BENCH_PRNG value (rbg|threefry2x32[:partitionable])
    or the default.  Raises early — main() checks this BEFORE spawning
    watchdogged TPU attempts, so a typo fails fast instead of burning
    the whole watchdog budget (or silently measuring the wrong PRNG)."""
    # default = the measured winner of the on-chip PRNG sweep
    # (tools/tpu_bench_experiments.py, 2026-07-31: threefry 304M,
    # threefry:partitionable 313M, rbg 311M steps/s at 131072 envs)
    choice = os.environ.get("CPR_BENCH_PRNG", "threefry2x32:partitionable")
    impl, _, part = choice.partition(":")
    if impl not in _PRNG_IMPLS or part not in ("", "partitionable") \
            or (part and impl != "threefry2x32"):
        # :partitionable is a threefry-only knob — accepting it on rbg
        # would tag rows with a configuration that changed nothing
        raise SystemExit(
            f"bench: bad CPR_BENCH_PRNG '{choice}' "
            f"(want rbg|threefry2x32[:partitionable])")
    return choice


def _apply_prng_choice():
    """Apply the validated PRNG choice — the knob
    tools/tpu_bench_experiments.py sweeps, so a measured winner folds
    in without code changes."""
    import jax

    impl, _, part = _prng_choice().partition(":")
    jax.config.update("jax_default_prng_impl", impl)
    if part == "partitionable":
        jax.config.update("jax_threefry_partitionable", True)


def _bank_and_gate(row: dict):
    """Bank one final row into the perf ledger and self-gate it against
    the banked history (cpr_tpu/perf).  Advisory by construction: the
    bench's contract is the JSON line on stdout, so a ledger or gate
    problem prints a warning and never costs the measurement.  Called
    only where FINAL rows exist — run_bench, run_configs, and the
    run_configs_isolated parent (run_one children are not final: the
    parent may still stamp outage/worker-health fields, and banking
    both shapes would double-count the run)."""
    try:
        from cpr_tpu import perf

        result = perf.bank_and_gate(
            row, root=os.path.dirname(os.path.abspath(__file__)))
        line = (f"perf-gate: {result['metric']} [{result['backend']}] "
                f"{result['verdict'].upper()}")
        if result.get("reason"):
            line += f" ({result['reason']})"
        print(line, file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — advisory, never fatal
        print(f"perf-gate: skipped ({e})", file=sys.stderr)


def run_bench(platform_hint: str, fallback_reason: str | None = None):
    """Measure and print the JSON line on whatever backend comes up.
    `fallback_reason` (set by main()'s watchdog when the TPU attempts
    died) tags the row as a chip outage rather than a regression."""
    supervisor.maybe_start_heartbeat()
    with supervisor.child_phase("init"):
        import jax

        if platform_hint == "cpu":
            jax.config.update("jax_platforms", "cpu")
        _apply_prng_choice()
        devs = jax.devices()
    platform = devs[0].platform
    print(f"bench: backend={platform} devices={len(devs)}",
          file=sys.stderr)

    # batch sweep on v5e-1 (2026-07): 8192 -> 137M steps/s, 65536 ->
    # 281M, 131072 -> 306M, 262144 -> 312M (saturated); 131072 keeps
    # compile + memory comfortable at ~98% of peak
    n_envs = 131072 if platform != "cpu" else 512
    manifest = telemetry.current().manifest(config=dict(
        metric="nakamoto_sm1", n_envs=n_envs, prng=_prng_choice()))
    with telemetry.current().span("bench:nakamoto_sm1"):
        steps_per_sec, rel, extras = measure_nakamoto(n_envs)
    mem_after = telemetry.device_memory_stats()
    if mem_after:
        manifest["memory_after"] = mem_after
    if not SM1_GUARD[0] < rel < SM1_GUARD[1]:
        raise GuardFailure(f"SM1 revenue {rel} off closed form 0.416")

    base = _cpu_baseline("nakamoto_sm1")
    row = {
        "metric": "nakamoto_selfish_mining_env_steps_per_sec_per_chip",
        "value": round(steps_per_sec),
        "unit": "env-steps/sec/chip",
        "vs_baseline": round(steps_per_sec / 10_000_000, 3),
        "backend": platform,
        "prng": _prng_choice(),
        **({"vs_cpu_baseline": round(steps_per_sec / base, 3)}
           if base else {}),
        **extras,
        **(_roofline_utilization(extras, steps_per_sec)
           if platform != "cpu" else {}),
        **(_outage_fields(fallback_reason, "nakamoto_selfish_mining")
           if fallback_reason is not None else {}),
        # a row measured after a warm restart carries the count so the
        # perf ledger can tag it (CPR_SUPERVISOR_RESTART, parent-set)
        **({"restart_count": supervisor.restart_count()}
           if supervisor.restart_count() else {}),
        "manifest": manifest,
    }
    print(json.dumps(row))
    _bank_and_gate(row)


# BASELINE.md target configs 2-4 (config 1 is the headline metric above;
# config 5, GhostDAG VI, is measured by the capstone tooling).  Sizes
# follow BASELINE.json; CPU fallbacks shrink so the watchdog always gets
# a tagged number.
CONFIGS = {
    # dict order is the measurement order for BOTH paths; every TPU
    # size below is the round-4 sweep winner (tools/tpu_dag_sweep.py):
    # the aggregate DAG-env rate PEAKS at small batches (8192 envs for
    # bk, 4096 for ethereum/tailstorm) and declines at larger ones, so
    # "bigger batch" is no longer the default
    "bk8_withholding": dict(
        fn="measure_bk", tpu=dict(n_envs=8192), cpu=dict(n_envs=128),
        guard=(0.05, 0.6), guard_name="get-ahead revenue share"),
    "tailstorm_ppo_train": dict(
        fn="measure_tailstorm_ppo", tpu=dict(n_envs=4096),
        cpu=dict(n_envs=64), guard=(0.0, 2.1),
        guard_name="policy entropy (2 actions + quorum head)"),
    # BASELINE config 3 prescribes 65k batched EPISODES: delivered as
    # 4096 auto-resetting streams x 4096 steps (~67k episodes/rep, see
    # measure_ethereum).  The literal 65536-env shape killed the axon
    # worker at any chunk length (round-3 session log) and measured
    # 3x slower per step at 16384 envs than at 4096 anyway.
    "ethereum_uncle_attack": dict(
        fn="measure_ethereum", tpu=dict(n_envs=4096),
        cpu=dict(n_envs=256, n_steps=1024), guard=(0.33, 0.55),
        guard_name="fn19 revenue share"),
    # cpr_tpu/netsim batched network sim: lanes are full honest-clique
    # runs, so the CPU size alone (24 lanes x 10k activations) already
    # beats the serial oracle loop on the same grid (PARITY.md)
    "netsim_sweep": dict(
        fn="measure_netsim", tpu=dict(n_envs=96),
        cpu=dict(n_envs=24), guard=(0.01, 0.06),
        guard_name="nakamoto orphan rate @ delay 30"),
    # grid-batched exact-MDP solving (cpr_tpu/mdp/grid.py): n_envs is
    # the (alpha, gamma) grid size per protocol; the rate counts
    # solved points/sec, so the metric/unit override the env-steps
    # default.  Guard: fc16 optimal revenue at the (0.45, 0.75)
    # corner, mfl=12 horizon=100 — exact solve gives ~0.753
    "mdp_grid": dict(
        fn="measure_mdp_grid", tpu=dict(n_envs=32),
        cpu=dict(n_envs=16), guard=(0.70, 0.80),
        guard_name="fc16 optimal revenue @ (0.45, 0.75)",
        metric="mdp_grid_points_per_sec", unit="grid-points/sec"),
    # state-sharded exact-MDP solving (cpr_tpu/parallel/
    # state_shard.py): n_envs is the fc16 fork-length; ONE solve's
    # state space shards over CPR_BENCH_DEVICES (pad_states covers
    # non-dividing counts) and the rate counts state backups/sec —
    # the ledger fingerprints it by cfg_state_shards, so 1- and
    # N-shard rows never gate each other.  Same revenue guard as
    # mdp_grid: the check is solve-correctness, not throughput
    "mdp_state_shard": dict(
        fn="measure_mdp_state_shard", tpu=dict(n_envs=12),
        cpu=dict(n_envs=12), guard=(0.70, 0.80),
        guard_name="fc16 optimal revenue @ (0.45, 0.75)",
        metric="mdp_states_per_sec", unit="states/sec"),
    # frontier-batched MDP compilation (cpr_tpu/mdp/frontier.py):
    # n_envs is the generic bitcoin dag_size_cutoff (6 -> 5730
    # states); the rate counts discovered states/sec, host-side work
    # on every backend.  Guard: transitions-per-state of the compiled
    # MDP — exactly 22710/5730 = 3.9634 at cutoff 6, so the band is a
    # graph-shape checksum, not a tolerance
    "mdp_compile": dict(
        fn="measure_mdp_compile", tpu=dict(n_envs=6),
        cpu=dict(n_envs=6), guard=(3.95, 3.98),
        guard_name="bitcoin@6 transitions per state",
        metric="mdp_compile_states_per_sec", unit="states/sec"),
    # adversary-in-the-network lanes (cpr_tpu/netsim/attack.py): n_envs
    # lanes over an alpha x policy grid on the 4-node clique; the rate
    # counts lanes/sec.  Guard: honest attacker relative revenue at
    # alpha=1/3 tracks compute share (orphan losses << the slack).
    # Sharding honors CPR_BENCH_DEVICES via _bench_mesh, like netsim
    "attack_sweep": dict(
        fn="measure_attack_sweep", tpu=dict(n_envs=64),
        cpu=dict(n_envs=16), guard=(0.28, 0.39),
        guard_name="honest attacker relative revenue @ alpha 1/3",
        metric="attack_sweep_lanes_per_sec", unit="lanes/sec"),
}


def _measure_config(name: str, platform: str, n_envs_override=None):
    """Measure one config on the current backend and return its JSON row
    (guard-checked)."""
    spec = CONFIGS[name]
    kw = dict(spec["cpu"] if platform == "cpu" else spec["tpu"])
    if n_envs_override is not None:
        kw["n_envs"] = int(n_envs_override)
    manifest = telemetry.current().manifest(config=dict(
        kw, metric=name, prng=_prng_choice()))
    with telemetry.current().span(f"bench:{name}"):
        rate, check, extras = globals()[spec["fn"]](**kw)
    mem_after = telemetry.device_memory_stats()
    if mem_after:
        manifest["memory_after"] = mem_after
    rate, check = float(rate), float(check)
    lo, hi = spec["guard"]
    if not lo < check < hi:
        raise GuardFailure(
            f"{name}: {spec['guard_name']} {check} outside ({lo}, {hi})")
    base = _cpu_baseline(name)
    return {
        "metric": spec.get("metric", f"{name}_env_steps_per_sec_per_chip"),
        # sub-1000 rates (e.g. grid points/sec) keep 3 decimals; the
        # env-steps rates stay integral as before
        "value": round(rate) if rate >= 1000 else round(rate, 3),
        "unit": spec.get("unit", "env-steps/sec/chip"),
        "check": round(check, 4),
        "backend": platform,
        "prng": _prng_choice(),
        **({"vs_cpu_baseline": round(rate / base, 3)} if base else {}),
        **extras,
        **(_roofline_utilization(extras, rate)
           if platform != "cpu" else {}),
        **{f"cfg_{k}": v for k, v in kw.items()},
        # see run_bench: post-warm-restart rows self-tag for the ledger
        **({"restart_count": supervisor.restart_count()}
           if supervisor.restart_count() else {}),
        "manifest": manifest,
    }


def _write_configs_json(rows):
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_CONFIGS.json")
    with open(path, "w") as f:
        json.dump(rows, f, indent=2)


def run_configs(platform_hint: str):
    """Measure configs 2-4 in-process (the CPR_BENCH_BACKEND=cpu path),
    print one JSON line each, and write BENCH_CONFIGS.json next to this
    file."""
    import jax

    if platform_hint == "cpu":
        jax.config.update("jax_platforms", "cpu")
    _apply_prng_choice()
    platform = jax.devices()[0].platform
    print(f"bench-configs: backend={platform}", file=sys.stderr)
    out = []
    for name in CONFIGS:
        row = _measure_config(name, platform)
        print(json.dumps(row))
        _bank_and_gate(row)
        out.append(row)
    _write_configs_json(out)


def run_one(name: str):
    """Child mode: measure a single config on the default backend.
    Isolation matters: a device fault in one config's kernel must not
    cost the other configs their numbers (round-3 lesson — the 65k-env
    ethereum kernel faulted the TPU and took bk's result down with it).
    CPU is forced via jax.config, not JAX_PLATFORMS: the axon PJRT
    plugin claims the chip regardless of that env var (observed)."""
    supervisor.maybe_start_heartbeat()
    with supervisor.child_phase("init"):
        import jax

        if os.environ.get("CPR_BENCH_BACKEND") == "cpu":
            jax.config.update("jax_platforms", "cpu")
        _apply_prng_choice()
        platform = jax.devices()[0].platform
    print(f"bench-one: {name} backend={platform}", file=sys.stderr)
    override = os.environ.get("CPR_BENCH_NENVS")
    # the override is a TPU ladder size — never apply it to a CPU
    # backend (a chip-claim race would otherwise measure CPU at TPU
    # batch sizes and burn the watchdog)
    if platform == "cpu":
        override = None
    try:
        row = _measure_config(name, platform,
                              int(override) if override else None)
    except GuardFailure as e:
        # distinct rc so the parent can tell a deterministic
        # correctness-guard failure from a device fault (no retry, no
        # descent, no CPU masking)
        print(f"bench-one: guard failed: {e}", file=sys.stderr)
        sys.exit(GUARD_RC)
    print(json.dumps(row))


# Extra descent rungs below each config's default TPU size (the first
# rung always comes from CONFIGS[name]["tpu"]["n_envs"]): on a device
# FAULT the runner steps down so a size-dependent failure (memory
# pressure) still yields an on-chip number at a recorded smaller batch.
CONFIG_DESCENT = {
    "ethereum_uncle_attack": (2048,),
}


def run_configs_isolated(timeout: float):
    """Parent mode for configs 2-4 on TPU: one supervised subprocess
    per config x ladder rung (cpr_tpu/supervisor: probe-before-run,
    heartbeat stall detection, probe-gated warm restart), CPU fallback
    per config, all rows written to BENCH_CONFIGS.json with their own
    backend tags.

    A hang no longer wedges the whole loop: the old one-strike flag
    skipped the TPU for every remaining config after a final-rung hang,
    even when earlier configs had already measured on chip.  Now the
    failing config records its partial result / CPU fallback and the
    NEXT config's probe-before-run decides whether the device is worth
    committing to — a recovered worker keeps measuring, a truly wedged
    one costs ~probe_timeout per remaining config instead of a full
    round each.

    Worker-health context: rows measured within ~2-5 min of a worker
    crash read 2-5x slow (round-3 session log), so every row is stamped
    quiet_worker=true (no fault observed by this parent) or
    secs_since_worker_fault, so a recovery-window reading cannot
    masquerade as a regression in later comparisons."""
    out = []
    last_fault_ts = None  # any failed/hung child attempt this run
    for name, spec in CONFIGS.items():
        ladder = (spec["tpu"]["n_envs"],) + CONFIG_DESCENT.get(name, ())
        row, cpu_row, last = None, None, "no attempt"
        guard_failed = False
        for n_envs in ladder:
            # Every rung gets one same-rung transient retry: no rung is
            # a known crasher anymore (the 65536 ethereum shape was
            # dropped from the ladder), so non-hang failures are
            # transient chip claims (single-rung configs: brief pause)
            # or a recovering worker after a crash (multi-rung ladders:
            # observed 60 s insufficient post-crash, twice — wait
            # longer).  Hangs/stalls additionally earn one probe-gated
            # warm restart inside supervise; GuardFailure never burns
            # any retry.
            pause = 15.0 if len(ladder) == 1 else 120.0
            rung_cfg = _supervisor_config(timeout, retry_pause_s=pause)

            def _note_fault(attempt, exc, delay, _name=name, _n=n_envs):
                nonlocal last_fault_ts
                last_fault_ts = telemetry.now()
                print(f"bench: {_name} n_envs={_n} {exc}",
                      file=sys.stderr)

            try:
                outcome = supervisor.supervise(
                    _child_cmd("--direct-one", [name]),
                    site=f"bench:{name}", config=rung_cfg,
                    env=dict(os.environ, CPR_BENCH_NENVS=str(n_envs)),
                    guard_rc=GUARD_RC, on_retry=_note_fault)
            except GuardFailure:
                # deterministic correctness failure: no retry, no
                # descent, and no CPU run to paper over it — surface
                # the error row (size is what we REQUESTED; the child's
                # stderr names what actually ran)
                last = ("correctness guard failed "
                        f"(requested n_envs={n_envs})")
                guard_failed = True
                break
            except supervisor.ProbeFailure as e:
                # the device is not even answering a tiny jit — no
                # point burning this config's wall budget; straight to
                # the CPU fallback.  The NEXT config re-probes, so a
                # recovery is picked up without a wedged-device flag.
                last = f"device probe failed ({e})"
                last_fault_ts = telemetry.now()
                print(f"bench: {name} n_envs={n_envs} {last}",
                      file=sys.stderr)
                break
            except supervisor.SupervisedHang:
                # hang/stall with the warm-restart budget exhausted
                last = "hung past watchdog"
                last_fault_ts = telemetry.now()
                print(f"bench: {name} n_envs={n_envs} {last}",
                      file=sys.stderr)
                if n_envs != ladder[-1]:
                    # a crash can present as an init-hang in the NEXT
                    # child while the worker restarts; with descent
                    # rungs left, pause for recovery and step down
                    # instead of writing the device off
                    print(f"bench: {name} n_envs={n_envs} hung; "
                          f"descending after recovery pause",
                          file=sys.stderr)
                    time.sleep(120.0)
                    continue
                # final-rung hang: CPU fallback for THIS config only —
                # the next config's probe decides about the device
                break
            except TransientFault as e:
                last = f"rc={e.rc}" if hasattr(e, "rc") else str(e)
                last_fault_ts = telemetry.now()
                print(f"bench: {name} n_envs={n_envs} {last}",
                      file=sys.stderr)
                if n_envs != ladder[-1]:
                    # pause before descending too, so descent never
                    # probes a restarting backend; no pause before a
                    # CPU fallback, which does not touch the worker
                    time.sleep(pause)
                continue
            cand = json.loads(outcome.payload.splitlines()[-1])
            if cand.get("backend") == "cpu":
                # chip-claim race: the child came up on CPU.  Not a
                # ladder success, but it IS a valid CPU fallback row —
                # keep it, stop probing.
                last, cpu_row = "backend came up cpu", cand
            else:
                row = cand
            break
        if row is None and cpu_row is None and not guard_failed:
            # CPU rung: wall-clock watchdog only (the CPU child forces
            # jax_platforms=cpu, so there is no device to stall on and
            # nothing for a probe to prove)
            a = supervisor.run_child(
                _child_cmd("--direct-one", [name]),
                wall_timeout_s=timeout, quiet_s=None,
                env=dict(os.environ, CPR_BENCH_BACKEND="cpu"))
            if a.status == "ok" and a.json_lines:
                cpu_row = json.loads(a.json_lines[-1])
            elif a.status == "failed" and a.rc == GUARD_RC:
                guard_failed = True
                last = f"{last}; then correctness guard failed on cpu"
            elif a.status in ("hung", "stalled"):
                last = f"{last}; then cpu fallback hung past watchdog"
            else:
                last = f"{last}; then cpu fallback rc={a.rc}"
        if row is None:
            # outage tagging is for device unavailability only — a
            # deterministic guard failure must stay a loud error row,
            # not dress up as a chip outage
            outage = ({} if guard_failed else _outage_fields(
                f"tpu attempts unsuccessful ({last})", name))
            if cpu_row is not None:
                row = dict(cpu_row,
                           note=f"tpu attempts unsuccessful ({last}); "
                                f"cpu fallback", **outage)
            else:
                row = {"metric": f"{name}_env_steps_per_sec_per_chip",
                       "error": f"attempts failed (last: {last})",
                       **outage}
        if row.get("backend") == "tpu":
            if last_fault_ts is None:
                row["quiet_worker"] = True
            else:
                row["secs_since_worker_fault"] = round(
                    telemetry.now() - last_fault_ts)
        print(json.dumps(row))
        _bank_and_gate(row)
        out.append(row)
    _write_configs_json(out)


def main():
    _prng_choice()  # fail fast on a bad override, before any attempts
    configs_mode = "--configs" in sys.argv
    if "--direct" in sys.argv:
        # child mode: let the default (TPU-preferring) backend come up;
        # on a host with no TPU this IS the CPU bench and its result is
        # relayed as-is (the 512-env CPU run finishes well inside the
        # watchdog timeout)
        try:
            run_bench("default")
        except GuardFailure as e:
            # deterministic correctness failure: surface it as GUARD_RC
            # so the parent neither retries nor masks it with a CPU run
            print(f"bench: guard failed: {e}", file=sys.stderr)
            sys.exit(GUARD_RC)
        return
    if "--direct-one" in sys.argv:
        run_one(sys.argv[sys.argv.index("--direct-one") + 1])
        return
    if os.environ.get("CPR_BENCH_BACKEND") == "cpu":
        run_configs("cpu") if configs_mode else run_bench("cpu")
        return
    # supervised TPU attempt (cpr_tpu/supervisor): probe-before-run so
    # a wedged chip costs ~probe_timeout, heartbeat stall detection so
    # a wedge mid-run is caught in seconds, one probe-gated warm
    # restart, one paused retry for transient child failures;
    # GuardFailure is never retried and never masked by a CPU run
    timeout = float(os.environ.get("CPR_BENCH_TPU_TIMEOUT", "360"))
    if configs_mode:
        # chunked ethereum legitimately runs ~100 s/rep at 16384 envs:
        # compile + 3 reps needs more than the single-kernel default,
        # and a merely-slow config must not be classified as a wedge
        run_configs_isolated(timeout * 2)
        return
    fallback_reason = "tpu attempts failed"
    try:
        print(supervisor.supervise(
            _child_cmd("--direct"), site="bench",
            config=_supervisor_config(timeout), guard_rc=GUARD_RC,
            on_retry=lambda a, e, d: print(
                f"bench: TPU attempt {a} {e}", file=sys.stderr)).payload)
        return
    except GuardFailure:
        # deterministic correctness-guard failure on the TPU: print an
        # error row so the failure is visible in the artifact
        print(json.dumps({
            "metric":
                "nakamoto_selfish_mining_env_steps_per_sec_per_chip",
            "error": "correctness guard failed on tpu backend",
        }))
        return
    except supervisor.ProbeFailure as e:
        print(f"bench: device probe failed ({e}), falling back to CPU",
              file=sys.stderr)
        fallback_reason = f"device probe failed ({e})"
    except supervisor.SupervisedHang as e:
        print(f"bench: TPU attempt hung ({e}), falling back to CPU",
              file=sys.stderr)
        fallback_reason = f"tpu watchdog: {e}"
    except TransientFault as e:
        print("bench: TPU attempts failed, falling back to CPU",
              file=sys.stderr)
        fallback_reason = (f"tpu attempts failed (last rc={e.rc})"
                           if hasattr(e, "rc")
                           else f"tpu attempts failed ({e})")
    run_bench("cpu", fallback_reason)  # configs mode returned above


if __name__ == "__main__":
    main()
