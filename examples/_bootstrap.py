"""Shared example-script preamble: repo-root import path + backend pick.

Import this first in every example:

    import _bootstrap  # noqa: F401
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.join(_os.path.dirname(
    _os.path.abspath(__file__)), ".."))  # repo-root import

if _os.environ.get("CPR_PLATFORM"):
    # select the backend programmatically — in some environments the
    # JAX_PLATFORMS env var is overridden at interpreter startup
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["CPR_PLATFORM"])
