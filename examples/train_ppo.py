"""Config-driven PPO training with per-alpha eval and checkpoints.

Usage: python examples/train_ppo.py [config.yaml] [out_dir] [n_updates]
Defaults to the nakamoto alpha-range config, 20 updates.
"""

import _bootstrap  # noqa: F401  (repo-root path + backend pick)

import os
import sys

from cpr_tpu.experiments import write_tsv
from cpr_tpu.train.config import TrainConfig
from cpr_tpu.train.driver import train_from_config

HERE = os.path.dirname(os.path.abspath(__file__))


def main():
    cfg_path = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        HERE, "..", "cpr_tpu", "train", "configs", "nakamoto.yaml")
    out_dir = sys.argv[2] if len(sys.argv) > 2 else "runs/example"
    n_updates = int(sys.argv[3]) if len(sys.argv) > 3 else 20
    cfg = TrainConfig.from_yaml(cfg_path)

    def progress(i, m):
        print(f"update {i + 1}: step_reward={m['mean_step_reward']:.4f} "
              f"entropy={m['entropy']:.3f}")

    params, history, eval_rows = train_from_config(
        cfg, out_dir=out_dir, n_updates=n_updates, progress=progress)
    print(write_tsv(eval_rows))
    print(f"checkpoints + metrics.jsonl in {out_dir}/")


if __name__ == "__main__":
    main()
