"""Config-driven PPO training with per-alpha eval and checkpoints.

Usage: python examples/train_ppo.py [config.yaml] [out_dir] [n_updates]
           [--resume]
Defaults to the nakamoto alpha-range config, 20 updates.  `--resume`
continues a preempted/crashed run from `<out_dir>/snapshot.msgpack`
(see docs/RESILIENCE.md).
"""

import _bootstrap  # noqa: F401  (repo-root path + backend pick)

import os
import sys

from cpr_tpu.experiments import write_tsv
from cpr_tpu.train.config import TrainConfig
from cpr_tpu.train.driver import train_from_config

HERE = os.path.dirname(os.path.abspath(__file__))


def main():
    argv = [a for a in sys.argv[1:] if a != "--resume"]
    resume = "--resume" in sys.argv
    cfg_path = argv[0] if len(argv) > 0 else os.path.join(
        HERE, "..", "cpr_tpu", "train", "configs", "nakamoto.yaml")
    out_dir = argv[1] if len(argv) > 1 else "runs/example"
    n_updates = int(argv[2]) if len(argv) > 2 else 20
    cfg = TrainConfig.from_yaml(cfg_path)

    def progress(i, m):
        print(f"update {i + 1}: step_reward={m['mean_step_reward']:.4f} "
              f"entropy={m['entropy']:.3f}")

    params, history, eval_rows = train_from_config(
        cfg, out_dir=out_dir, n_updates=n_updates, progress=progress,
        resume=resume)
    print(write_tsv(eval_rows))
    print(f"checkpoints + metrics.jsonl in {out_dir}/")


if __name__ == "__main__":
    main()
