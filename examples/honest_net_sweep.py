"""Honest-network sweep -> TSV (the reference's honest_net experiment).

Usage: python examples/honest_net_sweep.py [out.tsv]
"""

import _bootstrap  # noqa: F401  (repo-root path + backend pick)

import sys

from cpr_tpu.experiments import honest_net_rows, write_tsv


def main():
    rows = honest_net_rows(n_activations=5_000)
    out = sys.argv[1] if len(sys.argv) > 1 else None
    text = write_tsv(rows, out)
    print(text if out is None else f"wrote {len(rows)} rows to {out}")


if __name__ == "__main__":
    main()
