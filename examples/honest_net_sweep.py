"""Honest-network sweep -> TSV (the reference's honest_net experiment).

Usage: python examples/honest_net_sweep.py [out.tsv]
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.join(_os.path.dirname(
    _os.path.abspath(__file__)), ".."))  # repo-root import

if _os.environ.get("CPR_PLATFORM"):
    # select the backend programmatically — in some environments the
    # JAX_PLATFORMS env var is overridden at interpreter startup
    import jax as _jax

    _jax.config.update("jax_platforms", _os.environ["CPR_PLATFORM"])

import sys

from cpr_tpu.experiments import honest_net_rows, write_tsv


def main():
    rows = honest_net_rows(n_activations=5_000)
    out = sys.argv[1] if len(sys.argv) > 1 else None
    text = write_tsv(rows, out)
    print(text if out is None else f"wrote {len(rows)} rows to {out}")


if __name__ == "__main__":
    main()
