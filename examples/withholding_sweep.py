"""Withholding-policy sweep over an (alpha, gamma) grid — each policy's
whole grid runs as one vmap'd TPU kernel (the reference's withholding
experiment).

Usage: python examples/withholding_sweep.py [protocol-key] [out.tsv]
"""

import _bootstrap  # noqa: F401  (repo-root path + backend pick)

import sys

from cpr_tpu.experiments import withholding_rows, write_tsv


def main():
    key = sys.argv[1] if len(sys.argv) > 1 else "nakamoto"
    rows = withholding_rows(key, episode_len=256, reps=128)
    out = sys.argv[2] if len(sys.argv) > 2 else None
    text = write_tsv(rows, out)
    print(text if out is None else f"wrote {len(rows)} rows to {out}")


if __name__ == "__main__":
    main()
