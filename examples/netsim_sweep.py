"""Honest-network sweep on the JAX netsim engine -> TSV.

Same grid semantics as honest_net_sweep.py, but every protocol's
activation-delay column runs as vmapped lanes of ONE device program
(cpr_tpu/netsim).  `make netsim-smoke` runs this tiny with telemetry on
and schema-validates the artifact (netsim:run spans + the typed
`netsim` point event).

Usage: python examples/netsim_sweep.py [out.tsv]
"""

import _bootstrap  # noqa: F401  (repo-root path + backend pick)

import sys

from cpr_tpu.experiments import honest_net_rows, write_tsv

# nakamoto rides the fused scan path, bk the general event engine —
# the smoke covers both execution modes
PROTOCOLS = (
    ("nakamoto", {}),
    ("bk", dict(k=8, scheme="constant")),
)


def main():
    small = "--smoke" in sys.argv[1:]
    args = [a for a in sys.argv[1:] if a != "--smoke"]
    rows = honest_net_rows(
        protocols=PROTOCOLS,
        activation_delays=(30.0, 60.0, 120.0),
        n_activations=500 if small else 10_000,
        engine="jax")
    out = args[0] if args else None
    text = write_tsv(rows, out)
    print(text if out is None else f"wrote {len(rows)} rows to {out}")


if __name__ == "__main__":
    main()
