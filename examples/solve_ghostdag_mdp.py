"""Compile the GhostDAG attack MDP and solve it with mesh-sharded value
iteration (BASELINE.md capstone config 5).

The native (C++) compiler handles the big cutoffs: dag_size_cutoff=8
builds 1.19M states / 3.76M transitions in ~40s on one host core (the
Python BFS is kept as the cross-checked semantic anchor; pass --python
to use it on small cutoffs).

Usage: python examples/solve_ghostdag_mdp.py [dag_size_cutoff]
           [--python] [--rtdp]

--rtdp solves with the device RTDP (sampled trajectories, async
backups) instead of exact sweeps — the practical choice for cutoff 8's
5.27M-row PT table on a CPU host; the estimate lower-bounds the exact
optimum (docs/CAPSTONE.md has measured numbers).
"""

import _bootstrap  # noqa: F401  (repo-root path + backend pick)

import sys
import time

from cpr_tpu.mdp import Compiler, ptmdp
from cpr_tpu.mdp.generic import SingleAgent, get_protocol
from cpr_tpu.mdp.generic.native import compile_native
from cpr_tpu.parallel import default_mesh, sharded_value_iteration


def main():
    flags = [a for a in sys.argv[1:] if a.startswith("--")]
    unknown = set(flags) - {"--python", "--rtdp"}
    if unknown:
        sys.exit(f"unknown flag(s): {' '.join(sorted(unknown))} "
                 "(choose from --python --rtdp)")
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    cutoff = int(args[0]) if args else 7
    t0 = time.time()
    if "--python" in sys.argv:
        model = SingleAgent(get_protocol("ghostdag", k=2), alpha=0.3,
                            gamma=0.5, collect_garbage="simple",
                            merge_isomorphic=True,
                            truncate_common_chain=True,
                            dag_size_cutoff=cutoff)
        table = Compiler(model).mdp()
    else:
        table = compile_native("ghostdag", k=2, alpha=0.3, gamma=0.5,
                               collect_garbage="simple",
                               dag_size_cutoff=cutoff)
    mdp = ptmdp(table, horizon=100)
    print(f"compiled: {mdp.n_states} states, {mdp.n_transitions} "
          f"transitions in {time.time() - t0:.1f}s")
    tm = mdp.tensor()
    t0 = time.time()
    if "--rtdp" in sys.argv:
        import jax

        r = tm.rtdp(jax.random.PRNGKey(0), steps=200_000, batch=512,
                    eps=0.5)
        rev = tm.start_value(r["rtdp_value"]) / tm.start_value(
            r["rtdp_progress"])
        print(f"device RTDP: {time.time() - t0:.1f}s; revenue >= "
              f"{rev:.4f} (lower bound; honest = 0.3)")
        return
    # chunked VI always: the while-loop impl runs one unbounded device
    # execution, and the axon TPU worker kills any single execution
    # past ~60-75 s (tools/tpu_limit_probe.py) — exactly what a
    # multi-thousand-sweep solve is.  Chunk sized so a call stays far
    # inside the ceiling even at cutoff 8's 5.27M rows (~1-5 sweeps/s).
    chunk = 16 if mdp.n_transitions > 1_000_000 else 64
    # Anderson acceleration between chunks (VERDICT r4 #7): ~5x fewer
    # sweeps at the same fixpoint — the cutoff-8 solve was 3568 plain
    # Jacobi sweeps / 1817 s on one v5e chip
    vi = sharded_value_iteration(tm, default_mesh(), stop_delta=1e-6,
                                 impl="chunked", chunk=chunk, accel_m=3)
    rev = tm.start_value(vi["vi_value"]) / tm.start_value(
        vi["vi_progress"])
    print(f"sharded VI: {int(vi['vi_iter'])} sweeps in "
          f"{time.time() - t0:.1f}s; optimal revenue {rev:.4f} "
          f"(honest = 0.3)")


if __name__ == "__main__":
    main()
