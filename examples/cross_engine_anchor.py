"""Cross-engine anchoring demo: the same attack, two engines.

Runs a withholding policy through BOTH the jittable JAX environment
(collapsed 2-party model, the TPU hot path) and the C++ multi-node
discrete-event oracle (cpr_tpu.native), and prints the revenue from
each side plus the closed form where one exists.  This is the
validation pattern the test suite applies across protocols
(tests/test_oracle_equivalence.py).

Usage: python examples/cross_engine_anchor.py [nakamoto|ethereum|bk]
"""

import _bootstrap  # noqa: F401  (repo-root path + backend pick)

import sys

import numpy as np


def jax_share(env, policy, alpha, gamma, n_envs=512, steps=256):
    import jax

    from cpr_tpu.params import make_params

    params = make_params(alpha=alpha, gamma=gamma, max_steps=steps)
    keys = jax.random.split(jax.random.PRNGKey(0), n_envs)
    f = jax.jit(jax.vmap(lambda k: env.episode_stats(
        k, params, env.policies[policy], steps + 8)))
    st = jax.block_until_ready(f(keys))
    a = np.asarray(st["episode_reward_attacker"]).mean()
    d = np.asarray(st["episode_reward_defender"]).mean()
    return a / (a + d)


def oracle_share(proto, policy, alpha, gamma, **kw):
    from cpr_tpu.native import OracleSim

    s = OracleSim(proto, topology="selfish_mining", alpha=alpha,
                  gamma=gamma, attacker_policy=policy,
                  propagation_delay=1e-9, seed=0, **kw)
    s.run(60_000)
    rw = s.rewards(8)
    return rw[0] / sum(rw)


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "nakamoto"
    alpha, gamma = 0.35, 0.5
    if which == "nakamoto":
        from cpr_tpu.envs.nakamoto import NakamotoSSZ

        policy = "sapirshtein-2016-sm1"
        o = oracle_share("nakamoto", policy, alpha, gamma)
        j = jax_share(NakamotoSSZ(), policy, alpha, gamma)
        es = (alpha * (1 - alpha) ** 2 * (4 * alpha + gamma * (1 - 2 * alpha))
              - alpha**3) / (1 - alpha * (1 + (2 - alpha) * alpha))
        print(f"nakamoto {policy} @ a={alpha} g={gamma}:")
        print(f"  ES'14 closed form  {es:.4f}")
    elif which == "ethereum":
        from cpr_tpu.envs.ethereum import EthereumSSZ

        policy = "fn19"
        o = oracle_share("ethereum-byzantium", policy, alpha, gamma)
        j = jax_share(EthereumSSZ("byzantium", max_steps_hint=256),
                      policy, alpha, gamma, n_envs=256)
        print(f"ethereum-byzantium {policy} @ a={alpha} g={gamma}:")
    elif which == "bk":
        from cpr_tpu.envs.bk import BkSSZ

        policy = "honest"
        o = oracle_share("bk", policy, alpha, gamma, k=4, scheme="constant")
        j = jax_share(BkSSZ(k=4, incentive_scheme="constant",
                            max_steps_hint=256), policy, alpha, gamma,
                      n_envs=256)
        print(f"bk-4-constant {policy} @ a={alpha} g={gamma}:")
    else:
        sys.exit(f"unknown protocol {which!r} "
                 "(choose nakamoto, ethereum, or bk)")
    print(f"  C++ oracle engine  {o:.4f}")
    print(f"  JAX environment    {j:.4f}")
    print(f"  |difference|       {abs(o - j):.4f}")


if __name__ == "__main__":
    main()
