"""Policy-evaluation study: per-episode rows over an (alpha, gamma)
grid, aggregated to the rl-results model table — the rl-eval notebook
pipeline (eval-policies + rl-results-condensed) as one script.

Pass a checkpoint AND its training config to add the trained policy to
the comparison:

Usage: python examples/rl_eval_study.py [protocol-key] \
           [ckpt.msgpack config.yaml]
"""

import _bootstrap  # noqa: F401  (repo-root path + backend pick)

import sys

from cpr_tpu.experiments import aggregate, episode_rows, write_tsv

ALPHAS = (0.25, 0.33, 0.4, 0.45)
GAMMAS = (0.5,)
EPISODE_LEN = 256
REPS = 32


def main():
    key = sys.argv[1] if len(sys.argv) > 1 else "nakamoto"
    if len(sys.argv) == 3:
        sys.exit("a checkpoint needs its training config too: "
                 "rl_eval_study.py <protocol> <ckpt.msgpack> <cfg.yaml>")
    rows = episode_rows(key, alphas=ALPHAS, gammas=GAMMAS,
                        episode_len=EPISODE_LEN, reps=REPS)
    if len(sys.argv) > 3:
        from cpr_tpu.train.config import TrainConfig
        from cpr_tpu.train.driver import (build_env, load_checkpoint,
                                          ppo_config)

        cfg = TrainConfig.from_yaml(sys.argv[3])
        if cfg.protocol != key:
            sys.exit(f"checkpoint was trained on '{cfg.protocol}', "
                     f"not '{key}' — pass matching args")
        # build_env applies the same wrappers training used (e.g. the
        # AssumptionEnv +2 observation fields under scheduled alpha),
        # so the checkpoint's layer shapes match the template
        env = build_env(cfg)
        params = load_checkpoint(sys.argv[2], env, cfg)
        rows += episode_rows(key, sys.argv[2], alphas=ALPHAS,
                             gammas=GAMMAS, episode_len=EPISODE_LEN,
                             reps=REPS, kind="trained",
                             net_params=params,
                             hidden=ppo_config(cfg).hidden, env=env)
    table = aggregate(rows)
    print(write_tsv(table))
    print(f"# {len(rows)} episodes -> {len(table)} settings")


if __name__ == "__main__":
    main()
