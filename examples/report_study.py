"""End-to-end report study: reproduce the reference's honest-net pivots
and the rl-eval condensed model table from fresh sweeps (the numbered-
notebook consumption layer as one executable —
experiments/simulate/honest_net.py:35-77 and
experiments/rl-eval/rl-results-condensed.ipynb).

Usage: python examples/report_study.py [out_dir] [protocol-key]
"""

import _bootstrap  # noqa: F401  (repo-root path + backend pick)

import os
import sys

from cpr_tpu.experiments.report import honest_net_report, rl_eval_report


def main():
    out_dir = sys.argv[1] if len(sys.argv) > 1 else None
    key = sys.argv[2] if len(sys.argv) > 2 else "nakamoto"
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)

    hn_tsv = os.path.join(out_dir, "honest_net_expanded.tsv") \
        if out_dir else None
    _, _, text = honest_net_report(out_tsv=hn_tsv,
                                   n_activations=5_000)
    print("== honest-net pivots (honest_net.py:62-75) ==")
    print(text or "(no rows)")

    rl_tsv = os.path.join(out_dir, "rl_results_condensed.tsv") \
        if out_dir else None
    _, _, text = rl_eval_report(key, out_tsv=rl_tsv,
                                alphas=(0.25, 0.33, 0.4, 0.45),
                                episode_len=256, reps=16)
    print("\n== rl-results condensed model table ==")
    print(text)
    if out_dir:
        print(f"\nwrote TSVs to {out_dir}/")


if __name__ == "__main__":
    main()
