# Workflow entry points (reference: Makefile + mdp/justfile).
# The CPU mesh env vars mirror tests/conftest.py; bench/examples run on
# whatever backend JAX selects (TPU when healthy).

CPU_MESH = JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8

.PHONY: lint test test-slow bench perf-gate telemetry-smoke netsim-smoke resilience-smoke supervisor-smoke obs-smoke serve-smoke learn-smoke fleet-smoke chaos-smoke multichip-smoke mdp-smoke vi-smoke compile-smoke attack-smoke dryrun sweeps ghostdag train-dummy native asan

lint:  ## jaxlint over cpr_tpu/ + tools/ (pure AST, no JAX import,
	## ~1s); banks the JSON report under runs/ like the smoke flows
	## bank their artifacts.  Rule catalog: docs/ANALYSIS.md
	mkdir -p runs
	python tools/jaxlint.py cpr_tpu tools --output runs/jaxlint.json

test:  ## fast tier (< ~8 min on the 1-core host)
	python -m pytest tests/ -q

test-slow:  ## full suite incl. deep stochastic batteries (one process:
	## conftest releases compiled executables at the old split point,
	## which defuses the ~200-compile XLA:CPU JIT segfault)
	python -m pytest tests/ -q --runslow

# legacy two-process split, kept as a fallback if the cache-release
# workaround regresses on a future jaxlib
SLOW_TAIL = tests/test_registry.py tests/test_rtdp_explorer.py \
	tests/test_sdag_env.py tests/test_spar_env.py \
	tests/test_stree_env.py tests/test_tailstorm_env.py

test-slow-split:
	python -m pytest tests/ -q --runslow \
		$(addprefix --ignore=,$(SLOW_TAIL))
	python -m pytest $(SLOW_TAIL) -q --runslow

bench:  ## one-line JSON benchmark (TPU with CPU fallback)
	python bench.py

perf-gate:  ## regression gate over the banked bench trail: newest row
	## per metric x backend vs the best same-backend banked history
	## (median/MAD band; outage rows never baselines).  Nonzero exit on
	## any FAIL verdict.  Details: docs/OBSERVABILITY.md
	python tools/perf_report.py --gate

TELEMETRY_SMOKE = /tmp/cpr-telemetry-smoke.jsonl

telemetry-smoke:  ## tiny nakamoto CPU bench with telemetry + in-graph
	## device metrics on, then schema-validate the JSONL artifact
	## (nonzero exit on violation or if the v2 event types are absent;
	## v5 adds the perf_gate verdict the bench self-emits after banking)
	rm -f $(TELEMETRY_SMOKE)
	CPR_BENCH_BACKEND=cpu CPR_DEVICE_METRICS=1 \
		CPR_TELEMETRY=$(TELEMETRY_SMOKE) python bench.py
	python tools/trace_summary.py $(TELEMETRY_SMOKE) --validate \
		--expect device_metrics,compile,perf_gate

NETSIM_SMOKE = /tmp/cpr-netsim-smoke.jsonl

netsim-smoke:  ## tiny CPU netsim sweep (both execution modes: the
	## fused nakamoto scan and the general bk event engine) with
	## telemetry on, then schema-validate the artifact including the
	## typed `netsim` point event
	rm -f $(NETSIM_SMOKE)
	JAX_PLATFORMS=cpu CPR_DEVICE_METRICS=1 \
		CPR_TELEMETRY=$(NETSIM_SMOKE) \
		python examples/netsim_sweep.py --smoke /tmp/cpr-netsim-smoke.tsv
	python tools/trace_summary.py $(NETSIM_SMOKE) --validate \
		--expect netsim,device_metrics,compile

RESILIENCE_SMOKE_DIR = /tmp/cpr-resilience-smoke

resilience-smoke:  ## kill-and-resume determinism proof: tiny CPU train,
	## inject a crash mid-run, resume, assert the concatenated metrics
	## history is bit-identical to an uninterrupted run, and validate
	## the schema-v3 resilience telemetry events
	rm -rf $(RESILIENCE_SMOKE_DIR)
	python tools/resilience_smoke.py $(RESILIENCE_SMOKE_DIR)

SUPERVISOR_SMOKE_DIR = /tmp/cpr-supervisor-smoke

supervisor-smoke:  ## supervised-subprocess proof: injected hang@probe
	## (ProbeFailure bounded by probe_timeout) and hang@run (heartbeat
	## stall < 60s, exactly one probe-gated warm restart, escalation),
	## then a clean terminal-rung run and schema validation of the
	## typed v6 `supervisor` event trail
	rm -rf $(SUPERVISOR_SMOKE_DIR)
	python tools/supervisor_smoke.py $(SUPERVISOR_SMOKE_DIR)

OBS_SMOKE_DIR = /tmp/cpr-obs-smoke

obs-smoke:  ## v15 attribution-plane proof: two supervised server
	## runs (baseline + one-shot injected `slow@replica` stall), live
	## memory-watermark gauges asserted in a mid-run metrics.scrape and
	## in the drain report, both traces validated with `memory` events
	## and archived under distinct run ids, trace_diff over the
	## archived pair ranking the injected serve_burst span as the #1
	## culprit, a gated serve_p99_s FAIL carrying the run-id pair, a
	## clean lower-is-better serve_peak_bytes gate, and `perf_report
	## --gate --attribute` chasing the FAIL through the archive into a
	## culprit table.  Details: docs/OBSERVABILITY.md
	rm -rf $(OBS_SMOKE_DIR)
	python tools/obs_smoke.py $(OBS_SMOKE_DIR)

SERVE_SMOKE_DIR = /tmp/cpr-serve-smoke

serve-smoke:  ## continuous-batching service proof: supervised server
	## child, ~32 concurrent clients across the policy / interactive /
	## netsim / break-even endpoints, sustained full-occupancy
	## throughput within 20% of an equivalent batch rollout(), graceful
	## SIGTERM drain, v8 `serve`/`request` trace validation, a
	## trace_stitch pairing of the server and client streams, and
	## throughput + drain-report p50/p99 latency rows banked + gated in
	## the perf ledger.  Details: docs/SERVING.md
	rm -rf $(SERVE_SMOKE_DIR)
	python tools/serve_smoke.py $(SERVE_SMOKE_DIR)

LEARN_SMOKE_DIR = /tmp/cpr-learn-smoke

learn-smoke:  ## always-on-learning proof: supervised learner + serve
	## children wired into the closed sampler/learner loop — the
	## learner's untrained seq-0 snapshot serves first, fleet lanes
	## record experience into device rings and feed it over the wire,
	## PPO updates publish sealed snapshots, and the server hot-swaps
	## them zero-drain at burst boundaries; under client flood the mean
	## greedy relative_reward must measurably improve across >= 2
	## published swaps, hot-swap bit-determinism is asserted on
	## scripted lanes, both traces (+ their merge) validate with v17
	## `learn` events, and learn_samples_per_sec +
	## learn_snapshot_staleness_s rows are banked + gated.
	## Details: docs/LEARNING.md
	rm -rf $(LEARN_SMOKE_DIR)
	python tools/learn_smoke.py $(LEARN_SMOKE_DIR)

FLEET_SMOKE_DIR = /tmp/cpr-fleet-smoke

fleet-smoke:  ## fleet-resilience chaos proof: router + 2 replicas,
	## CPR_FAULT_INJECT kills replica 1 at its first burst under a
	## 32-client flood — zero client hangs, every episode (requeued
	## ones included) bit-identical to rollout(), in-band queue_full
	## sheds honored via call_with_retry, warm restart rejoins, then
	## v9 admission/route validation, a trace_stitch router-hop
	## pairing, and per-class p99 + shed-rate rows banked + gated.
	## v14 health plane ridealong: mid-flood Prometheus scrapes of
	## the router + replica --metrics-port endpoints and the in-band
	## metrics.scrape op, the fleet latency merge checked exact
	## against a merged-by-hand reference, >=1 SLO burn-rate alert
	## under the kill, the killed replica's blackbox dump validated,
	## and fleet_p99_s rows banked + gated from the router trace.
	## Details: docs/SERVING.md
	rm -rf $(FLEET_SMOKE_DIR)
	python tools/fleet_smoke.py $(FLEET_SMOKE_DIR)

CHAOS_SMOKE_DIR = /tmp/cpr-chaos-smoke

chaos-smoke:  ## randomized chaos campaign (v16 artifact integrity
	## plane): per seed (two distinct seeds), a replayable
	## ChaosSchedule arms a randomized replica kill/slowdown under a
	## 16-client flood (zero hangs, bit-identical episodes) while a
	## concurrent VI solve takes a corrupt-checkpoint-then-kill
	## sequence — resume quarantines the damaged checkpoint and cold
	## starts bit-identical to an uninterrupted solve; the grid-solve
	## cache entry is damaged and must regenerate (miss, never a
	## crash); every injected corruption is matched 1:1 by a typed
	## `integrity` event in the validated merged trace; and a
	## hand-tampered ledger row is skipped with an integrity event,
	## leaving perf_report --gate verdicts unchanged.
	## Details: docs/RESILIENCE.md
	rm -rf $(CHAOS_SMOKE_DIR)
	python tools/chaos_smoke.py $(CHAOS_SMOKE_DIR)

MULTICHIP_SMOKE_DIR = /tmp/cpr-multichip-smoke

multichip-smoke:  ## sharded hot-loop proof on a forced 4-device CPU
	## mesh: supervised serve runs at --devices 1 and 4 with the same
	## seeded flood, sharded rollout + netsim children, every output
	## asserted bit-identical across device counts, traces validated
	## (`--expect serve,device_metrics`), and per-device-count
	## serve_steps_per_sec rows banked + gated with the perf_report
	## scaling table.  Details: docs/SCALING.md
	rm -rf $(MULTICHIP_SMOKE_DIR)
	python tools/multichip_smoke.py $(MULTICHIP_SMOKE_DIR)

MDP_SMOKE_DIR = /tmp/cpr-mdp-smoke

mdp-smoke:  ## grid-batched MDP proof: parametric compile of fc16 +
	## aft20 (one BFS per protocol), revalue parity vs fresh compiles,
	## a 16-point (alpha, gamma) grid solved as ONE vmapped VI program
	## at forced 1 and 4 CPU devices with bit-identical per-point
	## fixpoints, a telemetry-spanned A/B where the grid beats the
	## serial per-point loop >= 3x, a serve mdp.solve_grid cache-hit
	## round-trip, v10 `mdp_solve` trace validation, and
	## mdp_grid_points_per_sec rows banked + gated at both device
	## counts.  Details: docs/MDP.md
	rm -rf $(MDP_SMOKE_DIR)
	python tools/mdp_smoke.py $(MDP_SMOKE_DIR)

VI_SMOKE_DIR = /tmp/cpr-vi-smoke

vi-smoke:  ## state-sharded VI proof: ONE bitcoin (fc16@6) solve with
	## its state space partitioned over forced 1 vs 4 CPU devices,
	## fixpoints bit-identical to each other and to the solo chunked
	## oracle, the in-graph RTDP start value checked against the
	## host-computed exact oracle (seeded, reproducible), the
	## rtdp_sharded_polish explore-then-certify handoff, a composed
	## ("g", "s") 2-D grid x state solve bit-identical to the 1-D
	## grid solve, v13 `mdp_solve` trace validation, and
	## mdp_states_per_sec rows banked + gated at state-shard counts
	## 1 and 4.  Details: docs/MDP.md "State-sharded solving"
	rm -rf $(VI_SMOKE_DIR)
	python tools/vi_smoke.py $(VI_SMOKE_DIR)

COMPILE_SMOKE_DIR = /tmp/cpr-compile-smoke

compile-smoke:  ## frontier-batched MDP compile proof: serial Compiler
	## vs frontier inline vs FORCED multi-worker expansion on the
	## generic bitcoin model, all three byte-identical, best frontier
	## states/sec over a core-adaptive floor (>= 2x on multi-core, >=
	## 4x target on >= 4 cores; parity on the 1-core CI), a
	## kill@compile_round=3 + resume leg byte-identical through the
	## real fault grammar, v12 `mdp_compile` trace validation, and
	## mdp_compile_states_per_sec rows banked + gated at workers 1 and
	## N.  Details: docs/MDP.md
	rm -rf $(COMPILE_SMOKE_DIR)
	python tools/compile_smoke.py $(COMPILE_SMOKE_DIR)

ATTACK_SMOKE_DIR = /tmp/cpr-attack-smoke

attack-smoke:  ## adversary-in-the-network proof: a protocol x
	## topology x alpha attack_sweep grid (nakamoto clean + an
	## unsupported protocol's reason-tagged error row) as ONE vmapped
	## lane program at forced 1 and 2 CPU devices with bit-identical
	## rows, the degenerate two-party anchor asserted (zero-delay
	## clique == NakamotoSSZ env at gamma=0), a serve
	## netsim.attack_sweep cache-hit round-trip with SIGTERM drain,
	## v11 `attack_sweep` trace validation, and
	## attack_sweep_lanes_per_sec rows banked + gated at both device
	## counts.  Details: docs/NETSIM.md
	rm -rf $(ATTACK_SMOKE_DIR)
	python tools/attack_smoke.py $(ATTACK_SMOKE_DIR)

dryrun:  ## multi-chip sharding dry run on the virtual CPU mesh
	$(CPU_MESH) python -c "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)"

sweeps:  ## honest-net + withholding sweep tables (TSV to stdout)
	python examples/honest_net_sweep.py
	python examples/withholding_sweep.py

ghostdag:  ## BASELINE config 5: native compile + mesh-sharded VI
	$(CPU_MESH) CPR_PLATFORM=cpu python examples/solve_ghostdag_mdp.py 7

train-dummy:  ## smoke the config-driven PPO driver
	python examples/train_ppo.py cpr_tpu/train/configs/dummy.yaml /tmp/cpr-train-dummy 4

native:  ## (re)build both C++ libraries
	python -c "import cpr_tpu.native as n; n.lib(); import cpr_tpu.mdp.generic.native as g; g.lib(); print('native libs ready')"

asan:  ## AddressSanitizer pass over both native libraries
	g++ -O1 -g -fsanitize=address -std=c++17 -shared -fPIC \
		cpr_tpu/native/src/generic_compiler.cpp -o /tmp/libgc_asan.so
	g++ -O1 -g -fsanitize=address -std=c++17 -shared -fPIC \
		cpr_tpu/native/src/oracle.cpp -o /tmp/liborc_asan.so
	LD_PRELOAD=$$(g++ -print-file-name=libasan.so) \
		ASAN_OPTIONS=detect_leaks=0:abort_on_error=1 \
		python tools/asan_drive.py
