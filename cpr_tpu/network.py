"""Network topology model + GraphML round-trip + simulation bridge.

Reference counterpart: simulator/lib/network.ml — the topology record
(nodes with compute + delay-distribution links, :3-33), constructors
symmetric_clique / two_agents / selfish_mining (:36-105), and the
GraphML round-trip used by graphml_runner and the igraph topology
studies (:115-232; experiments/simulate-topology/igraph.ml).

Custom topologies execute on the C++ oracle through its custom-link C
API; constant/uniform/exponential link delays map directly, other
distributions are rejected at run time.
"""

from __future__ import annotations

import ctypes
from dataclasses import dataclass, field
from xml.etree import ElementTree as ET

from cpr_tpu import distributions as dist
from cpr_tpu.native import OracleSim, lib


@dataclass
class Link:
    dest: int
    delay: dist.Distribution


@dataclass
class NetNode:
    compute: float
    links: list[Link] = field(default_factory=list)


@dataclass
class Network:
    nodes: list[NetNode]
    activation_delay: float = 1.0
    dissemination: str = "simple"


def symmetric_clique(n: int, *, activation_delay: float,
                     propagation_delay: float) -> Network:
    """network.ml:36-48."""
    d = dist.constant(propagation_delay)
    return Network(
        nodes=[NetNode(1.0 / n, [Link(j, d) for j in range(n) if j != i])
               for i in range(n)],
        activation_delay=activation_delay)


def two_agents(*, alpha: float, activation_delay: float) -> Network:
    """network.ml:50-59."""
    z = dist.constant(0.0)
    return Network(nodes=[NetNode(alpha, [Link(1, z)]),
                          NetNode(1.0 - alpha, [Link(0, z)])],
                   activation_delay=activation_delay)


def selfish_mining(*, alpha: float, gamma: float, defenders: int,
                   activation_delay: float,
                   propagation_delay: float) -> Network:
    """network.ml:61-105: gamma emulated by uniform attacker delays."""
    assert defenders >= 2
    d = defenders
    if gamma > (d - 1) / d:
        raise ValueError("gamma must not exceed (defenders-1)/defenders")
    g = max(gamma, 1e-6)  # see the oracle's gamma-0 note
    atk = dist.uniform(0.0, (d - 1) / d * propagation_delay / g)
    prop = dist.constant(propagation_delay)
    zero = dist.constant(0.0)
    nodes = [NetNode(alpha, [Link(j, atk) for j in range(1, d + 1)])]
    for i in range(1, d + 1):
        links = [Link(0, zero)]
        links += [Link(j, prop) for j in range(1, d + 1) if j != i]
        nodes.append(NetNode((1.0 - alpha) / d, links))
    return Network(nodes=nodes, activation_delay=activation_delay)


def random_regular(n: int, degree: int, *, activation_delay: float,
                   delay: dist.Distribution, compute=None,
                   seed: int = 0) -> Network:
    """Random connected degree-regular-ish topology — the stand-in for
    the reference's R/igraph-generated networks
    (experiments/simulate-topology/igraph.ml:1-50): a ring guarantees
    connectivity, random chords raise the degree; links are
    bidirectional."""
    import random as _random

    assert n >= 3 and degree >= 2
    rng = _random.Random(seed)
    # connected ring, normalized (a < b) so dedup sees every edge
    edges = {(min(i, (i + 1) % n), max(i, (i + 1) % n)) for i in range(n)}
    degs = [2] * n
    deficient = sum(1 for d in degs if d < degree)

    tries = 0
    while deficient > 0 and tries < n * degree * 10:
        tries += 1
        a, b = rng.randrange(n), rng.randrange(n)
        if a == b:
            continue
        e = (min(a, b), max(a, b))
        if e in edges or degs[a] >= degree or degs[b] >= degree:
            continue
        edges.add(e)
        for v in (a, b):
            degs[v] += 1
            if degs[v] == degree:
                deficient -= 1
    if compute is None:
        compute = [1.0 / n] * n
    nodes = [NetNode(c) for c in compute]
    for a, b in sorted(edges):
        nodes[a].links.append(Link(b, delay))
        nodes[b].links.append(Link(a, delay))
    # sparse graphs need relaying to converge (simulator.ml:494-507)
    return Network(nodes=nodes, activation_delay=activation_delay,
                   dissemination="flooding")


def preferential_attachment(n: int, m: int = 2, *,
                            distribution: str = "constant",
                            seed: int = 0) -> Network:
    """Barabási–Albert topology with the reference generator's node and
    edge attributes (experiments/simulate-topology/create-networks.R):
    exponential per-node solving rates normalized into compute shares,
    edge distances uniform in [1, 10], per-edge delay distribution keyed
    on the distance (constant / uniform +-50% / exponential with the
    distance as mean), flooding dissemination, and activation_delay set
    to 2x the mean compute-weighted distance (`net_bias`) so block
    intervals sit just above the expected message delay."""
    import random as _random

    assert n >= m + 1 and m >= 1
    rng = _random.Random(seed)
    # igraph sample_pa shape: grow from one vertex; each new vertex
    # attaches m edges to distinct existing vertices with probability
    # proportional to degree + 1 (zero-appeal keeps isolated targets
    # reachable)
    edges: set[tuple[int, int]] = set()
    degs = [0] * n
    for i in range(1, n):
        pool = list(range(i))
        weights = [degs[j] + 1 for j in pool]
        targets: set[int] = set()
        while len(targets) < min(m, i):
            (j,) = rng.choices(pool, weights=weights)
            targets.add(j)
        for j in targets:
            edges.add((j, i))
            degs[i] += 1
            degs[j] += 1

    rates = [rng.expovariate(1.0) for _ in range(n)]
    total = sum(rates)
    nodes = [NetNode(r / total) for r in rates]
    for a, b in sorted(edges):
        distance = rng.uniform(1.0, 10.0)
        if distribution == "constant":
            d = dist.constant(distance)
        elif distribution == "uniform":
            d = dist.uniform(0.5 * distance, 1.5 * distance)
        elif distribution == "exponential":
            d = dist.exponential(distance)
        else:
            raise ValueError(f"unknown distribution '{distribution}'")
        nodes[a].links.append(Link(b, d))
        nodes[b].links.append(Link(a, d))
    net = Network(nodes=nodes, dissemination="flooding")
    net.activation_delay = 2.0 * sum(
        s["net_bias"] for s in topology_stats(net)) / n
    return net


def topology_stats(net: Network) -> list[dict]:
    """Per-node farness / closeness / net_bias over expected link
    delays (create-networks.R:36-41): farness is the mean shortest-path
    distance to the other nodes, closeness its inverse, and net_bias
    the compute-weighted distance — the generator's measure of how far
    a node sits from the hash power."""
    import numpy as np
    from scipy.sparse.csgraph import shortest_path

    n = len(net.nodes)
    w = np.full((n, n), np.inf)
    np.fill_diagonal(w, 0.0)
    for i, nd in enumerate(net.nodes):
        for ln in nd.links:
            # scipy's dense csgraph reads 0 as "no edge" (and its
            # conversion flattens values below ~1e-8 to 0), so a
            # genuine zero-delay link (two_agents/selfish_mining) must
            # carry an epsilon — 1e-6 is six orders below real link
            # distances (1-10) yet survives the conversion
            ev = max(ln.delay.ev, 1e-6)
            w[i, ln.dest] = min(w[i, ln.dest], ev)
    d = shortest_path(w, method="D")
    compute = np.array([nd.compute for nd in net.nodes])
    out = []
    for i in range(n):
        farness = float(d[i].sum() / max(n - 1, 1))
        out.append({
            "farness": farness,
            "closeness": 1.0 / farness if farness > 0 else float("inf"),
            "net_bias": float((compute * d[i]).sum()),
        })
    return out


def write_topology_batch(outdir: str, *, count: int = 10, n: int = 13,
                         m: int = 2,
                         distributions=("constant", "uniform",
                                        "exponential"),
                         seed: int = 42) -> list[str]:
    """The create-networks.R batch: `count` preferential-attachment
    topologies per delay distribution, written as GraphML into
    `outdir` (consumed by experiments.graphml_runner / Network
    simulate)."""
    import os

    from cpr_tpu.resilience import atomic_write_text

    os.makedirs(outdir, exist_ok=True)
    paths = []
    tag = {"constant": "cns", "uniform": "uni", "exponential": "exp"}
    for di, distribution in enumerate(distributions):
        for i in range(count):
            net = preferential_attachment(
                n, m, distribution=distribution,
                seed=seed + i * 31 + di * 1009)
            path = os.path.join(
                outdir, f"{i + 1:03d}-{tag[distribution]}-graphml.xml")
            atomic_write_text(path, to_graphml(net))
            paths.append(path)
    return paths


# -- GraphML round-trip ------------------------------------------------------


def to_graphml(net: Network) -> str:
    """network.ml:115-170 analog: nodes carry compute, edges carry the
    link-delay distribution string; graph data holds activation delay
    and dissemination."""
    root = ET.Element("graphml",
                      xmlns="http://graphml.graphdrawing.org/xmlns")
    for kid, name, typ, dom in [
            ("d0", "activation_delay", "double", "graph"),
            ("d1", "dissemination", "string", "graph"),
            ("d2", "compute", "double", "node"),
            ("d3", "delay", "string", "edge")]:
        el = ET.SubElement(root, "key", id=kid)
        el.set("for", dom)
        el.set("attr.name", name)
        el.set("attr.type", typ)
    graph = ET.SubElement(root, "graph", edgedefault="directed")
    ET.SubElement(graph, "data", key="d0").text = \
        repr(net.activation_delay)
    ET.SubElement(graph, "data", key="d1").text = net.dissemination
    for i, node in enumerate(net.nodes):
        el = ET.SubElement(graph, "node", id=f"n{i}")
        ET.SubElement(el, "data", key="d2").text = repr(node.compute)
    for i, node in enumerate(net.nodes):
        for link in node.links:
            el = ET.SubElement(graph, "edge", source=f"n{i}",
                               target=f"n{link.dest}")
            ET.SubElement(el, "data", key="d3").text = \
                link.delay.to_string()
    return ET.tostring(root, encoding="unicode")


def of_graphml(xml: str) -> Network:
    root = ET.fromstring(xml)

    def strip(tag):
        return tag.rsplit("}", 1)[-1]

    keys = {}
    for el in root:
        if strip(el.tag) == "key":
            keys[el.get("id")] = el.get("attr.name")
    graph = next(el for el in root if strip(el.tag) == "graph")
    undirected = graph.get("edgedefault") == "undirected"
    activation_delay, dissemination = 1.0, "simple"
    node_ids: dict[str, int] = {}
    nodes: list[NetNode] = []
    for el in graph:
        tag = strip(el.tag)
        if tag == "data":
            name = keys.get(el.get("key"))
            if name == "activation_delay":
                activation_delay = float(el.text)
            elif name == "dissemination":
                dissemination = el.text.strip()
        elif tag == "node":
            compute = 0.0
            for d in el:
                if keys.get(d.get("key")) == "compute":
                    compute = float(d.text)
            node_ids[el.get("id")] = len(nodes)
            nodes.append(NetNode(compute))
    for el in graph:
        if strip(el.tag) == "edge":
            delay = dist.constant(0.0)
            for d in el:
                if keys.get(d.get("key")) == "delay":
                    delay = dist.of_string(d.text)
            src = node_ids[el.get("source")]
            dst = node_ids[el.get("target")]
            nodes[src].links.append(Link(dst, delay))
            if undirected:
                nodes[dst].links.append(Link(src, delay))
    return Network(nodes=nodes, activation_delay=activation_delay,
                   dissemination=dissemination)


# -- execution on the oracle -------------------------------------------------

_KINDS = {"constant": 0, "uniform": 1, "exponential": 2}


def simulate(net: Network, *, protocol: str = "nakamoto", k: int = 0,
             scheme: str = "", activations: int, seed: int = 0):
    """Run an arbitrary topology on the C++ oracle
    (simulate-topology/igraph.ml + graphml_runner analog).  Returns the
    OracleSim after `activations` puzzle solutions."""
    if net.dissemination not in ("simple", "flooding"):
        raise ValueError(
            f"unknown dissemination '{net.dissemination}'")
    n = len(net.nodes)
    L = lib()
    L.cpr_oracle_create_custom.restype = ctypes.c_void_p
    L.cpr_oracle_create_custom.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
        ctypes.c_double, ctypes.c_int, ctypes.c_uint64]
    compute = (ctypes.c_double * n)(*[nd.compute for nd in net.nodes])
    kind = (ctypes.c_int * (n * n))()
    p0 = (ctypes.c_double * (n * n))()
    p1 = (ctypes.c_double * (n * n))()
    # unlinked pairs: kind -1 tells the oracle to skip the send
    # entirely (no dead events in the queue)
    for i in range(n * n):
        kind[i] = -1
    for i, nd in enumerate(net.nodes):
        for link in nd.links:
            j = i * n + link.dest
            d = link.delay
            if d.kind not in _KINDS:
                raise ValueError(
                    f"oracle supports constant/uniform/exponential link "
                    f"delays, not '{d.kind}'")
            kind[j] = _KINDS[d.kind]
            p0[j] = d.params[0]
            p1[j] = d.params[1] if len(d.params) > 1 else 0.0
    handle = L.cpr_oracle_create_custom(
        protocol.encode(), k, scheme.encode(), n, compute, kind, p0, p1,
        net.activation_delay,
        1 if net.dissemination == "flooding" else 0, seed)
    if not handle:
        raise ValueError(f"oracle rejected protocol '{protocol}'")
    sim = OracleSim.__new__(OracleSim)
    sim._lib = L
    sim._h = handle
    sim.run(activations)
    return sim
