"""Network topology model + GraphML round-trip + simulation bridge.

Reference counterpart: simulator/lib/network.ml — the topology record
(nodes with compute + delay-distribution links, :3-33), constructors
symmetric_clique / two_agents / selfish_mining (:36-105), and the
GraphML round-trip used by graphml_runner and the igraph topology
studies (:115-232; experiments/simulate-topology/igraph.ml).

Custom topologies execute on the C++ oracle through its custom-link C
API; constant/uniform/exponential link delays map directly, other
distributions are rejected at run time.
"""

from __future__ import annotations

import ctypes
from dataclasses import dataclass, field
from xml.etree import ElementTree as ET

from cpr_tpu import distributions as dist
from cpr_tpu.native import OracleSim, lib


@dataclass
class Link:
    dest: int
    delay: dist.Distribution


@dataclass
class NetNode:
    compute: float
    links: list[Link] = field(default_factory=list)


@dataclass
class Network:
    nodes: list[NetNode]
    activation_delay: float = 1.0
    dissemination: str = "simple"


def symmetric_clique(n: int, *, activation_delay: float,
                     propagation_delay: float) -> Network:
    """network.ml:36-48."""
    d = dist.constant(propagation_delay)
    return Network(
        nodes=[NetNode(1.0 / n, [Link(j, d) for j in range(n) if j != i])
               for i in range(n)],
        activation_delay=activation_delay)


def two_agents(*, alpha: float, activation_delay: float) -> Network:
    """network.ml:50-59."""
    z = dist.constant(0.0)
    return Network(nodes=[NetNode(alpha, [Link(1, z)]),
                          NetNode(1.0 - alpha, [Link(0, z)])],
                   activation_delay=activation_delay)


def selfish_mining(*, alpha: float, gamma: float, defenders: int,
                   activation_delay: float,
                   propagation_delay: float) -> Network:
    """network.ml:61-105: gamma emulated by uniform attacker delays."""
    assert defenders >= 2
    d = defenders
    if gamma > (d - 1) / d:
        raise ValueError("gamma must not exceed (defenders-1)/defenders")
    g = max(gamma, 1e-6)  # see the oracle's gamma-0 note
    atk = dist.uniform(0.0, (d - 1) / d * propagation_delay / g)
    prop = dist.constant(propagation_delay)
    zero = dist.constant(0.0)
    nodes = [NetNode(alpha, [Link(j, atk) for j in range(1, d + 1)])]
    for i in range(1, d + 1):
        links = [Link(0, zero)]
        links += [Link(j, prop) for j in range(1, d + 1) if j != i]
        nodes.append(NetNode((1.0 - alpha) / d, links))
    return Network(nodes=nodes, activation_delay=activation_delay)


def random_regular(n: int, degree: int, *, activation_delay: float,
                   delay: dist.Distribution, compute=None,
                   seed: int = 0) -> Network:
    """Random connected degree-regular-ish topology — the stand-in for
    the reference's R/igraph-generated networks
    (experiments/simulate-topology/igraph.ml:1-50): a ring guarantees
    connectivity, random chords raise the degree; links are
    bidirectional."""
    import random as _random

    assert n >= 3 and degree >= 2
    rng = _random.Random(seed)
    # connected ring, normalized (a < b) so dedup sees every edge
    edges = {(min(i, (i + 1) % n), max(i, (i + 1) % n)) for i in range(n)}
    degs = [2] * n
    deficient = sum(1 for d in degs if d < degree)

    tries = 0
    while deficient > 0 and tries < n * degree * 10:
        tries += 1
        a, b = rng.randrange(n), rng.randrange(n)
        if a == b:
            continue
        e = (min(a, b), max(a, b))
        if e in edges or degs[a] >= degree or degs[b] >= degree:
            continue
        edges.add(e)
        for v in (a, b):
            degs[v] += 1
            if degs[v] == degree:
                deficient -= 1
    if compute is None:
        compute = [1.0 / n] * n
    nodes = [NetNode(c) for c in compute]
    for a, b in sorted(edges):
        nodes[a].links.append(Link(b, delay))
        nodes[b].links.append(Link(a, delay))
    # sparse graphs need relaying to converge (simulator.ml:494-507)
    return Network(nodes=nodes, activation_delay=activation_delay,
                   dissemination="flooding")


# -- GraphML round-trip ------------------------------------------------------


def to_graphml(net: Network) -> str:
    """network.ml:115-170 analog: nodes carry compute, edges carry the
    link-delay distribution string; graph data holds activation delay
    and dissemination."""
    root = ET.Element("graphml",
                      xmlns="http://graphml.graphdrawing.org/xmlns")
    for kid, name, typ, dom in [
            ("d0", "activation_delay", "double", "graph"),
            ("d1", "dissemination", "string", "graph"),
            ("d2", "compute", "double", "node"),
            ("d3", "delay", "string", "edge")]:
        el = ET.SubElement(root, "key", id=kid)
        el.set("for", dom)
        el.set("attr.name", name)
        el.set("attr.type", typ)
    graph = ET.SubElement(root, "graph", edgedefault="directed")
    ET.SubElement(graph, "data", key="d0").text = \
        repr(net.activation_delay)
    ET.SubElement(graph, "data", key="d1").text = net.dissemination
    for i, node in enumerate(net.nodes):
        el = ET.SubElement(graph, "node", id=f"n{i}")
        ET.SubElement(el, "data", key="d2").text = repr(node.compute)
    for i, node in enumerate(net.nodes):
        for link in node.links:
            el = ET.SubElement(graph, "edge", source=f"n{i}",
                               target=f"n{link.dest}")
            ET.SubElement(el, "data", key="d3").text = \
                link.delay.to_string()
    return ET.tostring(root, encoding="unicode")


def of_graphml(xml: str) -> Network:
    root = ET.fromstring(xml)

    def strip(tag):
        return tag.rsplit("}", 1)[-1]

    keys = {}
    for el in root:
        if strip(el.tag) == "key":
            keys[el.get("id")] = el.get("attr.name")
    graph = next(el for el in root if strip(el.tag) == "graph")
    undirected = graph.get("edgedefault") == "undirected"
    activation_delay, dissemination = 1.0, "simple"
    node_ids: dict[str, int] = {}
    nodes: list[NetNode] = []
    for el in graph:
        tag = strip(el.tag)
        if tag == "data":
            name = keys.get(el.get("key"))
            if name == "activation_delay":
                activation_delay = float(el.text)
            elif name == "dissemination":
                dissemination = el.text.strip()
        elif tag == "node":
            compute = 0.0
            for d in el:
                if keys.get(d.get("key")) == "compute":
                    compute = float(d.text)
            node_ids[el.get("id")] = len(nodes)
            nodes.append(NetNode(compute))
    for el in graph:
        if strip(el.tag) == "edge":
            delay = dist.constant(0.0)
            for d in el:
                if keys.get(d.get("key")) == "delay":
                    delay = dist.of_string(d.text)
            src = node_ids[el.get("source")]
            dst = node_ids[el.get("target")]
            nodes[src].links.append(Link(dst, delay))
            if undirected:
                nodes[dst].links.append(Link(src, delay))
    return Network(nodes=nodes, activation_delay=activation_delay,
                   dissemination=dissemination)


# -- execution on the oracle -------------------------------------------------

_KINDS = {"constant": 0, "uniform": 1, "exponential": 2}


def simulate(net: Network, *, protocol: str = "nakamoto", k: int = 0,
             scheme: str = "", activations: int, seed: int = 0):
    """Run an arbitrary topology on the C++ oracle
    (simulate-topology/igraph.ml + graphml_runner analog).  Returns the
    OracleSim after `activations` puzzle solutions."""
    if net.dissemination not in ("simple", "flooding"):
        raise ValueError(
            f"unknown dissemination '{net.dissemination}'")
    n = len(net.nodes)
    L = lib()
    L.cpr_oracle_create_custom.restype = ctypes.c_void_p
    L.cpr_oracle_create_custom.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
        ctypes.c_double, ctypes.c_int, ctypes.c_uint64]
    compute = (ctypes.c_double * n)(*[nd.compute for nd in net.nodes])
    kind = (ctypes.c_int * (n * n))()
    p0 = (ctypes.c_double * (n * n))()
    p1 = (ctypes.c_double * (n * n))()
    # unlinked pairs: kind -1 tells the oracle to skip the send
    # entirely (no dead events in the queue)
    for i in range(n * n):
        kind[i] = -1
    for i, nd in enumerate(net.nodes):
        for link in nd.links:
            j = i * n + link.dest
            d = link.delay
            if d.kind not in _KINDS:
                raise ValueError(
                    f"oracle supports constant/uniform/exponential link "
                    f"delays, not '{d.kind}'")
            kind[j] = _KINDS[d.kind]
            p0[j] = d.params[0]
            p1[j] = d.params[1] if len(d.params) > 1 else 0.0
    handle = L.cpr_oracle_create_custom(
        protocol.encode(), k, scheme.encode(), n, compute, kind, p0, p1,
        net.activation_delay,
        1 if net.dissemination == "flooding" else 0, seed)
    if not handle:
        raise ValueError(f"oracle rejected protocol '{protocol}'")
    sim = OracleSim.__new__(OracleSim)
    sim._lib = L
    sim._h = handle
    sim.run(activations)
    return sim
