"""Sdag — Simple Parallel PoW with DAG-structured voting — under the
SSZ-like withholding attack space, on the DAG tensor substrate.

Reference counterparts:
- protocol: simulator/protocols/sdag.ml — every vertex carries PoW; a
  vote references the *leaves of its miner's current quorum attempt* (so
  votes merge branches; a vote's number = cardinality of its vote
  closure), a block references leaves whose closure has exactly k-1
  votes, all confirming the same previous block (validity sdag.ml:139-172);
  quorum selection altruistic (longest-closure first) and heuristic
  (own-reward *density* greedy) return Full or Partial sets
  (sdag.ml:292-359,360-364); rewards constant/discount — the block miner
  earns 1 and each confirmed vote earns r, discount
  r = (fwd + bwd)/(k-1) with fwd/bwd counted inside the confirmed
  closure (sdag.ml:190-223); preference (height, confirming votes,
  earlier-seen) (sdag.ml:399-413),
- attack space: simulator/protocols/sdag_ssz.ml — 7-field observation
  (sdag_ssz.ml:22-46), Action8 with persistent Proceed/Prolong mining
  filter, prefix release scan, policies honest/release-block/
  override-block/override-catchup/minor-delay/avoid-loss,
- engine semantics: simulator/gym/engine.ml:97-273.

TPU re-design mirrors cpr_tpu.envs.stree; votes are multi-parent, so the
candidate frame closes over all parent columns and quorum sets live as
local boolean masks whose fwd/bwd reward terms are row/column sums of the
ancestor bit-matrix. The heuristic's reward-density argmax evaluates all
candidate additions at once with batched (C, C) matmuls.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from cpr_tpu import obs as obslib
from cpr_tpu.core import dag as D
from cpr_tpu.envs import quorum as Q
from cpr_tpu.envs.base import JaxEnv
from cpr_tpu.params import EnvParams

BLOCK, VOTE = 0, 1
EV_POW, EV_NETWORK = 0, 1

(ADOPT_PROLONG, OVERRIDE_PROLONG, MATCH_PROLONG, WAIT_PROLONG,
 ADOPT_PROCEED, OVERRIDE_PROCEED, MATCH_PROCEED, WAIT_PROCEED) = range(8)

INCENTIVE_SCHEMES = ("constant", "discount")
SUBBLOCK_SELECTIONS = ("altruistic", "heuristic")


def obs_fields(k: int):
    """sdag_ssz.ml:22-46."""
    return (
        obslib.Field("public_blocks", obslib.UINT, scale=1),
        obslib.Field("private_blocks", obslib.UINT, scale=1),
        obslib.Field("diff_blocks", obslib.INT, scale=1),
        obslib.Field("public_votes", obslib.UINT, scale=k),
        obslib.Field("private_votes_inclusive", obslib.UINT,
                     scale=max(k - 1, 1)),
        obslib.Field("private_votes_exclusive", obslib.UINT,
                     scale=max(k - 1, 1)),
        obslib.Field("event", obslib.DISCRETE, n=2),
    )


@struct.dataclass
class State:
    dag: D.Dag
    public: jnp.ndarray
    private: jnp.ndarray
    event: jnp.ndarray
    race_tip: jnp.ndarray
    mining_excl: jnp.ndarray
    stale: jnp.ndarray
    time: jnp.ndarray
    steps: jnp.ndarray
    n_activations: jnp.ndarray
    last_reward_attacker: jnp.ndarray
    last_reward_defender: jnp.ndarray
    last_progress: jnp.ndarray
    last_chain_time: jnp.ndarray
    last_sim_time: jnp.ndarray
    key: jax.Array


class SdagSSZ(JaxEnv):
    n_actions = 8
    # a fresh reset populates genesis + one _mine append; see
    # JaxEnv.reset_dag_rows contract
    reset_dag_rows = 2

    def __init__(self, k: int = 8, incentive_scheme: str = "constant",
                 subblock_selection: str = "heuristic",
                 unit_observation: bool = True, max_steps_hint: int = 256,
                 release_scan: int = 128, window: int | None = None,
                 anc_masks: bool | None = None):
        assert k >= 2  # sdag.ml:3-24 requires k >= 2
        assert incentive_scheme in INCENTIVE_SCHEMES
        assert subblock_selection in SUBBLOCK_SELECTIONS
        self.k = k
        self.q = k - 1
        self.incentive_scheme = incentive_scheme
        self.subblock_selection = subblock_selection
        self.unit_observation = unit_observation
        self.max_parents = max(k - 1, 1)  # leaves only (votes or blocks)
        self.C_MAX = 4 * k + 16
        # one PoW append per step; floored at the candidate window so
        # small hints with large k still hold a full quorum frame
        self.capacity = max(max_steps_hint + 8, self.C_MAX)
        # O(active-set) ring mode (see bk.py): the window must cover the
        # live fork with its vote sub-DAGs (k slots per withheld block)
        # and the C_MAX quorum-candidate frame; evicting a live slot
        # raises overflow like capacity exhaustion in full mode
        if window is not None:
            self.capacity = max(window, self.C_MAX)
        self.ring = window is not None
        # ancestry planes: ON by default only in ring mode (quadratic in
        # capacity; ring retire logic needs the masked queries), full
        # mode keeps the O(B) walk-based queries
        self.anc_masks = self.ring if anc_masks is None else anc_masks
        assert self.anc_masks or not self.ring, \
            "ring windows require anc_masks (walks could cross reclaimed slots)"
        self.STALE_WALK = 4
        self.release_scan = min(release_scan, self.capacity)
        self.fields = obs_fields(k)
        self.observation_length = len(self.fields)
        self.low, self.high = obslib.low_high(self.fields, unit_observation)
        self.policies = self._make_policies()

    # -- protocol primitives (sdag.ml) -------------------------------------

    def confirming(self, dag, b, extra_mask=None):
        # newer_than guards ring reuse: a reclaimed slot could carry a
        # stale signer equal to b's slot index (no-op in full mode)
        m = (dag.exists() & (dag.kind == VOTE) & (dag.signer == b)
             & D.newer_than(dag, b))
        if extra_mask is not None:
            m = m & extra_mask
        return m

    def last_block(self, dag, x):
        return jnp.where(dag.kind[x] == BLOCK, x, dag.signer[x])

    def last_block_all(self, dag):
        """(B,) last_block per slot (Q.last_of_kind_all)."""
        return Q.last_of_kind_all(dag, BLOCK)

    def prev_block(self, dag, b):
        """A block's previous block (sdag.ml:139-172: parent 0's signer).
        Cached in Dag.aux2 at append time — the walked form cost three
        chained gathers per chain level."""
        return dag.aux2[b]

    def block_lca(self, dag, a, b):
        """Common ancestor along the block chain (heights drop by 1 per
        prev_block step)."""
        if dag.has_masks:
            # the chain plane follows prev_block for blocks (appends pass
            # chain_parent=head), so the masked query is exact and cannot
            # cross reclaimed ring slots
            return jnp.maximum(D.common_ancestor_masked(dag, a, b), 0)

        def cond(state):
            x, y = state
            return (x != y) & (x >= 0) & (y >= 0)

        def body(state):
            x, y = state
            hx, hy = dag.height[x], dag.height[y]
            return (jnp.where(hx >= hy, self.prev_block(dag, x), x),
                    jnp.where(hy >= hx, self.prev_block(dag, y), y))

        x, _ = jax.lax.while_loop(cond, body, (a, b))
        return jnp.maximum(x, 0)

    def vote_score(self, dag):
        """compare_votes_in_block: vote number desc, DAG order on ties.
        The tiebreak uses append age relative to the retirement frontier:
        live gids satisfy gid - live_floor in [0, capacity), so the
        fraction stays in [0, 1) across ring wraps (in full mode it
        reduces to the old slots()/capacity form)."""
        age = (dag.age_key() - dag.live_floor).astype(jnp.float32)
        return dag.aux.astype(jnp.float32) - age / self.capacity

    def cmp_blocks(self, dag, x, y, vote_filter_mask):
        """sdag.ml:399-413: height then filtered confirming votes; the
        visible_since tiebreak always favors the incumbent y."""
        nx = self.confirming(dag, x, vote_filter_mask).sum()
        ny = self.confirming(dag, y, vote_filter_mask).sum()
        hx, hy = dag.height[x], dag.height[y]
        return jnp.where(x == y, False,
                         (hx > hy) | ((hx == hy) & (nx > ny)))

    def update_head(self, dag, old, cand, vote_filter_mask):
        return jnp.where(self.cmp_blocks(dag, cand, old, vote_filter_mask),
                         cand, old)

    # -- quorum selection ---------------------------------------------------

    def _select_heuristic(self, cidx, cvalid, abits, own_c):
        """Reward-density greedy (sdag.ml:330-359): repeatedly add the
        candidate whose closure maximizes (own reward gain)/(size gain)
        under the constant scheme, until the set reaches k-1 votes or
        nothing fits. All candidate additions are scored at once: for
        S'_c = S | closure(c), own reward(S') = sum over own x in S' of
        fwd(x) + bwd(x) = column + row sums of abits restricted to S'."""
        C = cidx.shape[0]
        q = self.q
        A = abits.astype(jnp.float32)

        def reward_rows(Sc):
            # Sc: (C, C) row c = candidate-set after adding c
            Sf = Sc.astype(jnp.float32)
            col = Sf @ A          # col[c, x] = |descendants of x in S'_c|
            row = Sf @ A.T        # row[c, x] = |closure(x) ∩ S'_c|
            contrib = (col + row - 1.0) * (own_c & cvalid)[None, :] * Sf
            return contrib.sum(axis=1)

        def body(_, carry):
            S, n, mrn = carry
            Sc = S[None, :] | abits
            size = Sc.sum(axis=1)
            mrt = reward_rows(Sc)
            eligible = cvalid & ~S & (size <= q) & (size > n)
            density = (mrt - mrn) / jnp.maximum(
                (size - n).astype(jnp.float32), 1.0)
            # ties -> first candidate in DAG order
            density = density - jnp.arange(C) * 1e-7
            density = jnp.where(eligible & (n < q), density, -jnp.inf)
            c = jnp.argmax(density).astype(jnp.int32)
            ok = density[c] > -jnp.inf
            S = jnp.where(ok, Sc[c], S)
            return (S, jnp.where(ok, size[c], n),
                    jnp.where(ok, mrt[c], mrn))

        z = jnp.zeros((C,), jnp.bool_)
        S, n, _ = jax.lax.fori_loop(
            0, max(q, 1), body, (z, jnp.int32(0), jnp.float32(0.0)))
        return S, n

    def select(self, dag, b, voter, vote_filter_mask, view_mask):
        """Full/Partial vote-set selection (sdag.ml:292-364). Returns
        (full, n, leaves_row) where leaves_row lists the true leaves of
        the selected set (finalize_quorum, sdag.ml:366-377), -1 padded."""
        cand = self.confirming(dag, b) & vote_filter_mask & view_mask
        own = dag.miner == voter
        cidx, cvalid, abits, oh = Q.candidate_frame(
            dag, cand, self.C_MAX, VOTE, max_vote_parents=self.max_parents)
        if self.subblock_selection == "altruistic":
            seen = jnp.where(voter == D.ATTACKER, dag.born_at,
                             dag.vis_d_since)
            n, S, _, _ = Q.quorum_altruistic(
                dag, cidx, cvalid, abits, oh, own, seen, dag.aux, self.q)
        else:
            own_c = (Q.oh_gather(oh, own) > 0.5)
            S, n = self._select_heuristic(cidx, cvalid, abits, own_c)
        # true leaves: x in S with no other S-member having x in its
        # closure (column count == 1)
        desc_in_S = (abits & S[:, None]).sum(axis=0)
        leaves_c = S & (desc_in_S == 1)
        row = Q.leaves_to_row(dag, cidx, leaves_c, cvalid, self.max_parents,
                              self.vote_score(dag))
        return (n == self.q), n, row, (cidx, cvalid, abits, S)

    def block_reward(self, dag, frame, miner):
        """sdag.ml:190-223: block miner earns 1; each confirmed vote v
        earns r = discount ? (fwd(v)+bwd(v))/(k-1) : 1 with fwd/bwd inside
        the confirmed closure."""
        cidx, cvalid, abits, S = frame
        A = abits.astype(jnp.float32)
        Sf = (S & cvalid).astype(jnp.float32)
        fwd = (Sf[:, None] * A).sum(axis=0)   # |descendants of x in S|
        bwd = (A * Sf[None, :]).sum(axis=1)   # |closure(x) ∩ S|
        if self.incentive_scheme == "discount":
            r = (fwd + bwd - 1.0) / max(self.q, 1)
        else:
            r = jnp.ones_like(fwd)
        in_S = S & cvalid
        m = dag.miner[jnp.maximum(cidx, 0)]
        atk = (jnp.where(in_S & (m == D.ATTACKER), r, 0.0).sum()
               + (miner == D.ATTACKER))
        dfn = (jnp.where(in_S & (m == D.DEFENDER), r, 0.0).sum()
               + (miner == D.DEFENDER))
        return atk, dfn

    def _mine_one(self, dag, head, view, vote_filter, miner, time, powh):
        """puzzle_payload' (sdag.ml:366-397): block on a Full selection,
        else a vote referencing the leaves of the Partial selection (or
        the block itself when empty)."""
        full, n, leaves_row, frame = self.select(
            dag, head, miner, vote_filter, view)
        atk, dfn = self.block_reward(dag, frame, miner)
        row_first_vote = jnp.full((self.max_parents,), D.NONE, jnp.int32
                                  ).at[0].set(head)
        row = jnp.where(full | (n > 0), leaves_row, row_first_vote)
        kind = jnp.where(full, BLOCK, VOTE)
        height = dag.height[head] + jnp.where(full, 1, 0)
        aux = jnp.where(full, 0, n + 1)  # vote number = closure size
        signer = jnp.where(full, D.NONE, head)
        progress = (height * self.k + aux).astype(jnp.float32)
        dag, idx = D.append(
            dag, row, kind=kind, height=height, aux=aux, pow_hash=powh,
            signer=signer, miner=miner, vis_a=True,
            vis_d=(miner == D.DEFENDER), time=time,
            reward_atk=jnp.where(full, atk, 0.0),
            reward_def=jnp.where(full, dfn, 0.0),
            progress=progress,
            # blocks cache their previous block (prev_block); votes
            # keep NONE (their chain queries go through signer)
            aux2=jnp.where(full, head, D.NONE),
            # point the chain plane at the block chain: a block's
            # parent0 is a leaf vote, so block_lca's masked path needs
            # the explicit prev-block pointer (votes keep parent0)
            chain_parent=jnp.where(full, head, row[0]))
        return dag, idx, full

    # -- env API (mirrors cpr_tpu.envs.stree) -------------------------------

    def reset(self, key: jax.Array, params: EnvParams):
        dag = D.empty(self.capacity, self.max_parents, ring=self.ring,
                      anc_masks=self.anc_masks)
        dag, root = D.append(
            dag, jnp.full((self.max_parents,), D.NONE, jnp.int32),
            kind=BLOCK, height=0, miner=D.NONE, vis_a=True, vis_d=True,
            time=0.0, progress=0.0)
        z = jnp.int32(0)
        f = jnp.float32(0.0)
        state = State(
            dag=dag, public=root, private=root,
            event=jnp.int32(EV_POW), race_tip=D.NONE,
            mining_excl=jnp.bool_(False),
            stale=jnp.zeros((self.capacity,), jnp.bool_),
            time=f, steps=z, n_activations=z,
            last_reward_attacker=f, last_reward_defender=f,
            last_progress=f, last_chain_time=f, last_sim_time=f,
            key=key,
        )
        state = self._mine(state, params)
        return state, self.observe(state)

    def _mine(self, state: State, params: EnvParams) -> State:
        dag = state.dag
        key, k_dt, k_mine, k_hash, k_gamma = jax.random.split(state.key, 5)
        dt = jax.random.exponential(k_dt) * params.activation_delay
        time = state.time + dt
        attacker = jax.random.uniform(k_mine) < params.alpha
        powh = jax.random.uniform(k_hash)

        tgt = jnp.maximum(state.race_tip, 0)
        still_tie = ((state.race_tip >= 0)
                     & ~self.cmp_blocks(dag, state.public, tgt, dag.vis_d)
                     & ~self.cmp_blocks(dag, tgt, state.public, dag.vis_d))
        gamma_hit = (~attacker & still_tie
                     & (jax.random.uniform(k_gamma) < params.gamma))
        def_head = jnp.where(gamma_hit, tgt, state.public)
        race_tip = jnp.where(attacker, state.race_tip, D.NONE)

        atk_filter = jnp.where(state.mining_excl,
                               dag.miner == D.ATTACKER, dag.exists())
        head = jnp.where(attacker, state.private, def_head)
        view = jnp.where(attacker, dag.vis_a, dag.vis_d)
        filt = jnp.where(attacker, atk_filter, dag.exists())
        miner = jnp.where(attacker, D.ATTACKER, D.DEFENDER)
        dag, idx, is_blk = self._mine_one(
            dag, head, view, filt, miner, time, powh)
        # the append may reclaim a ring slot whose stale bit is set;
        # the new occupant starts fresh (no-op in full mode)
        stale = state.stale.at[idx].set(False)

        private = jnp.where(attacker & is_blk, idx, state.private)
        public = jnp.where(
            attacker, state.public,
            jnp.where(is_blk,
                      self.update_head(dag, def_head, idx, dag.vis_d),
                      def_head))
        return state.replace(
            dag=dag, private=private, public=public, race_tip=race_tip,
            stale=stale,
            event=jnp.where(attacker, EV_POW, EV_NETWORK).astype(jnp.int32),
            time=time, n_activations=state.n_activations + 1, key=key,
        )

    def observe(self, state: State):
        """sdag_ssz.ml:226-249."""
        dag = state.dag
        ca = self.block_lca(dag, state.public, state.private)
        pub_votes = self.confirming(dag, state.public, dag.vis_d).sum()
        priv_inc = self.confirming(dag, state.private).sum()
        priv_exc = self.confirming(dag, state.private,
                                   dag.miner == D.ATTACKER).sum()
        return obslib.encode(
            self.fields,
            (
                dag.height[state.public] - dag.height[ca],
                dag.height[state.private] - dag.height[ca],
                dag.height[state.private] - dag.height[state.public],
                pub_votes, priv_inc, priv_exc,
                state.event,
            ),
            self.unit_observation,
        )

    def _release_sets(self, state: State):
        """Prefix release scan via the shared dense implementation."""
        dag = state.dag
        cands = dag.exists() & ~dag.vis_d & ~state.stale
        return Q.prefix_release_sets(
            dag, state.public, state.private, cands, self.release_scan,
            self.last_block_all(dag), self.cmp_blocks)

    def _apply(self, state: State, action) -> State:
        dag = state.dag
        is_adopt = (action == ADOPT_PROLONG) | (action == ADOPT_PROCEED)
        is_override = (action == OVERRIDE_PROLONG) | (action == OVERRIDE_PROCEED)
        is_match = (action == MATCH_PROLONG) | (action == MATCH_PROCEED)
        is_release = is_override | is_match
        mining_excl = action < 4

        override_set, match_set, found, new_head = self._release_sets(state)
        mask = jnp.where(is_override, override_set,
                         jnp.where(is_match, match_set,
                                   jnp.zeros_like(match_set)))
        released = D.release(dag, mask, state.time)
        dag = D.select_vis(is_release, released, dag)

        public = jnp.where(is_override & found, new_head, state.public)
        private = jnp.where(is_adopt, public, state.private)

        stale = Q.stale_after_adopt(
            dag, public, state.stale, is_adopt, self.release_scan,
            self.STALE_WALK, self.last_block_all(dag),
            lambda d, i: self.prev_block(d, i))

        rel_tip = D.last_by_age(dag, match_set)
        race_tip = jnp.where(
            is_match & found & (rel_tip >= 0),
            self.last_block(dag, jnp.maximum(rel_tip, 0)),
            jnp.where(is_adopt | is_override, D.NONE, state.race_tip))

        return state.replace(dag=dag, public=public, private=private,
                             race_tip=race_tip, stale=stale,
                             mining_excl=jnp.asarray(mining_excl))

    def step(self, state: State, action, params: EnvParams):
        state = self._apply(state, action)
        state = self._mine(state, params)
        state = state.replace(steps=state.steps + 1)
        dag = state.dag

        if self.ring:
            # retire everything strictly below the block-chain LCA of the
            # two heads: the race (both block forks and their vote
            # sub-DAGs) lives at or above it, so older slots are free to
            # be reclaimed by the ring
            ca = self.block_lca(dag, state.public, state.private)
            dag = D.retire_below(dag, dag.gid[jnp.maximum(ca, 0)])
            state = state.replace(
                dag=dag, race_tip=D.drop_if_retired(dag, state.race_tip))

        n_pub = self.confirming(dag, state.public).sum()
        n_priv = self.confirming(dag, state.private).sum()
        pub_better = (dag.height[state.public] > dag.height[state.private]) | (
            (dag.height[state.public] == dag.height[state.private])
            & (n_pub > n_priv))
        head = jnp.where(pub_better, state.public, state.private)

        return self.finish_step(
            state, params,
            reward_attacker=dag.cum_atk[head],
            reward_defender=dag.cum_def[head],
            progress=(dag.height[head] * self.k).astype(jnp.float32),
            chain_time=dag.born_at[head],
            extra_done=dag.overflow,
        )

    # -- policies (sdag_ssz.ml Policies) ------------------------------------

    def _make_policies(self):
        k = self.k

        def wrap(fn):
            def wrapped(obs):
                pub_b, priv_b, _, pub_v, priv_vi, priv_ve, _ev = \
                    self.decode_obs(obs)
                return fn(pub_b, priv_b, pub_v, priv_vi, priv_ve)
            return wrapped

        def honest(pub_b, priv_b, pub_v, priv_vi, priv_ve):
            return jnp.where(pub_b > 0, ADOPT_PROCEED, OVERRIDE_PROCEED)

        def release_block(pub_b, priv_b, pub_v, priv_vi, priv_ve):
            return jnp.where(
                priv_b < pub_b, ADOPT_PROCEED,
                jnp.where(priv_b > pub_b, OVERRIDE_PROCEED, WAIT_PROCEED))

        def override_block(pub_b, priv_b, pub_v, priv_vi, priv_ve):
            return jnp.where(
                priv_b < pub_b, ADOPT_PROCEED,
                jnp.where(pub_b == 0, WAIT_PROCEED, OVERRIDE_PROCEED))

        def override_catchup(pub_b, priv_b, pub_v, priv_vi, priv_ve):
            return jnp.where(
                priv_b < pub_b, ADOPT_PROCEED,
                jnp.where(
                    (priv_b == 0) & (pub_b == 0), WAIT_PROCEED,
                    jnp.where(
                        pub_b == 0, WAIT_PROCEED,
                        jnp.where(
                            (priv_vi == 0) & (priv_b == pub_b + 1),
                            OVERRIDE_PROCEED,
                            jnp.where(
                                (pub_b == priv_b)
                                & (priv_vi == pub_v + 1),
                                OVERRIDE_PROCEED,
                                jnp.where(priv_b - pub_b > 10,
                                          OVERRIDE_PROCEED,
                                          WAIT_PROCEED))))))

        def minor_delay(pub_b, priv_b, pub_v, priv_vi, priv_ve):
            return jnp.where(
                pub_b > priv_b, ADOPT_PROCEED,
                jnp.where(pub_b == 0, WAIT_PROCEED, OVERRIDE_PROCEED))

        def avoid_loss(pub_b, priv_b, pub_v, priv_vi, priv_ve):
            hp = pub_b * k + pub_v
            ap = priv_b * k + priv_vi
            return jnp.where(
                pub_b == 0, WAIT_PROCEED,
                jnp.where(
                    (pub_b == 1) & (hp == ap), MATCH_PROCEED,
                    jnp.where(
                        hp > ap, ADOPT_PROCEED,
                        jnp.where(
                            hp == ap - 1, OVERRIDE_PROCEED,
                            jnp.where(pub_b < priv_b - 10,
                                      OVERRIDE_PROCEED, WAIT_PROCEED)))))

        return {
            "honest": wrap(honest),
            "release-block": wrap(release_block),
            "override-block": wrap(override_block),
            "override-catchup": wrap(override_catchup),
            "minor-delay": wrap(minor_delay),
            "avoid-loss": wrap(avoid_loss),
        }
