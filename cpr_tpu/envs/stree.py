"""Stree — Simple Parallel PoW with tree-structured voting — under the
SSZ-like withholding attack space, on the DAG tensor substrate.

Reference counterparts:
- protocol: simulator/protocols/stree.ml — every vertex carries PoW; a
  vote extends the deepest branch confirming a block (depth = parent
  depth + 1, stree.ml:136-144), a block references its parent block plus
  quorum leaves whose vote closure has exactly k-1 votes
  (stree.ml:144-151); quorum selection altruistic/heuristic (+ optimal
  with 100-option cap -> heuristic fallback, stree.ml:383-486); rewards
  constant/discount/punish/hybrid pay the block AND its confirmed votes,
  discount rate (depth+1)/k (stree.ml:176-202); preference (height,
  confirming votes, earlier-seen) (stree.ml:518-531),
- attack space: simulator/protocols/stree_ssz.ml — 10-field observation
  with 2-valued event (stree_ssz.ml:22-44), Action8 with a *persistent*
  Proceed/Prolong mining filter (stree_ssz.ml:166,302-309), release =
  smallest withheld descendant prefix that flips (Override) or ties
  (Match) the defender's head (stree_ssz.ml:272-295), policies honest/
  release-block/override-block/override-catchup/minor-delay/avoid-loss
  (stree_ssz.ml:327-420),
- engine semantics: simulator/gym/engine.ml:97-273.

TPU re-design mirrors cpr_tpu.envs.tailstorm: votes store their block in
the `signer` column, quorum selection runs on the compacted candidate
frame (cpr_tpu.envs.quorum), the release scan is dense prefix algebra,
and descent-from-common-ancestor is tracked with a `stale` bit set at
Adopt. Unlike Tailstorm, blocks carry PoW, so appends are never
deduplicated and there are no Append interactions: one env step = one
attacker action + one Bernoulli(alpha) activation whose payload (block
vs vote) is decided at mining time.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from cpr_tpu import obs as obslib
from cpr_tpu.core import dag as D
from cpr_tpu.envs import quorum as Q
from cpr_tpu.envs.base import JaxEnv
from cpr_tpu.params import EnvParams

BLOCK, VOTE = 0, 1

# events: Discrete [`ProofOfWork; `Network] (stree_ssz.ml:49)
EV_POW, EV_NETWORK = 0, 1

(ADOPT_PROLONG, OVERRIDE_PROLONG, MATCH_PROLONG, WAIT_PROLONG,
 ADOPT_PROCEED, OVERRIDE_PROCEED, MATCH_PROCEED, WAIT_PROCEED) = range(8)

INCENTIVE_SCHEMES = ("constant", "discount", "punish", "hybrid")
SUBBLOCK_SELECTIONS = ("altruistic", "heuristic", "optimal")


def obs_fields(k: int):
    """stree_ssz.ml:22-49: public_votes/public_depth scale with k
    (stree_ssz.ml:43,46), the private_* fields with k-1."""
    q = max(k - 1, 1)
    return (
        obslib.Field("public_blocks", obslib.UINT, scale=1),
        obslib.Field("private_blocks", obslib.UINT, scale=1),
        obslib.Field("diff_blocks", obslib.INT, scale=1),
        obslib.Field("public_votes", obslib.UINT, scale=k),
        obslib.Field("private_votes_inclusive", obslib.UINT, scale=q),
        obslib.Field("private_votes_exclusive", obslib.UINT, scale=q),
        obslib.Field("public_depth", obslib.UINT, scale=k),
        obslib.Field("private_depth_inclusive", obslib.UINT, scale=q),
        obslib.Field("private_depth_exclusive", obslib.UINT, scale=q),
        obslib.Field("event", obslib.DISCRETE, n=2),
    )


@struct.dataclass
class State:
    dag: D.Dag
    public: jnp.ndarray
    private: jnp.ndarray
    event: jnp.ndarray
    race_tip: jnp.ndarray  # live match race target block (-1: none)
    mining_excl: jnp.ndarray  # bool: Prolong = exclusive vote filter
    stale: jnp.ndarray  # (B,) withheld blocks abandoned at an Adopt
    time: jnp.ndarray
    steps: jnp.ndarray
    n_activations: jnp.ndarray
    last_reward_attacker: jnp.ndarray
    last_reward_defender: jnp.ndarray
    last_progress: jnp.ndarray
    last_chain_time: jnp.ndarray
    last_sim_time: jnp.ndarray
    key: jax.Array


class StreeSSZ(JaxEnv):
    n_actions = 8
    # a fresh reset populates genesis + one _mine append; see
    # JaxEnv.reset_dag_rows contract
    reset_dag_rows = 2

    def __init__(self, k: int = 8, incentive_scheme: str = "constant",
                 subblock_selection: str = "heuristic",
                 unit_observation: bool = True, max_steps_hint: int = 256,
                 release_scan: int = 128, window: int | None = None,
                 anc_masks: bool | None = None):
        assert k >= 2
        assert incentive_scheme in INCENTIVE_SCHEMES
        assert subblock_selection in SUBBLOCK_SELECTIONS
        self.k = k
        self.q = k - 1
        self.incentive_scheme = incentive_scheme
        self.subblock_selection = subblock_selection
        if subblock_selection == "optimal":
            # static n-choose-(k-1) tables; candidate counts beyond the
            # window fall back to heuristic, matching the reference's
            # 100-option cap (stree.ml:389-391)
            self.opt_window = Q.optimal_window(k - 1, 4 * k + 16)
            self.opt_combos = Q.optimal_combos(k - 1, self.opt_window)
        self.unit_observation = unit_observation
        self.max_parents = k  # parent block + k-1 leaves
        self.C_MAX = 4 * k + 16
        # one PoW append per step; floored at the candidate window so
        # small hints with large k still hold a full quorum frame
        self.capacity = max(max_steps_hint + 8, self.C_MAX)
        # O(active-set) ring mode (see bk.py): the window must cover the
        # live fork with its vote trees (k slots per withheld block) and
        # the C_MAX quorum-candidate frame; evicting a live slot raises
        # overflow like capacity exhaustion in full mode
        if window is not None:
            self.capacity = max(window, self.C_MAX)
        self.ring = window is not None
        # ancestry planes: ON by default only in ring mode (quadratic in
        # capacity; ring retire logic needs the masked queries), full
        # mode keeps the O(B) walk-based queries
        self.anc_masks = self.ring if anc_masks is None else anc_masks
        assert self.anc_masks or not self.ring, \
            "ring windows require anc_masks (walks could cross reclaimed slots)"
        self.STALE_WALK = 4
        self.release_scan = min(release_scan, self.capacity)
        self.fields = obs_fields(k)
        self.observation_length = len(self.fields)
        self.low, self.high = obslib.low_high(self.fields, unit_observation)
        self.policies = self._make_policies()

    # -- protocol primitives (stree.ml) ------------------------------------

    def confirming(self, dag, b, extra_mask=None):
        # newer_than: ring-wrap guard against votes of a reclaimed slot's
        # previous occupant aliasing b (no-op in full mode)
        m = (dag.exists() & (dag.kind == VOTE) & (dag.signer == b)
             & D.newer_than(dag, b))
        if extra_mask is not None:
            m = m & extra_mask
        return m

    def last_block(self, dag, x):
        return jnp.where(dag.kind[x] == BLOCK, x, dag.signer[x])

    def last_block_all(self, dag):
        """(B,) last_block per slot (Q.last_of_kind_all)."""
        return Q.last_of_kind_all(dag, BLOCK)

    def common_ancestor(self, dag, a, b):
        """Block-chain LCA (blocks precede via parent slot 0): masked
        chain-row intersection with ancestry planes, else the
        height-synchronized walk (full mode; reclaim-safe there)."""
        if dag.has_masks:
            return D.common_ancestor_masked(dag, a, b)
        return D.common_ancestor_by_height(dag, a, b)

    def vote_score(self, dag):
        """compare_votes_in_block (stree.ml:96-100): depth desc, ties in
        DAG (insertion) order.  The tiebreak fraction uses the age key
        offset by the ring floor — in full mode that is exactly the slot
        id; in ring mode live gids stay within [floor, floor + W) absent
        overflow, so the fraction keeps insertion order without
        interleaving depths.  (Entries outside the live set may fall
        outside [0, 1); every consumer masks to live candidates.)"""
        age = (dag.age_key() - dag.live_floor).astype(jnp.float32)
        return dag.aux.astype(jnp.float32) - age / self.capacity

    def cmp_blocks(self, dag, x, y, vote_filter_mask):
        """stree.ml:518-527: height, filtered confirming votes; the
        visible_since tiebreak always favors the incumbent `y` (x is the
        newer block), so strict (height, count) decides."""
        nx = self.confirming(dag, x, vote_filter_mask).sum()
        ny = self.confirming(dag, y, vote_filter_mask).sum()
        hx, hy = dag.height[x], dag.height[y]
        return jnp.where(x == y, False,
                         (hx > hy) | ((hx == hy) & (nx > ny)))

    def update_head(self, dag, old, cand, vote_filter_mask):
        return jnp.where(self.cmp_blocks(dag, cand, old, vote_filter_mask),
                         cand, old)

    def quorum(self, dag, b, voter, vote_filter_mask, view_mask):
        """k-1 sized vote-closure selection (stree.ml:383-486)."""
        cand = self.confirming(dag, b) & vote_filter_mask & view_mask
        own = dag.miner == voter
        cidx, cvalid, abits, oh = Q.candidate_frame(dag, cand, self.C_MAX, VOTE)
        if self.subblock_selection == "altruistic":
            seen = jnp.where(voter == D.ATTACKER, dag.born_at,
                             dag.vis_d_since)
            n, _, leaves_c, n_cand = Q.quorum_altruistic(
                dag, cidx, cvalid, abits, oh, own, seen, dag.aux, self.q)
            found = (n == self.q) & (n_cand >= self.q)
        elif self.subblock_selection == "optimal":
            # stree pays discount r = (depth+1)/k and also pays the
            # block's miner (stree.ml:188-190), so the scorer gets
            # depth_plus=1 and miner_share=1; leaf preference follows
            # this env's vote_score so punish pays the scored branch
            found, leaves_c = Q.quorum_optimal_or_heuristic(
                dag, cidx, cvalid, abits, oh, own, dag.aux, self.q,
                self.opt_window, self.opt_combos, k=self.k,
                discount=self.incentive_scheme in ("discount", "hybrid"),
                punish=self.incentive_scheme in ("punish", "hybrid"),
                depth_plus=1, leaf_score=self.vote_score(dag),
                miner_share=1)
        else:
            found, leaves_c = Q.quorum_heuristic(
                dag, cidx, cvalid, abits, oh, own, self.q)
        row = Q.leaves_to_row(dag, cidx, leaves_c, cvalid, self.q,
                              self.vote_score(dag))
        return found, row

    def block_reward(self, dag, leaves_row, miner):
        """stree.ml:176-202: the block and its confirmed vote closure each
        earn r; discount r = (depth_first + 1)/k, punish restricts the
        closure to the deepest leaf's branch."""
        discount = self.incentive_scheme in ("discount", "hybrid")
        punish = self.incentive_scheme in ("punish", "hybrid")
        leaves = leaves_row[:1] if punish else leaves_row
        closure = jnp.zeros((self.capacity,), jnp.bool_)
        cur = jnp.where(leaves >= 0, leaves, -1)
        for _ in range(self.C_MAX):
            valid = (cur >= 0) & (dag.kind[jnp.maximum(cur, 0)] == VOTE)
            closure = closure.at[jnp.maximum(cur, 0)].max(valid)
            cur = jnp.where(valid, dag.parent0[jnp.maximum(cur, 0)], -1)
        depth0 = dag.aux[jnp.maximum(leaves_row[0], 0)]
        r = jnp.where(discount, (depth0 + 1).astype(jnp.float32) / self.k,
                      1.0)
        atk = r * ((closure & (dag.miner == D.ATTACKER)).sum()
                   + (miner == D.ATTACKER))
        dfn = r * ((closure & (dag.miner == D.DEFENDER)).sum()
                   + (miner == D.DEFENDER))
        return atk, dfn

    def _mine_one(self, dag, head, view, vote_filter, miner, time, powh):
        """puzzle_payload' (stree.ml:488-516): block draft when a k-1
        quorum exists, else a vote on the deepest filtered branch."""
        found, leaves = self.quorum(dag, head, miner, vote_filter, view)
        # block variant
        row_block = jnp.concatenate(
            [jnp.array([head], jnp.int32), leaves])
        atk, dfn = self.block_reward(dag, leaves, miner)
        # vote variant: deepest filtered+visible vote, else the block
        cand = self.confirming(dag, head, view) & vote_filter
        parent = jnp.where(
            cand.any(),
            jnp.argmax(jnp.where(cand, self.vote_score(dag), -jnp.inf)),
            head).astype(jnp.int32)
        depth = jnp.where(cand.any(), dag.aux[parent] + 1, 1)
        row_vote = jnp.full((self.max_parents,), D.NONE, jnp.int32
                            ).at[0].set(parent)

        row = jnp.where(found, row_block, row_vote)
        kind = jnp.where(found, BLOCK, VOTE)
        height = dag.height[head] + jnp.where(found, 1, 0)
        aux = jnp.where(found, 0, depth)
        signer = jnp.where(found, D.NONE, head)
        progress = (height * self.k + aux).astype(jnp.float32)
        dag, idx = D.append(
            dag, row, kind=kind, height=height, aux=aux, pow_hash=powh,
            signer=signer, miner=miner, vis_a=True,
            vis_d=(miner == D.DEFENDER), time=time,
            reward_atk=jnp.where(found, atk, 0.0),
            reward_def=jnp.where(found, dfn, 0.0),
            progress=progress)
        return dag, idx, found

    # -- env API ------------------------------------------------------------

    def reset(self, key: jax.Array, params: EnvParams):
        dag = D.empty(self.capacity, self.max_parents,
                      ring=self.ring, anc_masks=self.anc_masks)
        dag, root = D.append(
            dag, jnp.full((self.max_parents,), D.NONE, jnp.int32),
            kind=BLOCK, height=0, miner=D.NONE, vis_a=True, vis_d=True,
            time=0.0, progress=0.0)
        z = jnp.int32(0)
        f = jnp.float32(0.0)
        state = State(
            dag=dag, public=root, private=root,
            event=jnp.int32(EV_POW), race_tip=D.NONE,
            mining_excl=jnp.bool_(False),
            stale=jnp.zeros((self.capacity,), jnp.bool_),
            time=f, steps=z, n_activations=z,
            last_reward_attacker=f, last_reward_defender=f,
            last_progress=f, last_chain_time=f, last_sim_time=f,
            key=key,
        )
        state = self._mine(state, params)
        return state, self.observe(state)

    def _mine(self, state: State, params: EnvParams) -> State:
        dag = state.dag
        key, k_dt, k_mine, k_hash, k_gamma = jax.random.split(state.key, 5)
        dt = jax.random.exponential(k_dt) * params.activation_delay
        time = state.time + dt
        attacker = jax.random.uniform(k_mine) < params.alpha
        powh = jax.random.uniform(k_hash)

        # gamma race while the (height, votes) tie is live
        tgt = jnp.maximum(state.race_tip, 0)
        still_tie = ((state.race_tip >= 0)
                     & ~self.cmp_blocks(dag, state.public, tgt, dag.vis_d)
                     & ~self.cmp_blocks(dag, tgt, state.public, dag.vis_d))
        gamma_hit = (~attacker & still_tie
                     & (jax.random.uniform(k_gamma) < params.gamma))
        def_head = jnp.where(gamma_hit, tgt, state.public)
        race_tip = jnp.where(attacker, state.race_tip, D.NONE)

        atk_filter = jnp.where(state.mining_excl,
                               dag.miner == D.ATTACKER, dag.exists())
        head = jnp.where(attacker, state.private, def_head)
        view = jnp.where(attacker, dag.vis_a, dag.vis_d)
        filt = jnp.where(attacker, atk_filter, dag.exists())
        miner = jnp.where(attacker, D.ATTACKER, D.DEFENDER)
        dag, idx, is_blk = self._mine_one(
            dag, head, view, filt, miner, time, powh)
        # the appended slot may be a reclaimed ring slot: clear any stale
        # bit left by its previous occupant (no-op in full mode)
        stale = state.stale.at[idx].set(False)

        private = jnp.where(attacker & is_blk, idx, state.private)
        public = jnp.where(
            attacker, state.public,
            jnp.where(is_blk,
                      self.update_head(dag, def_head, idx, dag.vis_d),
                      def_head))
        return state.replace(
            dag=dag, private=private, public=public, race_tip=race_tip,
            stale=stale,
            event=jnp.where(attacker, EV_POW, EV_NETWORK).astype(jnp.int32),
            time=time, n_activations=state.n_activations + 1, key=key,
        )

    def observe(self, state: State):
        """stree_ssz.ml:242-270."""
        dag = state.dag
        ca = jnp.maximum(
            self.common_ancestor(dag, state.public, state.private), 0)

        def depth_count(mask):
            return (jnp.where(mask, dag.aux, 0).max(), mask.sum())

        pub_d, pub_v = depth_count(self.confirming(dag, state.public,
                                                   dag.vis_d))
        inc_d, inc_v = depth_count(self.confirming(dag, state.private))
        exc_d, exc_v = depth_count(self.confirming(
            dag, state.private, dag.miner == D.ATTACKER))
        return obslib.encode(
            self.fields,
            (
                dag.height[state.public] - dag.height[ca],
                dag.height[state.private] - dag.height[ca],
                dag.height[state.private] - dag.height[state.public],
                pub_v, inc_v, exc_v,
                pub_d, inc_d, exc_d,
                state.event,
            ),
            self.unit_observation,
        )

    def _release_sets(self, state: State):
        """stree_ssz.ml:272-295 via the shared dense prefix scan."""
        dag = state.dag
        cands = dag.exists() & ~dag.vis_d & ~state.stale
        return Q.prefix_release_sets(
            dag, state.public, state.private, cands, self.release_scan,
            self.last_block_all(dag), self.cmp_blocks)

    def _apply(self, state: State, action) -> State:
        """stree_ssz.ml:272-314."""
        dag = state.dag
        is_adopt = (action == ADOPT_PROLONG) | (action == ADOPT_PROCEED)
        is_override = (action == OVERRIDE_PROLONG) | (action == OVERRIDE_PROCEED)
        is_match = (action == MATCH_PROLONG) | (action == MATCH_PROCEED)
        is_release = is_override | is_match
        mining_excl = action < 4

        override_set, match_set, found, new_head = self._release_sets(state)
        mask = jnp.where(is_override, override_set,
                         jnp.where(is_match, match_set,
                                   jnp.zeros_like(match_set)))
        released = D.release(dag, mask, state.time)
        dag = D.select_vis(is_release, released, dag)

        public = jnp.where(is_override & found, new_head, state.public)
        private = jnp.where(is_adopt, public, state.private)

        stale = Q.stale_after_adopt(
            dag, public, state.stale, is_adopt, self.release_scan,
            self.STALE_WALK, self.last_block_all(dag),
            lambda d, i: d.parent0[i])

        # match race target: last block of the latest-appended released
        # vertex, armed only when a flipping prefix exists (last_by_age
        # is the wrap-safe highest-slot max)
        rel_tip = D.last_by_age(dag, match_set)
        race_tip = jnp.where(
            is_match & found & (rel_tip >= 0),
            self.last_block(dag, jnp.maximum(rel_tip, 0)),
            jnp.where(is_adopt | is_override, D.NONE, state.race_tip))

        return state.replace(dag=dag, public=public, private=private,
                             race_tip=race_tip, stale=stale,
                             mining_excl=jnp.asarray(mining_excl))

    def step(self, state: State, action, params: EnvParams):
        state = self._apply(state, action)
        state = self._mine(state, params)
        state = state.replace(steps=state.steps + 1)
        dag = state.dag

        if self.ring:
            # retire everything below the block-chain fork: later reads
            # start at public/private (descendants of their LCA), at
            # votes hanging on live blocks (appended after them, so
            # gid-above the LCA), or at withheld release candidates
            # (mined on the private fork).  The race tip may outlive the
            # fork — drop it while its slot still holds the original.
            ca = self.common_ancestor(dag, state.public, state.private)
            dag = D.retire_below(dag, dag.gid[jnp.maximum(ca, 0)])
            state = state.replace(
                dag=dag, race_tip=D.drop_if_retired(dag, state.race_tip))

        n_pub = self.confirming(dag, state.public).sum()
        n_priv = self.confirming(dag, state.private).sum()
        pub_better = (dag.height[state.public] > dag.height[state.private]) | (
            (dag.height[state.public] == dag.height[state.private])
            & (n_pub > n_priv))
        head = jnp.where(pub_better, state.public, state.private)

        return self.finish_step(
            state, params,
            reward_attacker=dag.cum_atk[head],
            reward_defender=dag.cum_def[head],
            progress=(dag.height[head] * self.k).astype(jnp.float32),
            chain_time=dag.born_at[head],
            extra_done=dag.overflow,
        )

    # -- policies (stree_ssz.ml:327-420) ------------------------------------

    def _make_policies(self):
        k = self.k

        def wrap(fn):
            def wrapped(obs):
                (pub_b, priv_b, _, pub_v, priv_vi, priv_ve,
                 _pd, inc_d, _ed, _ev) = self.decode_obs(obs)
                return fn(pub_b, priv_b, pub_v, priv_vi, priv_ve, inc_d)
            return wrapped

        def honest(pub_b, priv_b, pub_v, priv_vi, priv_ve, inc_d):
            return jnp.where(pub_b > 0, ADOPT_PROCEED, OVERRIDE_PROCEED)

        def release_block(pub_b, priv_b, pub_v, priv_vi, priv_ve, inc_d):
            return jnp.where(
                priv_b < pub_b, ADOPT_PROCEED,
                jnp.where(priv_b > pub_b, OVERRIDE_PROCEED, WAIT_PROCEED))

        def override_block(pub_b, priv_b, pub_v, priv_vi, priv_ve, inc_d):
            return jnp.where(
                priv_b < pub_b, ADOPT_PROCEED,
                jnp.where(pub_b == 0, WAIT_PROCEED, OVERRIDE_PROCEED))

        def override_catchup(pub_b, priv_b, pub_v, priv_vi, priv_ve, inc_d):
            return jnp.where(
                priv_b < pub_b, ADOPT_PROCEED,
                jnp.where(
                    (priv_b == 0) & (pub_b == 0), WAIT_PROCEED,
                    jnp.where(
                        pub_b == 0, WAIT_PROCEED,
                        jnp.where(
                            (inc_d == 0) & (priv_b == pub_b + 1),
                            OVERRIDE_PROCEED,
                            jnp.where(
                                (pub_b == priv_b)
                                & (priv_vi == pub_v + 1),
                                OVERRIDE_PROCEED,
                                jnp.where(priv_b - pub_b > 10,
                                          OVERRIDE_PROCEED,
                                          WAIT_PROCEED))))))

        def minor_delay(pub_b, priv_b, pub_v, priv_vi, priv_ve, inc_d):
            return jnp.where(
                pub_b > priv_b, ADOPT_PROCEED,
                jnp.where(pub_b == 0, WAIT_PROCEED, OVERRIDE_PROCEED))

        def avoid_loss(pub_b, priv_b, pub_v, priv_vi, priv_ve, inc_d):
            hp = pub_b * k + pub_v
            ap = priv_b * k + priv_vi
            return jnp.where(
                pub_b == 0, WAIT_PROCEED,
                jnp.where(
                    (pub_b == 1) & (hp == ap), MATCH_PROCEED,
                    jnp.where(
                        hp > ap, ADOPT_PROCEED,
                        jnp.where(
                            hp == ap - 1, OVERRIDE_PROCEED,
                            jnp.where(pub_b < priv_b - 10,
                                      OVERRIDE_PROCEED, WAIT_PROCEED)))))

        return {
            "honest": wrap(honest),
            "release-block": wrap(release_block),
            "override-block": wrap(override_block),
            "override-catchup": wrap(override_catchup),
            "minor-delay": wrap(minor_delay),
            "avoid-loss": wrap(avoid_loss),
        }
