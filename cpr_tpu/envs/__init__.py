"""Attack environments (jittable JAX kernels); the gymnasium adapters
and registered env ids live in cpr_tpu.gym.

The env contract mirrors the reference engine record
(reference: simulator/gym/intf.ml:3-13): n_actions, observation bounds,
create/reset/step, built-in policies — re-shaped as pure functions
`(state, action) -> (state, obs, reward, done, info)` so that `vmap`
batches thousands of episodes into one XLA program.
"""

from cpr_tpu.envs.registry import get, keys, register  # noqa: F401
