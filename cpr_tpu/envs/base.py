"""Base contract for jittable attack environments.

Reference counterpart: the engine record `{n_actions; observation_length;
create; reset; step; low; high; policies}` (simulator/gym/intf.ml:3-13) and
its construction in `Engine.of_module` (simulator/gym/engine.ml:97-273).

TPU re-design: an environment is a pair of pure functions over a PyTree
state. The state carries its own PRNG key; `step` threads it. Batched
execution is plain `jax.vmap`; episode loops are `lax.scan`.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp

from cpr_tpu.params import EnvParams

# info keys mirror the reference step info list (simulator/gym/engine.ml:224-241)
INFO_KEYS = (
    "step_reward_attacker",
    "step_reward_defender",
    "step_progress",
    "step_chain_time",
    "step_sim_time",
    "episode_reward_attacker",
    "episode_reward_defender",
    "episode_progress",
    "episode_chain_time",
    "episode_sim_time",
    "episode_n_steps",
    "episode_n_activations",
)


def _lane_where(mask, a, b):
    """Per-lane select with the (n_lanes,) mask broadcast over trailing
    axes — the splice primitive of the resident lane API."""
    m = mask.reshape(mask.shape + (1,) * (a.ndim - 1))
    return jnp.where(m, a, b)


def _mesh_wrap(fn, mesh, axis: str):
    """Mesh entry seam for the episode-stats drivers: commit the keys
    to the lane sharding (refusing uneven batches with both values
    named) and let GSPMD partition the built driver.  Sharded inputs
    keep their placement through the chunked host loop, so wrapping
    the entry is enough for every driver shape."""
    if mesh is None:
        return fn
    from jax.sharding import NamedSharding, PartitionSpec

    from cpr_tpu.parallel.lanes import check_even_shards
    sharding = NamedSharding(mesh, PartitionSpec(axis))

    def sharded(keys):
        check_even_shards(keys.shape[0], mesh, axis=axis,
                          what="episode streams")
        return fn(jax.device_put(keys, sharding))

    if hasattr(fn, "metrics_spec"):
        sharded.metrics_spec = fn.metrics_spec
    return sharded


class JaxEnv:
    """Abstract jittable environment.

    Subclasses define:
      n_actions: int
      fields: tuple[obs.Field, ...]
      unit_observation: bool
      reset(key, params) -> (state, obs)
      step(state, action, params) -> (state, obs, reward, done, info)
      policies: dict[str, Callable[obs -> action]]   (jittable)
    """

    n_actions: int
    observation_length: int
    policies: dict[str, Callable]

    # Envs whose state carries a `dag` may set this to the (static)
    # maximum number of DAG rows a fresh reset() can populate to get an
    # O(reset_dag_rows) logical DAG reset in auto-reset streams instead
    # of a full-capacity select.  Contract (checked by
    # tests/test_bk_env.py's logical-reset parity test): (a) reset()
    # appends at most this many rows, (b) every dag read is
    # exists()-masked or reached from a live tip, and (c) append()/
    # append_if() rewrite every field of a claimed slot.  Under that
    # contract the only live dag state across a reset boundary is
    # (n, overflow) plus the first reset_dag_rows rows — selecting just
    # those avoids copying the whole capacity-B structure (the padded
    # parents matrix made the full-tree select ~40 ms/step at 16k envs
    # on v5e).  None = full-tree select (always safe).
    reset_dag_rows: int | None = None

    def select_reset(self, done, rstate, state):
        """where(done, rstate, state) for auto-reset streams."""
        sel = lambda a, b: jnp.where(done, a, b)
        R = self.reset_dag_rows
        if R is None:
            return jax.tree.map(sel, rstate, state)

        def sel_rows(a, b):
            # static top-slice select: rows >= R are dead after a reset
            # (exists()-masked until an append rewrites them)
            if a.ndim == 0:  # n / overflow scalars
                return sel(a, b)
            return b.at[:R].set(jnp.where(done, a[:R], b[:R]))

        dag = jax.tree.map(sel_rows, rstate.dag, state.dag)
        updates = {
            f: jax.tree.map(sel, getattr(rstate, f), getattr(state, f))
            for f in state.__dataclass_fields__ if f != "dag"
        }
        return state.replace(dag=dag, **updates)

    def decode_obs(self, obs):
        """float observation -> per-field natural-scale int values
        (ssz_tools.ml:20-59 of_floatarray)."""
        from cpr_tpu import obs as obslib
        vals = [
            obslib.field_of_float(f, obs[..., i], self.unit_observation)
            for i, f in enumerate(self.fields)
        ]
        return tuple(jnp.asarray(v, jnp.int32) for v in vals)

    def reset(self, key: jax.Array, params: EnvParams):
        raise NotImplementedError

    def step(self, state, action, params: EnvParams):
        raise NotImplementedError

    def finish_step(self, state, params: EnvParams, *, reward_attacker,
                    reward_defender, progress, chain_time,
                    extra_done=False):
        """Shared step epilogue (engine.ml:209-241): termination test,
        reward delta, the step_/episode_ info dict, and the last_*
        bookkeeping. Returns (state, obs, reward, done, info); the state
        must carry the common bookkeeping fields (steps, time, last_*)."""
        done = ~(
            (state.steps < params.max_steps)
            & (progress < params.max_progress)
            & (state.time < params.max_time)
        ) | extra_done
        reward = reward_attacker - state.last_reward_attacker
        info = {
            "step_reward_attacker": reward,
            "step_reward_defender": reward_defender - state.last_reward_defender,
            "step_progress": progress - state.last_progress,
            "step_chain_time": chain_time - state.last_chain_time,
            "step_sim_time": state.time - state.last_sim_time,
            "episode_reward_attacker": reward_attacker,
            "episode_reward_defender": reward_defender,
            "episode_progress": progress,
            "episode_chain_time": chain_time,
            "episode_sim_time": state.time,
            "episode_n_steps": state.steps.astype(jnp.float32),
            "episode_n_activations": state.n_activations.astype(jnp.float32),
        }
        state = state.replace(
            last_reward_attacker=reward_attacker,
            last_reward_defender=reward_defender,
            last_progress=progress,
            last_chain_time=chain_time,
            last_sim_time=state.time,
        )
        return state, self.observe(state), reward, done, info

    # -- batched rollout helpers ------------------------------------------

    def _stream_init(self, key: jax.Array, params: EnvParams):
        """Episode-stream prologue shared by `rollout` and the chunked
        stats driver: split off the reset key and reset.  Both entry
        points must seed identically for the chunked-equals-unchunked
        contract to hold."""
        key, k0 = jax.random.split(key)
        return self.reset(k0, params)

    def _lane_step(self, state, action, params: EnvParams):
        """One auto-resetting transition of a single episode stream:
        step, then reset from the post-step PRNG key, then splice the
        fresh state in where the episode ended.

        This is the unit every driver in the repo advances streams by —
        `_autoreset_body` (hence `rollout` and both stats drivers) and
        the resident `step_lanes`/serve programs all call it, which is
        what makes a resident lane bit-identical to a solo rollout of
        the same key.

        Returns (state, obs_next, step_obs, reward, done, info) where
        `obs_next` is the continuation observation (post-reset at done)
        and `step_obs` is the raw post-step observation (terminal at
        done — the single-env gym surface returns this one)."""
        state, obs2, reward, done, info = self.step(state, action, params)
        # auto-reset, keeping the state PRNG stream
        rkey = state.key
        rstate, robs = self.reset(rkey, params)
        state = self.select_reset(done, rstate, state)
        obs_next = jnp.where(done, robs, obs2)
        return state, obs_next, obs2, reward, done, info

    def _autoreset_body(self, params: EnvParams, policy: Callable):
        """Scan body of an auto-resetting episode stream (shared by
        `rollout` and the chunked stats driver so both advance the
        stream identically).

        Deliberately metrics-free: device-metrics accumulation happens
        OUTSIDE the scan — folded from the stacked trajectory in
        `rollout(with_metrics=True)`, or derived from the per-lane
        episode aggregates in the stats drivers — because per-step
        carry updates cost ~7us per HLO per step on XLA:CPU, which
        measured as +72% on the 512-env nakamoto bench before the
        fold was hoisted."""
        takes_state = getattr(policy, "takes_state", False)

        def body(carry, _):
            state, obs = carry
            # policies normally see the observation (engine.ml:258-261);
            # policies with `takes_state = True` get the full env state
            # (used to execute MDP-solver policies that need e.g. the fork
            # relevance flag, which the observation does not expose)
            action = policy(state, obs) if takes_state else policy(obs)
            state, obs_next, _, reward, done, info = self._lane_step(
                state, action, params)
            return (state, obs_next), (obs, action, reward, done, info)

        return body

    # -- resident lane API (continuous batching) --------------------------
    #
    # The step-wise twin of `rollout`: a block of `n_lanes` independent
    # auto-resetting episode streams held resident on the device, with
    # lanes admitted (spliced from a fresh state) and retired (simply
    # stopped being stepped) on any tick.  cpr_tpu.serve multiplexes
    # concurrent client sessions onto these lanes; the gym adapters run
    # on the same programs with constant masks.  All three entry points
    # are jitted ON THE CLASS (static self), so every Core/BatchedCore/
    # serve instance over the same registry-memoized env shares one
    # compiled program instead of re-jitting per instance.

    @partial(jax.jit, static_argnums=0)
    def init_lanes(self, keys, params: EnvParams):
        """Fresh per-lane (state, obs) carry from per-lane keys, using
        the same stream prologue as `rollout` (split, then reset) — a
        lane admitted with key K therefore replays `rollout(K, ...)`
        bit-for-bit."""
        return jax.vmap(lambda k: self._stream_init(k, params))(keys)

    @partial(jax.jit, static_argnums=0)
    def reset_lanes(self, keys, params: EnvParams):
        """Fresh per-lane (state, obs) carry via a raw vmapped reset
        (no prologue split) — the gym adapters' historical seeding."""
        return jax.vmap(lambda k: self.reset(k, params))(keys)

    @partial(jax.jit, static_argnums=0, donate_argnums=1)
    def step_lanes(self, carry, actions, admit_mask, fresh_states,
                   step_mask, params: EnvParams):
        """Advance the resident lane block one tick.

        carry        -- (state, obs) with leading lane axis; DONATED —
                        callers must replace their handle with the
                        returned carry and must not pass buffers
                        aliasing it as `fresh_states`.
        actions      -- int32 (n_lanes,); only read where step_mask.
        admit_mask   -- bool (n_lanes,); lanes spliced from
                        `fresh_states` BEFORE stepping (admission).
        fresh_states -- (state, obs) like carry (e.g. from init_lanes /
                        reset_lanes); only read where admit_mask.
        step_mask    -- bool (n_lanes,); lanes that execute one
                        `_lane_step` this tick.  Held lanes (neither
                        admitted nor stepped) keep their state — PRNG
                        key included — bit-exactly.

        Returns (carry, (obs, reward, done, info)) where the output
        `obs` is the raw post-step observation for stepped lanes
        (terminal at done; the continuation obs lives in the carry) and
        the post-admission held observation for the rest — so a
        splice-only call (admit without step) reads the admitted lane's
        first observation straight from the outputs.  reward/done/info
        are zero/False/zero outside step_mask."""
        state, obs = carry
        fstate, fobs = fresh_states
        state = jax.tree.map(
            lambda a, b: _lane_where(admit_mask, a, b), fstate, state)
        obs = _lane_where(admit_mask, fobs, obs)
        new_state, obs_next, step_obs, reward, done, info = jax.vmap(
            lambda s, a: self._lane_step(s, a, params))(state, actions)
        live = step_mask
        state = jax.tree.map(
            lambda a, b: _lane_where(live, a, b), new_state, state)
        out_obs = _lane_where(live, step_obs, obs)
        obs = _lane_where(live, obs_next, obs)
        reward = jnp.where(live, reward, jnp.zeros_like(reward))
        done = done & live
        info = {k: jnp.where(live, v, jnp.zeros_like(v))
                for k, v in info.items()}
        return (state, obs), (out_obs, reward, done, info)

    @partial(jax.jit, static_argnums=(0, 3, 4, 5))
    def rollout(self, key: jax.Array, params: EnvParams, policy: Callable,
                n_steps: int, with_metrics: bool = False):
        """Run one auto-resetting episode stream for `n_steps` env steps.

        Returns per-step (obs, action, reward, done, info) stacked over time.
        vmap over `key` (and optionally `params`) for batching.

        `with_metrics=True` (static) additionally folds a
        device_metrics.rollout_spec() accumulator from the stacked
        trajectory (which this API materializes anyway) and returns
        (traj, acc) — acc stays on device; summarize it once per span
        with `device_metrics.rollout_spec().summarize`."""
        carry = self._stream_init(key, params)
        body = self._autoreset_body(params, policy)
        _, traj = jax.lax.scan(body, carry, None, length=n_steps)
        if not with_metrics:
            return traj
        from cpr_tpu import device_metrics
        spec = device_metrics.rollout_spec()
        obs, _, reward, done, info = traj
        acc = device_metrics.update_rollout(
            spec, spec.init(), reward=reward, done=done,
            ep_len=info["episode_n_steps"],
            nonfinite_obs=device_metrics.obs_nonfinite(obs))
        return traj, acc

    def episode_stats(self, key, params, policy, n_steps: int):
        """Final-info aggregation over completed episodes in a rollout."""
        obs, action, reward, done, info = self.rollout(key, params, policy, n_steps)
        n_done = jnp.maximum(done.sum(), 1)
        stats = {
            k: jnp.where(done, v, 0.0).sum() / n_done
            for k, v in info.items()
            if k.startswith("episode_")
        }
        stats["n_episodes"] = done.sum()
        return stats

    def make_episode_stats_fn(self, params: EnvParams, policy: Callable,
                              n_steps: int, chunk: int | None = None,
                              collect_metrics: bool = False,
                              mesh=None, mesh_axis: str = "d"):
        """Build `fn(keys) -> per-env stats dict` — the batched twin of
        `episode_stats`, optionally split into multiple device calls of
        `chunk` env steps each.

        `collect_metrics=True` accumulates a
        device_metrics.episode_stats_spec() accumulator alongside the
        stream: `fn` then returns (stats, acc) where acc is the
        env-axis-merged on-device accumulator (ONE readback via
        `fn.metrics_spec.summarize(acc)` after the caller's measure
        span — no host syncs are added inside the scan body or the
        chunk loop).  The spec rides on the returned fn as
        `fn.metrics_spec`.  Every cell derives from per-lane
        aggregates the driver already computes, so the scan-loop
        program is identical to the metrics-off build — that is what
        keeps the leave-it-on overhead <2% (see
        device_metrics.episode_stats_spec for the measured cost of
        the per-step alternative).

        Why chunking exists: the axon TPU worker crashes ("UNAVAILABLE:
        TPU worker process crashed or restarted") when a SINGLE device
        execution runs past ~60-75 s — measured with a pure-matmul probe
        (tools/tpu_limit_probe.py: a 33 s call and 5x25 s calls pass,
        one ~150 s call kills the worker), after rollout scans at large
        batch x DAG-capacity crossed the same ceiling in the round-3
        bench.  One episode scan per call is the right XLA shape only
        while it fits that budget; past it, the host loop carries the
        auto-reset stream between per-chunk calls and accumulates the
        done-masked partial sums — same math as `episode_stats` up to
        float summation order.

        The jitted pieces are built once here, so calling the returned
        fn repeatedly (bench reps) does not re-trace.

        `mesh` shards the episode batch over the given 1-D mesh axis
        (`mesh_axis`): keys are committed to
        `NamedSharding(mesh, P(mesh_axis))` at entry and GSPMD
        partitions the whole driver — the chunked host loop carries
        sharded buffers between per-chunk calls, so every shape
        (chunked, unchunked, metrics on/off) stays mesh-partitioned
        end to end.  The batch must divide the mesh axis
        (parallel.check_even_shards).  docs/SCALING.md covers the
        contract.
        """
        if chunk is not None and chunk <= 0:
            raise ValueError(f"chunk must be positive, got {chunk}")

        body = self._autoreset_body(params, policy)

        # derive the accumulator keys/dtypes from THIS env's info dict
        # (not the INFO_KEYS module constant) so envs with custom info
        # keep the chunked==unchunked contract
        def _probe(key):
            carry = self._stream_init(key, params)
            _, (_, _, _, _, info) = body(carry, None)
            return info
        info_spec = jax.eval_shape(_probe, jax.random.PRNGKey(0))

        spec, stat_keys = None, ()
        if collect_metrics:
            from cpr_tpu import device_metrics
            stat_keys = tuple(sorted(k for k in info_spec
                                     if k.startswith("episode_")))
            spec = device_metrics.episode_stats_spec(stat_keys)

        if chunk is None or chunk >= n_steps:
            if spec is None:
                return _mesh_wrap(jax.jit(jax.vmap(
                    lambda k: self.episode_stats(k, params, policy,
                                                 n_steps))),
                    mesh, mesh_axis)

            def one(k):
                (_, obs_last), traj = jax.lax.scan(
                    body, self._stream_init(k, params), None,
                    length=n_steps)
                _, _, _, done, info = traj
                n_done = jnp.maximum(done.sum(), 1)
                stats = {k2: jnp.where(done, v, 0.0).sum() / n_done
                         for k2, v in info.items()
                         if k2.startswith("episode_")}
                stats["n_episodes"] = done.sum()
                # every cell derives from the per-lane aggregates just
                # computed plus the scan's final carry — no new
                # consumer of per-step data, so the loop program stays
                # the exact metrics-off build
                acc = spec.init()
                acc = spec.count(acc, "env_steps", jnp.int32(n_steps))
                acc = spec.count(
                    acc, "nonfinite_obs_boundary",
                    device_metrics.obs_nonfinite(obs_last))
                acc = device_metrics.fold_episode_stats(
                    spec, acc, stats=stats,
                    n_episodes=stats["n_episodes"],
                    stat_keys=stat_keys)
                return stats, acc

            @jax.jit
            def run(keys):
                stats, acc = jax.vmap(one)(keys)
                # env-axis reduction stays in the same device program
                return stats, spec.merge_axis(acc, 0)

            def fn(keys):
                return run(keys)

            fn.metrics_spec = spec
            return _mesh_wrap(fn, mesh, mesh_axis)

        n_full, rem = divmod(n_steps, chunk)
        lengths = (chunk,) * n_full + ((rem,) if rem else ())
        acc_spec = {k: v.dtype for k, v in info_spec.items()
                    if k.startswith("episode_")}

        if spec is not None:
            return _mesh_wrap(self._make_chunked_metrics_fn(
                params, policy, lengths, spec, acc_spec, stat_keys),
                mesh, mesh_axis)

        @jax.jit
        def init(keys):
            return jax.vmap(lambda k: self._stream_init(k, params))(keys)

        # donate the carry: the host loop never reuses the previous
        # chunk's carry, and the env state dominates memory at large
        # batch x capacity (the 65536-env ethereum OOM class) — aliasing
        # input and output state halves that footprint
        @partial(jax.jit, static_argnums=1, donate_argnums=0)
        def run_chunk(carry, length):
            # accumulate the done-masked sums INSIDE the scan carry
            # instead of stacking per-step info and reducing after:
            # stacking costs O(n_envs * chunk * |info|) HBM and is what
            # pushed the 65536-env ethereum config out of memory
            def one(c):
                def step(acc_carry, _):
                    c, acc, nd = acc_carry
                    c2, (_, _, _, done, info) = body(c, None)
                    acc = {k: acc[k] + jnp.where(
                               done, info[k], jnp.zeros_like(info[k]))
                           for k in acc}
                    return (c2, acc, nd + done.astype(jnp.int32)), None

                acc0 = {k: jnp.zeros((), dt) for k, dt in acc_spec.items()}
                (c2, acc, nd), _ = jax.lax.scan(
                    step, (c, acc0, jnp.int32(0)), None, length=length)
                return c2, acc, nd
            return jax.vmap(one)(carry)

        def fn(keys):
            carry = init(keys)
            totals, n_done = None, None
            for length in lengths:
                carry, sums, d = run_chunk(carry, length)
                totals = sums if totals is None else {
                    k: totals[k] + sums[k] for k in totals}
                n_done = d if n_done is None else n_done + d
            nd = jnp.maximum(n_done, 1)
            stats = {k: v / nd for k, v in totals.items()}
            stats["n_episodes"] = n_done
            return stats

        return _mesh_wrap(fn, mesh, mesh_axis)

    def _make_chunked_metrics_fn(self, params, policy, lengths, spec,
                                 acc_spec, stat_keys):
        """The metrics twin of the chunked stats driver: the per-env
        device-metrics accumulator rides in the donated chunk carry
        next to the env state, the env-axis merge happens inside the
        final jitted call, and the host loop performs NO reads — one
        readback per whole stats call, same as the unchunked path.

        The scan body is the EXACT metrics-off program: counters bump
        once per chunk from values the chunk already produces (its
        static length, the live obs in the final carry), and the
        stats cells fold once per call in `finish` from the
        accumulated episode aggregates.  Folding per-step cells
        inside (or even after) the body instead measured +22..72% on
        the 512-env nakamoto CPU bench — XLA:CPU re-fuses every
        consumer of per-step data into the sequential loop at ~7us
        per HLO per step."""
        from cpr_tpu import device_metrics

        body = self._autoreset_body(params, policy)

        @jax.jit
        def init(keys):
            carry = jax.vmap(lambda k: self._stream_init(k, params))(keys)
            # vmap broadcasts the constant zero-accumulator per lane
            macc = jax.vmap(lambda _: spec.init())(
                jnp.zeros(keys.shape[0]))
            return carry, macc

        @partial(jax.jit, static_argnums=1, donate_argnums=0)
        def run_chunk(cm, length):
            def one(c, ma):
                def step(acc_carry, _):
                    inner, acc, nd = acc_carry
                    inner, (_, _, _, done, info) = body(inner, None)
                    acc = {k: acc[k] + jnp.where(
                               done, info[k], jnp.zeros_like(info[k]))
                           for k in acc}
                    return (inner, acc,
                            nd + done.astype(jnp.int32)), None

                acc0 = {k: jnp.zeros((), dt)
                        for k, dt in acc_spec.items()}
                (c2, acc, nd), _ = jax.lax.scan(
                    step, (c, acc0, jnp.int32(0)), None, length=length)
                # per-chunk, not per-step: the live obs is already in
                # the carry and `length` is a compile-time constant
                _, obs_b = c2
                ma = spec.count(ma, "env_steps", jnp.int32(length))
                ma = spec.count(
                    ma, "nonfinite_obs_boundary",
                    device_metrics.obs_nonfinite(obs_b))
                return c2, ma, acc, nd

            return jax.vmap(one)(*cm)

        # finalization is jitted (constants compile in) so the whole
        # call — not just the scan bodies — runs without a single
        # host<->device transfer under jax.transfer_guard("disallow")
        @jax.jit
        def finish(totals, n_done, macc):
            nd = jnp.maximum(n_done, 1)
            stats = {k: v / nd for k, v in totals.items()}

            def fold(ma, st, n):
                return device_metrics.fold_episode_stats(
                    spec, ma, stats=st, n_episodes=n,
                    stat_keys=stat_keys)

            macc = jax.vmap(fold)(
                macc, {k: stats[k] for k in stat_keys}, n_done)
            stats["n_episodes"] = n_done
            return stats, spec.merge_axis(macc, 0)

        def fn(keys):
            carry, macc = init(keys)
            totals, n_done = None, None
            for length in lengths:
                carry, macc, sums, d = run_chunk((carry, macc), length)
                totals = sums if totals is None else {
                    k: totals[k] + sums[k] for k in totals}
                n_done = d if n_done is None else n_done + d
            return finish(totals, n_done, macc)

        fn.metrics_spec = spec
        return fn


def relative_reward(info: dict[str, Any]) -> jax.Array:
    """attacker / (attacker + defender) at episode end
    (reference: gym/ocaml/cpr_gym/wrappers.py:8-26)."""
    a = info["episode_reward_attacker"]
    d = info["episode_reward_defender"]
    s = a + d
    return jnp.where(s != 0, a / jnp.where(s != 0, s, 1.0), 0.0)


def reward_per_progress(info: dict[str, Any]) -> jax.Array:
    """attacker / progress at episode end
    (reference: gym/ocaml/cpr_gym/wrappers.py:29-51)."""
    a = info["episode_reward_attacker"]
    p = info["episode_progress"]
    return jnp.where(p != 0, a / jnp.where(p != 0, p, 1.0), 0.0)
