"""Tailstorm under the SSZ-like withholding attack space, on the DAG
tensor substrate.

Reference counterparts:
- protocol: simulator/protocols/tailstorm.ml — summaries (no PoW) + depth-
  labelled vote trees (tailstorm.ml:54-72), validity (tailstorm.ml:156-180),
  summary preference by (height, confirming votes) (tailstorm.ml:183-194),
  reward schemes constant/discount/punish/hybrid (tailstorm.ml:204-227),
  sub-block selection altruistic_quorum (tailstorm.ml:271-313),
  heuristic_quorum (tailstorm.ml:329-380), optimal_quorum with 100-option
  cap + heuristic fallback (tailstorm.ml:418-506), honest handler
  (tailstorm.ml:565-608),
- attack space: simulator/protocols/tailstorm_ssz.ml — 10-field observation
  (tailstorm_ssz.ml:22-38), Action8 (ssz_tools.ml:230-263), agent with
  deferred private->public delivery (tailstorm_ssz.ml:210-219), release =
  smallest descendant prefix that flips (Override) or ties (Match) the
  defender's head (tailstorm_ssz.ml:292-314), summary (re-)appending with
  inclusive/exclusive vote filters (tailstorm_ssz.ml:322-346), policies
  honest/get-ahead/minor-delay/avoid-loss{,-a,-b}/long-delay
  (tailstorm_ssz.ml:365-472),
- engine semantics: simulator/gym/engine.ml:97-273 (one env step per
  attacker interaction, defender cloud, gamma via message ordering).

TPU re-design: blocks live in the fixed-capacity DAG; a vote's single
parent sits in slot 0; a summary's parents are its quorum leaves sorted by
(depth desc, hash asc), the deepest leaf in slot 0 (the precursor —
tailstorm.ml:196). Votes record their summary in the `signer` column, so
`confirming_votes` (tailstorm.ml:151-154) is one masked compare instead of
a DAG traversal; vote trees are forests of parent-pointer paths, so branch
closures are bounded pointer walks (depth <= D_MAX). Quorum selection is a
<= k-round greedy loop whose per-round scores are vectorized closure
counts. One env step processes exactly one attacker event: a pending
self-append, a defender summary, or one mining draw.

Documented deviations from the reference event-queue simulation:
- `optimal` sub-block selection enumerates a static n-choose-k table
  (cpr_tpu.envs.quorum.quorum_optimal) and falls back to `heuristic`
  at or before the reference's 100-option cap (tailstorm.ml:426-428):
  the positional window can trigger the fallback slightly earlier when
  escape-invalidation leaves holes in the candidate frame. Reward ties
  between quorum choices resolve in table order rather than the
  reference's list order.
- The defender cloud attempts one summary append per delivery batch
  (quorum over its visible votes) instead of one per delivered vertex;
  same-height summary *replacement* by the defender
  (tailstorm.ml:557-563) is not emulated. The attacker side re-appends
  replacements exactly as the reference agent does
  (tailstorm_ssz.ml:335-342).
- gamma races follow the Nakamoto env's rule: a Match ties the defender's
  head, and the next defender activation mines on the attacker's released
  summary with probability gamma (network.ml:61-105 collapsed to one
  Bernoulli draw).
- Vote-tree depth walks are capped at D_MAX = 3k+8; deeper withheld
  branches (unreachable under the reference's own policies, which cut
  forks at 10 blocks) would truncate closure counts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from cpr_tpu import obs as obslib
from cpr_tpu.core import dag as D
from cpr_tpu.envs import quorum as Q
from cpr_tpu.envs.base import JaxEnv
from cpr_tpu.params import EnvParams

# kinds
SUMMARY, VOTE = 0, 1

# events: Discrete [`Append; `ProofOfWork; `Network] (tailstorm_ssz.ml:54)
EV_APPEND, EV_POW, EV_NETWORK = 0, 1, 2

# Action8 ranks (ssz_tools.ml:230-263)
(ADOPT_PROLONG, OVERRIDE_PROLONG, MATCH_PROLONG, WAIT_PROLONG,
 ADOPT_PROCEED, OVERRIDE_PROCEED, MATCH_PROCEED, WAIT_PROCEED) = range(8)

INCENTIVE_SCHEMES = ("constant", "discount", "punish", "hybrid")
SUBBLOCK_SELECTIONS = ("altruistic", "heuristic", "optimal")


def obs_fields(k: int):
    """tailstorm_ssz.ml:41-55."""
    return (
        obslib.Field("public_blocks", obslib.UINT, scale=1),
        obslib.Field("private_blocks", obslib.UINT, scale=1),
        obslib.Field("diff_blocks", obslib.INT, scale=1),
        obslib.Field("public_votes", obslib.UINT, scale=k),
        obslib.Field("private_votes_inclusive", obslib.UINT, scale=k),
        obslib.Field("private_votes_exclusive", obslib.UINT, scale=k),
        obslib.Field("public_depth", obslib.UINT, scale=k),
        obslib.Field("private_depth_inclusive", obslib.UINT, scale=k),
        obslib.Field("private_depth_exclusive", obslib.UINT, scale=k),
        obslib.Field("event", obslib.DISCRETE, n=3),
    )


@struct.dataclass
class State:
    dag: D.Dag
    public: jnp.ndarray  # defender-preferred summary (simulated)
    private: jnp.ndarray  # attacker-preferred summary
    event: jnp.ndarray  # EV_*
    pending_append: jnp.ndarray  # attacker summary awaiting Append (-1)
    match_tgt: jnp.ndarray  # live match race target summary (-1: none)
    def_dirty: jnp.ndarray  # bool: defender gained votes since last attempt
    stale: jnp.ndarray  # (B,) bool: withheld blocks abandoned at an Adopt
    # episode bookkeeping (engine.ml:69-79)
    time: jnp.ndarray
    steps: jnp.ndarray
    n_activations: jnp.ndarray
    last_reward_attacker: jnp.ndarray
    last_reward_defender: jnp.ndarray
    last_progress: jnp.ndarray
    last_chain_time: jnp.ndarray
    last_sim_time: jnp.ndarray
    key: jax.Array


class TailstormSSZ(JaxEnv):
    n_actions = 8
    # fresh reset = genesis + one _advance append (a vote: def_dirty
    # starts False); the logical reset avoids full-tree selects of the
    # (B, B) ancestry planes per auto-reset step (JaxEnv.reset_dag_rows)
    reset_dag_rows = 2

    def __init__(self, k: int = 8, incentive_scheme: str = "discount",
                 subblock_selection: str = "heuristic",
                 unit_observation: bool = True, max_steps_hint: int = 256,
                 release_scan: int = 128, window: int | None = None,
                 anc_masks: bool | None = None):
        assert incentive_scheme in INCENTIVE_SCHEMES
        assert subblock_selection in SUBBLOCK_SELECTIONS
        self.k = k
        self.incentive_scheme = incentive_scheme
        self.subblock_selection = subblock_selection
        if subblock_selection == "optimal":
            # static n-choose-k tables; beyond the window the selection
            # falls back to heuristic, at or before the reference's
            # 100-option cap (tailstorm.ml:419-431, module docstring)
            self.opt_window = Q.optimal_window(k, 4 * k + 16)
            self.opt_combos = Q.optimal_combos(k, self.opt_window)
        self.unit_observation = unit_observation
        self.max_parents = k
        self.D_MAX = 3 * k + 8  # vote-path walk bound
        self.C_MAX = 4 * k + 16  # quorum candidate window (compacted)
        # <= 2 appends per step (attacker summary + defender summary/vote);
        # floored at the candidate window so small hints with large k
        # still hold a full quorum frame (top_k needs k <= capacity)
        self.capacity = max(2 * max_steps_hint + 8, self.C_MAX)
        # O(active-set) ring: the window replaces episode-length-
        # proportional capacity; it must cover the live fork (summaries
        # + their vote trees, ~(k+1) slots per withheld summary).  A
        # deeper fork overflows and ends the episode, like capacity
        # exhaustion in full mode.
        if window is not None:
            self.capacity = max(window, self.C_MAX)
        self.ring = window is not None
        # ancestry planes are quadratic in capacity, so they default ON
        # only in ring mode (where capacity is the small active-set
        # window and the retire logic needs the masked queries); full
        # mode falls back to walk-based LCA / stale descent, keeping
        # state O(capacity)
        self.anc_masks = self.ring if anc_masks is None else anc_masks
        assert self.anc_masks or not self.ring, \
            "ring windows require anc_masks (walks could cross reclaimed slots)"
        self.STALE_WALK = 4  # summary-chain descent check depth at Adopt
        assert self.C_MAX < (1 << 8), "composite sort keys use 8 bits"
        self.release_scan = min(release_scan, self.capacity)
        self.fields = obs_fields(k)
        self.observation_length = len(self.fields)
        self.low, self.high = obslib.low_high(self.fields, unit_observation)
        self.policies = self._make_policies()

    # -- protocol primitives (tailstorm.ml) --------------------------------

    def confirming(self, dag, s, extra_mask=None):
        """Votes confirming summary s (tailstorm.ml:151-154): votes store
        their summary in the `signer` column at append time.  The
        newer_than guard keeps a reclaimed slot's new occupant from
        inheriting a retired summary's still-resident votes (ring
        mode; all-true otherwise)."""
        m = (dag.exists() & (dag.kind == VOTE) & (dag.signer == s)
             & D.newer_than(dag, s))
        if extra_mask is not None:
            m = m & extra_mask
        return m

    def last_summary(self, dag, x):
        """tailstorm.ml:113-121."""
        return jnp.where(dag.kind[x] == SUMMARY, x, dag.signer[x])

    def last_summary_all(self, dag):
        """(B,) last_summary of every slot (Q.last_of_kind_all)."""
        return Q.last_of_kind_all(dag, SUMMARY)

    def prev_summary(self, dag, s):
        """Summary preceding s on the chain (tailstorm.ml:196 precursor,
        followed to the next summary). -1 for genesis.  Cached in
        Dag.aux2 at append time: the walked form (parent0 -> kind ->
        signer) cost three chained gathers per chain level."""
        return dag.aux2[s]

    def summary_lca(self, dag, a, b):
        """Common ancestor of two summaries along the summary chain
        (dagtools.ml:102-121): with ancestry planes, the chain plane
        follows the prev-summary pointer (append_summary passes
        chain_parent), so the LCA is one row intersection + height
        argmax instead of a height-synchronized while loop (~3 ms/step
        at 4096 envs, round-5 device profile). Without planes (full
        mode), walk the cached prev-summary pointers — heights drop by
        1 per step, so the loop is the standard synchronized descent."""
        if dag.has_masks:
            return jnp.maximum(D.common_ancestor_masked(dag, a, b), 0)

        def cond(st):
            x, y = st
            return (x != y) & (x >= 0) & (y >= 0)

        def body(st):
            x, y = st
            hx, hy = dag.height[x], dag.height[y]
            return (jnp.where(hx >= hy, self.prev_summary(dag, x), x),
                    jnp.where(hy >= hx, self.prev_summary(dag, y), y))

        x, _ = jax.lax.while_loop(cond, body, (a, b))
        return jnp.maximum(x, 0)

    def vote_ancestors(self, dag, starts):
        """(C, D_MAX) vote-path matrix: row i lists starts[i] and its vote
        ancestors (up to, excluding, the summary), -1 padded — the
        vectorized `acc_votes parents [x]` (tailstorm.ml:134-149). Votes
        have a single parent, so the closure of a vote is a path. Invalid
        starts (-1) produce all -1 rows."""
        is_vote = dag.kind == VOTE
        cur = jnp.where(
            (starts >= 0) & is_vote[jnp.maximum(starts, 0)], starts, -1)
        cols = []
        for _ in range(self.D_MAX):
            cols.append(cur)
            c = jnp.maximum(cur, 0)
            nxt = dag.parent0[c]
            ok = (cur >= 0) & (nxt >= 0) & is_vote[jnp.maximum(nxt, 0)]
            cur = jnp.where(ok, nxt, -1)
        return jnp.stack(cols, axis=1)

    def closure_counts(self, anc, masks):
        """(C, M) counts of masked vertices along each candidate's vote
        path. masks is (B, M) bool; anc (C, D_MAX) from
        `vote_ancestors`."""
        B = masks.shape[0]
        pad = jnp.concatenate(
            [masks, jnp.zeros((1, masks.shape[1]), masks.dtype)], axis=0)
        idx = jnp.where(anc >= 0, anc, B)
        return pad[idx].sum(axis=1).astype(jnp.int32)

    def mark_closure(self, anc_row, mask, on=True):
        """mask |= the vote path listed in anc_row (D_MAX,)."""
        valid = (anc_row >= 0) & jnp.asarray(on)
        return mask.at[jnp.maximum(anc_row, 0)].max(valid)

    def own_reward(self, dag, s, my):
        """The summary's own coinbase share for party `my` — used as the
        update_head tiebreak (tailstorm.ml:539-549).  Cached per slot in
        Dag.auxf (attacker) / Dag.auxg (defender) at append time — the
        cumulative-column delta needed a prev_summary walk per read."""
        return jnp.where(my == D.ATTACKER, dag.auxf[s], dag.auxg[s])

    def cmp_summaries(self, dag, x, y, vote_filter_mask, my):
        """compare_blocks (tailstorm.ml:539-549): height, then filtered
        confirming votes, then own reward. >0 iff x strictly preferred."""
        nx = self.confirming(dag, x, vote_filter_mask).sum()
        ny = self.confirming(dag, y, vote_filter_mask).sum()
        rx = self.own_reward(dag, x, my)
        ry = self.own_reward(dag, y, my)
        key_x = (dag.height[x], nx, rx)
        key_y = (dag.height[y], ny, ry)
        gt = jnp.bool_(False)
        eq = jnp.bool_(True)
        for a, b in zip(key_x, key_y):
            gt = gt | (eq & (a > b))
            eq = eq & (a == b)
        return jnp.where(x == y, False, gt)

    def update_head(self, dag, old, candidate, vote_filter_mask, my):
        """tailstorm.ml:552-555: switch only on strict improvement."""
        better = self.cmp_summaries(dag, candidate, old, vote_filter_mask, my)
        return jnp.where(better, candidate, old)

    # -- quorum selection ---------------------------------------------------

    def quorum(self, dag, b, voter, vote_filter_mask, view_mask):
        """Select k sub-blocks confirming b; returns (found, parents_row)
        with leaves sorted by (depth desc, hash asc)
        (compare_votes_in_block, tailstorm.ml:124-130). Selection runs on
        the compacted candidate frame (cpr_tpu.envs.quorum); overflow
        beyond C_MAX drops the newest candidates."""
        cand = self.confirming(dag, b) & vote_filter_mask & view_mask
        own = dag.miner == voter
        cidx, cvalid, abits, oh = Q.candidate_frame(dag, cand, self.C_MAX, VOTE)
        if self.subblock_selection == "altruistic":
            seen = jnp.where(voter == D.ATTACKER, dag.born_at,
                             dag.vis_d_since)
            n, _, leaves_c, n_cand = Q.quorum_altruistic(
                dag, cidx, cvalid, abits, oh, own, seen, dag.aux, self.k)
            found = (n == self.k) & (n_cand >= self.k)
        elif self.subblock_selection == "optimal":
            # tailstorm pays discount r = depth/k and pays votes only
            # (no summary-miner share, tailstorm.ml:204-218)
            found, leaves_c = Q.quorum_optimal_or_heuristic(
                dag, cidx, cvalid, abits, oh, own, dag.aux, self.k,
                self.opt_window, self.opt_combos, k=self.k,
                discount=self.incentive_scheme in ("discount", "hybrid"),
                punish=self.incentive_scheme in ("punish", "hybrid"),
                depth_plus=0,
                leaf_score=(dag.aux.astype(jnp.float32) - dag.pow_hash),
                miner_share=0)
        else:
            found, leaves_c = Q.quorum_heuristic(
                dag, cidx, cvalid, abits, oh, own, self.k)
        score = dag.aux.astype(jnp.float32) - dag.pow_hash  # depth - hash
        row = Q.leaves_to_row(dag, cidx, leaves_c, cvalid, self.k, score)
        return found, row, (cidx, cvalid, abits, oh, leaves_c)

    def summary_reward(self, dag, row, frame):
        """Coinbase of a summary draft (tailstorm.ml:204-227), computed
        on the candidate frame: the quorum's closure requirement means
        every selected vote's ancestors sit inside the frame, so the
        closure is a union of abits rows and the miner counts are frame-
        local matmul gathers — the old per-leaf vote_ancestors walk was
        D_MAX batched gathers per call."""
        discount = self.incentive_scheme in ("discount", "hybrid")
        punish = self.incentive_scheme in ("punish", "hybrid")
        cidx, cvalid, abits, oh, leaves_c = frame
        if punish:
            # only the best-score leaf's branch is paid; row[0] is that
            # leaf (leaves_to_row sorts by the same score)
            score_c = jnp.where(
                cvalid, Q.oh_gather(
                    oh, dag.aux.astype(jnp.float32) - dag.pow_hash),
                -jnp.inf)
            j = jnp.argmax(jnp.where(leaves_c, score_c, -jnp.inf))
            sel = abits[j] & leaves_c.any()
        else:
            sel = (leaves_c[:, None] & abits).any(axis=0)
        own_att = Q.oh_gather(oh, dag.miner == D.ATTACKER) > 0.5
        own_def = Q.oh_gather(oh, dag.miner == D.DEFENDER) > 0.5
        depth0 = dag.aux[jnp.maximum(row[0], 0)]
        r = jnp.where(discount, depth0.astype(jnp.float32) / self.k, 1.0)
        atk = r * (sel & own_att).sum()
        dfn = r * (sel & own_def).sum()
        return atk, dfn

    def append_summary(self, dag, b, voter, vote_filter_mask, view_mask,
                       time):
        """Append the next summary on b if a quorum exists; returns
        (dag, idx_or_-1, fresh) (tailstorm.ml:530-537).

        Summaries carry no PoW, so appends are deterministic and must be
        deduplicated against existing summaries with identical parent rows
        (simulator.ml:138-158 — redundant appends return the existing
        vertex and trigger no events). Rows are canonical (sorted by
        depth desc, hash asc), so row equality == quorum equality."""
        found, row, frame = self.quorum(dag, b, voter, vote_filter_mask,
                                        view_mask)
        atk, dfn = self.summary_reward(dag, row, frame)
        height = dag.height[b] + 1
        row_eq = dag.parents[0] == row[0]
        for p in range(1, len(dag.parents)):
            row_eq = row_eq & (dag.parents[p] == row[p])
        # a duplicate summary extends b, so it is younger than b — the
        # guard rejects stale rows whose slot pointers alias reclaimed
        # slots (ring wrap)
        dup_mask = (dag.exists() & (dag.kind == SUMMARY)
                    & (dag.height == height) & row_eq
                    & D.newer_than(dag, b))
        dup = jnp.where(dup_mask.any(),
                        jnp.argmax(dup_mask), D.NONE).astype(jnp.int32)
        fresh = found & (dup < 0)
        dag, idx = D.append_if(
            dag, fresh, row, kind=SUMMARY, height=height, aux=0,
            signer=D.NONE, miner=voter,
            vis_a=True, vis_d=(voter == D.DEFENDER),
            time=time, reward_atk=atk, reward_def=dfn,
            progress=(height * self.k).astype(jnp.float32),
            auxf=atk, auxg=dfn, aux2=b,
            # the linear history the chain plane follows is the summary
            # chain (tailstorm.ml:196), not parent slot 0 (a vote leaf)
            chain_parent=b,
        )
        out = jnp.where(fresh, idx, jnp.where(found, dup, D.NONE))
        return dag, out, fresh

    def mine_vote(self, dag, pref, voter, view_mask, time, pow_hash):
        """puzzle_payload (tailstorm.ml:509-528): vote on the deepest
        visible branch confirming the preferred summary."""
        cand = self.confirming(dag, pref, view_mask)
        score = dag.aux.astype(jnp.float32) - dag.pow_hash
        parent = jnp.where(cand.any(),
                           jnp.argmax(jnp.where(cand, score, -jnp.inf)),
                           pref).astype(jnp.int32)
        depth = jnp.where(cand.any(), dag.aux[parent] + 1, 1)
        height = dag.height[pref]
        row = jnp.full((self.max_parents,), D.NONE, jnp.int32).at[0].set(parent)
        dag, idx = D.append(
            dag, row, kind=VOTE, height=height, aux=depth,
            pow_hash=pow_hash, signer=pref, miner=voter,
            vis_a=True, vis_d=(voter == D.DEFENDER), time=time,
            progress=(height * self.k + depth).astype(jnp.float32),
        )
        return dag, idx

    # -- env API ------------------------------------------------------------

    def reset(self, key: jax.Array, params: EnvParams):
        # with anc_masks, summary-chain LCA, stale descent, and the
        # quorum frame's ancestor matrix all read the incremental
        # ancestry planes instead of walking
        dag = D.empty(self.capacity, self.max_parents,
                      ring=self.ring, anc_masks=self.anc_masks)
        # genesis summary, height 0 (tailstorm.ml:84)
        dag, root = D.append(
            dag, jnp.full((self.max_parents,), D.NONE, jnp.int32),
            kind=SUMMARY, height=0, miner=D.NONE, vis_a=True, vis_d=True,
            time=0.0, progress=0.0)
        z = jnp.int32(0)
        f = jnp.float32(0.0)
        state = State(
            dag=dag, public=root, private=root,
            event=jnp.int32(EV_POW), pending_append=D.NONE,
            match_tgt=D.NONE, def_dirty=jnp.bool_(False),
            stale=jnp.zeros((self.capacity,), jnp.bool_),
            time=f, steps=z, n_activations=z,
            last_reward_attacker=f, last_reward_defender=f,
            last_progress=f, last_chain_time=f, last_sim_time=f,
            key=key,
        )
        state = self._advance(state, params)
        return state, self.observe(state)

    def _advance(self, state: State, params: EnvParams) -> State:
        """Next attacker interaction: pending self-append, defender
        summary, or one mining draw (engine.ml:108-121 collapsed)."""

        def with_pending(state):
            # Append event: attacker learns its own summary
            # (tailstorm_ssz.ml:228-235)
            dag = state.dag
            private = self.update_head(
                dag, state.private, state.pending_append, dag.vis_a,
                jnp.int32(D.ATTACKER))
            return state.replace(
                private=private, event=jnp.int32(EV_APPEND),
                pending_append=D.NONE)

        def without_pending(state):
            def try_def_append(state):
                dag, s, fresh = self.append_summary(
                    state.dag, state.public, jnp.int32(D.DEFENDER),
                    state.dag.vis_d, state.dag.vis_d, state.time)

                def announced(state):
                    public = self.update_head(
                        dag, state.public, s, dag.vis_d, jnp.int32(D.DEFENDER))
                    # a freshly claimed slot must not inherit the old
                    # occupant's stale bit (ring reuse; no-op otherwise)
                    return state.replace(
                        dag=dag, public=public, event=jnp.int32(EV_NETWORK),
                        def_dirty=jnp.bool_(False),
                        stale=state.stale.at[jnp.maximum(s, 0)].set(False))

                def silent_or_mine(state):
                    # redundant append: the identical summary already
                    # exists (possibly appended withheld by the attacker);
                    # the defender adopts it without a new attacker
                    # interaction (simulator.ml:138-158 + engine
                    # skip_to_interaction)
                    def adopt_dup(state):
                        dag2 = dag.replace(
                            vis_d=dag.vis_d.at[jnp.maximum(s, 0)].set(True))
                        public = self.update_head(
                            dag2, state.public, s, dag2.vis_d,
                            jnp.int32(D.DEFENDER))
                        return state.replace(dag=dag2, public=public)

                    state = jax.lax.cond(
                        s >= 0, adopt_dup, lambda st: st, state)
                    return mine(state.replace(def_dirty=jnp.bool_(False)))

                return jax.lax.cond(fresh, announced, silent_or_mine, state)

            def mine(state):
                dag = state.dag
                key, k_dt, k_mine, k_hash, k_gamma = jax.random.split(
                    state.key, 5)
                dt = jax.random.exponential(k_dt) * params.activation_delay
                time = state.time + dt
                attacker = jax.random.uniform(k_mine) < params.alpha
                powh = jax.random.uniform(k_hash)

                # gamma race: defender mines on the matched release
                # (network.ml:61-105 collapsed); dead once either side is
                # strictly preferred (defenders only split between
                # equal-preference tips)
                tgt = jnp.maximum(state.match_tgt, 0)
                still_tie = (
                    ~self.cmp_summaries(dag, state.public, tgt, dag.vis_d,
                                        jnp.int32(D.DEFENDER))
                    & ~self.cmp_summaries(dag, tgt, state.public, dag.vis_d,
                                          jnp.int32(D.DEFENDER)))
                gamma_hit = (~attacker & (state.match_tgt >= 0) & still_tie
                             & (jax.random.uniform(k_gamma) < params.gamma))
                public = jnp.where(gamma_hit, jnp.maximum(state.match_tgt, 0),
                                   state.public)
                match_tgt = jnp.where(attacker, state.match_tgt, D.NONE)

                voter = jnp.where(attacker, D.ATTACKER, D.DEFENDER)
                pref = jnp.where(attacker, state.private, public)
                view = jnp.where(attacker, dag.vis_a, dag.vis_d)
                dag, vidx = self.mine_vote(dag, pref, voter, view, time, powh)
                return state.replace(
                    dag=dag, stale=state.stale.at[vidx].set(False),
                    public=public, match_tgt=match_tgt,
                    event=jnp.where(attacker, EV_POW, EV_NETWORK
                                    ).astype(jnp.int32),
                    def_dirty=state.def_dirty | ~attacker,
                    time=time, n_activations=state.n_activations + 1,
                    key=key,
                )

            return jax.lax.cond(state.def_dirty, try_def_append, mine, state)

        return jax.lax.cond(
            state.pending_append >= 0, with_pending, without_pending, state)

    def observe(self, state: State):
        """tailstorm_ssz.ml:262-290."""
        dag = state.dag
        ca = self.summary_lca(dag, state.public, state.private)

        def depth_count(mask):
            return (jnp.where(mask, dag.aux, 0).max(), mask.sum())

        pub_d, pub_v = depth_count(self.confirming(dag, state.public,
                                                   dag.vis_d))
        inc_d, inc_v = depth_count(self.confirming(dag, state.private))
        exc_d, exc_v = depth_count(self.confirming(
            dag, state.private, dag.miner == D.ATTACKER))
        return obslib.encode(
            self.fields,
            (
                dag.height[state.public] - dag.height[ca],
                dag.height[state.private] - dag.height[ca],
                dag.height[state.private] - dag.height[state.public],
                pub_v, inc_v, exc_v,
                pub_d, inc_d, exc_d,
                state.event,
            ),
            self.unit_observation,
        )

    def _release_sets(self, state: State):
        """tailstorm_ssz.ml:292-314 via the shared dense prefix scan
        (cpr_tpu.envs.quorum.prefix_release_sets); the flip tiebreak is
        the defender's own summary reward (tailstorm.ml:539-549)."""
        dag = state.dag

        def cmp(dag_, x, y, mask):
            return self.cmp_summaries(dag_, x, y, mask,
                                      jnp.int32(D.DEFENDER))

        cands = dag.exists() & ~dag.vis_d & ~state.stale
        last_all = self.last_summary_all(dag)
        return Q.prefix_release_sets(
            dag, state.public, state.private, cands, self.release_scan,
            last_all, cmp, extra_all=dag.auxg)

    def _apply(self, state: State, action) -> State:
        """tailstorm_ssz.ml:292-350."""
        dag = state.dag
        is_adopt = (action == ADOPT_PROLONG) | (action == ADOPT_PROCEED)
        is_override = (action == OVERRIDE_PROLONG) | (action == OVERRIDE_PROCEED)
        is_match = (action == MATCH_PROLONG) | (action == MATCH_PROCEED)
        is_release = is_override | is_match
        proceed = action >= 4  # Proceed: inclusive vote filter

        override_set, match_set, found, new_head = self._release_sets(state)
        mask = jnp.where(is_override, override_set,
                         jnp.where(is_match, match_set, jnp.zeros_like(match_set)))
        released = D.release(dag, mask, state.time)
        dag = D.select_vis(is_release, released, dag)

        # deliver to the simulated defender
        public = jnp.where(is_override & found, new_head, state.public)
        private = jnp.where(is_adopt, public, state.private)
        def_dirty = state.def_dirty | (is_release & mask.any())
        stale = Q.stale_after_adopt(
            dag, public, state.stale, is_adopt, self.release_scan,
            self.STALE_WALK, self.last_summary_all(dag),
            lambda d, i: self.prev_summary(d, i))

        # match race target: deepest released summary's chain tip; armed
        # only when a flipping prefix exists (found), i.e. the released
        # set ties the defender's head — a blind release-all is no race
        rel_tip = D.last_by_age(dag, match_set)
        match_tgt = jnp.where(
            is_match & found & (rel_tip >= 0),
            self.last_summary(dag, jnp.maximum(rel_tip, 0)),
            jnp.where(is_adopt | is_override, D.NONE, state.match_tgt))

        # append replacement/extension summary (tailstorm_ssz.ml:322-346);
        # extend derives from the PRE-action private tip (the reference's
        # `state.private_` in apply), so on Adopt the replacement summary
        # still targets the abandoned chain, not the freshly adopted one
        vote_filter = jnp.where(proceed, dag.exists(),
                                dag.miner == D.ATTACKER)
        has_conf = self.confirming(dag, state.private).any()
        prev = self.prev_summary(dag, state.private)
        extend = jnp.where(has_conf | (prev < 0), state.private, prev)
        dag, pending, fresh = self.append_summary(
            dag, extend, jnp.int32(D.ATTACKER), vote_filter, dag.vis_a,
            state.time)
        # redundant appends produce no Append interaction (the vertex is
        # already attacker-visible, so no OnNode event fires)
        pi = jnp.maximum(pending, 0)
        stale = stale.at[pi].set(jnp.where(fresh, False, stale[pi]))
        pending = jnp.where(fresh, pending, D.NONE)

        return state.replace(dag=dag, public=public, private=private,
                             match_tgt=match_tgt, def_dirty=def_dirty,
                             stale=stale, pending_append=pending)

    def step(self, state: State, action, params: EnvParams):
        state = self._apply(state, action)
        state = self._advance(state, params)
        state = state.replace(steps=state.steps + 1)
        dag = state.dag

        if self.ring:
            # retire below the summary one BEHIND the fork's LCA: a
            # private tip without confirming votes re-appends its
            # replacement on its predecessor (tailstorm_ssz.ml:335-342),
            # so that one extra summary (and its votes, all gid-above
            # it) must stay dereferenceable
            lca = self.summary_lca(dag, state.public, state.private)
            prev = self.prev_summary(dag, lca)
            anchor = jnp.where(prev >= 0, jnp.maximum(prev, 0), lca)
            dag = D.retire_below(dag, dag.gid[anchor])
            # a match race whose target summary retires is dead — the
            # slot may be reclaimed and must never be compared again
            state = state.replace(
                dag=dag,
                match_tgt=D.drop_if_retired(dag, state.match_tgt))

        # winner: compare_summaries = (height, confirming votes), ties to
        # the attacker (engine.ml:196-206; tailstorm.ml:183-194)
        n_pub = self.confirming(dag, state.public).sum()
        n_priv = self.confirming(dag, state.private).sum()
        pub_better = (dag.height[state.public] > dag.height[state.private]) | (
            (dag.height[state.public] == dag.height[state.private])
            & (n_pub > n_priv))
        head = jnp.where(pub_better, state.public, state.private)

        return self.finish_step(
            state, params,
            reward_attacker=dag.cum_atk[head],
            reward_defender=dag.cum_def[head],
            progress=(dag.height[head] * self.k).astype(jnp.float32),
            chain_time=dag.born_at[head],
            extra_done=dag.overflow,
        )

    # -- policies (tailstorm_ssz.ml:365-472) --------------------------------

    def _make_policies(self):
        k = self.k

        def wrap(fn):
            def wrapped(obs):
                (pub_b, priv_b, _, pub_v, priv_vi, priv_ve,
                 _pd, _id, _ed, _ev) = self.decode_obs(obs)
                return fn(pub_b, priv_b, pub_v, priv_vi, priv_ve)
            return wrapped

        def honest(pub_b, priv_b, pub_v, priv_vi, priv_ve):
            return jnp.where(pub_b > priv_b, ADOPT_PROCEED, OVERRIDE_PROCEED)

        def get_ahead(pub_b, priv_b, pub_v, priv_vi, priv_ve):
            return jnp.where(
                pub_b > priv_b, ADOPT_PROCEED,
                jnp.where(pub_b < priv_b, OVERRIDE_PROCEED, WAIT_PROCEED))

        def minor_delay(pub_b, priv_b, pub_v, priv_vi, priv_ve):
            return jnp.where(
                pub_b > priv_b, ADOPT_PROCEED,
                jnp.where(pub_b == 0, WAIT_PROCEED, OVERRIDE_PROCEED))

        def long_delay(pub_b, priv_b, pub_v, priv_vi, priv_ve):
            return jnp.where(
                pub_b > priv_b, ADOPT_PROCEED,
                jnp.where(
                    pub_b == 0, WAIT_PROCEED,
                    jnp.where(
                        pub_b + 10 < priv_b, OVERRIDE_PROCEED,
                        jnp.where(
                            pub_b * k + pub_v + 1 < priv_b * k + priv_vi,
                            WAIT_PROCEED, OVERRIDE_PROCEED))))

        def avoid_loss_a(pub_b, priv_b, pub_v, priv_vi, priv_ve):
            # avoid_loss (tailstorm_ssz.ml:407-422)
            return jnp.where(
                priv_b < pub_b, ADOPT_PROCEED,
                jnp.where(
                    pub_b == 0, WAIT_PROCEED,
                    jnp.where(
                        (priv_vi == 0) & (priv_b == pub_b + 1),
                        OVERRIDE_PROCEED,
                        jnp.where(
                            (pub_b == priv_b) & (priv_vi == pub_v + 1),
                            OVERRIDE_PROCEED,
                            jnp.where(priv_b - pub_b > 10,
                                      OVERRIDE_PROCEED, WAIT_PROCEED)))))

        def _avoid_loss_alt(match_action):
            def fn(pub_b, priv_b, pub_v, priv_vi, priv_ve):
                hp = pub_b * k + pub_v
                ap = priv_b * k + priv_vi
                return jnp.where(
                    pub_b == 0, WAIT_PROCEED,
                    jnp.where(
                        (pub_b == 1) & (hp == ap), match_action,
                        jnp.where(
                            hp > ap, ADOPT_PROCEED,
                            jnp.where(
                                hp == ap - 1, OVERRIDE_PROCEED,
                                jnp.where(pub_b < priv_b - 10,
                                          OVERRIDE_PROCEED, WAIT_PROCEED)))))
            return fn

        return {
            "honest": wrap(honest),
            "get-ahead": wrap(get_ahead),
            "minor-delay": wrap(minor_delay),
            "avoid-loss": wrap(_avoid_loss_alt(MATCH_PROCEED)),
            "avoid-loss-a": wrap(avoid_loss_a),
            "avoid-loss-b": wrap(_avoid_loss_alt(OVERRIDE_PROCEED)),
            "long-delay": wrap(long_delay),
        }
