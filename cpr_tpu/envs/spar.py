"""Spar — Simple Parallel PoW — under the SSZ-like withholding attack
space, on the DAG tensor substrate.

Reference counterparts:
- protocol: simulator/protocols/spar.ml — every puzzle solution is either
  a vote (single parent block, same height) or a block (parent block +
  k-1 votes on it, height+1) (spar.ml:100-117); the miner drafts a block
  as soon as k-1 votes confirm its preferred block, otherwise a vote
  (spar.ml:203-222); preference by (height, confirming votes, own-first,
  earliest-seen) (spar.ml:185-196); `Constant` (1 per PoW in the block's
  closure incl. the block) and `Block` (k to the block miner) rewards
  (spar.ml:140-156),
- attack space: simulator/protocols/spar_ssz.ml — 7-field observation
  (spar_ssz.ml:22-33), Action8 (ssz_tools.ml:230-263) where
  Proceed/Prolong set a *persistent* mining filter used by subsequent
  puzzle drafts (spar_ssz.ml:186-189,305-308), release targeting by
  (height, votes) of the public head with proposal fast-path
  (spar_ssz.ml:261-298), policies honest/selfish (spar_ssz.ml:332-351),
- engine semantics: simulator/gym/engine.ml:97-273 (one env step per
  attacker interaction, defender cloud, gamma via message ordering).

TPU re-design: one env step = one attacker action + one Bernoulli(alpha)
activation whose payload (block vs vote) is decided at mining time from
masked vote counts; vote selection for a block draft is one top-k over an
(own-first, earliest-seen) composite score. Votes store their block in the
`signer` column so confirming-vote counts are masked compares. gamma races
follow the Nakamoto env's rule: a release that ties the defender's
(height, votes) preference arms a race and the next defender activation
mines on the attacker's released block with probability gamma.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from cpr_tpu import obs as obslib
from cpr_tpu.core import dag as D
from cpr_tpu.envs.base import JaxEnv
from cpr_tpu.params import EnvParams

BLOCK, VOTE = 0, 1

# events: Discrete [`ProofOfWork; `Network] (spar_ssz.ml:45)
EV_POW, EV_NETWORK = 0, 1

# Action8 ranks (ssz_tools.ml:230-263)
(ADOPT_PROLONG, OVERRIDE_PROLONG, MATCH_PROLONG, WAIT_PROLONG,
 ADOPT_PROCEED, OVERRIDE_PROCEED, MATCH_PROCEED, WAIT_PROCEED) = range(8)


def obs_fields(k: int):
    """spar_ssz.ml:36-46."""
    return (
        obslib.Field("public_blocks", obslib.UINT, scale=1),
        obslib.Field("private_blocks", obslib.UINT, scale=1),
        obslib.Field("diff_blocks", obslib.INT, scale=1),
        obslib.Field("public_votes", obslib.UINT, scale=k - 1),
        obslib.Field("private_votes_inclusive", obslib.UINT, scale=k - 1),
        obslib.Field("private_votes_exclusive", obslib.UINT, scale=k - 1),
        obslib.Field("event", obslib.DISCRETE, n=2),
    )


@struct.dataclass
class State:
    dag: D.Dag
    public: jnp.ndarray  # defender-preferred block (simulated)
    private: jnp.ndarray  # attacker-preferred block
    event: jnp.ndarray  # EV_*
    race_tip: jnp.ndarray  # live match race target block (-1: none)
    mining_excl: jnp.ndarray  # bool: Prolong = exclusive vote filter
    # episode bookkeeping (engine.ml:69-79)
    time: jnp.ndarray
    steps: jnp.ndarray
    n_activations: jnp.ndarray
    last_reward_attacker: jnp.ndarray
    last_reward_defender: jnp.ndarray
    last_progress: jnp.ndarray
    last_chain_time: jnp.ndarray
    last_sim_time: jnp.ndarray
    key: jax.Array


class SparSSZ(JaxEnv):
    n_actions = 8
    # a fresh reset populates genesis + one _mine append; see
    # JaxEnv.reset_dag_rows contract
    reset_dag_rows = 2

    def __init__(self, k: int = 8, incentive_scheme: str = "constant",
                 unit_observation: bool = True, max_steps_hint: int = 256,
                 window: int | None = None,
                 anc_masks: bool | None = None):
        assert k >= 2
        assert incentive_scheme in ("constant", "block")
        self.k = k
        self.incentive_scheme = incentive_scheme
        self.unit_observation = unit_observation
        # exactly one PoW append per step; floored at the k+8 release
        # window (top_k needs k <= capacity)
        self.capacity = max(max_steps_hint + 8, k + 8)
        # O(active-set) ring mode (see bk.py): the window replaces the
        # episode-length-proportional capacity; it must cover the live
        # fork plus its confirming votes (k slots per withheld block).
        # A deeper fork evicts a live slot -> overflow ends the episode,
        # the same semantics as capacity exhaustion in full mode.
        if window is not None:
            self.capacity = max(window, k + 8)
        self.ring = window is not None
        # ancestry planes: ON by default only in ring mode (quadratic in
        # capacity; ring retire logic needs the masked queries), full
        # mode keeps the O(B) walk-based queries
        self.anc_masks = self.ring if anc_masks is None else anc_masks
        assert self.anc_masks or not self.ring, \
            "ring windows require anc_masks (walks could cross reclaimed slots)"
        self.max_parents = k
        self.fields = obs_fields(k)
        self.observation_length = len(self.fields)
        self.low, self.high = obslib.low_high(self.fields, unit_observation)
        self.policies = self._make_policies()

    # -- protocol primitives (spar.ml) -------------------------------------

    def confirming(self, dag, b, extra_mask=None):
        """Votes confirming block b (spar.ml:88-91); votes store their
        block in the `signer` column.  newer_than guards the ring wrap:
        a stale vote whose block slot was reclaimed by b would alias
        (no-op in full mode)."""
        m = (dag.exists() & (dag.kind == VOTE) & (dag.signer == b)
             & D.newer_than(dag, b))
        if extra_mask is not None:
            m = m & extra_mask
        return m

    def common_ancestor(self, dag, a, b):
        """Masked chain-row intersection with ancestry planes, else the
        height-synchronized walk (full mode; reclaim-safe there)."""
        if dag.has_masks:
            return D.common_ancestor_masked(dag, a, b)
        return D.common_ancestor_by_height(dag, a, b)

    def last_block(self, dag, x):
        """spar.ml:77-84."""
        return jnp.where(dag.kind[x] == BLOCK, x, dag.signer[x])

    def cmp_blocks(self, dag, x, y, vote_filter_mask, me):
        """Honest compare (spar.ml:185-196): height, confirming votes,
        own-appended first, earliest-seen first. >0 iff x preferred."""
        nx = self.confirming(dag, x, vote_filter_mask).sum()
        ny = self.confirming(dag, y, vote_filter_mask).sum()
        own_x = (dag.miner[x] == me).astype(jnp.int32)
        own_y = (dag.miner[y] == me).astype(jnp.int32)
        seen = jnp.where(me == D.ATTACKER, dag.born_at, dag.vis_d_since)
        key_x = (dag.height[x], nx, own_x, -seen[x])
        key_y = (dag.height[y], ny, own_y, -seen[y])
        gt = jnp.bool_(False)
        eq = jnp.bool_(True)
        for a, b in zip(key_x, key_y):
            gt = gt | (eq & (a > b))
            eq = eq & (a == b)
        return jnp.where(x == y, False, gt)

    def update_head(self, dag, old, cand, me):
        mask = jnp.where(me == D.ATTACKER, dag.exists(), dag.vis_d)
        better = self.cmp_blocks(dag, cand, old, mask, me)
        return jnp.where(better, cand, old)

    def _mine_one(self, dag, head, view, vote_filter, miner, time, powh):
        """puzzle_payload' (spar.ml:203-227): block if >= k-1 filtered
        votes confirm the head, else a vote. Returns (dag, idx, is_block)."""
        k = self.k
        votes = self.confirming(dag, head, view) & vote_filter
        n = votes.sum()
        make_block = n >= (k - 1)
        # vote choice: own first, then earliest seen (spar.ml:208-214)
        seen = jnp.where(miner == D.ATTACKER, dag.born_at, dag.vis_d_since)
        horizon = dag.born_at.max() + 1.0
        score = jnp.where(dag.miner == miner, seen, seen + horizon)
        vidx, vvalid = D.top_k_by(score, votes, k - 1)
        take = vvalid  # exactly k-1 valid when make_block
        row_block = jnp.concatenate([
            jnp.array([head], jnp.int32),
            jnp.where(take, vidx, D.NONE).astype(jnp.int32)])
        row_vote = jnp.full((self.max_parents,), D.NONE, jnp.int32
                            ).at[0].set(head)
        row = jnp.where(make_block, row_block, row_vote)
        height = dag.height[head] + jnp.where(make_block, 1, 0)
        # rewards at block append (spar.ml:140-156)
        ids = jnp.where(take, dag.miner[jnp.clip(vidx, 0)], D.NONE)
        if self.incentive_scheme == "constant":
            atk = ((ids == D.ATTACKER).sum() + (miner == D.ATTACKER)
                   ).astype(jnp.float32)
            dfn = ((ids == D.DEFENDER).sum() + (miner == D.DEFENDER)
                   ).astype(jnp.float32)
        else:  # block: k to the block miner
            atk = jnp.where(miner == D.ATTACKER, float(self.k), 0.0)
            dfn = jnp.where(miner == D.DEFENDER, float(self.k), 0.0)
        atk = jnp.where(make_block, atk, 0.0)
        dfn = jnp.where(make_block, dfn, 0.0)
        kind = jnp.where(make_block, BLOCK, VOTE)
        signer = jnp.where(make_block, D.NONE, head)
        progress = (height * k + jnp.where(make_block, 0, 1)
                    ).astype(jnp.float32)
        dag, idx = D.append(
            dag, row, kind=kind, height=height, pow_hash=powh,
            signer=signer, miner=miner, vis_a=True,
            vis_d=(miner == D.DEFENDER), time=time,
            reward_atk=atk, reward_def=dfn, progress=progress)
        return dag, idx, make_block

    # -- env API ------------------------------------------------------------

    def reset(self, key: jax.Array, params: EnvParams):
        dag = D.empty(self.capacity, self.max_parents,
                      ring=self.ring, anc_masks=self.anc_masks)
        dag, root = D.append(
            dag, jnp.full((self.max_parents,), D.NONE, jnp.int32),
            kind=BLOCK, height=0, miner=D.NONE, vis_a=True, vis_d=True,
            time=0.0, progress=0.0)
        z = jnp.int32(0)
        f = jnp.float32(0.0)
        state = State(
            dag=dag, public=root, private=root,
            event=jnp.int32(EV_POW), race_tip=D.NONE,
            mining_excl=jnp.bool_(False),
            time=f, steps=z, n_activations=z,
            last_reward_attacker=f, last_reward_defender=f,
            last_progress=f, last_chain_time=f, last_sim_time=f,
            key=key,
        )
        state = self._mine(state, params)
        return state, self.observe(state)

    def _mine(self, state: State, params: EnvParams) -> State:
        """One activation (engine.ml:108-121 collapsed)."""
        dag = state.dag
        key, k_dt, k_mine, k_hash, k_gamma = jax.random.split(state.key, 5)
        dt = jax.random.exponential(k_dt) * params.activation_delay
        time = state.time + dt
        attacker = jax.random.uniform(k_mine) < params.alpha
        powh = jax.random.uniform(k_hash)

        # gamma race (network.ml:61-105 collapsed): the defender mines on
        # the attacker's released tip while the preference tie is live
        tgt = jnp.maximum(state.race_tip, 0)
        still_tie = ((state.race_tip >= 0)
                     & (dag.height[tgt] == dag.height[state.public])
                     & (self.confirming(dag, tgt, dag.vis_d).sum()
                        == self.confirming(dag, state.public,
                                           dag.vis_d).sum()))
        gamma_hit = (~attacker & still_tie
                     & (jax.random.uniform(k_gamma) < params.gamma))
        def_head = jnp.where(gamma_hit, tgt, state.public)
        race_tip = jnp.where(attacker, state.race_tip, D.NONE)

        atk_filter = jnp.where(state.mining_excl,
                               dag.miner == D.ATTACKER, dag.exists())
        head = jnp.where(attacker, state.private, def_head)
        view = jnp.where(attacker, dag.vis_a, dag.vis_d)
        filt = jnp.where(attacker, atk_filter, dag.exists())
        miner = jnp.where(attacker, D.ATTACKER, D.DEFENDER)
        dag, idx, is_blk = self._mine_one(
            dag, head, view, filt, miner, time, powh)

        # prepare (spar_ssz.ml:209-222): attacker prefers its own block;
        # the defender runs update_head on the new block's chain
        private = jnp.where(attacker & is_blk, idx, state.private)
        public = jnp.where(
            attacker, state.public,
            jnp.where(is_blk,
                      self.update_head(dag, def_head, idx,
                                       jnp.int32(D.DEFENDER)),
                      def_head))
        return state.replace(
            dag=dag, private=private, public=public, race_tip=race_tip,
            event=jnp.where(attacker, EV_POW, EV_NETWORK).astype(jnp.int32),
            time=time, n_activations=state.n_activations + 1, key=key,
        )

    def observe(self, state: State):
        """spar_ssz.ml:226-253."""
        dag = state.dag
        ca = jnp.maximum(
            self.common_ancestor(dag, state.public, state.private), 0)
        pub_votes = self.confirming(dag, state.public, dag.vis_d).sum()
        priv_inc = self.confirming(dag, state.private).sum()
        priv_exc = self.confirming(dag, state.private,
                                   dag.miner == D.ATTACKER).sum()
        return obslib.encode(
            self.fields,
            (
                dag.height[state.public] - dag.height[ca],
                dag.height[state.private] - dag.height[ca],
                dag.height[state.private] - dag.height[state.public],
                pub_votes, priv_inc, priv_exc,
                state.event,
            ),
            self.unit_observation,
        )

    def _apply(self, state: State, action) -> State:
        """spar_ssz.ml:255-317."""
        dag = state.dag
        k = self.k
        is_adopt = (action == ADOPT_PROLONG) | (action == ADOPT_PROCEED)
        is_override = (action == OVERRIDE_PROLONG) | (action == OVERRIDE_PROCEED)
        is_match = (action == MATCH_PROLONG) | (action == MATCH_PROCEED)
        is_release = is_override | is_match
        mining_excl = action < 4  # Prolong variants

        # release targeting (spar_ssz.ml:261-273)
        h_pub = dag.height[state.public]
        nv_pub = self.confirming(dag, state.public, dag.vis_d).sum()
        tgt_h = jnp.where(is_override & (nv_pub >= k), h_pub + 1, h_pub)
        tgt_v = jnp.where(is_match, nv_pub,
                          jnp.where(nv_pub >= k, 0, nv_pub + 1))

        # private chain block at the target height: one masked chain-row
        # reduction with ancestry planes (block chains ride parent slot
        # 0), a precursor walk in full mode
        if dag.has_masks:
            blk = D.chain_first_at_most(dag, state.private, dag.height,
                                        tgt_h)
        else:
            blk = D.block_at_height(dag, state.private, tgt_h)
        blk = jnp.maximum(blk, 0)
        # proposal fast path (spar_ssz.ml:283-291): if quorum-many votes
        # requested, prefer an existing block child, FIRST in insertion
        # order (slot order wraps in a ring — first_by_age is the
        # wrap-safe lowest-slot argmax)
        child_blocks = D.children0_mask(dag, blk) & (dag.kind == BLOCK)
        has_prop = child_blocks.any()
        first_prop = jnp.maximum(D.first_by_age(dag, child_blocks), 0)
        use_prop = (tgt_v >= k) & has_prop
        rel_block = jnp.where(use_prop, first_prop, blk).astype(jnp.int32)
        rel_votes_n = jnp.where(use_prop, 0, tgt_v)

        votes = self.confirming(dag, rel_block)
        vidx, vvalid = D.top_k_by(dag.born_at, votes, self.k + 8)
        take = jnp.arange(self.k + 8) < rel_votes_n
        # fall back to releasing every confirming vote when the selection
        # window cannot hold the request (rel_votes_n > k+8) — otherwise
        # the release would silently ship fewer votes than the reference's
        # Compare.first nvotes selection and the override might not bite
        not_enough = (votes.sum() < rel_votes_n) | (rel_votes_n > self.k + 8)
        vote_mask = D.mask_of(vidx, vvalid & take, self.capacity)
        vote_mask = jnp.where(not_enough, votes, vote_mask)

        # recursive share: one closure-row read with ancestry planes,
        # the bounded chain walk in full mode; the chosen votes sit
        # directly on the released block, so a flat release covers them
        if dag.has_masks:
            released = D.release_masked(dag, rel_block, state.time)
        else:
            released = D.release_chain(dag, rel_block, state.time)
        released = D.release(released, vote_mask, state.time)
        dag = D.select_vis(is_release, released, dag)

        # deliver to the simulated defender; a tie arms the gamma race
        rb = self.last_block(dag, rel_block)
        public = jnp.where(
            is_release,
            self.update_head(dag, state.public, rb, jnp.int32(D.DEFENDER)),
            state.public)
        tie = (is_release & (rb != public)
               & (dag.height[rb] == dag.height[public])
               & (self.confirming(dag, rb, dag.vis_d).sum()
                  == self.confirming(dag, public, dag.vis_d).sum()))
        race_tip = jnp.where(tie, rb,
                             jnp.where(is_adopt | is_override, D.NONE,
                                       state.race_tip))
        private = jnp.where(is_adopt, public, state.private)
        return state.replace(dag=dag, public=public, private=private,
                             race_tip=race_tip,
                             mining_excl=jnp.asarray(mining_excl))

    def step(self, state: State, action, params: EnvParams):
        state = self._apply(state, action)
        state = self._mine(state, params)
        state = state.replace(steps=state.steps + 1)
        dag = state.dag

        if self.ring:
            # retire everything below the preference fork: every later
            # read starts at public/private (descendants of their common
            # ancestor) or at votes hanging on the fork (appended after
            # the CA, so gid-above it).  The race tip may outlive the
            # fork — drop it while its slot still holds the original.
            ca = D.common_ancestor_masked(dag, state.public, state.private)
            dag = D.retire_below(dag, dag.gid[jnp.maximum(ca, 0)])
            state = state.replace(
                dag=dag, race_tip=D.drop_if_retired(dag, state.race_tip))

        # winner (spar.ml:123-128): (height, confirming votes), ties to
        # the attacker (node 0 first in the fold)
        n_pub = self.confirming(dag, state.public).sum()
        n_priv = self.confirming(dag, state.private).sum()
        pub_better = (dag.height[state.public] > dag.height[state.private]) | (
            (dag.height[state.public] == dag.height[state.private])
            & (n_pub > n_priv))
        head = jnp.where(pub_better, state.public, state.private)

        return self.finish_step(
            state, params,
            reward_attacker=dag.cum_atk[head],
            reward_defender=dag.cum_def[head],
            progress=(dag.height[head] * self.k).astype(jnp.float32),
            chain_time=dag.born_at[head],
            extra_done=dag.overflow,
        )

    # -- policies (spar_ssz.ml:332-351) -------------------------------------

    def _make_policies(self):
        def wrap(fn):
            def wrapped(obs):
                pub_b, priv_b, _, pub_v, priv_vi, priv_ve, ev = \
                    self.decode_obs(obs)
                return fn(pub_b, priv_b, pub_v, priv_vi, priv_ve)
            return wrapped

        def honest(pub_b, priv_b, pub_v, priv_vi, priv_ve):
            return jnp.where(pub_b > 0, ADOPT_PROCEED, OVERRIDE_PROCEED)

        def selfish(pub_b, priv_b, pub_v, priv_vi, priv_ve):
            return jnp.where(
                priv_b < pub_b, ADOPT_PROCEED,
                jnp.where(
                    (priv_b == 0) & (pub_b == 0), WAIT_PROLONG,
                    jnp.where(pub_b == 0, WAIT_PROCEED, OVERRIDE_PROCEED)))

        return {"honest": wrap(honest), "selfish": wrap(selfish)}
