"""Tailstorm/ll (June '22) attack environment.

Reference counterpart: simulator/protocols/tailstorm_june.ml (kept by the
reference to reproduce its W&B run 257) and tailstorm_june_ssz.ml.  The
protocol is Stree's structure — proof-of-work summaries carrying k-1
depth-labelled votes, preference by (block, vote) — with Tailstorm's
reward menu plus a `block` scheme paying the whole k to the summary
miner (tailstorm_june.ml:176-205).  Sub-block selection is fixed to the
own-reward-first greedy quorum (tailstorm_june.ml:282-350), i.e. Stree's
`heuristic`.

The env therefore derives from StreeSSZ: same DAG layout, observation
fields, action space (8 actions), and policies; only the key, the scheme
menu, and the `block` reward branch differ.
"""

from __future__ import annotations

import jax.numpy as jnp

from cpr_tpu.core import dag as D
from cpr_tpu.envs.stree import StreeSSZ

INCENTIVE_SCHEMES = ("block", "constant", "discount", "punish", "hybrid")


class TailstormJuneSSZ(StreeSSZ):
    def __init__(self, k: int = 8, incentive_scheme: str = "constant",
                 unit_observation: bool = True, max_steps_hint: int = 256,
                 release_scan: int = 128):
        assert incentive_scheme in INCENTIVE_SCHEMES
        super().__init__(
            k=k,
            incentive_scheme=("constant" if incentive_scheme == "block"
                              else incentive_scheme),
            subblock_selection="heuristic",
            unit_observation=unit_observation,
            max_steps_hint=max_steps_hint,
            release_scan=release_scan)
        self.incentive_scheme = incentive_scheme

    def block_reward(self, dag, leaves_row, miner):
        """`block`: the summary's miner collects the whole k
        (tailstorm_june.ml:177 constant_block); other schemes follow
        Stree (same reward' core, tailstorm_june.ml:179-205)."""
        if self.incentive_scheme != "block":
            return super().block_reward(dag, leaves_row, miner)
        k = jnp.float32(self.k)
        atk = jnp.where(miner == D.ATTACKER, k, 0.0)
        dfn = jnp.where(miner == D.DEFENDER, k, 0.0)
        return atk, dfn
