"""Ethereum PoW (uncle blocks) under the SSZ-like withholding attack
space, on the DAG tensor substrate.

Reference counterparts:
- protocol: simulator/protocols/ethereum.ml — blocks with <= 2 uncles
  (Byzantium) or unbounded uncles (Whitepaper), data {height, work, miner}
  (ethereum.ml:66-70), uncle validity (recent within 6 generations, child
  of a chain ancestor, not already in chain/uncles, ethereum.ml:102-151),
  honest uncle selection over a 6-generation window with own-first,
  oldest-first preference (ethereum.ml:226-279), constant and discount
  reward schemes (ethereum.ml:174-198),
- attack space: simulator/protocols/ethereum_ssz.ml — 10-field observation
  (ethereum_ssz.ml:21-40), actions {Adopt_discard, Adopt_release,
  Override, Match, Release1, Wait} x uncle mining rule {own, foreign}
  (ethereum_ssz.ml:161-277), agent state machine (ethereum_ssz.ml:279-429),
  policies honest/selfish_release/selfish_discard/fn19/fn19pkel
  (ethereum_ssz.ml:444-538),
- engine semantics: simulator/gym/engine.ml:97-273 (one env step per
  attacker interaction, defender cloud, gamma via message ordering).

TPU re-design: blocks live in the fixed-capacity DAG; parent slot 0 is the
chain parent (the precursor — "uncles are not part of the linear history",
ethereum.ml:165), slots 1..U hold uncle references. The 6-generation uncle
window is a statically unrolled 6-step chain walk producing boolean
candidate masks; uncle selection is a masked top-k with an (own-first,
oldest-first) composite score (ethereum.ml:226-232). One env step is one
attacker action + one Bernoulli(alpha) mining draw.

Documented deviations from the reference:
- The reference swaps the preference mapping: `LongestChain` compares
  cumulative work and `HeaviestChain` compares height (ethereum.ml:80-84,
  the names are crossed). We reproduce the *behavior*: preset
  "whitepaper" prefers by work and progresses by height; preset
  "byzantium" prefers by height and progresses by work. Policies follow
  the same naming convention the reference uses (ethereum_ssz.ml:461-465).
- Whitepaper's unbounded uncle cap becomes a static `uncle_cap`
  (default 6): a tensor parents row needs a fixed width. Within the
  2-party selfish-mining game more than 6 includable orphans do not occur
  in practice (the 6-generation window bounds candidates).
- gamma races follow the Nakamoto env's strict-match rule: a released tip
  whose preference ties the defender head only splits defender compute
  when the competing defender block has just arrived (event == Network) —
  the propagation-race window of network.ml:61-105.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from cpr_tpu import obs as obslib
from cpr_tpu.core import dag as D
from cpr_tpu.envs.base import JaxEnv
from cpr_tpu.params import EnvParams

# events: Discrete [`ProofOfWork; `Network] (ethereum_ssz.ml:39)
EV_POW, EV_NETWORK = 0, 1

# action ranks (ethereum_ssz.ml:172-221, declaration order)
ADOPT_DISCARD, ADOPT_RELEASE, OVERRIDE, MATCH, RELEASE1, WAIT = range(6)
# uncle mining rules, index = own * 2 + foreign (ethereum_ssz.ml:238-241)
N_UNCLE_RULES = 4

OBS_FIELDS = (
    obslib.Field("public_height", obslib.UINT, scale=1),
    obslib.Field("public_work", obslib.UINT, scale=1),
    obslib.Field("private_height", obslib.UINT, scale=1),
    obslib.Field("private_work", obslib.UINT, scale=1),
    obslib.Field("diff_height", obslib.INT, scale=1),
    obslib.Field("diff_work", obslib.INT, scale=1),
    obslib.Field("public_orphans", obslib.UINT, scale=1),
    obslib.Field("private_orphans_inclusive", obslib.UINT, scale=1),
    obslib.Field("private_orphans_exclusive", obslib.UINT, scale=1),
    obslib.Field("event", obslib.DISCRETE, n=2),
)

UNCLE_WINDOW = 6  # generations (ethereum.ml:112, check_recent ethereum.ml:124-127)


@struct.dataclass
class State:
    dag: D.Dag
    public: jnp.ndarray  # defender cloud's preferred block
    private: jnp.ndarray  # attacker's preferred block
    event: jnp.ndarray  # EV_POW | EV_NETWORK
    race_tip: jnp.ndarray  # released tip of a live preference-tie race (-1)
    mining_own: jnp.ndarray  # bool, current uncle mining rule
    mining_foreign: jnp.ndarray  # bool
    # episode bookkeeping (engine.ml:69-79)
    time: jnp.ndarray
    steps: jnp.ndarray
    n_activations: jnp.ndarray
    last_reward_attacker: jnp.ndarray
    last_reward_defender: jnp.ndarray
    last_progress: jnp.ndarray
    last_chain_time: jnp.ndarray
    last_sim_time: jnp.ndarray
    key: jax.Array


class EthereumSSZ(JaxEnv):
    """Ethereum withholding attack env, one step per attacker interaction."""

    n_actions = 6 * N_UNCLE_RULES
    obs_fields = OBS_FIELDS
    observation_length = len(OBS_FIELDS)
    # a fresh reset populates genesis + one _mine block; the logical
    # reset (JaxEnv.reset_dag_rows contract) matters doubly here since
    # the ancestry planes are (B, B) — a full-tree select per auto-reset
    # step would copy them wholesale
    reset_dag_rows = 2

    def __init__(self, preset: str = "byzantium", *,
                 preference: str | None = None, progress: str | None = None,
                 max_uncles: int | None = None,
                 incentive_scheme: str | None = None,
                 uncle_cap: int = 6, unit_observation: bool = True,
                 strict_match: bool = True, max_steps_hint: int = 256,
                 window: int | None = None,
                 anc_masks: bool | None = None):
        # presets (ethereum.ml:12-24; behavioral mapping, see module doc)
        if preset == "whitepaper":
            defaults = dict(preference="work", progress="height",
                            max_uncles=None, incentive_scheme="constant")
        elif preset == "byzantium":
            defaults = dict(preference="height", progress="work",
                            max_uncles=2, incentive_scheme="discount")
        else:
            raise ValueError(f"unknown preset {preset!r}")
        self.preset = preset
        self.preference = preference or defaults["preference"]
        self.progress = progress or defaults["progress"]
        mu = max_uncles if max_uncles is not None else defaults["max_uncles"]
        self.max_uncles = min(mu, uncle_cap) if mu is not None else uncle_cap
        self.incentive_scheme = (incentive_scheme
                                 or defaults["incentive_scheme"])
        assert self.preference in ("height", "work")
        assert self.progress in ("height", "work")
        assert self.incentive_scheme in ("constant", "discount")
        self.unit_observation = unit_observation
        self.fields = OBS_FIELDS
        self.strict_match = strict_match
        # one block append per step + the reset draw
        self.capacity = max_steps_hint + 8
        # O(active-set) ring: per-step cost becomes O(window); the
        # window must cover the fork PLUS the 6-generation uncle
        # lookback below its common ancestor (the step retires below
        # height ca-7).  One block per step, so ~window steps of fork
        # + lookback fit; deeper forks overflow like capacity
        # exhaustion in full mode.
        if window is not None:
            self.capacity = max(window, UNCLE_WINDOW + 10)
        self.ring = window is not None
        # ancestry planes are (capacity, capacity): default ON only in
        # ring mode, where capacity is the small active-set window and
        # the retire logic needs the masked queries.  Full mode falls
        # back to the lifted jump walks, keeping state O(capacity).
        self.anc_masks = self.ring if anc_masks is None else anc_masks
        assert self.anc_masks or not self.ring, \
            "ring windows require anc_masks (walks could cross reclaimed slots)"
        self.max_parents = 1 + self.max_uncles
        self.low, self.high = obslib.low_high(OBS_FIELDS, unit_observation)
        self.policies = self._make_policies()

    # -- protocol primitives (ethereum.ml) ---------------------------------

    def pref(self, dag, b):
        """Preference value of block b (ethereum.ml:80-84; aux = work)."""
        if self.preference == "height":
            return dag.height[b]
        return dag.aux[b]

    def pref_all(self, dag):
        return dag.height if self.preference == "height" else dag.aux

    def progress_of(self, dag, b):
        v = dag.height[b] if self.progress == "height" else dag.aux[b]
        return v.astype(jnp.float32)

    def chain_window(self, dag, head):
        """(ancestors, in_chain) for the uncle window at `head`
        (ethereum.ml:237-246): `ancestors` = the up-to-6 proper chain
        ancestors as scalar slot ids (-1 when the walk ran out),
        `in_chain` = head plus the walked blocks and their included
        uncles (anc6's uncles excluded, exactly like the reference).

        Returns scalars instead of a (B,) nua mask so the caller can
        test "precursor is a window ancestor" with UNCLE_WINDOW scalar
        compares against parent0 — indexing a nua mask with the whole
        parent0 array is a batched (B,)->(B,) gather, ~11 ms/step at
        4096 envs on v5e (round-4 device profile)."""
        slots = dag.slots()
        in_chain = (slots == jnp.maximum(head, 0)) & (head >= 0)
        ancestors = []
        b = head
        for _ in range(UNCLE_WINDOW):
            bi = jnp.maximum(b, 0)
            p0 = dag.parent0[bi]
            has = (b >= 0) & (p0 >= 0)
            ancestors.append(jnp.where(has, p0, jnp.int32(-1)))
            for plane in dag.parents:
                v = plane[bi]
                ok = (slots == v) & (v >= 0) & has
                if dag.is_ring:
                    # a stored uncle pointer may reach below the
                    # retirement floor; once that slot is reclaimed the
                    # new occupant (younger than bi) must not be marked
                    # in-chain
                    ok = ok & (dag.gid[jnp.maximum(v, 0)]
                               <= dag.gid[bi])
                in_chain = in_chain | ok
            b = ancestors[-1]
        return ancestors, in_chain

    def uncle_candidates(self, dag, head, view_mask, filter_mask,
                         window=None):
        """Mask of includable uncles for a block on `head`
        (ethereum.ml:252-268): not in chain, chain parent among the
        non-uncle ancestors, visible in the miner's view, passing the
        mining-rule filter. Mask semantics dedupe candidates reachable via
        several window blocks.  `window` takes a precomputed
        chain_window(dag, head) so callers probing several filters at
        the same head (observe's inclusive/exclusive counts) pay for the
        6-level walk once."""
        ancestors, in_chain = window or self.chain_window(dag, head)
        p0 = dag.parent0
        # newer_than: a stale row's p0 aliasing a reclaimed ancestor
        # slot must not read as an uncle candidate (ring wrap; all-true
        # in full mode)
        on_anc = ((p0 == ancestors[0]) & (ancestors[0] >= 0)
                  & D.newer_than(dag, ancestors[0]))
        for a in ancestors[1:]:
            on_anc = on_anc | ((p0 == a) & (a >= 0)
                               & D.newer_than(dag, a))
        return (dag.exists() & view_mask & filter_mask
                & (p0 >= 0) & on_anc & ~in_chain)

    def select_uncles(self, dag, cand_mask, own_mask):
        """Top max_uncles candidates by (own first, lowest preference
        first) (ethereum.ml:226-232, Compare.at_most_first). Returns
        (idx, valid) of width max_uncles."""
        big = jnp.float32(1e7)
        score = (jnp.where(own_mask, 0.0, big)
                 + self.pref_all(dag).astype(jnp.float32))
        return D.top_k_by(score, cand_mask, self.max_uncles)

    def make_block(self, dag, head, view_mask, filter_mask, miner, time,
                   vis_d):
        """Append a block on `head` with selected uncles; computes work,
        height, and the miner/uncle rewards (ethereum.ml:174-198,270-277)."""
        cand = self.uncle_candidates(dag, head, view_mask, filter_mask)
        own = dag.miner == miner
        uidx, uvalid = self.select_uncles(dag, cand, own)
        n_uncles = uvalid.sum()
        height = dag.height[head] + 1
        work = dag.aux[head] + 1 + n_uncles

        # rewards (ethereum.ml:174-198): including miner 1 + n*1/32;
        # uncle miners 15/16 (constant) or (8-delta)/8 (discount)
        u_miner = dag.miner[jnp.clip(uidx, 0)]
        if self.incentive_scheme == "constant":
            u_reward = jnp.where(uvalid, 0.9375, 0.0)
        else:
            delta = (height - dag.height[jnp.clip(uidx, 0)]).astype(jnp.float32)
            u_reward = jnp.where(uvalid, (8.0 - delta) / 8.0, 0.0)
        miner_reward = 1.0 + n_uncles.astype(jnp.float32) * 0.03125
        atk = (jnp.where(u_miner == D.ATTACKER, u_reward, 0.0).sum()
               + jnp.where(miner == D.ATTACKER, miner_reward, 0.0))
        dfn = (jnp.where(u_miner == D.DEFENDER, u_reward, 0.0).sum()
               + jnp.where(miner == D.DEFENDER, miner_reward, 0.0))

        row = jnp.concatenate([
            jnp.array([head], jnp.int32),
            jnp.where(uvalid, uidx, D.NONE).astype(jnp.int32),
        ])
        dag, idx = D.append(
            dag, row, kind=0, height=height, aux=work, miner=miner,
            vis_a=True, vis_d=vis_d, time=time,
            reward_atk=atk, reward_def=dfn,
            progress=(height if self.progress == "height" else work
                      ).astype(jnp.float32),
        )
        return dag, idx

    def update_head(self, dag, old, candidate):
        """Strict preference improvement (ethereum.ml:281-285)."""
        better = self.pref(dag, candidate) > self.pref(dag, old)
        return jnp.where(better, candidate, old)

    def common_ancestor(self, dag, a, b):
        """Chain LCA: masked row intersection when the ancestry planes
        exist, else the (lifted) height-synchronized walk."""
        if dag.has_masks:
            return D.common_ancestor_masked(dag, a, b)
        return D.common_ancestor_by_height(dag, a, b)

    # -- env API -----------------------------------------------------------

    def reset(self, key: jax.Array, params: EnvParams):
        # with anc_masks, the incremental ancestry rows turn every
        # per-step walk (two common-ancestor walks, the release-target
        # walk, the release chain+closure fixpoint — 68% of the step in
        # the round-5 device profile) into one masked reduction; without
        # them, binary lifting keeps those walks O(log depth)
        dag = D.empty(self.capacity, self.max_parents,
                      anc_masks=self.anc_masks, lift=not self.anc_masks,
                      ring=self.ring)
        dag, root = D.append(
            dag, jnp.full((self.max_parents,), D.NONE, jnp.int32),
            kind=0, height=0, aux=0, miner=D.NONE, vis_a=True, vis_d=True,
            time=0.0, progress=0.0)
        z = jnp.int32(0)
        f = jnp.float32(0.0)
        state = State(
            dag=dag, public=root, private=root,
            event=jnp.int32(EV_POW), race_tip=jnp.int32(-1),
            mining_own=jnp.bool_(True), mining_foreign=jnp.bool_(True),
            time=f, steps=z, n_activations=z,
            last_reward_attacker=f, last_reward_defender=f,
            last_progress=f, last_chain_time=f, last_sim_time=f,
            key=key,
        )
        state = self._mine(state, params)
        return state, self.observe(state)

    def _mine(self, state: State, params: EnvParams) -> State:
        """One activation (simulator.ml:465-472 collapsed): Bernoulli(alpha)
        miner choice; the defender cloud splits by gamma while a
        preference-tie race is live."""
        dag = state.dag
        key, k_dt, k_mine, k_gamma = jax.random.split(state.key, 4)
        dt = jax.random.exponential(k_dt) * params.activation_delay
        time = state.time + dt
        attacker_mines = jax.random.uniform(k_mine) < params.alpha
        gamma_hit = jax.random.uniform(k_gamma) < params.gamma

        race_live = (state.race_tip >= 0) & (
            self.pref(dag, jnp.maximum(state.race_tip, 0))
            == self.pref(dag, state.public))
        def_parent = jnp.where(race_live & gamma_hit,
                               jnp.maximum(state.race_tip, 0), state.public)

        atk_filter = (jnp.where(state.mining_own,
                                dag.miner == D.ATTACKER, False)
                      | jnp.where(state.mining_foreign,
                                  dag.miner == D.DEFENDER, False))
        head = jnp.where(attacker_mines, state.private, def_parent)
        view = jnp.where(attacker_mines, dag.vis_a, dag.vis_d)
        filt = jnp.where(attacker_mines, atk_filter, dag.exists())
        miner = jnp.where(attacker_mines, D.ATTACKER, D.DEFENDER)
        dag, blk = self.make_block(
            dag, head, view, filt, miner, time,
            vis_d=~attacker_mines)

        private = jnp.where(attacker_mines, blk, state.private)
        public = jnp.where(attacker_mines, state.public,
                           self.update_head(dag, state.public, blk))
        # a defender block ends any race: either it extends the race tip
        # (which then wins by preference) or it reasserts the public chain
        race_tip = jnp.where(attacker_mines, state.race_tip, -1)
        return state.replace(
            dag=dag, private=private, public=public, race_tip=race_tip,
            event=jnp.where(attacker_mines, EV_POW, EV_NETWORK
                            ).astype(jnp.int32),
            time=time, n_activations=state.n_activations + 1, key=key,
        )

    def _release_upto(self, dag, private, target):
        """Find the first block walking back from `private` with
        preference <= target (ethereum_ssz.ml:404-412).

        Note: under work preference (whitepaper preset) work can jump by
        more than 1 per block (uncles), so the walk may stop strictly
        below `target` and release an already-public block — the
        reference's release_upto has exactly the same stop rule and
        behavior; Override is then a deliberate no-op.

        Preference is monotone nonincreasing down the chain (height and
        cumulative work both are), so the first satisfying block on the
        way down is the highest-height satisfying chain member — one
        masked reduction over the ancestry row when the planes exist,
        else a (lifted) monotone walk."""
        if dag.has_masks:
            return D.chain_first_at_most(dag, private, self.pref_all(dag),
                                         target)
        return D.walk_back(dag, private,
                           lambda d, i: self.pref(d, i) <= target)

    def _apply(self, state: State, action) -> State:
        """ethereum_ssz.ml:398-429."""
        dag = state.dag
        act = action // N_UNCLE_RULES
        uncle_rule = action % N_UNCLE_RULES
        mining_own = uncle_rule >= 2
        mining_foreign = (uncle_rule % 2) == 1

        is_adopt = (act == ADOPT_DISCARD) | (act == ADOPT_RELEASE)
        pub_pref = self.pref(dag, state.public)
        ca = self.common_ancestor(dag, state.public, state.private)
        ca = jnp.maximum(ca, 0)
        # non-walking actions get a huge target so the walk stops at the
        # private tip immediately instead of running to genesis
        target = jnp.where(
            act == MATCH, pub_pref,
            jnp.where(act == OVERRIDE, pub_pref + 1,
                      jnp.where(act == RELEASE1,
                                self.pref(dag, ca) + 1,
                                jnp.int32(1 << 30))))
        release_tip = jnp.where(
            act == ADOPT_RELEASE, state.private,
            self._release_upto(dag, state.private, target))
        do_release = (act == ADOPT_RELEASE) | (act == OVERRIDE) \
            | (act == MATCH) | (act == RELEASE1)
        release_tip = jnp.where(do_release, release_tip, jnp.int32(-1))

        # the recursive share (simulator.ml:401-419): with planes, one
        # closure-row read covers chain ancestors, uncles, and withheld
        # uncles-of-uncles alike — no chain walk, no visibility fixpoint
        # (round-5 profile: those while loops were 68% of the step);
        # without planes, the chain walk plus closure fixpoint.
        # select_vis, not a full-tree select: release only touches the
        # two defender-visibility arrays.
        if dag.has_masks:
            released = D.release_masked(dag, release_tip, state.time)
        else:
            released = D.release_closure(dag, release_tip, state.time)
        dag = D.select_vis(do_release, released, dag)

        # deliver the released tip to the defender cloud
        public = jnp.where(
            do_release,
            self.update_head(dag, state.public,
                             jnp.maximum(release_tip, 0)),
            state.public)
        private = jnp.where(is_adopt, public, state.private)

        # a release that ties the (possibly just updated) public head arms
        # the propagation race, in the match window (module doc)
        tie = do_release & (release_tip >= 0) & (
            self.pref(dag, jnp.maximum(release_tip, 0))
            == self.pref(dag, public)) & (
                jnp.maximum(release_tip, 0) != public)
        if self.strict_match:
            tie = tie & (state.event == EV_NETWORK)
        race_tip = jnp.where(tie, release_tip, state.race_tip)

        return state.replace(
            dag=dag, public=public, private=private, race_tip=race_tip,
            mining_own=mining_own, mining_foreign=mining_foreign,
        )

    def observe(self, state: State):
        """ethereum_ssz.ml:364-396."""
        dag = state.dag
        ca = jnp.maximum(
            self.common_ancestor(dag, state.public, state.private), 0)
        ph = dag.height[state.public] - dag.height[ca]
        pw = dag.aux[state.public] - dag.aux[ca]
        ah = dag.height[state.private] - dag.height[ca]
        aw = dag.aux[state.private] - dag.aux[ca]
        # orphan counts are draft uncle counts, capped by max_uncles;
        # the inclusive/exclusive pair shares one private-head window
        win_priv = self.chain_window(dag, state.private)
        pub_orph = jnp.minimum(
            self.uncle_candidates(dag, state.public, dag.vis_a,
                                  dag.vis_d).sum(),
            self.max_uncles)
        inc = jnp.minimum(
            self.uncle_candidates(dag, state.private, dag.vis_a,
                                  dag.miner >= 0, win_priv).sum(),
            self.max_uncles)
        exc = jnp.minimum(
            self.uncle_candidates(dag, state.private, dag.vis_a,
                                  dag.miner == D.ATTACKER, win_priv).sum(),
            self.max_uncles)
        return obslib.encode(
            OBS_FIELDS,
            (ph, pw, ah, aw, ah - ph, aw - pw, pub_orph, inc, exc,
             state.event),
            self.unit_observation,
        )

    def step(self, state: State, action, params: EnvParams):
        state = self._apply(state, action)
        state = self._mine(state, params)
        state = state.replace(steps=state.steps + 1)
        dag = state.dag

        if self.ring:
            # retire below the uncle window's floor: candidates may sit
            # up to UNCLE_WINDOW generations below the head, so keep
            # one extra height of slack under the fork's common
            # ancestor; a race tip whose block retires ends the race
            ca = jnp.maximum(
                D.common_ancestor_masked(dag, state.public,
                                         state.private), 0)
            anchor = D.chain_first_at_most(
                dag, ca, dag.height, dag.height[ca] - UNCLE_WINDOW - 1)
            dag = D.retire_below(
                dag, jnp.where(anchor >= 0,
                               dag.gid[jnp.maximum(anchor, 0)], 0))
            state = state.replace(
                dag=dag, race_tip=D.drop_if_retired(dag, state.race_tip))

        # winner over [attacker pref, defender pref], ties to the attacker
        # (ethereum.ml:159-162; node 0 first, engine.ml:196-206)
        pub_better = (self.pref(dag, state.public)
                      > self.pref(dag, state.private))
        head = jnp.where(pub_better, state.public, state.private)

        return self.finish_step(
            state, params,
            reward_attacker=dag.cum_atk[head],
            reward_defender=dag.cum_def[head],
            progress=self.progress_of(dag, head),
            chain_time=dag.born_at[head],
            extra_done=dag.overflow,
        )

    # -- policies (ethereum_ssz.ml:444-538) --------------------------------

    def _pref_fields(self, ph, pw, ah, aw):
        """Observation fields the reference policies compare, following its
        naming convention (ethereum_ssz.ml:461-465): whitepaper
        (`LongestChain`) compares heights, byzantium (`HeaviestChain`)
        compares works."""
        if self.preset == "whitepaper":
            return ah, ph
        return aw, pw

    def _make_policies(self):
        # uncle rule indices: own*2 + foreign
        ALL, OWN_ONLY = 3, 2

        def enc(a, u):
            return a * N_UNCLE_RULES + u

        def wrap(fn):
            def wrapped(obs):
                ph, pw, ah, aw, _, _, _, _, _, ev = self.decode_obs(obs)
                return fn(ph, pw, ah, aw, ev)
            return wrapped

        def honest(ph, pw, ah, aw, ev):
            return jnp.where(pw > 0, enc(ADOPT_RELEASE, ALL),
                             enc(OVERRIDE, ALL))

        def selfish(adopt_act):
            def pol(ph, pw, ah, aw, ev):
                priv, pub = self._pref_fields(ph, pw, ah, aw)
                return jnp.where(
                    priv < pub, enc(adopt_act, OWN_ONLY),
                    jnp.where(pub == 0, enc(WAIT, OWN_ONLY),
                              enc(OVERRIDE, OWN_ONLY)))
            return pol

        def fn19_body(adopt_act, rule):
            def pol(ph, pw, ah, aw, ev):
                pow_branch = jnp.where((ah == 2) & (ph == 1),
                                       enc(OVERRIDE, rule), enc(WAIT, rule))
                net_branch = jnp.where(
                    ah < ph, enc(adopt_act, rule),
                    jnp.where(ah == ph, enc(MATCH, rule),
                              jnp.where(ah == ph + 1, enc(OVERRIDE, rule),
                                        enc(RELEASE1, rule))))
                return jnp.where(ev == EV_POW, pow_branch, net_branch)
            return pol

        return {
            "honest": wrap(honest),
            "selfish_release": wrap(selfish(ADOPT_RELEASE)),
            "selfish_discard": wrap(selfish(ADOPT_DISCARD)),
            "fn19": wrap(fn19_body(ADOPT_DISCARD, ALL)),
            "fn19pkel": wrap(fn19_body(ADOPT_RELEASE, OWN_ONLY)),
        }
