"""Assumption-aware env wrapper (jittable).

Reference counterpart: AssumptionScheduleWrapper
(gym/ocaml/cpr_gym/wrappers.py:172-242) — append the current (alpha,
gamma) assumptions to the observation so one policy can generalize over
them.  In the TPU design the schedule itself lives in the *batch*: each
vmap lane carries its own EnvParams (see make_train per_env_params), and
this wrapper only extends the observation with the lane's parameters.
"""

from __future__ import annotations

import jax.numpy as jnp

from cpr_tpu.envs.base import JaxEnv
from cpr_tpu.params import EnvParams


class AssumptionEnv(JaxEnv):
    def __init__(self, inner: JaxEnv):
        self.inner = inner
        self.n_actions = inner.n_actions
        self.observation_length = inner.observation_length + 2
        self.low = jnp.concatenate(
            [jnp.asarray(inner.low), jnp.zeros(2)])
        self.high = jnp.concatenate(
            [jnp.asarray(inner.high), jnp.ones(2)])
        self.policies = {
            name: self._strip(fn) for name, fn in inner.policies.items()}

    @staticmethod
    def _strip(fn):
        if getattr(fn, "takes_state", False):
            def wrapped(state, obs):
                return fn(state, obs[..., :-2])
            wrapped.takes_state = True
        else:
            def wrapped(obs):
                return fn(obs[..., :-2])
        return wrapped

    @staticmethod
    def _extend(obs, params: EnvParams):
        a = jnp.asarray(params.alpha, jnp.float32).reshape(())
        g = jnp.asarray(params.gamma, jnp.float32).reshape(())
        return jnp.concatenate(
            [obs, jnp.stack([a, g]).astype(obs.dtype)])

    def reset(self, key, params: EnvParams):
        state, obs = self.inner.reset(key, params)
        return state, self._extend(obs, params)

    def step(self, state, action, params: EnvParams):
        state, obs, reward, done, info = self.inner.step(
            state, action, params)
        return state, self._extend(obs, params), reward, done, info
