"""Bₖ protocol under the SSZ-like withholding attack space, on the DAG
tensor substrate.

Reference counterparts:
- protocol: simulator/protocols/bk.ml — k votes (PoW) per block, blocks
  signed by the leader (smallest vote hash), votes ordered by hash inside
  the block (bk.ml:110-132), quorum selection with replace-hash fast paths
  (bk.ml:233-279), `Block`/`Constant` reward schemes (bk.ml:151-176),
- attack space: simulator/protocols/bk_ssz.ml — 8 actions (Adopt|Override|
  Match|Wait x Prolong|Proceed, ssz_tools.ml:230-263), 8-field observation
  (bk_ssz.ml:21-48), release logic targeting (height, votes) of the public
  head (bk_ssz.ml:271-306), proposals appended with inclusive (Proceed) or
  exclusive (Prolong) vote filters (bk_ssz.ml:316-326),
- engine semantics: simulator/gym/engine.ml:97-273 (one env step per
  attacker interaction; `Append` events for the attacker's own proposals
  are separate interactions, as are defender proposals arriving right
  after the vote that completed their quorum).

TPU re-design: the PoW hash is a uniform float32 (only order matters);
quorum selection is masked top-k over the capacity-B child scan; chain
walks are bounded while loops. One env step processes exactly one
attacker event: a pending self-append, a defender proposal, or one mining
draw.

Documented deviations from the reference event-queue simulation:
- The defender cloud is one honest node (the engine's collapse). gamma
  has no effect here: Bₖ block preference is decided by the strict
  (height, votes, leader-hash) comparison (bk.ml:217-226), never by
  message arrival order; in the reference gamma only perturbs vote
  arrival order, which vanishes at cloud granularity.
- The `lead` observation uses the leader vote's miner id. The reference
  compares the (unsigned) vote's signature against the attacker id
  (bk_ssz.ml:240-249), which is vacuously false; we implement the
  documented intent ("attacker is truthful leader on leading public
  block").
- Attacker-view `visible_since` is the append time (the attacker hears
  defender messages instantly in the selfish-mining network,
  network.ml:85-95).
- Measured against the C++ multi-node oracle's BkAgent
  (tests/test_oracle_equivalence.py): honest play agrees within 0.01
  for alpha <= 1/3 (drifting to ~0.02 by alpha = 0.4).  `get-ahead`
  carries a STRUCTURAL deviation, characterized at (alpha=0.45,
  gamma=0.5): oracle - env = +0.0445 at k=1 and -0.0325 at k=4.
  Decomposition (rounds 3-4, tools/bk_gap_decompose.py): (a) episode
  truncation is NOT the cause — env revenue is invariant from 128 to
  512 steps (+-0.002); (b) the multi-node/delay component is NOT the
  cause at moderate gamma — the oracle's two_agents and selfish_mining
  topologies agree within 0.007 at gamma <= 0.5 (gamma=0.9 diverges
  ~+0.12: delay-shuffled vote arrival starts flipping defender
  preferences — documented out-of-model); (c) the k=1 gap IS
  gym-vs-simulator interaction granularity: the gym engine
  (engine.ml:97-273, which this env implements) gives the attacker a
  separate `Append` interaction right after its own proposal lands,
  while the simulator's event-driven agent re-acts only at the next
  event — grafting Append granularity onto the oracle
  ("get-ahead-appendint") closes 95% of the k=1 gap
  (test_bk_gym_granularity_parity pins the matched-granularity
  agreement at <=0.015); (d) the k=4 residual is DELIVERY-BATCH
  granularity (round-5 decomposition): the event-loop defender runs
  its handler per delivered vertex and can propose mid-release on a
  partial vote set, while this collapse applies a release atomically
  and attempts one defender proposal per delivery batch — NOT a
  multi-defender race (the single-defender oracle shows the same
  gap).  Grafting atomic delivery onto the oracle
  ("get-ahead-atomicrel") closes the k=4 gap to ~0.002
  (test_bk_k4_delivery_batch_parity, pinned <= 0.015); the ungrafted
  anchor keeps its characterized +-0.02 pin.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from cpr_tpu import obs as obslib
from cpr_tpu.core import dag as D
from cpr_tpu.envs.base import JaxEnv
from cpr_tpu.params import EnvParams

# kinds
BLOCK, VOTE = 0, 1

# events: Discrete [`Append; `ProofOfWork; `Network] (bk_ssz.ml:47)
EV_APPEND, EV_POW, EV_NETWORK = 0, 1, 2

# Action8 ranks (ssz_tools.ml:230-263)
(ADOPT_PROLONG, OVERRIDE_PROLONG, MATCH_PROLONG, WAIT_PROLONG,
 ADOPT_PROCEED, OVERRIDE_PROCEED, MATCH_PROCEED, WAIT_PROCEED) = range(8)


def obs_fields(k: int):
    return (
        obslib.Field("public_blocks", obslib.UINT, scale=1),
        obslib.Field("private_blocks", obslib.UINT, scale=1),
        obslib.Field("diff_blocks", obslib.INT, scale=1),
        obslib.Field("public_votes", obslib.UINT, scale=k),
        obslib.Field("private_votes_inclusive", obslib.UINT, scale=k),
        obslib.Field("private_votes_exclusive", obslib.UINT, scale=k),
        obslib.Field("lead", obslib.BOOL),
        obslib.Field("event", obslib.DISCRETE, n=3),
    )


@struct.dataclass
class State:
    dag: D.Dag
    public: jnp.ndarray  # defender-preferred block (simulated)
    private: jnp.ndarray  # attacker-preferred block
    event: jnp.ndarray  # EV_*
    pending_append: jnp.ndarray  # attacker proposal awaiting Append (-1)
    # episode bookkeeping (engine.ml:69-79)
    time: jnp.ndarray
    steps: jnp.ndarray
    n_activations: jnp.ndarray
    last_reward_attacker: jnp.ndarray
    last_reward_defender: jnp.ndarray
    last_progress: jnp.ndarray
    last_chain_time: jnp.ndarray
    last_sim_time: jnp.ndarray
    key: jax.Array


class BkSSZ(JaxEnv):
    n_actions = 8
    # a fresh reset populates at most genesis + one first interaction
    # (the _advance epilogue appends a vote or a defender proposal);
    # see JaxEnv.reset_dag_rows contract + logical-reset parity test
    reset_dag_rows = 2

    def __init__(self, k: int = 8, incentive_scheme: str = "constant",
                 unit_observation: bool = True, max_steps_hint: int = 256,
                 window: int | None = None,
                 anc_masks: bool | None = None):
        assert incentive_scheme in ("constant", "block")
        self.k = k
        self.incentive_scheme = incentive_scheme
        self.unit_observation = unit_observation
        # <= 2 appends per step (attacker proposal + PoW/defender
        # proposal); floored at k so quorum top_k always fits
        self.capacity = max(2 * max_steps_hint + 8, k + 8)
        # O(active-set) mode: a ring window of `window` slots replaces
        # the episode-length-proportional capacity — per-step cost
        # becomes O(window) like the reference's event loop only ever
        # touching the live fork (simulator.ml:421-533).  The window
        # must cover the fork plus its votes ((k+1) slots per withheld
        # block); a deeper fork overflows and ends the episode, exactly
        # like capacity exhaustion in full mode.
        if window is not None:
            self.capacity = max(window, k + 8)
        self.ring = window is not None
        # ancestry planes default ON only in ring mode: there they are
        # O(window^2) and replace every walk with a masked reduction; in
        # full mode they are O(episode_len^2) per env — a silent memory
        # blowup under vmap — so full mode defaults to the walk-based
        # queries (O(B) state).  Ring REQUIRES the planes: retire/
        # staleness logic reads masked common ancestors, and a walk in
        # a ring could traverse reclaimed slots.
        self.anc_masks = self.ring if anc_masks is None else anc_masks
        assert self.anc_masks or not self.ring, \
            "ring windows require anc_masks (walks could cross reclaimed slots)"
        self.max_parents = k + 1
        self.fields = obs_fields(k)
        self.observation_length = len(self.fields)
        self.low, self.high = obslib.low_high(self.fields, unit_observation)
        self.policies = self._make_policies()

    # -- protocol primitives (bk.ml) --------------------------------------

    def is_block(self, dag, idx_mask):
        return idx_mask & (dag.kind == BLOCK)

    def votes_on(self, dag, b, extra_mask=None):
        """Mask of votes confirming block b (bk.ml:100-103).  Votes
        attach to their block via parent slot 0, so the flat-precursor
        scan suffices (Dag.parent0)."""
        m = D.children0_mask(dag, b) & (dag.kind == VOTE)
        if extra_mask is not None:
            m = m & extra_mask
        return m

    def leader_hash(self, dag, b):
        """Hash of the block's leader vote; genesis has none -> +inf ==
        max_pow (bk.ml:205-215).  Cached in Dag.auxf at append time —
        re-gathering it through the padded parents matrix cost
        ~100 ms/step at 16k envs on chip."""
        return dag.auxf[b]

    def leader_hash_all(self, dag):
        """(B,) leader hash per block slot (Dag.auxf cache)."""
        return dag.auxf

    def row_leader_hash(self, dag, row):
        """Leader hash of a proposal row before it is appended: the
        hash of its lead vote (row slot 1; votes are sorted ascending
        by hash, bk.ml:110-132)."""
        v0 = row[1]
        return jnp.where(v0 >= 0, dag.pow_hash[jnp.maximum(v0, 0)], D.NO_POW)

    def cmp_blocks(self, dag, x, y, vote_filter_mask):
        """compare_blocks (bk.ml:217-226): height, then filtered confirming
        votes, then smaller leader hash, then earlier defender visibility.
        Returns >0 iff x is strictly preferred over y."""
        nx = self.votes_on(dag, x, vote_filter_mask).sum()
        ny = self.votes_on(dag, y, vote_filter_mask).sum()
        key_x = (dag.height[x], nx, -self.leader_hash(dag, x), -dag.vis_d_since[x])
        key_y = (dag.height[y], ny, -self.leader_hash(dag, y), -dag.vis_d_since[y])

        def lex(a, b):
            gt = jnp.bool_(False)
            eq = jnp.bool_(True)
            for xa, xb in zip(a, b):
                gt = gt | (eq & (xa > xb))
                eq = eq & (xa == xb)
            return gt

        return jnp.where(x == y, False, lex(key_x, key_y))

    def update_head(self, dag, old, candidate, vote_filter_mask):
        """bk.ml:228-231: switch only on strict improvement."""
        better = self.cmp_blocks(dag, candidate, old, vote_filter_mask)
        return jnp.where(better, candidate, old)

    def quorum(self, dag, b, voter, vote_filter_mask, view_mask):
        """bk.ml:233-279. Returns (found, parents_row) for a proposal on b
        by `voter` — quorum of k votes, voter's smallest hash leading.
        `view_mask` is the voter's visibility (the per-node view of
        dag.ml:39-45): both the candidate votes and the replace-hash fast
        path only see vertices in the view."""
        k = self.k
        votes = self.votes_on(dag, b, vote_filter_mask & view_mask)
        mine = votes & (dag.aux == voter)
        theirs = votes & (dag.aux != voter)
        my_hash = jnp.where(mine, dag.pow_hash, jnp.inf).min()
        # replace_hash: best leader among visible child blocks of b
        child_blocks = D.children0_mask(dag, b) & (dag.kind == BLOCK) & view_mask
        replace_hash = jnp.where(
            child_blocks, self.leader_hash_all(dag), jnp.inf).min()
        nvotes = votes.sum()
        nmine = mine.sum()

        # case 1: k of my own votes, smallest hashes first
        idx_mine, valid_mine = D.top_k_by(dag.pow_hash, mine, k)
        # case 2: all of mine (nmine < k here) + their votes with hash >
        # my_hash (keeps the voter leading), earliest seen first
        theirs_ok = theirs & (dag.pow_hash > my_hash)
        # attacker view visibility time == born time (see module docstring)
        seen = jnp.where(voter == D.ATTACKER, dag.born_at, dag.vis_d_since)
        idx_theirs, valid_theirs = D.top_k_by(seen, theirs_ok, k)
        n_needed = k - nmine
        take_theirs = jnp.arange(k) < n_needed
        mine_sel = D.mask_of(idx_mine, valid_mine, dag.capacity)
        sel_mask = mine_sel | D.mask_of(
            idx_theirs, valid_theirs & take_theirs, dag.capacity)

        case1 = nmine >= k
        quorum_mask = jnp.where(case1, mine_sel, sel_mask)

        enough_theirs = theirs_ok.sum() >= n_needed
        found = (replace_hash > my_hash) & (nvotes >= k) & (case1 | enough_theirs)

        # parent row: [b, votes sorted ascending by hash] (bk.ml:110-132)
        vidx, vvalid = D.top_k_by(dag.pow_hash, quorum_mask, k)
        row = jnp.concatenate([jnp.array([b], jnp.int32),
                               jnp.where(vvalid, vidx, D.NONE)])
        return found, row

    def reward_of_block(self, dag, parents_row, signer):
        """Per-block coinbase at append time (bk.ml:151-176)."""
        votes = parents_row[1:]
        valid = votes >= 0
        if self.incentive_scheme == "constant":
            # NOTE: keep the k-index gather — a (k, B) one-hot mask
            # form was tried and measured 22x SLOWER end-to-end on chip
            # (XLA pathology not chased; small-k gathers are fine)
            ids = dag.aux[jnp.clip(votes, 0)]
            atk = (valid & (ids == D.ATTACKER)).sum().astype(jnp.float32)
            dfn = (valid & (ids == D.DEFENDER)).sum().astype(jnp.float32)
        else:  # block: leader takes k
            atk = jnp.where(signer == D.ATTACKER, float(self.k), 0.0)
            dfn = jnp.where(signer == D.DEFENDER, float(self.k), 0.0)
        return atk, dfn

    def append_proposal(self, dag, b, voter, vote_filter_mask, view_mask, time):
        """Append a quorum proposal on b if possible; returns
        (dag, idx_or_-1).  Row-level conditional append (D.append_if) —
        the old append-then-rollback select copied the whole DAG twice
        per call and dominated the step cost on chip."""
        found, row = self.quorum(dag, b, voter, vote_filter_mask, view_mask)
        atk, dfn = self.reward_of_block(dag, row, voter)
        height = dag.height[b] + 1
        return D.append_if(
            dag, found, row, kind=BLOCK, height=height, aux=0,
            signer=voter, miner=voter,
            vis_a=True, vis_d=(voter == D.DEFENDER),
            time=time, reward_atk=atk, reward_def=dfn,
            progress=(height * self.k).astype(jnp.float32),
            auxf=self.row_leader_hash(dag, row),
        )

    # -- env API ----------------------------------------------------------

    def reset(self, key: jax.Array, params: EnvParams):
        # anc_masks: the chain/closure rows replace the three per-step
        # while-loop walks (common ancestor, height target, release
        # chain) with masked reductions; gated because the planes are
        # quadratic in capacity (see __init__)
        dag = D.empty(self.capacity, self.max_parents,
                      ring=self.ring, anc_masks=self.anc_masks)
        # genesis block (bk.ml:48); no leader vote -> +inf leader hash
        dag, root = D.append(
            dag, jnp.full((self.max_parents,), D.NONE, jnp.int32),
            kind=BLOCK, height=0, miner=D.NONE, vis_a=True, vis_d=True,
            time=0.0, progress=0.0, auxf=D.NO_POW)
        z = jnp.int32(0)
        f = jnp.float32(0.0)
        state = State(
            dag=dag, public=root, private=root,
            event=jnp.int32(EV_POW), pending_append=D.NONE,
            time=f, steps=z, n_activations=z,
            last_reward_attacker=f, last_reward_defender=f,
            last_progress=f, last_chain_time=f, last_sim_time=f,
            key=key,
        )
        state = self._advance(state, params)
        return state, self.observe(state)

    def last_block(self, dag, x):
        """bk.ml:78-87: the block a vertex belongs to."""
        return jnp.where(dag.kind[x] == BLOCK, x, dag.parent0[x])

    def common_ancestor(self, dag, a, b):
        """Preference-fork common ancestor: one chain-row intersection
        with ancestry planes, else the height-synchronized walk (the
        pre-plane path, reclaim-safe only in full mode)."""
        if dag.has_masks:
            return D.common_ancestor_masked(dag, a, b)
        return D.common_ancestor_by_height(dag, a, b)

    def _advance(self, state: State, params: EnvParams) -> State:
        """Produce the next attacker interaction: pending self-append,
        defender proposal, or one mining draw (engine.ml:108-121
        collapsed).

        The three cases are merged into ONE conditional row append
        instead of nested lax.cond branches: under vmap a cond is a
        select over both branch results, and selecting a whole State
        (DAG included) copies every array per step — the dominant cost
        on chip.  All selects here are scalar- or row-level; the RNG key
        advances every step (iid splits — the same process
        distribution; the pre-merge code consumed a split only on
        mining steps)."""
        dag = state.dag
        has_pending = state.pending_append >= 0

        # defender proposal on its preferred block (honest handler
        # bk.ml:297-310 via quorum over defender-visible votes)
        found, prow = self.quorum(dag, state.public, jnp.int32(D.DEFENDER),
                                  dag.vis_d, dag.vis_d)
        do_prop = ~has_pending & found
        do_mine = ~has_pending & ~found

        # mining draw (drawn always, consumed when do_mine)
        key, k_dt, k_mine, k_hash = jax.random.split(state.key, 4)
        dt = jax.random.exponential(k_dt) * params.activation_delay
        time = jnp.where(do_mine, state.time + dt, state.time)
        attacker = jax.random.uniform(k_mine) < params.alpha
        powh = jax.random.uniform(k_hash)
        target = jnp.where(attacker, state.private, state.public)
        vrow = jnp.full((self.max_parents,), D.NONE, jnp.int32
                        ).at[0].set(target)
        miner_v = jnp.where(attacker, D.ATTACKER, D.DEFENDER
                            ).astype(jnp.int32)

        h_prop = dag.height[state.public] + 1
        h_tgt = dag.height[target]
        atk, dfn = self.reward_of_block(dag, prow, jnp.int32(D.DEFENDER))
        dag, idx = D.append_if(
            dag, do_prop | do_mine,
            jnp.where(do_prop, prow, vrow),
            kind=jnp.where(do_prop, BLOCK, VOTE),
            height=jnp.where(do_prop, h_prop, h_tgt),
            aux=jnp.where(do_prop, 0, miner_v),
            pow_hash=jnp.where(do_prop, D.NO_POW, powh),
            signer=jnp.where(do_prop, D.DEFENDER, D.NONE),
            miner=jnp.where(do_prop, D.DEFENDER, miner_v),
            vis_a=True,
            # defender's proposal is public; a mined vote starts withheld
            # iff the attacker mined it.  (The defender's own vote lands
            # on its preferred block, so its preference is unchanged;
            # attacker-release preference flips happen at delivery time
            # in _apply.)
            vis_d=jnp.where(do_prop, True, ~attacker),
            time=time,
            reward_atk=jnp.where(do_prop, atk, 0.0),
            reward_def=jnp.where(do_prop, dfn, 0.0),
            progress=jnp.where(do_prop, h_prop * self.k,
                               h_tgt * self.k + 1).astype(jnp.float32),
            auxf=jnp.where(do_prop, self.row_leader_hash(dag, prow),
                           D.NO_POW),
        )
        public = jnp.where(
            do_prop,
            self.update_head(dag, state.public, jnp.maximum(idx, 0),
                             dag.vis_d),
            state.public)
        event = jnp.where(
            has_pending, EV_APPEND,
            jnp.where(do_prop, EV_NETWORK,
                      jnp.where(attacker, EV_POW, EV_NETWORK))
        ).astype(jnp.int32)
        return state.replace(
            dag=dag, public=public,
            private=jnp.where(has_pending, state.pending_append,
                              state.private),
            event=event, pending_append=D.NONE, time=time,
            n_activations=state.n_activations + do_mine.astype(jnp.int32),
            key=key,
        )

    def observe(self, state: State):
        """bk_ssz.ml:225-263."""
        dag = state.dag
        ca = jnp.maximum(
            self.common_ancestor(dag, state.public, state.private), 0)
        pub_votes = self.votes_on(dag, state.public, dag.vis_d).sum()
        priv_inc = self.votes_on(dag, state.private).sum()
        priv_exc = self.votes_on(dag, state.private,
                                 dag.miner == D.ATTACKER).sum()
        votes_pub = self.votes_on(dag, state.public)
        any_votes = votes_pub.any()
        leader = jnp.argmin(jnp.where(votes_pub, dag.pow_hash, jnp.inf))
        lead = any_votes & (dag.aux[leader] == D.ATTACKER)
        return obslib.encode(
            self.fields,
            (
                dag.height[state.public] - dag.height[ca],
                dag.height[state.private] - dag.height[ca],
                dag.height[state.private] - dag.height[state.public],
                pub_votes,
                priv_inc,
                priv_exc,
                lead,
                state.event,
            ),
            self.unit_observation,
        )

    def _apply(self, state: State, action) -> State:
        """bk_ssz.ml:265-331."""
        dag = state.dag
        k = self.k
        is_adopt = (action == ADOPT_PROLONG) | (action == ADOPT_PROCEED)
        is_override = (action == OVERRIDE_PROLONG) | (action == OVERRIDE_PROCEED)
        is_match = (action == MATCH_PROLONG) | (action == MATCH_PROCEED)
        is_release = is_override | is_match
        proceed = action >= 4  # Proceed variants: inclusive vote filter

        # release targeting (bk_ssz.ml:271-283)
        h_pub = dag.height[state.public]
        nv_pub = self.votes_on(dag, state.public, dag.vis_d).sum()
        tgt_h = jnp.where(is_override & (nv_pub >= k), h_pub + 1, h_pub)
        tgt_v = jnp.where(is_match, nv_pub,
                          jnp.where(nv_pub >= k, 0, nv_pub + 1))

        # private chain block at the target height: one masked reduction
        # over the ancestry row (block chains ride parent slot 0, so the
        # chain plane holds exactly the private block chain); full mode
        # walks the precursor chain instead
        if dag.has_masks:
            blk = D.chain_first_at_most(dag, state.private, dag.height,
                                        tgt_h)
        else:
            blk = D.block_at_height(dag, state.private, tgt_h)
        blk = jnp.maximum(blk, 0)
        # if quorum-size votes requested, prefer an existing proposal
        # child; the reference takes the FIRST child block in insertion
        # order, not the best by leader hash (bk_ssz.ml:294-300) —
        # insertion order is the age key (slot order wraps in a ring)
        child_blocks = D.children0_mask(dag, blk) & (dag.kind == BLOCK)
        has_prop = child_blocks.any()
        first_prop = jnp.maximum(D.first_by_age(dag, child_blocks), 0)
        use_prop = (tgt_v >= k) & has_prop
        rel_block = jnp.where(use_prop, first_prop, blk)
        rel_votes_n = jnp.where(use_prop, 0, tgt_v)
        # release earliest-seen votes on the released block.  Selection
        # width 16 keeps top_k on the iterative (sort-free) path; a
        # request beyond it falls back to releasing every vote on the
        # block (over-release by a few votes in that tail), exactly like
        # the existing not_enough fallback — requests that deep need
        # nv_pub > 16 on one block, beyond the reference's own policy
        # reach
        votes = self.votes_on(dag, rel_block)
        vidx, vvalid = D.top_k_by(dag.born_at, votes, self.capacity_topk)
        take = jnp.arange(self.capacity_topk) < rel_votes_n
        release_all = (votes.sum() < rel_votes_n) | \
            (rel_votes_n > self.capacity_topk)
        vote_mask = D.mask_of(vidx, vvalid & take, self.capacity)
        vote_mask = jnp.where(release_all, votes, vote_mask)

        # recursive share via the closure row (was a while-loop chain
        # walk); the chosen votes sit directly on the released block's
        # chain, so a flat release covers their ancestry.  Full mode
        # keeps the chain walk (bounded by the withheld depth).
        if dag.has_masks:
            released = D.release_masked(dag, rel_block, state.time)
        else:
            released = D.release_chain(dag, rel_block, state.time)
        released = D.release(released, vote_mask, state.time)
        dag = D.select_vis(is_release, released, dag)

        # deliver to the simulated defender (bk_ssz.ml:196-205)
        public = jnp.where(
            is_release,
            self.update_head(dag, state.public,
                             self.last_block(dag, rel_block), dag.vis_d),
            state.public)
        private = jnp.where(is_adopt, public, state.private)

        # attacker proposal (bk_ssz.ml:316-326)
        vote_filter = jnp.where(proceed, dag.exists(),
                                dag.miner == D.ATTACKER)
        dag, prop = self.append_proposal(
            dag, private, jnp.int32(D.ATTACKER), vote_filter, dag.vis_a,
            state.time)

        return state.replace(dag=dag, public=public, private=private,
                             pending_append=prop)

    @property
    def capacity_topk(self):
        # capped at 16 so the release-selection top_k stays on the
        # iterative extraction path (lax.top_k beyond that lowers to a
        # full capacity-wide sort, ~0.6 ms/step at 4096 envs); deeper
        # requests use the release-everything fallback in _apply
        return min(self.capacity, 2 * self.k + 8, 16)

    def step(self, state: State, action, params: EnvParams):
        state = self._apply(state, action)
        state = self._advance(state, params)
        state = state.replace(steps=state.steps + 1)
        dag = state.dag

        if self.ring:
            # retire everything below the preference fork: every later
            # read starts at public/private/pending (all descendants of
            # their common ancestor) or at votes hanging on the fork
            # (appended after the CA, so gid-above it)
            ca = D.common_ancestor_masked(dag, state.public, state.private)
            dag = D.retire_below(dag, dag.gid[jnp.maximum(ca, 0)])
            state = state.replace(dag=dag)

        # winner over [attacker pref, defender pref]; ties attacker first
        # (engine.ml:196-206; referee compare: height then all votes,
        # bk.ml:134-147)
        n_pub = self.votes_on(dag, state.public).sum()
        n_priv = self.votes_on(dag, state.private).sum()
        pub_better = (dag.height[state.public] > dag.height[state.private]) | (
            (dag.height[state.public] == dag.height[state.private])
            & (n_pub > n_priv))
        head = jnp.where(pub_better, state.public, state.private)

        return self.finish_step(
            state, params,
            reward_attacker=dag.cum_atk[head],
            reward_defender=dag.cum_def[head],
            progress=(dag.height[head] * self.k).astype(jnp.float32),
            chain_time=dag.born_at[head],
            extra_done=dag.overflow,
        )

    # -- policies (bk_ssz.ml:346-404) --------------------------------------

    def _make_policies(self):
        k = self.k

        def wrap(fn):
            def wrapped(obs):
                (pub_b, priv_b, _, pub_v, priv_vi, priv_ve, lead, ev
                 ) = self.decode_obs(obs)
                return fn(pub_b, priv_b, pub_v, priv_vi, priv_ve, lead, ev)
            return wrapped

        def honest(pub_b, priv_b, pub_v, priv_vi, priv_ve, lead, ev):
            return jnp.where(pub_b > priv_b, ADOPT_PROCEED, OVERRIDE_PROCEED)

        def get_ahead(pub_b, priv_b, pub_v, priv_vi, priv_ve, lead, ev):
            return jnp.where(
                pub_b > priv_b, ADOPT_PROCEED,
                jnp.where(pub_b < priv_b, OVERRIDE_PROCEED, WAIT_PROCEED))

        def minor_delay(pub_b, priv_b, pub_v, priv_vi, priv_ve, lead, ev):
            return jnp.where(
                pub_b > priv_b, ADOPT_PROCEED,
                jnp.where(pub_b == 0, WAIT_PROCEED, OVERRIDE_PROCEED))

        def avoid_loss(pub_b, priv_b, pub_v, priv_vi, priv_ve, lead, ev):
            # avoid_loss_alt (bk_ssz.ml:389-400)
            hp = pub_b * k + pub_v
            ap = priv_b * k + priv_vi
            return jnp.where(
                pub_b == 0, WAIT_PROCEED,
                jnp.where(
                    (pub_b == 1) & (hp == ap), MATCH_PROCEED,
                    jnp.where(
                        hp > ap, ADOPT_PROCEED,
                        jnp.where(
                            hp == ap - 1, OVERRIDE_PROCEED,
                            jnp.where(pub_b < priv_b - 10,
                                      OVERRIDE_PROCEED, WAIT_PROCEED)))))

        return {
            "honest": wrap(honest),
            "get-ahead": wrap(get_ahead),
            "minor-delay": wrap(minor_delay),
            "avoid-loss": wrap(avoid_loss),
        }
