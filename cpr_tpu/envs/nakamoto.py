"""Nakamoto consensus under the SSZ'16 selfish-mining attack space — as a
closed-form, fully jittable JAX environment.

Reference counterparts:
- protocol: simulator/protocols/nakamoto.ml (longest chain, reward 1/block)
- attack space: simulator/protocols/nakamoto_ssz.ml (Observation
  {public_blocks, private_blocks, diff_blocks, event}, Actions
  Adopt|Override|Match|Wait, built-in policies honest/simple/
  eyal-sirer-2014/sapirshtein-2016-sm1)
- gym engine semantics: simulator/gym/engine.ml:97-273 (selfish-mining
  network with ~zero propagation delay, defender cloud, gamma emulated by
  uniform attacker message delays, network.ml:61-105)
- the same collapse to (a, h, fork) appears in the reference's Rust gym
  (gym/rust/src/fc16.rs:28-139).

TPU re-design: because `Engine.of_module` reduces the simulation to a
2-party attacker-vs-defender-cloud game whose only decision points are
attacker interactions, one env step == one action + one Bernoulli(alpha)
mining draw (+ one Bernoulli(gamma) communication draw when a match race is
live). State is a handful of scalars; `vmap` packs millions of episodes
into one XLA kernel. Rewards/progress on the common chain are accumulated
incrementally, mirroring the reference's accumulation along `precursor`
(simulator/lib/simulator.ml:377-388); the step reward is the delta of the
attacker's accumulated reward at the winner head (engine.ml:196-249).

Known deviations from the reference's event-queue semantics (documented):
- `chain_time` tracks the mining time of the current chain tips, not every
  block's first-visibility timestamp (info metric only).
- Adopt while a match race is live drops the race (the reference's split
  defenders could still extend the attacker's release; its own fc16 model
  makes the same simplification, gym/rust/src/fc16.rs:132-138).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from flax import struct

from cpr_tpu import obs as obslib
from cpr_tpu.envs.base import JaxEnv
from cpr_tpu.params import EnvParams

# action encoding mirrors Variants.to_rank order (nakamoto_ssz.ml:116-154)
ADOPT, OVERRIDE, MATCH, WAIT = 0, 1, 2, 3

# event encoding mirrors Discrete [`ProofOfWork; `Network] (nakamoto_ssz.ml:38)
EV_POW, EV_NETWORK = 0, 1

OBS_FIELDS = (
    obslib.Field("public_blocks", obslib.UINT, scale=1),
    obslib.Field("private_blocks", obslib.UINT, scale=1),
    obslib.Field("diff_blocks", obslib.INT, scale=1),
    obslib.Field("event", obslib.DISCRETE, n=2),
)


@struct.dataclass
class State:
    # fork state relative to the common ancestor
    a: jnp.ndarray  # private (attacker) blocks after common ancestor
    h: jnp.ndarray  # public (defender) blocks after common ancestor
    event: jnp.ndarray  # EV_POW | EV_NETWORK, what triggered this interaction
    match_h: jnp.ndarray  # height of live match race (-1: none)
    # common-chain accumulators (precursor-accumulation, simulator.ml:377-388)
    ca_atk: jnp.ndarray
    ca_def: jnp.ndarray
    ca_progress: jnp.ndarray
    # clocks
    time: jnp.ndarray
    t_priv: jnp.ndarray  # mining time of private tip
    t_pub: jnp.ndarray  # mining time of public tip
    # episode bookkeeping (engine.ml:69-79)
    steps: jnp.ndarray
    n_activations: jnp.ndarray
    last_reward_attacker: jnp.ndarray
    last_reward_defender: jnp.ndarray
    last_progress: jnp.ndarray
    last_chain_time: jnp.ndarray
    last_sim_time: jnp.ndarray
    key: jax.Array


class NakamotoSSZ(JaxEnv):
    """cpr-nakamoto SSZ attack env, one step per attacker interaction."""

    n_actions = 4
    obs_fields = OBS_FIELDS
    observation_length = len(OBS_FIELDS)

    def __init__(self, unit_observation: bool = True, strict_match: bool = True):
        # strict_match=True reproduces the reference event-queue network:
        # a Match only splits the defenders when applied at the interaction
        # where the competing defender block just arrived (the propagation
        # race window, network.ml:61-105). strict_match=False reproduces the
        # SSZ'16 MDP convention (gym/rust/src/fc16.rs:104-115) where a match
        # race stays live across Wait actions.
        self.unit_observation = unit_observation
        self.strict_match = strict_match
        self.fields = OBS_FIELDS
        self.low, self.high = obslib.low_high(OBS_FIELDS, unit_observation)
        # built once: policy identity is the jit cache key for rollout
        self.policies = self._make_policies()

    # -- observation ------------------------------------------------------

    def observe(self, state: State):
        """nakamoto_ssz.ml:220-230."""
        return obslib.encode(
            OBS_FIELDS,
            (state.h, state.a, state.a - state.h, state.event),
            self.unit_observation,
        )


    # -- dynamics ---------------------------------------------------------

    def _mine(self, state: State, params: EnvParams) -> State:
        """One activation: Bernoulli(alpha) miner choice plus the gamma
        communication race (engine.ml:108-121 fast-forward collapsed to one
        draw; simulator.ml:465-472 PoW clock)."""
        key, k_dt, k_mine, k_gamma = jax.random.split(state.key, 4)
        dt = jax.random.exponential(k_dt) * params.activation_delay
        time = state.time + dt
        attacker_mines = jax.random.uniform(k_mine) < params.alpha
        gamma_hit = jax.random.uniform(k_gamma) < params.gamma

        # attacker branch: extend private chain
        a_att = state.a + 1

        # defender branch: extend public chain; if a match race is live at
        # the public tip, a gamma share of defender compute mines on the
        # attacker's released block instead (network.ml:61-105)
        on_split = (state.match_h >= 0) & (state.match_h == state.h)
        def_on_attacker = on_split & gamma_hit
        # gamma success: common ancestor jumps to the released block; the
        # new defender block sits on top of h released attacker blocks
        ca_atk_d = state.ca_atk + jnp.where(def_on_attacker, state.h, 0).astype(jnp.float32)
        ca_prog_d = state.ca_progress + jnp.where(def_on_attacker, state.h, 0).astype(jnp.float32)
        a_def = jnp.where(def_on_attacker, state.a - state.h, state.a)
        h_def = jnp.where(def_on_attacker, 1, state.h + 1)

        return state.replace(
            a=jnp.where(attacker_mines, a_att, a_def),
            h=jnp.where(attacker_mines, state.h, h_def),
            ca_atk=jnp.where(attacker_mines, state.ca_atk, ca_atk_d),
            ca_progress=jnp.where(attacker_mines, state.ca_progress, ca_prog_d),
            match_h=jnp.where(attacker_mines, state.match_h, -1),
            event=jnp.where(attacker_mines, EV_POW, EV_NETWORK),
            time=time,
            t_priv=jnp.where(attacker_mines, time, state.t_priv),
            t_pub=jnp.where(attacker_mines, state.t_pub, time),
            n_activations=state.n_activations + 1,
            key=key,
        )

    def reset(self, key: jax.Array, params: EnvParams):
        z = jnp.int32(0)
        f = jnp.float32(0.0)
        state = State(
            a=z, h=z, event=jnp.int32(EV_POW), match_h=jnp.int32(-1),
            ca_atk=f, ca_def=f, ca_progress=f,
            time=f, t_priv=f, t_pub=f,
            steps=z, n_activations=z,
            last_reward_attacker=f, last_reward_defender=f,
            last_progress=f, last_chain_time=f, last_sim_time=f,
            key=key,
        )
        # the reference fast-forwards to the first attacker interaction at
        # env construction (engine.ml:137-141): one mining draw
        state = self._mine(state, params)
        return state, self.observe(state)

    def _apply(self, state: State, action) -> State:
        """Apply the agent action (nakamoto_ssz.ml:232-259)."""
        a, h = state.a, state.h

        # Adopt: private <- public; h defender blocks join the common chain
        adopt = action == ADOPT
        # Override: release block at height h+1; effective iff a > h
        # (otherwise only the private head is released, which the public
        # ignores: update_head requires strictly larger height,
        # nakamoto.ml:85-89)
        override_eff = (action == OVERRIDE) & (a > h)
        # Match: release block at height h; forms a live race iff the
        # attacker has a block at that height and (strict mode) the
        # competing defender block just arrived
        match_eff = (action == MATCH) & (a >= h) & (h > 0)
        if self.strict_match:
            match_eff = match_eff & (state.event == EV_NETWORK)

        ca_atk = state.ca_atk + jnp.where(override_eff, h + 1, 0).astype(jnp.float32)
        ca_def = state.ca_def + jnp.where(adopt, h, 0).astype(jnp.float32)
        ca_progress = (
            state.ca_progress
            + jnp.where(adopt, h, 0).astype(jnp.float32)
            + jnp.where(override_eff, h + 1, 0).astype(jnp.float32)
        )
        new_a = jnp.where(adopt, 0, jnp.where(override_eff, a - (h + 1), a))
        new_h = jnp.where(adopt | override_eff, 0, h)
        match_h = jnp.where(
            match_eff, h, jnp.where(adopt | override_eff, -1, state.match_h)
        )
        t_priv = jnp.where(adopt, state.t_pub, state.t_priv)
        # after an effective override the public tip is the released
        # attacker block (approximated by the private tip's mining time)
        t_pub = jnp.where(override_eff, state.t_priv, state.t_pub)
        return state.replace(
            a=new_a, h=new_h, ca_atk=ca_atk, ca_def=ca_def,
            ca_progress=ca_progress, match_h=match_h,
            t_priv=t_priv, t_pub=t_pub,
        )

    def step(self, state: State, action, params: EnvParams):
        """engine.ml:176-249: apply action, fast-forward to the next
        attacker interaction, compute winner head, rewards, termination."""
        state = self._apply(state, action)
        state = self._mine(state, params)
        state = state.replace(steps=state.steps + 1)

        # winner over node preferences; ties go to the attacker because it
        # is node 0 in the fold (engine.ml:196-206, nakamoto.ml:43-48)
        head_private = state.a >= state.h
        reward_attacker = state.ca_atk + jnp.where(head_private, state.a, 0).astype(jnp.float32)
        reward_defender = state.ca_def + jnp.where(head_private, 0, state.h).astype(jnp.float32)
        progress = state.ca_progress + jnp.maximum(state.a, state.h).astype(jnp.float32)
        chain_time = jnp.where(head_private, state.t_priv, state.t_pub)

        return self.finish_step(
            state, params,
            reward_attacker=reward_attacker,
            reward_defender=reward_defender,
            progress=progress,
            chain_time=chain_time,
        )

    # -- built-in policies (nakamoto_ssz.ml:274-350) ----------------------

    def _policy(self, fn):
        def wrapped(obs):
            h, a, _, event = self.decode_obs(obs)
            return fn(a, h, event)
        return wrapped

    def _make_policies(self):
        def honest(a, h, event):
            return jnp.where(a > h, OVERRIDE, jnp.where(a < h, ADOPT, WAIT))

        def simple(a, h, event):
            return jnp.where(h > 0, jnp.where(a < h, ADOPT, OVERRIDE), WAIT)

        def es_2014(a, h, event):
            # Eyal & Sirer 2014 (nakamoto_ssz.ml:294-321)
            return jnp.where(
                a < h, ADOPT,
                jnp.where(
                    (h == 0) & (a == 1), WAIT,
                    jnp.where(
                        (h == 1) & (a == 1), MATCH,
                        jnp.where(
                            (h == 1) & (a == 2), OVERRIDE,
                            jnp.where(
                                h > 0,
                                jnp.where(a - h == 1, OVERRIDE, MATCH),
                                WAIT,
                            ),
                        ),
                    ),
                ),
            )

        def sm1(a, h, event):
            # Sapirshtein et al. 2016, SM1 (nakamoto_ssz.ml:325-339)
            return jnp.where(
                h > a, ADOPT,
                jnp.where(
                    (h == 1) & (a == 1), MATCH,
                    jnp.where((h == a - 1) & (h >= 1), OVERRIDE, WAIT),
                ),
            )

        return {
            "honest": self._policy(honest),
            "simple": self._policy(simple),
            "eyal-sirer-2014": self._policy(es_2014),
            "sapirshtein-2016-sm1": self._policy(sm1),
        }
