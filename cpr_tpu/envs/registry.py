"""Keyed environment registry.

Reference counterpart: the protocol/attack-space registry and string keys
(simulator/protocols/cpr_protocols.ml:11-180,786-903) plus the gym env ids
registered in gym/ocaml/cpr_gym/envs.py:166-192.
"""

from __future__ import annotations

from typing import Callable

_REGISTRY: dict[str, Callable] = {}


def register(key: str, factory: Callable):
    _ensure_builtin()
    if key in _REGISTRY:
        raise ValueError(f"duplicate env key: {key}")
    _REGISTRY[key] = factory


def get(key: str, **kwargs):
    """Instantiate the env registered under `key`."""
    _ensure_builtin()
    try:
        factory = _REGISTRY[key]
    except KeyError:
        raise KeyError(
            f"unknown env '{key}'; choose from {sorted(_REGISTRY)}"
        ) from None
    return factory(**kwargs)


def keys():
    _ensure_builtin()
    return sorted(_REGISTRY)


_BUILTIN_LOADED = False


def _ensure_builtin():
    global _BUILTIN_LOADED
    if _BUILTIN_LOADED:
        return
    from cpr_tpu.envs.bk import BkSSZ
    from cpr_tpu.envs.ethereum import EthereumSSZ
    from cpr_tpu.envs.nakamoto import NakamotoSSZ
    from cpr_tpu.envs.tailstorm import TailstormSSZ

    _BUILTIN_LOADED = True
    for key, factory in [
        ("nakamoto", NakamotoSSZ),
        ("bk", BkSSZ),
        ("ethereum", EthereumSSZ),
        ("ethereum-whitepaper",
         lambda **kw: EthereumSSZ("whitepaper", **kw)),
        ("ethereum-byzantium",
         lambda **kw: EthereumSSZ("byzantium", **kw)),
        ("tailstorm", TailstormSSZ),
    ]:
        if key not in _REGISTRY:
            _REGISTRY[key] = factory
