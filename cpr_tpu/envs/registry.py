"""Keyed environment registry + protocol-key parser.

Reference counterpart: the protocol/attack-space registry and string keys
(simulator/protocols/cpr_protocols.ml:11-180) with the `of_key` grammar
(cpr_protocols.ml:786-903) that parses keys like `nakamoto`,
`bk-8-constant`, `tailstorm-8-discount-heuristic`; plus the gym env ids
registered in gym/ocaml/cpr_gym/envs.py:166-192.
"""

from __future__ import annotations

from typing import Callable

_REGISTRY: dict[str, Callable] = {}


def register(key: str, factory: Callable):
    _ensure_builtin()
    if key in _REGISTRY:
        raise ValueError(f"duplicate env key: {key}")
    _REGISTRY[key] = factory
    # a full key may already have been served via the parse_key family
    # fallback; drop those memo entries so the new factory wins
    for mk in [mk for mk in _ENV_MEMO if mk[0] == key]:
        del _ENV_MEMO[mk]


_ENV_MEMO: dict = {}

# Collection-style one-line info strings (simulator/lib/collection.ml
# keyed registries carry (key, info, object); cpr_protocols.ml attaches
# a describe_* string to every constructor)
_INFO = {
    "nakamoto": "Nakamoto consensus / longest chain",
    "bk": "Bk: k parallel PoW votes per block, leader-signed",
    "ethereum": "Ethereum PoW with uncles (whitepaper/byzantium presets)",
    "ethereum-whitepaper": "Ethereum PoW, whitepaper uncle rules",
    "ethereum-byzantium": "Ethereum PoW, byzantium uncle rules",
    "spar": "Simple parallel PoW (k PoW per block, k-1 votes)",
    "stree": "Parallel PoW with tree-structured votes",
    "sdag": "Parallel PoW with DAG-structured votes (k >= 2)",
    "tailstorm": "Tailstorm: summaries over depth-labelled vote trees",
    "tailstormjune": "Tailstorm, June'22 variant (W&B run 257 repro)",
}


def describe(key: str | None = None):
    """Info string(s) for registered env families; `describe()` lists
    everything (the Collection iteration pattern)."""
    _ensure_builtin()
    if key is not None:
        family = key if key in _REGISTRY else parse_key(key)[0]
        return _INFO.get(family, "")
    return {k: _INFO.get(k, "") for k in sorted(_REGISTRY)}


def get(key: str, **kwargs):
    """Instantiate the env for `key` — either a registered family name
    with explicit kwargs, or a full protocol key parsed by `parse_key`.

    kwargs forward to the env constructor, so the performance knobs
    every DAG env shares flow through here: `window=<int>` turns on the
    O(active-set) ring mode and `anc_masks=<bool>` overrides the
    ancestry-plane default (ON in ring mode, OFF at full capacity).

    Identical (key, kwargs) return the SAME env object: envs are
    immutable config holders, and jit caches key on the env instance
    (rollout/step have static self), so sharing instances shares
    compiled kernels across callers — e.g. across tests in one process.
    Do NOT mutate a returned env (set attributes, wrap in place): every
    other caller of the same key sees the change — including callers
    that fetched the instance BEFORE any `clear_memo()`.  To customize
    an env, construct it directly from its class (or wrap it in a new
    object); clear_memo() only stops FUTURE get() calls from sharing."""
    _ensure_builtin()
    try:
        memo_key = (key, tuple(sorted(kwargs.items())))
        hash(memo_key)
    except TypeError:
        memo_key = None
    if memo_key is not None and memo_key in _ENV_MEMO:
        return _ENV_MEMO[memo_key]
    factory = _REGISTRY.get(key)
    if factory is None:
        family, parsed = parse_key(key)
        factory = _REGISTRY.get(family)
        if factory is None:
            raise KeyError(
                f"unknown env '{key}'; choose from {sorted(_REGISTRY)}")
        parsed.update(kwargs)
        kwargs = parsed
    env = factory(**kwargs)
    if memo_key is not None:
        _ENV_MEMO[memo_key] = env
    return env


def clear_memo():
    """Drop all memoized env instances — subsequent get() calls build
    fresh objects (at the cost of re-jitting their kernels).  Use before
    intentionally mutating an env, or to bound the memo's footprint in
    a long-lived process."""
    _ENV_MEMO.clear()


def keys():
    _ensure_builtin()
    return sorted(_REGISTRY)


def get_sized(key: str, max_steps_hint: int, **kwargs):
    """get() with a capacity hint, dropped for envs that don't plan
    capacity (e.g. nakamoto's closed-form scalar state).  Signature
    inspection (not try/except) decides, so constructor-internal
    TypeErrors still surface."""
    import inspect

    _ensure_builtin()
    factory = _REGISTRY.get(key)
    if factory is None:
        family, _ = parse_key(key)
        factory = _REGISTRY.get(family)
    takes_hint = False
    if factory is not None:
        try:
            sig = inspect.signature(factory)
            takes_hint = "max_steps_hint" in sig.parameters or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in sig.parameters.values())
        except (TypeError, ValueError):
            takes_hint = True
    if takes_hint:
        return get(key, max_steps_hint=max_steps_hint, **kwargs)
    return get(key, **kwargs)


def parse_key(key: str):
    """Parse a reference-style protocol key (cpr_protocols.ml:786-903):

        nakamoto
        ethereum-whitepaper | ethereum-byzantium
        bk-<k>-<constant|block>
        spar-<k>-<constant|block>
        stree-<k>-<scheme>[-<selection>]
        sdag-<k>-<constant|discount>[-<selection>]
        tailstorm-<k>-<scheme>[-<selection>]

    Returns (family, kwargs)."""
    parts = key.split("-")
    family = parts[0]
    if family in ("nakamoto",) and len(parts) == 1:
        return family, {}
    if family == "ethereum":
        # our grammar keys the reward preset; the reference keys the
        # incentive scheme (`ethereum-discount`, cpr_protocols.ml:815-818)
        if len(parts) == 2 and parts[1] in ("whitepaper", "byzantium"):
            return family, {"preset": parts[1]}
        raise KeyError(f"cannot parse protocol key '{key}': expected "
                       "ethereum-<whitepaper|byzantium>")
    grammars = {
        # family: (schemes, selections or None, min k) — like the
        # reference grammar, every option is mandatory
        # (cpr_protocols.ml:800-811 fails on a missing option); sdag
        # additionally requires k >= 2 (sdag.ml:24)
        "bk": (("constant", "block"), None, 1),
        "spar": (("constant", "block"), None, 1),
        "stree": (("constant", "discount", "punish", "hybrid"),
                  ("altruistic", "heuristic", "optimal"), 1),
        "sdag": (("constant", "discount"), ("altruistic", "heuristic"), 2),
        "tailstorm": (("constant", "discount", "punish", "hybrid"),
                      ("altruistic", "heuristic", "optimal"), 1),
        "tailstormjune": (("constant", "discount", "punish", "hybrid",
                           "block"), None, 1),
    }
    if family in grammars:
        schemes, selections, min_k = grammars[family]
        want_parts = 3 if selections is None else 4
        if len(parts) != want_parts or not parts[1].isdigit():
            raise KeyError(
                f"cannot parse protocol key '{key}': expected "
                f"{family}-<k>-<scheme>"
                + ("-<selection>" if selections else ""))
        kw = {"k": int(parts[1])}
        if kw["k"] < min_k:
            raise KeyError(f"cannot parse protocol key '{key}': "
                           f"{family} requires k >= {min_k}")
        if parts[2] not in schemes:
            raise KeyError(f"cannot parse protocol key '{key}': "
                           f"scheme must be one of {schemes}")
        kw["incentive_scheme"] = parts[2]
        if selections is not None:
            if parts[3] not in selections:
                raise KeyError(f"cannot parse protocol key '{key}': "
                               f"selection must be one of {selections}")
            kw["subblock_selection"] = parts[3]
        return family, kw
    raise KeyError(f"cannot parse protocol key '{key}'")


_BUILTIN_LOADED = False


def _ensure_builtin():
    global _BUILTIN_LOADED
    if _BUILTIN_LOADED:
        return
    from cpr_tpu.envs.bk import BkSSZ
    from cpr_tpu.envs.ethereum import EthereumSSZ
    from cpr_tpu.envs.nakamoto import NakamotoSSZ
    from cpr_tpu.envs.sdag import SdagSSZ
    from cpr_tpu.envs.spar import SparSSZ
    from cpr_tpu.envs.stree import StreeSSZ
    from cpr_tpu.envs.tailstorm import TailstormSSZ
    from cpr_tpu.envs.tailstorm_june import TailstormJuneSSZ

    _BUILTIN_LOADED = True
    for key, factory in [
        ("nakamoto", NakamotoSSZ),
        ("bk", BkSSZ),
        ("ethereum", EthereumSSZ),
        ("ethereum-whitepaper",
         lambda **kw: EthereumSSZ("whitepaper", **kw)),
        ("ethereum-byzantium",
         lambda **kw: EthereumSSZ("byzantium", **kw)),
        ("spar", SparSSZ),
        ("stree", StreeSSZ),
        ("sdag", SdagSSZ),
        ("tailstorm", TailstormSSZ),
        ("tailstormjune", TailstormJuneSSZ),
    ]:
        if key not in _REGISTRY:
            _REGISTRY[key] = factory
