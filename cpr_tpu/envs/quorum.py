"""Shared sub-block (vote) quorum machinery for the parallel-PoW protocol
family: Tailstorm (tailstorm.ml), Stree (stree.ml), Sdag (sdag.ml).

All three protocols select a bounded set of "votes" confirming the current
block/summary, subject to a closure constraint: selecting a vote implies
selecting all its vote ancestors (`acc_votes parents [x]`,
tailstorm.ml:134-149, stree.ml:103-117, sdag.ml acc_votes). The reference
walks linked DAG structures per decision; here the candidates are
compacted into a fixed window of C slot-ascending indices and their
ancestor relation is materialized as a dense (C, C) boolean matrix built
by one-hot parent rows closed with log-doubling matmuls — MXU-friendly,
no gathers or scatters in the selection rounds.

Votes have one parent in tailstorm/stree (paths) and up to P parents in
sdag (sub-DAGs); the transitive closure covers both.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cpr_tpu.core import dag as D


def frame_onehot(dag, cidx, cvalid):
    """(C, B) float32 one-hot rows for the compacted candidate indices.
    Gathering candidate-local values as `oh @ values` runs on the MXU;
    a (C,)-vector dynamic gather per field ran ~11 ms/step each at 4096
    envs on v5e (round-4 device profile)."""
    oh = (cidx[:, None] == dag.slots()[None, :]) & cvalid[:, None]
    return oh.astype(jnp.float32)


def oh_gather(oh, arr):
    """(C,) candidate-local values of a (B,) per-slot array via the
    one-hot matmul (exact for int values < 2^24).

    Non-finite entries are zeroed first: the matmul multiplies EVERY
    slot by its one-hot weight, and 0 * inf = NaN would poison every
    output row whenever the array holds an inf anywhere (vis_d_since is
    inf on withheld slots, pow_hash is NO_POW=inf on non-PoW slots).
    Candidates themselves always carry finite values, so zeroing the
    out-of-frame infs is lossless; rows for invalid candidates read 0
    and must be masked by the caller.

    Precision HIGHEST is load-bearing: TPU matmuls default to bf16
    operand truncation, which rounds integer values above 256 — slot
    ids up to capacity (520 at default hints) would come back off by
    one or two ON CHIP while every CPU test stays exact."""
    arr = arr.astype(jnp.float32)
    return jnp.matmul(oh, jnp.where(jnp.isfinite(arr), arr, 0.0),
                      precision=jax.lax.Precision.HIGHEST)


def last_of_kind_all(dag, kind: int):
    """(B,) block/summary of every vertex, elementwise: a vertex of
    `kind` is its own block; anything else stores its block in the
    signer column (the shared convention of the parallel-PoW family).
    Consumed by prefix_release_sets/stale_after_adopt as `last_all` —
    indexing with dag.slots() would compile to a real batched gather."""
    return jnp.where(dag.kind == kind, dag.slots(), dag.signer)


def candidate_frame(dag, cand, C: int, vote_kind: int, max_vote_parents: int = 1):
    """Compact the candidate votes to C slot-ascending indices and build
    the candidate-local ancestor bit-matrix abits (C, C): abits[i, j] ==
    candidate j lies in candidate i's vote closure (including i == j).

    The reference reaches candidates through a *filtered* child traversal
    (tailstorm.ml:509-531), so a vote with a vote parent outside the
    candidate set is unreachable — such rows are invalidated (and the
    invalidation propagates to their descendants through the closure).

    Returns (cidx, cvalid, abits, oh); cidx is -1-padded, oh is the
    frame_onehot matrix for candidate-local gathers.
    """
    assert C < (1 << 8), "composite sort keys reserve 8 bits for C-sized fields"
    cidx, cvalid = D.top_k_by(dag.age_key().astype(jnp.float32), cand, C)
    cidx = jnp.where(cvalid, cidx, -1)
    oh = frame_onehot(dag, cidx, cvalid)

    if dag.has_masks:
        # the ancestor relation is already materialized: a candidate's
        # vote ancestors are its closure-plane row restricted to votes
        # of the same block (votes store their block in `signer`, so
        # deeper blocks' votes — also in the closure — drop out).  Two
        # one-hot matmuls replace the per-parent adjacency build plus
        # the log-doubling closure (three 5.3 ms calls per step at 4096
        # envs in the round-5 tailstorm device profile).  bf16 operands
        # are exact here: one-hot rows make every entry 0 or 1.
        rows = jnp.matmul(oh.astype(jnp.bfloat16),
                          dag.closure.astype(jnp.bfloat16)) > 0.5
        if dag.is_ring:
            gid_c = oh_gather(oh, dag.gid).astype(jnp.int32)
            rows = rows & (dag.gid[None, :] <= gid_c[:, None])
        sig_c = jnp.where(cvalid,
                          oh_gather(oh, dag.signer).astype(jnp.int32), -2)
        anc_votes = (rows & (dag.kind == vote_kind)[None, :]
                     & (dag.signer[None, :] == sig_c[:, None]))
        if dag.is_ring:
            # the signer match above compares SLOT ids: after a wrap, a
            # still-resident vote of the signer slot's previous occupant
            # aliases sig_c and reads as an (out-of-frame) vote ancestor,
            # escaping the whole branch. Genuine confirmers are younger
            # than their block (the D.newer_than argument, vectorized
            # over the candidate blocks, same guard as
            # prefix_release_sets' conf_rows).
            gid_sig = oh_gather(frame_onehot(dag, sig_c, cvalid),
                                dag.gid).astype(jnp.int32)
            anc_votes = anc_votes & (dag.gid[None, :] > gid_sig[:, None])
        frame_mask = D.mask_of(cidx, cvalid, dag.capacity)
        # reachability runs through filtered child traversals
        # (tailstorm.ml:509-531): an out-of-frame vote ancestor makes
        # the whole branch unreachable (escape propagates transitively
        # through the closure, so one test per candidate suffices)
        escaped = (anc_votes & ~frame_mask[None, :]).any(axis=1)
        cvalid = cvalid & ~escaped
        abits = (jnp.matmul(anc_votes.astype(jnp.bfloat16),
                            oh.astype(jnp.bfloat16).T) > 0.5)
        abits = abits & cvalid[:, None] & cvalid[None, :]
        return cidx, cvalid, abits, oh

    adj = jnp.zeros((C, C), jnp.float32)
    escaped = jnp.zeros((C,), jnp.bool_)
    for p in range(max_vote_parents):
        # candidate-local parent slot via the one-hot matmul; invalid
        # candidates read 0 from the matmul, map them back to -1
        par = oh_gather(oh, dag.parents[p]).astype(jnp.int32)
        par = jnp.where(cvalid, par, -1)
        # membership: match[i, j] == (par[i] == cidx[j]), replaces the
        # searchsorted binary search (a while_loop of gathers on TPU)
        match = (par[:, None] == cidx[None, :]) & (par[:, None] >= 0)
        par_in_frame = match.any(axis=1)
        # is par[i] a vote at all (in or out of frame)?  scan the global
        # kind array once per plane: par_is_vote[i] = kind[par[i]] ==
        # vote_kind, computed as a one-hot reduction over B
        par_oh = (par[:, None] == dag.slots()[None, :])
        par_is_vote = (cvalid & (par >= 0)
                       & (par_oh & (dag.kind == vote_kind)[None, :])
                       .any(axis=1))
        escaped = escaped | (par_is_vote & ~par_in_frame)
        adj = adj + (match & par_is_vote[:, None]).astype(jnp.float32)
    reach = jnp.minimum(adj, 1.0) + jnp.eye(C, dtype=jnp.float32)
    for _ in range(max(1, (C - 1).bit_length())):
        reach = jnp.minimum(reach + reach @ reach, 1.0)
    abits = reach > 0.0
    cvalid = cvalid & ~(abits & escaped[None, :]).any(axis=1)
    abits = abits & cvalid[:, None]
    return cidx, cvalid, abits, oh


def quorum_heuristic(dag, cidx, cvalid, abits, oh, own, q: int):
    """Own-reward-first greedy branch selection (tailstorm.ml:329-380,
    stree.ml:~300): each round includes the candidate whose fresh closure
    maximizes (own count, total count), DAG order on ties; <= q rounds.
    Returns (found, leaves_c) with leaves_c a local boolean mask of the
    chosen branch tips."""
    C = cidx.shape[0]
    own_c = (oh_gather(oh, own) > 0.5) & cvalid

    def body(_, carry):
        inc, leaves_c, n_rem = carry
        fresh = abits & ~inc[None, :]
        f_all = fresh.sum(axis=1)
        f_own = (fresh & own_c[None, :]).sum(axis=1)
        eligible = cvalid & ~inc & (f_all >= 1) & (f_all <= n_rem)
        score = ((f_own * (q + 2) + f_all) << 8) + (C - jnp.arange(C))
        score = jnp.where(eligible & (n_rem > 0), score, -1)
        c = jnp.argmax(score).astype(jnp.int32)
        ok = score[c] >= 0
        inc = inc | (abits[c] & ok)
        leaves_c = leaves_c.at[c].max(ok)
        return inc, leaves_c, n_rem - jnp.where(ok, f_all[c], 0)

    z = jnp.zeros((C,), jnp.bool_)
    _, leaves_c, n_rem = jax.lax.fori_loop(
        0, max(q, 1), body, (z, z, jnp.int32(q)))
    return (n_rem == 0) & (cvalid.sum() >= q), leaves_c


def quorum_altruistic(dag, cidx, cvalid, abits, oh, own, seen, depth,
                      q: int):
    """Longest-branch-first greedy selection (tailstorm.ml:271-313,
    stree.ml:~230, sdag.ml altruistic_quorum): scan candidates by
    (depth desc, own first, seen asc), adding whole closures that still
    fit. Returns (n, set_c, tips_c, n_cand): n selected votes, the
    selected-set mask, the taken tips, and the candidate count — callers
    decide Full (n == q) vs Partial."""
    C = cidx.shape[0]
    # 12-bit depth field: composite key is 12+1+8+8 = 29 bits < int32.
    # Depths reach D_MAX = 3k+8 in tailstorm; 4095 covers any k that fits
    # a DAG window, unlike a 6-bit field which saturated at k >= 19.
    d_max = (1 << 12) - 1
    d = jnp.minimum(oh_gather(oh, depth).astype(jnp.int32), d_max)
    own_c = oh_gather(oh, own) > 0.5
    # invalid rows must sort to +inf seen; the matmul gives them 0.0
    seen_c = jnp.where(cvalid, oh_gather(oh, seen), jnp.inf)
    seen_rank = jnp.argsort(jnp.argsort(seen_c)).astype(jnp.int32)
    comp = ((((d_max - d) << 1 | (~own_c).astype(jnp.int32))
             << 8) + seen_rank) << 8
    comp = comp + jnp.arange(C, dtype=jnp.int32)  # stable: DAG order
    order = jnp.argsort(jnp.where(cvalid, comp, jnp.iinfo(jnp.int32).max))
    n_cand = cvalid.sum()

    def cond(carry):
        i, _, _, n = carry
        return (n < q) & (i < n_cand)

    def body(carry):
        i, acc, leaves_c, n = carry
        c = order[i]
        fresh = (abits[c] & ~acc).sum()
        take = (fresh >= 1) & (n + fresh <= q)
        acc = acc | (abits[c] & take)
        leaves_c = leaves_c.at[c].max(take)
        return i + 1, acc, leaves_c, n + jnp.where(take, fresh, 0)

    z = jnp.zeros((C,), jnp.bool_)
    _, acc, leaves_c, n = jax.lax.while_loop(
        cond, body, (jnp.int32(0), z, z, jnp.int32(0)))
    return n, acc, leaves_c, n_cand


def optimal_window(q: int, C: int, max_options: int = 100) -> int:
    """Largest candidate-window W with C(W, q) <= max_options — the
    static-shape form of the reference's option cap
    (tailstorm.ml:419-431: more than `max_options` n-choose-k choices
    falls back to the heuristic).  comb(n, q) grows in n, so
    `n_cand > W` if and only if the reference would fall back."""
    import math

    W = q
    while W + 1 <= C and math.comb(W + 1, q) <= max_options:
        W += 1
    return W


def optimal_combos(q: int, W: int):
    """(n_opt, W) bool table of all size-q subsets of the window."""
    import itertools

    import numpy as np

    rows = []
    for combo in itertools.combinations(range(W), q):
        row = np.zeros(W, bool)
        row[list(combo)] = True
        rows.append(row)
    return np.asarray(rows)


def quorum_optimal(dag, cidx, cvalid, abits, oh, own, depth, q: int,
                   combos, *, k: int, discount: bool, punish: bool,
                   depth_plus: int = 0, leaf_score=None,
                   miner_share: int = 0):
    """Exhaustive reward-optimal selection (tailstorm.ml:418-506,
    stree.ml equivalent): enumerate every closed size-q vote subset and
    keep the one maximizing the miner's own reward under the incentive
    scheme.  `combos` is the static optimal_combos table; the caller
    falls back to the heuristic when candidates exceed the window.

    The scorer must mirror the env's payout exactly or the argmax
    inverts, hence three env-specific knobs:
    - depth_plus: discount numerator offset — tailstorm pays r = depth/k
      (tailstorm.ml reward'), stree/tailstorm_june pay r = (depth+1)/k
      (stree.ml:176-193);
    - leaf_score: the env's own vote_score array (capacity,), used to
      pick the branch the punish scheme will actually pay (the envs use
      it in leaves_to_row, so tiebreaks agree by construction);
    - miner_share: 1 when the scheme also pays the block's miner r
      (stree.ml:188-190 adds the block to the rewarded set), 0 when it
      pays votes only (tailstorm).

    Returns (found, leaves_c).  Deviation: the reference breaks reward
    ties via its list ordering of choices; here ties go to the first
    combination in table order (candidate-slot order), which is
    deterministic but may pick a different equally-rewarded quorum.
    """
    C = cidx.shape[0]
    W = combos.shape[1]
    sel = jnp.zeros((combos.shape[0], C), jnp.bool_).at[:, :W].set(
        jnp.asarray(combos))
    own_c = (oh_gather(oh, own) > 0.5) & cvalid
    depth_c = jnp.where(cvalid, oh_gather(oh, depth).astype(jnp.int32), -1)
    n_cand = cvalid.sum()

    ok_valid = (sel & ~cvalid[None, :]).sum(axis=1) == 0
    # closure-closed: every selected vote's vote-ancestors are selected
    escape = (sel[:, :, None] & abits[None, :, :]
              & ~sel[:, None, :]).any(axis=(1, 2))
    valid = ok_valid & ~escape & (n_cand >= q)

    # the leaf the punish scheme pays: highest env leaf_score (the same
    # preference the env's leaves_to_row applies)
    if leaf_score is None:
        leaf_score = dag.aux.astype(jnp.float32) - dag.pow_hash
    score_c = jnp.where(cvalid, oh_gather(oh, leaf_score), -jnp.inf)
    deep_key = jnp.where(sel, score_c[None, :], -jnp.inf)
    deepest = jnp.argmax(deep_key, axis=1)
    depth_max = jnp.max(jnp.where(sel, depth_c[None, :], -1), axis=1)

    r = jnp.where(discount,
                  (depth_max + depth_plus).astype(jnp.float32) / k, 1.0)
    rewarded = jnp.where(punish, abits[deepest], sel)
    score = r * ((rewarded & own_c[None, :]).sum(axis=1) + miner_share)
    score = jnp.where(valid, score, -jnp.inf)

    best = jnp.argmax(score)
    found = valid.any()
    sel_best = sel[best] & found
    # leaves: selected votes with no selected strict descendant
    # (abits[i, j]: j lies in i's closure, including i == j)
    desc = sel_best[:, None] & abits & ~jnp.eye(C, dtype=jnp.bool_)
    leaves_c = sel_best & ~desc.any(axis=0)
    return found, leaves_c


def quorum_optimal_or_heuristic(dag, cidx, cvalid, abits, oh, own, depth,
                                q: int, window: int, combos, *, k: int,
                                discount: bool, punish: bool,
                                depth_plus: int = 0, leaf_score=None,
                                miner_share: int = 0):
    """Optimal selection with the reference's option-cap fallback: when
    any valid candidate sits beyond the static window (more combinations
    than the cap, or escape-invalidation pushed a valid vote past slot
    W), use the heuristic instead.  The second case is conservative: the
    reference packs candidates densely and might still enumerate; here
    the window is positional, so out-of-window candidates force the
    fallback."""
    found_o, leaves_o = quorum_optimal(
        dag, cidx, cvalid, abits, oh, own, depth, q, combos, k=k,
        discount=discount, punish=punish, depth_plus=depth_plus,
        leaf_score=leaf_score, miner_share=miner_share)
    found_h, leaves_h = quorum_heuristic(dag, cidx, cvalid, abits, oh,
                                         own, q)
    C = cidx.shape[0]
    over = (cvalid & (jnp.arange(C) >= window)).any()
    return (jnp.where(over, found_h, found_o),
            jnp.where(over, leaves_h, leaves_o))


def leaves_to_row(dag, cidx, leaves_c, cvalid, width: int, score):
    """Map the local leaves mask back to global slots (scatter-free,
    D.mask_of) and pick the parent row: `width` leaves sorted descending
    by `score` (a (B,) array), -1 padded."""
    leaves = D.mask_of(cidx, leaves_c & cvalid, dag.capacity)
    idx, valid = D.top_k_by(score, leaves, width, largest=True)
    return jnp.where(valid, idx, D.NONE).astype(jnp.int32)


def prefix_release_sets(dag, public, private, cands, R: int, last_all,
                        cmp_fn, extra_all=None):
    """Override/Match release-set computation shared by the tailstorm,
    stree, and sdag envs (tailstorm_ssz.ml:292-314 and twins): scan the
    withheld candidates in DAG (= slot, topological) order; the Override
    set is the smallest prefix whose release flips the defender's head,
    the Match set is that prefix minus the flipping vertex; if no prefix
    flips, both release everything.

    All prefixes are evaluated at once: for every prefix j the defender's
    head-comparison terms are cumulative counts. The flip rule is
    (height, confirming votes[, extra]) strictly greater.

    - last_all: (B,) block/summary of every vertex, precomputed
      elementwise by the caller (votes store their block in `signer`, so
      this is a where(), not a walk),
    - cmp_fn(dag, x, y, vote_filter_mask): strict preference, used for the
      window-overflow fallback (release everything, head flips iff the
      attacker's preferred block wins once fully visible),
    - extra_all: optional (B,) per-vertex tiebreak values (tailstorm's
      defender own-reward, cached in Dag.auxg at append time).

    Candidate-local values come from one-hot matmul rows, not dynamic
    gathers — at R=128 x 4096 envs each batched gather ran ~11 ms/step
    on v5e (round-4 device profile).

    Returns (override_set, match_set, found, new_head).
    """
    ridx, rvalid = D.top_k_by(dag.age_key().astype(jnp.float32), cands, R)
    roh = frame_onehot(dag, ridx, rvalid)

    def rg(arr):
        return oh_gather(roh, arr)

    lb = jnp.where(rvalid, rg(last_all).astype(jnp.int32), 0)
    csig = jnp.where(rvalid, rg(dag.signer).astype(jnp.int32), -1)

    # in all three envs votes (and only votes) store their block/summary
    # in the signer column, so signer >= 0 identifies confirming votes
    is_conf = dag.exists() & (dag.signer >= 0)
    conf_rows = ((is_conf & dag.vis_d)[:, None]
                 & (dag.signer[:, None] == lb[None, :]))
    if dag.is_ring:
        # ring wrap: a retired summary's still-resident votes alias the
        # reclaimed slot's new occupant; genuine confirmers are younger
        # than their summary (same guard as D.newer_than, vectorized
        # over the candidate summaries)
        gid_lb = oh_gather(frame_onehot(dag, lb, rvalid),
                           dag.gid).astype(jnp.int32)
        conf_rows = conf_rows & (dag.gid[:, None] > gid_lb[None, :])
    conf_vis = conf_rows.sum(axis=0)
    cand_vote = (csig >= 0) & rvalid
    cmat = cand_vote[:, None] & (csig[:, None] == lb[None, :])
    leq = jnp.triu(jnp.ones((R, R), jnp.bool_))
    nconf = conf_vis + (cmat & leq).sum(axis=0)

    pub_vis = (is_conf & dag.vis_d & (dag.signer == public)
               & D.newer_than(dag, public)).sum()
    npub = pub_vis + jnp.cumsum(cand_vote & (csig == public))

    # every vertex is appended with its block/summary's height, so
    # height[last(x)] == height[x] and one matmul row suffices
    h_lb = jnp.where(rvalid, rg(dag.height).astype(jnp.int32), 0)
    h_pub = dag.height[public]
    flip = (h_lb > h_pub) | ((h_lb == h_pub) & (nconf > npub))
    if extra_all is not None:
        # the tiebreak reads at each candidate's BLOCK (lb), not the
        # candidate slot itself: vote slots carry the field's default
        # (tailstorm votes append auxg=0), so an rg(extra_all) gather
        # at the candidate would zero the tiebreak for vote candidates
        e_lb = oh_gather(frame_onehot(dag, lb, rvalid), extra_all)
        e_pub = extra_all[jnp.maximum(public, 0)]
        flip = flip | ((h_lb == h_pub) & (nconf == npub) & (e_lb > e_pub))
    flip = flip & (lb != public) & rvalid
    overflow = cands.sum() > R
    found = flip.any() & ~overflow
    j_stop = jnp.argmax(flip).astype(jnp.int32)
    take_o = jnp.where(found, jnp.arange(R) <= j_stop, rvalid)
    take_m = jnp.where(found, jnp.arange(R) < j_stop, rvalid)
    override_set = ((take_o & rvalid).astype(jnp.float32) @ roh) > 0.5
    match_set = ((take_m & rvalid).astype(jnp.float32) @ roh) > 0.5
    override_set = jnp.where(overflow, cands, override_set)
    match_set = jnp.where(overflow, cands, match_set)
    all_flip = cmp_fn(dag, private, public, dag.vis_d | cands)
    found = found | (overflow & all_flip)
    new_head = jnp.where(
        overflow, jnp.where(all_flip, private, public),
        jnp.where(found, lb[j_stop], public))
    return override_set, match_set, found, new_head


def stale_after_adopt(dag, public, stale, is_adopt, R: int, walk: int,
                      last_all, prev_fn):
    """Stale-bit update at Adopt, shared by tailstorm/stree/sdag:
    adopting moves the common ancestor to `public`, abandoning every
    withheld vertex that does not descend from it. Descent is checked on
    the compacted withheld set by walking each vertex's block/summary
    chain down `walk` levels (deeper withheld branches above the adopted
    head cannot exist: the attacker adopts because it is behind).
    `last_all` is the same precomputed (B,) block/summary array as in
    prefix_release_sets.

    With ancestry masks the descent test is one chain-plane column
    read (does x's chain pass through `public`?) — no compaction, no
    per-level gathers, and no `walk` depth bound (the bound was safe
    only because deeper withheld branches cannot exist; the column is
    exact at any depth)."""
    withheld = ~dag.vis_d & dag.exists() & ~stale
    if dag.has_masks:
        keep_mask = D.descendants_mask(dag, public)
        return jnp.where(is_adopt, stale | (withheld & ~keep_mask), stale)
    widx, wvalid = D.top_k_by(dag.age_key().astype(jnp.float32), withheld, R)
    woh = frame_onehot(dag, widx, wvalid)
    cur = jnp.where(wvalid, oh_gather(woh, last_all).astype(jnp.int32), -1)
    keeps = jnp.zeros_like(wvalid)
    for _ in range(walk):
        keeps = keeps | (cur == public)
        cur = jnp.where(cur >= 0, prev_fn(dag, jnp.maximum(cur, 0)), -1)
    keep_mask = ((keeps & wvalid).astype(jnp.float32) @ woh) > 0.5
    return jnp.where(is_adopt, stale | (withheld & ~keep_mask), stale)
