"""The jaxlint rule registry.

Each rule encodes an invariant an earlier PR established by hand (the
motivating PR is named in `rationale`; full catalog with examples in
docs/ANALYSIS.md).  All rules are pure AST/tokenize — no rule may
import jax or cpr_tpu runtime modules (cross-module facts like the
telemetry EVENT_FIELDS schema are read by parsing the source, see
LintContext.event_fields).
"""

from __future__ import annotations

import ast
import re

from cpr_tpu.analysis.core import LintContext, Rule, SourceFile

# rule 5's "known hot paths": files whose jitted carry loops the bench
# trail showed dominate device memory/throughput (BENCH_r03/r04; the
# 65536-env ethereum OOM class motivated donation in envs/base.py)
HOT_CARRY_PATHS = (
    "cpr_tpu/envs/base.py",
    "cpr_tpu/train/ppo.py",
    "cpr_tpu/netsim/engine.py",
    "cpr_tpu/serve/engine.py",
    # the grid-batched VI carry is [G, S] x 3 planes — G grid points
    # of value/progress/policy stepped per chunk dispatch, the
    # dominant resident block of a grid solve
    "cpr_tpu/mdp/explicit.py",
    "cpr_tpu/mdp/grid.py",
    # the in-graph RTDP while_loop carries the full [S] value/progress
    # planes plus visit counters and the priority buffer — the whole
    # point of the port is keeping that state device-resident, so an
    # undonated input table doubles the explored-table footprint
    "cpr_tpu/mdp/rtdp_graph.py",
)
# ...and every module under parallel/ — notably the sharded resident
# lane stepper (parallel/lanes.py): its mesh-sharded carries are
# n_devices times the single-device footprint, so an undonated carry
# there wastes memory on every chip at once — and under learn/: the
# experience rings ride the serve burst carry ([L, C, ...] per field),
# so an undonated buffer doubles the recording plane's footprint on
# every drain cycle
HOT_CARRY_PREFIXES = ("cpr_tpu/parallel/", "cpr_tpu/learn/")

_LOOPS = (ast.For, ast.AsyncFor, ast.While)
_COMPREHENSIONS = (ast.ListComp, ast.SetComp, ast.DictComp,
                   ast.GeneratorExp)
_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def dotted(node) -> str | None:
    """'jax.random.split' for Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_call(node) -> tuple[bool, ast.Call | None]:
    """(is a jax.jit construction, the call carrying jit's kwargs) —
    matches `jax.jit(...)` and `partial(jax.jit, ...)`."""
    if not isinstance(node, ast.Call):
        return False, None
    d = dotted(node.func)
    if d in ("jax.jit", "jit"):
        return True, node
    if d in ("partial", "functools.partial") and node.args:
        if dotted(node.args[0]) in ("jax.jit", "jit"):
            return True, node
    return False, None


def _enclosing(src: SourceFile, node, kinds):
    for anc in src.ancestors(node):
        if isinstance(anc, kinds):
            return anc
    return None


class WallClockRule(Rule):
    id = "wall-clock"
    summary = ("no time.time()/naive datetime.now() under cpr_tpu/ — "
               "interval timing goes through telemetry.now or Span")
    rationale = ("PR 2: on an async-dispatch backend a wall-clock "
                 "bracket measures dispatch, not execution; time.time "
                 "is neither monotonic nor high-resolution.  Absorbs "
                 "the PR-2 tokenize sweep test.")

    _NAIVE = ("datetime.now", "datetime.datetime.now",
              "datetime.utcnow", "datetime.datetime.utcnow",
              "datetime.today", "datetime.datetime.today")

    def check(self, src: SourceFile, ctx: LintContext):
        if not src.rel.startswith("cpr_tpu/"):
            return
        for node in src.nodes:
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d == "time.time":
                yield self.finding(
                    src, node,
                    "time.time() — use telemetry.now (perf_counter) or "
                    "a fenced Span for intervals")
            elif (d in self._NAIVE and not node.args
                  and not node.keywords):
                yield self.finding(
                    src, node,
                    f"naive {d}() — pass an explicit tz "
                    "(datetime.now(timezone.utc)) for wall-clock "
                    "metadata; intervals go through telemetry.now")


class RawWriteRule(Rule):
    id = "raw-write"
    summary = ("no truncating open(path, 'w'/'wb') artifact writes "
               "outside resilience.py — use resilience.atomic_write_*")
    rationale = ("PR 4: a crash mid-write must never leave a "
                 "half-written artifact under its final name; every "
                 "artifact write goes through tmp+fsync+os.replace.  "
                 "Append-mode streams (telemetry JSONL) are exempt — "
                 "appends never truncate.")

    def check(self, src: SourceFile, ctx: LintContext):
        if src.rel == "cpr_tpu/resilience.py":
            return
        for node in src.nodes:
            if not (isinstance(node, ast.Call)
                    and dotted(node.func) in ("open", "io.open")):
                continue
            mode = None
            if len(node.args) > 1:
                mode = node.args[1]
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = kw.value
            if (isinstance(mode, ast.Constant)
                    and isinstance(mode.value, str)
                    and ("w" in mode.value or "x" in mode.value)):
                yield self.finding(
                    src, node,
                    f"raw open(..., {mode.value!r}) — route artifact "
                    "writes through resilience.atomic_write_bytes/"
                    "_json/_text so readers never see a torn file")


class EventSchemaRule(Rule):
    id = "event-schema"
    summary = ("telemetry .event(name, ...) call sites using a typed "
               "EVENT_FIELDS name must pass every declared field")
    rationale = ("PR 3: trace_summary --validate enforces the schema "
                 "on artifacts at runtime; this catches the producer "
                 "drift statically, before a smoke run has to fail.  "
                 "EVENT_FIELDS is resolved from cpr_tpu/telemetry.py "
                 "by AST, cross-module, without importing it.")

    def check(self, src: SourceFile, ctx: LintContext):
        schema = ctx.event_fields()
        if not schema:
            return
        for node in src.nodes:
            if not isinstance(node, ast.Call):
                continue
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "event" and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                name = node.args[0].value
                required = schema.get(name)
                if not required:
                    continue
                kwnames = {kw.arg for kw in node.keywords}
                if None in kwnames:  # **kwargs: not statically checkable
                    continue
                missing = [k for k in required if k not in kwnames]
                if missing:
                    yield self.finding(
                        src, node,
                        f"typed event '{name}' missing declared "
                        f"field(s) {missing} (telemetry.EVENT_FIELDS)")
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "emit"
                    and len(node.args) == 1
                    and isinstance(node.args[0], ast.Dict)):
                d = node.args[0]
                keys = {k.value for k in d.keys
                        if isinstance(k, ast.Constant)}
                if len(keys) != len(d.keys):
                    continue  # dynamic/** keys: not checkable
                vals = {k.value: v for k, v in zip(d.keys, d.values)
                        if isinstance(k, ast.Constant)}
                name_node = vals.get("name")
                if (vals.get("kind") is None
                        or not isinstance(name_node, ast.Constant)):
                    continue
                required = schema.get(name_node.value)
                if required:
                    missing = [k for k in required if k not in keys]
                    if missing:
                        yield self.finding(
                            src, node,
                            f"typed event '{name_node.value}' emitted "
                            f"without declared field(s) {missing}")


class JitInLoopRule(Rule):
    id = "jit-in-loop"
    summary = ("no jax.jit constructed in a loop body or jit-and-"
               "called in one expression — each construction is a "
               "fresh cache, so every call retraces")
    rationale = ("PR 3: the compile_watch retrace pin proved stable "
                 "call sites compile exactly once; a jit wrapper "
                 "built per iteration (or per call via "
                 "`jax.jit(f)(x)`) silently recompiles every time.  "
                 "Factory functions that build, cache, and return a "
                 "jitted callable are fine.")

    def check(self, src: SourceFile, ctx: LintContext):
        for node in src.nodes:
            is_jit, _ = _is_jit_call(node)
            if not is_jit:
                continue
            parent = src.parents.get(node)
            if isinstance(parent, ast.Call) and parent.func is node:
                yield self.finding(
                    src, node,
                    "jax.jit(f)(...) constructs a fresh jit cache per "
                    "call — bind the jitted callable once and reuse it")
                continue
            for anc in src.ancestors(node):
                if isinstance(anc, _SCOPES):
                    # constructed when the enclosing function runs;
                    # loop ancestry beyond it is the caller's problem
                    break
                if isinstance(anc, _LOOPS + _COMPREHENSIONS):
                    yield self.finding(
                        src, node,
                        "jax.jit constructed inside a loop — every "
                        "iteration gets a fresh cache and retraces; "
                        "hoist the construction out of the loop")
                    break


_STEPPY = re.compile(r"(^|_)(step|train_step)(_fn)?$")
_CARRYISH = re.compile(r"(^|_)(carry|state)$")


class DonateCarryRule(Rule):
    id = "donate-carry"
    summary = ("jitted carry-pytree loops on hot paths must donate "
               "the carry (donate_argnums) or carry an explicit "
               "annotated waiver")
    rationale = ("PR 1/PR 4: aliasing the chunk/train carry halves "
                 "peak device memory on the 65536-env ethereum class; "
                 "non-donating hot loops silently double it back.  "
                 "Scoped to envs/base.py, train/ppo.py, "
                 "netsim/engine.py, parallel/.")

    def _wrapped_first_param(self, src, jit_call, carrier):
        """Name of the wrapped callable's first parameter, resolved
        lexically (decorated def, local def by name, or lambda);
        None when unresolvable."""
        parent = src.parents.get(jit_call)
        if (isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef))
                and jit_call in parent.decorator_list):
            args = parent.args.args
            return args[0].arg if args else None
        target = None
        if dotted(jit_call.func) in ("jax.jit", "jit") and jit_call.args:
            target = jit_call.args[0]
        elif len(jit_call.args) > 1:  # partial(jax.jit, f, ...)
            target = jit_call.args[1]
        if isinstance(target, ast.Lambda):
            args = target.args.args
            return args[0].arg if args else None
        if isinstance(target, ast.Name):
            for n in src.nodes:
                if (isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and n.name == target.id):
                    args = n.args.args
                    return args[0].arg if args else None
            # unresolved (e.g. a function passed in as a parameter):
            # fall back to the name itself — `jax.jit(step_fn)` on a
            # hot path is the PPO update loop shape
            if _STEPPY.search(target.id):
                return "carry"
        if isinstance(target, ast.Call):
            for n in ast.walk(target):
                if isinstance(n, ast.Name) and _STEPPY.search(n.id):
                    return "carry"
        return None

    def check(self, src: SourceFile, ctx: LintContext):
        if not (src.rel in HOT_CARRY_PATHS
                or src.rel.startswith(HOT_CARRY_PREFIXES)):
            return
        for node in src.nodes:
            is_jit, kw_carrier = _is_jit_call(node)
            if not is_jit:
                continue
            kwnames = {kw.arg for kw in kw_carrier.keywords}
            if kwnames & {"donate_argnums", "donate_argnames"}:
                continue
            first = self._wrapped_first_param(src, node, kw_carrier)
            if first is not None and _CARRYISH.search(first):
                yield self.finding(
                    src, node,
                    f"jitted hot-path callable takes carry pytree "
                    f"'{first}' without donate_argnums — the previous "
                    "carry is dead after the call; donate it (or "
                    "waive with a reasoned disable if old buffers "
                    "are deliberately kept, e.g. best/revert aliasing)")


_KEY_PRODUCERS = ("jax.random.PRNGKey", "jax.random.key",
                  "jax.random.split", "jax.random.fold_in",
                  "jax.random.wrap_key_data",
                  "random.PRNGKey", "random.split", "random.fold_in",
                  "jr.PRNGKey", "jr.split", "jr.fold_in")

# fold_in(key, data) derives a fresh stream distinguished by `data`;
# feeding the same base key to fold_in repeatedly (e.g. with a loop
# index) is the sanctioned per-iteration idiom, not a reuse
_FOLD_INS = ("jax.random.fold_in", "random.fold_in", "jr.fold_in")


class KeyReuseRule(Rule):
    id = "key-reuse"
    summary = ("a PRNG key variable must not feed two sampling calls "
               "without an intervening split/fold_in rebinding")
    rationale = ("PR 5 lanes and every vmapped sweep assume "
                 "statistically independent draws; reusing a consumed "
                 "key replays the identical stream (the "
                 "measure_rtdp.py segment bug class).  Indexed "
                 "sub-keys (keys[i]) are distinct streams and exempt.")

    def check(self, src: SourceFile, ctx: LintContext):
        scopes = [src.tree] + [
            n for n in src.nodes
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for scope in scopes:
            yield from self._check_scope(src, scope)

    # -- per-scope linear dataflow ------------------------------------

    def _check_scope(self, src, scope):
        body = scope.body if hasattr(scope, "body") else []
        state: dict[str, dict] = {}
        findings: list = []
        self._run(body, state, loops=(), findings=findings, src=src)
        yield from findings

    def _run(self, stmts, state, loops, findings, src):
        for st in stmts:
            self._stmt(st, state, loops, findings, src)

    def _stmt(self, st, state, loops, findings, src):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # nested scopes get their own pass
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._expr(st.iter, state, loops, findings, src)
            self._clear_targets(st.target, state)
            inner = loops + (id(st),)
            self._run(st.body, state, inner, findings, src)
            self._run(st.orelse, state, loops, findings, src)
            return
        if isinstance(st, ast.While):
            self._expr(st.test, state, loops, findings, src)
            self._run(st.body, state, loops + (id(st),), findings, src)
            self._run(st.orelse, state, loops, findings, src)
            return
        if isinstance(st, ast.If):
            self._expr(st.test, state, loops, findings, src)
            snap = {k: dict(v) for k, v in state.items()}
            self._run(st.body, state, loops, findings, src)
            after_body = state
            other = snap
            self._run(st.orelse, other, loops, findings, src)
            # merge: a name is "used" if either branch used it
            for k in set(after_body) | set(other):
                a, b = after_body.get(k), other.get(k)
                if a is None or b is None:
                    after_body.pop(k, None)
                    continue
                a["uses"] = max(a["uses"], b["uses"])
                a["flagged"] = a["flagged"] or b["flagged"]
            return
        if isinstance(st, ast.Try):
            self._run(st.body, state, loops, findings, src)
            for h in st.handlers:
                self._run(h.body, state, loops, findings, src)
            self._run(st.orelse, state, loops, findings, src)
            self._run(st.finalbody, state, loops, findings, src)
            return
        if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = st.value
            targets = (st.targets if isinstance(st, ast.Assign)
                       else [st.target])
            produced = (isinstance(value, ast.Call)
                        and dotted(value.func) in _KEY_PRODUCERS)
            tnames = {name for t in targets
                      for name in self._target_names(t)}
            if value is not None:
                # the split-rebind idiom `k, k1 = jax.random.split(k)`
                # consumes-and-replaces k in one statement: the RHS use
                # of a name that is also a target is not a reuse
                self._expr(value, state, loops, findings, src,
                           exempt=tnames if produced else frozenset())
            for name in tnames:
                if produced:
                    state[name] = {"uses": 0, "loops": loops,
                                   "flagged": False}
                else:
                    state.pop(name, None)
            return
        if isinstance(st, ast.With) or isinstance(st, ast.AsyncWith):
            for item in st.items:
                self._expr(item.context_expr, state, loops, findings, src)
                if item.optional_vars is not None:
                    self._clear_targets(item.optional_vars, state)
            self._run(st.body, state, loops, findings, src)
            return
        # generic statement: walk its expressions
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self._expr(child, state, loops, findings, src)

    def _target_names(self, t):
        if isinstance(t, ast.Name):
            yield t.id
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield from self._target_names(e)
        elif isinstance(t, ast.Starred):
            yield from self._target_names(t.value)

    def _clear_targets(self, t, state):
        for name in self._target_names(t):
            state.pop(name, None)

    def _expr(self, node, state, loops, findings, src,
              exempt=frozenset()):
        """Record key consumptions: tracked Names appearing in call
        arguments (not func position, not under a Subscript — keys[i]
        selects a distinct sub-key).  Lambda bodies are skipped —
        closures are not linear dataflow in the enclosing scope."""
        stack = [node]
        while stack:
            call = stack.pop()
            if isinstance(call, ast.Lambda):
                continue
            stack.extend(ast.iter_child_nodes(call))
            if isinstance(call, ast.NamedExpr):
                # walrus rebinding inside an expression
                if (isinstance(call.value, ast.Call)
                        and dotted(call.value.func) in _KEY_PRODUCERS
                        and isinstance(call.target, ast.Name)):
                    state[call.target.id] = {"uses": 0, "loops": loops,
                                             "flagged": False}
            if not isinstance(call, ast.Call):
                continue
            if dotted(call.func) in _FOLD_INS:
                continue  # derivation, not consumption
            for arg in list(call.args) + [kw.value for kw in call.keywords]:
                for name_node in self._arg_key_names(arg):
                    if name_node.id in exempt:
                        continue
                    rec = state.get(name_node.id)
                    if rec is None or rec["flagged"]:
                        continue
                    escaped_loop = any(lp not in rec["loops"]
                                       for lp in loops)
                    if rec["uses"] >= 1:
                        rec["flagged"] = True
                        findings.append(self.finding(
                            src, name_node,
                            f"PRNG key '{name_node.id}' consumed again "
                            "without an intervening "
                            "jax.random.split/fold_in — the identical "
                            "stream replays"))
                    elif escaped_loop:
                        rec["flagged"] = True
                        findings.append(self.finding(
                            src, name_node,
                            f"PRNG key '{name_node.id}' bound outside "
                            "this loop is consumed every iteration — "
                            "fold_in the iteration index or split per "
                            "iteration"))
                    else:
                        rec["uses"] += 1

    def _arg_key_names(self, arg):
        """Direct Name nodes inside one call argument.  Skips
        Subscripts (keys[i] is a fresh sub-key), Attributes (key.shape
        reads metadata, it does not consume), closures, and nested
        Calls — the outer expression walk visits nested calls itself,
        so descending here would double-count `f(g(key))`."""
        stack = [arg]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Name):
                yield n
            elif isinstance(n, (ast.Subscript, ast.Attribute, ast.Call,
                                ast.Lambda, ast.FunctionDef,
                                ast.AsyncFunctionDef)):
                continue
            else:
                stack.extend(ast.iter_child_nodes(n))


class HostSyncRule(Rule):
    id = "host-sync"
    summary = ("no host-sync calls (.item(), float()/int() on traced "
               "values, np.asarray, device_get, block_until_ready) "
               "inside lax.scan / while_loop / fori_loop bodies")
    rationale = ("PR 3: the chunked stats driver passes "
                 "jax.transfer_guard('disallow') end-to-end; a host "
                 "sync inside a traced loop body either crashes at "
                 "trace time or, worse, silently falls back to a "
                 "per-step device round-trip.")

    _NP_SYNCS = ("np.asarray", "np.array", "numpy.asarray",
                 "numpy.array", "onp.asarray", "onp.array",
                 "jax.device_get")

    def _body_functions(self, src):
        """(body_expr, via) for every callable passed as a traced loop
        body, resolving Names to same-file defs."""
        defs: dict[str, list] = {}
        for n in src.nodes:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(n.name, []).append(n)
        out = []
        for node in src.nodes:
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func) or ""
            cands = []
            if d.endswith("lax.scan") and node.args:
                cands = [node.args[0]]
            elif d.endswith("lax.while_loop") and len(node.args) >= 2:
                cands = [node.args[0], node.args[1]]
            elif d.endswith("lax.fori_loop") and len(node.args) >= 3:
                cands = [node.args[2]]
            for c in cands:
                if isinstance(c, ast.Lambda):
                    out.append((c, d))
                elif isinstance(c, ast.Name):
                    out.extend((fd, d) for fd in defs.get(c.id, ()))
        return out

    def check(self, src: SourceFile, ctx: LintContext):
        seen = set()
        for body, via in self._body_functions(src):
            for node in ast.walk(body):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                msg = None
                d = dotted(node.func)
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("item",
                                               "block_until_ready")
                        and not node.args):
                    msg = f".{node.func.attr}()"
                elif d in self._NP_SYNCS:
                    msg = f"{d}(...)"
                elif (isinstance(node.func, ast.Name)
                        and node.func.id in ("float", "int", "bool")
                        and len(node.args) == 1
                        and not isinstance(node.args[0], ast.Constant)):
                    msg = f"{node.func.id}(...) on a traced value"
                if msg:
                    seen.add(id(node))
                    yield self.finding(
                        src, node,
                        f"host sync {msg} inside a {via} body — "
                        "traced loop bodies must stay on device "
                        "(ConcretizationError at best, a silent "
                        "per-step transfer at worst)")


RULES: tuple[Rule, ...] = (
    WallClockRule(),
    RawWriteRule(),
    EventSchemaRule(),
    JitInLoopRule(),
    DonateCarryRule(),
    KeyReuseRule(),
    HostSyncRule(),
)


def rule_ids() -> tuple[str, ...]:
    return tuple(r.id for r in RULES)
