"""jaxlint — JAX-aware static analysis over the cpr_tpu codebase.

PRs 1-5 accumulated correctness/perf invariants that lived only in
prose and one-off tests: spans fence before timestamping, artifacts go
through `resilience.atomic_write_*`, telemetry point events match the
typed `EVENT_FIELDS` schema, no wall-clock interval timing in the
package, and jitted hot loops must not silently retrace or sync.  This
package turns those invariants into an always-on CI gate: a pure
AST/tokenize rule engine (no JAX import — linting the repo takes ~1s
on the 1-core host) with a registry of rules, inline
`# jaxlint: disable=<rule>` escape hatches, and a JSON baseline for
grandfathered findings.

Entry points:

* `tools/jaxlint.py` — the CLI (`--format json`, per-rule disables,
  `--baseline`); `make lint` runs it over `cpr_tpu/` + `tools/` and
  banks the JSON artifact under `runs/`.
* `run_lint(paths)` — the in-process API the tier-1 test suite calls
  (tests/test_jaxlint.py), so every future PR inherits the gate.

Rule catalog and per-rule rationale: docs/ANALYSIS.md.

This module and its submodules import only the standard library:
keeping the linter importable without initializing a JAX backend is a
hard requirement (the CLI loads this package without executing
`cpr_tpu/__init__.py`, which pulls jax via params).
"""

from cpr_tpu.analysis.core import (  # noqa: F401
    Finding,
    LintContext,
    Rule,
    SourceFile,
    iter_source_files,
    load_baseline,
    run_lint,
)
from cpr_tpu.analysis.rules import RULES, rule_ids  # noqa: F401
