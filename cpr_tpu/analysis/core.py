"""Rule engine: source model, disable comments, registry, baseline.

Stdlib-only (ast + tokenize + io) — see the package docstring for why
the no-JAX-at-import property is load-bearing.
"""

from __future__ import annotations

import ast
import io
import json
import os
import tokenize
from dataclasses import dataclass, field

DISABLE_MARKER = "jaxlint:"


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location.  `path` is
    repo-relative POSIX so findings are stable across checkouts (the
    JSON format and the baseline both key on it)."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def key(self) -> tuple:
        return (self.rule, self.path, self.line)

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message}


class Rule:
    """Base class: subclasses set `id` (stable kebab-case, the CLI and
    disable comments use it), `summary`, and `rationale` (which PR's
    invariant the rule encodes), and implement `check`."""

    id: str = ""
    summary: str = ""
    rationale: str = ""

    def check(self, src: "SourceFile", ctx: "LintContext"):
        raise NotImplementedError

    def finding(self, src: "SourceFile", node, message: str) -> Finding:
        return Finding(self.id, src.rel, getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0), message)


def _parse_disables(text: str) -> tuple[dict, set]:
    """-> (line -> set of rule ids, file-wide set).  Grammar:

        # jaxlint: disable=rule[,rule]            (this line only)
        # jaxlint: disable-next-line=rule[,rule]  (the following line)
        # jaxlint: disable-file=rule[,rule]       (whole file)

    An inline disable is the sanctioned escape hatch for a deliberate
    violation — pair it with a reason in the surrounding comment.
    """
    per_line: dict[int, set] = {}
    per_file: set = set()
    try:
        toks = tokenize.generate_tokens(io.StringIO(text).readline)
        for tok in toks:
            if tok.type != tokenize.COMMENT:
                continue
            body = tok.string.lstrip("#").strip()
            if not body.startswith(DISABLE_MARKER):
                continue
            directive = body[len(DISABLE_MARKER):].strip()
            # allow trailing prose after the rule list ("— reason")
            directive = directive.split()[0] if directive else ""
            for prefix, line in (("disable-file=", None),
                                 ("disable-next-line=",
                                  tok.start[0] + 1),
                                 ("disable=", tok.start[0])):
                if directive.startswith(prefix):
                    rules = {r.strip() for r in
                             directive[len(prefix):].split(",") if r.strip()}
                    if line is None:
                        per_file.update(rules)
                    else:
                        per_line.setdefault(line, set()).update(rules)
                    break
    except tokenize.TokenError:
        pass  # a syntax error surfaces via ast.parse below instead
    return per_line, per_file


@dataclass
class SourceFile:
    """Parsed view of one file: AST, raw text, disable directives, and
    a child->parent node map (rules need lexical ancestry for loop /
    decorator / immediate-call context).  `nodes` is the full tree in
    ast.walk order, captured once at load — rules iterate it instead of
    re-walking, which keeps whole-repo lint time linear in rule count
    only through the (cheap) per-node isinstance checks."""

    path: str  # absolute
    rel: str   # repo-relative POSIX
    text: str
    tree: ast.AST
    disabled_lines: dict = field(default_factory=dict)
    disabled_file: set = field(default_factory=set)
    parents: dict = field(default_factory=dict)
    nodes: list = field(default_factory=list)

    @classmethod
    def load(cls, path: str, root: str) -> "SourceFile | None":
        with open(path, "rb") as f:
            raw = f.read()
        try:
            text = raw.decode("utf-8")
            tree = ast.parse(text, filename=path)
        except (SyntaxError, UnicodeDecodeError):
            return None  # not lintable; other gates own syntax errors
        per_line, per_file = _parse_disables(text)
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        src = cls(path=path, rel=rel, text=text, tree=tree,
                  disabled_lines=per_line, disabled_file=per_file)
        for parent in ast.walk(tree):
            src.nodes.append(parent)
            for child in ast.iter_child_nodes(parent):
                src.parents[child] = parent
        return src

    def ancestors(self, node):
        while node in self.parents:
            node = self.parents[node]
            yield node

    def suppressed(self, finding: Finding) -> bool:
        if finding.rule in self.disabled_file or "all" in self.disabled_file:
            return True
        rules = self.disabled_lines.get(finding.line, ())
        return finding.rule in rules or "all" in rules


@dataclass
class LintContext:
    """Cross-file facts rules resolve lazily: the repo root and the
    typed EVENT_FIELDS schema read from cpr_tpu/telemetry.py — by AST,
    not import, so the schema check needs no package (or jax) import."""

    root: str
    _event_fields: dict | None = None

    def event_fields(self) -> dict:
        if self._event_fields is None:
            self._event_fields = _read_event_fields(
                os.path.join(self.root, "cpr_tpu", "telemetry.py"))
        return self._event_fields


def _read_event_fields(telemetry_path: str) -> dict:
    """EVENT_FIELDS as a {name: (field, ...)} dict, or {} when the
    module or the assignment is missing (rule degrades to a no-op
    rather than inventing a schema)."""
    try:
        with open(telemetry_path, "rb") as f:
            tree = ast.parse(f.read(), filename=telemetry_path)
    except (OSError, SyntaxError):
        return {}
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "EVENT_FIELDS"):
            try:
                value = ast.literal_eval(node.value)
            except ValueError:
                return {}
            if isinstance(value, dict):
                return {str(k): tuple(v) for k, v in value.items()}
    return {}


def iter_source_files(paths, root: str):
    """Yield absolute paths of .py files under `paths` (files or
    directories, relative to `root`), skipping caches, in sorted order
    for deterministic output."""
    out = []
    for p in paths:
        p = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__"
                                 and not d.startswith("."))
            out.extend(os.path.join(dirpath, fn)
                       for fn in sorted(filenames) if fn.endswith(".py"))
    return sorted(set(out))


def load_baseline(path: str) -> set:
    """Grandfathered finding keys {(rule, path, line), ...} from a JSON
    baseline file (format: {"findings": [{rule, path, line}, ...]}) —
    the gate starts at zero NEW findings even on a tree with known
    debt.  Regenerate wholesale with `--write-baseline` (line numbers
    drift; hand-editing is not the workflow)."""
    with open(path) as f:
        data = json.load(f)
    return {(f_["rule"], f_["path"], int(f_["line"]))
            for f_ in data.get("findings", [])}


def run_lint(paths, root: str | None = None, disable=(),
             baseline: set | None = None) -> list[Finding]:
    """Lint `paths` with every registered rule except `disable`d ids;
    findings suppressed inline or present in `baseline` are dropped.
    Returns findings sorted by (path, line, rule)."""
    from cpr_tpu.analysis.rules import RULES

    root = os.path.abspath(root or _default_root())
    disable = set(disable)
    unknown = disable - {r.id for r in RULES}
    if unknown:
        raise ValueError(f"unknown rule id(s): {sorted(unknown)}")
    ctx = LintContext(root=root)
    rules = [r for r in RULES if r.id not in disable]
    findings: list[Finding] = []
    for path in iter_source_files(paths, root):
        src = SourceFile.load(path, root)
        if src is None:
            continue
        for rule in rules:
            for f in rule.check(src, ctx):
                if src.suppressed(f):
                    continue
                if baseline and f.key() in baseline:
                    continue
                findings.append(f)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def _default_root() -> str:
    # cpr_tpu/analysis/core.py -> repo root two levels up from cpr_tpu/
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
