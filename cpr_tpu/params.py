"""Environment parameters.

Mirror of the reference gym parameter record and its validation
(reference: simulator/gym/engine.ml:5-52) plus the defender-count derivation
from gamma (reference: gym/ocaml/cpr_gym/envs.py:70-82).

Unlike the reference (which validates once at env construction), parameters
here are a JAX PyTree so that batched environments can sweep (alpha, gamma)
grids inside one compiled kernel (`vmap` over EnvParams leaves).
"""

from __future__ import annotations

import math

import jax.numpy as jnp
from flax import struct


class ParameterError(ValueError):
    pass


@struct.dataclass
class EnvParams:
    """Selfish-mining environment parameters.

    alpha: attacker share of compute, 0 <= alpha <= 1.
    gamma: attacker network advantage, 0 <= gamma < 1. When the attacker
        matches a freshly arrived defender block, a `gamma` fraction of
        defender compute mines on the attacker's release.
    defenders: number of defender nodes the reference would instantiate;
        kept for parity of the derived quantities, the collapsed JAX engine
        models the defenders as one cloud (reference: simulator/gym/engine.ml:100-107
        uses near-zero propagation delay, which makes the cloud exact).
    activation_delay: mean time between puzzle solutions (difficulty).
    max_steps / max_progress / max_time: episode termination criteria
        (reference: simulator/gym/engine.ml:209-214).
    """

    alpha: jnp.ndarray  # float
    gamma: jnp.ndarray  # float
    defenders: jnp.ndarray  # int
    activation_delay: jnp.ndarray  # float
    max_steps: jnp.ndarray  # int
    max_progress: jnp.ndarray  # float
    max_time: jnp.ndarray  # float


def make_params(
    *,
    alpha: float,
    gamma: float,
    defenders: int | None = None,
    activation_delay: float = 1.0,
    max_steps: int | None = None,
    max_progress: float | None = None,
    max_time: float | None = None,
) -> EnvParams:
    """Validate and build EnvParams.

    Validation mirrors reference simulator/gym/engine.ml:37-51; the
    defenders-from-gamma rule mirrors gym/ocaml/cpr_gym/envs.py:70-82.
    """
    if math.isnan(activation_delay):
        raise ParameterError("activation_delay cannot be NaN")
    if math.isnan(alpha):
        raise ParameterError("alpha cannot be NaN")
    if math.isnan(gamma):
        raise ParameterError("gamma cannot be NaN")
    if alpha < 0.0 or alpha > 1.0:
        raise ParameterError("alpha < 0 || alpha > 1")
    if gamma < 0.0 or gamma > 1.0:
        raise ParameterError("gamma < 0 || gamma > 1")
    if activation_delay <= 0.0:
        raise ParameterError("activation_delay <= 0")
    if max_steps is None and max_progress is None and max_time is None:
        raise ParameterError(
            "set at least one of max_steps, max_progress, max_time"
        )
    if defenders is None:
        if gamma >= 1.0:
            raise ParameterError("gamma must be smaller than 1")
        defenders = max(2, int(math.ceil(1.0 / (1.0 - gamma))))
    if defenders < 1:
        raise ParameterError("defenders < 1")
    max_steps = max_steps if max_steps is not None else (1 << 30)
    max_progress = max_progress if max_progress is not None else float("inf")
    max_time = max_time if max_time is not None else float("inf")
    if max_steps <= 0:
        raise ParameterError("max_steps <= 0")
    if max_progress <= 0.0:
        raise ParameterError("max_progress <= 0")
    if max_time <= 0.0:
        raise ParameterError("max_time <= 0")
    return EnvParams(
        alpha=jnp.float32(alpha),
        gamma=jnp.float32(gamma),
        defenders=jnp.int32(defenders),
        activation_delay=jnp.float32(activation_delay),
        max_steps=jnp.int32(max_steps),
        max_progress=jnp.float32(max_progress),
        max_time=jnp.float32(max_time),
    )


def stack_params(kwargs_list) -> EnvParams:
    """Stack many make_params(**kwargs) into one EnvParams whose leaves
    carry a leading axis — the batched form consumed by vmap'd sweeps
    and per-lane schedule training."""
    import jax

    ps = [make_params(**kw) for kw in kwargs_list]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *ps)
