"""The perf ledger: banked bench rows normalized into one record shape.

Three artifact dialects feed it (everything `BENCH*.json` next to
bench.py):

* driver-round artifacts — `{"n": round, "tail": stderr, "parsed": row}`
  (one parsed row per round; the stderr tail is kept because pre-PR2
  rounds ran before CPU-fallback rows carried `outage` tags — a
  "falling back to CPU" marker in the tail backfills the tag, so r02
  and r05 can never become CPU baselines),
* config banks — `BENCH_CONFIGS*.json` row lists (round from the rNN
  filename suffix; the suffix-less current bank counts as newest),
* single-row banks — `BENCH_self_r*.json` style one-object files.

`iter_trace_rows` additionally lifts the span rates out of a telemetry
JSONL trace (`per_sec` counters under the stream's manifest backend),
so sweep/training traces land on the same trend surface as bench rows,
and the drain-time `serve` report events of the serving layer
(cpr_tpu/serve) as `serve_steps_per_sec` / `serve_occupancy` rows — a
serving session's sustained throughput is banked and gated exactly
like a bench row.

Ledger records (`ledger: 5` — v5 stamps the producing run's `run` id
on every record (trace-lifted rows inherit it from the stream's
manifest), so a gate verdict can name the exact runs it compared and
`perf_report --attribute` can chase a FAIL through the run archive
(cpr_tpu/perf/archive.py) into a trace diff.  v4 banks the
measurement's device span as `cfg_devices` in every config
fingerprint, so multi-chip rows (sharded serve/rollout/netsim lanes,
docs/SCALING.md) gate against their own per-device-count history
instead of drifting against single-device baselines.  Backfill-safe:
a row with no `n_devices` key measured one device and fingerprints as
cfg_devices=1.  v3 added the `direction` field so lower-is-better
metrics (latencies: `serve_p50_s`/`serve_p99_s`) gate correctly.
Like every earlier bump, v5 changed every row_id, and the ledger file
is regenerable scratch, so a pre-v5 ledger is simply deleted and
re-ingested rather than migrated):

    metric, backend, value, unit, check, round, source,
    direction ("higher" | "lower" — which way is better; inferred
    from the metric name unless the row says otherwise),
    outage, fallback_reason, error,
    probe (health-check row, never a measurement),
    restart_count (warm restarts preceding the measuring child),
    run (the producing run id, null when the source predates v8 run
    stamping — the archive key for attribution),
    config (prng/window/cfg_*), fingerprint (metric x config hash),
    time_utc / git_sha / device_kind (from the embedded manifest),
    row_id (content hash — ingestion dedup key)

The ledger file is append-only JSONL: `append` never edits or drops an
existing line, and every write goes through `resilience.atomic_write_text`
(tmp+fsync+rename — the jaxlint `raw-write` gate passes with no
waivers), so a crash mid-bank can never tear the history a later gate
judges against.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import re

from cpr_tpu.resilience import artifact_fault_point, atomic_write_text

LEDGER_VERSION = 5
LEDGER_ENV_VAR = "CPR_PERF_LEDGER"

# fallback_reason stamped onto rows whose artifact predates the outage
# tagging (PR 2) but whose stderr tail records the backend switch
INFERRED_FALLBACK = "inferred: artifact stderr tail records a CPU fallback"

_FALLBACK_MARKERS = ("falling back to CPU", "hung past")


def default_ledger_path(root: str) -> str:
    """$CPR_PERF_LEDGER, else `<root>/runs/perf_ledger.jsonl` (scratch:
    fully regenerable from the tracked banks, so gitignored)."""
    return (os.environ.get(LEDGER_ENV_VAR)
            or os.path.join(root, "runs", "perf_ledger.jsonl"))


def _digest(obj) -> str:
    return hashlib.sha1(
        json.dumps(obj, sort_keys=True, default=str).encode()).hexdigest()[:12]


def metric_direction(metric) -> str:
    """Which way is better for a metric: "higher" (throughputs,
    rates — the default) or "lower" (latencies/durations).  Inference
    follows the repo's naming convention — `*_s` metrics are seconds
    (serve_p50_s, serve_p99_s, compile_s...), everything else is a
    rate or count.  A row's explicit `direction` key overrides this
    (normalize_row)."""
    return "lower" if str(metric).endswith("_s") else "higher"


def config_fingerprint(metric: str, config: dict) -> str:
    """Stable hash of metric x measurement config — the ledger key that
    decides which banked rows are directly comparable.  A gate across
    differing fingerprints is still run (same backend trumps same
    batch size) but flagged `config_drift`."""
    return _digest({"metric": metric, **config})


def normalize_row(row: dict, *, source: str = "live",
                  rnd: int | None = None, tail_hint: bool = False) -> dict:
    """One bench row -> one ledger record.  `tail_hint` says the source
    artifact's stderr tail recorded a CPU fallback (outage backfill for
    pre-tagging rounds).  Error rows normalize too — the ledger is the
    full trail, eligibility is the gate's job."""
    metric = str(row.get("metric") or "")
    value = row.get("value")
    outage = bool(row.get("outage"))
    reason = row.get("fallback_reason")
    if not outage and tail_hint and row.get("backend") == "cpu":
        outage, reason = True, INFERRED_FALLBACK
    config = {k: row[k] for k in sorted(row) if k.startswith("cfg_")}
    for k in ("prng", "window"):
        if k in row:
            config[k] = row[k]
    # v4: the device span is part of the fingerprint — a 4-chip
    # serve/rollout/netsim rate is a different measurement from the
    # 1-chip one and must gate against its own history.  Rows banked
    # before multi-chip lanes carry no n_devices key and measured one
    # device, so the absent-key default of 1 is exact, not a guess.
    if "cfg_devices" not in config:
        nd = row.get("n_devices")
        config["cfg_devices"] = (int(nd)
                                 if isinstance(nd, (int, float)) and nd
                                 else 1)
    man = row.get("manifest") or {}
    direction = row.get("direction")
    if direction not in ("higher", "lower"):
        direction = metric_direction(metric)
    rec = {
        "ledger": LEDGER_VERSION,
        "metric": metric,
        "backend": row.get("backend"),
        # v3: which way is better — the gate flips its band for
        # "lower" so a p99 regression fails exactly like a
        # steps/sec drop (cpr_tpu/perf/gate.py)
        "direction": direction,
        "value": (float(value)
                  if isinstance(value, (int, float)) else None),
        "unit": row.get("unit"),
        "check": row.get("check"),
        "round": rnd,
        "source": source,
        "outage": outage,
        "fallback_reason": reason,
        "error": row.get("error"),
        # supervisor provenance (cpr_tpu/supervisor): probe rows are
        # device health checks, never measurements — the gate skips
        # them and they can never become baselines; rows measured
        # after a warm restart carry the count so a recovery-window
        # number stays distinguishable in the trail
        "probe": bool(row.get("probe")),
        "restart_count": (int(row["restart_count"])
                          if isinstance(row.get("restart_count"),
                                        (int, float)) else 0),
        # v5: the producing run id (manifest `run`, inherited through
        # $CPR_RUN_ID) — null for pre-v8 sources.  NOT part of the
        # fingerprint: which run measured a number never changes what
        # it is comparable against, it only makes the row resolvable
        # through the run archive for attribution.
        "run": (str(row["run"]) if row.get("run")
                else (str(man["run"]) if man.get("run") else None)),
        "config": config,
        "fingerprint": config_fingerprint(metric, config),
        "time_utc": man.get("time_utc"),
        "git_sha": man.get("git_sha"),
        "device_kind": man.get("device_kind"),
    }
    rec["row_id"] = _digest(rec)
    return rec


def _filename_round(base: str) -> int | None:
    m = re.search(r"r(\d+)", base)
    return int(m.group(1)) if m else None


def iter_bank_rows(root: str):
    """Yield (row, source, round, tail_hint) for every row banked in
    the `BENCH*.json` artifacts under `root` (rows without a `metric`
    key — e.g. a round that produced no parse — are skipped)."""
    for path in sorted(glob.glob(os.path.join(root, "BENCH*.json"))):
        base = os.path.basename(path)
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(data, dict) and "tail" in data:
            # driver-round artifact: one parsed row + the stderr tail
            rnd = data.get("n")
            rnd = int(rnd) if isinstance(rnd, int) else None
            tail = data.get("tail") or ""
            hint = any(m in tail for m in _FALLBACK_MARKERS)
            rows = [data.get("parsed")]
        else:
            rnd = _filename_round(base)
            hint = False
            rows = data if isinstance(data, list) else [data]
        for row in rows:
            if isinstance(row, dict) and row.get("metric"):
                yield row, base, rnd, hint


# serve report detail key -> (ledger metric, unit); rates in a report
# are over busy (dispatch) wall time — see ResidentEngine.report —
# and p50/p99 are the episode.run endpoint's total-latency quantiles
# (lower-is-better: metric_direction flips the gate band for them)
_SERVE_METRICS = (("steps_per_sec", "serve_steps_per_sec", "steps/sec"),
                  ("occupancy", "serve_occupancy", "fraction"),
                  ("p50_s", "serve_p50_s", "seconds"),
                  ("p99_s", "serve_p99_s", "seconds"))


def _memory_row(mem: dict, *, backend, run, config,
                extra: dict | None = None) -> dict:
    """One v15 memory watermark -> a `<scope>_peak_bytes` ledger row.
    Lower-is-better rides explicitly (the name carries no `_s` suffix
    — the serve_shed_rate precedent), and the sampling source joins
    the fingerprint: an RSS watermark is host-process memory and must
    never gate against a device-allocator one."""
    scope = re.sub(r"[^0-9A-Za-z]+", "_",
                   str(mem.get("scope") or "mem")).strip("_") or "mem"
    row = {"metric": f"{scope}_peak_bytes", "backend": backend,
           "run": run, "value": mem.get("peak_bytes"),
           "unit": "bytes", "direction": "lower",
           **{f"cfg_{k}": v for k, v in config.items()}}
    if mem.get("source"):
        row["cfg_mem_source"] = str(mem["source"])
    if extra:
        row.update(extra)
    return row


def iter_trace_rows(path: str):
    """Yield ledger-shaped rows from a telemetry JSONL trace: one per
    span carrying `per_sec` counters, metric `<span path>:<counter>`,
    up to four per `serve` report event (the serving layer's
    drain-time throughput + latency summary; _SERVE_METRICS), and a
    throughput + per-point-latency pair per `mdp_solve` event (grid-
    batched exact-MDP solves, schema v10), and a lower-is-better
    `<scope>_peak_bytes` row per v15 memory watermark (point event or
    serve drain report block); backend/config/run taken from the last
    manifest seen before the row (the stream layout every producer
    follows) — the run id is what lets `perf_report --attribute`
    resolve a banked number back to its archived trace."""
    base = os.path.basename(path)
    backend, config, run = None, {}, None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except ValueError:
                continue
            if e.get("kind") == "manifest":
                backend = e.get("backend")
                # v5: the stream's run id rides every lifted row, so a
                # banked rate resolves back to its archived trace
                if e.get("run"):
                    run = str(e["run"])
                config = {k: v for k, v in (e.get("config") or {}).items()
                          if isinstance(v, (str, int, float, bool))}
            elif e.get("kind") == "span" and e.get("per_sec"):
                for counter, rate in e["per_sec"].items():
                    yield ({"metric": f"{e.get('path')}:{counter}_per_sec",
                            "backend": backend, "run": run, "value": rate,
                            "unit": f"{counter}/sec",
                            **{f"cfg_{k}": v for k, v in config.items()}},
                           base)
            elif (e.get("kind") == "event" and e.get("name") == "serve"
                  and e.get("action") == "report"):
                detail = e.get("detail") or {}
                # the engine's own device span (report n_devices) is
                # authoritative for cfg_devices — stamped after the
                # manifest config spread so it wins over a stale
                # `devices` config key (ledger v4 fingerprints)
                nd = detail.get("n_devices")
                dev_cfg = ({"cfg_devices": int(nd)}
                           if isinstance(nd, (int, float)) and nd
                           else {})
                for key, metric, unit in _SERVE_METRICS:
                    value = detail.get(key)
                    if not isinstance(value, (int, float)):
                        continue
                    yield ({"metric": metric, "backend": backend,
                            "run": run, "value": value, "unit": unit,
                            **{f"cfg_{k}": v for k, v in config.items()},
                            **dev_cfg},
                           base)
                # per-priority-class tails: serve_p99_s rows tagged
                # cfg_class so each class gates against its own
                # banked history (distinct fingerprints)
                by_class = detail.get("class_p99_s")
                if isinstance(by_class, dict):
                    for cls, value in sorted(by_class.items()):
                        if not isinstance(value, (int, float)):
                            continue
                        yield ({"metric": "serve_p99_s",
                                "backend": backend, "run": run,
                                "value": value,
                                "unit": "seconds",
                                "cfg_class": str(cls),
                                **{f"cfg_{k}": v
                                   for k, v in config.items()},
                                **dev_cfg},
                               base)
                # admission-control shed rate: lower-is-better but the
                # name carries no `_s` suffix, so the direction rides
                # explicitly (normalize_row honors it)
                shed_rate = detail.get("shed_rate")
                if isinstance(shed_rate, (int, float)):
                    yield ({"metric": "serve_shed_rate",
                            "backend": backend, "run": run,
                            "value": shed_rate,
                            "unit": "fraction", "direction": "lower",
                            **{f"cfg_{k}": v for k, v in config.items()},
                            **dev_cfg},
                           base)
                # v17: the always-on learning plane's drain summary
                # (server _drain builds it when experience rings are
                # armed) — sampler throughput plus final snapshot
                # staleness (`_s` suffix: lower-is-better)
                learn = detail.get("learn")
                if isinstance(learn, dict):
                    for key, metric, unit in (
                            ("samples_per_sec",
                             "learn_samples_per_sec", "samples/sec"),
                            ("snapshot_staleness_s",
                             "learn_snapshot_staleness_s", "seconds")):
                        value = learn.get(key)
                        if not isinstance(value, (int, float)):
                            continue
                        yield ({"metric": metric, "backend": backend,
                                "run": run, "value": value,
                                "unit": unit,
                                **{f"cfg_{k}": v
                                   for k, v in config.items()},
                                **dev_cfg},
                               base)
                # v15: the serve memory watermark rides the drain
                # report (the `memory` point event is also lifted,
                # below — the report block covers streams cut before
                # the final event landed)
                mem = detail.get("memory")
                if isinstance(mem, dict) and isinstance(
                        mem.get("peak_bytes"), (int, float)):
                    yield (_memory_row(mem, backend=backend, run=run,
                                       config=config, extra=dev_cfg),
                           base)
            elif (e.get("kind") == "event" and e.get("name") == "serve"
                  and e.get("action") == "fleet_report"):
                # v14: the router's drain-time fleet merge — exact
                # bucket-sum of every replica's latency board — banks
                # one `fleet_p99_s` row per op-family, tagged
                # cfg_family so each family gates against its own
                # history (`_s` suffix: lower-is-better)
                fleet = (e.get("detail") or {}).get("fleet_p99_s")
                if isinstance(fleet, dict):
                    for family, value in sorted(fleet.items()):
                        if not isinstance(value, (int, float)):
                            continue
                        yield ({"metric": "fleet_p99_s",
                                "backend": backend, "run": run,
                                "value": value,
                                "unit": "seconds",
                                "cfg_family": str(family),
                                **{f"cfg_{k}": v
                                   for k, v in config.items()}},
                               base)
            elif (e.get("kind") == "event"
                  and e.get("name") == "mdp_solve"):
                # schema v10: grid-batched exact-MDP solves bank their
                # points/sec throughput and per-point solve latency
                # (`_s` suffix: lower-is-better via metric_direction),
                # fingerprinted by protocol/cutoff/grid shape and the
                # solve's own device count.  v13 adds state-sharded
                # solves: `state_shards` joins the fingerprint (only
                # when the event carries it, so pre-v13 row ids are
                # unchanged) and `states_per_sec` banks as its own
                # metric — a 1-shard sweep rate never gates against a
                # 4-shard one (the halo traffic alone moves it)
                grid = e.get("grid") or []
                mdp_cfg = {
                    **{f"cfg_{k}": v for k, v in config.items()},
                    "cfg_protocol": str(e.get("protocol")),
                    "cfg_cutoff": e.get("cutoff"),
                    "cfg_grid": "x".join(str(x) for x in grid),
                }
                nd = e.get("n_devices")
                if isinstance(nd, (int, float)) and nd:
                    mdp_cfg["cfg_devices"] = int(nd)
                # absent key fingerprints the same as 1 shard (the
                # gate's .get default), so unsharded rows banked
                # before v13 keep their row ids
                ns = e.get("state_shards")
                if isinstance(ns, (int, float)) and int(ns) > 1:
                    mdp_cfg["cfg_state_shards"] = int(ns)
                pps = e.get("points_per_sec")
                if isinstance(pps, (int, float)):
                    yield ({"metric": "mdp_grid_points_per_sec",
                            "backend": backend, "run": run, "value": pps,
                            "unit": "grid-points/sec", **mdp_cfg},
                           base)
                    solve_s = e.get("solve_s")
                    points = e.get("points")
                    if (isinstance(solve_s, (int, float))
                            and isinstance(points, int) and points > 0):
                        yield ({"metric": "mdp_grid_point_solve_s",
                                "backend": backend, "run": run,
                                "value": round(solve_s / points, 6),
                                "unit": "seconds", **mdp_cfg}, base)
                sps = e.get("states_per_sec")
                if isinstance(sps, (int, float)):
                    yield ({"metric": "mdp_states_per_sec",
                            "backend": backend, "run": run, "value": sps,
                            "unit": "states/sec", **mdp_cfg}, base)
            elif (e.get("kind") == "event"
                  and e.get("name") == "mdp_compile"):
                # schema v12: frontier-batched MDP compiles bank their
                # states/sec throughput, fingerprinted by protocol/
                # cutoff/worker count — a 1-worker compile never gates
                # against a 4-worker one, nor fc16@8 against
                # ghostdag@7
                sps = e.get("states_per_sec")
                if not isinstance(sps, (int, float)):
                    continue
                cmp_cfg = {
                    **{f"cfg_{k}": v for k, v in config.items()},
                    "cfg_protocol": str(e.get("protocol")),
                    "cfg_cutoff": e.get("cutoff"),
                    "cfg_workers": int(e.get("n_workers") or 1),
                }
                yield ({"metric": "mdp_compile_states_per_sec",
                        "backend": backend, "run": run, "value": sps,
                        "unit": "states/sec", **cmp_cfg}, base)
            elif (e.get("kind") == "event"
                  and e.get("name") == "attack_sweep"):
                # schema v11: adversary-in-the-network sweeps bank
                # their vmapped lane throughput, fingerprinted by
                # protocol/topology/sweep shape and the sweep's own
                # device count — a clique-4 sweep never gates against
                # a ring-6 one, nor an 8-lane grid against a 16-lane
                lps = e.get("lanes_per_sec")
                if not isinstance(lps, (int, float)):
                    continue
                atk_cfg = {
                    **{f"cfg_{k}": v for k, v in config.items()},
                    "cfg_protocol": str(e.get("protocol")),
                    "cfg_topology": str(e.get("topology")),
                }
                for shape_key in ("lanes", "activations"):
                    if isinstance(e.get(shape_key), (int, float)):
                        atk_cfg[f"cfg_{shape_key}"] = int(e[shape_key])
                nd = e.get("n_devices")
                if isinstance(nd, (int, float)) and nd:
                    atk_cfg["cfg_devices"] = int(nd)
                yield ({"metric": "attack_sweep_lanes_per_sec",
                        "backend": backend, "run": run, "value": lps,
                        "unit": "lanes/sec", **atk_cfg}, base)
            elif (e.get("kind") == "event"
                  and e.get("name") == "memory"):
                # schema v15: live memory watermarks bank one
                # lower-is-better `<scope>_peak_bytes` row apiece,
                # sitting next to the `vi_working_set_bytes`
                # prediction so claim meets measurement
                if isinstance(e.get("peak_bytes"), (int, float)):
                    yield (_memory_row(e, backend=backend, run=run,
                                       config=config),
                           base)


class Ledger:
    """Append-only JSONL ledger with content-addressed dedup and
    verify-on-read (v16): every row's `row_id` IS its content hash, so
    `records()` recomputes it and skips-and-reports any row whose bytes
    no longer match — one hand-edited or bit-flipped line can never
    become a gate baseline, and the skip is a typed `integrity` event,
    not a silent drop."""

    def __init__(self, path: str):
        self.path = path
        self._reported: set = set()

    def _skip(self, line_no: int, reason: str):
        from cpr_tpu.integrity import integrity_event
        key = (line_no, reason)
        if key in self._reported:
            return  # records() runs per append; report each line once
        self._reported.add(key)
        integrity_event(artifact=f"{self.path}:{line_no}",
                        kind="ledger_row", reason=reason,
                        action="quarantined")

    def records(self) -> list[dict]:
        out = []
        try:
            with open(self.path) as f:
                lines = f.readlines()
        except OSError:
            return out
        for i, line in enumerate(lines, 1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                # a torn line cannot happen (atomic writes); a
                # hand-edited one must not wedge — skip and report
                self._skip(i, "truncated")
                continue
            rid = row.get("row_id") if isinstance(row, dict) else None
            if rid is not None:
                from cpr_tpu.integrity import row_digest
                if row_digest(row) != rid:
                    self._skip(i, "checksum")
                    continue
            out.append(row)
        return out

    def append(self, records) -> int:
        """Append the not-yet-banked records (row_id dedup) and return
        how many were new.  Existing lines are preserved verbatim —
        the ledger is append-only by construction."""
        try:
            with open(self.path) as f:
                existing = f.read()
        except OSError:
            existing = ""
        seen = {r.get("row_id") for r in self.records()}
        fresh = [r for r in records
                 if r.get("row_id") and r["row_id"] not in seen]
        if not fresh:
            return 0
        lines = "".join(json.dumps(r, sort_keys=True) + "\n"
                        for r in fresh)
        atomic_write_text(self.path, existing + lines)
        # chaos seam: corrupt@ledger / truncate@ledger / garble_json@
        # ledger damage the just-banked file — verify-on-read above is
        # what must catch it
        artifact_fault_point("ledger", self.path)
        return len(fresh)

    def ingest_banks(self, root: str) -> int:
        """Normalize + bank every `BENCH*.json` row under `root`;
        idempotent (re-running adds nothing)."""
        return self.append([
            normalize_row(row, source=src, rnd=rnd, tail_hint=hint)
            for row, src, rnd, hint in iter_bank_rows(root)])

    def ingest_trace(self, path: str) -> int:
        """Bank the span rates of one telemetry JSONL trace."""
        return self.append([normalize_row(row, source=src)
                            for row, src in iter_trace_rows(path)])
