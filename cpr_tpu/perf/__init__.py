"""Perf ledger + runtime regression gate over the banked bench trail.

ROADMAP item 5's runtime half: the repo banks a `BENCH_*.json` /
device-metrics / telemetry trail every driver round, but until this
package nothing ever read it back — two of five rounds (r02, r05)
silently delivered CPU numbers, and a same-backend steps/sec drop
would have sailed through unnoticed.  Following the throughput
accounting discipline of accelerated-RL systems (arXiv:1803.02811's
sampler/learner rate tracking), the trail becomes a first-class,
queryable, *gating* observability surface:

* `ledger` — normalizes every banked bench row (driver-round
  artifacts, `BENCH_CONFIGS*` row lists, single-row banks), its
  embedded run manifest, and optionally the span rates of a telemetry
  trace into one schema-versioned record shape, keyed by
  metric x backend x config fingerprint, persisted as an append-only
  JSONL ledger.  All writes go through `resilience.atomic_*` (the
  jaxlint `raw-write` rule holds with no waivers); ingestion is
  idempotent (content-addressed `row_id` dedup).

* `gate` — compares a fresh row against the best same-backend banked
  rows using robust statistics (median/MAD band over the top-k values;
  `outage`/`fallback_reason` and error rows are never baselines) and
  emits a typed `perf_gate` telemetry event (schema v5) carrying the
  pass/warn/fail verdict and the baseline it judged against.  A
  CPU-fallback row is never gated against a TPU baseline: backends
  never mix, and an outage row is skipped outright (the `tpu_outage`
  event already tags it).

Consumers: `bench.py` banks and self-gates every row it prints
(advisory — the bench must always deliver a number);
`tools/perf_report.py` renders trend tables / a markdown report and
returns a nonzero exit code in `--gate` mode (`make perf-gate`);
docs/OBSERVABILITY.md documents verdict bands and how to bless an
intentional perf change.

Import-time this package is jax-free (like telemetry/resilience), so
bench.py's watchdog parent can bank rows without initializing a
backend.
"""

from cpr_tpu.perf import archive
from cpr_tpu.perf.archive import (ARCHIVE_ENV_VAR, archive_dir,
                                  archive_run, find_runs, load_run,
                                  primary_stream, run_streams)
from cpr_tpu.perf.gate import (baseline_rows, emit_gate_event, gate_row,
                               gate_summary)
from cpr_tpu.perf.ledger import (LEDGER_ENV_VAR, LEDGER_VERSION, Ledger,
                                 config_fingerprint, default_ledger_path,
                                 iter_bank_rows, iter_trace_rows,
                                 metric_direction, normalize_row)

__all__ = [
    "ARCHIVE_ENV_VAR",
    "LEDGER_ENV_VAR",
    "LEDGER_VERSION",
    "Ledger",
    "archive",
    "archive_dir",
    "archive_run",
    "bank_and_gate",
    "baseline_rows",
    "config_fingerprint",
    "default_ledger_path",
    "emit_gate_event",
    "find_runs",
    "gate_row",
    "gate_summary",
    "iter_bank_rows",
    "iter_trace_rows",
    "load_run",
    "metric_direction",
    "normalize_row",
    "primary_stream",
    "run_streams",
]


def bank_and_gate(row: dict, root: str, *, source: str = "live",
                  ledger_path: str | None = None) -> dict:
    """Bank one fresh bench row and self-gate it: ingest the tracked
    banks under `root` into the ledger (idempotent), gate `row` against
    the banked history, append it, and emit the `perf_gate` event.
    Returns the gate result — the caller decides what a verdict means
    (bench.py only reports; tools/perf_report.py --gate enforces)."""
    ledger = Ledger(ledger_path or default_ledger_path(root))
    ledger.ingest_banks(root)
    rec = normalize_row(row, source=source)
    result = gate_row(rec, ledger.records())
    ledger.append([rec])
    emit_gate_event(result)
    return result
