"""Run archive: content-addressed per-run artifact records (v15).

Every observability surface before this module answered questions
about ONE run: a telemetry stream summarizes, a ledger row gates, a
blackbox dumps.  Cross-run attribution — "what changed between the
run that passed and the run that failed?" — needs the runs themselves
to be findable after the fact, which they were not: a run's artifacts
(the server stream, the supervisor child streams, client streams,
blackbox dumps, banked ledger rows) scatter across scratch dirs keyed
only by the `run_id` buried in their manifests.

This module indexes them.  `archive_run()` scans a set of paths (or
discovers streams by run id), classifies each artifact, content-hashes
it, and writes one per-run record under `runs/archive/` (override:
`$CPR_OBS_ARCHIVE`) — an atomic JSON file plus an append-only
`index.jsonl` audit line.  `find_runs()`/`load_run()` query by
run id, git SHA, config fingerprint, or time window; `run_streams()`
hands the telemetry paths back to the consumers that learned to read
the archive: `tools/trace_summary.py`, `tools/trace_stitch.py`,
`tools/trace_diff.py`, and `perf_report --attribute` (which chases a
v15 `perf_gate` verdict's `run`/`baseline_runs` ids into a culprit
span table).

Like ledger/latency, jax-free at import; every record write goes
through `resilience.atomic_write_json` (the `index.jsonl` audit trail
appends, which the raw-write rule exempts).
"""

from __future__ import annotations

import hashlib
import json
import os
from datetime import datetime, timezone

from cpr_tpu import resilience

ARCHIVE_VERSION = 1
ARCHIVE_ENV_VAR = "CPR_OBS_ARCHIVE"
DEFAULT_ARCHIVE_DIR = os.path.join("runs", "archive")

# artifact kinds a record distinguishes (everything else is "file")
KIND_TELEMETRY = "telemetry"
KIND_BLACKBOX = "blackbox"
KIND_LEDGER = "ledger"
KIND_FILE = "file"


def archive_dir(root: str | None = None) -> str:
    """The archive root: explicit arg, else $CPR_OBS_ARCHIVE, else
    runs/archive."""
    return (root or os.environ.get(ARCHIVE_ENV_VAR)
            or DEFAULT_ARCHIVE_DIR)


def record_path(run: str, root: str | None = None) -> str:
    return os.path.join(archive_dir(root), f"run-{run}.json")


def index_path(root: str | None = None) -> str:
    return os.path.join(archive_dir(root), "index.jsonl")


def config_fingerprint(config: dict | None) -> str | None:
    """Stable fingerprint of a manifest's resolved config dict — the
    archive's cross-run "same setup?" key (the ledger fingerprints
    metric x cfg_* instead; this one is config-only so two runs of
    different metrics still match)."""
    if not config:
        return None
    blob = json.dumps(config, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()[:16]


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            h.update(chunk)
    return h.hexdigest()


def scan_stream(path: str) -> dict:
    """One pass over a JSONL artifact: run ids, manifest metadata
    (git_sha / backend / config / time window), span + event tallies.
    Malformed lines are skipped, never fatal — a truncated stream from
    a crashed child is exactly what the archive must still index."""
    runs: list[str] = []
    git_shas: list[str] = []
    backends: list[str] = []
    configs: list[dict] = []
    times: list[str] = []
    n_events = n_spans = n_manifests = n_lines = 0
    events: dict[str, int] = {}
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                n_lines += 1
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(e, dict):
                    continue
                kind = e.get("kind")
                if kind == "manifest":
                    n_manifests += 1
                    if e.get("run") and e["run"] not in runs:
                        runs.append(str(e["run"]))
                    if e.get("git_sha") and e["git_sha"] not in git_shas:
                        git_shas.append(str(e["git_sha"]))
                    if e.get("backend") and e["backend"] not in backends:
                        backends.append(str(e["backend"]))
                    if isinstance(e.get("config"), dict):
                        configs.append(e["config"])
                    if e.get("time_utc"):
                        times.append(str(e["time_utc"]))
                elif kind == "span":
                    n_spans += 1
                elif kind == "event":
                    n_events += 1
                    nm = str(e.get("name") or "?")
                    events[nm] = events.get(nm, 0) + 1
    except OSError:
        pass
    return {"runs": runs, "git_shas": git_shas, "backends": backends,
            "configs": configs, "n_lines": n_lines,
            "n_manifests": n_manifests, "n_spans": n_spans,
            "n_events": n_events, "events": events,
            "time_first": times[0] if times else None,
            "time_last": times[-1] if times else None}


def classify(path: str, scan: dict) -> str:
    """Artifact kind from filename + contents."""
    base = os.path.basename(path)
    if base.startswith("blackbox-"):
        return KIND_BLACKBOX
    if "ledger" in base and base.endswith(".jsonl"):
        return KIND_LEDGER
    if scan["n_manifests"] or scan["n_spans"] or scan["n_events"]:
        return KIND_TELEMETRY
    return KIND_FILE


def _artifact(path: str, role: str | None = None) -> dict | None:
    """One artifact entry: content hash, size, kind, stream stats."""
    path = os.path.abspath(path)
    try:
        size = os.path.getsize(path)
        digest = _sha256(path)
    except OSError:
        return None
    scan = scan_stream(path) if path.endswith((".jsonl", ".json")) \
        else {"runs": [], "git_shas": [], "backends": [], "configs": [],
              "n_lines": 0, "n_manifests": 0, "n_spans": 0,
              "n_events": 0, "events": {}, "time_first": None,
              "time_last": None}
    art = {"path": path, "kind": classify(path, scan),
           "sha256": digest, "bytes": size,
           "runs": scan["runs"], "n_spans": scan["n_spans"],
           "n_events": scan["n_events"], "events": scan["events"],
           "_scan": scan}
    if role:
        art["role"] = role
    return art


def discover_artifacts(search_dirs, run: str) -> list[str]:
    """Walk `search_dirs` for JSONL artifacts that belong to `run`:
    telemetry streams whose manifests carry the run id, and blackbox
    dumps named `blackbox-<run>-*.jsonl`.  This is how a post-hoc
    archive pass finds the supervisor-child and client streams the
    archiving process never opened itself."""
    found: list[str] = []
    for d in search_dirs:
        if not os.path.isdir(d):
            continue
        for base, _dirs, files in os.walk(d):
            for name in sorted(files):
                if not name.endswith(".jsonl"):
                    continue
                p = os.path.join(base, name)
                if name.startswith(f"blackbox-{run}-"):
                    found.append(p)
                    continue
                if run in scan_stream(p)["runs"]:
                    found.append(p)
    return found


def archive_run(paths=(), *, run: str | None = None,
                root: str | None = None, search_dirs=(),
                roles: dict | None = None,
                label: str | None = None,
                extra: dict | None = None) -> dict:
    """Index one run's artifacts into the archive.  `paths` are
    explicit artifact files; `search_dirs` are additionally walked for
    streams carrying the run id (discovery needs `run`, or a run id
    resolvable from the explicit paths' manifests).  Re-archiving the
    same run merges artifacts by content hash — the record converges,
    the index stays append-only (latest line wins on read).  Returns
    the written record."""
    roles = roles or {}
    arts: list[dict] = []
    for p in paths:
        a = _artifact(p, roles.get(p) or roles.get(os.path.abspath(p)))
        if a is not None:
            arts.append(a)
    if run is None:
        for a in arts:
            if a["runs"]:
                run = a["runs"][0]
                break
    if run is None:
        raise ValueError("archive_run: no run id — pass run= or at "
                         "least one stream whose manifest carries one")
    known = {a["sha256"] for a in arts}
    for p in discover_artifacts(search_dirs, run):
        a = _artifact(p, roles.get(p) or roles.get(os.path.abspath(p)))
        if a is not None and a["sha256"] not in known:
            known.add(a["sha256"])
            arts.append(a)
    # record-level metadata from the first manifest-bearing artifact
    git_sha = backend = fingerprint = None
    config = None
    time_utc = None
    for a in arts:
        scan = a["_scan"]
        if git_sha is None and scan["git_shas"]:
            git_sha = scan["git_shas"][0]
        if backend is None and scan["backends"]:
            backend = scan["backends"][0]
        if config is None and scan["configs"]:
            config = scan["configs"][0]
            fingerprint = config_fingerprint(config)
        if time_utc is None and scan["time_first"]:
            time_utc = scan["time_first"]
    for a in arts:
        a.pop("_scan", None)
    rec = {
        "archive": ARCHIVE_VERSION,
        "run": run,
        "time_utc": time_utc or datetime.now(timezone.utc).isoformat(
            timespec="seconds"),
        "git_sha": git_sha,
        "backend": backend,
        "fingerprint": fingerprint,
        "config": config,
        "artifacts": arts,
    }
    if label:
        rec["label"] = label
    if extra:
        rec["extra"] = extra
    # merge with any existing record for this run (idempotent: same
    # artifacts dedup by content hash; label/extra: newest wins)
    prev = load_run(run, root)
    if prev:
        seen = {a["sha256"] for a in arts}
        for a in prev.get("artifacts", ()):
            if a.get("sha256") not in seen:
                seen.add(a.get("sha256"))
                arts.append(a)
        for k in ("git_sha", "backend", "fingerprint", "config",
                  "label", "extra"):
            if rec.get(k) is None and prev.get(k) is not None:
                rec[k] = prev[k]
        if prev.get("time_utc") and (not time_utc
                                     or prev["time_utc"] < time_utc):
            rec["time_utc"] = prev["time_utc"]
    # v16 verify-on-read: the record carries its own content digest, so
    # a hand-edited or bit-flipped record is detected (and skipped with
    # a typed `integrity` event) instead of silently steering
    # attribution at a wrong trace
    rec["record_sha256"] = _record_digest(rec)
    resilience.atomic_write_json(record_path(run, root), rec)
    # append-only audit line (append mode: raw-write exempt, and an
    # append can at worst tear its own line, never the trail)
    idx = index_path(root)
    os.makedirs(os.path.dirname(idx) or ".", exist_ok=True)
    with open(idx, "a") as f:
        f.write(json.dumps({
            "run": run, "time_utc": rec["time_utc"],
            "git_sha": git_sha, "fingerprint": fingerprint,
            "n_artifacts": len(arts),
            "record": os.path.basename(record_path(run, root)),
        }, default=str) + "\n")
        f.flush()
    # what we just wrote is what a verified read returns
    return dict(rec, integrity="verified")


def _record_digest(rec: dict) -> str:
    """Content digest of a record minus its own seal fields — stable
    across the JSON round trip (sorted keys, default=str exactly as
    the writer serialized)."""
    body = {k: v for k, v in rec.items()
            if k not in ("record_sha256", "integrity")}
    return hashlib.sha256(json.dumps(
        body, sort_keys=True, default=str).encode()).hexdigest()


def _verified_record(path: str) -> dict | None:
    """Parse + verify one record file.  Returns the record tagged
    `integrity: "verified"` (digest matched) or `"unverified"`
    (pre-v19 record with no digest); a record that fails to parse or
    contradicts its digest is quarantined, reported as a typed
    `integrity` event, and skipped (None) — the run-archive twin of
    the ledger's skip-and-report."""
    from cpr_tpu import integrity
    try:
        with open(path) as f:
            rec = json.load(f)
    except OSError:
        return None
    except ValueError:
        integrity.quarantine(path, kind="archive_record",
                             reason="truncated", action="quarantined",
                             sidecars=())
        return None
    if not isinstance(rec, dict):
        return None
    expected = rec.get("record_sha256")
    if expected is None:
        return dict(rec, integrity="unverified")
    if _record_digest(rec) != expected:
        integrity.quarantine(path, kind="archive_record",
                             reason="checksum", action="quarantined",
                             sidecars=())
        return None
    return dict(rec, integrity="verified")


def load_run(run: str, root: str | None = None) -> dict | None:
    """The archived record for one run id, or None."""
    return _verified_record(record_path(run, root))


def find_runs(root: str | None = None, *, run: str | None = None,
              git_sha: str | None = None,
              fingerprint: str | None = None,
              since: str | None = None,
              until: str | None = None) -> list[dict]:
    """Query the archive.  Filters AND together; `since`/`until` are
    ISO-8601 UTC strings compared lexicographically against each
    record's `time_utc` (the format run_manifest stamps).  `git_sha`
    matches by prefix, so a short SHA works.  Results sort newest
    first."""
    d = archive_dir(root)
    out: list[dict] = []
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("run-") and name.endswith(".json")):
            continue
        rec = _verified_record(os.path.join(d, name))
        if rec is None or "run" not in rec:
            continue
        if run is not None and rec.get("run") != run:
            continue
        if git_sha is not None and not str(
                rec.get("git_sha") or "").startswith(git_sha):
            continue
        if fingerprint is not None \
                and rec.get("fingerprint") != fingerprint:
            continue
        t = str(rec.get("time_utc") or "")
        if since is not None and t < since:
            continue
        if until is not None and t > until:
            continue
        out.append(rec)
    out.sort(key=lambda r: str(r.get("time_utc") or ""), reverse=True)
    return out


def run_streams(rec: dict, kind: str = KIND_TELEMETRY,
                role: str | None = None) -> list[str]:
    """Artifact paths of one kind (existing files only — the archive
    records scratch artifacts, which may have been cleaned)."""
    out = []
    for a in rec.get("artifacts", ()):
        if a.get("kind") != kind:
            continue
        if role is not None and a.get("role") != role:
            continue
        p = a.get("path")
        if p and os.path.exists(p):
            out.append(p)
    return out


def primary_stream(rec: dict) -> str | None:
    """The run's most span-rich telemetry stream — the default side of
    a trace diff (role "server" wins outright when labeled)."""
    best, best_key = None, (-1, -1)
    for a in rec.get("artifacts", ()):
        if a.get("kind") != KIND_TELEMETRY:
            continue
        p = a.get("path")
        if not (p and os.path.exists(p)):
            continue
        key = (1 if a.get("role") == "server" else 0,
               int(a.get("n_spans") or 0))
        if key > best_key:
            best, best_key = p, key
    return best
