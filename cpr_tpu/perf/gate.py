"""Runtime perf-regression detection over ledger records.

Verdict semantics (docs/OBSERVABILITY.md "Perf ledger & regression
gate"):

* The baseline is the **best** same-backend, same-metric banked rows —
  top-k by value, preferring rows with the candidate's exact config
  fingerprint (falling back to same-backend rows at the SAME
  `cfg_devices` with `config_drift` flagged, so a batch-size change is
  still gated but self-describes as not like-for-like; the fallback
  never crosses a device-count boundary — a 4-chip rate judged
  against 1-chip history would re-create exactly the drift ledger v4's
  cfg_devices fingerprints exist to prevent).
* Every metric has a **direction** (ledger v3): "higher" is better
  for throughputs (the default), "lower" for latencies
  (`serve_p50_s`/`serve_p99_s`, anything `*_s`).  For "lower" the
  best rows are the *lowest* values and the band flips — a value
  *above* `med + max(frac * med, noise)` warns/fails, so a p99
  regression trips the gate exactly like a steps/sec drop.
* `outage`/`fallback_reason` rows and error rows are **never**
  baselines: a CPU number delivered during a chip outage is a fact
  about the outage, not about the code.  Supervisor `probe` rows
  (cpr_tpu/supervisor health checks) are likewise never baselines and
  skip the gate entirely — a tiny-jit liveness check measures nothing.
* The band is robust: median/MAD over the baseline pool.  A drop
  deeper than `max(warn_frac * median, mad_k * 1.4826 * MAD)` warns;
  deeper than the `fail_frac` analog fails.  The MAD term keeps a
  noisy history (e.g. a 15x round-over-round improvement trail) from
  flagging every honest fluctuation; the fractional floor keeps a
  suspiciously-quiet history from flagging sub-noise jitter.
* A candidate that is itself an outage/error row is `skip`ped, never
  judged: gating a CPU-fallback value against anything would re-create
  exactly the r05 misread the outage tags exist to prevent, and the
  `tpu_outage` event already marks the stream.  Backends never mix —
  a CPU row is only ever compared to CPU history.

Every gate emits a typed `perf_gate` telemetry event (schema v5:
metric, backend, verdict, value, baseline; v15 adds `run` +
`baseline_runs` — the candidate's and baseline rows' run ids) so the
verdict is part of the same post-mortem trail the bench rows live in,
and a FAIL/WARN can be chased through the run archive into the exact
candidate/baseline trace pair (`perf_report --attribute` →
`tools/trace_diff.py`).
"""

from __future__ import annotations

from cpr_tpu import telemetry
from cpr_tpu.perf.ledger import metric_direction

# verdict band defaults: fractions of the baseline median a drop must
# exceed, and the MAD multiplier that widens the band on noisy history
WARN_FRAC = 0.10
FAIL_FRAC = 0.25
MAD_K = 4.0
TOP_K = 5

# MAD -> sigma-equivalent scale for normally-distributed noise
_MAD_SCALE = 1.4826


def _median(vals):
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def baseline_rows(records, metric: str, backend) -> list[dict]:
    """The gate-eligible history for metric x backend: same backend
    only (a CPU-fallback row is never judged against a TPU baseline),
    no outage/fallback rows, no error rows, no supervisor probe rows
    (a tiny-jit health check measures liveness, not throughput),
    positive numeric value."""
    return [r for r in records
            if r.get("metric") == metric and r.get("backend") == backend
            and not r.get("outage") and not r.get("error")
            and not r.get("probe")
            and isinstance(r.get("value"), (int, float))
            and r["value"] > 0]


def gate_row(candidate: dict, history, *, top_k: int = TOP_K,
             warn_frac: float = WARN_FRAC, fail_frac: float = FAIL_FRAC,
             mad_k: float = MAD_K) -> dict:
    """Judge one ledger record against the banked history.  Returns
    {verdict: pass|warn|fail|skip, metric, backend, value, direction,
    baseline, config_drift, reason}; `baseline` names the rows judged
    against (median/mad/n/best/best_source/thresholds) or None."""
    direction = candidate.get("direction")
    if direction not in ("higher", "lower"):
        direction = metric_direction(candidate.get("metric"))
    result = {
        "metric": candidate.get("metric"),
        "backend": candidate.get("backend"),
        "value": candidate.get("value"),
        "direction": direction,
        "verdict": "pass",
        "baseline": None,
        # v15 attribution plane: the candidate's run id and the run
        # ids behind the baseline rows ride the verdict, so a
        # FAIL/WARN resolves through the run archive into an exact
        # A/B trace pair for tools/trace_diff.py
        "run": candidate.get("run"),
        "baseline_runs": [],
        "config_drift": False,
        "reason": "",
    }
    if candidate.get("error"):
        result.update(verdict="skip",
                      reason="error row: nothing to gate")
        return result
    if candidate.get("probe"):
        result.update(verdict="skip", reason=(
            "supervisor probe row: a device health check, not a "
            "measurement — never gated, never a baseline"))
        return result
    if candidate.get("outage"):
        result.update(verdict="skip", reason=(
            "outage/fallback row: not gated (the tpu_outage tag "
            "already explains it; a fallback value judged against "
            "healthy history would only re-create the r05 misread)"))
        return result
    if not isinstance(candidate.get("value"), (int, float)):
        result.update(verdict="skip", reason="row carries no value")
        return result
    pool = [r for r in baseline_rows(history, candidate["metric"],
                                     candidate["backend"])
            if r.get("row_id") != candidate.get("row_id")]
    if not pool:
        result["reason"] = ("no same-backend baseline banked yet "
                            "(first measurement)")
        return result
    same_fp = [r for r in pool
               if r.get("fingerprint") == candidate.get("fingerprint")]
    if same_fp:
        pool = same_fp
    else:
        # ledger v4: the drift fallback never crosses a device-count
        # boundary — judging a 4-chip rate against 1-chip history (or
        # vice versa) is the exact misread cfg_devices exists to
        # prevent, so an off-count candidate with no same-count
        # history passes as a first measurement instead.  Same logic
        # for cfg_workers (frontier compiles: a 1-worker rate must
        # never gate a 4-worker one) and cfg_state_shards (state-
        # sharded VI: the per-sweep halo exchange alone moves the
        # sweep rate across shard counts).
        devs = lambda r: ((r.get("config") or {}).get("cfg_devices", 1),  # noqa: E731
                          (r.get("config") or {}).get("cfg_workers", 1),
                          (r.get("config") or {}).get("cfg_state_shards", 1))
        pool = [r for r in pool if devs(r) == devs(candidate)]
        if not pool:
            dd, dw, ds = devs(candidate)
            result["reason"] = (
                "no same-device/worker/state-shard-count baseline "
                f"banked yet (first measurement at cfg_devices={dd}, "
                f"cfg_workers={dw}, cfg_state_shards={ds})")
            return result
        result["config_drift"] = True
    lower = direction == "lower"
    # "best" is the top of the trail in the metric's own direction:
    # highest throughputs, lowest latencies
    best = sorted(pool, key=lambda r: r["value"] if lower
                  else -r["value"])[:top_k]
    vals = [r["value"] for r in best]
    med = _median(vals)
    mad = _median([abs(v - med) for v in vals])
    noise = mad_k * _MAD_SCALE * mad
    value = float(candidate["value"])
    baseline = {
        "median": med, "mad": mad, "n": len(vals),
        "best": best[0]["value"],
        "best_source": best[0].get("source"),
        "best_round": best[0].get("round"),
        "best_run": best[0].get("run"),
    }
    seen_runs = []
    for r in best:
        if r.get("run") and r["run"] not in seen_runs:
            seen_runs.append(r["run"])
    result["baseline_runs"] = seen_runs
    if lower:
        warn_above = med + max(warn_frac * med, noise)
        fail_above = med + max(fail_frac * med, noise)
        verdict = ("fail" if value > fail_above
                   else "warn" if value > warn_above else "pass")
        baseline.update(warn_above=warn_above, fail_above=fail_above)
        band_txt = (f"warn>{warn_above:.6g} fail>{fail_above:.6g}")
    else:
        warn_below = med - max(warn_frac * med, noise)
        fail_below = med - max(fail_frac * med, noise)
        verdict = ("fail" if value < fail_below
                   else "warn" if value < warn_below else "pass")
        baseline.update(warn_below=warn_below, fail_below=fail_below)
        band_txt = (f"warn<{warn_below:.6g} fail<{fail_below:.6g}")
    result.update(
        verdict=verdict,
        baseline=baseline,
        reason=(f"value {value:.6g} vs median-of-best {med:.6g} "
                f"({value / med - 1.0:+.1%}, {direction} is better; "
                f"{band_txt}"
                + (", config drifted from baseline"
                   if result["config_drift"] else "") + ")"))
    return result


def emit_gate_event(result: dict):
    """Emit the typed `perf_gate` event for one verdict (schema v5;
    v15 adds the candidate/baseline run ids for archive chase)."""
    telemetry.current().event(
        "perf_gate", metric=result["metric"], backend=result["backend"],
        verdict=result["verdict"], value=result["value"],
        baseline=result["baseline"], run=result.get("run"),
        baseline_runs=result.get("baseline_runs") or [],
        config_drift=result["config_drift"],
        direction=result.get("direction"), reason=result["reason"])


def gate_summary(results) -> dict:
    """Tally verdicts: {pass: n, warn: n, fail: n, skip: n, ok: bool}
    — `ok` is False iff any gate failed (the `--gate` exit code)."""
    counts = {"pass": 0, "warn": 0, "fail": 0, "skip": 0}
    for r in results:
        counts[r["verdict"]] = counts.get(r["verdict"], 0) + 1
    counts["ok"] = counts["fail"] == 0
    return counts
