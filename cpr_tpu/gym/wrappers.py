"""Reward/observation/schedule wrappers for the gymnasium adapter.

Reference counterpart: gym/ocaml/cpr_gym/wrappers.py:8-289, ported to the
gymnasium 5-tuple step API (terminated/truncated).  Episode end means
`terminated or truncated` throughout.
"""

from __future__ import annotations

import collections
import itertools
import warnings

import gymnasium
import numpy as np


class CprWrapper(gymnasium.Wrapper):
    """Shared base: forwards the `policy` dispatch the reference Core
    exposes (envs.py:58-66) through wrapper stacks — gymnasium 1.x no
    longer auto-forwards attributes."""

    def policy(self, obs, name="honest"):
        return self.env.policy(obs, name)


class SparseRelativeRewardWrapper(CprWrapper):
    """Zero reward until episode end, then attacker/(attacker+defender)
    (wrappers.py:8-26)."""

    def step(self, action):
        obs, _r, term, trunc, info = self.env.step(action)
        reward = 0.0
        if term or trunc:
            a = info["episode_reward_attacker"]
            d = info["episode_reward_defender"]
            reward = a / (a + d) if (a + d) != 0 else 0.0
        return obs, reward, term, trunc, info


class SparseRewardPerProgressWrapper(CprWrapper):
    """Zero reward until episode end, then attacker/progress
    (wrappers.py:29-51) — the right objective for protocols with dynamic
    rewards (Ethereum, Tailstorm discount)."""

    def step(self, action):
        obs, _r, term, trunc, info = self.env.step(action)
        reward = 0.0
        if term or trunc:
            p = info["episode_progress"]
            reward = info["episode_reward_attacker"] / p if p != 0 else 0.0
        return obs, reward, term, trunc, info


class DenseRewardPerProgressWrapper(CprWrapper):
    """Dense per-step attacker reward normalized by a progress target;
    episodes end at that target so the divisor is known upfront, and the
    end-of-episode mismatch is corrected (wrappers.py:54-113)."""

    def __init__(self, env, episode_len: int):
        super().__init__(env)
        self.drpb_max_progress = episode_len
        self.drpb_factor = 1.0 / episode_len
        ck = self.env.unwrapped.core_kwargs
        want = {"max_time": None, "max_steps": episode_len * 100,
                "max_progress": episode_len}
        for k, v in want.items():
            if ck.get(k) is not None and ck[k] != v:
                warnings.warn(
                    f"DenseRewardPerProgressWrapper overwrites '{k}'")
            ck[k] = v

    def reset(self, **kwargs):
        self.drpb_acc = 0.0
        return self.env.reset(**kwargs)

    def step(self, action):
        obs, reward, term, trunc, info = self.env.step(action)
        reward = info["step_reward_attacker"] * self.drpb_factor
        self.drpb_acc += reward
        if term or trunc:
            got = info["episode_progress"]
            want = self.drpb_max_progress
            if got < want:
                warnings.warn(f"observed too little progress: {got}/{want}")
            if got > want * 1.1:
                warnings.warn(f"observed too much progress: {got}/{want}")
            if got != want and got != 0:
                reward += (want - got) * self.drpb_acc / got
        return obs, reward, term, trunc, info


class ExtendObservationWrapper(CprWrapper):
    """Append info-derived fields to the observation (wrappers.py:116-153).
    `fields` is a list of (fn(wrapper, info), low, high, default)."""

    def __init__(self, env, fields):
        super().__init__(env)
        if not fields:
            raise ValueError("ExtendObservationWrapper: fields is empty")
        self.eow_fields = fields
        self.eow_n = len(fields)
        low = np.append(self.observation_space.low,
                        [f[1] for f in fields])
        high = np.append(self.observation_space.high,
                         [f[2] for f in fields])
        self.observation_space = gymnasium.spaces.Box(
            low, high, dtype=np.float64)

    def reset(self, **kwargs):
        obs, info = self.env.reset(**kwargs)
        ext = [f[3] for f in self.eow_fields]
        return np.append(obs, ext), info

    def step(self, action):
        obs, reward, term, trunc, info = self.env.step(action)
        ext = [f[0](self, info) for f in self.eow_fields]
        return np.append(obs, ext), reward, term, trunc, info

    def policy(self, obs, name="honest"):
        return self.env.policy(obs[: -self.eow_n], name)


class MapRewardWrapper(CprWrapper):
    """reward <- fn(reward, info) (wrappers.py:156-169)."""

    def __init__(self, env, fn):
        super().__init__(env)
        self.mrw_fn = fn

    def step(self, action):
        obs, reward, term, trunc, info = self.env.step(action)
        return obs, self.mrw_fn(reward, info), term, trunc, info


class AssumptionScheduleWrapper(CprWrapper):
    """Re-draw alpha/gamma on each reset (constant, iterable cycle, or
    callable schedule), append the assumptions to the observation, report
    them in info; optionally show the agent different ("pretend") values
    (wrappers.py:172-242).  This is what trains assumption-generic
    policies."""

    def __init__(self, env, alpha=None, gamma=None, pretend_alpha=None,
                 pretend_gamma=None):
        super().__init__(env)
        self.asw_alpha_fn = self._scheduler(alpha)
        self.asw_gamma_fn = self._scheduler(gamma)
        self.asw_pretend_alpha = pretend_alpha
        self.asw_pretend_gamma = pretend_gamma
        self.asw_alpha = None
        self.asw_gamma = None
        low = np.append(self.observation_space.low, [0.0, 0.0])
        high = np.append(self.observation_space.high, [1.0, 1.0])
        self.observation_space = gymnasium.spaces.Box(
            low, high, dtype=np.float64)

    @staticmethod
    def _scheduler(x):
        if callable(x):
            return x
        try:
            it = itertools.cycle(x)
            return lambda: next(it)
        except TypeError:
            return lambda: x

    def _observation(self, obs):
        a = (self.asw_alpha if self.asw_pretend_alpha is None
             else float(self.asw_pretend_alpha))
        g = (self.asw_gamma if self.asw_pretend_gamma is None
             else float(self.asw_pretend_gamma))
        return np.append(obs, [a, g])

    def policy(self, obs, name="honest"):
        return self.env.policy(obs[:-2], name)

    def reset(self, **kwargs):
        ck = self.env.unwrapped.core_kwargs
        # None schedule = keep the wrapped env's assumption unchanged
        self.asw_alpha = self.asw_alpha_fn()
        if self.asw_alpha is None:
            self.asw_alpha = ck["alpha"]
        else:
            ck["alpha"] = self.asw_alpha
        self.asw_gamma = self.asw_gamma_fn()
        if self.asw_gamma is None:
            self.asw_gamma = ck["gamma"]
        else:
            ck["gamma"] = self.asw_gamma
        obs, info = self.env.reset(**kwargs)
        return AssumptionScheduleWrapper._observation(self, obs), info

    def step(self, action):
        obs, reward, term, trunc, info = self.env.step(action)
        info["alpha"] = self.asw_alpha
        info["gamma"] = self.asw_gamma
        obs = AssumptionScheduleWrapper._observation(self, obs)
        return obs, reward, term, trunc, info


class EpisodeRecorderWrapper(CprWrapper):
    """Ring buffer of the last n episodes' rewards + chosen info keys
    (wrappers.py:245-266); feeds per-alpha evaluation aggregation."""

    def __init__(self, env, n: int = 42, info_keys=()):
        super().__init__(env)
        self.erw_info_keys = tuple(info_keys)
        self.erw_history = collections.deque([], maxlen=n)
        self.erw_episode_reward = 0.0

    def reset(self, **kwargs):
        self.erw_episode_reward = 0.0
        return self.env.reset(**kwargs)

    def step(self, action):
        obs, reward, term, trunc, info = self.env.step(action)
        self.erw_episode_reward += reward
        if term or trunc:
            entry = {k: info[k] for k in self.erw_info_keys}
            entry["episode_reward"] = self.erw_episode_reward
            self.erw_history.append(entry)
        return obs, reward, term, trunc, info


class ClearInfoWrapper(CprWrapper):
    """Keep only `keep_keys` in info — cuts IPC cost before
    vectorization (wrappers.py:269-289)."""

    def __init__(self, env, keep_keys=()):
        super().__init__(env)
        self.ciw_keys = tuple(keep_keys)

    def step(self, action):
        obs, reward, term, trunc, was_info = self.env.step(action)
        info = {k: was_info[k] for k in self.ciw_keys if k in was_info}
        return obs, reward, term, trunc, info
