"""gymnasium adapter over the jittable JAX environments.

Reference counterpart: gym/ocaml/cpr_gym/envs.py — `Core(gym.Env)` over
the OCaml engine (:9-93), the composed `env_fn` (:99-163), and the
registered ids (:96,166-192).  The north-star contract is the same:
`gymnasium.make("cpr-nakamoto-v0")` hands a standard env to an unchanged
external trainer, with the TPU/JAX engine behind the step call.

Where the reference marshals through a CPython extension into the OCaml
runtime, this adapter jits the env's reset/step once per instance and
feeds numpy scalars across — the single-env gym surface is the
compatibility path; high-throughput training uses the vmap'd rollout
kernels directly (cpr_tpu.train.ppo) or `BatchedCore` below.
"""

from __future__ import annotations

import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from cpr_tpu.envs import registry
from cpr_tpu.envs.base import JaxEnv
from cpr_tpu.params import ParameterError, make_params


class Core(gymnasium.Env):
    """Single gymnasium env over a JaxEnv.

    `proto` is a JaxEnv instance or a registry/protocol key
    ("nakamoto", "tailstorm-8-discount-heuristic", ...); construction
    kwargs mirror the reference Core (envs.py:12-53): alpha, gamma,
    activation_delay, defenders, and at least one of max_steps /
    max_progress / max_time.
    """

    metadata = {"render_modes": ["ascii"]}

    def __init__(self, proto: JaxEnv | str = "nakamoto", *, alpha=0.25,
                 gamma=0.5, activation_delay=1.0, defenders=None,
                 max_steps=None, max_progress=None, max_time=None,
                 seed: int = 0, **proto_kwargs):
        if max_steps is None and max_progress is None and max_time is None:
            raise ParameterError(
                "set at least one of max_steps, max_progress, max_time")
        if isinstance(proto, str):
            if max_steps is not None and "max_steps_hint" not in proto_kwargs:
                proto = registry.get_sized(proto, int(max_steps),
                                           **proto_kwargs)
            else:
                proto = registry.get(proto, **proto_kwargs)
        self.jax_env: JaxEnv = proto
        # mutable parameter record, re-read on every reset — wrappers
        # reconfigure assumptions by writing here (the reference's
        # core_kwargs contract, envs.py:20-24, wrappers.py:227-235)
        self.core_kwargs = dict(
            alpha=alpha, gamma=gamma, activation_delay=activation_delay,
            defenders=defenders, max_steps=max_steps,
            max_progress=max_progress, max_time=max_time)

        self._reset_fn = jax.jit(proto.reset)
        self._step_fn = jax.jit(proto.step)
        self._key = jax.random.PRNGKey(seed)
        self._state = None
        self.params = None

        self.action_space = gymnasium.spaces.Discrete(proto.n_actions)
        self.observation_space = gymnasium.spaces.Box(
            np.asarray(proto.low, np.float64),
            np.asarray(proto.high, np.float64), dtype=np.float64)

    # -- gymnasium API ---------------------------------------------------

    def reset(self, *, seed=None, options=None):
        super().reset(seed=seed)
        if seed is not None:
            self._key = jax.random.PRNGKey(seed)
        self.params = make_params(**self.core_kwargs)
        self._key, k = jax.random.split(self._key)
        self._state, obs = self._reset_fn(k, self.params)
        return np.asarray(obs, np.float64), {}

    def step(self, action):
        self._state, obs, reward, done, info = self._step_fn(
            self._state, jnp.int32(action), self.params)
        info = {k: float(v) for k, v in info.items()}
        return (np.asarray(obs, np.float64), float(reward), bool(done),
                False, info)

    def render(self):
        fields = getattr(self.jax_env, "fields", ())
        if self._state is None or not fields:
            print(f"<{type(self.jax_env).__name__}: not reset>")
            return
        obs = np.asarray(self.jax_env.observe(self._state))
        vals = self.jax_env.decode_obs(obs)
        print(", ".join(f"{f.name}={int(v)}"
                        for f, v in zip(fields, vals)))

    # -- reference surface beyond gymnasium ------------------------------

    def policies(self):
        return self.jax_env.policies.keys()

    def policy(self, obs, name="honest"):
        try:
            fn = self.jax_env.policies[name]
        except KeyError:
            raise ValueError(
                f"{name} is not a valid policy; choose from "
                + ", ".join(self.policies()))
        if getattr(fn, "takes_state", False):
            return int(fn(self._state, jnp.asarray(obs, jnp.float32)))
        return int(fn(jnp.asarray(obs, jnp.float32)))


class BatchedCore(gymnasium.Env):
    """vmap-batched variant: actions/observations/rewards carry a leading
    `n_envs` axis and episodes auto-reset per lane.  This is the
    TPU-throughput path for external trainers that can consume batched
    streams (the analog of wrapping the reference Core in
    sb3 SubprocVecEnv — except the batch is one compiled kernel)."""

    metadata = {"render_modes": []}

    def __init__(self, proto: JaxEnv | str = "nakamoto", *, n_envs: int = 128,
                 seed: int = 0, **kwargs):
        self._single = Core(proto, seed=seed, **kwargs)
        env = self._single.jax_env
        self.jax_env = env
        self.core_kwargs = self._single.core_kwargs
        self.n_envs = n_envs
        self._key = jax.random.PRNGKey(seed)
        self._reset_fn = jax.jit(jax.vmap(env.reset, in_axes=(0, None)))
        self._step_fn = jax.jit(jax.vmap(env.step, in_axes=(0, 0, None)))
        self._state = None
        self.params = None
        self.action_space = gymnasium.spaces.MultiDiscrete(
            np.full(n_envs, env.n_actions))
        low = np.tile(np.asarray(env.low, np.float64), (n_envs, 1))
        high = np.tile(np.asarray(env.high, np.float64), (n_envs, 1))
        self.observation_space = gymnasium.spaces.Box(low, high,
                                                      dtype=np.float64)

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._key = jax.random.PRNGKey(seed)
        self.params = make_params(**self.core_kwargs)
        self._key, k = jax.random.split(self._key)
        self._state, obs = self._reset_fn(
            jax.random.split(k, self.n_envs), self.params)
        return np.asarray(obs, np.float64), {}

    def step(self, actions):
        state, obs, reward, done, info = self._step_fn(
            self._state, jnp.asarray(actions, jnp.int32), self.params)
        np_done = np.asarray(done)
        if np_done.any():
            # per-lane auto-reset, keeping each lane's PRNG stream
            rstate, robs = self._reset_fn(state.key, self.params)
            state = jax.tree.map(
                lambda a, b: jnp.where(
                    done.reshape(done.shape + (1,) * (a.ndim - 1)), a, b),
                rstate, state)
            obs = jnp.where(done[:, None], robs, obs)
        self._state = state
        info = {k: np.asarray(v) for k, v in info.items()}
        return (np.asarray(obs, np.float64), np.asarray(reward),
                np_done, np.zeros_like(np_done), info)
