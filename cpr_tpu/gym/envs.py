"""gymnasium adapter over the jittable JAX environments.

Reference counterpart: gym/ocaml/cpr_gym/envs.py — `Core(gym.Env)` over
the OCaml engine (:9-93), the composed `env_fn` (:99-163), and the
registered ids (:96,166-192).  The north-star contract is the same:
`gymnasium.make("cpr-nakamoto-v0")` hands a standard env to an unchanged
external trainer, with the TPU/JAX engine behind the step call.

Where the reference marshals through a CPython extension into the OCaml
runtime, this adapter drives the env's resident lane API
(`JaxEnv.step_lanes`, jitted once on the class) with constant masks and
feeds numpy scalars across — the single-env gym surface is the
compatibility path; high-throughput training uses the vmap'd rollout
kernels directly (cpr_tpu.train.ppo) or `BatchedCore` below.  Routing
both adapters through the one resident program (instead of a fresh
`jax.jit(proto.step)` per instance) means N adapter instances over the
same registry-memoized env share a single compiled step — and
`BatchedCore.step` is one device dispatch with a donated carry instead
of step-then-maybe-reset double dispatch behind a host sync.
"""

from __future__ import annotations

import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from cpr_tpu.envs import registry
from cpr_tpu.envs.base import JaxEnv
from cpr_tpu.params import ParameterError, make_params


class Core(gymnasium.Env):
    """Single gymnasium env over a JaxEnv.

    `proto` is a JaxEnv instance or a registry/protocol key
    ("nakamoto", "tailstorm-8-discount-heuristic", ...); construction
    kwargs mirror the reference Core (envs.py:12-53): alpha, gamma,
    activation_delay, defenders, and at least one of max_steps /
    max_progress / max_time.
    """

    metadata = {"render_modes": ["ascii"]}

    def __init__(self, proto: JaxEnv | str = "nakamoto", *, alpha=0.25,
                 gamma=0.5, activation_delay=1.0, defenders=None,
                 max_steps=None, max_progress=None, max_time=None,
                 seed: int = 0, **proto_kwargs):
        if max_steps is None and max_progress is None and max_time is None:
            raise ParameterError(
                "set at least one of max_steps, max_progress, max_time")
        if isinstance(proto, str):
            if max_steps is not None and "max_steps_hint" not in proto_kwargs:
                proto = registry.get_sized(proto, int(max_steps),
                                           **proto_kwargs)
            else:
                proto = registry.get(proto, **proto_kwargs)
        self.jax_env: JaxEnv = proto
        # mutable parameter record, re-read on every reset — wrappers
        # reconfigure assumptions by writing here (the reference's
        # core_kwargs contract, envs.py:20-24, wrappers.py:227-235)
        self.core_kwargs = dict(
            alpha=alpha, gamma=gamma, activation_delay=activation_delay,
            defenders=defenders, max_steps=max_steps,
            max_progress=max_progress, max_time=max_time)

        self._key = jax.random.PRNGKey(seed)
        # width-1 resident lane block: (state, obs) carry + constant
        # masks (never admit through step_lanes; always step lane 0)
        self._carry = None
        self._fresh = None
        self._no_admit = jnp.zeros(1, bool)
        self._step_all = jnp.ones(1, bool)
        self.params = None

        self.action_space = gymnasium.spaces.Discrete(proto.n_actions)
        self.observation_space = gymnasium.spaces.Box(
            np.asarray(proto.low, np.float64),
            np.asarray(proto.high, np.float64), dtype=np.float64)

    def _state0(self):
        """Unbatched env state of the single lane (render/policy)."""
        return jax.tree.map(lambda a: a[0], self._carry[0])

    # -- gymnasium API ---------------------------------------------------

    def reset(self, *, seed=None, options=None):
        super().reset(seed=seed)
        if seed is not None:
            self._key = jax.random.PRNGKey(seed)
        self.params = make_params(**self.core_kwargs)
        self._key, k = jax.random.split(self._key)
        # two dispatches of the same program so the fresh template and
        # the (donated!) carry never share buffers
        self._fresh = self.jax_env.reset_lanes(k[None], self.params)
        self._carry = self.jax_env.reset_lanes(k[None], self.params)
        return np.asarray(self._carry[1][0], np.float64), {}

    def step(self, action):
        self._carry, (obs, reward, done, info) = self.jax_env.step_lanes(
            self._carry, jnp.asarray([action], jnp.int32), self._no_admit,
            self._fresh, self._step_all, self.params)
        info = {k: float(v[0]) for k, v in info.items()}
        return (np.asarray(obs[0], np.float64), float(reward[0]),
                bool(done[0]), False, info)

    def render(self):
        fields = getattr(self.jax_env, "fields", ())
        if self._carry is None or not fields:
            print(f"<{type(self.jax_env).__name__}: not reset>")
            return
        obs = np.asarray(self.jax_env.observe(self._state0()))
        vals = self.jax_env.decode_obs(obs)
        print(", ".join(f"{f.name}={int(v)}"
                        for f, v in zip(fields, vals)))

    # -- reference surface beyond gymnasium ------------------------------

    def policies(self):
        return self.jax_env.policies.keys()

    def policy(self, obs, name="honest"):
        try:
            fn = self.jax_env.policies[name]
        except KeyError:
            raise ValueError(
                f"{name} is not a valid policy; choose from "
                + ", ".join(self.policies()))
        if getattr(fn, "takes_state", False):
            return int(fn(self._state0(), jnp.asarray(obs, jnp.float32)))
        return int(fn(jnp.asarray(obs, jnp.float32)))


class BatchedCore(gymnasium.Env):
    """vmap-batched variant: actions/observations/rewards carry a leading
    `n_envs` axis and episodes auto-reset per lane.  This is the
    TPU-throughput path for external trainers that can consume batched
    streams (the analog of wrapping the reference Core in
    sb3 SubprocVecEnv — except the batch is one compiled kernel)."""

    metadata = {"render_modes": []}

    def __init__(self, proto: JaxEnv | str = "nakamoto", *, n_envs: int = 128,
                 seed: int = 0, **kwargs):
        self._single = Core(proto, seed=seed, **kwargs)
        env = self._single.jax_env
        self.jax_env = env
        self.core_kwargs = self._single.core_kwargs
        self.n_envs = n_envs
        self._key = jax.random.PRNGKey(seed)
        self._carry = None
        self._fresh = None
        self._no_admit = jnp.zeros(n_envs, bool)
        self._step_all = jnp.ones(n_envs, bool)
        self.params = None
        self.action_space = gymnasium.spaces.MultiDiscrete(
            np.full(n_envs, env.n_actions))
        low = np.tile(np.asarray(env.low, np.float64), (n_envs, 1))
        high = np.tile(np.asarray(env.high, np.float64), (n_envs, 1))
        self.observation_space = gymnasium.spaces.Box(low, high,
                                                      dtype=np.float64)

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._key = jax.random.PRNGKey(seed)
        self.params = make_params(**self.core_kwargs)
        self._key, k = jax.random.split(self._key)
        keys = jax.random.split(k, self.n_envs)
        # distinct buffers: the carry is donated on every step while the
        # fresh template must stay alive for the (constant-false) admit;
        # the template is never spliced, so it draws its own folded
        # stream instead of replaying `keys`
        self._fresh = self.jax_env.reset_lanes(
            jax.random.split(jax.random.fold_in(k, 1), self.n_envs),
            self.params)
        self._carry = self.jax_env.reset_lanes(keys, self.params)
        return np.asarray(self._carry[1], np.float64), {}

    def step(self, actions):
        # one resident dispatch: step + per-lane auto-reset fused, each
        # lane keeping its own PRNG stream (previously: vmapped step,
        # host sync on done, then a second reset+splice dispatch)
        self._carry, (_, reward, done, info) = self.jax_env.step_lanes(
            self._carry, jnp.asarray(actions, jnp.int32), self._no_admit,
            self._fresh, self._step_all, self.params)
        obs = self._carry[1]  # continuation obs: post-reset at done
        np_done = np.asarray(done)
        info = {k: np.asarray(v) for k, v in info.items()}
        return (np.asarray(obs, np.float64), np.asarray(reward),
                np_done, np.zeros_like(np_done), info)
