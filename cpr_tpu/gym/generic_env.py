"""Alternative gym: sampled implicit-model environments.

Reference counterpart: the Rust/pyo3 gym (gym/rust/) — `FC16SSZwPT`
(fc16.rs:28-212), the closed-form SSZ'16 Bitcoin env with probabilistic
termination, and the generic petgraph env with the Release/Consider/
Continue action space encoded into one f32 in (-1, 1)
(generic/mod.rs:224-313) plus per-step invariant checking
(generic/mod.rs:107).

Here both ride the host-side implicit-MDP machinery this framework
already has: the fc16 literature model (cpr_tpu.mdp.models) and the
generic DAG model (cpr_tpu.mdp.generic) — written once, reused by the
compiler, RTDP, and these envs.  The TPU hot path stays with the
jittable SSZ envs; these are the CPU-side general-action-space gyms,
like the reference's Rust extension is.
"""

from __future__ import annotations

import random

import gymnasium
import numpy as np

from cpr_tpu.mdp.generic import (Consider, Continue, Release, SingleAgent,
                                 get_protocol)
from cpr_tpu.mdp.implicit import Model
from cpr_tpu.mdp.models import Fc16BitcoinSM
from cpr_tpu.mdp.models.bitcoin_sm import ADOPT, MATCH, OVERRIDE, WAIT


def _squash(x):
    return x / (1.0 + x)


class FC16Env(gymnasium.Env):
    """SSZ'16 Bitcoin selfish mining with probabilistic termination
    (fc16.rs:28-139): state (a, h, fork), Bernoulli mining/termination
    draws, observation [a, h, fork] squashed into [0, 1).

    Discrete(4) actions Adopt/Override/Match/Wait (the fc16 model's
    order); an unavailable action falls back to Wait, which is always
    available below the fork-length cutoff."""

    metadata = {"render_modes": []}
    ACTIONS = (ADOPT, OVERRIDE, MATCH, WAIT)

    def __init__(self, *, alpha: float = 0.3, gamma: float = 0.5,
                 horizon: int = 100, maximum_fork_length: int = 64,
                 seed: int = 0):
        self.model = Fc16BitcoinSM(alpha=alpha, gamma=gamma,
                                   maximum_fork_length=maximum_fork_length)
        self.horizon = horizon
        self.rng = random.Random(seed)
        self.action_space = gymnasium.spaces.Discrete(4)
        self.observation_space = gymnasium.spaces.Box(
            0.0, 1.0, shape=(3,), dtype=np.float64)
        self.state = None

    def _obs(self):
        s = self.state
        return np.array([_squash(float(s.a)), _squash(float(s.h)),
                         _squash(float(s.fork))], np.float64)

    def reset(self, *, seed=None, options=None):
        super().reset(seed=seed)
        if seed is not None:
            self.rng = random.Random(seed)
        states = self.model.start()
        r = self.rng.random() * sum(p for _, p in states)
        acc = 0.0
        for s, p in states:
            acc += p
            if r <= acc:
                break
        self.state = s
        return self._obs(), {}

    def _sample(self, transitions):
        r = self.rng.random() * sum(t.probability for t in transitions)
        acc = 0.0
        for t in transitions:
            acc += t.probability
            if r <= acc:
                return t
        return transitions[-1]

    def step(self, action):
        avail = self.model.actions(self.state)
        a = self.ACTIONS[int(action)]
        if a not in avail:
            a = WAIT if WAIT in avail else avail[0]
        t = self._sample(self.model.apply(a, self.state))
        self.state = t.state
        reward, progress = t.reward, t.progress
        # probabilistic termination (Bar-Zur AFT'20): each unit of
        # progress flips the termination coin; fair shutdown settles
        # withheld blocks
        done = (progress > 0.0 and self.rng.random()
                > (1.0 - 1.0 / self.horizon) ** progress)
        if done:
            ts = self.model.shutdown(self.state)
            if ts:
                t = self._sample(ts)
                self.state = t.state
                reward += t.reward
                progress += t.progress
        info = {"progress": progress}
        return self._obs(), float(reward), done, False, info


def encode_action(kind: str, index: int = 0) -> float:
    """ActionHum -> f32 in (-1, 1) (generic/mod.rs:236-248): Release(i)
    maps below zero, Consider(i) above, Continue to exactly 0; indices
    near zero get more of the action space."""
    if kind == "continue":
        return 0.0
    x = float(index) + 1.0
    if kind == "release":
        return -x / (1.0 + x)
    if kind == "consider":
        return x / (1.0 + x)
    raise ValueError(kind)


def decode_action(a: float) -> tuple[str, int]:
    """f32 -> (kind, index) (generic/mod.rs:250-279)."""
    assert -1.0 <= a <= 1.0, f"action {a} outside [-1, 1]"
    if a == -1.0:
        return "release", 255
    if a == 1.0:
        return "consider", 255
    x = -a / (a - 1.0) if a >= 0.0 else a / (a + 1.0)
    x = round(x)
    if x < 0:
        return "release", min(-x - 1, 255)
    if x > 0:
        return "consider", min(x - 1, 255)
    return "continue", 0


class GenericEnv(gymnasium.Env):
    """Generic DAG-protocol attack env with the Release/Consider/
    Continue action space (generic/mod.rs:224-560) over the
    cpr_tpu.mdp.generic model: protocols bitcoin/ethereum/byzantium/
    parallel/ghostdag, alpha/gamma randomness, probabilistic termination
    with fair shutdown, defender-chain reward tracking.

    Action space Box(-1, 1): the scalar encodes Release(i)/Consider(i)/
    Continue; i indexes the available-action lists (block-id order); an
    out-of-range index clamps to the last available entry, Continue when
    none is available (mirroring the Rust env's saturating decode).
    """

    metadata = {"render_modes": []}

    def __init__(self, protocol: str = "bitcoin", *, alpha: float = 0.3,
                 gamma: float = 0.5, horizon: int = 50, seed: int = 0,
                 dag_size_cutoff: int | None = 24, **proto_kwargs):
        self.model: Model = SingleAgent(
            get_protocol(protocol, **proto_kwargs), alpha=alpha,
            gamma=gamma, collect_garbage="simple", merge_isomorphic=False,
            truncate_common_chain=True, dag_size_cutoff=dag_size_cutoff)
        self.horizon = horizon
        self.rng = random.Random(seed)
        self.action_space = gymnasium.spaces.Box(
            -1.0, 1.0, shape=(1,), dtype=np.float32)
        self.observation_space = gymnasium.spaces.Box(
            0.0, 1.0, shape=(5,), dtype=np.float64)
        self.state = None

    def _obs(self):
        s = self.state
        atk = self.model.proto.history(s.aview(), s.astate)
        dfn = self.model.proto.history(s.dview(), s.dstate)
        common = 0
        for x, y in zip(atk, dfn):
            if x != y:
                break
            common += 1
        return np.array([
            _squash(float(s.dag.size() - 1)),
            _squash(float(bin(s.withheld).count("1"))),
            _squash(float(bin(s.ignored).count("1"))),
            _squash(float(len(atk) - common)),
            _squash(float(len(dfn) - common)),
        ], np.float64)

    def reset(self, *, seed=None, options=None):
        super().reset(seed=seed)
        if seed is not None:
            self.rng = random.Random(seed)
        states = self.model.start()
        r = self.rng.random() * sum(p for _, p in states)
        acc = 0.0
        for s, p in states:
            acc += p
            if r <= acc:
                break
        self.state = s
        return self._obs(), {}

    def _semantic(self, action) -> object:
        kind, idx = decode_action(float(np.asarray(action).reshape(())))
        if kind == "continue":
            return Continue()
        avail = [a for a in self.model.actions(self.state)
                 if isinstance(a, Release if kind == "release"
                               else Consider)]
        if not avail:
            return Continue()
        return avail[min(idx, len(avail) - 1)]

    def step(self, action):
        t = self._sample(self.model.apply(self._semantic(action),
                                          self.state))
        self.state = t.state
        reward, progress = t.reward, t.progress
        done = (progress > 0.0 and self.rng.random()
                > (1.0 - 1.0 / self.horizon) ** progress)
        if done:
            ts = self.model.shutdown(self.state)
            if ts:
                t = self._sample(ts)
                self.state = t.state
                reward += t.reward
                progress += t.progress
        return self._obs(), float(reward), done, False, \
            {"progress": progress}

    def _sample(self, transitions):
        r = self.rng.random() * sum(t.probability for t in transitions)
        acc = 0.0
        for t in transitions:
            acc += t.probability
            if r <= acc:
                return t
        return transitions[-1]
