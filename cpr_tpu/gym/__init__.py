"""gymnasium plugin boundary: env ids + composed env factory.

Reference counterpart: gym/ocaml/cpr_gym/envs.py:96-192 — the registered
ids `core-v0`, `cpr-v0`, `cpr-nakamoto-v0`, `cpr-tailstorm-v0` and the
`env_fn` composition (Core + AssumptionScheduleWrapper + reward wrapper +
normalization).  Importing this module registers the ids; external
trainers then use plain `gymnasium.make("cpr-nakamoto-v0")` with the
JAX/TPU engine behind it.
"""

from __future__ import annotations

import gymnasium

from cpr_tpu.gym import wrappers
from cpr_tpu.gym.envs import BatchedCore, Core


def env_fn(protocol="nakamoto", protocol_args=None,
           _protocol_args=None, episode_len=128, alpha=0.45,
           gamma=0.5, pretend_alpha=None, pretend_gamma=None,
           defenders=None, reward="sparse_relative",
           normalize_reward=True, seed=0):
    """Composed environment (reference env_fn, envs.py:99-163):
    Core + assumption schedule + reward shaping + normalization."""
    protocol_args = {**(_protocol_args or {}), **(protocol_args or {})}

    rewards = {
        "sparse_relative": (
            wrappers.SparseRelativeRewardWrapper,
            dict(max_steps=episode_len)),
        "sparse_per_progress": (
            wrappers.SparseRewardPerProgressWrapper,
            dict(max_steps=episode_len)),
        # same bounds the wrapper will install, so it overwrites nothing
        "dense_per_progress": (
            lambda env: wrappers.DenseRewardPerProgressWrapper(
                env, episode_len=episode_len),
            dict(max_steps=episode_len * 100, max_progress=episode_len)),
    }
    try:
        reward_wrapper, env_args = rewards[reward]
    except KeyError:
        raise ValueError(
            f"unknown reward '{reward}'; choose from {sorted(rewards)}")

    env = Core(protocol, alpha=0.25, gamma=0.0, defenders=defenders,
               seed=seed, **env_args, **protocol_args)
    env = wrappers.AssumptionScheduleWrapper(
        env, alpha=alpha, gamma=gamma,
        pretend_alpha=pretend_alpha, pretend_gamma=pretend_gamma)
    env.reset()  # apply the schedule's first alpha/gamma draw
    env = reward_wrapper(env)
    if normalize_reward:
        env = wrappers.MapRewardWrapper(env, lambda r, i: r / i["alpha"])
    return env


def _register():
    from cpr_tpu.gym.generic_env import FC16Env, GenericEnv

    specs = [
        dict(id="core-v0", entry_point=Core),
        dict(id="cpr-v0", entry_point=env_fn),
        # the alternative gym (reference: gym/rust/cpr_gym_rs/envs.py)
        dict(id="FC16SSZwPT-v0", entry_point=FC16Env),
        dict(id="cpr-generic-v0", entry_point=GenericEnv),
        dict(id="cpr-nakamoto-v0", entry_point=env_fn,
             kwargs=dict(protocol="nakamoto", reward="sparse_relative")),
        dict(id="cpr-tailstorm-v0", entry_point=env_fn,
             kwargs=dict(protocol="tailstorm",
                         _protocol_args=dict(
                             k=8, incentive_scheme="discount",
                             subblock_selection="heuristic"),
                         reward="sparse_per_progress")),
    ]
    for spec in specs:  # per-id guard: re-import must be idempotent
        if spec["id"] not in gymnasium.envs.registry:
            gymnasium.register(**spec)


_register()

__all__ = ["Core", "BatchedCore", "env_fn", "wrappers"]
