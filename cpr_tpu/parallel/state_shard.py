"""State-axis sharded Bellman backups — VI past one device's memory.

`sharded_value_iteration` (transition sharding) pays a psum of the
full [S*A] Q planes per sweep and still keeps every plane replicated,
so one (alpha, gamma) point is capped by ONE lane's memory.  This
module shards the STATE axis instead: each device owns a contiguous
block of S/n states plus exactly the transitions that leave it, runs
the per-block segment-sum backup locally, and per sweep exchanges only
the [S] value/progress vectors (a tiled all_gather of the per-block
slices — the boundary "halo" every shard's `value[dst]` gather reads).
Per-shard memory drops from O(T + S*A) to O(T/n + S*A/n + S); the
collective traffic per sweep is 2*(S - S/n)*itemsize per device
instead of 2*S*A.

Bit-identity by construction: every (state, action) segment lies
wholly in one shard with its transitions in the original relative
order, so each partial sum, each greedy argmax row, and the gathered
[S] iterate are the same floats the single-device `impl="chunked"`
solve produces — `tests/test_state_shard.py` pins fc16/aft20/ghostdag
at 1 vs 4 forced-CPU devices, including through kill@vi_chunk+resume.

Chunked impl only: the host chunk seam (explicit.run_chunk_driver) is
what provides checkpoint/resume and fault retries, and the carry
(value, prog) is a replicated full-[S] pair at every chunk boundary,
so the checkpoint format is identical to the single-device driver's.
`impl="while"` is refused by name.  The grid axis composes: see
`make_grid_state_chunk_step` (grid x state 2-D mesh — PR 13's [G]
plane sharding with each point's backup itself state-sharded).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from functools import partial

from cpr_tpu.mdp.explicit import (TensorMDP, _greedy_backup,
                                  _valid_actions, check_vi_working_set,
                                  make_vi_sweep, resolve_vi_impl,
                                  run_chunk_driver, vi_residuals_event,
                                  vi_working_set_bytes)
from cpr_tpu.parallel.lanes import check_even_shards

__all__ = [
    "partition_by_state_block",
    "sharded_state_value_iteration",
    "make_grid_state_chunk_step",
    "state_halo_bytes",
]


def partition_by_state_block(tm: TensorMDP, n: int,
                             S_pad: int | None = None):
    """Bucket the COO transition columns by source-state block.

    Block b of `n` owns states [b*S/n, (b+1)*S/n); every transition is
    routed to its source's block with src LOCALIZED (src - block
    start), blocks are padded to the max block length with inert rows
    (prob 0, src = S/n — the local segment id lands out of range and
    the scatter-add drops it, so padding cannot even flip a -0.0), and
    the padded blocks are concatenated so `PartitionSpec(axis)` hands
    shard b exactly its block.

    Frontier-compiled MDPs arrive pre-bucketed — FrontierCompiler
    assigns state ids in BFS discovery order and emits each round's
    transitions with nondecreasing src, so the bucketing permutation
    degenerates to a split (no argsort pass).

    Returns `(cols, slot, t_blk)`: cols the six [n*t_blk] numpy
    columns (src_local, act, dst, prob, reward, progress), `slot` the
    destination index of each original transition inside the padded
    layout (callers with per-point probability planes — the grid
    solver — scatter their [G, T] columns through it), and `t_blk`
    the per-shard padded transition count.

    `S_pad` (a multiple of n, >= n_states) blocks over an internally
    padded state space: the pad states own no transitions (so they
    back up to value 0 / policy -1 — inert) and callers slice the
    gathered vectors back to [n_states].  This is how `pad_states=True`
    entry points solve state counts that do not divide the mesh.
    """
    S = S_pad if S_pad is not None else tm.n_states
    if S % n or S < tm.n_states:
        raise ValueError(
            f"cannot shard {S} states into {n} blocks: {S} % {n} = "
            f"{S % n}")
    s_blk = S // n
    src = np.asarray(tm.src, np.int64)
    T = src.shape[0]
    blk = src // s_blk
    counts = np.bincount(blk, minlength=n)
    t_blk = max(int(counts.max()), 1) if T else 1
    if np.all(src[1:] >= src[:-1]):
        order = np.arange(T)  # pre-bucketed (frontier compiles)
    else:
        order = np.argsort(blk, kind="stable")
    starts = np.zeros(n, np.int64)
    starts[1:] = np.cumsum(counts)[:-1]
    blk_o = blk[order]
    slot_o = blk_o * t_blk + (np.arange(T) - starts[blk_o])
    slot = np.empty(T, np.int64)
    slot[order] = slot_o
    src_local = np.full(n * t_blk, s_blk, np.int32)  # pad: out of range
    src_local[slot] = (src - blk * s_blk).astype(np.int32)
    cols = [src_local]
    for col, fill, dt in ((tm.act, 0, np.int32), (tm.dst, 0, np.int32),
                          (tm.prob, 0.0, None), (tm.reward, 0.0, None),
                          (tm.progress, 0.0, None)):
        a = np.asarray(col)
        out = np.full(n * t_blk, fill, dt or a.dtype)
        out[slot] = a
        cols.append(out)
    return tuple(cols), slot, t_blk


def state_halo_bytes(S: int, n: int, dtype) -> int:
    """Bytes of value+progress crossing device boundaries per sweep:
    each of the n shards all-gathers the (S - S/n) remote entries of
    both vectors (the policy gather happens once per chunk — noise)."""
    if n <= 1:
        return 0
    return 2 * (S - S // n) * np.dtype(dtype).itemsize * n


def sharded_state_value_iteration(tm: TensorMDP, mesh, *,
                                  axis: str = "d", max_iter: int = 0,
                                  discount: float = 1.0,
                                  eps: float | None = None,
                                  stop_delta: float | None = None,
                                  impl: str | None = None,
                                  chunk: int = 64,
                                  checkpoint_path: str | None = None,
                                  checkpoint_every: int = 1,
                                  value0=None, progress0=None,
                                  pad_states: bool = False,
                                  protocol: str | None = None,
                                  cutoff: int | None = None):
    """Value iteration with the STATE axis sharded over the mesh —
    same dict, same fixpoint, bit-identical to
    `TensorMDP.value_iteration(impl="chunked")` (see module
    docstring).  `value0`/`progress0` warm-start the solve (the
    in-graph RTDP handoff — cpr_tpu/mdp/rtdp_graph.py); a resumable
    checkpoint overrides a warm start.  `protocol`/`cutoff` label the
    emitted `mdp_solve` telemetry event (schema v13: `state_shards`,
    `halo_bytes`, `states_per_sec` ride as extras).

    State counts that do not divide the mesh are refused up front by
    name (check_even_shards) unless `pad_states=True`, which blocks
    over an internally padded state space — the pad states own no
    transitions, are never a destination, and are sliced off before
    return, so the real-state fixpoint stays bit-identical (padded
    entries back up to exactly 0 and cannot move the sweep delta).

    Chunked impl only — `impl="while"` is refused: the host chunk
    seam is what carries kill@vi_chunk retries and checkpoint/resume
    through the sharded path, and a mesh program with no host seam
    would lose both.  The CPR_VI_IMPL env default does not apply
    here; an explicit impl other than "chunked" raises.
    """
    from cpr_tpu import telemetry

    impl = resolve_vi_impl(impl or "chunked")
    if impl != "chunked":
        raise ValueError(
            "state-sharded VI requires impl='chunked': the host "
            "between-chunk seam is what provides checkpoint/resume "
            "and fault retries; the while impl is a single device "
            "program with no such seam (use "
            "cpr_tpu.parallel.sharded_value_iteration for a "
            "transition-sharded while solve)")
    stop_delta = tm.resolve_stop_delta(
        discount=discount, eps=eps, stop_delta=stop_delta,
        max_iter=max_iter)
    tm._check_segment_width()
    S, A = tm.n_states, tm.n_actions
    n = mesh.shape[axis]
    if pad_states:
        S_pad = S + (-S % n)
    else:
        check_even_shards(S, mesh, axis=axis, what="states")
        S_pad = S
    t0 = telemetry.now()
    (src_l, act, dst, prob, reward, progress), _, t_blk = \
        partition_by_state_block(tm, n, S_pad)
    check_vi_working_set(t_blk, S_pad, A, tm.prob.dtype, shards=n)
    s_blk = S_pad // n
    sweep = make_vi_sweep(s_blk, A)  # local src ids: the same math
    disc = jnp.asarray(discount, tm.prob.dtype)
    cols = tuple(jnp.asarray(c) for c in
                 (src_l, act, dst, prob, reward, progress))

    def make_chunk_fn(steps: int):
        def body(src_l, act, dst, prob, reward, progress, value, prog):
            valid, any_valid = _valid_actions(src_l, act, prob, s_blk, A)

            def sweep_step(carry, _):
                value, prog, _ = carry
                v_blk, p_blk, pol_blk = sweep(
                    src_l, act, dst, prob, reward, progress, valid,
                    any_valid, disc, value, prog)
                v2 = jax.lax.all_gather(v_blk, axis, tiled=True)
                p2 = jax.lax.all_gather(p_blk, axis, tiled=True)
                return (v2, p2, pol_blk), jnp.abs(v2 - value).max()

            pol0 = jnp.full((s_blk,), -1, jnp.int32)
            (v, p, pol_blk), deltas = jax.lax.scan(
                sweep_step, (value, prog, pol0), None, length=steps)
            pol = jax.lax.all_gather(pol_blk, axis, tiled=True)
            return v, p, pol, deltas

        return body

    from cpr_tpu.parallel import _shard_map

    @partial(jax.jit, static_argnums=(2,), donate_argnums=(0, 1))
    def chunk_fn(value, prog, steps):
        return _shard_map(
            make_chunk_fn(steps), mesh=mesh,
            in_specs=(P(axis),) * 6 + (P(), P()),
            out_specs=(P(), P(), P(), P()),
            check_vma=False,
        )(*cols, value, prog)

    max_iter_ = max_iter if max_iter > 0 else (1 << 30)

    def pad0(x):
        if x is None or S_pad == S:
            return x
        return np.concatenate([np.asarray(x),
                               np.zeros(S_pad - S, np.asarray(x).dtype)])

    value, progress_v, policy, delta, it, resid = run_chunk_driver(
        chunk_fn, S_pad, tm.prob.dtype, stop_delta, max_iter_, chunk,
        checkpoint_path=checkpoint_path,
        checkpoint_every=checkpoint_every,
        value0=pad0(value0), prog0=pad0(progress0),
        predicted_bytes=vi_working_set_bytes(
            t_blk, S_pad, A, tm.prob.dtype, shards=n))
    resid = vi_residuals_event(impl, int(it), resid, stop_delta, delta)
    vi_time = telemetry.now() - t0
    halo = state_halo_bytes(S_pad, n, tm.prob.dtype)
    telemetry.current().event(
        "mdp_solve", protocol=protocol, cutoff=cutoff, grid=[1, 1],
        sweeps=int(it), converged=int(float(delta) <= float(stop_delta)),
        points=1, n_states=S, n_transitions=int(np.asarray(tm.src).shape[0]),
        n_devices=int(n), state_shards=int(n), halo_bytes=int(halo),
        solve_s=round(vi_time, 6),
        states_per_sec=(round(S * int(it) / vi_time, 3)
                        if vi_time > 0 else None))
    return dict(
        vi_discount=discount,
        vi_delta=float(delta),
        vi_stop_delta=stop_delta,
        vi_policy=np.asarray(policy)[:S],
        vi_value=np.asarray(value)[:S],
        vi_progress=np.asarray(progress_v)[:S],
        vi_iter=int(it),
        vi_max_iter=max_iter,
        vi_residuals=resid,
        vi_time=vi_time,
        vi_state_shards=int(n),
        vi_halo_bytes=int(halo),
    )


def make_grid_state_chunk_step(tm: TensorMDP, G: int, probs, *,
                               discount, mesh, axis: str = "g",
                               state_axis: str = "s"):
    """Grid x state 2-D mesh chunk step: PR 13's [G] grid-plane
    sharding with each point's Bellman backup itself state-sharded.

    The [G, T] probability plane is bucketed through the state
    partition's `slot` map and sharded over BOTH axes; each (g, s)
    shard computes its [t_blk, G_blk] contribution columns and runs
    ONE segment-sum over the transition axis (a vmap over the grid
    axis would wrap the collective — transposing keeps the gather and
    the scatter-add a single 2-D program), then all-gathers only its
    [G_blk, s_blk] value/progress slices along the state axis.  The
    greedy backup (pure per-state math) is vmapped over G_blk.

    Same bit-freezing contract as explicit.make_grid_vi_chunk: frozen
    points pass their carry through unchanged and report delta 0, so
    each point's fixpoint equals the 1-D grid solve (and the solo
    chunked solve) bit-for-bit.

    Returns `(chunk_step, place)` with the run_grid_chunk_driver
    calling convention — `chunk_step(carry, frozen, steps)`, `place`
    putting [G, ...] grid-major host arrays under the grid sharding
    (probs is placed internally, once).
    """
    from cpr_tpu.parallel import _shard_map

    S, A = tm.n_states, tm.n_actions
    n_g = mesh.shape[axis]
    n_s = mesh.shape[state_axis]
    check_even_shards(G, mesh, axis=axis, what="grid points")
    check_even_shards(S, mesh, axis=state_axis, what="states")
    (src_l, act, dst, prob_probe, reward, progress), slot, t_blk = \
        partition_by_state_block(tm, n_s)
    check_vi_working_set(t_blk, S, A, tm.prob.dtype, shards=n_s)
    s_blk = S // n_s
    probs = np.asarray(probs)
    probs_b = np.zeros((G, n_s * t_blk), probs.dtype)
    probs_b[:, slot] = probs
    gshard = NamedSharding(mesh, P(axis))
    rep_t = NamedSharding(mesh, P(state_axis))
    probs_dev = jax.device_put(probs_b,
                               NamedSharding(mesh, P(axis, state_axis)))
    consts = tuple(jax.device_put(jnp.asarray(c), rep_t)
                   for c in (src_l, act, dst, reward, progress))
    disc = float(discount)

    def place(x):
        return jax.device_put(x, gshard)

    def body(value, prog, pol, frozen, probs, src_l, act, dst, reward,
             progress, steps):
        # local shapes: value/prog/pol [G_blk, S], frozen [G_blk],
        # probs [G_blk, t_blk], transition columns [t_blk]
        seg = src_l * jnp.int32(A) + act
        nseg = s_blk * A
        mass = jax.ops.segment_sum(
            jnp.where(probs > 0, 1.0, 0.0).T, seg, num_segments=nseg)
        valid = mass.T.reshape(-1, s_blk, A) > 0  # [G_blk, s_blk, A]
        any_valid = valid.any(-1)

        def sweep_step(carry, _):
            value, prog, _ = carry
            qv = jax.ops.segment_sum(
                (probs * (reward + disc * value[:, dst])).T, seg,
                num_segments=nseg).T.reshape(-1, s_blk, A)
            qp = jax.ops.segment_sum(
                (probs * (progress + disc * prog[:, dst])).T, seg,
                num_segments=nseg).T.reshape(-1, s_blk, A)
            v_blk, p_blk, pol_blk = jax.vmap(_greedy_backup)(
                qv, qp, valid, any_valid)
            v2 = jax.lax.all_gather(v_blk, state_axis, axis=1,
                                    tiled=True)
            p2 = jax.lax.all_gather(p_blk, state_axis, axis=1,
                                    tiled=True)
            delta = jnp.abs(v2 - value).max(axis=1)
            return (v2, p2, pol_blk), delta

        pol0 = jnp.full(value.shape[:1] + (s_blk,), -1, jnp.int32)
        (v2, p2, pol_blk), deltas = jax.lax.scan(
            sweep_step, (value, prog, pol0), None, length=steps)
        pol2 = jax.lax.all_gather(pol_blk, state_axis, axis=1,
                                  tiled=True)
        fz = frozen[:, None]
        v2 = jnp.where(fz, value, v2)
        p2 = jnp.where(fz, prog, p2)
        pol2 = jnp.where(fz, pol, pol2)
        deltas = jnp.where(fz, 0.0, deltas.T)  # -> [G_blk, steps]
        return (v2, p2, pol2), deltas

    def chunk(carry, frozen, steps):
        value, prog, pol = carry
        return _shard_map(
            partial(body, steps=steps), mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis), P(axis),
                      P(axis, state_axis)) + (P(state_axis),) * 5,
            out_specs=((P(axis), P(axis), P(axis)), P(axis)),
            check_vma=False,
        )(value, prog, pol, frozen, probs_dev, *consts)

    chunk_step = jax.jit(chunk, static_argnums=(2,),
                         donate_argnums=(0,),
                         in_shardings=(gshard, gshard),
                         out_shardings=(gshard, gshard))
    return chunk_step, place
