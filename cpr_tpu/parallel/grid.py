"""Grid-axis sharded chunk step for grid-batched value iteration.

The grid solver (cpr_tpu/mdp/grid.py `grid_value_iteration`) vmaps the
chunked Bellman sweep over a [G] axis of (alpha, gamma) points.  That
axis is embarrassingly parallel — every point solves an independent
MDP over the SAME transition structure — which makes it a far better
scaling seam than sharding transitions (sharded_value_iteration pays a
psum per sweep; the grid axis pays nothing): `make_grid_chunk_step`
partitions the [G, *] planes over a 1-D mesh axis with `NamedSharding`
and replicates the shared COO columns, so one dispatch advances every
grid point on whichever device owns it, bit-identically to the
single-device program (tests/test_mdp_grid.py).

Same contract as the lane stepper (lanes.py): grid-major pytrees under
`NamedSharding(mesh, P(axis))`, shared columns replicated under `P()`,
the carry donated with matched in/out shardings so the chunk loop
aliases in place and never inserts a resharding collective.  Uneven
grids are refused up front (`check_even_shards`).
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from cpr_tpu.mdp.explicit import make_grid_vi_chunk
from cpr_tpu.parallel.lanes import check_even_shards

__all__ = ["make_grid_chunk_step"]


def make_grid_chunk_step(tm, G: int, *, discount, mesh=None,
                         axis: str = "d"):
    """Build the jitted grid chunk step over `tm`'s transition
    structure (a TensorMDP template; its probe probability column is
    unused — per-point columns arrive as the [G, T] `probs` plane).

    Returns `(chunk_step, place)`:
    `chunk_step(carry, probs, frozen, steps)` advances every unfrozen
    grid point `steps` Bellman sweeps and returns `(carry, deltas)`
    with deltas [G, steps]; `place(x)` device-puts a grid-major host
    array under the grid sharding (identity placement when mesh is
    None).  `probs` is placed once by the caller via `place` and
    reused across chunks."""
    S, A = tm.n_states, tm.n_actions
    body = make_grid_vi_chunk(S, A)
    consts = (tm.src, tm.act, tm.dst, tm.reward, tm.progress)
    disc = float(discount)
    jit_kw = {}
    if mesh is not None:
        check_even_shards(G, mesh, axis=axis, what="grid points")
        gshard = NamedSharding(mesh, P(axis))
        rep = NamedSharding(mesh, P())
        consts = tuple(jax.device_put(c, rep) for c in consts)
        # carry pytree prefix: one sharding covers all three [G, S]
        # planes; deltas [G, steps] shard the same axis
        jit_kw = dict(in_shardings=(gshard, gshard, gshard),
                      out_shardings=(gshard, gshard))

        def place(x):
            return jax.device_put(x, gshard)
    else:
        def place(x):
            return jax.device_put(x)

    src, act, dst, reward, progress = consts

    def chunk(carry, probs, frozen, steps):
        return body(carry, src, act, dst, probs, reward, progress,
                    disc, frozen, steps)

    chunk_step = jax.jit(chunk, static_argnums=(3,),
                         donate_argnums=(0,), **jit_kw)
    return chunk_step, place
