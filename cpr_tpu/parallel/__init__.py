"""Device-mesh parallelism.

Reference counterpart: the reference has NO distributed backend — its
parallelism is fork-based task farms (experiments/simulate/csv_runner.ml:
105-131 via Parany) and process-per-env rollouts (experiments/train/
ppo.py:283 via SubprocVecEnv). See SURVEY.md §2.8 for the full mapping.

TPU re-design: three first-class parallel axes, all on one `jax.sharding.
Mesh` with XLA collectives over ICI (intra-slice) / DCN (across slices):

- env-batch data parallelism: `vmap` over episodes (free, no mesh),
- device data parallelism: episode batches sharded over the mesh
  (`shard_envs`), and the RESIDENT lane block of the serving layer
  sharded the same way (`make_sharded_lane_fns` — lanes.py: the
  init/reset/step lane programs with NamedSharding'd, donated
  carries),
- solver parallelism: value-iteration sweeps with transitions sharded
  over devices and `psum`-reduced Bellman backups
  (`sharded_value_iteration`) — the analog of model/tensor parallelism
  for the MDP workload.

The same code runs on a virtual CPU mesh (tests, CI) and on real TPU
slices; the mesh is the only seam.  Batch sizes must divide the mesh
axis — `check_even_shards` raises a ValueError naming both values
instead of XLA's opaque sharding error.  docs/SCALING.md walks the
whole story (contract, CI, blessing a scaling row).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from functools import partial

from cpr_tpu.mdp.explicit import (TensorMDP, _valid_actions,
                                  make_vi_chunk, resolve_vi_impl,
                                  ring_residuals, run_chunk_driver,
                                  vi_residuals_event, vi_while_loop)
from cpr_tpu.parallel.grid import make_grid_chunk_step
from cpr_tpu.parallel.lanes import (ShardedLaneFns, check_even_shards,
                                    make_sharded_lane_fns)
from cpr_tpu.parallel.state_shard import (make_grid_state_chunk_step,
                                          partition_by_state_block,
                                          sharded_state_value_iteration,
                                          state_halo_bytes)
from cpr_tpu.telemetry import now


def _shard_map(body, *, mesh, in_specs, out_specs, check_vma=True):
    """jax.shard_map across jax versions: the public API (>= 0.6) takes
    `check_vma`; on older jax the function lives in jax.experimental and
    the same knob is spelled `check_rep`."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map

    return shard_map(body, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=check_vma)

__all__ = [
    "default_mesh",
    "shard_envs",
    "sharded_value_iteration",
    "sharded_state_value_iteration",
    "make_grid_chunk_step",
    "make_grid_state_chunk_step",
    "partition_by_state_block",
    "state_halo_bytes",
    "make_sharded_rollout_fn",
    "sharded_rollout",
    "make_sharded_lane_fns",
    "ShardedLaneFns",
    "check_even_shards",
]


def default_mesh(axis: str = "d", devices=None) -> Mesh:
    """One-dimensional mesh over all (or the given) devices."""
    devices = jax.devices() if devices is None else devices
    return Mesh(np.asarray(devices), (axis,))


def shard_envs(mesh: Mesh, tree, axis: str = "d"):
    """Place a batched env state/keys PyTree with the batch dimension
    sharded over the mesh (device data parallelism for episode
    batches).  The batch must divide the mesh axis — refused up front
    with both values named (check_even_shards) instead of surfacing
    XLA's opaque uneven-sharding error downstream."""
    leaves = jax.tree.leaves(tree)
    batched = [x for x in leaves if getattr(x, "ndim", 0) >= 1]
    if batched:
        check_even_shards(batched[0].shape[0], mesh, axis=axis,
                          what="batched envs")
    sharding = NamedSharding(mesh, P(axis))
    return jax.device_put(tree, sharding)


def make_sharded_rollout_fn(env, mesh: Mesh, params, policy,
                            n_steps: int, axis: str = "d",
                            chunk: int | None = None,
                            collect_metrics: bool = False):
    """Build `fn(keys) -> stats` running vmap'd `JaxEnv.episode_stats`
    with the episode batch sharded over the mesh. XLA partitions the
    whole rollout program; no collectives are needed until the caller
    aggregates the returned stats.  The jitted pieces are built once —
    call the returned fn per rep without re-tracing.

    `chunk` splits the episode scan across device calls exactly like
    the single-device `JaxEnv.make_episode_stats_fn` (sharded inputs
    keep their placement through the host loop, so each per-chunk call
    stays mesh-partitioned) — for workers that bound single-execution
    time (docs/TPU_SESSION_r03.md).

    `collect_metrics` threads the per-device in-graph metrics
    accumulator through the sharded rollout exactly as on one device
    (the env-axis merge is part of the partitioned program, so the
    accumulator cells come back as replicated scalars — still one
    readback per call).

    Delegates to `JaxEnv.make_episode_stats_fn(mesh=...)` — the mesh
    is a first-class knob of the driver itself, so this wrapper only
    names the parallel/ entry point; batches that do not divide the
    mesh axis are refused with both values named."""
    return env.make_episode_stats_fn(params, policy, n_steps,
                                     chunk=chunk,
                                     collect_metrics=collect_metrics,
                                     mesh=mesh, mesh_axis=axis)


def sharded_rollout(env, mesh: Mesh, keys, params, policy, n_steps: int,
                    axis: str = "d", chunk: int | None = None):
    """One-shot wrapper over `make_sharded_rollout_fn` (build the fn
    once instead when calling repeatedly)."""
    return make_sharded_rollout_fn(env, mesh, params, policy, n_steps,
                                   axis, chunk)(keys)


def sharded_value_iteration(tm: TensorMDP, mesh: Mesh, *, axis: str = "d",
                            max_iter: int = 0, discount: float = 1.0,
                            eps: float | None = None,
                            stop_delta: float | None = None,
                            impl: str | None = None, chunk: int = 64,
                            accel_m: int = 0,
                            checkpoint_path: str | None = None,
                            checkpoint_every: int = 1):
    """Value iteration with the transition table sharded over the mesh.

    Each device owns a contiguous transition chunk (padded with
    zero-probability entries), computes a partial per-(state,action)
    backup with a local segment-sum, and the partial Q tables are
    `psum`-combined over ICI. Values/policies stay replicated, so each
    sweep is one all-reduce of an (S, A) table — the halo exchange for
    cross-shard transitions described in SURVEY.md §2.8.

    Semantics identical to `TensorMDP.value_iteration` (same greedy
    backup, same stop rule); returns the same dict.  `impl` mirrors the
    single-device option: "while" (default) or "chunked" (fixed-size
    scan chunks + host-side convergence — the axon-TPU while_loop-fault
    workaround, needed here too or the capstone's on-chip sharded solve
    would hit the same fault); CPR_VI_IMPL sets the default.  `accel_m`
    opts the chunked impl into Anderson acceleration between chunks
    (explicit.run_chunk_driver — ~5x fewer sweeps on the fc16 PT-MDP,
    same fixpoint to stop_delta; the GhostDAG capstone turns it on).
    `checkpoint_path` (chunked impl only) opts into between-chunk
    crash checkpoints + resume — values/policies are replicated, so
    the host-side checkpoint seam is identical to the single-device
    driver's (docs/RESILIENCE.md).
    """
    stop_delta = tm.resolve_stop_delta(
        discount=discount, eps=eps, stop_delta=stop_delta, max_iter=max_iter)
    tm._check_segment_width()
    impl = resolve_vi_impl(impl)
    if checkpoint_path is not None and impl == "while":
        raise ValueError(
            "checkpoint_path requires impl='chunked': the while impl "
            "runs as one device program with no between-chunk seam")
    t0 = now()
    n = mesh.shape[axis]
    S, A = tm.n_states, tm.n_actions
    pad = (-tm.src.shape[0]) % n

    def padt(x):
        # zero-probability padding: inert in both the Bellman backup and
        # the probability-mass validity test of _valid_actions
        return jnp.concatenate([x, jnp.zeros((pad,), x.dtype)])

    coo = tuple(padt(x) for x in
                (tm.src, tm.act, tm.dst, tm.prob, tm.reward, tm.progress))
    max_iter_ = max_iter if max_iter > 0 else (1 << 30)

    @jax.jit
    def run():
        def body(src, act, dst, prob, reward, progress):
            return vi_while_loop(
                src, act, dst, prob, reward, progress, S, A, discount,
                stop_delta, max_iter_,
                reduce=lambda x: jax.lax.psum(x, axis))

        return _shard_map(
            body, mesh=mesh,
            in_specs=(P(axis),) * 6,
            out_specs=(P(),) * 6,
            check_vma=False,
        )(*coo)

    def run_chunked():
        @partial(jax.jit, static_argnums=(2,))
        def chunk_fn(value, prog, steps):
            def body(src, act, dst, prob, reward, progress, value, prog):
                psum = lambda x: jax.lax.psum(x, axis)  # noqa: E731
                # valid masks recomputed per chunk call (one extra
                # psum'd segment-sum per `chunk` sweeps, ~1/chunk
                # overhead) — hoisting them across shard_map calls
                # would need a second staged program for little gain
                valid, any_valid = _valid_actions(src, act, prob, S, A,
                                                  psum)
                return make_vi_chunk(S, A, psum)(
                    src, act, dst, prob, reward, progress, valid,
                    any_valid, discount, value, prog, steps)

            return _shard_map(
                body, mesh=mesh,
                in_specs=(P(axis),) * 6 + (P(), P()),
                out_specs=(P(),) * 4,
                check_vma=False,
            )(*coo, value, prog)

        return run_chunk_driver(chunk_fn, S, tm.prob.dtype, stop_delta,
                                max_iter_, chunk, accel_m=accel_m,
                                checkpoint_path=checkpoint_path,
                                checkpoint_every=checkpoint_every)

    if impl == "while":
        value, progress_v, policy, delta, it, resid = run()
        resid = ring_residuals(resid, int(it))
    else:
        value, progress_v, policy, delta, it, resid = run_chunked()
    resid = vi_residuals_event(impl, int(it), resid, stop_delta, delta)
    return dict(
        vi_discount=discount,
        vi_delta=float(delta),
        vi_stop_delta=stop_delta,
        vi_policy=np.asarray(policy),
        vi_value=np.asarray(value),
        vi_progress=np.asarray(progress_v),
        vi_iter=int(it),
        vi_max_iter=max_iter,
        vi_residuals=resid,
        vi_time=now() - t0,
    )
