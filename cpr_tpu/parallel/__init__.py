"""Device-mesh parallelism.

Reference counterpart: the reference has NO distributed backend — its
parallelism is fork-based task farms (experiments/simulate/csv_runner.ml:
105-131 via Parany) and process-per-env rollouts (experiments/train/
ppo.py:283 via SubprocVecEnv). See SURVEY.md §2.8 for the full mapping.

TPU re-design: three first-class parallel axes, all on one `jax.sharding.
Mesh` with XLA collectives over ICI (intra-slice) / DCN (across slices):

- env-batch data parallelism: `vmap` over episodes (free, no mesh),
- device data parallelism: episode batches sharded over the mesh
  (`shard_envs`),
- solver parallelism: value-iteration sweeps with transitions sharded
  over devices and `psum`-reduced Bellman backups
  (`sharded_value_iteration`) — the analog of model/tensor parallelism
  for the MDP workload.

The same code runs on a virtual CPU mesh (tests, CI) and on real TPU
slices; the mesh is the only seam.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cpr_tpu.mdp.explicit import TensorMDP, make_vi_sweep

__all__ = [
    "default_mesh",
    "shard_envs",
    "sharded_value_iteration",
    "sharded_rollout",
]


def default_mesh(axis: str = "d", devices=None) -> Mesh:
    """One-dimensional mesh over all (or the given) devices."""
    devices = jax.devices() if devices is None else devices
    return Mesh(np.asarray(devices), (axis,))


def shard_envs(mesh: Mesh, tree, axis: str = "d"):
    """Place a batched env state/keys PyTree with the batch dimension
    sharded over the mesh (device data parallelism for episode batches)."""
    sharding = NamedSharding(mesh, P(axis))
    return jax.device_put(tree, sharding)


def sharded_rollout(env, mesh: Mesh, keys, params, policy, n_steps: int,
                    axis: str = "d"):
    """vmap'd `JaxEnv.episode_stats` with the episode batch sharded over
    the mesh. XLA partitions the whole rollout program; no collectives
    are needed until the caller aggregates the returned stats."""
    keys = shard_envs(mesh, keys, axis)
    fn = jax.jit(jax.vmap(lambda k: env.episode_stats(k, params, policy, n_steps)))
    return fn(keys)


def sharded_value_iteration(tm: TensorMDP, mesh: Mesh, *, axis: str = "d",
                            max_iter: int = 0, discount: float = 1.0,
                            eps: float | None = None,
                            stop_delta: float | None = None):
    """Value iteration with the transition table sharded over the mesh.

    Each device owns a contiguous transition chunk (padded with
    zero-probability entries), computes a partial per-(state,action)
    backup with a local segment-sum, and the partial Q tables are
    `psum`-combined over ICI. Values/policies stay replicated, so each
    sweep is one all-reduce of an (S, A) table — the halo exchange for
    cross-shard transitions described in SURVEY.md §2.8.

    Semantics identical to `TensorMDP.value_iteration` (same greedy
    backup, same stop rule); returns the same dict.
    """
    stop_delta = tm.resolve_stop_delta(
        discount=discount, eps=eps, stop_delta=stop_delta, max_iter=max_iter)
    t0 = time.time()
    n = mesh.shape[axis]
    S, A = tm.n_states, tm.n_actions
    T = tm.src.shape[0]
    pad = (-T) % n

    def padt(x, fill=0):
        return jnp.concatenate([x, jnp.full((pad,), fill, x.dtype)])

    src = padt(tm.src)
    act = padt(tm.act)
    dst = padt(tm.dst)
    prob = padt(tm.prob)  # zero probability: contributes nothing
    reward = padt(tm.reward)
    progress = padt(tm.progress)
    max_iter_ = max_iter if max_iter > 0 else (1 << 30)

    # NOTE: padding entries have prob=0 but still count in the
    # action-validity mask if left at (src=0, act=0); mask on prob instead.
    def valid_reduce(x):
        return jax.lax.psum(x, axis)

    sweep = make_vi_sweep(S, A, reduce=valid_reduce)

    shard_map = jax.shard_map

    @jax.jit
    def run():
        spec = P(axis)
        rep = P()

        def body(src, act, dst, prob, reward, progress):
            # validity from probability mass, so padding is inert
            seg = src * jnp.int32(A) + act
            counts = jax.lax.psum(
                jax.ops.segment_sum(jnp.where(prob > 0, 1.0, 0.0), seg,
                                    num_segments=S * A), axis)
            valid = (counts > 0).reshape(S, A)
            any_valid = valid.any(axis=1)

            def cond(carry):
                _, _, _, delta, i = carry
                return (delta > stop_delta) & (i < max_iter_)

            def step(value, prog):
                return sweep(src, act, dst, prob, reward, progress, valid,
                             any_valid, discount, value, prog)

            def body_fn(carry):
                value, prog, _, _, i = carry
                v2, p2, pol = step(value, prog)
                return v2, p2, pol, jnp.abs(v2 - value).max(), i + 1

            z = jnp.zeros(S, prob.dtype)
            v, p, pol = step(z, z)
            delta = jnp.abs(v - z).max()
            return jax.lax.while_loop(cond, body_fn, (v, p, pol, delta, 1))

        return shard_map(
            body, mesh=mesh,
            in_specs=(spec,) * 6,
            out_specs=(rep, rep, rep, rep, rep),
            check_vma=False,
        )(src, act, dst, prob, reward, progress)

    value, progress_v, policy, delta, it = run()
    return dict(
        vi_discount=discount,
        vi_delta=float(delta),
        vi_stop_delta=stop_delta,
        vi_policy=np.asarray(policy),
        vi_value=np.asarray(value),
        vi_progress=np.asarray(progress_v),
        vi_iter=int(it),
        vi_max_iter=max_iter,
        vi_time=time.time() - t0,
    )
