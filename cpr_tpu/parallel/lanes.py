"""Sharded resident lane stepper: the multi-chip twin of the
`JaxEnv` lane API (envs/base.py).

`make_sharded_lane_fns(env, mesh)` rebuilds the three resident lane
entry points — `init_lanes` / `reset_lanes` / `step_lanes` — as jitted
programs whose lane batch is partitioned over a 1-D mesh axis with
`NamedSharding`, so one dispatch advances `n_lanes` streams spread
across every device on the axis.  The wrapped functions are the
CLASS-jitted originals (via `__wrapped__`), not re-implementations:
held-lane bit-freezing, mid-flight admission splicing, and the rollout
stream prologue are the same code, so a lane admitted with seed S
still replays `rollout(PRNGKey(S))` bit-for-bit — now on whichever
shard owns it (tests/test_sharded_lanes.py asserts bit-identity
against the single-device path).

The contract that makes chaining free (the pjit/pod pattern from
SNIPPETS.md): every fn takes and returns lane-major pytrees under the
SAME `NamedSharding(mesh, P(axis))`, params stay replicated, and the
carry is donated with matched in/out specs — so `init -> step -> step`
never inserts a resharding collective, and the donated carry aliases
in place on every shard.

Uneven batches are refused up front (`check_even_shards`): XLA's error
for a non-divisible sharded axis is opaque, and padding would break
the lane-index <-> session mapping the serving layer relies on.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from functools import partial

__all__ = ["check_even_shards", "make_sharded_lane_fns",
           "ShardedLaneFns"]


def check_even_shards(n: int, mesh: Mesh, *, axis: str = "d",
                      what: str = "lanes") -> int:
    """Refuse a batch that does not divide the mesh axis, naming both
    values — instead of XLA's opaque sharding error.  Returns the
    device count on the axis."""
    n_devices = int(mesh.shape[axis])
    n = int(n)
    if n_devices < 1:
        raise ValueError(f"mesh axis '{axis}' has no devices")
    if n % n_devices:
        raise ValueError(
            f"cannot shard {n} {what} evenly over {n_devices} devices "
            f"(mesh axis '{axis}': {n} % {n_devices} = "
            f"{n % n_devices}); use a multiple of the device count or "
            f"a smaller mesh")
    return n_devices


class ShardedLaneFns:
    """The three resident lane programs of one env, sharded over one
    mesh axis.  Mirrors the `JaxEnv` lane API call-for-call; build via
    `make_sharded_lane_fns`.

    Attributes `lane` / `replicated` are the two `NamedSharding`s every
    argument uses — callers staging their own lane-major programs on
    top (e.g. the serve burst) reuse them so specs stay matched across
    chained dispatches."""

    def __init__(self, env, mesh: Mesh, axis: str = "d"):
        self.env = env
        self.mesh = mesh
        self.axis = axis
        self.n_devices = int(mesh.shape[axis])
        if self.n_devices < 1:
            raise ValueError(f"mesh axis '{axis}' has no devices")
        self.lane = NamedSharding(mesh, P(axis))
        self.replicated = NamedSharding(mesh, P())

        # the CLASS-jitted originals (static self), unwrapped back to
        # plain functions so the sharded build is the same code with
        # different placement — behavior drift is impossible by
        # construction
        raw_init = type(env).init_lanes.__wrapped__
        raw_reset = type(env).reset_lanes.__wrapped__
        raw_step = type(env).step_lanes.__wrapped__

        # params replicate (scalar leaves); everything lane-major
        # shards on the leading axis.  Donation needs in-spec ==
        # out-spec for the carry, which holds: lane in, lane out.
        self._init = jax.jit(partial(raw_init, env),
                             in_shardings=(self.lane, self.replicated),
                             out_shardings=self.lane)
        self._reset = jax.jit(partial(raw_reset, env),
                              in_shardings=(self.lane, self.replicated),
                              out_shardings=self.lane)
        self._step = jax.jit(
            partial(raw_step, env), donate_argnums=0,
            in_shardings=(self.lane, self.lane, self.lane, self.lane,
                          self.lane, self.replicated),
            out_shardings=self.lane)

    def _check(self, n: int, what: str) -> None:
        check_even_shards(n, self.mesh, axis=self.axis, what=what)

    def shard(self, tree):
        """Commit a lane-major pytree to the lane sharding (committed
        arrays skip the implicit transfer on the next call)."""
        return jax.device_put(tree, self.lane)

    def init_lanes(self, keys, params):
        """Sharded `JaxEnv.init_lanes`: fresh per-lane (state, obs)
        via the rollout stream prologue, lane axis partitioned."""
        self._check(keys.shape[0], "lanes")
        return self._init(keys, params)

    def reset_lanes(self, keys, params):
        """Sharded `JaxEnv.reset_lanes` (raw vmapped reset)."""
        self._check(keys.shape[0], "lanes")
        return self._reset(keys, params)

    def step_lanes(self, carry, actions, admit_mask, fresh_states,
                   step_mask, params):
        """Sharded `JaxEnv.step_lanes`; the carry is DONATED and comes
        back under the same lane sharding (no resharding between
        chained calls).  Admission/hold semantics are the single-device
        ones, applied per shard."""
        self._check(actions.shape[0], "lanes")
        return self._step(carry, actions, admit_mask, fresh_states,
                          step_mask, params)


def make_sharded_lane_fns(env, mesh: Mesh, *,
                          axis: str = "d") -> ShardedLaneFns:
    """Build the sharded resident lane programs for `env` over `mesh`.

        mesh = default_mesh(devices=jax.devices()[:4])
        lanes = make_sharded_lane_fns(env, mesh)
        carry = lanes.init_lanes(keys, params)      # lane-sharded
        carry, out = lanes.step_lanes(carry, ...)   # donated, sharded

    The lane count of every call must divide the mesh axis
    (`check_even_shards`)."""
    return ShardedLaneFns(env, mesh, axis)
