"""Host-side latency histograms for the serving SLO plane.

The serving layer (cpr_tpu/serve) needs per-op-family latency
quantiles — p50/p95/p99 queue wait, service and total time — cheap
enough to update on every request and to snapshot on every heartbeat.
Like telemetry/ and perf/, this module is jax-free at import and
allocation-free on the observe path: a histogram is one fixed vector
of integer bucket counts over log-scale edges, so `observe` is a
bisect + increment and `snapshot` is a single pass.

Quantiles are estimated by log-linear interpolation inside the owning
bucket, clamped to the observed min/max.  With the default edges
(16 buckets per decade over 1 microsecond .. 1000 seconds) the
estimate is within ~7% of the true value anywhere in range, which is
far inside the verdict bands the perf gate applies to the banked
`serve_p50_s` / `serve_p99_s` rows (cpr_tpu/perf/gate.py).

`LatencyBoard` maps op families ("episode.run", "netsim.query", ...)
to histograms and is what the server embeds in its `stats` reply,
`heartbeat` event and drain `report` (docs/SERVING.md).
"""

from __future__ import annotations

import math
from bisect import bisect_right

# default edges: log-scale, _PER_DECADE buckets per decade spanning
# [10**_LO_EXP, 10**_HI_EXP) seconds — wide enough for a sub-10us
# device dispatch and a multi-minute break-even sweep alike
_LO_EXP = -6
_HI_EXP = 3
_PER_DECADE = 16


def default_edges() -> tuple:
    """The shared log-scale bucket edges (seconds), increasing."""
    n = (_HI_EXP - _LO_EXP) * _PER_DECADE + 1
    return tuple(10.0 ** (_LO_EXP + i / _PER_DECADE) for i in range(n))


class LatencyHistogram:
    """Fixed-bucket log-scale histogram of durations in seconds.

    Buckets are `len(edges) + 1` counts: (-inf, e0), [e0, e1), ...,
    [eN, inf) — underflow and overflow included, like the
    device_metrics hist cells."""

    __slots__ = ("edges", "counts", "count", "sum_s", "min_s", "max_s")

    def __init__(self, edges=None):
        self.edges = tuple(edges) if edges is not None else default_edges()
        if not self.edges or any(b <= a for a, b in
                                 zip(self.edges, self.edges[1:])):
            raise ValueError("edges must be non-empty and increasing")
        self.counts = [0] * (len(self.edges) + 1)
        self.count = 0
        self.sum_s = 0.0
        self.min_s = math.inf
        self.max_s = -math.inf

    def observe(self, dur_s: float):
        """Fold one duration (seconds; negatives clamp to 0 — clock
        skew between stamps must never corrupt the board)."""
        d = float(dur_s)
        if not math.isfinite(d):
            return
        d = max(0.0, d)
        self.counts[bisect_right(self.edges, d)] += 1
        self.count += 1
        self.sum_s += d
        self.min_s = min(self.min_s, d)
        self.max_s = max(self.max_s, d)

    def merge(self, other: "LatencyHistogram"):
        """Fold another histogram (same edges) into this one."""
        if other.edges != self.edges:
            raise ValueError("cannot merge histograms with differing edges")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.sum_s += other.sum_s
        self.min_s = min(self.min_s, other.min_s)
        self.max_s = max(self.max_s, other.max_s)

    def quantile(self, q: float) -> float | None:
        """Estimated q-quantile (0..1) in seconds, or None when empty.
        Log-linear interpolation inside the owning bucket, clamped to
        the observed [min, max] so a one-sample histogram reports the
        sample, not a bucket edge."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        if self.count == 0:
            return None
        rank = q * self.count
        seen = 0.0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if seen + c >= rank:
                frac = 0.0 if c == 0 else min(1.0, max(
                    0.0, (rank - seen) / c))
                val = self._interp(i, frac)
                return min(self.max_s, max(self.min_s, val))
            seen += c
        return self.max_s

    def _interp(self, bucket: int, frac: float) -> float:
        # underflow/overflow buckets have one open side: report the
        # closed edge (clamping to min/max refines it anyway)
        if bucket == 0:
            return self.edges[0]
        if bucket == len(self.edges):
            return self.edges[-1]
        lo, hi = self.edges[bucket - 1], self.edges[bucket]
        if lo <= 0:
            return lo + frac * (hi - lo)
        return lo * (hi / lo) ** frac

    def snapshot(self) -> dict:
        """JSON-ready summary: count/sum/min/max/mean + p50/p95/p99."""
        if self.count == 0:
            return {"count": 0}
        return {
            "count": self.count,
            "sum_s": self.sum_s,
            "min_s": self.min_s,
            "max_s": self.max_s,
            "mean_s": self.sum_s / self.count,
            "p50_s": self.quantile(0.50),
            "p95_s": self.quantile(0.95),
            "p99_s": self.quantile(0.99),
        }


# family-cardinality bound: per-tenant / per-class labels make the
# family space attacker-controlled under multi-tenant traffic, so a
# board never allocates more than `max_families` histograms — later
# novel families fold into one shared overflow bucket instead
OVERFLOW_FAMILY = "__overflow__"
DEFAULT_MAX_FAMILIES = 64


class LatencyBoard:
    """Per-op-family latency histograms, lazily created on first
    observe (families are dynamic: every serve op plus the engine's
    device families land here).  Cardinality is bounded: once
    `max_families` distinct families exist, observations for novel
    families land in the shared `OVERFLOW_FAMILY` histogram — memory
    stays O(max_families) however many labels clients invent."""

    def __init__(self, edges=None, max_families: int = DEFAULT_MAX_FAMILIES):
        if max_families <= 0:
            raise ValueError(f"max_families must be positive, "
                             f"got {max_families}")
        self._edges = tuple(edges) if edges is not None else default_edges()
        self.max_families = max_families
        self._hists: dict[str, LatencyHistogram] = {}

    def observe(self, family: str, dur_s: float):
        h = self._hists.get(family)
        if h is None:
            if (len(self._hists) >= self.max_families
                    and family != OVERFLOW_FAMILY):
                # the overflow family itself may be minted past the cap
                # (it IS the cap's escape hatch)
                return self.observe(OVERFLOW_FAMILY, dur_s)
            h = self._hists[family] = LatencyHistogram(self._edges)
        h.observe(dur_s)

    def get(self, family: str) -> LatencyHistogram | None:
        return self._hists.get(family)

    @property
    def families(self) -> tuple:
        return tuple(sorted(self._hists))

    def snapshot(self) -> dict:
        """{family: histogram snapshot} over every family observed."""
        return {k: self._hists[k].snapshot() for k in self.families}
