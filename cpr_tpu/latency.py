"""Host-side latency histograms for the serving SLO plane.

The serving layer (cpr_tpu/serve) needs per-op-family latency
quantiles — p50/p95/p99 queue wait, service and total time — cheap
enough to update on every request and to snapshot on every heartbeat.
Like telemetry/ and perf/, this module is jax-free at import and
allocation-free on the observe path: a histogram is one fixed vector
of integer bucket counts over log-scale edges, so `observe` is a
bisect + increment and `snapshot` is a single pass.

Quantiles are estimated by log-linear interpolation inside the owning
bucket, clamped to the observed min/max.  With the default edges
(16 buckets per decade over 1 microsecond .. 1000 seconds) the
estimate is within ~7% of the true value anywhere in range, which is
far inside the verdict bands the perf gate applies to the banked
`serve_p50_s` / `serve_p99_s` rows (cpr_tpu/perf/gate.py).

`LatencyBoard` maps op families ("episode.run", "netsim.query", ...)
to histograms and is what the server embeds in its `stats` reply,
`heartbeat` event and drain `report` (docs/SERVING.md).
"""

from __future__ import annotations

import math
from bisect import bisect_right

# default edges: log-scale, _PER_DECADE buckets per decade spanning
# [10**_LO_EXP, 10**_HI_EXP) seconds — wide enough for a sub-10us
# device dispatch and a multi-minute break-even sweep alike
_LO_EXP = -6
_HI_EXP = 3
_PER_DECADE = 16


def default_edges() -> tuple:
    """The shared log-scale bucket edges (seconds), increasing."""
    n = (_HI_EXP - _LO_EXP) * _PER_DECADE + 1
    return tuple(10.0 ** (_LO_EXP + i / _PER_DECADE) for i in range(n))


class LatencyHistogram:
    """Fixed-bucket log-scale histogram of durations in seconds.

    Buckets are `len(edges) + 1` counts: (-inf, e0), [e0, e1), ...,
    [eN, inf) — underflow and overflow included, like the
    device_metrics hist cells."""

    __slots__ = ("edges", "counts", "count", "sum_s", "min_s", "max_s")

    def __init__(self, edges=None):
        self.edges = tuple(edges) if edges is not None else default_edges()
        if not self.edges or any(b <= a for a, b in
                                 zip(self.edges, self.edges[1:])):
            raise ValueError("edges must be non-empty and increasing")
        self.counts = [0] * (len(self.edges) + 1)
        self.count = 0
        self.sum_s = 0.0
        self.min_s = math.inf
        self.max_s = -math.inf

    def observe(self, dur_s: float):
        """Fold one duration (seconds; negatives clamp to 0 — clock
        skew between stamps must never corrupt the board)."""
        d = float(dur_s)
        if not math.isfinite(d):
            return
        d = max(0.0, d)
        self.counts[bisect_right(self.edges, d)] += 1
        self.count += 1
        self.sum_s += d
        self.min_s = min(self.min_s, d)
        self.max_s = max(self.max_s, d)

    def merge(self, other: "LatencyHistogram"):
        """Fold another histogram (same edges) into this one."""
        if other.edges != self.edges:
            raise ValueError("cannot merge histograms with differing edges")
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.sum_s += other.sum_s
        self.min_s = min(self.min_s, other.min_s)
        self.max_s = max(self.max_s, other.max_s)

    def quantile(self, q: float) -> float | None:
        """Estimated q-quantile (0..1) in seconds, or None when empty.
        Log-linear interpolation inside the owning bucket, clamped to
        the observed [min, max] so a one-sample histogram reports the
        sample, not a bucket edge."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        if self.count == 0:
            return None
        rank = q * self.count
        seen = 0.0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if seen + c >= rank:
                frac = 0.0 if c == 0 else min(1.0, max(
                    0.0, (rank - seen) / c))
                val = self._interp(i, frac)
                return min(self.max_s, max(self.min_s, val))
            seen += c
        return self.max_s

    def _interp(self, bucket: int, frac: float) -> float:
        # underflow/overflow buckets have one open side: report the
        # closed edge (clamping to min/max refines it anyway)
        if bucket == 0:
            return self.edges[0]
        if bucket == len(self.edges):
            return self.edges[-1]
        lo, hi = self.edges[bucket - 1], self.edges[bucket]
        if lo <= 0:
            return lo + frac * (hi - lo)
        return lo * (hi / lo) ** frac

    def snapshot(self) -> dict:
        """JSON-ready summary: count/sum/min/max/mean + p50/p95/p99."""
        if self.count == 0:
            return {"count": 0}
        return {
            "count": self.count,
            "sum_s": self.sum_s,
            "min_s": self.min_s,
            "max_s": self.max_s,
            "mean_s": self.sum_s / self.count,
            "p50_s": self.quantile(0.50),
            "p95_s": self.quantile(0.95),
            "p99_s": self.quantile(0.99),
        }

    # -- wire form (v14 fleet merge) ------------------------------------
    #
    # A snapshot() carries quantile ESTIMATES, which cannot be merged
    # (quantile-of-quantiles is wrong in general); the wire form below
    # carries the raw bucket counts, so a router can reconstruct a
    # replica's histogram and bucket-sum it into a fleet board exactly.
    # Buckets ship sparse ([index, count] pairs over the nonzero cells)
    # — with the default 145 edges a lightly-loaded family is a handful
    # of pairs, not a 146-zero vector per heartbeat.

    def to_dict(self) -> dict:
        """Raw mergeable form: sparse nonzero buckets + exact moments.
        `n_edges` guards the merge — histograms only combine when built
        over the same edge vector (from_dict re-checks)."""
        return {
            "n_edges": len(self.edges),
            "count": self.count,
            "sum_s": self.sum_s,
            "min_s": None if self.count == 0 else self.min_s,
            "max_s": None if self.count == 0 else self.max_s,
            "buckets": [[i, c] for i, c in enumerate(self.counts) if c],
        }

    @classmethod
    def from_dict(cls, raw: dict, edges=None) -> "LatencyHistogram":
        """Rebuild a histogram from `to_dict` output onto `edges`
        (default shared edges).  Raises ValueError on an edge-count or
        bucket-index mismatch — a silent misalignment here would corrupt
        every fleet quantile downstream."""
        h = cls(edges)
        if int(raw.get("n_edges", -1)) != len(h.edges):
            raise ValueError(
                f"histogram wire form built over {raw.get('n_edges')} "
                f"edges, expected {len(h.edges)}")
        for i, c in raw.get("buckets", ()):
            i, c = int(i), int(c)
            if not 0 <= i < len(h.counts):
                raise ValueError(f"bucket index {i} out of range "
                                 f"[0, {len(h.counts)})")
            if c < 0:
                raise ValueError(f"negative bucket count {c}")
            h.counts[i] += c
        h.count = int(raw.get("count", 0))
        h.sum_s = float(raw.get("sum_s", 0.0))
        if h.count != sum(h.counts):
            raise ValueError(
                f"bucket counts sum to {sum(h.counts)}, header says "
                f"{h.count}")
        if h.count:
            h.min_s = float(raw["min_s"])
            h.max_s = float(raw["max_s"])
        return h


# family-cardinality bound: per-tenant / per-class labels make the
# family space attacker-controlled under multi-tenant traffic, so a
# board never allocates more than `max_families` histograms — later
# novel families fold into one shared overflow bucket instead
OVERFLOW_FAMILY = "__overflow__"
DEFAULT_MAX_FAMILIES = 64


class LatencyBoard:
    """Per-op-family latency histograms, lazily created on first
    observe (families are dynamic: every serve op plus the engine's
    device families land here).  Cardinality is bounded: once
    `max_families` distinct families exist, observations for novel
    families land in the shared `OVERFLOW_FAMILY` histogram — memory
    stays O(max_families) however many labels clients invent."""

    def __init__(self, edges=None, max_families: int = DEFAULT_MAX_FAMILIES):
        if max_families <= 0:
            raise ValueError(f"max_families must be positive, "
                             f"got {max_families}")
        self._edges = tuple(edges) if edges is not None else default_edges()
        self.max_families = max_families
        self._hists: dict[str, LatencyHistogram] = {}

    def observe(self, family: str, dur_s: float):
        h = self._hists.get(family)
        if h is None:
            if (len(self._hists) >= self.max_families
                    and family != OVERFLOW_FAMILY):
                # the overflow family itself may be minted past the cap
                # (it IS the cap's escape hatch)
                return self.observe(OVERFLOW_FAMILY, dur_s)
            h = self._hists[family] = LatencyHistogram(self._edges)
        h.observe(dur_s)

    def get(self, family: str) -> LatencyHistogram | None:
        return self._hists.get(family)

    @property
    def families(self) -> tuple:
        return tuple(sorted(self._hists))

    def snapshot(self) -> dict:
        """{family: histogram snapshot} over every family observed."""
        return {k: self._hists[k].snapshot() for k in self.families}

    def to_dict(self) -> dict:
        """{family: raw histogram wire form} — the mergeable companion
        to `snapshot()` (v14: replicas ship this to the router, which
        bucket-sums it into the fleet board via `merge_dict`)."""
        return {k: self._hists[k].to_dict() for k in self.families}

    def merge_dict(self, raw: dict):
        """Exact bucket-sum merge of a `to_dict` payload into this
        board.  Families novel past `max_families` fold into
        `OVERFLOW_FAMILY` (merged there, not dropped) — the same
        cardinality bound `observe` applies, so a hostile replica
        payload cannot blow up router memory."""
        for family in sorted(raw):
            h = LatencyHistogram.from_dict(raw[family], self._edges)
            dst = self._hists.get(family)
            if dst is None:
                if (len(self._hists) >= self.max_families
                        and family != OVERFLOW_FAMILY):
                    family = OVERFLOW_FAMILY
                    dst = self._hists.get(family)
            if dst is None:
                dst = self._hists[family] = LatencyHistogram(self._edges)
            dst.merge(h)
