"""Crash-safety layer: atomic writes, full-state snapshots, retry with
backoff, preemption handling, and deterministic fault injection.

TPU preemption and device faults are routine at production scale, so
every long-running loop in the repo (PPO training, chunked VI solves,
the bench watchdog) funnels its recovery logic through this module:

* **Atomic writes** — `atomic_write_bytes`/`atomic_write_json`: tmp
  file in the destination directory + fsync + `os.replace`, so a crash
  mid-write can never leave a half-written artifact under the final
  name.  A reader sees the old file or the new file, nothing else.

* **Full-state train snapshots** — `save_train_snapshot` /
  `load_train_snapshot` serialize the ENTIRE train carry (TrainState
  params + opt_state + step, env state, live observations, PRNG key)
  plus best/revert bookkeeping via flax msgpack, with the manifest
  embedded in the payload (a sidecar `.json` rides along for humans,
  but resume trusts only the atomically-written msgpack — a crash
  between two file renames cannot produce a torn pair).  Restoring the
  snapshot and continuing is bit-identical to never having stopped
  (proven by tests/test_resilience.py and `make resilience-smoke`).

* **Retry/backoff** — `with_retries(fn, classify=...)`: exponential
  backoff + jitter, a `retry` telemetry event per re-attempt, and a
  classifier that separates deterministic failures (`GuardFailure` —
  retrying cannot help and must not mask the signal) from transient
  device faults (worth re-attempting).  `AssertionError` is
  deliberately *retryable*: assertions raised inside jax internals are
  infra failures and must not masquerade as guard failures (bench.py
  invariant, now shared and under test).

* **Preemption** — `preemption_guard()` installs SIGTERM/SIGINT
  handlers that set a flag; loops poll `preempt_requested()` between
  updates, write a final snapshot + `preempt-model.msgpack`, emit a
  `preempted` event, and return cleanly (TPU preemption-notice
  semantics: you get seconds, not minutes).

* **Fault injection** — `CPR_FAULT_INJECT="kill@update=7"` (grammar in
  docs/RESILIENCE.md) arms one-shot faults at named sites
  (`fault_point("update", i)` in the loops), so every recovery path
  above is exercised by fast deterministic CPU tests instead of hoping
  a real outage finds the bugs first.  The `hang` action blocks at the
  site (CPR_FAULT_HANG_S seconds) instead of raising — the wedge mode
  the axon backend actually exhibits — so the supervisor's heartbeat
  stall detection (cpr_tpu/supervisor.py) is provable the same way.

Import-time this module is jax-free (flax/numpy are imported inside
the snapshot helpers) so bench.py's parent process can use the retry
machinery without initializing a backend.
"""

from __future__ import annotations

import io
import json
import os
import random
import signal
import tempfile
import threading
import time
from contextlib import contextmanager
from typing import Callable

from cpr_tpu import integrity, telemetry
from cpr_tpu.integrity import IntegrityError  # re-export  # noqa: F401

SNAPSHOT_VERSION = 1
FAULT_ENV_VAR = "CPR_FAULT_INJECT"

# metrics.jsonl keys that legitimately differ between two bit-identical
# runs (fenced wall time and its derived rate) — stripped by
# `metrics_fingerprint` before any determinism comparison
VOLATILE_METRIC_KEYS = ("wall_s", "steps_per_sec")


# -- failure taxonomy --------------------------------------------------------


class GuardFailure(Exception):
    """A deterministic correctness-guard violation — distinct from
    AssertionError so assertions raised inside jax internals or env code
    cannot masquerade as guard failures and suppress the retry/descent
    ladder (they are infra failures and should be retried).  Never
    retried: the same inputs will fail the same way, and a retry would
    only bury the signal."""


class TransientFault(Exception):
    """A failure worth re-attempting: transient chip claims, I/O
    hiccups, a recovering worker.  Raisers may attach context (bench
    attaches the child's return code as `.rc`)."""


class InjectedFault(Exception):
    """Base for faults raised by the CPR_FAULT_INJECT harness."""


class InjectedKill(InjectedFault):
    """Simulated hard kill at a fault point.  Classified fatal (a real
    SIGKILL cannot be retried from inside the process) so it unwinds
    the whole loop exactly like the crash it stands in for."""


def default_classify(exc: BaseException) -> bool:
    """Shared retry classifier: True = transient, worth retrying.

    Deterministic failures (GuardFailure) and simulated kills are
    fatal; everything else derived from Exception — including
    AssertionError, per the masquerade invariant above — is presumed
    transient.  with_retries only ever catches Exception, so
    KeyboardInterrupt/SystemExit always propagate regardless."""
    return not isinstance(exc, (GuardFailure, InjectedKill))


def with_retries(fn: Callable, *, classify: Callable | None = None,
                 max_attempts: int = 3, base_delay_s: float = 0.5,
                 max_delay_s: float = 30.0, jitter_frac: float = 0.25,
                 jitter: str = "additive",
                 sleep: Callable = time.sleep, rng=None,
                 on_retry: Callable | None = None, name: str | None = None):
    """Call `fn()` with exponential backoff on transient failures.

    With the default `jitter="additive"`, delay before attempt k+1 is
    `min(base * 2**(k-1), max) * (1 + j)`, j uniform in
    [0, jitter_frac) — enough to decorrelate a couple of workers
    chasing the same recovering device, but a whole fleet retrying the
    same shed still clumps near the deterministic floor.
    `jitter="full"` uses AWS-style full jitter instead: delay uniform
    in [0, min(base * 2**(k-1), max)] — the fleet spreads over the
    entire window, at the cost of occasional near-zero delays (the
    serve client's shed-retry path wants this; a lone bench worker
    does not).  Each re-attempt emits a `retry` telemetry event
    (attempt, delay_s, error) and calls `on_retry(attempt, exc,
    delay_s)` if given (bench uses it to stamp worker-fault
    timestamps).  `classify(exc) -> bool` decides retryability
    (default: `default_classify`); a fatal exception or the last
    attempt's failure re-raises immediately."""
    classify = classify or default_classify
    if jitter not in ("additive", "full"):
        raise ValueError(f"jitter must be 'additive' or 'full', "
                         f"got {jitter!r}")
    rand = rng if rng is not None else random.random
    label = name or getattr(fn, "__name__", "fn")
    for attempt in range(1, max_attempts + 1):
        try:
            return fn()
        except Exception as exc:  # noqa: BLE001 — classifier decides
            if attempt >= max_attempts or not classify(exc):
                raise
            cap = min(base_delay_s * (2.0 ** (attempt - 1)), max_delay_s)
            if jitter == "full":
                delay = cap * rand()
            else:
                delay = cap * (1.0 + jitter_frac * rand())
            telemetry.current().event(
                "retry", attempt=attempt, delay_s=round(delay, 3),
                error=f"{type(exc).__name__}: {exc}", site=label)
            if on_retry is not None:
                on_retry(attempt, exc, delay)
            sleep(delay)


# -- atomic writes -----------------------------------------------------------


def atomic_write_bytes(path: str, data: bytes):
    """Write `data` to `path` atomically: tmp file in the same
    directory (os.replace cannot cross filesystems), fsync, rename.
    On any failure the tmp file is removed and `path` is untouched."""
    path = os.path.abspath(path)
    d = os.path.dirname(path)
    os.makedirs(d or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    # best-effort directory fsync so the rename itself is durable
    try:
        dfd = os.open(d or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass


def atomic_write_json(path: str, obj):
    atomic_write_bytes(path, (json.dumps(obj, indent=2, default=str)
                              + "\n").encode())


def atomic_write_text(path: str, text: str, encoding: str = "utf-8"):
    atomic_write_bytes(path, text.encode(encoding))


# -- sealed (checksummed) artifact writes ------------------------------------
#
# v16: the single write/read seam every persisted artifact funnels
# through.  `sealed_write` = atomic_write_bytes of the payload wrapped
# in integrity.seal's envelope (magic + seal schema + length + sha256
# on one ASCII header line), then the artifact-damage fault point for
# the site — so chaos specs corrupt exactly what production storage
# would.  `sealed_read` verifies the envelope before ANY deserializer
# sees the bytes; on damage the file is quarantined
# (<path>.quarantine/), one typed v16 `integrity` event fires with the
# caller-declared recovery action, and IntegrityError propagates for
# the caller's policy (miss-and-recompute, fall back to cold start,
# refuse).  Pre-v19 unsealed artifacts pass through tagged
# "unverified" — the compat shim, not a verification.


def sealed_write(path: str, data: bytes, *, site: str | None = None,
                 schema: int = integrity.SEAL_SCHEMA):
    """Atomically write `data` wrapped in the checksummed envelope.
    `site` names the artifact-damage fault site armed by chaos specs
    (checkpoint, vi_chunk, compile_round, cache, snapshot...)."""
    atomic_write_bytes(path, integrity.seal(data, schema=schema))
    if site is not None:
        artifact_fault_point(site, path)


def sealed_write_json(path: str, obj, *, site: str | None = None):
    sealed_write(path, (json.dumps(obj, indent=2, default=str)
                        + "\n").encode(), site=site)


def sealed_read(path: str, *, kind: str = "artifact",
                action: str = "quarantined",
                sidecars: tuple = (".json",)) -> tuple[bytes, str]:
    """Read + verify a sealed artifact.  Returns (payload, tag), tag
    "verified" for an intact envelope or "unverified" for a pre-v19
    unsealed file (compat shim — the downstream deserializer is then
    the detector of last resort).  On a damaged envelope the artifact
    moves to <path>.quarantine/, one `integrity` event fires with the
    caller's declared recovery `action` (quarantined | regenerated |
    refused), and the typed IntegrityError propagates."""
    with open(path, "rb") as f:
        data = f.read()
    try:
        return integrity.unseal(data, artifact=path, kind=kind)
    except IntegrityError as exc:
        integrity.quarantine(path, kind=kind, reason=exc.reason,
                             action=action, sidecars=sidecars)
        raise


def sealed_read_json(path: str, *, kind: str = "artifact",
                     action: str = "quarantined") -> tuple[dict, str]:
    """`sealed_read` + JSON decode, with a decode failure of the
    *verified or legacy* payload handled exactly like a torn envelope
    (quarantine + typed event + IntegrityError) — a cache entry that
    parses is the only cache entry that exists."""
    payload, tag = sealed_read(path, kind=kind, action=action)
    try:
        return json.loads(payload.decode("utf-8", "replace")), tag
    except ValueError:
        integrity.quarantine(path, kind=kind, reason="truncated",
                             action=action)
        raise IntegrityError(
            f"{kind} {path}: payload is not valid JSON",
            artifact=path, kind=kind, reason="truncated") from None


def reject_undecodable(path: str, *, kind: str, err,
                       action: str = "quarantined") -> IntegrityError:
    """A payload that cleared (or predates) the envelope but fails to
    deserialize is corruption the envelope could not see — a garbled
    pre-v19 file, or damage that happened before the seal was written.
    Same recovery path as a torn envelope: quarantine, one typed
    event, and a returned IntegrityError for the caller to raise."""
    integrity.quarantine(path, kind=kind, reason="truncated",
                         action=action)
    return IntegrityError(
        f"{kind} {path}: payload does not deserialize ({err})",
        artifact=path, kind=kind, reason="truncated")


# -- deterministic fault injection -------------------------------------------

_ACTIONS = ("kill", "io_error", "fault", "nan", "preempt", "hang",
            "slow") + integrity.ARTIFACT_ACTIONS
# occurrence-counted sites (kill@vi_chunk=3 means the third pass)
_COUNTED_SITES = ("checkpoint", "vi_chunk", "compile_round")
# artifact-damage actions (v16): fire at *write* sites through
# `artifact_fault_point(site, path)` — the just-written file is
# damaged in place (bit flip / truncation / JSON garbling via
# integrity.damage_artifact), the deterministic stand-in for storage
# corruption.  They keep their own per-site occurrence counters
# (`corrupt@vi_chunk=2` = the 2nd checkpoint WRITE at that site), so
# arming them never perturbs the indices of the compute-site actions
# above at the same site name.
_ARTIFACT_ACTIONS = integrity.ARTIFACT_ACTIONS

# how long an injected `hang` blocks.  The default approximates a truly
# wedged process (the supervisor's watchdog must kill the child, exactly
# as with a real axon wedge); in-process grammar tests set it tiny so
# `fire` returns and the one-shot/count bookkeeping can be asserted.
HANG_DURATION_ENV_VAR = "CPR_FAULT_HANG_S"
_DEFAULT_HANG_S = 3600.0

# how long an injected `slow` sleeps before RETURNING (v15): unlike
# `hang` it is a cooperative, bounded slowdown — the site survives,
# just late — which is what a regression looks like in a trace.  The
# obs smoke injects one at a serve burst and asserts trace_diff names
# the phase that ate it (tools/obs_smoke.py).
SLOW_DURATION_ENV_VAR = "CPR_FAULT_SLOW_S"
_DEFAULT_SLOW_S = 0.75


class FaultSpec:
    """One armed fault: `action@site=index` (e.g. `kill@update=7`), or
    bare `action@site` for index 1 — the first pass, which is the whole
    story for sites hit once per process (the supervisor's `probe` and
    `run`).  Sites with an explicit loop index (`update`) match that
    index; occurrence-counted sites (`checkpoint`, `vi_chunk`) match
    the n-th time the process passes the site.  One-shot: fires once,
    then disarms — a resumed run re-entering the same index must not
    re-fire (the injected crash already happened)."""

    def __init__(self, raw: str):
        self.raw = raw.strip()
        try:
            if "=" in self.raw:
                action_site, idx = self.raw.split("=")
                self.index = int(idx)
            else:
                action_site = self.raw
                self.index = 1
            self.action, self.site = action_site.split("@")
        except ValueError:
            raise ValueError(
                f"bad fault spec {raw!r}: want action@site[=index] "
                f"(e.g. kill@update=7, hang@probe)") from None
        if self.action not in _ACTIONS:
            raise ValueError(f"bad fault action {self.action!r}: "
                             f"one of {_ACTIONS}")
        self.armed = True


def parse_fault_specs(spec: str) -> list[FaultSpec]:
    """Parse a comma-separated CPR_FAULT_INJECT value."""
    return [FaultSpec(part) for part in spec.split(",") if part.strip()]


class FaultInjector:
    """Holds the armed specs + per-site occurrence counters."""

    def __init__(self, specs):
        self.specs = list(specs)
        self.counts: dict[str, int] = {}

    def fire(self, site: str, index: int | None = None) -> str | None:
        """Called at a fault point.  Returns the action name for
        cooperative actions ("nan", "preempt"), None when nothing
        fires; raises for "kill"/"io_error"/"fault".  Artifact-damage
        specs never fire here — they live on the write path
        (`fire_artifact`) with their own counters."""
        if index is None:
            index = self.counts.get(site, 0) + 1
            self.counts[site] = index
        for s in self.specs:
            if s.action in _ARTIFACT_ACTIONS:
                continue
            if not (s.armed and s.site == site and s.index == index):
                continue
            s.armed = False
            telemetry.current().event(
                "fault_injected", spec=s.raw, site=site, index=index)
            if s.action == "kill":
                raise InjectedKill(s.raw)
            if s.action == "io_error":
                raise OSError(f"injected I/O error ({s.raw})")
            if s.action == "fault":
                raise TransientFault(f"injected device fault ({s.raw})")
            if s.action == "preempt":
                request_preempt(f"injected ({s.raw})")
            if s.action == "hang":
                # a wedged backend neither returns nor raises — block
                # (the fault_injected event above already hit the sink,
                # so the trace records WHERE the hang was injected even
                # though this process is about to be killed)
                time.sleep(float(os.environ.get(
                    HANG_DURATION_ENV_VAR, _DEFAULT_HANG_S)))
            if s.action == "slow":
                # a bounded cooperative slowdown: sleep, then continue
                # — the deterministic stand-in for a perf regression
                # (the site's own timers absorb the sleep, so the delay
                # lands in whatever span/latency family covers it)
                time.sleep(float(os.environ.get(
                    SLOW_DURATION_ENV_VAR, _DEFAULT_SLOW_S)))
            return s.action
        return None

    def fire_artifact(self, site: str, path: str,
                      index: int | None = None) -> str | None:
        """Called right after an artifact lands at `path` on a write
        site.  Matches only artifact-damage specs (`corrupt@`,
        `truncate@`, `garble_json@`), counted in a namespace of their
        own (`<site>#artifact`) so `corrupt@vi_chunk=2` means the 2nd
        checkpoint *write* regardless of how many compute passes the
        plain `vi_chunk` fault point has counted.  Damages the file in
        place and returns the action name (None when nothing fires)."""
        key = site + "#artifact"
        if index is None:
            index = self.counts.get(key, 0) + 1
            self.counts[key] = index
        for s in self.specs:
            if s.action not in _ARTIFACT_ACTIONS:
                continue
            if not (s.armed and s.site == site and s.index == index):
                continue
            s.armed = False
            telemetry.current().event(
                "fault_injected", spec=s.raw, site=site, index=index,
                artifact=path)
            integrity.damage_artifact(path, s.action)
            return s.action
        return None


_injector: FaultInjector | None = None
_injector_src: str | None = None


def injector() -> FaultInjector:
    """The process-wide injector, rebuilt (counters and armed state
    reset) whenever the CPR_FAULT_INJECT value changes — so a resumed
    run that unsets the var runs clean."""
    global _injector, _injector_src
    src = os.environ.get(FAULT_ENV_VAR, "")
    if _injector is None or src != _injector_src:
        _injector = FaultInjector(parse_fault_specs(src))
        _injector_src = src
    return _injector


def fault_point(site: str, index: int | None = None) -> str | None:
    """Mark a named fault-injection site.  `index` pins loop-indexed
    sites (`update`); counted sites (`checkpoint`, `vi_chunk`) pass
    None.  Free when CPR_FAULT_INJECT is unset (one dict lookup)."""
    return injector().fire(site, index)


def artifact_fault_point(site: str, path: str,
                         index: int | None = None) -> str | None:
    """Mark a named artifact-write site: called by `sealed_write` (and
    the ledger's append path) right after the artifact is durably at
    `path`, so an armed `corrupt@`/`truncate@`/`garble_json@` spec can
    damage exactly the n-th write.  Free when nothing is armed."""
    return injector().fire_artifact(site, path, index)


# -- preemption --------------------------------------------------------------

_PREEMPT = {"requested": False, "reason": None}


def request_preempt(reason: str = "signal"):
    _PREEMPT["requested"] = True
    _PREEMPT["reason"] = reason


def preempt_requested() -> bool:
    return _PREEMPT["requested"]


def preempt_reason() -> str | None:
    return _PREEMPT["reason"]


@contextmanager
def preemption_guard(signals=(signal.SIGTERM, signal.SIGINT)):
    """Install SIGTERM/SIGINT handlers that request a cooperative stop
    instead of unwinding mid-update.  The flag is cleared on entry and
    polled by the training loop between updates; previous handlers are
    restored on exit.  Off the main thread (where Python forbids
    signal handlers) this degrades to a plain flag guard — injected
    `preempt@...` faults still work."""
    _PREEMPT["requested"] = False
    _PREEMPT["reason"] = None
    prev = {}
    if threading.current_thread() is threading.main_thread():
        def handler(signum, frame):
            request_preempt(signal.Signals(signum).name)
        for s in signals:
            prev[s] = signal.signal(s, handler)
    try:
        yield _PREEMPT
    finally:
        for s, h in prev.items():
            signal.signal(s, h)


# -- full-state train snapshots ----------------------------------------------
#
# Payload layout (flax msgpack, one atomically-written file):
#   {"meta": {"version", "update", "has_best", "best"},
#    "carry": (TrainState, env_state, obs, key),
#    "best_params": params-shaped tree (== carry params when no best)}
# The meta rides INSIDE the payload: a sidecar written in a second
# rename could tear against the payload (new data + old meta claims
# the wrong update index and silently corrupts the resumed history).
# The sidecar `.json` exists for humans and tooling only.


def _meta_template() -> dict:
    return {"version": 0, "update": 0, "has_best": 0, "best": 0.0}


def save_train_snapshot(path: str, carry, *, update: int,
                        best: float | None = None, best_params=None,
                        config: dict | None = None):
    """Atomically snapshot the full train carry + best/revert state.
    `best_params=None` (no eval yet) stores the current params with
    `has_best=0` — flax's from_bytes needs a params-shaped tree either
    way."""
    from flax import serialization

    has_best = best_params is not None
    finite_best = (best is not None and best == best
                   and best not in (float("inf"), float("-inf")))
    meta = {"version": SNAPSHOT_VERSION, "update": int(update),
            "has_best": int(has_best),
            "best": float(best) if finite_best else 0.0}
    payload = {"meta": meta, "carry": carry,
               "best_params": best_params if has_best else carry[0].params}
    sealed_write(path, serialization.to_bytes(payload), site="checkpoint")
    sidecar = dict(meta, time_utc=telemetry.run_manifest()["time_utc"])
    if config is not None:
        sidecar["config"] = config
    atomic_write_json(path + ".json", sidecar)


def load_train_snapshot(path: str, template_carry):
    """Restore a snapshot into the shape of `template_carry` (a fresh
    `init_fn(...)` carry for the same config).  Returns
    (carry, best_params_or_None, meta)."""
    from flax import serialization

    template = {"meta": _meta_template(), "carry": template_carry,
                "best_params": template_carry[0].params}
    payload, tag = sealed_read(path, kind="train_snapshot")
    try:
        restored = serialization.from_bytes(template, payload)
    except IntegrityError:
        raise
    except Exception as e:  # msgpack raises its own hierarchy
        raise reject_undecodable(path, kind="train_snapshot",
                                 err=e) from e
    meta = dict(restored["meta"], integrity=tag)
    if meta["version"] != SNAPSHOT_VERSION:
        raise ValueError(
            f"snapshot {path} has version {meta['version']}, "
            f"this build reads version {SNAPSHOT_VERSION}")
    best_params = restored["best_params"] if meta["has_best"] else None
    if not meta["has_best"]:
        meta["best"] = None
    return restored["carry"], best_params, meta


# -- VI solve checkpoints ----------------------------------------------------
#
# Long chunked solves checkpoint (value, progress, iteration count,
# residual history so far) between chunks.  One atomic npz file; the
# sidecar json is informational.  The checkpoint is crash-recovery
# scratch: deleted when the solve completes.


def save_vi_checkpoint(path: str, *, value, prog, it: int, resids,
                       stop_delta: float):
    import numpy as np

    buf = io.BytesIO()
    np.savez(buf, value=np.asarray(value), prog=np.asarray(prog),
             it=np.asarray(int(it)),
             resid=(np.concatenate([np.asarray(r) for r in resids])
                    if resids else np.zeros(0, np.asarray(value).dtype)),
             stop_delta=np.asarray(float(stop_delta)))
    sealed_write(path, buf.getvalue(), site="vi_chunk")
    atomic_write_json(path + ".json", {
        "version": SNAPSHOT_VERSION, "it": int(it),
        "S": int(np.asarray(value).shape[0]),
        "dtype": str(np.asarray(value).dtype),
        "stop_delta": float(stop_delta)})


def load_vi_checkpoint(path: str, *, S: int, dtype):
    """Returns (value, prog, it, resid) as numpy, validated against the
    solve's state-space size and dtype (a checkpoint from a different
    MDP must not silently seed this solve)."""
    import numpy as np

    payload, _ = sealed_read(path, kind="vi_checkpoint")
    try:
        with np.load(io.BytesIO(payload)) as z:
            value, prog = z["value"], z["prog"]
            it, resid = int(z["it"]), z["resid"]
    except Exception as e:  # np.load: ValueError/OSError/BadZipFile
        raise reject_undecodable(path, kind="vi_checkpoint",
                                 err=e) from e
    if value.shape != (S,):
        raise ValueError(f"VI checkpoint {path} has S={value.shape}, "
                         f"solve expects ({S},)")
    if value.dtype != np.dtype(dtype):
        raise ValueError(f"VI checkpoint {path} has dtype {value.dtype}, "
                         f"solve expects {np.dtype(dtype)}")
    return value, prog, it, resid


def save_grid_vi_checkpoint(path: str, *, value, prog, pol, frozen,
                            conv_it, final_delta, it: int, resids,
                            stop_delta: float):
    """Grid-VI twin of save_vi_checkpoint (mdp/explicit.py
    run_grid_chunk_driver): the per-point planes AND the per-point
    convergence state (frozen mask, freeze iterations, final deltas,
    converged policies) ride in one atomically-written npz — a resumed
    grid solve must keep already-frozen points bit-frozen, which the
    scalar VI checkpoint cannot express."""
    import numpy as np

    value = np.asarray(value)
    buf = io.BytesIO()
    np.savez(buf, value=value, prog=np.asarray(prog),
             pol=np.asarray(pol), frozen=np.asarray(frozen),
             conv_it=np.asarray(conv_it),
             final_delta=np.asarray(final_delta),
             it=np.asarray(int(it)),
             resid=(np.concatenate([np.asarray(r) for r in resids],
                                   axis=1)
                    if resids else np.zeros((value.shape[0], 0),
                                            value.dtype)),
             stop_delta=np.asarray(float(stop_delta)))
    sealed_write(path, buf.getvalue(), site="vi_chunk")
    atomic_write_json(path + ".json", {
        "version": SNAPSHOT_VERSION, "kind": "grid_vi", "it": int(it),
        "G": int(value.shape[0]), "S": int(value.shape[1]),
        "dtype": str(value.dtype), "stop_delta": float(stop_delta)})


def load_grid_vi_checkpoint(path: str, *, G: int, S: int, dtype):
    """Load a grid-VI checkpoint as a dict of numpy arrays, validated
    against the solve's [G, S] plane shape and dtype."""
    import numpy as np

    payload, _ = sealed_read(path, kind="grid_vi_checkpoint")
    try:
        with np.load(io.BytesIO(payload)) as z:
            st = {k: z[k] for k in ("value", "prog", "pol", "frozen",
                                    "conv_it", "final_delta", "it",
                                    "resid")}
    except Exception as e:
        raise reject_undecodable(path, kind="grid_vi_checkpoint",
                                 err=e) from e
    if st["value"].shape != (G, S):
        raise ValueError(f"grid VI checkpoint {path} has plane "
                         f"{st['value'].shape}, solve expects {(G, S)}")
    if st["value"].dtype != np.dtype(dtype):
        raise ValueError(f"grid VI checkpoint {path} has dtype "
                         f"{st['value'].dtype}, solve expects "
                         f"{np.dtype(dtype)}")
    return st


# -- frontier-compile checkpoints --------------------------------------------
#
# The frontier-batched MDP compiler (cpr_tpu/mdp/frontier.py)
# checkpoints between rounds: the partial transition columns
# concatenated so far, the pickled state/action/start tables, and the
# frontier position.  Same atomic-npz + informational-sidecar shape as
# the VI checkpoints; same crash-recovery-scratch lifecycle (deleted
# when the compile completes).  `model_fp` pins the checkpoint to the
# model it came from — a checkpoint from a different protocol/cutoff
# must not silently seed this compile.


def save_compile_checkpoint(path: str, *, columns: dict, blob: bytes,
                            round_idx: int, explored_upto: int,
                            model_fp: str):
    import numpy as np

    buf = io.BytesIO()
    np.savez(buf, blob=np.frombuffer(blob, np.uint8),
             round=np.asarray(int(round_idx)),
             explored=np.asarray(int(explored_upto)),
             model_fp=np.asarray(model_fp),
             **{k: np.asarray(v) for k, v in columns.items()})
    sealed_write(path, buf.getvalue(), site="compile_round")
    atomic_write_json(path + ".json", {
        "version": SNAPSHOT_VERSION, "kind": "mdp_compile",
        "round": int(round_idx), "explored": int(explored_upto),
        "transitions": int(len(columns["src"])),
        "model_fp": model_fp})


def load_compile_checkpoint(path: str, *, model_fp: str) -> dict:
    """Load a frontier-compile checkpoint as a dict of numpy arrays
    plus the raw `blob` bytes, validated against the resuming model's
    fingerprint."""
    import numpy as np

    payload, _ = sealed_read(path, kind="compile_checkpoint")
    try:
        with np.load(io.BytesIO(payload)) as z:
            st = {k: z[k] for k in z.files}
    except Exception as e:
        raise reject_undecodable(path, kind="compile_checkpoint",
                                 err=e) from e
    got = str(st.pop("model_fp"))
    if got != model_fp:
        raise ValueError(f"compile checkpoint {path} is for model "
                         f"{got}, this compile is {model_fp}")
    st["blob"] = st["blob"].tobytes()
    return st


# -- metrics.jsonl resume helpers --------------------------------------------


def trim_metrics_log(path: str, upto: int):
    """Drop rows logged past update `upto` (the last snapshot): a
    killed run may have logged updates the snapshot never saw, and the
    resumed run will re-produce them.  Header lines (`run: true`) and
    rows at or before `upto` survive.  Atomic rewrite."""
    if not os.path.exists(path):
        return
    keep = []
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if not ln:
                continue
            try:
                row = json.loads(ln)
            except ValueError:
                continue
            if row.get("preempted"):
                continue  # stale lifecycle marker: the run continues
            u = row.get("update")
            if not row.get("run") and u is not None and u > upto:
                continue
            keep.append(json.dumps(row))
    atomic_write_bytes(path, ("\n".join(keep) + "\n" if keep
                              else "").encode())


def metrics_fingerprint(path: str) -> list[dict]:
    """The determinism-comparable content of a metrics.jsonl stream:
    every non-header row with the volatile timing keys
    (`VOLATILE_METRIC_KEYS`) stripped.  Two runs of the same config —
    one uninterrupted, one killed-and-resumed — must produce equal
    fingerprints (the resilience acceptance criterion)."""
    rows = []
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if not ln:
                continue
            row = json.loads(ln)
            if row.get("run") or row.get("preempted"):
                continue  # headers/lifecycle markers differ by construction
            rows.append({k: v for k, v in row.items()
                         if k not in VOLATILE_METRIC_KEYS})
    return rows
