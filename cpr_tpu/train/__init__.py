"""RL training drivers.

Reference counterpart: experiments/train/ppo.py (stable-baselines3 PPO over
SubprocVecEnv process-per-env rollouts, W&B logging, YAML configs).

TPU re-design: a native JAX PPO where rollouts are the vmap'd env kernel
itself (no process boundary, no host<->device copies inside an update) and
the whole train step — rollout, GAE, minibatched clipped-surrogate updates
— is one jitted program, shardable over a device mesh (data-parallel env
batch x tensor-parallel policy network).
"""

from cpr_tpu.train.ppo import PPOConfig, make_train, ActorCritic  # noqa: F401
