"""Native JAX PPO over vmap-batched attack environments.

Reference counterpart: experiments/train/ppo.py — sb3 PPO("MlpPolicy"),
SubprocVecEnv(n_envs) process-per-env rollouts (:278-288), reward shaping
(:217-244), per-alpha eval aggregation (:296-374). Here the policy is a
flax MLP actor-critic (sb3's MlpPolicy shape), rollouts are the jitted env
kernel, and one `train_step` = rollout + GAE + minibatched clipped
surrogate updates, all inside a single XLA program. Multi-chip scaling:
the env batch is sharded over the mesh's data axis and the policy's hidden
layers over the tensor axis (see `shardings`).

The two halves of a train_step are independently replaceable:

  * `make_update_phase` builds the GAE + minibatch-update half alone,
    with (T, N) taken from the trajectory itself — `make_train` runs it
    on its own rollout, the always-on learner (learn/learner.py, via
    `make_experience_update`) runs the same program on experience the
    serve fleet recorded;
  * `make_train(rollout_phase=...)` swaps the rollout half —
    `make_lane_rollout` steps the resident lane block
    (`JaxEnv.step_lanes`, optionally mesh-sharded like
    parallel/lanes.py), the sampler/learner decoupling of ROADMAP
    item 2 (arXiv:1803.02811).

Sampler-side action keys are `fold_in`-derived experience streams
(learn/buffer.py `experience_stream`): a lane admitted with PRNGKey(S)
spends PRNGKey(S) itself on env dynamics, and the legacy rollout
consumes `split(key)` children — the experience stream is a sibling
`fold_in` derivation of the lane key, so sampler-side and legacy
rollout-side trajectories can never alias a key.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from flax import struct
from flax.training.train_state import TrainState

from cpr_tpu import device_metrics, resilience, telemetry
from cpr_tpu.envs.base import JaxEnv
from cpr_tpu.learn.buffer import EXPERIENCE_STREAM, experience_stream
from cpr_tpu.params import EnvParams

__all__ = [
    "PPOConfig", "ActorCritic", "Transition", "EXPERIENCE_STREAM",
    "experience_stream", "shardings", "make_update_phase", "make_train",
    "make_lane_rollout", "make_experience_update", "maybe_checkify",
    "relative_reward_on_done", "train",
]


@struct.dataclass
class PPOConfig:
    n_envs: int = 64
    n_steps: int = 128  # rollout length per update
    lr: float = 3e-4
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    entropy_coef: float = 0.01
    vf_coef: float = 0.5
    max_grad_norm: float = 0.5
    update_epochs: int = 4
    n_minibatches: int = 4
    hidden: tuple[int, ...] = (64, 64)  # sb3 MlpPolicy default net_arch
    anneal_lr: bool = False
    total_updates: int = 1000  # for lr annealing
    # KL-adaptive early stop (sb3 target_kl; reference runs relied on
    # sb3's stability machinery, experiments/train/ppo.py:296-374):
    # once the approximate KL to the rollout policy exceeds
    # 1.5 * target_kl, the remaining minibatch updates of this
    # train_step are skipped.  Guards the collapse mode where one large
    # policy step jumps into the never-release attractor
    # (docs/TRAIN_DAG_r04.md).  None = off.
    target_kl: float | None = None


class ActorCritic(nn.Module):
    """MLP actor-critic, the sb3 "MlpPolicy" shape (ppo.py:399-417)."""

    n_actions: int
    hidden: tuple[int, ...] = (64, 64)

    @nn.compact
    def __call__(self, obs):
        x = obs
        for i, h in enumerate(self.hidden):
            x = nn.tanh(nn.Dense(h, name=f"pi_{i}")(x))
        logits = nn.Dense(self.n_actions, name="pi_head")(x)
        v = obs
        for i, h in enumerate(self.hidden):
            v = nn.tanh(nn.Dense(h, name=f"vf_{i}")(v))
        value = nn.Dense(1, name="vf_head")(v)
        return logits, value.squeeze(-1)


@struct.dataclass
class Transition:
    obs: jnp.ndarray
    action: jnp.ndarray
    logp: jnp.ndarray
    value: jnp.ndarray
    reward: jnp.ndarray
    done: jnp.ndarray
    info: dict[str, jnp.ndarray]


def shardings(mesh, dp_axis: str = "dp", tp_axis: str = "tp"):
    """Sharding rules for the train state and batch: env batch over the
    data axis, MLP hidden weights over the tensor axis, everything else
    replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    batch = NamedSharding(mesh, P(dp_axis))

    def param_spec(path, x):
        # Dense kernels: (in, out) — shard the output features of hidden
        # layers and the input features of the heads over tp
        names = [getattr(p, "key", str(p)) for p in path]
        if x.ndim == 2:
            if any("head" in n for n in names):
                return NamedSharding(mesh, P(tp_axis, None))
            return NamedSharding(mesh, P(None, tp_axis))
        if x.ndim == 1 and not any("head" in n for n in names):
            return NamedSharding(mesh, P(tp_axis))
        return NamedSharding(mesh, P())

    return batch, param_spec


def make_update_phase(net: ActorCritic, cfg: PPOConfig, *,
                      collect: bool = False, mspec=None):
    """Build the update half of a PPO step: GAE + epoch/minibatch
    clipped-surrogate scans over ONE trajectory.

    (T, N) come from the trajectory's own shapes, not cfg — the same
    program serves make_train's rollout (cfg.n_steps x cfg.n_envs) and
    the learner's fed experience windows (learn/learner.py), whose
    batch geometry is the serve fleet's, not the trainer's.

    Returns update_phase(ts, traj, last_value, key) ->
    (ts, key, metrics); traj.info must carry the episode aggregate
    keys (`episode_reward_attacker`/`_defender`) the episode metrics
    read."""

    def gae(traj: Transition, last_value):
        def back(carry, t):
            adv_next, v_next = carry
            nonterm = 1.0 - t.done.astype(jnp.float32)
            delta = t.reward + cfg.gamma * v_next * nonterm - t.value
            adv = delta + cfg.gamma * cfg.gae_lambda * nonterm * adv_next
            return (adv, t.value), adv

        (_, _), advs = jax.lax.scan(
            back, (jnp.zeros_like(last_value), last_value), traj, reverse=True)
        return advs, advs + traj.value

    def loss_fn(params, batch, adv, target):
        logits, value = net.apply(params, batch.obs)
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(logp_all, batch.action[:, None], axis=1)[:, 0]
        ratio = jnp.exp(logp - batch.logp)
        adv_n = (adv - adv.mean()) / (adv.std() + 1e-8)
        pg1 = ratio * adv_n
        pg2 = jnp.clip(ratio, 1.0 - cfg.clip_eps, 1.0 + cfg.clip_eps) * adv_n
        pg_loss = -jnp.minimum(pg1, pg2).mean()
        v_clipped = batch.value + jnp.clip(
            value - batch.value, -cfg.clip_eps, cfg.clip_eps)
        v_loss = 0.5 * jnp.maximum(
            (value - target) ** 2, (v_clipped - target) ** 2).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        total = pg_loss + cfg.vf_coef * v_loss - cfg.entropy_coef * entropy
        # Schulman's low-variance KL estimator: E[(r - 1) - log r]
        logratio = logp - batch.logp
        approx_kl = ((jnp.exp(logratio) - 1.0) - logratio).mean()
        return total, dict(pg_loss=pg_loss, v_loss=v_loss, entropy=entropy,
                           approx_kl=approx_kl)

    def update_minibatch(ts, cont, mb):
        """One minibatch step, gated by the KL early-stop flag: once a
        minibatch's approximate KL crosses 1.5 * target_kl, this and
        every later minibatch of the train_step become no-ops (the sb3
        target_kl contract, applied at minibatch granularity)."""
        batch, adv, target = mb
        grads, metrics = jax.grad(loss_fn, has_aux=True)(ts.params, batch, adv, target)
        if cfg.target_kl is None:
            return ts.apply_gradients(grads=grads), cont, metrics
        cont = cont & (metrics["approx_kl"] <= 1.5 * cfg.target_kl)
        new_ts = ts.apply_gradients(grads=grads)
        ts = jax.tree.map(lambda a, b: jnp.where(cont, a, b), new_ts, ts)
        metrics["kl_stop"] = (~cont).astype(jnp.float32)
        # applied marks minibatches whose update actually took effect —
        # skipped ones still compute losses (lax.scan has no break) and
        # must not dilute the reported means
        metrics["applied"] = cont.astype(jnp.float32)
        return ts, cont, metrics

    def update_phase(ts, traj: Transition, last_value, key):
        n_steps, n_envs = traj.action.shape
        advs, targets = gae(traj, last_value)

        # flatten (T, N) -> (T*N,)
        flat = jax.tree.map(
            lambda x: x.reshape((n_steps * n_envs,) + x.shape[2:]), traj)
        advs_f = advs.reshape(-1)
        targets_f = targets.reshape(-1)

        acc = None
        if collect:
            # NaN/Inf birth counter on the advantage estimates: GAE is
            # where a single poisoned reward/value fans out into the
            # whole update
            acc = mspec.count(mspec.init(), "nonfinite_advantages",
                              ~jnp.isfinite(advs_f))

        def epoch(carry, _):
            ts, cont, key, acc = carry
            key, k_perm = jax.random.split(key)
            mb_size = n_steps * n_envs // cfg.n_minibatches
            perm = jax.random.permutation(
                k_perm, n_steps * n_envs
            )[:cfg.n_minibatches * mb_size].reshape(cfg.n_minibatches, mb_size)

            def one_mb(carry, idx):
                ts, cont, acc = carry
                take = lambda x: x[idx]
                mb = (jax.tree.map(take, flat), take(advs_f), take(targets_f))
                ts, cont, metrics = update_minibatch(ts, cont, mb)
                if collect:
                    acc2 = mspec.count(acc, "minibatches", 1)
                    nf = (~jnp.isfinite(metrics["pg_loss"])
                          | ~jnp.isfinite(metrics["v_loss"])
                          | ~jnp.isfinite(metrics["entropy"]))
                    acc2 = mspec.count(acc2, "nonfinite_loss", nf)
                    acc2 = mspec.observe(acc2, "approx_kl",
                                         metrics["approx_kl"])
                    if cfg.target_kl is not None:
                        acc2 = mspec.count(acc2, "minibatches_skipped",
                                           metrics["applied"] < 0.5)
                    acc = acc2
                return (ts, cont, acc), metrics

            (ts, cont, acc), metrics = jax.lax.scan(
                one_mb, (ts, cont, acc), perm)
            return (ts, cont, key, acc), metrics

        (ts, _, key, acc), metrics = jax.lax.scan(
            epoch, (ts, jnp.bool_(True), key, acc), None,
            length=cfg.update_epochs)
        if cfg.target_kl is None:
            metrics = jax.tree.map(lambda x: x.mean(), metrics)
        else:
            # sb3 stops the epoch loop at the KL breach, so its reported
            # losses average only the minibatches that ran; here the scan
            # runs every minibatch as a gated no-op, so the loss metrics
            # are weighted by `applied` (kl_stop keeps the plain mean:
            # it IS the skipped fraction)
            w = metrics.pop("applied")
            n = jnp.maximum(w.sum(), 1.0)
            gated = ("pg_loss", "v_loss", "approx_kl")
            metrics = {k: (v * w).sum() / n if k in gated else v.mean()
                       for k, v in metrics.items()}
        metrics["mean_step_reward"] = traj.reward.mean()
        metrics["episode_reward_attacker"] = (
            jnp.where(traj.done, traj.info["episode_reward_attacker"], 0.0).sum()
            / jnp.maximum(traj.done.sum(), 1))
        metrics["episode_reward_defender"] = (
            jnp.where(traj.done, traj.info["episode_reward_defender"], 0.0).sum()
            / jnp.maximum(traj.done.sum(), 1))
        metrics["n_episodes"] = traj.done.sum()
        if collect:
            # reserved key: callers pop the accumulator before their
            # float() sweep and summarize it once per telemetry span
            metrics["device_metrics"] = acc
        return ts, key, metrics

    return update_phase


def make_train(env: JaxEnv, env_params: EnvParams, cfg: PPOConfig,
               reward_transform: Callable | None = None,
               per_env_params: bool = False,
               rollout_phase: Callable | None = None):
    """Build (init_fn, train_step) — both jittable, mesh-shardable.

    reward_transform(reward, info, done) -> shaped reward; the analog of
    the reference's reward shaping pipeline (ppo.py:217-244 and the
    wrappers in gym/ocaml/cpr_gym/wrappers.py).

    per_env_params: env_params leaves carry a leading (n_envs,) axis and
    each env lane runs its own (alpha, gamma, ...) — the batched analog
    of training under an assumption schedule
    (wrappers.py:172-242 / cfg alpha lists and ranges).

    rollout_phase(carry) -> (carry, traj): replaces the built-in
    vmapped `env.step` scan — `make_lane_rollout` steps the resident
    lane block instead (the serve sampler's unit), carry layout
    unchanged (ts, env_state, obs, key).
    """
    net = ActorCritic(env.n_actions, cfg.hidden)
    p_axis = 0 if per_env_params else None
    # in-graph sentinels/stats (CPR_DEVICE_METRICS=1), read at build
    # time: the off path stays the exact pre-metrics program (acc=None
    # threads through the scans as an empty pytree)
    collect = device_metrics.enabled()
    mspec = device_metrics.ppo_spec() if collect else None
    update_phase = make_update_phase(net, cfg, collect=collect, mspec=mspec)

    def lr_schedule(count):
        if not cfg.anneal_lr:
            return cfg.lr
        frac = 1.0 - count / (cfg.total_updates * cfg.update_epochs * cfg.n_minibatches)
        return cfg.lr * jnp.maximum(frac, 0.0)

    tx = optax.chain(
        optax.clip_by_global_norm(cfg.max_grad_norm),
        optax.adam(lr_schedule, eps=1e-5),
    )

    def init_fn(key):
        key, k_net, k_env = jax.random.split(key, 3)
        obs_dim = env.observation_length
        params = net.init(k_net, jnp.zeros((1, obs_dim)))
        ts = TrainState.create(apply_fn=net.apply, params=params, tx=tx)
        env_keys = jax.random.split(k_env, cfg.n_envs)
        env_state, obs = jax.vmap(
            lambda k, p: env.reset(k, p), in_axes=(0, p_axis)
        )(env_keys, env_params)
        return ts, env_state, obs, key

    def env_step(carry, _):
        ts, env_state, obs, key = carry
        key, k_act = jax.random.split(key)
        logits, value = net.apply(ts.params, obs)
        action = jax.random.categorical(k_act, logits)
        logp = jax.nn.log_softmax(logits)[jnp.arange(cfg.n_envs), action]
        env_state, obs2, reward, done, info = jax.vmap(
            lambda s, a, p: env.step(s, a, p), in_axes=(0, 0, p_axis)
        )(env_state, action, env_params)
        if reward_transform is not None:
            reward = reward_transform(reward, info, done)
        # auto-reset finished episodes, continuing each env's PRNG stream
        reset_state, reset_obs = jax.vmap(
            lambda s, p: env.reset(s.key, p), in_axes=(0, p_axis)
        )(env_state, env_params)
        env_state = jax.tree.map(
            lambda a, b: jnp.where(
                done.reshape(done.shape + (1,) * (a.ndim - 1)), a, b),
            reset_state, env_state)
        obs2 = jnp.where(done[:, None], reset_obs, obs2)
        t = Transition(obs=obs, action=action, logp=logp, value=value,
                       reward=reward, done=done, info=info)
        return (ts, env_state, obs2, key), t

    if rollout_phase is None:
        def rollout_phase(carry):
            return jax.lax.scan(env_step, carry, None, length=cfg.n_steps)

    def train_step(carry):
        """One PPO update: rollout cfg.n_steps x cfg.n_envs, GAE,
        cfg.update_epochs x cfg.n_minibatches minibatch updates."""
        carry, traj = rollout_phase(carry)
        ts, env_state, obs, key = carry
        _, last_value = net.apply(ts.params, obs)
        ts, key, metrics = update_phase(ts, traj, last_value, key)
        return (ts, env_state, obs, key), metrics

    train_step.metrics_spec = mspec
    return init_fn, train_step


def make_lane_rollout(env: JaxEnv, env_params: EnvParams, cfg: PPOConfig,
                      *, reward_transform: Callable | None = None,
                      mesh=None, mesh_axis: str = "d"):
    """A drop-in `rollout_phase` over the resident lane stepper.

    Steps cfg.n_envs lanes with the raw `JaxEnv.step_lanes` unit (the
    same per-lane program the serve engine's bursts and the gym
    adapters advance, envs/base.py) instead of the vmapped `env.step`
    scan — the sampler half of the decoupled loop, trainable in place.
    With `mesh`, the lane carry is pinned to the partitioned lane axis
    each step (the NamedSharding layout of parallel/lanes.py), so the
    whole rollout runs data-parallel under GSPMD — the mesh story
    ROADMAP item 2 names, now shared between serve and train.

    Action keys are experience streams: per-lane `fold_in` derivations
    of the carry key (learn/buffer.py), folded again by the step index
    — never the `split` sequence the legacy rollout consumes, so the
    two samplers can never alias a key (tests/test_learn.py).
    """
    net = ActorCritic(env.n_actions, cfg.hidden)
    # the raw (unjitted, undonated) lane stepper: it inlines into the
    # rollout scan, where the enclosing train_step jit owns donation
    step_raw = type(env).step_lanes.__wrapped__
    lane_sh = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec
        from cpr_tpu.parallel import check_even_shards
        check_even_shards(cfg.n_envs, mesh, axis=mesh_axis,
                          what="cfg.n_envs")
        lane_sh = NamedSharding(mesh, PartitionSpec(mesh_axis))

    def pin(tree):
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, lane_sh), tree)

    def rollout_phase(carry):
        ts, env_state, obs, key = carry
        lane_keys = jax.vmap(
            lambda i: jax.random.fold_in(experience_stream(key), i)
        )(jnp.arange(cfg.n_envs))
        no_admit = jnp.zeros(cfg.n_envs, bool)
        step_all = jnp.ones(cfg.n_envs, bool)

        def body(c, t):
            env_state, obs = c
            logits, value = net.apply(ts.params, obs)
            k_t = jax.vmap(lambda k: jax.random.fold_in(k, t))(lane_keys)
            action = jax.vmap(jax.random.categorical)(k_t, logits)
            action = action.astype(jnp.int32)
            logp = jnp.take_along_axis(
                jax.nn.log_softmax(logits), action[:, None], axis=1)[:, 0]
            (env_state, obs2), (_, reward, done, info) = step_raw(
                env, (env_state, obs), action, no_admit,
                (env_state, obs), step_all, env_params)
            if reward_transform is not None:
                reward = reward_transform(reward, info, done)
            t_out = Transition(obs=obs, action=action, logp=logp,
                               value=value, reward=reward, done=done,
                               info=info)
            if lane_sh is not None:
                env_state, obs2 = pin(env_state), pin(obs2)
            return (env_state, obs2), t_out

        (env_state, obs), traj = jax.lax.scan(
            body, (env_state, obs), jnp.arange(cfg.n_steps, dtype=jnp.int32))
        return (ts, env_state, obs, key), traj

    return rollout_phase


def make_experience_update(n_actions: int, obs_dim: int, cfg: PPOConfig,
                           *, reward_transform: Callable | None = None):
    """The learner half of the decoupled sampler/learner loop
    (arXiv:1803.02811): a jitted PPO update over externally-fed
    experience windows (learn/learner.py runs this on batches the
    serve fleet recorded via learn/buffer.py).

    logp/value are recomputed under the CURRENT params — the fed
    actions may come from a stale snapshot or even a scripted policy,
    so the clipped surrogate's ratio is centered at 1 for the learner's
    own policy; the approximation's staleness is bounded by the swap
    SLO (docs/LEARNING.md).

    Batch layout (time-major; shapes fixed per process so the program
    compiles once): obs [T, N, obs_dim] f32, action [T, N] i32,
    reward/era/erd [T, N] f32, done [T, N] bool, last_obs [N, obs_dim].

    Returns (net, init_fn, update, mspec): init_fn(key) -> TrainState,
    update(ts, batch, key) -> (ts, key, metrics) with ts DONATED (the
    learner reassigns its train state every update; one resident copy
    of params + opt state, the hot-path donation discipline).
    """
    net = ActorCritic(int(n_actions), cfg.hidden)
    collect = device_metrics.enabled()
    mspec = device_metrics.ppo_spec() if collect else None
    update_phase = make_update_phase(net, cfg, collect=collect, mspec=mspec)
    tx = optax.chain(
        optax.clip_by_global_norm(cfg.max_grad_norm),
        optax.adam(cfg.lr, eps=1e-5),
    )

    def init_fn(key):
        params = net.init(key, jnp.zeros((1, int(obs_dim))))
        return TrainState.create(apply_fn=net.apply, params=params, tx=tx)

    def update(ts, batch, key):
        obs, action, done = batch["obs"], batch["action"], batch["done"]
        logits, value = net.apply(ts.params, obs)
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(logits), action[..., None], axis=-1)[..., 0]
        info = {"episode_reward_attacker": batch["era"],
                "episode_reward_defender": batch["erd"]}
        reward = batch["reward"]
        if reward_transform is not None:
            reward = reward_transform(reward, info, done)
        traj = Transition(obs=obs, action=action, logp=logp, value=value,
                          reward=reward, done=done, info=info)
        _, last_value = net.apply(ts.params, batch["last_obs"])
        return update_phase(ts, traj, last_value, key)

    return net, init_fn, jax.jit(update, donate_argnums=0), mspec


def maybe_checkify(step_fn):
    """jit `step_fn`, under checkify float checks when CPR_CHECKIFY=1.

    The opt-in debug mode for silent NaN/Inf births inside the update:
    checkify instruments every float op in the traced program, and the
    wrapper syncs on the error payload each call — this is the
    slow-but-exact complement to the free in-graph sentinels
    (device_metrics.ppo_spec), not something to leave on in a bench.
    On error: one `checkify_error` telemetry event, then the usual
    JaxRuntimeError via err.throw()."""
    # donate-carry waived on both jits: train/driver.py keeps live
    # references INTO the previous carry across updates (best_params
    # for the revert-on-NaN path aliases carry[0].params), so donating
    # the carry would hand XLA buffers the revert still needs
    if os.environ.get(telemetry.CHECKIFY_ENV_VAR) != "1":
        # jaxlint: disable-next-line=donate-carry
        return jax.jit(step_fn)
    from jax.experimental import checkify

    # jaxlint: disable-next-line=donate-carry
    checked = jax.jit(checkify.checkify(
        step_fn, errors=checkify.float_checks))

    def step(carry):
        err, out = checked(carry)
        msg = err.get()
        if msg:
            telemetry.current().event("checkify_error", error=msg)
            err.throw()
        return out

    return step


def relative_reward_on_done(reward, info, done):
    """Sparse relative reward shaping
    (gym/ocaml/cpr_gym/wrappers.py:8-26): at episode end, the attacker's
    share of total reward; zero elsewhere."""
    a = info["episode_reward_attacker"]
    d = info["episode_reward_defender"]
    s = a + d
    rel = jnp.where(s != 0, a / jnp.where(s != 0, s, 1.0), 0.0)
    return jnp.where(done, rel, 0.0)


def train(env, env_params, cfg: PPOConfig, *, n_updates: int, seed: int = 0,
          reward_transform=relative_reward_on_done, mesh=None,
          progress: Callable[[int, dict], Any] | None = None):
    """Run PPO for n_updates; returns (train_state, metrics history).

    `mesh` shards the sampling env batch over the mesh's "dp" axis
    (shard_envs) so the rollout half of every train_step runs
    data-parallel across devices; cfg.n_envs must divide the axis
    (shard_envs raises with both values named).  docs/SCALING.md
    covers the mesh contract shared with serve and netsim."""
    init_fn, train_step = make_train(env, env_params, cfg, reward_transform)
    carry = init_fn(jax.random.PRNGKey(seed))
    if mesh is not None:
        from cpr_tpu.parallel import shard_envs
        ts, env_state, obs, key = carry
        env_state = shard_envs(mesh, env_state, "dp")
        obs = shard_envs(mesh, obs, "dp")
        carry = (ts, env_state, obs, key)
    step = maybe_checkify(train_step)
    history = []
    tele = telemetry.current()
    steps_per_update = cfg.n_envs * cfg.n_steps
    # the guard clears any stale preempt flag on entry — without it a
    # previously handled preemption in this process would silently
    # truncate every later train() call at update 0
    with resilience.preemption_guard():
        for i in range(n_updates):
            # same fault/preemption sites as the config driver, so
            # harness tests and ops tooling behave identically on the
            # plain loop (no snapshotting here — use train_from_config
            # for resumable runs)
            resilience.fault_point("update", i + 1)
            if resilience.preempt_requested():
                tele.event("preempted", update=i,
                           reason=resilience.preempt_reason())
                break
            with tele.span("update", env_steps=steps_per_update) as sp:
                carry, metrics = step(carry)
                sp.fence(carry)
                acc = metrics.pop("device_metrics", None)
                host_metrics = {k: float(v) for k, v in metrics.items()}
            if acc is not None:
                device_metrics.emit("ppo_update", train_step.metrics_spec,
                                    acc, update=i)
            host_metrics["wall_s"] = round(sp.dur_s, 6)
            if sp.dur_s > 0:
                host_metrics["steps_per_sec"] = round(
                    steps_per_update / sp.dur_s)
            if progress is not None:
                progress(i, host_metrics)
            history.append(host_metrics)
    return carry[0], history
