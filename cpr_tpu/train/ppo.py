"""Native JAX PPO over vmap-batched attack environments.

Reference counterpart: experiments/train/ppo.py — sb3 PPO("MlpPolicy"),
SubprocVecEnv(n_envs) process-per-env rollouts (:278-288), reward shaping
(:217-244), per-alpha eval aggregation (:296-374). Here the policy is a
flax MLP actor-critic (sb3's MlpPolicy shape), rollouts are the jitted env
kernel, and one `train_step` = rollout + GAE + minibatched clipped
surrogate updates, all inside a single XLA program. Multi-chip scaling:
the env batch is sharded over the mesh's data axis and the policy's hidden
layers over the tensor axis (see `shardings`).
"""

from __future__ import annotations

import os
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from flax import struct
from flax.training.train_state import TrainState

from cpr_tpu import device_metrics, resilience, telemetry
from cpr_tpu.envs.base import JaxEnv
from cpr_tpu.params import EnvParams


@struct.dataclass
class PPOConfig:
    n_envs: int = 64
    n_steps: int = 128  # rollout length per update
    lr: float = 3e-4
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    entropy_coef: float = 0.01
    vf_coef: float = 0.5
    max_grad_norm: float = 0.5
    update_epochs: int = 4
    n_minibatches: int = 4
    hidden: tuple[int, ...] = (64, 64)  # sb3 MlpPolicy default net_arch
    anneal_lr: bool = False
    total_updates: int = 1000  # for lr annealing
    # KL-adaptive early stop (sb3 target_kl; reference runs relied on
    # sb3's stability machinery, experiments/train/ppo.py:296-374):
    # once the approximate KL to the rollout policy exceeds
    # 1.5 * target_kl, the remaining minibatch updates of this
    # train_step are skipped.  Guards the collapse mode where one large
    # policy step jumps into the never-release attractor
    # (docs/TRAIN_DAG_r04.md).  None = off.
    target_kl: float | None = None


class ActorCritic(nn.Module):
    """MLP actor-critic, the sb3 "MlpPolicy" shape (ppo.py:399-417)."""

    n_actions: int
    hidden: tuple[int, ...] = (64, 64)

    @nn.compact
    def __call__(self, obs):
        x = obs
        for i, h in enumerate(self.hidden):
            x = nn.tanh(nn.Dense(h, name=f"pi_{i}")(x))
        logits = nn.Dense(self.n_actions, name="pi_head")(x)
        v = obs
        for i, h in enumerate(self.hidden):
            v = nn.tanh(nn.Dense(h, name=f"vf_{i}")(v))
        value = nn.Dense(1, name="vf_head")(v)
        return logits, value.squeeze(-1)


@struct.dataclass
class Transition:
    obs: jnp.ndarray
    action: jnp.ndarray
    logp: jnp.ndarray
    value: jnp.ndarray
    reward: jnp.ndarray
    done: jnp.ndarray
    info: dict[str, jnp.ndarray]


def shardings(mesh, dp_axis: str = "dp", tp_axis: str = "tp"):
    """Sharding rules for the train state and batch: env batch over the
    data axis, MLP hidden weights over the tensor axis, everything else
    replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    batch = NamedSharding(mesh, P(dp_axis))

    def param_spec(path, x):
        # Dense kernels: (in, out) — shard the output features of hidden
        # layers and the input features of the heads over tp
        names = [getattr(p, "key", str(p)) for p in path]
        if x.ndim == 2:
            if any("head" in n for n in names):
                return NamedSharding(mesh, P(tp_axis, None))
            return NamedSharding(mesh, P(None, tp_axis))
        if x.ndim == 1 and not any("head" in n for n in names):
            return NamedSharding(mesh, P(tp_axis))
        return NamedSharding(mesh, P())

    return batch, param_spec


def make_train(env: JaxEnv, env_params: EnvParams, cfg: PPOConfig,
               reward_transform: Callable | None = None,
               per_env_params: bool = False):
    """Build (init_fn, train_step) — both jittable, mesh-shardable.

    reward_transform(reward, info, done) -> shaped reward; the analog of
    the reference's reward shaping pipeline (ppo.py:217-244 and the
    wrappers in gym/ocaml/cpr_gym/wrappers.py).

    per_env_params: env_params leaves carry a leading (n_envs,) axis and
    each env lane runs its own (alpha, gamma, ...) — the batched analog
    of training under an assumption schedule
    (wrappers.py:172-242 / cfg alpha lists and ranges).
    """
    net = ActorCritic(env.n_actions, cfg.hidden)
    p_axis = 0 if per_env_params else None
    # in-graph sentinels/stats (CPR_DEVICE_METRICS=1), read at build
    # time: the off path stays the exact pre-metrics program (acc=None
    # threads through the scans as an empty pytree)
    collect = device_metrics.enabled()
    mspec = device_metrics.ppo_spec() if collect else None

    def lr_schedule(count):
        if not cfg.anneal_lr:
            return cfg.lr
        frac = 1.0 - count / (cfg.total_updates * cfg.update_epochs * cfg.n_minibatches)
        return cfg.lr * jnp.maximum(frac, 0.0)

    tx = optax.chain(
        optax.clip_by_global_norm(cfg.max_grad_norm),
        optax.adam(lr_schedule, eps=1e-5),
    )

    def init_fn(key):
        key, k_net, k_env = jax.random.split(key, 3)
        obs_dim = env.observation_length
        params = net.init(k_net, jnp.zeros((1, obs_dim)))
        ts = TrainState.create(apply_fn=net.apply, params=params, tx=tx)
        env_keys = jax.random.split(k_env, cfg.n_envs)
        env_state, obs = jax.vmap(
            lambda k, p: env.reset(k, p), in_axes=(0, p_axis)
        )(env_keys, env_params)
        return ts, env_state, obs, key

    def env_step(carry, _):
        ts, env_state, obs, key = carry
        key, k_act = jax.random.split(key)
        logits, value = net.apply(ts.params, obs)
        action = jax.random.categorical(k_act, logits)
        logp = jax.nn.log_softmax(logits)[jnp.arange(cfg.n_envs), action]
        env_state, obs2, reward, done, info = jax.vmap(
            lambda s, a, p: env.step(s, a, p), in_axes=(0, 0, p_axis)
        )(env_state, action, env_params)
        if reward_transform is not None:
            reward = reward_transform(reward, info, done)
        # auto-reset finished episodes, continuing each env's PRNG stream
        reset_state, reset_obs = jax.vmap(
            lambda s, p: env.reset(s.key, p), in_axes=(0, p_axis)
        )(env_state, env_params)
        env_state = jax.tree.map(
            lambda a, b: jnp.where(
                done.reshape(done.shape + (1,) * (a.ndim - 1)), a, b),
            reset_state, env_state)
        obs2 = jnp.where(done[:, None], reset_obs, obs2)
        t = Transition(obs=obs, action=action, logp=logp, value=value,
                       reward=reward, done=done, info=info)
        return (ts, env_state, obs2, key), t

    def gae(traj: Transition, last_value):
        def back(carry, t):
            adv_next, v_next = carry
            nonterm = 1.0 - t.done.astype(jnp.float32)
            delta = t.reward + cfg.gamma * v_next * nonterm - t.value
            adv = delta + cfg.gamma * cfg.gae_lambda * nonterm * adv_next
            return (adv, t.value), adv

        (_, _), advs = jax.lax.scan(
            back, (jnp.zeros_like(last_value), last_value), traj, reverse=True)
        return advs, advs + traj.value

    def loss_fn(params, batch, adv, target):
        logits, value = net.apply(params, batch.obs)
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(logp_all, batch.action[:, None], axis=1)[:, 0]
        ratio = jnp.exp(logp - batch.logp)
        adv_n = (adv - adv.mean()) / (adv.std() + 1e-8)
        pg1 = ratio * adv_n
        pg2 = jnp.clip(ratio, 1.0 - cfg.clip_eps, 1.0 + cfg.clip_eps) * adv_n
        pg_loss = -jnp.minimum(pg1, pg2).mean()
        v_clipped = batch.value + jnp.clip(
            value - batch.value, -cfg.clip_eps, cfg.clip_eps)
        v_loss = 0.5 * jnp.maximum(
            (value - target) ** 2, (v_clipped - target) ** 2).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        total = pg_loss + cfg.vf_coef * v_loss - cfg.entropy_coef * entropy
        # Schulman's low-variance KL estimator: E[(r - 1) - log r]
        logratio = logp - batch.logp
        approx_kl = ((jnp.exp(logratio) - 1.0) - logratio).mean()
        return total, dict(pg_loss=pg_loss, v_loss=v_loss, entropy=entropy,
                           approx_kl=approx_kl)

    def update_minibatch(ts, cont, mb):
        """One minibatch step, gated by the KL early-stop flag: once a
        minibatch's approximate KL crosses 1.5 * target_kl, this and
        every later minibatch of the train_step become no-ops (the sb3
        target_kl contract, applied at minibatch granularity)."""
        batch, adv, target = mb
        grads, metrics = jax.grad(loss_fn, has_aux=True)(ts.params, batch, adv, target)
        if cfg.target_kl is None:
            return ts.apply_gradients(grads=grads), cont, metrics
        cont = cont & (metrics["approx_kl"] <= 1.5 * cfg.target_kl)
        new_ts = ts.apply_gradients(grads=grads)
        ts = jax.tree.map(lambda a, b: jnp.where(cont, a, b), new_ts, ts)
        metrics["kl_stop"] = (~cont).astype(jnp.float32)
        # applied marks minibatches whose update actually took effect —
        # skipped ones still compute losses (lax.scan has no break) and
        # must not dilute the reported means
        metrics["applied"] = cont.astype(jnp.float32)
        return ts, cont, metrics

    def train_step(carry):
        """One PPO update: rollout cfg.n_steps x cfg.n_envs, GAE,
        cfg.update_epochs x cfg.n_minibatches minibatch updates."""
        carry, traj = jax.lax.scan(env_step, carry, None, length=cfg.n_steps)
        ts, env_state, obs, key = carry
        _, last_value = net.apply(ts.params, obs)
        advs, targets = gae(traj, last_value)

        # flatten (T, N) -> (T*N,)
        flat = jax.tree.map(
            lambda x: x.reshape((cfg.n_steps * cfg.n_envs,) + x.shape[2:]), traj)
        advs_f = advs.reshape(-1)
        targets_f = targets.reshape(-1)

        acc = None
        if collect:
            # NaN/Inf birth counter on the advantage estimates: GAE is
            # where a single poisoned reward/value fans out into the
            # whole update
            acc = mspec.count(mspec.init(), "nonfinite_advantages",
                              ~jnp.isfinite(advs_f))

        def epoch(carry, _):
            ts, cont, key, acc = carry
            key, k_perm = jax.random.split(key)
            mb_size = cfg.n_steps * cfg.n_envs // cfg.n_minibatches
            perm = jax.random.permutation(
                k_perm, cfg.n_steps * cfg.n_envs
            ).reshape(cfg.n_minibatches, mb_size)

            def one_mb(carry, idx):
                ts, cont, acc = carry
                take = lambda x: x[idx]
                mb = (jax.tree.map(take, flat), take(advs_f), take(targets_f))
                ts, cont, metrics = update_minibatch(ts, cont, mb)
                if collect:
                    acc2 = mspec.count(acc, "minibatches", 1)
                    nf = (~jnp.isfinite(metrics["pg_loss"])
                          | ~jnp.isfinite(metrics["v_loss"])
                          | ~jnp.isfinite(metrics["entropy"]))
                    acc2 = mspec.count(acc2, "nonfinite_loss", nf)
                    acc2 = mspec.observe(acc2, "approx_kl",
                                         metrics["approx_kl"])
                    if cfg.target_kl is not None:
                        acc2 = mspec.count(acc2, "minibatches_skipped",
                                           metrics["applied"] < 0.5)
                    acc = acc2
                return (ts, cont, acc), metrics

            (ts, cont, acc), metrics = jax.lax.scan(
                one_mb, (ts, cont, acc), perm)
            return (ts, cont, key, acc), metrics

        (ts, _, key, acc), metrics = jax.lax.scan(
            epoch, (ts, jnp.bool_(True), key, acc), None,
            length=cfg.update_epochs)
        if cfg.target_kl is None:
            metrics = jax.tree.map(lambda x: x.mean(), metrics)
        else:
            # sb3 stops the epoch loop at the KL breach, so its reported
            # losses average only the minibatches that ran; here the scan
            # runs every minibatch as a gated no-op, so the loss metrics
            # are weighted by `applied` (kl_stop keeps the plain mean:
            # it IS the skipped fraction)
            w = metrics.pop("applied")
            n = jnp.maximum(w.sum(), 1.0)
            gated = ("pg_loss", "v_loss", "approx_kl")
            metrics = {k: (v * w).sum() / n if k in gated else v.mean()
                       for k, v in metrics.items()}
        metrics["mean_step_reward"] = traj.reward.mean()
        metrics["episode_reward_attacker"] = (
            jnp.where(traj.done, traj.info["episode_reward_attacker"], 0.0).sum()
            / jnp.maximum(traj.done.sum(), 1))
        metrics["episode_reward_defender"] = (
            jnp.where(traj.done, traj.info["episode_reward_defender"], 0.0).sum()
            / jnp.maximum(traj.done.sum(), 1))
        metrics["n_episodes"] = traj.done.sum()
        if collect:
            # reserved key: callers pop the accumulator before their
            # float() sweep and summarize it once per telemetry span
            metrics["device_metrics"] = acc
        return (ts, env_state, obs, key), metrics

    train_step.metrics_spec = mspec
    return init_fn, train_step


def maybe_checkify(step_fn):
    """jit `step_fn`, under checkify float checks when CPR_CHECKIFY=1.

    The opt-in debug mode for silent NaN/Inf births inside the update:
    checkify instruments every float op in the traced program, and the
    wrapper syncs on the error payload each call — this is the
    slow-but-exact complement to the free in-graph sentinels
    (device_metrics.ppo_spec), not something to leave on in a bench.
    On error: one `checkify_error` telemetry event, then the usual
    JaxRuntimeError via err.throw()."""
    # donate-carry waived on both jits: train/driver.py keeps live
    # references INTO the previous carry across updates (best_params
    # for the revert-on-NaN path aliases carry[0].params), so donating
    # the carry would hand XLA buffers the revert still needs
    if os.environ.get(telemetry.CHECKIFY_ENV_VAR) != "1":
        # jaxlint: disable-next-line=donate-carry
        return jax.jit(step_fn)
    from jax.experimental import checkify

    # jaxlint: disable-next-line=donate-carry
    checked = jax.jit(checkify.checkify(
        step_fn, errors=checkify.float_checks))

    def step(carry):
        err, out = checked(carry)
        msg = err.get()
        if msg:
            telemetry.current().event("checkify_error", error=msg)
            err.throw()
        return out

    return step


def relative_reward_on_done(reward, info, done):
    """Sparse relative reward shaping
    (gym/ocaml/cpr_gym/wrappers.py:8-26): at episode end, the attacker's
    share of total reward; zero elsewhere."""
    a = info["episode_reward_attacker"]
    d = info["episode_reward_defender"]
    s = a + d
    rel = jnp.where(s != 0, a / jnp.where(s != 0, s, 1.0), 0.0)
    return jnp.where(done, rel, 0.0)


def train(env, env_params, cfg: PPOConfig, *, n_updates: int, seed: int = 0,
          reward_transform=relative_reward_on_done, mesh=None,
          progress: Callable[[int, dict], Any] | None = None):
    """Run PPO for n_updates; returns (train_state, metrics history).

    `mesh` shards the sampling env batch over the mesh's "dp" axis
    (shard_envs) so the rollout half of every train_step runs
    data-parallel across devices; cfg.n_envs must divide the axis
    (shard_envs raises with both values named).  docs/SCALING.md
    covers the mesh contract shared with serve and netsim."""
    init_fn, train_step = make_train(env, env_params, cfg, reward_transform)
    carry = init_fn(jax.random.PRNGKey(seed))
    if mesh is not None:
        from cpr_tpu.parallel import shard_envs
        ts, env_state, obs, key = carry
        env_state = shard_envs(mesh, env_state, "dp")
        obs = shard_envs(mesh, obs, "dp")
        carry = (ts, env_state, obs, key)
    step = maybe_checkify(train_step)
    history = []
    tele = telemetry.current()
    steps_per_update = cfg.n_envs * cfg.n_steps
    # the guard clears any stale preempt flag on entry — without it a
    # previously handled preemption in this process would silently
    # truncate every later train() call at update 0
    with resilience.preemption_guard():
        for i in range(n_updates):
            # same fault/preemption sites as the config driver, so
            # harness tests and ops tooling behave identically on the
            # plain loop (no snapshotting here — use train_from_config
            # for resumable runs)
            resilience.fault_point("update", i + 1)
            if resilience.preempt_requested():
                tele.event("preempted", update=i,
                           reason=resilience.preempt_reason())
                break
            with tele.span("update", env_steps=steps_per_update) as sp:
                carry, metrics = step(carry)
                sp.fence(carry)
                acc = metrics.pop("device_metrics", None)
                host_metrics = {k: float(v) for k, v in metrics.items()}
            if acc is not None:
                device_metrics.emit("ppo_update", train_step.metrics_spec,
                                    acc, update=i)
            host_metrics["wall_s"] = round(sp.dur_s, 6)
            if sp.dur_s > 0:
                host_metrics["steps_per_sec"] = round(
                    steps_per_update / sp.dur_s)
            if progress is not None:
                progress(i, host_metrics)
            history.append(host_metrics)
    return carry[0], history
