"""YAML training configuration model.

Reference counterpart: experiments/train/cfg_model/__init__.py:12-137 —
pydantic config with protocol variant, alpha schedules (fixed / list /
range), env + PPO + eval blocks, parsed from YAML files
(experiments/train/configs/*.yaml).  Protocols here are addressed by the
registry key grammar ("nakamoto", "tailstorm-8-discount-heuristic", ...)
instead of a parallel class hierarchy.
"""

from __future__ import annotations

from typing import List, Literal, Union

import numpy as np
import yaml
from pydantic import BaseModel, field_validator, model_validator


class Range(BaseModel):
    min: float
    max: float


Alpha = Union[float, List[float], Range]


class PPOBlock(BaseModel):
    lr: float = 3e-4
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    ent_coef: float = 0.01
    vf_coef: float = 0.5
    n_steps: int = 128
    n_minibatches: int = 4
    update_epochs: int = 4
    n_layers: int = 2
    layer_size: int = 64
    anneal_lr: bool = False
    # KL-adaptive early stop (sb3 target_kl): skip remaining minibatch
    # updates once approx KL > 1.5 * target_kl.  None = off.
    target_kl: float | None = None


class EvalBlock(BaseModel):
    # evaluate every `freq` updates, skipping the first
    # `start_at_iteration` (cfg_model/__init__.py:80-105)
    freq: int = 10
    start_at_iteration: int = 1
    alpha_step: float = 0.025
    episodes_per_alpha: int = 64


class TrainConfig(BaseModel):
    protocol: str = "nakamoto"
    alpha: Alpha = 0.33
    gamma: float = 0.5
    episode_len: int = 128
    # dense_per_progress mirrors the reference's DenseRewardPerProgress
    # wrapper (gym/ocaml/cpr_gym/wrappers.py:54-113): episodes terminate
    # at target progress `episode_len`, per-step reward is the attacker
    # reward delta / target, with an end-of-episode mismatch correction.
    reward: Literal["sparse_relative", "sparse_per_progress",
                    "dense_per_progress"] = "sparse_relative"
    shape: Literal["raw", "cut", "exp"] = "raw"
    n_envs: int = 256
    total_updates: int = 200
    seed: int = 0
    # best-checkpoint revert-on-collapse: after an eval scoring below
    # `revert_frac` x the best score so far, training restarts from the
    # best checkpoint (fresh optimizer state).  Together with target_kl
    # this keeps the FINAL policy near its peak instead of decaying into
    # the never-release attractor (docs/TRAIN_DAG_r04.md).  None = off.
    revert_frac: float | None = None
    ppo: PPOBlock = PPOBlock()
    eval: EvalBlock = EvalBlock()

    @field_validator("gamma")
    @classmethod
    def _gamma_range(cls, v):
        if not 0.0 <= v < 1.0:
            raise ValueError("gamma must be in [0, 1)")
        return v

    @model_validator(mode="after")
    def _dense_shape(self):
        if self.reward == "dense_per_progress" and self.shape != "raw":
            raise ValueError(
                "dense_per_progress emits per-step rewards; the sparse "
                "end-of-episode shapings (cut/exp) do not apply")
        return self

    @classmethod
    def from_yaml(cls, path: str) -> "TrainConfig":
        with open(path) as f:
            return cls.model_validate(yaml.safe_load(f))

    # -- schedule helpers ------------------------------------------------

    def alpha_is_scheduled(self) -> bool:
        return not isinstance(self.alpha, float)

    def lane_alphas(self, n: int) -> np.ndarray:
        """Per-env-lane alphas covering the schedule (the batched analog
        of per-reset schedule draws)."""
        if isinstance(self.alpha, float):
            return np.full(n, self.alpha)
        if isinstance(self.alpha, Range):
            return np.linspace(self.alpha.min, self.alpha.max, n)
        return np.asarray(
            [self.alpha[i % len(self.alpha)] for i in range(n)])

    def eval_alphas(self) -> np.ndarray:
        if isinstance(self.alpha, float):
            return np.asarray([self.alpha])
        if isinstance(self.alpha, Range):
            n = max(2, int(round(
                (self.alpha.max - self.alpha.min) / self.eval.alpha_step)) + 1)
            return np.linspace(self.alpha.min, self.alpha.max, n)
        return np.asarray(sorted(set(self.alpha)))
