"""Config-driven PPO training: schedules, per-alpha eval, checkpoints.

Reference counterpart: experiments/train/ppo.py — alpha schedules
(:105-141), reward shaping raw/cut/exp (:217-244), the per-alpha
EvalCallback aggregation (:296-374), and model.zip / best-model.zip /
last-model.zip checkpoints (:429-453).  sb3 + SubprocVecEnv become the
native JAX trainer over one vmap'd env batch whose lanes carry the
schedule (make_train per_env_params); checkpoints are flax-serialized
parameter files.
"""

from __future__ import annotations

import json
import os
from contextlib import nullcontext
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from cpr_tpu import device_metrics, telemetry
from cpr_tpu.envs.registry import get_sized
from cpr_tpu.envs.assumption import AssumptionEnv
from cpr_tpu.params import stack_params
from cpr_tpu.train.config import TrainConfig
from cpr_tpu.train.ppo import (ActorCritic, PPOConfig, make_train,
                               maybe_checkify)


# Dense per-progress episodes terminate at target *progress*; max_steps
# is only a runaway guard.  The gym wrapper uses a loose 100x guard
# (cpr_tpu/gym/__init__.py core-v0 registration); here the factor also
# sizes the fixed DAG capacity and the scan length of every rollout, so
# it is a deliberate 4x — enough for any policy that makes progress at
# >= 1/4 the honest rate; pathological full-withholding episodes
# truncate at the cap instead of running 100x-long scans.
DENSE_RUNAWAY_FACTOR = 4


def _stack_params(alphas, gamma, episode_len, *, dense=False):
    if dense:
        return stack_params([dict(alpha=float(a), gamma=gamma,
                                  max_steps=(DENSE_RUNAWAY_FACTOR
                                             * episode_len),
                                  max_progress=float(episode_len))
                             for a in alphas])
    return stack_params([dict(alpha=float(a), gamma=gamma,
                              max_steps=episode_len) for a in alphas])


def make_reward_transform(cfg: TrainConfig, lane_alphas) -> Callable:
    """Sparse objective + shaping + 1/alpha normalization
    (ppo.py:217-244; wrappers.py:8-51)."""
    alphas = jnp.asarray(lane_alphas, jnp.float32)

    def transform(reward, info, done):
        a = info["episode_reward_attacker"]
        d = info["episode_reward_defender"]
        p = info["episode_progress"]
        if cfg.reward == "dense_per_progress":
            # per-step emission a_delta/h; the end-of-episode correction
            # a/p - a/h trues the total up to the real per-progress
            # objective (the sum of deltas over an episode is a, so the
            # emitted total is a/h — wrappers.py:78-113 stateless form)
            h = float(cfg.episode_len)
            step = info["step_reward_attacker"] / h
            corr = jnp.where(
                done, a / jnp.where(p != 0, p, 1.0) - a / h, 0.0)
            return (step + corr) / alphas
        if cfg.reward == "sparse_relative":
            s = a + d
            base = jnp.where(s != 0, a / jnp.where(s != 0, s, 1.0), 0.0)
        else:  # sparse_per_progress
            base = jnp.where(p != 0, a / jnp.where(p != 0, p, 1.0), 0.0)
        if cfg.shape == "cut":
            # punish honest-looking behaviour (ppo.py:224-236): no
            # orphans means the episode was ~honest, scale by 0.9
            orphans = jnp.where(
                p > 0, info["episode_n_activations"] / p, jnp.inf)
            base = jnp.where((base > 0) & (orphans <= 1.05),
                             base * 0.9, base)
        elif cfg.shape == "exp":
            base = jnp.where(base > 0, jnp.exp(base - 1.0), 0.0)
        return jnp.where(done, base / alphas, 0.0)

    return transform


def ppo_config(cfg: TrainConfig) -> PPOConfig:
    p = cfg.ppo
    return PPOConfig(
        n_envs=cfg.n_envs, n_steps=p.n_steps, lr=p.lr, gamma=p.gamma,
        gae_lambda=p.gae_lambda, clip_eps=p.clip_eps,
        entropy_coef=p.ent_coef, vf_coef=p.vf_coef,
        update_epochs=p.update_epochs, n_minibatches=p.n_minibatches,
        hidden=tuple([p.layer_size] * p.n_layers),
        anneal_lr=p.anneal_lr, total_updates=cfg.total_updates,
        target_kl=p.target_kl)


def build_env(cfg: TrainConfig):
    # dense episodes run up to 4*episode_len steps (progress can lag
    # steps); size DAG capacity for the worst case, not the target
    hint = cfg.episode_len * (
        DENSE_RUNAWAY_FACTOR if cfg.reward == "dense_per_progress" else 1)
    env = get_sized(cfg.protocol, hint)
    if cfg.alpha_is_scheduled():
        env = AssumptionEnv(env)
    return env


_EVAL_FN_CACHE: dict = {}


def _eval_fn(env, hidden, episode_len):
    """Jitted (net_params, keys, stacked_params) -> stats, cached so
    repeated evals during one training run compile once."""
    cache_key = (id(env), hidden, episode_len)
    fn = _EVAL_FN_CACHE.get(cache_key)
    if fn is None:
        net = ActorCritic(env.n_actions, hidden)

        def run(net_params, keys, params):
            def policy(obs):
                logits, _ = net.apply(net_params, obs)
                return jnp.argmax(logits, axis=-1)

            return jax.vmap(jax.vmap(
                lambda k, p: env.episode_stats(
                    k, p, policy, episode_len + 8),
                in_axes=(0, None)), in_axes=(0, 0))(keys, params)

        fn = _EVAL_FN_CACHE[cache_key] = jax.jit(run)
    return fn


def evaluate_per_alpha(env, cfg: TrainConfig, net_params, *,
                       episodes_per_alpha=None, seed=1):
    """Greedy-policy evaluation on the eval alpha grid; one batched
    kernel over (alphas x episodes) — the EvalCallback aggregation
    (ppo.py:296-374) as a single program.  Returns one row per alpha."""
    alphas = cfg.eval_alphas()
    reps = episodes_per_alpha or cfg.eval.episodes_per_alpha
    dense = cfg.reward == "dense_per_progress"
    params = _stack_params(alphas, cfg.gamma, cfg.episode_len, dense=dense)
    # dense episodes terminate on progress, which can lag steps; give the
    # eval rollout the same runaway budget as training (4x)
    fn = _eval_fn(env, ppo_config(cfg).hidden,
                  cfg.episode_len * (DENSE_RUNAWAY_FACTOR if dense else 1))
    keys = jax.random.split(jax.random.PRNGKey(seed), (len(alphas), reps))
    stats = jax.block_until_ready(fn(net_params, keys, params))
    rows = []
    for i, a in enumerate(alphas):
        atk = float(np.asarray(
            stats["episode_reward_attacker"][i]).mean())
        dfn = float(np.asarray(
            stats["episode_reward_defender"][i]).mean())
        prg = float(np.asarray(stats["episode_progress"][i]).mean())
        rows.append({
            "alpha": float(a),
            "gamma": cfg.gamma,
            "relative_reward": atk / (atk + dfn) if atk + dfn else 0.0,
            "reward_per_progress": atk / prg if prg else 0.0,
            "episode_progress": prg,
        })
    return rows


def save_checkpoint(path: str, net_params, meta: dict | None = None):
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(serialization.to_bytes(net_params))
    if meta is not None:
        with open(path + ".json", "w") as f:
            json.dump(meta, f)


def load_checkpoint(path: str, env, cfg: TrainConfig):
    net = ActorCritic(env.n_actions, ppo_config(cfg).hidden)
    template = net.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, env.observation_length)))
    with open(path, "rb") as f:
        return serialization.from_bytes(template, f.read())


def train_from_config(cfg: TrainConfig, *, out_dir: str | None = None,
                      n_updates: int | None = None, mesh=None,
                      progress: Callable | None = None):
    """Full training run: returns (net_params, history, eval_rows).

    Checkpoints (when out_dir is set): last-model.msgpack after every
    eval, best-model.msgpack when the mean eval relative reward improves
    (ppo.py:429-453 contract).
    """
    env = build_env(cfg)
    lane_alphas = cfg.lane_alphas(cfg.n_envs)
    env_params = _stack_params(lane_alphas, cfg.gamma, cfg.episode_len,
                               dense=cfg.reward == "dense_per_progress")
    pcfg = ppo_config(cfg)
    transform = make_reward_transform(cfg, lane_alphas)
    init_fn, train_step = make_train(env, env_params, pcfg, transform,
                                     per_env_params=True)
    carry = init_fn(jax.random.PRNGKey(cfg.seed))
    if mesh is not None:
        from cpr_tpu.parallel import shard_envs
        ts, env_state, obs, key = carry
        env_state = shard_envs(mesh, env_state, "dp")
        obs = shard_envs(mesh, obs, "dp")
        carry = (ts, env_state, obs, key)
    step = maybe_checkify(train_step)

    total = n_updates if n_updates is not None else cfg.total_updates
    history, eval_rows, best = [], [], -np.inf
    best_params = None
    metrics_log = None
    tele = telemetry.current()
    steps_per_update = cfg.n_envs * pcfg.n_steps
    manifest = telemetry.run_manifest(config=dict(
        protocol=cfg.protocol, seed=cfg.seed, n_envs=cfg.n_envs,
        episode_len=cfg.episode_len, reward=cfg.reward,
        n_steps=pcfg.n_steps, total_updates=total))
    if device_metrics.enabled():
        # XLA's own estimate of one update (flops, bytes) into the run
        # manifest; costs one extra compile, so it rides the same
        # opt-in as the in-graph metrics
        cost = telemetry.cost_snapshot(train_step, carry)
        if cost is not None:
            manifest["train_step_cost"] = cost
    if out_dir is not None:
        os.makedirs(out_dir, exist_ok=True)
        # self-describing run dir: the manifest rides both as its own
        # file and in the metrics header, so a copied-out metrics.jsonl
        # still says what backend/config produced it
        with open(os.path.join(out_dir, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=2)
        # JSONL metrics stream (the W&B-run-log analog, ppo.py:180-193):
        # one line per update, eval rows tagged; a header line separates
        # runs appended into the same directory
        metrics_log = open(os.path.join(out_dir, "metrics.jsonl"), "a")
        metrics_log.write(json.dumps(
            {"run": True, "protocol": cfg.protocol, "seed": cfg.seed,
             "total_updates": total, "manifest": manifest}) + "\n")
        metrics_log.flush()
    try:
        for i in range(total):
            # CPR_PROFILE_DIR captures ONE warm update (the second: the
            # first pays compile) instead of the whole run
            prof = (telemetry.maybe_profile("train_update")
                    if i == 1 else nullcontext())
            with prof, tele.span("update",
                                 env_steps=steps_per_update) as sp:
                carry, metrics = step(carry)
                sp.fence(carry)
                acc = metrics.pop("device_metrics", None)
                m = {k: float(v) for k, v in metrics.items()}
            if acc is not None:
                device_metrics.emit("ppo_update",
                                    train_step.metrics_spec, acc,
                                    update=i + 1)
            m["wall_s"] = round(sp.dur_s, 6)
            if sp.dur_s > 0:
                m["steps_per_sec"] = round(steps_per_update / sp.dur_s)
            history.append(m)
            if metrics_log is not None:
                metrics_log.write(json.dumps({"update": i + 1, **m}) + "\n")
                # flushed per update: a crash must not eat the stream's
                # tail (pre-telemetry, unflushed rows were only safe at
                # eval points)
                metrics_log.flush()
            if progress is not None:
                progress(i, m)
            # the first start_at_iteration updates never evaluate (early
            # deterministic policies are degenerate — cfg_model rationale)
            due = (i + 1) % cfg.eval.freq == 0 or i + 1 == total
            if due and i + 1 > cfg.eval.start_at_iteration:
                with tele.span("eval"):
                    rows = evaluate_per_alpha(env, cfg, carry[0].params)
                for r in rows:
                    r["update"] = i + 1
                eval_rows.extend(rows)
                if metrics_log is not None:
                    for r in rows:
                        metrics_log.write(
                            json.dumps({"eval": True, **r}) + "\n")
                    metrics_log.flush()
                # best/revert tracking is independent of checkpointing
                # (revert_frac must protect out_dir-less programmatic
                # runs too); only the file writes need out_dir
                score = float(np.mean(
                    [r["relative_reward"] for r in rows]))
                meta = dict(update=i + 1, score=score,
                            protocol=cfg.protocol)
                if out_dir is not None:
                    save_checkpoint(os.path.join(out_dir,
                                                 "last-model.msgpack"),
                                    carry[0].params, meta)
                if score > best:
                    best = score
                    best_params = carry[0].params
                    if out_dir is not None:
                        save_checkpoint(os.path.join(out_dir,
                                                     "best-model.msgpack"),
                                        carry[0].params, meta)
                elif (cfg.revert_frac is not None
                      and best_params is not None
                      and score < cfg.revert_frac * best):
                    # collapse: restart from the best checkpoint with
                    # fresh optimizer state, so one bad policy step
                    # cannot drag the run into the never-release
                    # attractor for good
                    ts = carry[0]
                    ts = ts.replace(
                        params=best_params,
                        opt_state=ts.tx.init(best_params))
                    carry = (ts,) + tuple(carry[1:])
                    tele.event("revert", update=i + 1, score=score,
                               best=best)
                    if metrics_log is not None:
                        metrics_log.write(json.dumps(
                            {"revert": True, "update": i + 1,
                             "score": score, "best": best}) + "\n")
                        metrics_log.flush()
    finally:
        if metrics_log is not None:
            metrics_log.close()
    return carry[0].params, history, eval_rows
