"""Config-driven PPO training: schedules, per-alpha eval, checkpoints.

Reference counterpart: experiments/train/ppo.py — alpha schedules
(:105-141), reward shaping raw/cut/exp (:217-244), the per-alpha
EvalCallback aggregation (:296-374), and model.zip / best-model.zip /
last-model.zip checkpoints (:429-453).  sb3 + SubprocVecEnv become the
native JAX trainer over one vmap'd env batch whose lanes carry the
schedule (make_train per_env_params); checkpoints are flax-serialized
parameter files.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import weakref
from contextlib import nullcontext
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from cpr_tpu import device_metrics, resilience, telemetry
from cpr_tpu.envs.registry import get_sized
from cpr_tpu.envs.assumption import AssumptionEnv
from cpr_tpu.params import stack_params
from cpr_tpu.train.config import TrainConfig
from cpr_tpu.train.ppo import (ActorCritic, PPOConfig, make_train,
                               maybe_checkify)


# Dense per-progress episodes terminate at target *progress*; max_steps
# is only a runaway guard.  The gym wrapper uses a loose 100x guard
# (cpr_tpu/gym/__init__.py core-v0 registration); here the factor also
# sizes the fixed DAG capacity and the scan length of every rollout, so
# it is a deliberate 4x — enough for any policy that makes progress at
# >= 1/4 the honest rate; pathological full-withholding episodes
# truncate at the cap instead of running 100x-long scans.
DENSE_RUNAWAY_FACTOR = 4


def _stack_params(alphas, gamma, episode_len, *, dense=False):
    if dense:
        return stack_params([dict(alpha=float(a), gamma=gamma,
                                  max_steps=(DENSE_RUNAWAY_FACTOR
                                             * episode_len),
                                  max_progress=float(episode_len))
                             for a in alphas])
    return stack_params([dict(alpha=float(a), gamma=gamma,
                              max_steps=episode_len) for a in alphas])


def make_reward_transform(cfg: TrainConfig, lane_alphas) -> Callable:
    """Sparse objective + shaping + 1/alpha normalization
    (ppo.py:217-244; wrappers.py:8-51)."""
    alphas = jnp.asarray(lane_alphas, jnp.float32)

    def transform(reward, info, done):
        a = info["episode_reward_attacker"]
        d = info["episode_reward_defender"]
        p = info["episode_progress"]
        if cfg.reward == "dense_per_progress":
            # per-step emission a_delta/h; the end-of-episode correction
            # a/p - a/h trues the total up to the real per-progress
            # objective (the sum of deltas over an episode is a, so the
            # emitted total is a/h — wrappers.py:78-113 stateless form)
            h = float(cfg.episode_len)
            step = info["step_reward_attacker"] / h
            corr = jnp.where(
                done, a / jnp.where(p != 0, p, 1.0) - a / h, 0.0)
            return (step + corr) / alphas
        if cfg.reward == "sparse_relative":
            s = a + d
            base = jnp.where(s != 0, a / jnp.where(s != 0, s, 1.0), 0.0)
        else:  # sparse_per_progress
            base = jnp.where(p != 0, a / jnp.where(p != 0, p, 1.0), 0.0)
        if cfg.shape == "cut":
            # punish honest-looking behaviour (ppo.py:224-236): no
            # orphans means the episode was ~honest, scale by 0.9
            orphans = jnp.where(
                p > 0, info["episode_n_activations"] / p, jnp.inf)
            base = jnp.where((base > 0) & (orphans <= 1.05),
                             base * 0.9, base)
        elif cfg.shape == "exp":
            base = jnp.where(base > 0, jnp.exp(base - 1.0), 0.0)
        return jnp.where(done, base / alphas, 0.0)

    return transform


def ppo_config(cfg: TrainConfig) -> PPOConfig:
    p = cfg.ppo
    return PPOConfig(
        n_envs=cfg.n_envs, n_steps=p.n_steps, lr=p.lr, gamma=p.gamma,
        gae_lambda=p.gae_lambda, clip_eps=p.clip_eps,
        entropy_coef=p.ent_coef, vf_coef=p.vf_coef,
        update_epochs=p.update_epochs, n_minibatches=p.n_minibatches,
        hidden=tuple([p.layer_size] * p.n_layers),
        anneal_lr=p.anneal_lr, total_updates=cfg.total_updates,
        target_kl=p.target_kl)


def build_env(cfg: TrainConfig):
    # dense episodes run up to 4*episode_len steps (progress can lag
    # steps); size DAG capacity for the worst case, not the target
    hint = cfg.episode_len * (
        DENSE_RUNAWAY_FACTOR if cfg.reward == "dense_per_progress" else 1)
    env = get_sized(cfg.protocol, hint)
    if cfg.alpha_is_scheduled():
        env = AssumptionEnv(env)
    return env


# Keyed by the env OBJECT via weakref, not id(env): a GC'd env's id can
# be reused by a new env, silently serving a jitted fn closed over the
# wrong env.  (The cached fn closes over the env, so in practice an
# entry keeps its key alive — same lifetime as the old id-keyed cache,
# but an id collision is now structurally impossible.)
_EVAL_FN_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _eval_fn(env, hidden, episode_len):
    """Jitted (net_params, keys, stacked_params) -> stats, cached so
    repeated evals during one training run compile once."""
    per_env = _EVAL_FN_CACHE.get(env)
    if per_env is None:
        per_env = _EVAL_FN_CACHE[env] = {}
    fn = per_env.get((hidden, episode_len))
    if fn is None:
        net = ActorCritic(env.n_actions, hidden)

        def run(net_params, keys, params):
            def policy(obs):
                logits, _ = net.apply(net_params, obs)
                return jnp.argmax(logits, axis=-1)

            return jax.vmap(jax.vmap(
                lambda k, p: env.episode_stats(
                    k, p, policy, episode_len + 8),
                in_axes=(0, None)), in_axes=(0, 0))(keys, params)

        fn = per_env[(hidden, episode_len)] = jax.jit(run)
    return fn


def evaluate_per_alpha(env, cfg: TrainConfig, net_params, *,
                       episodes_per_alpha=None, seed=1):
    """Greedy-policy evaluation on the eval alpha grid; one batched
    kernel over (alphas x episodes) — the EvalCallback aggregation
    (ppo.py:296-374) as a single program.  Returns one row per alpha."""
    alphas = cfg.eval_alphas()
    reps = episodes_per_alpha or cfg.eval.episodes_per_alpha
    dense = cfg.reward == "dense_per_progress"
    params = _stack_params(alphas, cfg.gamma, cfg.episode_len, dense=dense)
    # dense episodes terminate on progress, which can lag steps; give the
    # eval rollout the same runaway budget as training (4x)
    fn = _eval_fn(env, ppo_config(cfg).hidden,
                  cfg.episode_len * (DENSE_RUNAWAY_FACTOR if dense else 1))
    keys = jax.random.split(jax.random.PRNGKey(seed), (len(alphas), reps))
    stats = jax.block_until_ready(fn(net_params, keys, params))
    rows = []
    for i, a in enumerate(alphas):
        atk = float(np.asarray(
            stats["episode_reward_attacker"][i]).mean())
        dfn = float(np.asarray(
            stats["episode_reward_defender"][i]).mean())
        prg = float(np.asarray(stats["episode_progress"][i]).mean())
        rows.append({
            "alpha": float(a),
            "gamma": cfg.gamma,
            "relative_reward": atk / (atk + dfn) if atk + dfn else 0.0,
            "reward_per_progress": atk / prg if prg else 0.0,
            "episode_progress": prg,
        })
    return rows


def save_checkpoint(path: str, net_params, meta: dict | None = None,
                    *, site: str = "checkpoint"):
    """Sealed atomic params checkpoint (tmp + fsync + os.replace +
    checksummed envelope), so best-model.msgpack can never be observed
    half-written OR half-true.  The meta sidecar lands BEFORE the
    model rename — a reader that sees the new model always sees meta
    at least as new — and carries the payload's sha256 so
    `load_policy_snapshot` can prove the pair belongs together."""
    data = serialization.to_bytes(net_params)
    if meta is not None:
        meta = dict(meta, payload_sha256=hashlib.sha256(data).hexdigest())
        resilience.atomic_write_json(path + ".json", meta)
    resilience.sealed_write(path, data, site=site)


def load_checkpoint(path: str, env, cfg: TrainConfig):
    net = ActorCritic(env.n_actions, ppo_config(cfg).hidden)
    template = net.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, env.observation_length)))
    payload, _ = resilience.sealed_read(path, kind="model_checkpoint",
                                        action="refused")
    try:
        return serialization.from_bytes(template, payload)
    except resilience.IntegrityError:
        raise
    except Exception as e:  # msgpack raises its own hierarchy
        raise resilience.reject_undecodable(
            path, kind="model_checkpoint", err=e,
            action="refused") from e


def serving_meta(env, cfg: TrainConfig) -> dict:
    """Net-reconstruction record embedded in every checkpoint meta
    sidecar: with these fields the msgpack is self-contained — a
    consumer (cpr_tpu.serve's policy endpoint) rebuilds the ActorCritic
    and deserializes params without the TrainConfig or the env
    registry."""
    return dict(protocol=cfg.protocol,
                n_actions=int(env.n_actions),
                observation_length=int(env.observation_length),
                hidden=list(ppo_config(cfg).hidden),
                episode_len=int(cfg.episode_len),
                gamma=float(cfg.gamma))


def export_policy_snapshot(path: str, net_params, *, protocol: str,
                           n_actions: int, observation_length: int,
                           hidden, **extra):
    """Write a self-contained serving snapshot (msgpack + JSON meta
    sidecar, both atomic).  The meta carries everything
    `load_policy_snapshot` needs; `extra` fields ride along untouched.
    Training checkpoints written by `train_from_config` satisfy the
    same contract via `serving_meta`."""
    meta = dict(protocol=protocol, n_actions=int(n_actions),
                observation_length=int(observation_length),
                hidden=[int(h) for h in hidden], **extra)
    save_checkpoint(path, net_params, meta, site="snapshot")
    return meta


def load_policy_network(path: str):
    """Load a serving snapshot as its reconstruction pieces — returns
    (net, params, meta) instead of a closed-over policy.  The serving
    layer's hot-swap path needs the params separately: the engine holds
    them as an argument of the compiled burst and replaces them at a
    burst boundary without retracing (ResidentEngine.swap_policy).
    `meta["payload_sha256"]` is the snapshot fingerprint the whole
    learning loop correlates on (learn events, heartbeats, no-op swap
    detection).

    Refuses loudly (typed IntegrityError, never a KeyError or a
    silently wrong net) when the sidecar is missing, the sidecar's
    payload fingerprint contradicts the msgpack on disk, or the sealed
    payload fails its checksum — serving a half-written or mismatched
    policy is worse than crashing."""
    from cpr_tpu.integrity import IntegrityError, integrity_event

    sidecar = path + ".json"
    try:
        with open(sidecar) as f:
            meta = json.load(f)
    except (OSError, ValueError) as exc:
        integrity_event(artifact=path, kind="policy_snapshot",
                        reason="sidecar_missing", action="refused",
                        detail=str(exc))
        raise IntegrityError(
            f"policy snapshot {path}: meta sidecar {sidecar} is "
            f"missing or unreadable ({exc}) — re-export with "
            f"export_policy_snapshot; the msgpack alone does not "
            f"define the net shape",
            artifact=path, kind="policy_snapshot",
            reason="sidecar_missing") from None
    missing = [k for k in ("n_actions", "observation_length", "hidden")
               if k not in meta]
    if missing:
        raise ValueError(
            f"{path}.json is not a serving snapshot: missing {missing} "
            f"(write checkpoints with export_policy_snapshot or a "
            f"train_from_config recent enough to embed serving_meta)")
    payload, tag = resilience.sealed_read(path, kind="policy_snapshot",
                                          action="refused")
    expected = meta.get("payload_sha256")
    if expected is not None:
        found = hashlib.sha256(payload).hexdigest()
        if found != expected:
            integrity_event(artifact=path, kind="policy_snapshot",
                            reason="sidecar_missing", action="refused",
                            detail="sidecar fingerprint mismatch")
            raise IntegrityError(
                f"policy snapshot {path}: meta sidecar {sidecar} "
                f"expects payload sha256 {expected[:12]}…, file on "
                f"disk hashes to {found[:12]}… — the pair is torn "
                f"(stale sidecar or swapped msgpack); re-export both",
                artifact=path, kind="policy_snapshot",
                reason="sidecar_missing")
    meta = dict(meta, integrity=tag)
    net = ActorCritic(int(meta["n_actions"]),
                      tuple(int(h) for h in meta["hidden"]))
    template = net.init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, int(meta["observation_length"]))))
    try:
        params = serialization.from_bytes(template, payload)
    except IntegrityError:
        raise
    except Exception as e:  # garbled pre-seal payload, no fingerprint
        raise resilience.reject_undecodable(
            path, kind="policy_snapshot", err=e,
            action="refused") from e
    if "payload_sha256" not in meta:
        # older sidecars predate the fingerprint; derive it so every
        # consumer downstream can rely on the field
        meta = dict(meta,
                    payload_sha256=hashlib.sha256(payload).hexdigest())
    return net, params, meta


def load_policy_snapshot(path: str):
    """Reconstruct a jittable greedy policy `obs -> action` from a
    serving snapshot — the `.json` meta sidecar alone defines the net
    shape, so no TrainConfig or env instance is required.  Returns
    (policy, meta); same integrity refusals as `load_policy_network`,
    which this wraps."""
    net, params, meta = load_policy_network(path)

    def policy(obs):
        logits, _ = net.apply(params, obs)
        return jnp.argmax(logits, axis=-1)

    return policy, meta


def train_from_config(cfg: TrainConfig, *, out_dir: str | None = None,
                      n_updates: int | None = None, mesh=None,
                      progress: Callable | None = None,
                      resume: bool | str = False,
                      snapshot_freq: int | None = None,
                      metrics_port: int | None = None):
    """Full training run: returns (net_params, history, eval_rows).

    Checkpoints (when out_dir is set): last-model.msgpack after every
    eval, best-model.msgpack when the mean eval relative reward improves
    (ppo.py:429-453 contract).

    Crash safety (docs/RESILIENCE.md): `out_dir/snapshot.msgpack` holds
    the FULL train carry (params + optimizer state + env state + PRNG
    key) plus best/revert bookkeeping, written atomically every
    `snapshot_freq` updates (default: the eval cadence) and at the final
    update.  `resume=True` (or a snapshot path) restores the carry,
    trims metrics.jsonl rows the snapshot never saw, and continues —
    bit-identically to a run that was never interrupted.  SIGTERM/SIGINT
    between updates snapshot + write `preempt-model.msgpack` and return
    cleanly.  On resume, `history`/`eval_rows` cover only the resumed
    segment; metrics.jsonl carries the whole run.

    Live health plane (v14): a `cpr_train` MetricsRegistry tracks the
    update rate and the snapshot staleness — seconds (and updates)
    since the last durable snapshot, the restart-cost SLO a
    sampler/learner split watches.  `metrics_port` exposes it over
    HTTP (0 = ephemeral) for scraping mid-run.
    """
    env = build_env(cfg)
    lane_alphas = cfg.lane_alphas(cfg.n_envs)
    env_params = _stack_params(lane_alphas, cfg.gamma, cfg.episode_len,
                               dense=cfg.reward == "dense_per_progress")
    pcfg = ppo_config(cfg)
    transform = make_reward_transform(cfg, lane_alphas)
    init_fn, train_step = make_train(env, env_params, pcfg, transform,
                                     per_env_params=True)
    carry = init_fn(jax.random.PRNGKey(cfg.seed))
    if mesh is not None:
        from cpr_tpu.parallel import shard_envs
        ts, env_state, obs, key = carry
        env_state = shard_envs(mesh, env_state, "dp")
        obs = shard_envs(mesh, obs, "dp")
        carry = (ts, env_state, obs, key)
    step = maybe_checkify(train_step)

    total = n_updates if n_updates is not None else cfg.total_updates
    history, eval_rows, best = [], [], -np.inf
    best_params = None
    metrics_log = None
    tele = telemetry.current()
    steps_per_update = cfg.n_envs * pcfg.n_steps
    snap_config = dict(
        protocol=cfg.protocol, seed=cfg.seed, n_envs=cfg.n_envs,
        episode_len=cfg.episode_len, reward=cfg.reward,
        n_steps=pcfg.n_steps, total_updates=total)
    manifest = telemetry.run_manifest(config=dict(snap_config))
    # the stream gets the manifest too (no-op without a sink), so a
    # CPR_TELEMETRY capture of a training run validates standalone
    tele.emit(manifest)

    # live training health plane: update rate + snapshot staleness
    # (time/updates since the last durable snapshot — the bound on
    # lost work a preemption costs, ROADMAP item 2's SLO)
    from cpr_tpu.monitor.registry import MetricsRegistry
    health = MetricsRegistry(namespace="cpr_train")
    # v15 live memory watermark: sampled once per update alongside the
    # gauges, emitted as the typed `memory` event when the run winds
    # down (exception path included — the finally below owns it)
    mem = telemetry.MemoryWatermark("train")
    mem.sample()
    metrics_server = None
    if metrics_port is not None:
        from cpr_tpu.monitor.expo import MetricsServer
        metrics_server = MetricsServer(health.render_prometheus,
                                       port=metrics_port)
        metrics_server.start()
    # (wall stamp of last snapshot, update it covered)
    last_snap = [telemetry.now(), None]

    def _refresh_train_gauges(update, m):
        health.set("update", update,
                   help="updates completed this segment")
        wall = m.get("wall_s")
        health.set("updates_per_sec",
                   1.0 / wall if wall else None,
                   help="training update rate")
        health.set("steps_per_sec", m.get("steps_per_sec"),
                   help="env steps per second")
        health.set("snapshot_staleness_s",
                   telemetry.now() - last_snap[0],
                   help="seconds since the last durable snapshot")
        health.set("snapshot_staleness_updates",
                   (update - last_snap[1]
                    if last_snap[1] is not None else update),
                   help="updates since the last durable snapshot")
        mem.sample()
        if mem.peak_bytes is not None:
            health.set("memory_peak_bytes", mem.peak_bytes,
                       help="peak device/process memory this run "
                            "(bytes; max across devices)")
        if mem.in_use_bytes is not None:
            health.set("memory_in_use_bytes", mem.in_use_bytes,
                       help="device/process memory in use at last "
                            "sample (bytes)")
        if mem.headroom_bytes is not None:
            health.set("memory_headroom_bytes", mem.headroom_bytes,
                       help="allocator limit minus peak (bytes)")

    snap_path = (resume if isinstance(resume, str) else
                 os.path.join(out_dir, "snapshot.msgpack")
                 if out_dir is not None else None)
    snap_freq = (snapshot_freq
                 or int(os.environ.get("CPR_SNAPSHOT_FREQ", "0"))
                 or cfg.eval.freq)

    def _save_model(path, params, meta, kind):
        # injected io_error@checkpoint faults land inside the retried
        # callable, so a transient write failure is re-attempted
        def write():
            resilience.fault_point("checkpoint")
            save_checkpoint(path, params, meta)
        resilience.with_retries(write, max_attempts=3, base_delay_s=0.1,
                                max_delay_s=2.0, name=f"save:{kind}")
        # NB the artifact kind rides as `what`: a point event's `kind`
        # key is the JSONL record kind ("event") and must not be shadowed
        tele.event("checkpoint", path=path, what=kind)

    def _save_snapshot(update):
        def write():
            resilience.fault_point("checkpoint")
            resilience.save_train_snapshot(
                snap_path, carry, update=update, best=best,
                best_params=best_params, config=snap_config)
        resilience.with_retries(write, max_attempts=3, base_delay_s=0.1,
                                max_delay_s=2.0, name="save:snapshot")
        last_snap[0] = telemetry.now()
        last_snap[1] = update
        tele.event("checkpoint", path=snap_path, what="snapshot",
                   update=update)

    start_update = 0
    if resume:
        if snap_path is None:
            raise ValueError("resume requires out_dir or a snapshot path")
        # the sidecar is informational, but when present its config
        # fingerprint guards against resuming under a different config
        # (shape-compatible mismatches would otherwise pass silently)
        sidecar = snap_path + ".json"
        if os.path.exists(sidecar):
            with open(sidecar) as f:
                fp = json.load(f).get("config")
            if fp is not None and fp != snap_config:
                raise ValueError(
                    f"snapshot {snap_path} was written by config {fp}, "
                    f"this run is {snap_config}")
        try:
            carry, best_params, snap_meta = (
                resilience.load_train_snapshot(snap_path, carry))
        except resilience.IntegrityError:
            # detect -> quarantine -> recover: sealed_read already
            # moved the damaged snapshot to <path>.quarantine/ and
            # emitted the typed `integrity` event; training falls back
            # to a cold start, which is bit-identical to never having
            # snapshotted (the resilience acceptance criterion) — the
            # corrupt bytes were never deserialized into the carry
            snap_meta = None
        if snap_meta is not None:
            best = snap_meta["best"] if snap_meta["has_best"] else -np.inf
            start_update = snap_meta["update"]
        if snap_meta is not None and mesh is not None:
            from cpr_tpu.parallel import shard_envs
            ts, env_state, obs, key = carry
            env_state = shard_envs(mesh, env_state, "dp")
            obs = shard_envs(mesh, obs, "dp")
            carry = (ts, env_state, obs, key)
        last_snap[1] = start_update  # the restored snapshot's coverage
        if snap_meta is not None:
            tele.event("resume", path=snap_path, update=start_update)
    if device_metrics.enabled():
        # XLA's own estimate of one update (flops, bytes) into the run
        # manifest; costs one extra compile, so it rides the same
        # opt-in as the in-graph metrics
        cost = telemetry.cost_snapshot(train_step, carry)
        if cost is not None:
            manifest["train_step_cost"] = cost
    if out_dir is not None:
        os.makedirs(out_dir, exist_ok=True)
        # self-describing run dir: the manifest rides both as its own
        # file and in the metrics header, so a copied-out metrics.jsonl
        # still says what backend/config produced it
        resilience.atomic_write_json(
            os.path.join(out_dir, "manifest.json"), manifest)
        metrics_path = os.path.join(out_dir, "metrics.jsonl")
        if resume:
            # a killed run may have logged updates past the snapshot;
            # the resumed run re-produces them, so drop the orphans or
            # the stream would carry duplicate update numbers
            resilience.trim_metrics_log(metrics_path, start_update)
        # JSONL metrics stream (the W&B-run-log analog, ppo.py:180-193):
        # one line per update, eval rows tagged; a header line separates
        # runs appended into the same directory
        metrics_log = open(metrics_path, "a")
        header = {"run": True, "protocol": cfg.protocol, "seed": cfg.seed,
                  "total_updates": total, "manifest": manifest}
        if resume:
            header["resumed_from"] = snap_path
            header["start_update"] = start_update
        metrics_log.write(json.dumps(header) + "\n")
        metrics_log.flush()
    preempt_ctx = resilience.preemption_guard()
    try:
        preempt_ctx.__enter__()
        for i in range(start_update, total):
            # fault-injection site for this update; "nan" poisons the
            # params so the nonfinite-loss recovery below is testable
            act = resilience.fault_point("update", i + 1)
            if act == "nan":
                ts = carry[0]
                carry = (ts.replace(params=jax.tree_util.tree_map(
                    lambda x: jnp.full_like(x, jnp.nan), ts.params)),
                    ) + tuple(carry[1:])
            if resilience.preempt_requested():
                # preemption notice (SIGTERM/SIGINT or injected):
                # snapshot, drop a params-only preempt-model, exit clean
                reason = resilience.preempt_reason()
                if snap_path is not None:
                    _save_snapshot(i)
                if out_dir is not None:
                    _save_model(
                        os.path.join(out_dir, "preempt-model.msgpack"),
                        carry[0].params,
                        dict(update=i, protocol=cfg.protocol,
                             reason=reason), "preempt")
                tele.event("preempted", update=i, reason=reason)
                if metrics_log is not None:
                    metrics_log.write(json.dumps(
                        {"preempted": True, "update": i,
                         "reason": reason}) + "\n")
                    metrics_log.flush()
                break
            # CPR_PROFILE_DIR captures ONE warm update (the second: the
            # first pays compile) instead of the whole run
            prof = (telemetry.maybe_profile("train_update")
                    if i == 1 else nullcontext())
            with prof, tele.span("update",
                                 env_steps=steps_per_update) as sp:
                carry, metrics = step(carry)
                sp.fence(carry)
                acc = metrics.pop("device_metrics", None)
                m = {k: float(v) for k, v in metrics.items()}
            if acc is not None:
                device_metrics.emit("ppo_update",
                                    train_step.metrics_spec, acc,
                                    update=i + 1)
            m["wall_s"] = round(sp.dur_s, 6)
            if sp.dur_s > 0:
                m["steps_per_sec"] = round(steps_per_update / sp.dur_s)
            history.append(m)
            _refresh_train_gauges(i + 1, m)
            if metrics_log is not None:
                metrics_log.write(json.dumps({"update": i + 1, **m}) + "\n")
                # flushed per update: a crash must not eat the stream's
                # tail (pre-telemetry, unflushed rows were only safe at
                # eval points)
                metrics_log.flush()
            if progress is not None:
                progress(i, m)
            # nonfinite-loss recovery: a NaN/Inf loss means the params
            # (or optimizer moments) are already poisoned — restart
            # from the best checkpoint with fresh optimizer state, same
            # contract as the eval-score revert below.  Without a best
            # yet there is nothing safe to restore; the row above keeps
            # the poisoning visible either way.
            if (best_params is not None
                    and any(not math.isfinite(m.get(k, 0.0))
                            for k in ("pg_loss", "v_loss"))):
                ts = carry[0]
                carry = (ts.replace(
                    params=best_params,
                    opt_state=ts.tx.init(best_params)),
                    ) + tuple(carry[1:])
                tele.event("revert", update=i + 1, score=None, best=best,
                           reason="nonfinite_loss")
                if metrics_log is not None:
                    metrics_log.write(json.dumps(
                        {"revert": True, "update": i + 1,
                         "reason": "nonfinite_loss", "best": best}) + "\n")
                    metrics_log.flush()
            # the first start_at_iteration updates never evaluate (early
            # deterministic policies are degenerate — cfg_model rationale)
            due = (i + 1) % cfg.eval.freq == 0 or i + 1 == total
            if due and i + 1 > cfg.eval.start_at_iteration:
                with tele.span("eval"):
                    rows = evaluate_per_alpha(env, cfg, carry[0].params)
                for r in rows:
                    r["update"] = i + 1
                eval_rows.extend(rows)
                if metrics_log is not None:
                    for r in rows:
                        metrics_log.write(
                            json.dumps({"eval": True, **r}) + "\n")
                    metrics_log.flush()
                # best/revert tracking is independent of checkpointing
                # (revert_frac must protect out_dir-less programmatic
                # runs too); only the file writes need out_dir
                score = float(np.mean(
                    [r["relative_reward"] for r in rows]))
                # serving_meta makes the checkpoint loadable by
                # load_policy_snapshot (cpr_tpu.serve policy endpoint)
                meta = dict(update=i + 1, score=score,
                            **serving_meta(env, cfg))
                if out_dir is not None:
                    _save_model(os.path.join(out_dir,
                                             "last-model.msgpack"),
                                carry[0].params, meta, "last")
                if score > best:
                    best = score
                    best_params = carry[0].params
                    if out_dir is not None:
                        _save_model(os.path.join(out_dir,
                                                 "best-model.msgpack"),
                                    carry[0].params, meta, "best")
                elif (cfg.revert_frac is not None
                      and best_params is not None
                      and score < cfg.revert_frac * best):
                    # collapse: restart from the best checkpoint with
                    # fresh optimizer state, so one bad policy step
                    # cannot drag the run into the never-release
                    # attractor for good
                    ts = carry[0]
                    ts = ts.replace(
                        params=best_params,
                        opt_state=ts.tx.init(best_params))
                    carry = (ts,) + tuple(carry[1:])
                    tele.event("revert", update=i + 1, score=score,
                               best=best)
                    if metrics_log is not None:
                        metrics_log.write(json.dumps(
                            {"revert": True, "update": i + 1,
                             "score": score, "best": best}) + "\n")
                        metrics_log.flush()
            # snapshot AFTER the eval block so best/revert bookkeeping
            # from this update's eval is inside it; the final update
            # always snapshots, so resuming a finished run is a no-op
            if snap_path is not None and (
                    (i + 1) % snap_freq == 0 or i + 1 == total):
                _save_snapshot(i + 1)
    finally:
        # restore the pre-loop SIGTERM/SIGINT handlers even when the
        # loop unwinds via an exception
        preempt_ctx.__exit__(None, None, None)
        mem.sample()
        mem.emit()
        if metrics_log is not None:
            metrics_log.close()
        if metrics_server is not None:
            metrics_server.stop()
    return carry[0].params, history, eval_rows
