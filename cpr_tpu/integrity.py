"""Artifact integrity plane: checksummed envelopes, corruption
quarantine, and seeded chaos schedules (v16).

Every exactness guarantee in this repo — bit-identical resumes,
seed-replay failover, ledger-gated baselines — ultimately trusts bytes
read back from disk.  Production storage corrupts: a torn rename the
atomic-write discipline cannot see (the *old* file was already bad), a
bit flip under the filesystem, a hand-edit, a partial copy.  This
module makes corruption a *detected, typed, recoverable* event instead
of a crash or silent poison:

* **Sealed envelope** — `seal`/`unseal` wrap an artifact's payload in
  a one-line ASCII header: magic + seal schema + payload length +
  sha256.  `resilience.sealed_write`/`sealed_read` are the single
  write/read seam (atomic exactly as before); every persisted artifact
  family adopts it — train/policy snapshots, VI/grid-VI/compile
  checkpoints, the mdp-grid/attack/break-even caches.  Pre-v19
  unsealed artifacts still read (compat shim) but are tagged
  `integrity: "unverified"` — detection starts at the first sealed
  write, not at a flag day.

* **Detect -> quarantine -> recover** — a corrupt artifact is never
  deserialized into state.  `quarantine()` moves it (and its sidecar)
  to `<path>.quarantine/` and emits one typed schema-v16 `integrity`
  event (artifact/kind/reason/action); the *consumer* declares the
  recovery policy via the event's action: caches treat corruption as a
  miss and recompute (`regenerated`), checkpoint resume falls back to
  a cold start — bit-identical, the solve is deterministic either way
  (`quarantined`), snapshot load refuses loudly (`refused` — serving a
  half-written policy is worse than crashing), and the ledger/archive
  skip-and-report so one bad row can never poison a gate baseline.

* **Chaos schedules** — the fault grammar grows artifact-level actions
  (`corrupt@`, `truncate@`, `garble_json@` — resilience.py damages the
  just-written file through `damage_artifact` here), and
  `ChaosSchedule` composes seeded randomized fault sequences (kills,
  stalls, corruption, slow-IO) for `tools/chaos_smoke.py` — replayable
  from the seed alone, so a failing campaign is a repro, not a flake.

Import-time this module is jax-free (stdlib + telemetry only) so the
supervisor/bench parents and the perf tooling can verify artifacts
without initializing a backend.
"""

from __future__ import annotations

import hashlib
import json
import os
import random

from cpr_tpu import telemetry

# envelope header: b"CPRSEAL1 <schema> <length> <sha256hex>\n" + payload
SEAL_MAGIC = b"CPRSEAL1"
SEAL_SCHEMA = 1

REASONS = ("checksum", "truncated", "version", "sidecar_missing")
ACTIONS = ("quarantined", "regenerated", "refused")

# fault-grammar actions that damage a just-written artifact in place
# (dispatched by resilience.FaultInjector to damage_artifact below)
ARTIFACT_ACTIONS = ("corrupt", "truncate", "garble_json")


class IntegrityError(Exception):
    """A persisted artifact failed verification.  Named and actionable:
    carries the artifact path, its kind, and the typed reason (one of
    REASONS) so callers can branch on policy — and so the error a user
    sees says *which* file to look at and *what* was wrong with it."""

    def __init__(self, message: str, *, artifact: str, kind: str,
                 reason: str):
        super().__init__(message)
        self.artifact = artifact
        self.kind = kind
        self.reason = reason


def integrity_event(*, artifact: str, kind: str, reason: str,
                    action: str, **extra):
    """Emit one typed v16 `integrity` event (the only emitter — every
    detection funnels through here so the chaos smoke can match
    injected corruptions 1:1 against the validated trace).  On the
    wire the family travels as `artifact_kind`: `kind` is the
    telemetry envelope discriminator and a payload field named `kind`
    would shadow it."""
    telemetry.current().event("integrity", artifact=artifact,
                              artifact_kind=kind, reason=reason,
                              action=action, **extra)


# -- sealed envelope ---------------------------------------------------------


def seal(payload: bytes, *, schema: int = SEAL_SCHEMA) -> bytes:
    """Wrap payload bytes in the checksummed envelope."""
    digest = hashlib.sha256(payload).hexdigest()
    header = b"%s %d %d %s\n" % (SEAL_MAGIC, schema, len(payload),
                                 digest.encode())
    return header + payload


def is_sealed(data: bytes) -> bool:
    return data.startswith(SEAL_MAGIC + b" ")


def unseal(data: bytes, *, artifact: str = "<bytes>",
           kind: str = "artifact") -> tuple[bytes, str]:
    """Verify + strip the envelope.  Returns (payload, tag) where tag
    is "verified" (sealed, digest matched) or "unverified" (pre-v19
    unsealed artifact — passed through for the downstream deserializer
    to judge).  Raises IntegrityError with a typed reason when the
    envelope is present but the bytes behind it are damaged."""
    if not is_sealed(data):
        # compat shim: a file written before the envelope landed.  A
        # truncated-to-nothing file lands here too — the consumer's
        # deserializer is the detector of last resort.
        return data, "unverified"
    nl = data.find(b"\n")
    if nl < 0:
        raise IntegrityError(
            f"{kind} {artifact}: sealed header is torn (no payload)",
            artifact=artifact, kind=kind, reason="truncated")
    try:
        _, schema_s, length_s, digest = data[:nl].decode().split(" ")
        schema, length = int(schema_s), int(length_s)
    except ValueError:
        raise IntegrityError(
            f"{kind} {artifact}: sealed header is malformed",
            artifact=artifact, kind=kind, reason="truncated") from None
    if schema > SEAL_SCHEMA:
        raise IntegrityError(
            f"{kind} {artifact}: sealed with schema {schema}, this "
            f"build reads <= {SEAL_SCHEMA}",
            artifact=artifact, kind=kind, reason="version")
    payload = data[nl + 1:]
    if len(payload) != length:
        raise IntegrityError(
            f"{kind} {artifact}: payload is {len(payload)} bytes, "
            f"header promises {length} (truncated or torn write)",
            artifact=artifact, kind=kind, reason="truncated")
    got = hashlib.sha256(payload).hexdigest()
    if got != digest:
        raise IntegrityError(
            f"{kind} {artifact}: sha256 mismatch — header has "
            f"{digest[:12]}…, payload hashes to {got[:12]}… (bytes "
            f"corrupted on disk)",
            artifact=artifact, kind=kind, reason="checksum")
    return payload, "verified"


# -- quarantine --------------------------------------------------------------


def quarantine_dir(path: str) -> str:
    return path + ".quarantine"


def quarantine(path: str, *, kind: str, reason: str,
               action: str = "quarantined", sidecars=(".json",),
               emit: bool = True) -> str | None:
    """Move a corrupt artifact (plus any existing sidecars) into
    `<path>.quarantine/` so it is preserved for the post-mortem but
    can never be deserialized into state again, and emit the typed
    `integrity` event.  Returns the quarantined path (None when the
    artifact vanished underneath us — the event still fires: the
    *detection* happened)."""
    qdir = quarantine_dir(path)
    dest = None
    base = os.path.basename(path)
    try:
        os.makedirs(qdir, exist_ok=True)
        dest = os.path.join(qdir, base)
        n = 0
        while os.path.exists(dest):
            n += 1
            dest = os.path.join(qdir, f"{base}.{n}")
        os.replace(path, dest)
    except OSError:
        dest = None
    for ext in sidecars:
        side = path + ext
        if os.path.exists(side):
            try:
                os.replace(side, os.path.join(
                    qdir, os.path.basename(dest or side) + ext))
            except OSError:
                pass
    if emit:
        integrity_event(artifact=path, kind=kind, reason=reason,
                        action=action, quarantine=dest)
    return dest


# -- injected artifact damage ------------------------------------------------


def damage_artifact(path: str, action: str):
    """Deterministically damage an on-disk artifact in place — the
    storage-corruption stand-ins the fault grammar arms (`corrupt@`,
    `truncate@`, `garble_json@`).  Deliberately NOT atomic: real
    corruption isn't."""
    size = os.path.getsize(path)
    if action == "corrupt":
        # flip the last byte — always inside the sealed payload, so the
        # digest check (not just a decode error) is what must catch it
        with open(path, "r+b") as f:
            f.seek(max(size - 1, 0))
            tail = f.read(1) or b"\0"
            f.seek(max(size - 1, 0))
            f.write(bytes([tail[0] ^ 0xFF]))
    elif action == "truncate":
        os.truncate(path, size // 2)
    elif action == "garble_json":
        with open(path, "r+b") as f:
            f.write(b'{"garbled": ')
            f.truncate()
    else:
        raise ValueError(f"unknown artifact damage action {action!r}")


# -- chaos schedules ---------------------------------------------------------


class ChaosSchedule:
    """A seeded, replayable composition of randomized fault sequences
    for the chaos campaign (tools/chaos_smoke.py).  Everything derives
    from `seed` through one private random.Random — two constructions
    with the same seed produce identical schedules (asserted by test
    and by the smoke itself), so a failing campaign replays exactly.

    Scenario legs (each a CPR_FAULT_INJECT spec string, or a list of
    them):

    * `fleet_specs()` — per-round fault spec for the router+replicas
      under client flood: replica kills and cooperative slowdowns,
      randomized over target replica / occurrence index.
    * `solve_specs()` — the kill+corrupt sequence for the concurrent
      VI solve: damage one checkpoint write (randomized action), then
      kill a later chunk, so resume must fall back past the corrupted
      checkpoint to a cold start.
    * `cache_action()` — which artifact damage hits the grid cache.
    """

    def __init__(self, seed: int, *, rounds: int = 3, replicas: int = 2):
        self.seed = int(seed)
        self.rounds = int(rounds)
        self.replicas = int(replicas)
        rng = random.Random(self.seed)
        self._fleet = []
        for _ in range(self.rounds):
            specs = [f"kill@replica={rng.randrange(self.replicas)}"]
            if rng.random() < 0.5:
                specs.append("slow@replica="
                             f"{rng.randrange(self.replicas)}")
            self._fleet.append(",".join(specs))
        damage = rng.choice(ARTIFACT_ACTIONS)
        ckpt = rng.randint(1, 2)
        self._solve = (f"{damage}@vi_chunk={ckpt},"
                       f"kill@vi_chunk={ckpt + 1}")
        self._cache = rng.choice(ARTIFACT_ACTIONS)

    def fleet_specs(self) -> list[str]:
        return list(self._fleet)

    def solve_specs(self) -> str:
        return self._solve

    def cache_action(self) -> str:
        return self._cache

    def describe(self) -> dict:
        """JSON-safe self-description (logged by the smoke so the repro
        command — same seed — is always in the artifact)."""
        return {"seed": self.seed, "rounds": self.rounds,
                "replicas": self.replicas, "fleet": self._fleet,
                "solve": self._solve, "cache": self._cache}


# -- verify-on-read helpers for content-addressed rows -----------------------


def row_digest(row: dict, *, exclude=("row_id",)) -> str:
    """Recompute a ledger row's content hash exactly as
    perf.ledger._digest stamped it (sha1[:12] of the sorted-key JSON
    without the row_id itself) — verify-on-read for append-only JSONL
    where a whole-file envelope cannot work."""
    body = {k: v for k, v in row.items() if k not in exclude}
    return hashlib.sha1(
        json.dumps(body, sort_keys=True, default=str).encode()
    ).hexdigest()[:12]
