"""Structured traces, DAG exports, and malformed-DAG forensics.

Reference counterparts: the structured sim log with GraphML export
(simulator/lib/log.ml:1-160), the dot/GraphML DAG serializers
(simulator/lib/dagtools.ml:136-226), and the malformed-DAG dump hook
`CPR_MALFORMED_DAG_TO_FILE` (dagtools.ml:227-293, Makefile:1).

Everything here is host-side: JAX env states are pulled off-device once
per export, and the C++ oracle exposes its causal trace through the
ctypes API.  The common currency is `DagView` — plain node/edge lists
with typed attributes — which both engines can produce.
"""

from __future__ import annotations

import ctypes
import os
from dataclasses import dataclass, field
from xml.etree import ElementTree as ET
from xml.sax.saxutils import escape

import numpy as np

EVENT_KINDS = ("appends", "shares", "receives", "learns")


@dataclass
class DagView:
    """nodes: one dict per block, must contain 'id'; edges: (child,
    parent) pairs; events: (time, kind, node, block) causal trace."""

    nodes: list[dict] = field(default_factory=list)
    edges: list[tuple[int, int]] = field(default_factory=list)
    events: list[tuple[float, str, int, int]] = field(default_factory=list)


# -- adapters ----------------------------------------------------------------


def view_of_env_state(dag) -> DagView:
    """DagView of a JAX env's Dag pytree (cpr_tpu.core.dag.Dag)."""
    n = int(dag.n)
    parents = np.stack([np.asarray(p) for p in dag.parents], axis=1)[:n]
    view = DagView()
    fields = {
        "kind": np.asarray(dag.kind)[:n],
        "height": np.asarray(dag.height)[:n],
        "aux": np.asarray(dag.aux)[:n],
        "miner": np.asarray(dag.miner)[:n],
        "vis_a": np.asarray(dag.vis_a)[:n],
        "vis_d": np.asarray(dag.vis_d)[:n],
        "born_at": np.asarray(dag.born_at)[:n],
    }
    for i in range(n):
        node = {"id": i}
        for k, arr in fields.items():
            v = arr[i]
            node[k] = bool(v) if arr.dtype == bool else (
                float(v) if arr.dtype.kind == "f" else int(v))
        view.nodes.append(node)
        for p in parents[i]:
            if p >= 0:
                view.edges.append((i, int(p)))
    return view


def view_of_oracle(sim) -> DagView:
    """DagView + causal trace of a cpr_tpu.native.OracleSim."""
    L = sim._lib
    L.cpr_oracle_trace_len.restype = ctypes.c_long
    L.cpr_oracle_trace_len.argtypes = [ctypes.c_void_p]
    L.cpr_oracle_trace_get.restype = None
    L.cpr_oracle_trace_get.argtypes = [ctypes.c_void_p, ctypes.c_long,
                                       ctypes.POINTER(ctypes.c_double)]
    L.cpr_oracle_block.restype = None
    L.cpr_oracle_block.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                   ctypes.POINTER(ctypes.c_double)]
    L.cpr_oracle_block_parent.restype = ctypes.c_int
    L.cpr_oracle_block_parent.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                          ctypes.c_int]
    view = DagView()
    n = int(sim.metric("n_blocks")) + 1  # incl genesis
    buf = (ctypes.c_double * 6)()
    for i in range(n):
        L.cpr_oracle_block(sim._h, i, buf)
        view.nodes.append({
            "id": i, "miner": int(buf[0]), "height": int(buf[1]),
            "is_vote": bool(buf[2]), "vote_id": int(buf[3]),
            "time": float(buf[4]),
        })
        for j in range(int(buf[5])):
            p = L.cpr_oracle_block_parent(sim._h, i, j)
            if p >= 0:
                view.edges.append((i, p))
    if sim.metric("trace_truncated"):
        import warnings

        warnings.warn("oracle trace hit its capacity; the exported "
                      "event chain is incomplete")
    tb = (ctypes.c_double * 4)()
    for i in range(L.cpr_oracle_trace_len(sim._h)):
        L.cpr_oracle_trace_get(sim._h, i, tb)
        view.events.append((float(tb[0]), EVENT_KINDS[int(tb[1])],
                            int(tb[2]), int(tb[3])))
    return view


# -- exporters ---------------------------------------------------------------


def to_dot(view: DagView) -> str:
    """Graphviz dot text (dagtools.ml:136-192 analog)."""
    lines = ["digraph dag {", "  rankdir=RL;"]
    for nd in view.nodes:
        label = ", ".join(f"{k}={v}" for k, v in nd.items() if k != "id")
        lines.append(f'  b{nd["id"]} [label="{escape(label)}"];')
    for child, parent in view.edges:
        lines.append(f"  b{child} -> b{parent};")
    lines.append("}")
    return "\n".join(lines)


def to_graphml(view: DagView) -> str:
    """GraphML with typed data keys; vertices + parent edges + the event
    chain when present (log.ml to_graphml analog)."""
    root = ET.Element("graphml",
                      xmlns="http://graphml.graphdrawing.org/xmlns")
    keys: dict[tuple[str, str], str] = {}

    def key_id(name, typ):
        kid = keys.get((name, typ))
        if kid is None:
            kid = f"k{len(keys)}"
            keys[(name, typ)] = kid
            el = ET.Element("key", id=kid)
            el.set("for", "node")
            el.set("attr.name", name)
            el.set("attr.type", typ)
            root.insert(0, el)
        return kid

    graph = ET.SubElement(root, "graph", edgedefault="directed")

    def data_of(el, d):
        for k, v in d.items():
            if k == "id":
                continue
            typ = ("boolean" if isinstance(v, bool)
                   else "double" if isinstance(v, float)
                   else "long" if isinstance(v, int) else "string")
            de = ET.SubElement(el, "data", key=key_id(k, typ))
            de.text = str(v).lower() if isinstance(v, bool) else str(v)

    for nd in view.nodes:
        el = ET.SubElement(graph, "node", id=f"vertex{nd['id']}")
        data_of(el, nd)
    for child, parent in view.edges:
        ET.SubElement(graph, "edge", source=f"vertex{child}",
                      target=f"vertex{parent}")
    for i, (time, kind, node, block) in enumerate(view.events):
        el = ET.SubElement(graph, "node", id=f"event{i}")
        data_of(el, {"time": float(time), "event": kind,
                     "node": int(node)})
        ET.SubElement(graph, "edge", source=f"event{i}",
                      target=f"vertex{block}")
        if i > 0:
            ET.SubElement(graph, "edge", source=f"event{i - 1}",
                          target=f"event{i}")
    return ET.tostring(root, encoding="unicode")


# -- forensics ---------------------------------------------------------------

MALFORMED_ENV_VAR = "CPR_MALFORMED_DAG_TO_FILE"


class MalformedDag(Exception):
    pass


def raise_malformed(view: DagView, message: str):
    """Dump the offending DAG as dot when $CPR_MALFORMED_DAG_TO_FILE is
    set, then raise (dagtools.ml Exn.raise, :227-293)."""
    path = os.environ.get(MALFORMED_ENV_VAR)
    if path:
        # lazy import: the forensics dump is the only resilience use in
        # this module, and trace stays import-light for the ctypes views
        from cpr_tpu.resilience import atomic_write_text

        atomic_write_text(path, to_dot(view))
        message = f"{message} (DAG dumped to {path})"
    raise MalformedDag(message)
