"""Fixed-capacity block DAG as a structure of arrays.

Reference counterparts:
- simulator/lib/dag.ml — append-only DAG, serial ids, O(1) parent/child
  access, per-node visibility views (dag.ml:39-45),
- simulator/lib/simulator.ml:2-10 — per-block metadata {value; pow;
  signature; visibility; received_at; rewards},
- the Rust gym's per-block view triple (gym/rust/src/generic/mod.rs:21-44):
  attacker view / defender view / network state,
- reward accumulation along `precursor` (simulator/lib/simulator.ml:377-388)
  becomes per-block cumulative reward columns written at append time.

TPU re-design: capacity-B arrays; "views" are boolean visibility masks;
children lookups are masked scans over the parent matrix; chain walks are
bounded `lax.while_loop`s following parent slot 0 (the precursor). All ops
are O(B) or O(B*P) vector ops that XLA fuses; B is sized from the episode
length (one PoW + at most one structural append per step), so no
compaction is needed within an episode.

Convention: two parties — miner 0 is the attacker, miner 1 the defender
cloud (the collapse performed by the reference gym engine,
simulator/gym/engine.ml:100-107). `vis_a` is the attacker's view mask,
`vis_d` the defender cloud's. A block appended by the attacker starts
vis_a & ~vis_d == withheld; releasing sets vis_d (the simulator's
recursive share of withheld ancestors, simulator.ml:401-419, is
`release_with_ancestors`).
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
from flax import struct

NONE = jnp.int32(-1)
NO_POW = jnp.float32(jnp.inf)  # pow_hash for non-PoW blocks; smaller = better

ATTACKER = 0
DEFENDER = 1


@struct.dataclass
class Dag:
    # Parent slots as P separate (B,) int32 planes (NONE-padded):
    # parents[p][b] is block b's p-th parent.  NOT an array — three TPU
    # layout pathologies killed the matrix forms (round-4 device
    # profiles at 16k envs): a (B, P) matrix pads P up to 128 lanes
    # (~14x the logical bytes); a (P, B) matrix fixes padding but its
    # vmapped column write (dynamic-update-slice) wants a batch-minor
    # layout while the row reads want batch-major, so XLA keeps TWO
    # copies alive with ~7 ms transposing copies per scan step.  As
    # separate planes, writes are the same in-place row scatters as
    # every other per-slot field and reads are free static picks.
    parents: tuple
    # free-form per-slot float32 protocol fields written at append time
    # (bk: auxf = leader-vote hash; tailstorm: auxf/auxg = the summary's
    # own attacker/defender coinbase).  Exist so protocols can cache a
    # derived scalar instead of re-gathering it through parent
    # indirections every step (bk's leader-hash re-gather was
    # 102 ms/step at 16k envs).
    auxf: jnp.ndarray  # (B,) float32
    auxg: jnp.ndarray  # (B,) float32
    # free-form per-slot int32 protocol pointer written at append time
    # (tailstorm: the summary this summary extends; sdag: a block's
    # previous block).  Caches one level of parent indirection so chain
    # walks cost one gather per level instead of three (parent0 ->
    # kind -> signer).
    aux2: jnp.ndarray  # (B,) int32, NONE when unused
    # binary-lifting jump pointers along the precursor chain: the
    # 2nd/4th/8th/16th ancestor of each slot (NONE past the root).
    # Ancestors never change in an append-only DAG, so each is O(1) at
    # append time (anc2[new] = parent0[p0], anc4[new] = anc2[anc2[new]],
    # ...).  walk_back uses them to jump: under vmap a walk runs the
    # MAX trip count over the whole batch (~30+ under withholding
    # policies), which dominated the ethereum step.
    anc2: jnp.ndarray  # (B,) int32
    anc4: jnp.ndarray  # (B,) int32
    anc8: jnp.ndarray  # (B,) int32
    anc16: jnp.ndarray  # (B,) int32
    # ring-window occupancy (O(active-set) mode, zero-length when off):
    # slot s holds the block with global id gid[s]; appends claim slot
    # n % W, overwriting the W-th-oldest block.  The reference's event
    # loop only ever touches the live fork (simulator/lib/simulator.ml:
    # 421-533, dag.ml:28 append) — the ring is the tensor analog: every
    # per-step O(capacity) op shrinks to O(window) regardless of
    # episode length.  `live_floor` is the env-maintained retirement
    # frontier (lowest gid that may still be dereferenced; everything
    # below is retired like the reference's finalized history), and
    # evicting a block at/above it raises `overflow` — the same
    # episode-invalid semantics as capacity overflow in full mode.
    gid: jnp.ndarray  # (W,) int32, occupant global id (NONE = never used)
    live_floor: jnp.ndarray  # () int32, lowest still-referenceable gid
    # incremental ancestry bitmask planes (zero-length when off):
    # chain[x] marks x and its ancestors along the designated chain
    # pointer (parent slot 0 unless append passes chain_parent);
    # closure[x] marks x and the full recursive parent-row closure (the
    # simulator's recursive share set, simulator.ml:401-419).  Both rows
    # are written once at append (ancestors never change in an
    # append-only DAG), so every chain walk / release fixpoint that was
    # a lax.while_loop of per-iteration gathers (batch-MAX trip counts;
    # 68% of the ethereum step in the round-4/5 device profiles)
    # becomes ONE masked reduction over the (W,) row.
    chain: jnp.ndarray  # (W, W) bool
    closure: jnp.ndarray  # (W, W) bool
    kind: jnp.ndarray  # (B,) int32, protocol block-type tag
    height: jnp.ndarray  # (B,) int32
    aux: jnp.ndarray  # (B,) int32, protocol field (vote id, depth, ...)
    pow_hash: jnp.ndarray  # (B,) float32, NO_POW if not attached via PoW
    signer: jnp.ndarray  # (B,) int32, NONE if unsigned
    miner: jnp.ndarray  # (B,) int32, ATTACKER / DEFENDER / NONE (roots)
    vis_a: jnp.ndarray  # (B,) bool, attacker sees it
    vis_d: jnp.ndarray  # (B,) bool, defender cloud sees it
    vis_d_since: jnp.ndarray  # (B,) float32, when the defenders saw it
    born_at: jnp.ndarray  # (B,) float32, append time
    cum_atk: jnp.ndarray  # (B,) float32, attacker reward along precursors
    cum_def: jnp.ndarray  # (B,) float32
    cum_prog: jnp.ndarray  # (B,) float32, progress at this block
    n: jnp.ndarray  # () int32, number of blocks
    overflow: jnp.ndarray  # () bool, capacity exceeded (episode invalid)

    @property
    def is_ring(self) -> bool:
        return self.gid.shape[0] > 0

    @property
    def has_masks(self) -> bool:
        return self.chain.shape[0] > 0

    @property
    def parent0(self) -> jnp.ndarray:
        """(B,) precursor plane (parent slot 0) — the one the chain
        walks and slot-0 children scans read."""
        return self.parents[0]

    @property
    def capacity(self) -> int:
        return self.parents[0].shape[-1]

    @property
    def max_parents(self) -> int:
        return len(self.parents)

    def slots(self):
        """(B,) iota over block slots."""
        return jnp.arange(self.capacity, dtype=jnp.int32)

    def exists(self):
        """Mask of slots holding a live block.  Ring mode: gid < n
        rejects stale occupants surviving a logical reset (a claimed
        slot's gid is always in [n - W, n), while stale slots hold gids
        from a PREVIOUS episode that the current count has not reached
        — see JaxEnv.reset_dag_rows)."""
        if self.is_ring:
            return (self.gid >= 0) & (self.gid < self.n)
        return self.slots() < self.n

    def age_key(self):
        """(B,) int32 insertion-order key (smaller = appended earlier).
        Full mode appends slots in order so the slot id IS the age; the
        ring wraps, so ordering must use the occupant gid.  Use this
        wherever 'first/last appended' matters (candidate-frame
        compaction order, release prefixes, newest-released tips)."""
        return self.gid if self.is_ring else self.slots()


def empty(capacity: int, max_parents: int, lift: bool = False,
          ring: bool = False, anc_masks: bool = False) -> Dag:
    """`lift=True` materializes the binary-lifting ancestor planes
    (anc2..anc16) for O(log) walk_back jumps; off they are zero-length
    placeholders and appends skip their maintenance — the extra four
    row writes per append cost more than short walks save (bk measured
    -17% with lift on; ethereum's deep release walks gain).  Lift
    requires height to increment by exactly 1 along parent slot 0 (see
    common_ancestor_by_height) and monotone walk_back stop predicates
    (see walk_back's contract).

    `ring=True` turns the capacity into a sliding window over the W
    most recent blocks (see Dag.gid): appends wrap, and the env must
    keep `live_floor` at the retirement frontier (retire_below) so
    evictions of still-referenced blocks raise `overflow`.  Not
    combinable with `lift` — a jump target below the floor would read
    a reused slot's new occupant.

    `anc_masks=True` materializes the incremental chain/closure
    ancestry planes (see Dag.chain/closure and the *_mask queries).
    The planes are O(B^2) per env — 2*B^2 bytes that vmap multiplies by
    the batch size (at B=2048 that is 8 MiB/env, 8 GiB at 1k envs) —
    so they are meant for ring windows, where B is the small active-set
    window, not the episode length."""
    B, P = capacity, max_parents
    assert not (ring and lift), "ring + lift: jumps could land on reused slots"
    if anc_masks and not ring and B > 2048:
        warnings.warn(
            f"anc_masks=True at capacity {B} materializes two ({B}, {B}) "
            f"planes ({2 * B * B / 2**20:.0f} MiB per env, scaled by the "
            "vmap batch). Use a ring window (which bounds the planes to "
            "the active set) or anc_masks=False with the walk-based "
            "queries.", stacklevel=2)
    LB = B if lift else 0
    RB = B if ring else 0
    MB = B if anc_masks else 0
    f = lambda fill, dt: jnp.full((B,), fill, dt)
    g = lambda: jnp.full((LB,), NONE, jnp.int32)
    return Dag(
        parents=tuple(jnp.full((B,), NONE, jnp.int32) for _ in range(P)),
        gid=jnp.full((RB,), NONE, jnp.int32),
        live_floor=jnp.int32(0),
        chain=jnp.zeros((MB, MB), jnp.bool_),
        closure=jnp.zeros((MB, MB), jnp.bool_),
        auxf=f(0.0, jnp.float32),
        auxg=f(0.0, jnp.float32),
        aux2=f(NONE, jnp.int32),
        anc2=g(), anc4=g(), anc8=g(), anc16=g(),
        kind=f(0, jnp.int32),
        height=f(0, jnp.int32),
        aux=f(0, jnp.int32),
        pow_hash=f(NO_POW, jnp.float32),
        signer=f(NONE, jnp.int32),
        miner=f(NONE, jnp.int32),
        vis_a=f(False, jnp.bool_),
        vis_d=f(False, jnp.bool_),
        vis_d_since=f(0.0, jnp.float32),
        born_at=f(0.0, jnp.float32),
        cum_atk=f(0.0, jnp.float32),
        cum_def=f(0.0, jnp.float32),
        cum_prog=f(0.0, jnp.float32),
        n=jnp.int32(0),
        overflow=jnp.bool_(False),
    )


def append(dag: Dag, parents, *, kind=0, height=0, aux=0, pow_hash=NO_POW,
           signer=NONE, miner=NONE, vis_a=True, vis_d=True, time=0.0,
           reward_atk=0.0, reward_def=0.0, progress=None, auxf=0.0,
           auxg=0.0, aux2=NONE, chain_parent=None):
    """Append one block; returns (dag, index). `parents` is a (P,) int32
    row (NONE-padded); parent slot 0 is the precursor along which
    cumulative rewards accumulate (simulator.ml:377-388). `progress`
    defaults to cum_prog[precursor] + 1 when None-like is passed
    explicitly; pass the absolute progress value otherwise."""
    dag, idx = append_if(
        dag, jnp.bool_(True), parents, kind=kind, height=height, aux=aux,
        pow_hash=pow_hash, signer=signer, miner=miner, vis_a=vis_a,
        vis_d=vis_d, time=time, reward_atk=reward_atk,
        reward_def=reward_def, progress=progress, auxf=auxf, auxg=auxg,
        aux2=aux2, chain_parent=chain_parent)
    return dag, idx


def append_if(dag: Dag, cond, parents, *, kind=0, height=0, aux=0,
              pow_hash=NO_POW, signer=NONE, miner=NONE, vis_a=True,
              vis_d=True, time=0.0, reward_atk=0.0, reward_def=0.0,
              progress=None, auxf=0.0, auxg=0.0, aux2=NONE,
              chain_parent=None):
    """`append` gated by traced bool `cond`; returns (dag, idx_or_NONE).

    Replaces the append-then-rollback pattern
    (``dag2, i = append(...); tree.map(where(cond), dag2, dag)``): the
    full-state select costs two whole-DAG copies per call and, inside a
    scan, defeats in-place carry updates.  Every field is written with a
    row-level conditional scatter (see put below) on its own (B,) plane
    — with parents stored as per-slot planes these are the same cheap
    in-place updates as every other per-slot field.  (A (P, B) parents
    MATRIX must not come back here: its vmapped column write wants a
    batch-minor layout and XLA then keeps a second transposed copy of
    the matrix alive across the scan, ~7 ms per step at 16k envs —
    round-4 device profile.)"""
    # `chain_parent` names the block the chain-ancestry plane follows
    # (defaults to parent slot 0); protocols whose linear history is
    # not the precursor pass their own pointer (tailstorm: the summary
    # this summary extends)
    if dag.is_ring:
        idx = jax.lax.rem(dag.n, jnp.int32(dag.capacity))
        # evicting a live block at/above the retirement frontier means
        # the window was too small for this fork — episode invalid,
        # same semantics as running out of capacity in full mode
        evicted = dag.gid[idx]
        overflow = dag.overflow | (
            cond & (evicted >= 0) & (evicted < dag.n)
            & (evicted >= dag.live_floor))
    else:
        idx = jnp.minimum(dag.n, dag.capacity - 1)
        overflow = dag.overflow | (cond & (dag.n >= dag.capacity))
    p0 = parents[0]
    has_p0 = p0 >= 0
    base = jnp.where(has_p0, p0, 0)
    cum_atk = jnp.where(has_p0, dag.cum_atk[base], 0.0) + reward_atk
    cum_def = jnp.where(has_p0, dag.cum_def[base], 0.0) + reward_def
    if progress is None:
        cum_prog = jnp.where(has_p0, dag.cum_prog[base], 0.0) + 1.0
    else:
        cum_prog = jnp.asarray(progress, jnp.float32)

    def put(arr, value):
        # row-level conditional scatter: .at[idx].set is an in-place
        # carry update inside scans (a one-hot where() here forces a
        # full read+write of every array per step — measured 1.3x
        # slower end-to-end on chip; the scatter wins despite TPU's
        # dislike of dynamic indices)
        value = jnp.asarray(value, arr.dtype)
        return arr.at[idx].set(jnp.where(cond, value, arr[idx]))

    if dag.anc2.shape[0]:  # lifted DAG (static): maintain jump planes
        # ancestors of the new block already exist and never change, so
        # each level is one scalar gather through the previous plane
        def hop(plane, v):
            return jnp.where(v >= 0, plane[jnp.maximum(v, 0)], NONE)

        v2 = hop(dag.parents[0], p0)
        v4 = hop(dag.anc2, v2)
        v8 = hop(dag.anc4, v4)
        v16 = hop(dag.anc8, v8)
        anc = dict(anc2=put(dag.anc2, v2), anc4=put(dag.anc4, v4),
                   anc8=put(dag.anc8, v8), anc16=put(dag.anc16, v16))
    else:
        anc = {}

    if dag.is_ring:
        anc["gid"] = put(dag.gid, dag.n)

    if dag.has_masks:
        # ancestry rows: ancestors never change in an append-only DAG,
        # so one row write per plane at append replaces every later
        # walk/fixpoint with a masked reduction (see chain_mask /
        # closure_mask / common_ancestor_masked / release_masked)
        new_bit = jnp.arange(dag.capacity, dtype=jnp.int32) == idx
        cp = parents[0] if chain_parent is None else chain_parent
        crow = new_bit | _valid_row(dag, dag.chain, cp)
        orow = new_bit
        for p in range(dag.max_parents):
            orow = orow | _valid_row(dag, dag.closure, parents[p])
        anc["chain"] = put(dag.chain, crow)
        anc["closure"] = put(dag.closure, orow)

    dag = dag.replace(
        parents=tuple(put(plane, parents[p])
                      for p, plane in enumerate(dag.parents)),
        auxf=put(dag.auxf, auxf),
        auxg=put(dag.auxg, auxg),
        aux2=put(dag.aux2, aux2),
        **anc,
        kind=put(dag.kind, kind),
        height=put(dag.height, height),
        aux=put(dag.aux, aux),
        pow_hash=put(dag.pow_hash, pow_hash),
        signer=put(dag.signer, signer),
        miner=put(dag.miner, miner),
        vis_a=put(dag.vis_a, vis_a),
        vis_d=put(dag.vis_d, vis_d),
        vis_d_since=put(dag.vis_d_since,
                        jnp.where(jnp.asarray(vis_d),
                                  jnp.asarray(time, jnp.float32),
                                  jnp.float32(jnp.inf))),
        born_at=put(dag.born_at, time),
        cum_atk=put(dag.cum_atk, cum_atk),
        cum_def=put(dag.cum_def, cum_def),
        cum_prog=put(dag.cum_prog, cum_prog),
        # ring mode: n is the total append count (gids keep growing);
        # full mode clamps so idx stays pinned at the last slot
        n=(dag.n + cond.astype(jnp.int32) if dag.is_ring
           else jnp.minimum(dag.n + cond.astype(jnp.int32), dag.capacity)),
        overflow=overflow,
    )
    return dag, jnp.where(cond, idx, NONE)


def retire_below(dag: Dag, floor_gid) -> Dag:
    """Raise the ring retirement frontier to `floor_gid` (monotone).
    Envs call this once per step with the gid of their common-ancestor
    frontier — everything strictly below it is finalized history that
    only lives on in the cumulative reward/progress columns, exactly
    like the reference only ever touches the live fork
    (simulator.ml:421-533).  No-op in full mode."""
    if not dag.is_ring:
        return dag
    return dag.replace(
        live_floor=jnp.maximum(dag.live_floor,
                               jnp.asarray(floor_gid, jnp.int32)))


def _valid_row(dag: Dag, plane, x):
    """(B,) bits of `plane[x]` that still refer to their original
    blocks: in ring mode a slot reclaimed after x's append carries a
    larger occupant gid, so the occupant-gid filter removes exactly the
    stale columns (same argument as append's inherit)."""
    xi = jnp.maximum(x, 0)
    row = jnp.where(x >= 0, plane[xi], False)
    if dag.is_ring:
        row = row & (dag.gid <= dag.gid[xi]) & (dag.gid >= 0)
    return row


def chain_mask(dag: Dag, x) -> jnp.ndarray:
    """(B,) mask of x and its ancestors along the chain pointer (the
    incremental twin of walking parent slot 0 / the env's chain_parent;
    requires empty(anc_masks=True))."""
    return _valid_row(dag, dag.chain, x)


def closure_mask(dag: Dag, x) -> jnp.ndarray:
    """(B,) mask of x and its full recursive parent-row closure — the
    simulator's recursive share set (simulator.ml:401-419), O(B) per
    query instead of an ancestor fixpoint."""
    return _valid_row(dag, dag.closure, x)


def release_masked(dag: Dag, tip, time) -> Dag:
    """release_with_ancestors via the closure plane: one row read, no
    while loop.  Equivalent because 'defender-visible implies ancestors
    visible' holds inductively (honest nodes mine on visible blocks;
    every release goes through a recursive share), so re-releasing the
    already-visible part of the closure is a no-op."""
    return release(dag, closure_mask(dag, tip), time)


def common_ancestor_masked(dag: Dag, a, b):
    """Common ancestor of two chain tips via one row intersection: the
    deepest shared element is the one of maximum height (heights are
    strictly increasing along a chain).  Masked twin of
    common_ancestor_by_height (dagtools.ml:102-121)."""
    m = chain_mask(dag, a) & chain_mask(dag, b)
    best = jnp.argmax(jnp.where(m, dag.height, -1)).astype(jnp.int32)
    return jnp.where(m.any(), best, NONE)


def chain_first_at_most(dag: Dag, tip, values, target, extra_mask=None):
    """First block walking the chain down from `tip` whose `values`
    entry is <= target (optionally also satisfying `extra_mask`) — the
    masked twin of walk_back/block_at_height for monotone-nonincreasing
    `values` (height, cumulative work): the first satisfying block on
    the way down is the highest-height satisfying chain member."""
    m = chain_mask(dag, tip) & (values <= target)
    if extra_mask is not None:
        m = m & extra_mask
    best = jnp.argmax(jnp.where(m, dag.height, -1)).astype(jnp.int32)
    return jnp.where(m.any(), best, NONE)


def drop_if_retired(dag: Dag, idx):
    """NONE if the block at slot `idx` has retired below the ring
    floor, else `idx` unchanged.  For env-state slot pointers (race
    tips, match targets) that may outlive the fork: call immediately
    after retire_below, while the occupant is still the original block
    — after a reclaim the gid compare would read the NEW occupant.
    No-op in full mode."""
    if not dag.is_ring:
        return idx
    retired = (idx >= 0) & (dag.gid[jnp.maximum(idx, 0)] < dag.live_floor)
    return jnp.where(retired, NONE, idx)


def first_by_age(dag: Dag, mask):
    """Index of the earliest-appended block in `mask` (insertion order;
    NONE if empty).  Replaces lowest-slot argmax where 'first' must
    mean age — in ring mode slot order wraps."""
    key = jnp.where(mask, dag.age_key(), jnp.int32(2**30))
    best = jnp.argmin(key).astype(jnp.int32)
    return jnp.where(mask.any(), best, NONE)


def last_by_age(dag: Dag, mask):
    """Index of the latest-appended block in `mask` (NONE if empty) —
    the wrap-safe form of `where(mask, slots, -1).max()`."""
    key = jnp.where(mask, dag.age_key(), jnp.int32(-1))
    best = jnp.argmax(key).astype(jnp.int32)
    return jnp.where(mask.any(), best, NONE)


def descendants_mask(dag: Dag, a) -> jnp.ndarray:
    """(B,) mask of blocks having `a` on their chain-ancestry row (a
    included) — one column read of the chain plane.  Replaces bounded
    descent walks ('does x's chain pass through a?').  Ring staleness:
    a row's bit at column a refers to a PREVIOUS occupant iff the
    current occupant is younger than the row owner, so requiring the
    row owner to be at least as young as `a` keeps exactly the bits
    that mean the current occupant."""
    ai = jnp.maximum(a, 0)
    col = jnp.where(a >= 0, dag.chain[:, ai], False)
    if dag.is_ring:
        col = col & (dag.gid >= dag.gid[ai])
    return col & dag.exists()


def select_vis(cond, released: Dag, dag: Dag) -> Dag:
    """where(cond, released, dag) specialized to what release() can
    change: the two defender-visibility arrays.  A full-pytree
    tree.map select copies every DAG field (parents included) twice per
    call; release never touches anything else, so selecting vis_d /
    vis_d_since alone keeps the scan carry update in place."""
    return dag.replace(
        vis_d=jnp.where(cond, released.vis_d, dag.vis_d),
        vis_d_since=jnp.where(cond, released.vis_d_since,
                              dag.vis_d_since),
    )


def newer_than(dag: Dag, v) -> jnp.ndarray:
    """(B,) mask of blocks appended AFTER v — the ring guard for every
    stored-pointer equality query.  After a wrap, a stale row's slot
    pointer aliases the slot's NEW occupant (a vote of a retired block
    r still resident when r's slot is reclaimed by x would read as a
    child of x); genuine referrers are always younger than their
    target, and stale rows always predate the reclaimer, so the age
    compare separates them exactly.  All-true in full mode."""
    if not dag.is_ring:
        return jnp.ones((dag.capacity,), jnp.bool_)
    return dag.gid > dag.gid[jnp.maximum(v, 0)]


def children_mask(dag: Dag, v) -> jnp.ndarray:
    """(B,) mask of blocks having v among their parents (dag.ml:44)."""
    hit = dag.parents[0] == v
    for plane in dag.parents[1:]:
        hit = hit | (plane == v)
    return dag.exists() & hit & newer_than(dag, v)


def children0_mask(dag: Dag, v) -> jnp.ndarray:
    """(B,) mask of blocks whose PRECURSOR (parent slot 0) is v.  For
    protocols where every attachment of interest rides slot 0 — bk votes
    and proposals both precede via slot 0 — this replaces a padded
    (B, P)-matrix scan with a flat (B,) compare (~10x cheaper on TPU,
    see Dag.parent0)."""
    return dag.exists() & (dag.parent0 == v) & newer_than(dag, v)


def release(dag: Dag, mask, time) -> Dag:
    """Make the masked withheld blocks visible to the defender cloud."""
    newly = mask & ~dag.vis_d & dag.exists()
    return dag.replace(
        vis_d=dag.vis_d | newly,
        vis_d_since=jnp.where(newly, time, dag.vis_d_since),
    )


def parents_hit(dag: Dag, mask) -> jnp.ndarray:
    """(B,) mask of blocks that appear in the parent row of any block in
    `mask` — the one-hop "scatter child hits onto parent slots" step
    shared by the ancestor fixpoints below."""
    B = dag.capacity
    hits = jnp.zeros((B,), jnp.bool_)
    for p in range(dag.max_parents):
        col = dag.parents[p]
        hit = mask & (col >= 0)
        hits = hits | (
            jnp.zeros((B,), jnp.bool_).at[jnp.clip(col, 0)].max(hit))
    return hits


def parents_hit_dense(dag: Dag, mask) -> jnp.ndarray:
    """parents_hit via a dense (B, B) compare per plane instead of a
    batched scatter.  On TPU a vmapped scatter with a (B,)-wide index
    vector serializes (~9 ms per plane at 4096 envs x B=264 — round-4
    device profile); the dense compare is plain elementwise work and an
    any-reduce, ~10x cheaper for small-capacity DAGs.  O(B^2) per plane:
    use only where B^2 x P stays modest (ethereum's release closure at
    B=264, P=3); the scatter form wins for big-B x many-plane DAGs."""
    slots = jnp.arange(dag.capacity, dtype=jnp.int32)
    hits = jnp.zeros((dag.capacity,), jnp.bool_)
    for p in range(dag.max_parents):
        col = dag.parents[p]
        m = mask & (col >= 0)
        hits = hits | (m[:, None] & (col[:, None] == slots[None, :])
                       ).any(axis=0)
    return hits


def ancestors_mask(dag: Dag, v) -> jnp.ndarray:
    """(B,) mask of v and all its ancestors (fixpoint BFS over the parent
    matrix; the analog of dagtools.ml:73-100 iterate_ancestors). The loop
    runs until the mask stops growing, <= DAG height iterations on any
    DAG produced by `append` (parents always point at earlier slots)."""
    B = dag.capacity
    seed = jnp.zeros((B,), jnp.bool_).at[jnp.maximum(v, 0)].set(v >= 0)

    def body(state):
        mask, _ = state
        new = mask | parents_hit(dag, mask)
        return new, (new != mask).any()

    def cond(state):
        return state[1]

    mask, _ = jax.lax.while_loop(cond, body, (seed, v >= 0))
    return mask


def release_with_ancestors(dag: Dag, v, time) -> Dag:
    """Share v and (recursively) its withheld ancestors — the simulator's
    recursive share (simulator.ml:401-419)."""
    return release(dag, ancestors_mask(dag, v), time)


def release_chain(dag: Dag, tip, time) -> Dag:
    """Release `tip`, its full parent row, and walk down the precursor
    chain until a block that was defender-visible BEFORE this call.
    Equivalent to `release_with_ancestors` whenever non-precursor parents
    (votes) sit directly on precursor-chain blocks — true for all
    chain+vote protocols here — but costs O(newly released) instead of a
    full-DAG ancestor fixpoint per call.

    The stop test uses each next tip's visibility as read before its row
    was released: releasing block t's parent row marks row[0] visible, so
    re-reading vis_d after the release would terminate the walk after one
    iteration and under-release chains withheld deeper than 2.

    The loop carries ONLY the two visibility arrays release() can
    change; everything else (parents rows, existence) is read from the
    enclosing dag.  Carrying the whole Dag re-materializes the padded
    parents matrix every iteration — the dominant cost of withholding
    steps at large batch on TPU."""
    B = dag.capacity
    exists = dag.exists()
    slots = jnp.arange(B, dtype=jnp.int32)

    def cond(carry):
        _, _, t, t_vis = carry
        return (t >= 0) & ~t_vis

    def body(carry):
        vis_d, vis_d_since, t, _ = carry
        nxt = dag.parent0[t]
        # pre-release visibility of the next tip: must be read before
        # release() marks the whole row (nxt included) visible
        nxt_vis = vis_d[jnp.maximum(nxt, 0)]
        # release t + its parent row.  The row is read one PARENT SLOT
        # at a time — dag.parents[p] is a free static slice of the
        # (P, B) matrix and [t] a scalar gather — because a batched
        # column gather (parents[:, t]) makes XLA keep a second,
        # batch-minor copy of the whole matrix alive across the scan
        # (two ~7 ms transposing copies per step at 16k envs).
        mask = slots == t
        for p in range(dag.max_parents):
            v = dag.parents[p][t]
            mask = mask | ((slots == v) & (v >= 0))
        newly = mask & ~vis_d & exists
        vis_d = vis_d | newly
        vis_d_since = jnp.where(newly, time, vis_d_since)
        return vis_d, vis_d_since, nxt, nxt_vis

    tip_vis = dag.vis_d[jnp.maximum(tip, 0)]
    vis_d, vis_d_since, _, _ = jax.lax.while_loop(
        cond, body, (dag.vis_d, dag.vis_d_since, tip, tip_vis))
    return dag.replace(vis_d=vis_d, vis_d_since=vis_d_since)


def release_closure(dag: Dag, tip, time) -> Dag:
    """`release_chain` plus a visibility-closure fixpoint: any parent
    referenced by a defender-visible block becomes visible too.

    Matches the reference's fully recursive share (simulator.ml:401-419)
    even when a released non-precursor parent carries its OWN withheld
    parent row — e.g. an orphaned ethereum uncle U (made while withheld,
    including withheld uncle W) later re-included by a new chain block:
    the chain walk releases U via the row but never walks U, so W needs
    the closure pass.  The loop exits after a single check in the common
    case (uncle nesting is rare), so per-step cost stays O(newly
    released) instead of release_with_ancestors' height-deep fixpoint."""
    dag = release_chain(dag, tip, time)
    exists = dag.exists()

    def missing(vis_d):
        # parents referenced by visible blocks but not yet visible
        ref = parents_hit_dense(dag, exists & vis_d)
        return ref & ~vis_d & exists

    def body(carry):
        vis_d, vis_d_since, m = carry
        newly = m & ~vis_d & exists
        vis_d = vis_d | newly
        vis_d_since = jnp.where(newly, time, vis_d_since)
        return vis_d, vis_d_since, missing(vis_d)

    # the fixpoint, like the chain walk above, carries only the two
    # visibility arrays (parents_hit reads the matrix from the closure)
    vis_d, vis_d_since, _ = jax.lax.while_loop(
        lambda c: c[2].any(), body,
        (dag.vis_d, dag.vis_d_since, missing(dag.vis_d)))
    return dag.replace(vis_d=vis_d, vis_d_since=vis_d_since)


def walk_back(dag: Dag, tip, stop_fn):
    """Follow parent slot 0 from `tip` while not stop_fn(dag, idx),
    returning the first chain node where stop_fn holds (or -1 past the
    root).

    CONTRACT: stop_fn must be MONOTONE along the precursor chain (once
    true at a node, true at every chain ancestor) — true for the height
    and preference targets every caller uses.  That licenses binary
    lifting: each iteration takes the largest anc2/4/8/16 jump whose
    LANDING node does not yet satisfy stop_fn, else one parent0 step —
    O(log depth) iterations instead of O(depth).  Under vmap the trip
    count is the max over the batch (~30+ under withholding policies),
    which made the linear walk the dominant cost of the ethereum step
    (round-4 device profile)."""

    def cond(i):
        return (i >= 0) & ~stop_fn(dag, i)

    if dag.anc2.shape[0]:  # lifted DAG (static): jump walk

        def ok(j):
            # candidate jump target j is usable iff it exists and has
            # not passed the stop boundary
            return (j >= 0) & ~stop_fn(dag, jnp.maximum(j, 0))

        def body(i):
            j16 = dag.anc16[i]
            j8 = dag.anc8[i]
            j4 = dag.anc4[i]
            j2 = dag.anc2[i]
            return jnp.where(
                ok(j16), j16,
                jnp.where(ok(j8), j8,
                          jnp.where(ok(j4), j4,
                                    jnp.where(ok(j2), j2,
                                              dag.parent0[i]))))
    else:

        def body(i):
            return dag.parent0[i]

    return jax.lax.while_loop(cond, body, tip)


def block_at_height(dag: Dag, tip, target_height, is_block_fn=None):
    """Walk the precursor chain from `tip` down to the first block with
    height <= target_height (nakamoto_ssz.ml:238-247, bk_ssz.ml:283-291).

    `is_block_fn` makes the stop predicate NON-monotone along the chain
    (false-then-true is possible below the height boundary), which the
    lifted walk_back's jump contract forbids — that combination walks
    linearly instead."""
    def stop(dag, i):
        ok = dag.height[i] <= target_height
        if is_block_fn is not None:
            ok = ok & is_block_fn(dag, i)
        return ok

    if is_block_fn is not None and dag.anc2.shape[0]:
        # linear walk: jumps could overshoot the first satisfying block
        def cond(i):
            return (i >= 0) & ~stop(dag, i)

        return jax.lax.while_loop(cond, lambda i: dag.parent0[i], tip)
    return walk_back(dag, tip, stop)


def common_ancestor_by_height(dag: Dag, a, b):
    """Common ancestor of two chain tips linked via parent slot 0, using
    heights to synchronize the walk (dagtools.ml:102-121, re-shaped as a
    height-indexed two-pointer loop; on a lifted DAG the walk jumps via
    the anc planes — equalize by the largest power <= the height
    difference, then descend both tips one level wherever their
    J-ancestors differ, the classic binary-lifting LCA).

    LIFTED-DAG PRECONDITION: height must increment by exactly 1 along
    parent slot 0 (true for ethereum, the only lifted env) — the
    equalize phase equates "jump J ancestors" with "drop J height
    units"; a protocol with height jumps > 1 along the precursor must
    not enable empty(lift=True)."""

    def cond(state):
        x, y = state
        return (x != y) & (x >= 0) & (y >= 0)

    if dag.anc2.shape[0]:  # lifted DAG (static)

        def body(state):
            x, y = state
            hx, hy = dag.height[x], dag.height[y]
            d = hx - hy

            def down(i, dist):
                # largest jump <= dist that stays on the chain
                j16, j8 = dag.anc16[i], dag.anc8[i]
                j4, j2 = dag.anc4[i], dag.anc2[i]
                return jnp.where(
                    (dist >= 16) & (j16 >= 0), j16,
                    jnp.where((dist >= 8) & (j8 >= 0), j8,
                              jnp.where((dist >= 4) & (j4 >= 0), j4,
                                        jnp.where((dist >= 2) & (j2 >= 0),
                                                  j2, dag.parent0[i]))))

            # equal heights: largest level whose ancestors still differ
            # keeps both tips strictly below the common ancestor
            x16, y16 = dag.anc16[x], dag.anc16[y]
            x8, y8 = dag.anc8[x], dag.anc8[y]
            x4, y4 = dag.anc4[x], dag.anc4[y]
            x2, y2 = dag.anc2[x], dag.anc2[y]
            u16 = (x16 >= 0) & (y16 >= 0) & (x16 != y16)
            u8 = (x8 >= 0) & (y8 >= 0) & (x8 != y8)
            u4 = (x4 >= 0) & (y4 >= 0) & (x4 != y4)
            u2 = (x2 >= 0) & (y2 >= 0) & (x2 != y2)
            eq_x = jnp.where(u16, x16, jnp.where(u8, x8, jnp.where(
                u4, x4, jnp.where(u2, x2, dag.parent0[x]))))
            eq_y = jnp.where(u16, y16, jnp.where(u8, y8, jnp.where(
                u4, y4, jnp.where(u2, y2, dag.parent0[y]))))

            new_x = jnp.where(d > 0, down(x, d), jnp.where(d < 0, x, eq_x))
            new_y = jnp.where(d < 0, down(y, -d), jnp.where(d > 0, y, eq_y))
            return new_x, new_y
    else:

        def body(state):
            x, y = state
            hx, hy = dag.height[x], dag.height[y]
            # step the higher one down; on ties step both
            step_x = hx >= hy
            step_y = hy >= hx
            return (jnp.where(step_x, dag.parent0[x], x),
                    jnp.where(step_y, dag.parent0[y], y))

    x, y = jax.lax.while_loop(cond, body, (a, b))
    return x


def mask_of(idx, valid, B: int) -> jnp.ndarray:
    """(B,) bool mask with idx[i] set where valid[i] — the scatter-free
    form of ``zeros.at[idx].max(valid)``.  On TPU a vmapped scatter
    with a (k,)-index vector costs ~0.3 ms/step at 4096 envs (round-4
    device profile); the (k, B) one-hot compare + any-reduce is plain
    elementwise work."""
    slots = jnp.arange(B, dtype=jnp.int32)
    return ((idx[:, None] == slots[None, :]) & valid[:, None]).any(axis=0)


def top_k_by(score, mask, k: int, largest: bool = False):
    """Indices of the k best masked entries by score (ascending by
    default — used for smallest-hash vote selection). Returns (idx, valid)
    where valid marks real entries (fewer than k may match).

    Small k extracts iteratively (k argmin/argmax passes) instead of
    lax.top_k: on TPU top_k lowers to a full sort of the capacity-B
    lane, ~3 ms per call at 16k envs x 520 slots (round-4 device
    profile) — the extraction loop is ~5x cheaper and keeps top_k's
    tie-by-lowest-index order (argmin/argmax return the first hit)."""
    neutral = -jnp.inf if largest else jnp.inf
    s = jnp.where(mask, score, neutral).astype(jnp.float32)
    if k <= 16:
        slots = jnp.arange(s.shape[-1], dtype=jnp.int32)
        pick = jnp.argmax if largest else jnp.argmin
        best = jnp.max if largest else jnp.min
        idxs, valids = [], []
        for _ in range(k):
            j = pick(s).astype(jnp.int32)
            v = best(s)
            idxs.append(j)
            valids.append(v != neutral)
            s = jnp.where(slots == j, neutral, s)
        return jnp.stack(idxs), jnp.stack(valids)
    if largest:
        vals, idx = jax.lax.top_k(s, k)
        valid = vals > -jnp.inf
    else:
        vals, idx = jax.lax.top_k(-s, k)
        valid = vals > -jnp.inf
    return idx, valid
