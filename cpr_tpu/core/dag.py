"""Fixed-capacity block DAG as a structure of arrays.

Reference counterparts:
- simulator/lib/dag.ml — append-only DAG, serial ids, O(1) parent/child
  access, per-node visibility views (dag.ml:39-45),
- simulator/lib/simulator.ml:2-10 — per-block metadata {value; pow;
  signature; visibility; received_at; rewards},
- the Rust gym's per-block view triple (gym/rust/src/generic/mod.rs:21-44):
  attacker view / defender view / network state,
- reward accumulation along `precursor` (simulator/lib/simulator.ml:377-388)
  becomes per-block cumulative reward columns written at append time.

TPU re-design: capacity-B arrays; "views" are boolean visibility masks;
children lookups are masked scans over the parent matrix; chain walks are
bounded `lax.while_loop`s following parent slot 0 (the precursor). All ops
are O(B) or O(B*P) vector ops that XLA fuses; B is sized from the episode
length (one PoW + at most one structural append per step), so no
compaction is needed within an episode.

Convention: two parties — miner 0 is the attacker, miner 1 the defender
cloud (the collapse performed by the reference gym engine,
simulator/gym/engine.ml:100-107). `vis_a` is the attacker's view mask,
`vis_d` the defender cloud's. A block appended by the attacker starts
vis_a & ~vis_d == withheld; releasing sets vis_d (the simulator's
recursive share of withheld ancestors, simulator.ml:401-419, is
`release_with_ancestors`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

NONE = jnp.int32(-1)
NO_POW = jnp.float32(jnp.inf)  # pow_hash for non-PoW blocks; smaller = better

ATTACKER = 0
DEFENDER = 1


@struct.dataclass
class Dag:
    parents: jnp.ndarray  # (B, P) int32, NONE-padded
    kind: jnp.ndarray  # (B,) int32, protocol block-type tag
    height: jnp.ndarray  # (B,) int32
    aux: jnp.ndarray  # (B,) int32, protocol field (vote id, depth, ...)
    pow_hash: jnp.ndarray  # (B,) float32, NO_POW if not attached via PoW
    signer: jnp.ndarray  # (B,) int32, NONE if unsigned
    miner: jnp.ndarray  # (B,) int32, ATTACKER / DEFENDER / NONE (roots)
    vis_a: jnp.ndarray  # (B,) bool, attacker sees it
    vis_d: jnp.ndarray  # (B,) bool, defender cloud sees it
    vis_d_since: jnp.ndarray  # (B,) float32, when the defenders saw it
    born_at: jnp.ndarray  # (B,) float32, append time
    cum_atk: jnp.ndarray  # (B,) float32, attacker reward along precursors
    cum_def: jnp.ndarray  # (B,) float32
    cum_prog: jnp.ndarray  # (B,) float32, progress at this block
    n: jnp.ndarray  # () int32, number of blocks
    overflow: jnp.ndarray  # () bool, capacity exceeded (episode invalid)

    @property
    def capacity(self) -> int:
        return self.parents.shape[0]

    @property
    def max_parents(self) -> int:
        return self.parents.shape[1]

    def slots(self):
        """(B,) iota over block slots."""
        return jnp.arange(self.capacity, dtype=jnp.int32)

    def exists(self):
        return self.slots() < self.n


def empty(capacity: int, max_parents: int) -> Dag:
    B, P = capacity, max_parents
    f = lambda fill, dt: jnp.full((B,), fill, dt)
    return Dag(
        parents=jnp.full((B, P), NONE, jnp.int32),
        kind=f(0, jnp.int32),
        height=f(0, jnp.int32),
        aux=f(0, jnp.int32),
        pow_hash=f(NO_POW, jnp.float32),
        signer=f(NONE, jnp.int32),
        miner=f(NONE, jnp.int32),
        vis_a=f(False, jnp.bool_),
        vis_d=f(False, jnp.bool_),
        vis_d_since=f(0.0, jnp.float32),
        born_at=f(0.0, jnp.float32),
        cum_atk=f(0.0, jnp.float32),
        cum_def=f(0.0, jnp.float32),
        cum_prog=f(0.0, jnp.float32),
        n=jnp.int32(0),
        overflow=jnp.bool_(False),
    )


def append(dag: Dag, parents, *, kind=0, height=0, aux=0, pow_hash=NO_POW,
           signer=NONE, miner=NONE, vis_a=True, vis_d=True, time=0.0,
           reward_atk=0.0, reward_def=0.0, progress=None):
    """Append one block; returns (dag, index). `parents` is a (P,) int32
    row (NONE-padded); parent slot 0 is the precursor along which
    cumulative rewards accumulate (simulator.ml:377-388). `progress`
    defaults to cum_prog[precursor] + 1 when None-like is passed
    explicitly; pass the absolute progress value otherwise."""
    idx = jnp.minimum(dag.n, dag.capacity - 1)
    overflow = dag.overflow | (dag.n >= dag.capacity)
    p0 = parents[0]
    has_p0 = p0 >= 0
    base = jnp.where(has_p0, p0, 0)
    cum_atk = jnp.where(has_p0, dag.cum_atk[base], 0.0) + reward_atk
    cum_def = jnp.where(has_p0, dag.cum_def[base], 0.0) + reward_def
    if progress is None:
        cum_prog = jnp.where(has_p0, dag.cum_prog[base], 0.0) + 1.0
    else:
        cum_prog = jnp.asarray(progress, jnp.float32)
    dag = dag.replace(
        parents=dag.parents.at[idx].set(parents),
        kind=dag.kind.at[idx].set(kind),
        height=dag.height.at[idx].set(height),
        aux=dag.aux.at[idx].set(aux),
        pow_hash=dag.pow_hash.at[idx].set(pow_hash),
        signer=dag.signer.at[idx].set(signer),
        miner=dag.miner.at[idx].set(miner),
        vis_a=dag.vis_a.at[idx].set(vis_a),
        vis_d=dag.vis_d.at[idx].set(vis_d),
        vis_d_since=dag.vis_d_since.at[idx].set(
            jnp.where(jnp.asarray(vis_d), jnp.asarray(time, jnp.float32),
                      jnp.float32(jnp.inf))),
        born_at=dag.born_at.at[idx].set(time),
        cum_atk=dag.cum_atk.at[idx].set(cum_atk),
        cum_def=dag.cum_def.at[idx].set(cum_def),
        cum_prog=dag.cum_prog.at[idx].set(cum_prog),
        n=jnp.minimum(dag.n + 1, dag.capacity),
        overflow=overflow,
    )
    return dag, idx


def children_mask(dag: Dag, v) -> jnp.ndarray:
    """(B,) mask of blocks having v among their parents (dag.ml:44)."""
    return dag.exists() & (dag.parents == v).any(axis=1)


def release(dag: Dag, mask, time) -> Dag:
    """Make the masked withheld blocks visible to the defender cloud."""
    newly = mask & ~dag.vis_d & dag.exists()
    return dag.replace(
        vis_d=dag.vis_d | newly,
        vis_d_since=jnp.where(newly, time, dag.vis_d_since),
    )


def parents_hit(dag: Dag, mask) -> jnp.ndarray:
    """(B,) mask of blocks that appear in the parent row of any block in
    `mask` — the one-hop "scatter child hits onto parent slots" step
    shared by the ancestor fixpoints below."""
    B = dag.capacity
    hits = jnp.zeros((B,), jnp.bool_)
    for p in range(dag.max_parents):
        col = dag.parents[:, p]
        hit = mask & (col >= 0)
        hits = hits | (
            jnp.zeros((B,), jnp.bool_).at[jnp.clip(col, 0)].max(hit))
    return hits


def ancestors_mask(dag: Dag, v) -> jnp.ndarray:
    """(B,) mask of v and all its ancestors (fixpoint BFS over the parent
    matrix; the analog of dagtools.ml:73-100 iterate_ancestors). The loop
    runs until the mask stops growing, <= DAG height iterations on any
    DAG produced by `append` (parents always point at earlier slots)."""
    B = dag.capacity
    seed = jnp.zeros((B,), jnp.bool_).at[jnp.maximum(v, 0)].set(v >= 0)

    def body(state):
        mask, _ = state
        new = mask | parents_hit(dag, mask)
        return new, (new != mask).any()

    def cond(state):
        return state[1]

    mask, _ = jax.lax.while_loop(cond, body, (seed, v >= 0))
    return mask


def release_with_ancestors(dag: Dag, v, time) -> Dag:
    """Share v and (recursively) its withheld ancestors — the simulator's
    recursive share (simulator.ml:401-419)."""
    return release(dag, ancestors_mask(dag, v), time)


def release_chain(dag: Dag, tip, time) -> Dag:
    """Release `tip`, its full parent row, and walk down the precursor
    chain until an already-defender-visible block. Equivalent to
    `release_with_ancestors` whenever non-precursor parents (votes) sit
    directly on precursor-chain blocks — true for all chain+vote protocols
    here — but costs O(newly released) instead of a full-DAG ancestor
    fixpoint per call."""
    B = dag.capacity

    def cond(carry):
        dag, t = carry
        return (t >= 0) & ~dag.vis_d[jnp.maximum(t, 0)]

    def body(carry):
        dag, t = carry
        row = dag.parents[t]
        mask = jnp.zeros((B,), jnp.bool_).at[jnp.clip(row, 0)].max(row >= 0)
        mask = mask.at[t].set(True)
        dag = release(dag, mask, time)
        return dag, row[0]

    dag, _ = jax.lax.while_loop(cond, body, (dag, tip))
    return dag


def release_closure(dag: Dag, tip, time) -> Dag:
    """`release_chain` plus a visibility-closure fixpoint: any parent
    referenced by a defender-visible block becomes visible too.

    Matches the reference's fully recursive share (simulator.ml:401-419)
    even when a released non-precursor parent carries its OWN withheld
    parent row — e.g. an orphaned ethereum uncle U (made while withheld,
    including withheld uncle W) later re-included by a new chain block:
    the chain walk releases U via the row but never walks U, so W needs
    the closure pass.  The loop exits after a single check in the common
    case (uncle nesting is rare), so per-step cost stays O(newly
    released) instead of release_with_ancestors' height-deep fixpoint."""
    dag = release_chain(dag, tip, time)

    def missing(d):
        ref = parents_hit(d, d.exists() & d.vis_d)
        return ref & ~d.vis_d & d.exists()

    def body(carry):
        d, m = carry
        d = release(d, m, time)
        return d, missing(d)

    dag, _ = jax.lax.while_loop(lambda c: c[1].any(), body,
                                (dag, missing(dag)))
    return dag


def walk_back(dag: Dag, tip, stop_fn):
    """Follow parent slot 0 from `tip` while not stop_fn(dag, idx).
    Terminates at the root (parent -1) at the latest — <= DAG height
    iterations; the chain-walk primitive behind `last_block`, height
    targeting, and common ancestors."""

    def cond(i):
        return (i >= 0) & ~stop_fn(dag, i)

    def body(i):
        nxt = dag.parents[i, 0]
        return nxt

    return jax.lax.while_loop(cond, body, tip)


def block_at_height(dag: Dag, tip, target_height, is_block_fn=None):
    """Walk the precursor chain from `tip` down to the first block with
    height <= target_height (nakamoto_ssz.ml:238-247, bk_ssz.ml:283-291)."""
    def stop(dag, i):
        ok = dag.height[i] <= target_height
        if is_block_fn is not None:
            ok = ok & is_block_fn(dag, i)
        return ok

    return walk_back(dag, tip, stop)


def common_ancestor_by_height(dag: Dag, a, b):
    """Common ancestor of two chain tips linked via parent slot 0, using
    heights to synchronize the walk (dagtools.ml:102-121, re-shaped as a
    height-indexed two-pointer loop)."""

    def cond(state):
        x, y = state
        return (x != y) & (x >= 0) & (y >= 0)

    def body(state):
        x, y = state
        hx, hy = dag.height[x], dag.height[y]
        # step the higher one down; on ties step both
        step_x = hx >= hy
        step_y = hy >= hx
        return (jnp.where(step_x, dag.parents[x, 0], x),
                jnp.where(step_y, dag.parents[y, 0], y))

    x, y = jax.lax.while_loop(cond, body, (a, b))
    return x


def top_k_by(score, mask, k: int, largest: bool = False):
    """Indices of the k best masked entries by score (ascending by
    default — used for smallest-hash vote selection). Returns (idx, valid)
    where valid marks real entries (fewer than k may match)."""
    s = jnp.where(mask, score, jnp.inf if not largest else -jnp.inf)
    if largest:
        vals, idx = jax.lax.top_k(s, k)
        valid = vals > -jnp.inf
    else:
        vals, idx = jax.lax.top_k(-s, k)
        valid = vals > -jnp.inf
    return idx, valid
