"""Fixed-capacity block DAG as a structure of arrays.

Reference counterparts:
- simulator/lib/dag.ml — append-only DAG, serial ids, O(1) parent/child
  access, per-node visibility views (dag.ml:39-45),
- simulator/lib/simulator.ml:2-10 — per-block metadata {value; pow;
  signature; visibility; received_at; rewards},
- the Rust gym's per-block view triple (gym/rust/src/generic/mod.rs:21-44):
  attacker view / defender view / network state,
- reward accumulation along `precursor` (simulator/lib/simulator.ml:377-388)
  becomes per-block cumulative reward columns written at append time.

TPU re-design: capacity-B arrays; "views" are boolean visibility masks;
children lookups are masked scans over the parent matrix; chain walks are
bounded `lax.while_loop`s following parent slot 0 (the precursor). All ops
are O(B) or O(B*P) vector ops that XLA fuses; B is sized from the episode
length (one PoW + at most one structural append per step), so no
compaction is needed within an episode.

Convention: two parties — miner 0 is the attacker, miner 1 the defender
cloud (the collapse performed by the reference gym engine,
simulator/gym/engine.ml:100-107). `vis_a` is the attacker's view mask,
`vis_d` the defender cloud's. A block appended by the attacker starts
vis_a & ~vis_d == withheld; releasing sets vis_d (the simulator's
recursive share of withheld ancestors, simulator.ml:401-419, is
`release_with_ancestors`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

NONE = jnp.int32(-1)
NO_POW = jnp.float32(jnp.inf)  # pow_hash for non-PoW blocks; smaller = better

ATTACKER = 0
DEFENDER = 1


@struct.dataclass
class Dag:
    # Parent slots as P separate (B,) int32 planes (NONE-padded):
    # parents[p][b] is block b's p-th parent.  NOT an array — three TPU
    # layout pathologies killed the matrix forms (round-4 device
    # profiles at 16k envs): a (B, P) matrix pads P up to 128 lanes
    # (~14x the logical bytes); a (P, B) matrix fixes padding but its
    # vmapped column write (dynamic-update-slice) wants a batch-minor
    # layout while the row reads want batch-major, so XLA keeps TWO
    # copies alive with ~7 ms transposing copies per scan step.  As
    # separate planes, writes are the same in-place row scatters as
    # every other per-slot field and reads are free static picks.
    parents: tuple
    # free-form per-slot float32 protocol fields written at append time
    # (bk: auxf = leader-vote hash; tailstorm: auxf/auxg = the summary's
    # own attacker/defender coinbase).  Exist so protocols can cache a
    # derived scalar instead of re-gathering it through parent
    # indirections every step (bk's leader-hash re-gather was
    # 102 ms/step at 16k envs).
    auxf: jnp.ndarray  # (B,) float32
    auxg: jnp.ndarray  # (B,) float32
    # free-form per-slot int32 protocol pointer written at append time
    # (tailstorm: the summary this summary extends; sdag: a block's
    # previous block).  Caches one level of parent indirection so chain
    # walks cost one gather per level instead of three (parent0 ->
    # kind -> signer).
    aux2: jnp.ndarray  # (B,) int32, NONE when unused
    kind: jnp.ndarray  # (B,) int32, protocol block-type tag
    height: jnp.ndarray  # (B,) int32
    aux: jnp.ndarray  # (B,) int32, protocol field (vote id, depth, ...)
    pow_hash: jnp.ndarray  # (B,) float32, NO_POW if not attached via PoW
    signer: jnp.ndarray  # (B,) int32, NONE if unsigned
    miner: jnp.ndarray  # (B,) int32, ATTACKER / DEFENDER / NONE (roots)
    vis_a: jnp.ndarray  # (B,) bool, attacker sees it
    vis_d: jnp.ndarray  # (B,) bool, defender cloud sees it
    vis_d_since: jnp.ndarray  # (B,) float32, when the defenders saw it
    born_at: jnp.ndarray  # (B,) float32, append time
    cum_atk: jnp.ndarray  # (B,) float32, attacker reward along precursors
    cum_def: jnp.ndarray  # (B,) float32
    cum_prog: jnp.ndarray  # (B,) float32, progress at this block
    n: jnp.ndarray  # () int32, number of blocks
    overflow: jnp.ndarray  # () bool, capacity exceeded (episode invalid)

    @property
    def parent0(self) -> jnp.ndarray:
        """(B,) precursor plane (parent slot 0) — the one the chain
        walks and slot-0 children scans read."""
        return self.parents[0]

    @property
    def capacity(self) -> int:
        return self.parents[0].shape[-1]

    @property
    def max_parents(self) -> int:
        return len(self.parents)

    def slots(self):
        """(B,) iota over block slots."""
        return jnp.arange(self.capacity, dtype=jnp.int32)

    def exists(self):
        return self.slots() < self.n


def empty(capacity: int, max_parents: int) -> Dag:
    B, P = capacity, max_parents
    f = lambda fill, dt: jnp.full((B,), fill, dt)
    return Dag(
        parents=tuple(jnp.full((B,), NONE, jnp.int32) for _ in range(P)),
        auxf=f(0.0, jnp.float32),
        auxg=f(0.0, jnp.float32),
        aux2=f(NONE, jnp.int32),
        kind=f(0, jnp.int32),
        height=f(0, jnp.int32),
        aux=f(0, jnp.int32),
        pow_hash=f(NO_POW, jnp.float32),
        signer=f(NONE, jnp.int32),
        miner=f(NONE, jnp.int32),
        vis_a=f(False, jnp.bool_),
        vis_d=f(False, jnp.bool_),
        vis_d_since=f(0.0, jnp.float32),
        born_at=f(0.0, jnp.float32),
        cum_atk=f(0.0, jnp.float32),
        cum_def=f(0.0, jnp.float32),
        cum_prog=f(0.0, jnp.float32),
        n=jnp.int32(0),
        overflow=jnp.bool_(False),
    )


def append(dag: Dag, parents, *, kind=0, height=0, aux=0, pow_hash=NO_POW,
           signer=NONE, miner=NONE, vis_a=True, vis_d=True, time=0.0,
           reward_atk=0.0, reward_def=0.0, progress=None, auxf=0.0,
           auxg=0.0, aux2=NONE):
    """Append one block; returns (dag, index). `parents` is a (P,) int32
    row (NONE-padded); parent slot 0 is the precursor along which
    cumulative rewards accumulate (simulator.ml:377-388). `progress`
    defaults to cum_prog[precursor] + 1 when None-like is passed
    explicitly; pass the absolute progress value otherwise."""
    dag, idx = append_if(
        dag, jnp.bool_(True), parents, kind=kind, height=height, aux=aux,
        pow_hash=pow_hash, signer=signer, miner=miner, vis_a=vis_a,
        vis_d=vis_d, time=time, reward_atk=reward_atk,
        reward_def=reward_def, progress=progress, auxf=auxf, auxg=auxg,
        aux2=aux2)
    return dag, idx


def append_if(dag: Dag, cond, parents, *, kind=0, height=0, aux=0,
              pow_hash=NO_POW, signer=NONE, miner=NONE, vis_a=True,
              vis_d=True, time=0.0, reward_atk=0.0, reward_def=0.0,
              progress=None, auxf=0.0, auxg=0.0, aux2=NONE):
    """`append` gated by traced bool `cond`; returns (dag, idx_or_NONE).

    Replaces the append-then-rollback pattern
    (``dag2, i = append(...); tree.map(where(cond), dag2, dag)``): the
    full-state select costs two whole-DAG copies per call and, inside a
    scan, defeats in-place carry updates.  Every field is written with a
    row-level conditional scatter (see put below) on its own (B,) plane
    — with parents stored as per-slot planes these are the same cheap
    in-place updates as every other per-slot field.  (A (P, B) parents
    MATRIX must not come back here: its vmapped column write wants a
    batch-minor layout and XLA then keeps a second transposed copy of
    the matrix alive across the scan, ~7 ms per step at 16k envs —
    round-4 device profile.)"""
    idx = jnp.minimum(dag.n, dag.capacity - 1)
    overflow = dag.overflow | (cond & (dag.n >= dag.capacity))
    p0 = parents[0]
    has_p0 = p0 >= 0
    base = jnp.where(has_p0, p0, 0)
    cum_atk = jnp.where(has_p0, dag.cum_atk[base], 0.0) + reward_atk
    cum_def = jnp.where(has_p0, dag.cum_def[base], 0.0) + reward_def
    if progress is None:
        cum_prog = jnp.where(has_p0, dag.cum_prog[base], 0.0) + 1.0
    else:
        cum_prog = jnp.asarray(progress, jnp.float32)

    def put(arr, value):
        # row-level conditional scatter: .at[idx].set is an in-place
        # carry update inside scans (a one-hot where() here forces a
        # full read+write of every array per step — measured 1.3x
        # slower end-to-end on chip; the scatter wins despite TPU's
        # dislike of dynamic indices)
        value = jnp.asarray(value, arr.dtype)
        return arr.at[idx].set(jnp.where(cond, value, arr[idx]))

    dag = dag.replace(
        parents=tuple(put(plane, parents[p])
                      for p, plane in enumerate(dag.parents)),
        auxf=put(dag.auxf, auxf),
        auxg=put(dag.auxg, auxg),
        aux2=put(dag.aux2, aux2),
        kind=put(dag.kind, kind),
        height=put(dag.height, height),
        aux=put(dag.aux, aux),
        pow_hash=put(dag.pow_hash, pow_hash),
        signer=put(dag.signer, signer),
        miner=put(dag.miner, miner),
        vis_a=put(dag.vis_a, vis_a),
        vis_d=put(dag.vis_d, vis_d),
        vis_d_since=put(dag.vis_d_since,
                        jnp.where(jnp.asarray(vis_d),
                                  jnp.asarray(time, jnp.float32),
                                  jnp.float32(jnp.inf))),
        born_at=put(dag.born_at, time),
        cum_atk=put(dag.cum_atk, cum_atk),
        cum_def=put(dag.cum_def, cum_def),
        cum_prog=put(dag.cum_prog, cum_prog),
        n=jnp.minimum(dag.n + cond.astype(jnp.int32), dag.capacity),
        overflow=overflow,
    )
    return dag, jnp.where(cond, idx, NONE)


def select_vis(cond, released: Dag, dag: Dag) -> Dag:
    """where(cond, released, dag) specialized to what release() can
    change: the two defender-visibility arrays.  A full-pytree
    tree.map select copies every DAG field (parents included) twice per
    call; release never touches anything else, so selecting vis_d /
    vis_d_since alone keeps the scan carry update in place."""
    return dag.replace(
        vis_d=jnp.where(cond, released.vis_d, dag.vis_d),
        vis_d_since=jnp.where(cond, released.vis_d_since,
                              dag.vis_d_since),
    )


def children_mask(dag: Dag, v) -> jnp.ndarray:
    """(B,) mask of blocks having v among their parents (dag.ml:44)."""
    hit = dag.parents[0] == v
    for plane in dag.parents[1:]:
        hit = hit | (plane == v)
    return dag.exists() & hit


def children0_mask(dag: Dag, v) -> jnp.ndarray:
    """(B,) mask of blocks whose PRECURSOR (parent slot 0) is v.  For
    protocols where every attachment of interest rides slot 0 — bk votes
    and proposals both precede via slot 0 — this replaces a padded
    (B, P)-matrix scan with a flat (B,) compare (~10x cheaper on TPU,
    see Dag.parent0)."""
    return dag.exists() & (dag.parent0 == v)


def release(dag: Dag, mask, time) -> Dag:
    """Make the masked withheld blocks visible to the defender cloud."""
    newly = mask & ~dag.vis_d & dag.exists()
    return dag.replace(
        vis_d=dag.vis_d | newly,
        vis_d_since=jnp.where(newly, time, dag.vis_d_since),
    )


def parents_hit(dag: Dag, mask) -> jnp.ndarray:
    """(B,) mask of blocks that appear in the parent row of any block in
    `mask` — the one-hop "scatter child hits onto parent slots" step
    shared by the ancestor fixpoints below."""
    B = dag.capacity
    hits = jnp.zeros((B,), jnp.bool_)
    for p in range(dag.max_parents):
        col = dag.parents[p]
        hit = mask & (col >= 0)
        hits = hits | (
            jnp.zeros((B,), jnp.bool_).at[jnp.clip(col, 0)].max(hit))
    return hits


def parents_hit_dense(dag: Dag, mask) -> jnp.ndarray:
    """parents_hit via a dense (B, B) compare per plane instead of a
    batched scatter.  On TPU a vmapped scatter with a (B,)-wide index
    vector serializes (~9 ms per plane at 4096 envs x B=264 — round-4
    device profile); the dense compare is plain elementwise work and an
    any-reduce, ~10x cheaper for small-capacity DAGs.  O(B^2) per plane:
    use only where B^2 x P stays modest (ethereum's release closure at
    B=264, P=3); the scatter form wins for big-B x many-plane DAGs."""
    slots = jnp.arange(dag.capacity, dtype=jnp.int32)
    hits = jnp.zeros((dag.capacity,), jnp.bool_)
    for p in range(dag.max_parents):
        col = dag.parents[p]
        m = mask & (col >= 0)
        hits = hits | (m[:, None] & (col[:, None] == slots[None, :])
                       ).any(axis=0)
    return hits


def ancestors_mask(dag: Dag, v) -> jnp.ndarray:
    """(B,) mask of v and all its ancestors (fixpoint BFS over the parent
    matrix; the analog of dagtools.ml:73-100 iterate_ancestors). The loop
    runs until the mask stops growing, <= DAG height iterations on any
    DAG produced by `append` (parents always point at earlier slots)."""
    B = dag.capacity
    seed = jnp.zeros((B,), jnp.bool_).at[jnp.maximum(v, 0)].set(v >= 0)

    def body(state):
        mask, _ = state
        new = mask | parents_hit(dag, mask)
        return new, (new != mask).any()

    def cond(state):
        return state[1]

    mask, _ = jax.lax.while_loop(cond, body, (seed, v >= 0))
    return mask


def release_with_ancestors(dag: Dag, v, time) -> Dag:
    """Share v and (recursively) its withheld ancestors — the simulator's
    recursive share (simulator.ml:401-419)."""
    return release(dag, ancestors_mask(dag, v), time)


def release_chain(dag: Dag, tip, time) -> Dag:
    """Release `tip`, its full parent row, and walk down the precursor
    chain until a block that was defender-visible BEFORE this call.
    Equivalent to `release_with_ancestors` whenever non-precursor parents
    (votes) sit directly on precursor-chain blocks — true for all
    chain+vote protocols here — but costs O(newly released) instead of a
    full-DAG ancestor fixpoint per call.

    The stop test uses each next tip's visibility as read before its row
    was released: releasing block t's parent row marks row[0] visible, so
    re-reading vis_d after the release would terminate the walk after one
    iteration and under-release chains withheld deeper than 2.

    The loop carries ONLY the two visibility arrays release() can
    change; everything else (parents rows, existence) is read from the
    enclosing dag.  Carrying the whole Dag re-materializes the padded
    parents matrix every iteration — the dominant cost of withholding
    steps at large batch on TPU."""
    B = dag.capacity
    exists = dag.exists()
    slots = jnp.arange(B, dtype=jnp.int32)

    def cond(carry):
        _, _, t, t_vis = carry
        return (t >= 0) & ~t_vis

    def body(carry):
        vis_d, vis_d_since, t, _ = carry
        nxt = dag.parent0[t]
        # pre-release visibility of the next tip: must be read before
        # release() marks the whole row (nxt included) visible
        nxt_vis = vis_d[jnp.maximum(nxt, 0)]
        # release t + its parent row.  The row is read one PARENT SLOT
        # at a time — dag.parents[p] is a free static slice of the
        # (P, B) matrix and [t] a scalar gather — because a batched
        # column gather (parents[:, t]) makes XLA keep a second,
        # batch-minor copy of the whole matrix alive across the scan
        # (two ~7 ms transposing copies per step at 16k envs).
        mask = slots == t
        for p in range(dag.max_parents):
            v = dag.parents[p][t]
            mask = mask | ((slots == v) & (v >= 0))
        newly = mask & ~vis_d & exists
        vis_d = vis_d | newly
        vis_d_since = jnp.where(newly, time, vis_d_since)
        return vis_d, vis_d_since, nxt, nxt_vis

    tip_vis = dag.vis_d[jnp.maximum(tip, 0)]
    vis_d, vis_d_since, _, _ = jax.lax.while_loop(
        cond, body, (dag.vis_d, dag.vis_d_since, tip, tip_vis))
    return dag.replace(vis_d=vis_d, vis_d_since=vis_d_since)


def release_closure(dag: Dag, tip, time) -> Dag:
    """`release_chain` plus a visibility-closure fixpoint: any parent
    referenced by a defender-visible block becomes visible too.

    Matches the reference's fully recursive share (simulator.ml:401-419)
    even when a released non-precursor parent carries its OWN withheld
    parent row — e.g. an orphaned ethereum uncle U (made while withheld,
    including withheld uncle W) later re-included by a new chain block:
    the chain walk releases U via the row but never walks U, so W needs
    the closure pass.  The loop exits after a single check in the common
    case (uncle nesting is rare), so per-step cost stays O(newly
    released) instead of release_with_ancestors' height-deep fixpoint."""
    dag = release_chain(dag, tip, time)
    exists = dag.exists()

    def missing(vis_d):
        # parents referenced by visible blocks but not yet visible
        ref = parents_hit_dense(dag, exists & vis_d)
        return ref & ~vis_d & exists

    def body(carry):
        vis_d, vis_d_since, m = carry
        newly = m & ~vis_d & exists
        vis_d = vis_d | newly
        vis_d_since = jnp.where(newly, time, vis_d_since)
        return vis_d, vis_d_since, missing(vis_d)

    # the fixpoint, like the chain walk above, carries only the two
    # visibility arrays (parents_hit reads the matrix from the closure)
    vis_d, vis_d_since, _ = jax.lax.while_loop(
        lambda c: c[2].any(), body,
        (dag.vis_d, dag.vis_d_since, missing(dag.vis_d)))
    return dag.replace(vis_d=vis_d, vis_d_since=vis_d_since)


def walk_back(dag: Dag, tip, stop_fn):
    """Follow parent slot 0 from `tip` while not stop_fn(dag, idx).
    Terminates at the root (parent -1) at the latest — <= DAG height
    iterations; the chain-walk primitive behind `last_block`, height
    targeting, and common ancestors."""

    def cond(i):
        return (i >= 0) & ~stop_fn(dag, i)

    def body(i):
        return dag.parent0[i]

    return jax.lax.while_loop(cond, body, tip)


def block_at_height(dag: Dag, tip, target_height, is_block_fn=None):
    """Walk the precursor chain from `tip` down to the first block with
    height <= target_height (nakamoto_ssz.ml:238-247, bk_ssz.ml:283-291)."""
    def stop(dag, i):
        ok = dag.height[i] <= target_height
        if is_block_fn is not None:
            ok = ok & is_block_fn(dag, i)
        return ok

    return walk_back(dag, tip, stop)


def common_ancestor_by_height(dag: Dag, a, b):
    """Common ancestor of two chain tips linked via parent slot 0, using
    heights to synchronize the walk (dagtools.ml:102-121, re-shaped as a
    height-indexed two-pointer loop)."""

    def cond(state):
        x, y = state
        return (x != y) & (x >= 0) & (y >= 0)

    def body(state):
        x, y = state
        hx, hy = dag.height[x], dag.height[y]
        # step the higher one down; on ties step both
        step_x = hx >= hy
        step_y = hy >= hx
        return (jnp.where(step_x, dag.parent0[x], x),
                jnp.where(step_y, dag.parent0[y], y))

    x, y = jax.lax.while_loop(cond, body, (a, b))
    return x


def mask_of(idx, valid, B: int) -> jnp.ndarray:
    """(B,) bool mask with idx[i] set where valid[i] — the scatter-free
    form of ``zeros.at[idx].max(valid)``.  On TPU a vmapped scatter
    with a (k,)-index vector costs ~0.3 ms/step at 4096 envs (round-4
    device profile); the (k, B) one-hot compare + any-reduce is plain
    elementwise work."""
    slots = jnp.arange(B, dtype=jnp.int32)
    return ((idx[:, None] == slots[None, :]) & valid[:, None]).any(axis=0)


def top_k_by(score, mask, k: int, largest: bool = False):
    """Indices of the k best masked entries by score (ascending by
    default — used for smallest-hash vote selection). Returns (idx, valid)
    where valid marks real entries (fewer than k may match).

    Small k extracts iteratively (k argmin/argmax passes) instead of
    lax.top_k: on TPU top_k lowers to a full sort of the capacity-B
    lane, ~3 ms per call at 16k envs x 520 slots (round-4 device
    profile) — the extraction loop is ~5x cheaper and keeps top_k's
    tie-by-lowest-index order (argmin/argmax return the first hit)."""
    neutral = -jnp.inf if largest else jnp.inf
    s = jnp.where(mask, score, neutral).astype(jnp.float32)
    if k <= 16:
        slots = jnp.arange(s.shape[-1], dtype=jnp.int32)
        pick = jnp.argmax if largest else jnp.argmin
        best = jnp.max if largest else jnp.min
        idxs, valids = [], []
        for _ in range(k):
            j = pick(s).astype(jnp.int32)
            v = best(s)
            idxs.append(j)
            valids.append(v != neutral)
            s = jnp.where(slots == j, neutral, s)
        return jnp.stack(idxs), jnp.stack(valids)
    if largest:
        vals, idx = jax.lax.top_k(s, k)
        valid = vals > -jnp.inf
    else:
        vals, idx = jax.lax.top_k(-s, k)
        valid = vals > -jnp.inf
    return idx, valid
