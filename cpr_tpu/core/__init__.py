"""Core DAG substrate: fixed-capacity structure-of-arrays block DAGs.

Reference counterpart: simulator/lib/dag.ml (append-only mutable DAG with
per-node visibility views) and the per-block metadata of the simulator
(simulator/lib/simulator.ml:2-10). Re-designed as a PyTree of arrays so
protocols become pure functions and envs stay jittable.
"""

from cpr_tpu.core.dag import Dag  # noqa: F401
