"""Experiment drivers: sweeps, TSV output, break-even search.

Reference counterpart: experiments/ — the csv_runner task farm
(simulate/csv_runner.ml:61-143), honest_net (simulate/honest_net.ml),
withholding (simulate/withholding.ml), and the rl-eval break-even search
(rl-eval/break_even.py:13-50).

TPU re-design: where the reference forks a process per simulation task
(Parany), the JAX sweeps batch the whole parameter grid into one vmap'd
kernel; the multi-node honest-network studies run on the C++ oracle
engine (cpr_tpu.native), which plays the role of the reference's
compiled simulator.
"""

from cpr_tpu.experiments.sweep import run_task, write_tsv
from cpr_tpu.experiments.honest_net import honest_net_rows
from cpr_tpu.experiments.withholding import withholding_rows
from cpr_tpu.experiments.break_even import break_even
from cpr_tpu.experiments.measure_rtdp import measure_rtdp_rows
from cpr_tpu.experiments.analysis import (efficiency_pivot, expand_rows,
                                          gini)
from cpr_tpu.experiments.rl_eval import aggregate, episode_rows

__all__ = ["write_tsv", "run_task", "honest_net_rows", "withholding_rows",
           "break_even", "measure_rtdp_rows", "expand_rows",
           "efficiency_pivot", "gini", "episode_rows", "aggregate"]
