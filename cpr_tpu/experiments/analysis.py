"""Post-processing for honest-net sweep output.

Reference counterpart: experiments/simulate/honest_net.py:1-77 — the
pandas consumption layer over the TSV: per-row expansion of the
"|"-joined per-node arrays into gini coefficients, weakest/strongest
node shares, per-node efficiency (reward share / activation share), and
the two gini deltas, followed by a (block_interval x protocol) pivot.
Here `expand_rows` works on the dict rows `honest_net_rows` produces
directly (no file round-trip needed) and `efficiency_pivot` reproduces
the pivot as a nested dict so callers don't need pandas; `to_dataframe`
hands the expanded rows to pandas for anyone who wants the notebook
workflow.
"""

from __future__ import annotations

import numpy as np


def gini(x) -> float:
    """Gini coefficient via relative mean absolute difference (the
    reference uses the same O(n^2) formula, honest_net.py:12-25)."""
    x = np.asarray(x, dtype=float)
    mu = x.mean()
    if mu == 0.0:
        return 0.0
    mad = np.abs(np.subtract.outer(x, x)).mean()
    return 0.5 * mad / mu

def parse_array(s) -> np.ndarray:
    """Decode a "|"-joined per-node array cell (honest_net.py:28-32)."""
    if s is None or s == "":
        return np.array([float("nan")])
    if isinstance(s, str):
        return np.fromstring(s, dtype=float, sep="|")
    return np.asarray(s, dtype=float)


def expand_row(row: dict) -> dict:
    """honest_net.py:35-57's `expand`: weakest/strongest/gini stats for
    compute, activations, reward, and efficiency, plus gini deltas.
    Error rows (per-task capture) pass through unexpanded."""
    if row.get("error"):
        return {}
    compute = parse_array(row["compute"])
    weakest = int(np.argmin(compute))
    strongest = int(np.argmax(compute))
    d: dict = {}

    def wsg(k, v):
        d[k + "_weakest"] = float(v[weakest])
        d[k + "_strongest"] = float(v[strongest])
        d[k + "_gini"] = float(gini(v))

    def normalized(v):
        """Share vector, or None when the total is zero (e.g. a run too
        short to form any block earns zero reward) — a silent 0/0 would
        spread NaN cells through the TSV and the pivot."""
        s = v.sum()
        return v / s if s > 0 else None

    rcompute = normalized(compute)
    if rcompute is None:
        return {"error": "expand: zero total compute"}
    wsg("compute", rcompute)
    ractivations = normalized(parse_array(row["node_activations"]))
    if ractivations is None:
        return {"error": "expand: zero total activations"}
    wsg("activations", ractivations)
    rreward = normalized(parse_array(row["reward"]))
    if rreward is None:
        return {"error": "expand: zero total reward"}
    wsg("reward", rreward)
    # per-node zero activations make efficiency = reward/0 undefined for
    # that node (short runs); keep the other stats and note the omission
    # rather than spreading inf/nan through the efficiency columns
    if (ractivations > 0).all():
        wsg("efficiency", rreward / ractivations)
    else:
        d["expand_note"] = "efficiency undefined: node with 0 activations"
    d["activations_compute_gini_delta"] = \
        d["activations_gini"] - d["compute_gini"]
    d["reward_activations_gini_delta"] = \
        d["reward_gini"] - d["activations_gini"]
    return d


def expand_rows(rows: list[dict]) -> list[dict]:
    """Join each row with its expansion (honest_net.py:60)."""
    return [{**r, **expand_row(r)} for r in rows]


def efficiency_pivot(rows: list[dict], value: str = "efficiency_weakest",
                     index: str = "activation_delay") -> dict:
    """The reference's closing pivot (honest_net.py:62-69):
    {(protocol, k, scheme): {activation_delay: value}}."""
    out: dict = {}
    for r in rows:
        if r.get("error") or value not in r:
            continue
        col = (r["protocol"], r.get("k", 1),
               r.get("incentive_scheme", "constant"))
        out.setdefault(col, {})[r[index]] = r[value]
    return out


def to_dataframe(rows: list[dict]):
    """Expanded rows as a pandas DataFrame (notebook workflow)."""
    import pandas as pd

    return pd.DataFrame(expand_rows(rows))
