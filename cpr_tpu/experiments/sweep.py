"""TSV sweep output.

Reference counterpart: the csv_runner row collection and `Info.pp_rows`
TSV printer (experiments/simulate/csv_runner.ml:16-29, lib/info.ml:26-60):
rows are typed key-value dicts; the writer unions all keys into one
header and prints row-major TSV, empty cells for missing keys.
"""

from __future__ import annotations

import io
from typing import Callable, Iterable

from cpr_tpu.resilience import atomic_write_text
from cpr_tpu.telemetry import now


def _fmt(v) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def write_tsv(rows: Iterable[dict], path: str | None = None) -> str:
    """Serialize dict rows to TSV (union of keys, first-seen order).
    Writes to `path` when given; returns the TSV text either way."""
    rows = list(rows)
    cols: list[str] = []
    for r in rows:
        for k in r:
            if k not in cols:
                cols.append(k)
    buf = io.StringIO()
    buf.write("\t".join(cols) + "\n")
    for r in rows:
        buf.write("\t".join(_fmt(r.get(c)) for c in cols) + "\n")
    text = buf.getvalue()
    if path is not None:
        atomic_write_text(path, text)
    return text


def run_task(task: Callable[[], list[dict] | dict], ident: dict) -> list[dict]:
    """Run one sweep task, capturing failures as rows instead of raising.

    The reference's task farm records a failing simulation's error in its
    TSV row and carries on with the rest of the sweep
    (experiments/simulate/csv_runner.ml:83-102); one bad grid point must
    not kill a 19-config run.  `ident` carries the identifying columns
    (protocol, alpha, ...) for the error row; successful tasks return
    their row(s) untouched.
    """
    t0 = now()
    try:
        out = task()
        return out if isinstance(out, list) else [out]
    except KeyboardInterrupt:
        raise
    except Exception as e:  # noqa: BLE001 — sweep must degrade per-task
        return [{**ident,
                 "error": f"{type(e).__name__}: {e}",
                 # machine-readable class so downstream tooling can
                 # filter error rows without parsing the message; a
                 # task can attach a more specific slug by setting a
                 # `reason` attribute on the exception it raises
                 "reason": getattr(e, "reason", "runtime-error"),
                 "machine_duration_s": now() - t0}]
