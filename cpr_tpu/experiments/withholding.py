"""Withholding-attack sweeps as batched TPU kernels.

Reference counterpart: experiments/simulate/withholding.ml:4-99 — fixed
attack policies evaluated over alpha x gamma grids.  The reference runs
one simulation process per grid point (Parany fork farm,
csv_runner.ml:105-131); here the WHOLE grid for one (protocol, policy)
pair is a single vmap'd `episode_stats` kernel: EnvParams is a PyTree of
scalars, so stacking the grid into leading axes and vmapping over
(key, params) turns the sweep into one XLA program per policy.
"""

from __future__ import annotations

import jax
import numpy as np

from cpr_tpu import telemetry
from cpr_tpu.envs.registry import get_sized
from cpr_tpu.experiments.sweep import run_task
from cpr_tpu.params import stack_params

DEFAULT_ALPHAS = (0.1, 0.2, 0.25, 0.33, 0.4, 0.45, 0.5)
DEFAULT_GAMMAS = (0.0, 0.5, 0.75, 0.9)


def _stack_params(grid, max_steps):
    return stack_params([dict(alpha=a, gamma=g, max_steps=max_steps)
                         for a, g in grid])


def withholding_rows(protocol_key: str, policies=None, *,
                     alphas=DEFAULT_ALPHAS, gammas=DEFAULT_GAMMAS,
                     episode_len: int = 256, reps: int = 128,
                     seed: int = 0, env_kwargs=None):
    """One row per (policy, alpha, gamma); all grid points and reps of a
    policy run as one batched kernel."""
    env = get_sized(protocol_key, episode_len, **(env_kwargs or {}))
    if policies is None:
        policies = list(env.policies)
    grid = [(a, g) for a in alphas for g in gammas]
    params = _stack_params(grid, episode_len)
    base_key = jax.random.PRNGKey(seed)

    def one(pol, pi):
        # fold_in per policy: the closure used to capture one shared
        # key grid, so every policy replayed the identical activation
        # streams (the key-reuse class jaxlint flags lexically)
        keys = jax.random.split(jax.random.fold_in(base_key, pi),
                                (len(grid), reps))
        fn = jax.jit(jax.vmap(jax.vmap(
            lambda k, p: env.episode_stats(
                k, p, env.policies[pol], episode_len + 8),
            in_axes=(0, None)), in_axes=(0, 0)))
        with telemetry.current().span(
                "withholding", env_steps=len(grid) * reps * episode_len,
                grid_points=len(grid)) as sp:
            stats = sp.fence(fn(keys, params))
        dt = sp.dur_s
        atk = np.asarray(stats["episode_reward_attacker"]).mean(axis=1)
        dfn = np.asarray(stats["episode_reward_defender"]).mean(axis=1)
        prg = np.asarray(stats["episode_progress"]).mean(axis=1)
        out = []
        for i, (a, g) in enumerate(grid):
            total = atk[i] + dfn[i]
            out.append({
                "protocol": protocol_key,
                "attack": f"{protocol_key}-{pol}",
                "alpha": a,
                "gamma": g,
                "episode_len": episode_len,
                "reps": reps,
                "reward_attacker": float(atk[i]),
                "reward_defender": float(dfn[i]),
                "relative_reward": float(atk[i] / total) if total else 0.0,
                "reward_per_progress":
                    float(atk[i] / prg[i]) if prg[i] else 0.0,
                "machine_duration_s": dt / len(grid),
            })
        return out

    rows = []
    for pi, pol in enumerate(policies):
        rows.extend(run_task(
            lambda p=pol, i=pi: one(p, i),
            {"protocol": protocol_key, "attack": f"{protocol_key}-{pol}"}))
    return rows
