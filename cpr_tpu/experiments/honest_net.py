"""Honest-network sweep on the multi-node oracle engine.

Reference counterpart: experiments/simulate/honest_net.ml:4-49 — honest
10-node cliques, protocols x activation delays, orphan-rate and
efficiency rows into TSV.  The reference farms tasks over processes
(csv_runner.ml:105-131); the oracle is C++ and single tasks are fast, so
a plain loop suffices — rows carry `machine_duration_s` like the
reference's Mtime counter (csv_runner.ml:65,76).
"""

from __future__ import annotations

from cpr_tpu.experiments.sweep import run_task
from cpr_tpu.native import OracleSim
from cpr_tpu.telemetry import now

DEFAULT_PROTOCOLS = (
    ("nakamoto", {}),
    ("ethereum-whitepaper", {}),
    ("ethereum-byzantium", {}),
    ("bk", dict(k=4, scheme="constant")),
    ("bk", dict(k=8, scheme="constant")),
    ("bk", dict(k=8, scheme="block")),
    # tailstorm rows feed the reference report's second pivot
    # (honest_net.py:68-75: reward-activations gini delta)
    ("tailstorm", dict(k=8, scheme="constant")),
    ("tailstorm", dict(k=8, scheme="discount")),
)

DEFAULT_ACTIVATION_DELAYS = (30.0, 60.0, 120.0, 300.0, 600.0)


def honest_net_rows(protocols=DEFAULT_PROTOCOLS,
                    activation_delays=DEFAULT_ACTIVATION_DELAYS,
                    *, n_nodes: int = 10, n_activations: int = 10_000,
                    propagation_delay: float = 1.0, seed: int = 0):
    """One row per (protocol, activation_delay) honest clique run."""
    def one(proto, kw, ad):
        t0 = now()
        s = OracleSim(proto, topology="clique", n_nodes=n_nodes,
                      activation_delay=ad,
                      propagation_delay=propagation_delay,
                      seed=seed, **kw)
        try:
            s.run(n_activations)
            rewards = s.rewards(n_nodes)
            activations = s.activations(n_nodes)
            n_blocks = s.metric("n_blocks")
            on_chain = s.metric("on_chain")
            progress = s.metric("progress")
            return {
                "network": f"honest_clique_{n_nodes}",
                "protocol": proto,
                "k": kw.get("k", 1),
                "incentive_scheme": kw.get("scheme", "constant"),
                "activation_delay": ad,
                "activations": n_activations,
                "sim_time": s.metric("sim_time"),
                "head_height": s.metric("head_height"),
                "head_progress": progress,
                "n_blocks": n_blocks,
                "on_chain": on_chain,
                # the reference battery's definition
                # (cpr_protocols.ml:504-509): PoW not reflected in head
                # progress, over PoW spent.  1 - on_chain/n_blocks would
                # count non-PoW appends (tailstorm summaries, bk
                # proposals) as orphanable and overstate the rate ~40x
                # for the parallel family.
                "orphan_rate":
                    max(0.0, 1.0 - progress / n_activations),
                "reward_total": sum(rewards),
                "reward_min": min(rewards),
                "reward_max": max(rewards),
                # per-node arrays, "|"-joined like the reference TSV
                # (csv_runner.ml:43-48,77-78); honest cliques weight
                # compute uniformly (models.ml honest_clique)
                "compute": "|".join("1" for _ in range(n_nodes)),
                "node_activations": "|".join(str(a) for a in activations),
                "reward": "|".join(f"{r:.6g}" for r in rewards),
                "machine_duration_s": now() - t0,
            }
        finally:
            s.close()

    rows = []
    for proto, kw in protocols:
        for ad in activation_delays:
            rows.extend(run_task(
                lambda p=proto, k=kw, a=ad: one(p, k, a),
                {"network": f"honest_clique_{n_nodes}", "protocol": proto,
                 "k": kw.get("k", 1),
                 "incentive_scheme": kw.get("scheme", "constant"),
                 "activation_delay": ad}))
    return rows
