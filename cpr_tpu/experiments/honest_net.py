"""Honest-network sweep on the multi-node oracle engine.

Reference counterpart: experiments/simulate/honest_net.ml:4-49 — honest
10-node cliques, protocols x activation delays, orphan-rate and
efficiency rows into TSV.  The reference farms tasks over processes
(csv_runner.ml:105-131); the oracle is C++ and single tasks are fast, so
a plain loop suffices — rows carry `machine_duration_s` like the
reference's Mtime counter (csv_runner.ml:65,76).

Two engines produce the same row schema:

- ``engine="oracle"`` (default): one serial C++ oracle run per
  (protocol, activation_delay) grid point.
- ``engine="jax"``: the cpr_tpu.netsim batch engine — all activation
  delays of a protocol execute as vmapped lanes of ONE device program
  (protocols netsim doesn't implement degrade to error rows, exactly
  like an unknown protocol does on the oracle path).

Both paths time their work with telemetry spans and stamp every row
with fields from `run_manifest()` (engine/backend/git_sha) so a TSV
artifact is interpretable without the process that wrote it.
"""

from __future__ import annotations

from cpr_tpu import telemetry
from cpr_tpu.experiments.sweep import run_task
from cpr_tpu.native import OracleSim

DEFAULT_PROTOCOLS = (
    ("nakamoto", {}),
    ("ethereum-whitepaper", {}),
    ("ethereum-byzantium", {}),
    ("bk", dict(k=4, scheme="constant")),
    ("bk", dict(k=8, scheme="constant")),
    ("bk", dict(k=8, scheme="block")),
    # tailstorm rows feed the reference report's second pivot
    # (honest_net.py:68-75: reward-activations gini delta)
    ("tailstorm", dict(k=8, scheme="constant")),
    ("tailstorm", dict(k=8, scheme="discount")),
)

DEFAULT_ACTIVATION_DELAYS = (30.0, 60.0, 120.0, 300.0, 600.0)


def _manifest_fields(tele, engine: str, config: dict) -> dict:
    """Emit a run manifest into the telemetry artifact and return the
    compact per-row provenance columns derived from it."""
    man = tele.manifest(config=config)
    return {
        "engine": engine,
        "backend": man.get("backend", ""),
        "git_sha": man.get("git_sha", "") or "",
    }


def _row(*, n_nodes, proto, kw, ad, n_activations, sim_time,
         head_height, progress, n_blocks, on_chain, rewards,
         activations, duration_s, stamp):
    return {
        "network": f"honest_clique_{n_nodes}",
        "protocol": proto,
        "k": kw.get("k", 1),
        "incentive_scheme": kw.get("scheme", "constant"),
        "activation_delay": ad,
        "activations": n_activations,
        "sim_time": sim_time,
        "head_height": head_height,
        "head_progress": progress,
        "n_blocks": n_blocks,
        "on_chain": on_chain,
        # the reference battery's definition
        # (cpr_protocols.ml:504-509): PoW not reflected in head
        # progress, over PoW spent.  1 - on_chain/n_blocks would
        # count non-PoW appends (tailstorm summaries, bk
        # proposals) as orphanable and overstate the rate ~40x
        # for the parallel family.
        "orphan_rate": max(0.0, 1.0 - progress / n_activations),
        "reward_total": sum(rewards),
        "reward_min": min(rewards),
        "reward_max": max(rewards),
        # per-node arrays, "|"-joined like the reference TSV
        # (csv_runner.ml:43-48,77-78); honest cliques weight
        # compute uniformly (models.ml honest_clique)
        "compute": "|".join("1" for _ in range(n_nodes)),
        "node_activations": "|".join(str(a) for a in activations),
        "reward": "|".join(f"{r:.6g}" for r in rewards),
        "machine_duration_s": duration_s,
        **stamp,
    }


def _oracle_rows(protocols, activation_delays, *, n_nodes,
                 n_activations, propagation_delay, seed, tele, stamp):
    def one(proto, kw, ad):
        with tele.span("honest_net:oracle",
                       activations=n_activations) as sp:
            s = OracleSim(proto, topology="clique", n_nodes=n_nodes,
                          activation_delay=ad,
                          propagation_delay=propagation_delay,
                          seed=seed, **kw)
            try:
                s.run(n_activations)
                rewards = s.rewards(n_nodes)
                activations = s.activations(n_nodes)
                metrics = {name: s.metric(name) for name in (
                    "sim_time", "head_height", "n_blocks", "on_chain",
                    "progress")}
            finally:
                s.close()
        return _row(
            n_nodes=n_nodes, proto=proto, kw=kw, ad=ad,
            n_activations=n_activations,
            sim_time=metrics["sim_time"],
            head_height=metrics["head_height"],
            progress=metrics["progress"],
            n_blocks=metrics["n_blocks"],
            on_chain=metrics["on_chain"],
            rewards=rewards, activations=activations,
            duration_s=sp.dur_s, stamp=stamp)

    rows = []
    for proto, kw in protocols:
        for ad in activation_delays:
            rows.extend(run_task(
                lambda p=proto, k=kw, a=ad: one(p, k, a),
                {"network": f"honest_clique_{n_nodes}", "protocol": proto,
                 "k": kw.get("k", 1),
                 "incentive_scheme": kw.get("scheme", "constant"),
                 "activation_delay": ad, **stamp}))
    return rows


def _netsim_rows(protocols, activation_delays, *, n_nodes,
                 n_activations, propagation_delay, seed, tele, stamp):
    """One vmapped netsim program per protocol config: each activation
    delay is a lane, so the whole column of the sweep grid runs as a
    single device call."""
    from cpr_tpu import netsim
    from cpr_tpu.network import symmetric_clique

    delays = [float(a) for a in activation_delays]
    net = symmetric_clique(n_nodes, activation_delay=delays[0],
                          propagation_delay=propagation_delay)

    def batch(proto, kw):
        k = kw.get("k", 1)
        scheme = kw.get("scheme", "constant")
        if not netsim.supports(proto, k, scheme):
            err = ValueError(
                f"netsim supports protocols {netsim.SUPPORTED_PROTOCOLS}"
                f", not '{proto}' (k={k}, scheme='{scheme}')")
            err.reason = "unsupported-protocol"
            raise err
        eng = netsim.Engine(net, protocol=proto, k=k, scheme=scheme,
                            activations=n_activations)
        with tele.span("honest_net:netsim", lanes=len(delays),
                       activations=len(delays) * n_activations) as sp:
            out = eng.run([seed] * len(delays), delays)
        # amortized per-lane share of the one batched device call
        share = sp.dur_s / max(len(delays), 1)
        rows = []
        for i, ad in enumerate(delays):
            rewards = [float(r) for r in out["reward"][i]]
            activations = [int(a) for a in out["node_act"][i]]
            rows.append(_row(
                n_nodes=n_nodes, proto=proto, kw=kw, ad=ad,
                n_activations=n_activations,
                sim_time=float(out["sim_time"][i]),
                head_height=int(out["head_height"][i]),
                progress=float(out["progress"][i]),
                n_blocks=int(out["n_blocks"][i]),
                on_chain=float(out["on_chain"][i]),
                rewards=rewards, activations=activations,
                duration_s=share, stamp=stamp))
        return rows

    rows = []
    for proto, kw in protocols:
        rows.extend(run_task(
            lambda p=proto, k=kw: batch(p, k),
            {"network": f"honest_clique_{n_nodes}", "protocol": proto,
             "k": kw.get("k", 1),
             "incentive_scheme": kw.get("scheme", "constant"), **stamp}))
    return rows


def honest_net_rows(protocols=DEFAULT_PROTOCOLS,
                    activation_delays=DEFAULT_ACTIVATION_DELAYS,
                    *, n_nodes: int = 10, n_activations: int = 10_000,
                    propagation_delay: float = 1.0, seed: int = 0,
                    engine: str = "oracle"):
    """One row per (protocol, activation_delay) honest clique run."""
    if engine not in ("oracle", "jax"):
        raise ValueError(f"engine must be 'oracle' or 'jax', not "
                         f"'{engine}'")
    tele = telemetry.current()
    stamp = _manifest_fields(tele, engine, dict(
        sweep="honest_net", engine=engine, n_nodes=n_nodes,
        n_activations=n_activations, seed=seed))
    impl = _netsim_rows if engine == "jax" else _oracle_rows
    with tele.span("honest_net:sweep", tasks=len(protocols)
                   * len(activation_delays)):
        return impl(protocols, activation_delays, n_nodes=n_nodes,
                    n_activations=n_activations,
                    propagation_delay=propagation_delay, seed=seed,
                    tele=tele, stamp=stamp)
