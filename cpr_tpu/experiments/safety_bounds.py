"""Safety-bound queue simulations vs analytical bounds.

Reference counterpart: experiments/safety-bounds/ml/ — the QueueSim
micro discrete-event engine (QueueSim.ml), the "rigged" longest-chain
safety model version0 (bounds.ml:7-70, after the GR22AFT paper's model
where the attacker steals every tailgater), and the Guo-Ren AFT'22
analytical latency-security bounds (GR22AFT.ml).

The math here is the published paper's (like the fc16/aft20 MDP models,
it must match the literature); the engine is a ~30-line heap loop.
"""

from __future__ import annotations

import heapq
import math
import random
from dataclasses import dataclass


class QueueSim:
    """Tiny discrete-event loop: handler(schedule, time, event) returns
    None to continue or the outcome to stop (QueueSim.ml)."""

    def __init__(self, init_events, handler):
        self.queue = []
        self.seq = 0
        self.time = 0.0
        self.handler = handler
        for t, e in init_events:
            heapq.heappush(self.queue, (t, self.seq, e))
            self.seq += 1

    def schedule(self, delay, event):
        heapq.heappush(self.queue, (self.time + delay, self.seq, event))
        self.seq += 1

    def run(self):
        while self.queue:
            self.time, _, event = heapq.heappop(self.queue)
            out = self.handler(self.schedule, self.time, event)
            if out is not None:
                return out
        raise RuntimeError("empty queue")


@dataclass(frozen=True)
class GR22Params:
    k: int  # confirmation depth
    delta: float  # message delay bound
    lam: float  # total mining rate
    rho: float  # honest fraction

    @property
    def p(self) -> float:
        """Probability a block is an honest 'lagger' (GR22AFT.ml p)."""
        return self.rho * math.exp(-self.lam * self.delta)


def t1upper(x: GR22Params) -> float:
    """Guo-Ren theorem 1 upper bound on safety violation."""
    p = x.p
    assert p > 0.5, "bound needs honest laggers in the majority"
    return (2.0 + 2.0 * math.sqrt(p / (1.0 - p))) * \
        (4.0 * p * (1.0 - p)) ** x.k


def t1lower(x: GR22Params) -> float:
    return (4.0 * x.rho * (1.0 - x.rho)) ** x.k / math.sqrt(x.k)


def catchup_probability(deficit: int, p: float) -> float:
    """Chance a rigged attacker ever closes a `deficit`-block gap
    (gambler's ruin, GR22AFT.ml t2F1)."""
    q = 1.0 - p
    return (q / p) ** deficit


def rigged_attack(*, k: int, cutoff: int, tau: float, lam: float,
                  alpha: float, delta: float, atk_plus: int = 0,
                  rng: random.Random) -> bool:
    """One episode of the version0 rigged model (bounds.ml:17-70): the
    attacker owns its own blocks AND every honest tailgater (mined
    within delta of the previous block); a target transaction enters the
    defender chain after time tau and commits after k confirmations;
    returns True when the attacker can revert it."""
    state = {"attacker": 0, "defender": 0, "tx": ("pending",)}

    def sample_mining():
        d = rng.expovariate(lam)
        return d, (d <= delta, rng.random() <= alpha)

    def handler(schedule, now, event):
        if state["tx"][0] == "pending":
            state["attacker"] = max(state["attacker"], state["defender"])
        tailgater, by_attacker = event
        if by_attacker or tailgater:
            state["attacker"] += 1
        else:
            state["defender"] += 1
            tx = state["tx"]
            if tx[0] == "pending" and now >= tau:
                state["tx"] = ("included", state["defender"])
            elif tx[0] == "included" and state["defender"] >= tx[1] + k:
                state["tx"] = ("committed",)
        schedule(*sample_mining())
        if state["tx"][0] != "committed":
            return None
        if state["attacker"] >= state["defender"]:
            return True
        deficit = state["defender"] - state["attacker"]
        if deficit > cutoff:
            p = GR22Params(k=k, delta=delta, lam=lam,
                           rho=1.0 - alpha).p
            return rng.random() <= catchup_probability(
                deficit - atk_plus, p)
        return None

    d, e = sample_mining()
    return QueueSim([(d, e)], handler).run()


def violation_rate(*, k: int, alpha: float, lam: float, delta: float,
                   tau: float = 1.0, cutoff: int = 32,
                   episodes: int = 2000, seed: int = 0) -> float:
    rng = random.Random(seed)
    fails = sum(
        rigged_attack(k=k, cutoff=cutoff, tau=tau, lam=lam, alpha=alpha,
                      delta=delta, rng=rng)
        for _ in range(episodes))
    return fails / episodes
