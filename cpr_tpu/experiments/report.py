"""Executable reports: the reference's end-consumption tables as plain
functions + rendered text, no notebook runtime required.

Reference counterparts:
- experiments/simulate/honest_net.py:35-77 — expand the honest-net TSV
  into gini/weakest/strongest stats and print two pivots
  (efficiency_weakest and tailstorm's reward-activations gini delta by
  block interval x (protocol, k, scheme)),
- experiments/rl-eval/rl-results-condensed.ipynb — the policy-vs-alpha
  model table of attacker relative revenue,
- mdp/justfile:1-8's numbered-notebook pipeline, which consumes the
  same artifacts.

Each report returns structured data (dict pivots / row lists) AND a
rendered text table, and optionally writes the expanded TSVs the
reference writes — so `python examples/report_study.py` reproduces the
reference's tables end-to-end from a fresh sweep.
"""

from __future__ import annotations

from cpr_tpu.experiments.analysis import efficiency_pivot, expand_rows
from cpr_tpu.experiments.honest_net import honest_net_rows
from cpr_tpu.experiments.rl_eval import aggregate, episode_rows
from cpr_tpu.experiments.sweep import write_tsv


def render_pivot(pivot: dict, index_name: str, value_name: str) -> str:
    """Nested {col_key: {index: value}} dict -> aligned text table."""
    cols = sorted(pivot.keys(), key=str)
    idx = sorted({i for col in pivot.values() for i in col})
    head = [index_name] + [str(c) for c in cols]
    lines = ["\t".join(head)]
    for i in idx:
        cells = [str(i)]
        for c in cols:
            v = pivot[c].get(i)
            cells.append("-" if v is None else f"{v:.4f}")
        lines.append("\t".join(cells))
    return "\n".join(lines) + f"\n[{value_name}]"


def honest_net_report(rows=None, *, out_tsv=None, **sweep_kwargs):
    """The honest_net.py report end-to-end: sweep (or take rows),
    expand per-node arrays into gini/weakest/strongest stats, build the
    reference's two pivots, optionally write the expanded TSV.

    Returns (expanded_rows, pivots, text) where pivots maps the pivot
    name to the {(protocol, k, scheme): {activation_delay: value}}
    nested dict (honest_net.py:63-75's two print() pivots)."""
    if rows is None:
        rows = honest_net_rows(**sweep_kwargs)
    expanded = expand_rows(rows)
    pivots = {
        "efficiency_weakest": efficiency_pivot(
            expanded, value="efficiency_weakest"),
        "tailstorm_reward_activations_gini_delta": efficiency_pivot(
            [r for r in expanded if "tailstorm" in str(r["protocol"])],
            value="reward_activations_gini_delta"),
    }
    text = "\n\n".join(
        render_pivot(p, "activation_delay", name)
        for name, p in pivots.items() if p)
    if out_tsv:
        write_tsv(expanded, out_tsv)
    return expanded, pivots, text


def train_report(metrics_jsonl: str, *, every: int = 1):
    """Training-run report over the driver's metrics.jsonl (the
    replacement for the reference's live W&B panels,
    experiments/train/ppo.py:296-374): the learning curve as
    (update, step_reward, entropy, pg_loss) rows plus the per-alpha
    eval table of the final eval pass.

    Returns (curve_rows, eval_rows, text)."""
    import json

    curve, evals = [], []
    with open(metrics_jsonl) as f:
        for line in f:
            r = json.loads(line)
            if r.get("update") is None:
                continue  # run-header / schema-drifted rows
            (evals if r.get("eval") is True else curve).append(r)
    curve = curve[::max(every, 1)]
    last_update = max((r.get("update") for r in evals
                       if r.get("update") is not None), default=None)
    final_eval = [r for r in evals if r.get("update") == last_update]
    lines = ["update\tmean_step_reward\tentropy\tpg_loss"]
    for r in curve:
        lines.append(f"{r.get('update', '-')}\t"
                     f"{r.get('mean_step_reward', float('nan')):.5f}\t"
                     f"{r.get('entropy', float('nan')):.3f}\t"
                     f"{r.get('pg_loss', float('nan')):.2e}")
    lines.append("")
    lines.append("final eval (update %s):" % last_update)
    lines.append("alpha\tgamma\trelative_reward")
    for r in sorted(final_eval, key=lambda r: (r["alpha"], r["gamma"])):
        lines.append(f"{r['alpha']}\t{r['gamma']}\t"
                     f"{r['relative_reward']:.4f}")
    return curve, final_eval, "\n".join(lines)


def rl_eval_report(protocol_key: str = "nakamoto", *, out_tsv=None,
                   **eval_kwargs):
    """The rl-results-condensed model table end-to-end: per-episode
    eval rows for every built-in policy over an alpha grid, aggregated
    to mean attacker relative revenue per (policy, alpha, gamma).

    Returns (episode_rows, table_rows, text); table_rows are the
    aggregate() records (policy, alpha, gamma, episodes, relative
    revenue mean/std), the condensed table the reference's rl-eval
    notebooks end on."""
    rows = episode_rows(protocol_key, **eval_kwargs)
    table = aggregate(rows)
    cols = ("protocol", "policy", "kind", "alpha", "gamma", "n",
            "relrew_mean", "relrew_std", "rpp_mean", "orphans_mean")
    lines = ["\t".join(cols)]
    for r in table:
        lines.append("\t".join(
            f"{r[c]:.4f}" if isinstance(r[c], float) else str(r[c])
            for c in cols))
    text = "\n".join(lines)
    if out_tsv:
        write_tsv(table, out_tsv)
    return rows, table, text
