"""RTDP measurement sweep: sampled solver vs exact value iteration.

Reference counterpart: mdp/sprint-2-rtdp/measure-rtdp.py — run RTDP on
a battery of attack models with a step budget, record explored-state
counts and start-value trajectories, and compare against the exact VI
solve of the same (truncated) model.

One row per (model, step budget): explored states, RTDP start value /
progress, exact VI revenue, relative error, wall-times.  Feeds
write_tsv like every other sweep.
"""

from __future__ import annotations

from cpr_tpu.mdp import Compiler, ptmdp
from cpr_tpu.telemetry import now
from cpr_tpu.mdp.models import Aft20BitcoinSM, Fc16BitcoinSM
from cpr_tpu.mdp.rtdp import RTDP


def rtdp_battery(alphas=(0.25, 0.33, 0.4), gamma=0.5, fork_len=12):
    battery = []
    for a in alphas:
        battery.append((f"fc16-{a}", lambda a=a: Fc16BitcoinSM(
            alpha=a, gamma=gamma, maximum_fork_length=fork_len)))
        battery.append((f"aft20-{a}", lambda a=a: Aft20BitcoinSM(
            alpha=a, gamma=gamma, maximum_fork_length=fork_len)))
    return battery


def measure_rtdp_rows(battery=None, *, horizon=30, step_budgets=(50_000,),
                      eps=0.2, eps_honest=0.05, es=0.1, seed=0,
                      stop_delta=1e-6, device_rtdp=True,
                      device_batch=128, device_eps=0.4):
    """For each model: exact jitted-VI revenue once, then one host-RTDP
    run per step budget (continuing the same run between budgets, so
    rows show convergence over the budget schedule), plus — when
    `device_rtdp` — the device solver (TensorMDP.rtdp) warm-started
    from zero at the same per-budget step counts for comparison."""
    rows = []
    if battery is None:
        battery = rtdp_battery()
    for name, factory in battery:
        model = factory()  # stateless: RTDP and exact VI share it
        t0 = now()
        tm = ptmdp(Compiler(model).mdp(), horizon=horizon).tensor()
        vi = tm.value_iteration(stop_delta=stop_delta)
        prog = tm.start_value(vi["vi_progress"])
        exact = float(tm.start_value(vi["vi_value"]) / prog) if prog else 0.0
        vi_s = now() - t0

        solver = RTDP(ptmdp_model(model, horizon), eps=eps,
                      eps_honest=eps_honest, es=es, seed=seed)
        done, rtdp_s = 0, 0.0
        dev_v = dev_p = None
        dev_done, dev_s = 0, 0.0
        for budget in sorted(step_budgets):
            t0 = now()
            solver.run(budget - done)
            rtdp_s += now() - t0  # cumulative, like `steps`
            done = budget
            v, g = solver.start_value_and_progress()
            est = v / g if g else 0.0
            row = {
                "model": name, "steps": budget,
                "n_states": solver.n_states,
                "rtdp_revenue": est, "vi_revenue": exact,
                "abs_error": abs(est - exact),
                "rtdp_s": rtdp_s, "vi_s": vi_s,
            }
            if device_rtdp:
                import jax

                # batched lanes: budget counts total sampled steps
                dev_steps = max(1, (budget - dev_done) // device_batch)
                # fresh stream per continuation segment — reusing the
                # same key would replay the previous segment's draws
                seg_key = jax.random.fold_in(
                    jax.random.PRNGKey(seed), budget)
                r = tm.rtdp(seg_key, steps=dev_steps,
                            batch=device_batch, eps=device_eps,
                            value0=dev_v, progress0=dev_p)
                dev_v, dev_p = r["rtdp_value"], r["rtdp_progress"]
                dev_s += r["rtdp_time"]
                dev_done = budget
                dg = tm.start_value(dev_p)
                dest = tm.start_value(dev_v) / dg if dg else 0.0
                row["device_rtdp_revenue"] = dest
                row["device_rtdp_s"] = dev_s
            rows.append(row)
    return rows


def ptmdp_model(model, horizon):
    """The PTO wrapper as an implicit model (what RTDP samples from)."""
    from cpr_tpu.mdp.implicit import PTOWrapper

    return PTOWrapper(model, horizon=horizon, terminal_state="terminal")
