"""Policy-evaluation study: per-episode rows + aggregation tables.

Reference counterpart: the rl-eval notebook layer
(experiments/rl-eval/eval-policies.ipynb — hard-coded and trained
policies evaluated over (protocol x alpha x gamma) grids into an
`episodes` DataFrame; rl-results-condensed.ipynb — groupby aggregation
to relrew mean/std and reward-per-progress per setting;
find-break-even-points.ipynb — orphans/payoff derivations).

TPU re-design: one jitted kernel per (env, policy) evaluates the whole
(alpha x gamma) grid x reps lanes and returns only the episode-end info
columns; episodes are extracted host-side from the done mask, so the
rows are REAL per-episode observations (the notebooks' episodes.pkl
granularity), not lane means.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from cpr_tpu.envs.registry import get_sized
from cpr_tpu.params import stack_params

_COLS = ("episode_reward_attacker", "episode_reward_defender",
         "episode_progress", "episode_n_activations", "episode_sim_time")


def _collect(env, policy_fn, keys, params, n_steps):
    """Jitted rollout collector: only done flags + episode-end columns
    come back to the host."""

    def one(k, p):
        _, _, _, done, info = env.rollout(k, p, policy_fn, n_steps)
        return {"done": done, **{c: info[c] for c in _COLS}}

    fn = jax.jit(jax.vmap(jax.vmap(one, in_axes=(0, None)),
                          in_axes=(0, 0)))
    return jax.device_get(fn(keys, params))


def episode_rows(protocol_key: str, policies=None, *,
                 alphas=(0.25, 0.33, 0.45), gammas=(0.5,),
                 episode_len: int = 128, reps: int = 32, seed: int = 0,
                 env_kwargs=None, kind: str = "hard-coded",
                 net_params=None, hidden=(64, 64), env=None):
    """One row per completed episode, for either the env's hard-coded
    policies (`kind="hard-coded"`) or a trained ActorCritic checkpoint
    (`kind="trained"`, pass net_params from driver.load_checkpoint and
    policies as the label to record).  Pass `env` to evaluate on the
    exact env a checkpoint was trained with (e.g. driver.build_env's
    AssumptionEnv wrapping, whose +2 observation fields the net's first
    layer expects); protocol_key then only labels the rows."""
    if env is None:
        env = get_sized(protocol_key, episode_len, **(env_kwargs or {}))
    grid = [(a, g) for a in alphas for g in gammas]
    params = stack_params([dict(alpha=a, gamma=g, max_steps=episode_len)
                           for a, g in grid])
    base_key = jax.random.PRNGKey(seed)
    n_steps = episode_len + 8

    if kind == "trained":
        from cpr_tpu.train.ppo import ActorCritic

        net = ActorCritic(env.n_actions, hidden)

        def greedy(obs):
            logits, _ = net.apply(net_params, obs)
            return jnp.argmax(logits, axis=-1)

        policy_map = {str(policies or "trained"): greedy}
    elif kind == "hard-coded":
        if policies is None:
            policies = list(env.policies)
        elif isinstance(policies, str):
            policies = [policies]
        policy_map = {p: env.policies[p] for p in policies}
    else:
        raise ValueError(f"unknown kind '{kind}' "
                         "(expected 'hard-coded' or 'trained')")

    rows = []
    for pi, (pol_name, pol_fn) in enumerate(policy_map.items()):
        # fold_in per policy: every policy used to consume the same key
        # grid, so their episodes replayed identical activation streams
        # and the cross-policy comparison shared all its noise
        keys = jax.random.split(jax.random.fold_in(base_key, pi),
                                (len(grid), reps))
        out = _collect(env, pol_fn, keys, params, n_steps)
        done = np.asarray(out["done"], bool)  # [grid, reps, steps]
        for gi, (a, g) in enumerate(grid):
            mask = done[gi]
            vals = {c: np.asarray(out[c])[gi][mask] for c in _COLS}
            for e in range(mask.sum()):
                atk = float(vals["episode_reward_attacker"][e])
                dfn = float(vals["episode_reward_defender"][e])
                prg = float(vals["episode_progress"][e])
                acts = float(vals["episode_n_activations"][e])
                rows.append({
                    "protocol": protocol_key,
                    "policy": pol_name,
                    "kind": kind,
                    "alpha": a,
                    "gamma": g,
                    "episode_len": episode_len,
                    "episode_relrew":
                        atk / (atk + dfn) if atk + dfn else 0.0,
                    "episode_rpp": atk / prg if prg else 0.0,
                    "episode_progress": prg,
                    "episode_n_activations": acts,
                    # find-break-even-points.ipynb's derived columns
                    "orphans": acts / prg if prg else float("inf"),
                })
    return rows


_SETTING = ("protocol", "policy", "kind", "alpha", "gamma")


def aggregate(rows: list[dict]) -> list[dict]:
    """rl-results-condensed.ipynb's model table: one row per setting
    with episode counts and relrew / rpp / orphans statistics."""
    groups: dict = {}
    for r in rows:
        groups.setdefault(tuple(r[k] for k in _SETTING), []).append(r)
    out = []
    for key, rs in sorted(groups.items()):
        relrew = np.array([r["episode_relrew"] for r in rs])
        rpp = np.array([r["episode_rpp"] for r in rs])
        orph = np.array([r["orphans"] for r in rs])
        out.append({
            **dict(zip(_SETTING, key)),
            "n": len(rs),
            "relrew_mean": float(relrew.mean()),
            "relrew_std": float(relrew.std()),
            "rpp_mean": float(rpp.mean()),
            "orphans_mean": float(orph[np.isfinite(orph)].mean())
            if np.isfinite(orph).any() else float("inf"),
        })
    return out


def to_dataframe(rows: list[dict]):
    """episodes.pkl-style DataFrame for the notebook workflow."""
    import pandas as pd

    return pd.DataFrame(rows)
