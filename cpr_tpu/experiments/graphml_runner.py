"""GraphML-in -> simulate -> GraphML-out pipe + dot visualization.

Reference counterpart: simulator/bin/graphml_runner.ml:4-44 (read a
network GraphML, run the named protocol on it, emit the resulting DAG +
metrics as GraphML) and experiments/simulate/visualize.ml (short sims
rendered to graphviz dot).
"""

from __future__ import annotations

from xml.etree import ElementTree as ET

from cpr_tpu import network as netlib
from cpr_tpu import telemetry
from cpr_tpu import trace
from cpr_tpu.envs.registry import parse_key


def _oracle_args(protocol_key: str):
    """Map a protocol key onto the oracle's (protocol, k, scheme)."""
    if protocol_key == "nakamoto":
        return "nakamoto", 0, ""
    family, kw = parse_key(protocol_key)
    if family == "ethereum":
        return f"ethereum-{kw.get('preset', 'byzantium')}", 0, ""
    return family, kw.get("k", 0), kw.get("incentive_scheme", "")


def run_graphml(xml_in: str, *, protocol: str = "nakamoto",
                activations: int = 1000, seed: int = 0) -> str:
    """The graphml_runner pipe: parse the network, simulate, and return
    GraphML holding the block DAG, the causal trace, and run metrics."""
    net = netlib.of_graphml(xml_in)
    proto, k, scheme = _oracle_args(protocol)
    tele = telemetry.current()
    with tele.span("graphml:simulate", activations=activations) as sp:
        sim = netlib.simulate(net, protocol=proto, k=k, scheme=scheme,
                              activations=activations, seed=seed)
    view = trace.view_of_oracle(sim)
    out = trace.to_graphml(view)
    root = ET.fromstring(out)
    graph = next(el for el in root if el.tag.endswith("graph"))
    man = tele.manifest(config=dict(
        pipe="graphml_runner", protocol=protocol,
        activations=activations, seed=seed))
    for name, value in [
            ("protocol", protocol),
            ("activations", activations),
            ("sim_time", sim.metric("sim_time")),
            ("head_progress", sim.metric("progress")),
            ("machine_duration_s", sp.dur_s),
            ("backend", man.get("backend", "")),
            ("git_sha", man.get("git_sha", "") or "")]:
        el = ET.SubElement(graph, "data", key=f"run_{name}")
        el.text = str(value)
    sim.close()
    return ET.tostring(root, encoding="unicode")


def visualize(protocol: str = "nakamoto", *, activations: int = 20,
              n_nodes: int = 3, activation_delay: float = 10.0,
              propagation_delay: float = 1.0, seed: int = 0) -> str:
    """Short simulation rendered to graphviz dot (visualize.ml analog)."""
    from cpr_tpu.native import OracleSim

    proto, k, scheme = _oracle_args(protocol)
    sim = OracleSim(proto, k=k, scheme=scheme, topology="clique",
                    n_nodes=n_nodes, activation_delay=activation_delay,
                    propagation_delay=propagation_delay, seed=seed)
    sim.run(activations)
    dot = trace.to_dot(trace.view_of_oracle(sim))
    sim.close()
    return dot
