"""Break-even search: the smallest alpha where an attack beats honesty.

Reference counterpart: experiments/rl-eval/break_even.py:13-50 — skopt
Gaussian-process minimization of |revenue(alpha)/alpha - 1| with
joblib.Memory caching.  skopt is unavailable here, and the objective
excess(alpha) = revenue(alpha)/alpha - 1 is monotone increasing for the
withholding policies studied, so a Monte-Carlo bisection finds the root
directly; each evaluation is one vmap'd batched kernel, and results are
memoized on disk keyed by the evaluation parameters.
"""

from __future__ import annotations

import hashlib
import json
import os

import jax
import numpy as np

import cpr_tpu
from cpr_tpu import resilience, telemetry
from cpr_tpu.envs.registry import get_sized
from cpr_tpu.params import make_params

# override with CPR_TPU_CACHE; delete the directory to bust the cache
_CACHE_DIR = os.environ.get(
    "CPR_TPU_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache", "cpr_tpu",
                 "break_even"))


def _cached(key: dict, compute):
    os.makedirs(_CACHE_DIR, exist_ok=True)
    # the package version salts the key so env/policy fixes invalidate
    # cached revenues (bump __version__ when semantics change)
    key = dict(key, _version=cpr_tpu.__version__)
    h = hashlib.sha256(
        json.dumps(key, sort_keys=True).encode()).hexdigest()[:24]
    path = os.path.join(_CACHE_DIR, h + ".json")
    if os.path.exists(path):
        # corruption is a MISS (quarantine + typed `integrity` event +
        # recompute), pre-v19 unsealed entries still read — the
        # solve_grid_cached policy
        from cpr_tpu import integrity
        try:
            data, _ = resilience.sealed_read_json(
                path, kind="break_even_cache", action="regenerated")
            return data["value"]
        except resilience.IntegrityError:
            pass
        except (OSError, KeyError, TypeError):
            integrity.quarantine(path, kind="break_even_cache",
                                 reason="truncated", action="regenerated")
    value = compute()
    # atomic + sealed: a Ctrl-C mid-dump must not leave a torn cache
    # entry that poisons every later read of this grid point
    resilience.sealed_write_json(path, {"key": key, "value": value},
                                 site="cache")
    return value


def revenue(protocol_key: str, policy: str, *, alpha: float, gamma: float,
            episode_len: int = 256, reps: int = 512, seed: int = 0,
            cache: bool = True) -> float:
    """Mean attacker relative revenue of `policy` at (alpha, gamma)."""
    key = dict(protocol=protocol_key, policy=policy, alpha=alpha,
               gamma=gamma, episode_len=episode_len, reps=reps, seed=seed)

    def compute():
        env = get_sized(protocol_key, episode_len)
        params = make_params(alpha=alpha, gamma=gamma,
                             max_steps=episode_len)
        keys = jax.random.split(jax.random.PRNGKey(seed), reps)
        fn = jax.jit(jax.vmap(lambda k: env.episode_stats(
            k, params, env.policies[policy], episode_len + 8)))
        with telemetry.current().span(
                "break_even_revenue",
                env_steps=reps * episode_len) as sp:
            stats = sp.fence(fn(keys))
        a = float(np.asarray(stats["episode_reward_attacker"]).mean())
        d = float(np.asarray(stats["episode_reward_defender"]).mean())
        return a / (a + d) if (a + d) else 0.0

    return _cached(key, compute) if cache else compute()


def break_even(protocol_key: str, policy: str, *, gamma: float,
               support=(0.1, 0.5), tol: float = 0.005,
               episode_len: int = 256, reps: int = 512,
               seed: int = 0) -> float:
    """Bisection root of excess(alpha) = revenue/alpha - 1 over
    `support`; returns the break-even alpha (clipped to the support
    bounds when the policy is never/always profitable there)."""
    lo, hi = support

    def excess(a):
        return revenue(protocol_key, policy, alpha=a, gamma=gamma,
                       episode_len=episode_len, reps=reps, seed=seed) / a - 1.0

    if excess(lo) > 0:
        return lo
    if excess(hi) < 0:
        return hi
    while hi - lo > tol:
        mid = 0.5 * (lo + hi)
        if excess(mid) > 0:
            hi = mid
        else:
            lo = mid
    return 0.5 * (lo + hi)


def exact_revenue_curve(protocol: str, *, gamma: float, cutoff: int,
                        alphas, horizon: int = 100,
                        stop_delta: float = 1e-6, native: bool = False,
                        k: int = 2, mesh=None, full: bool = False):
    """OPTIMAL-attack revenue over `alphas` at fixed gamma from one
    cached grid solve of the exact MDP (cpr_tpu.mdp.solve_grid_cached:
    one parametric compile, one vmapped grid VI, disk-cached by content
    fingerprint).  Where the Monte-Carlo `revenue` scores a FIXED
    policy with sampling noise, this is the value-iteration optimum —
    an upper bound over policies with no estimator variance.

    `full=True` returns the solve-cache provenance alongside the
    curve (the serve break_even endpoints surface it): a dict with
    `revenue`, `alphas`, `cached` (fingerprint-keyed disk-cache hit)
    and the ParamMDP content `fingerprint`."""
    from cpr_tpu.mdp.grid import solve_grid_cached

    out = solve_grid_cached(protocol, cutoff=cutoff, alphas=alphas,
                            gammas=(gamma,), horizon=horizon,
                            stop_delta=stop_delta, native=native, k=k,
                            mesh=mesh)
    rev = [float(r) for r in out["revenue"]]
    if full:
        return dict(revenue=rev, alphas=[float(a) for a in out["alphas"]],
                    cached=bool(out["cached"]),
                    fingerprint=out["fingerprint"])
    return rev


def break_even_exact(protocol: str, *, gamma: float, cutoff: int,
                     support=(0.1, 0.5), grid: int = 17,
                     horizon: int = 100, stop_delta: float = 1e-6,
                     native: bool = False, k: int = 2,
                     mesh=None, full: bool = False):
    """Exact-MDP break-even alpha: the root of excess(alpha) =
    revenue(alpha)/alpha - 1 for the OPTIMAL attack, from one cached
    grid solve over `grid` evenly-spaced alphas in `support` (the
    whole curve costs one compile + one batched solve, so a dense grid
    is cheaper here than bisection is for the Monte-Carlo path).  The
    root is located by sign change and refined by linear interpolation
    between the bracketing grid points; clipped to the support bounds
    when the attack is never/always profitable there (same convention
    as `break_even`).  `full=True` wraps the root with the solve-cache
    provenance (`cached`, `fingerprint`) like exact_revenue_curve."""
    lo, hi = support
    alphas = list(np.linspace(lo, hi, grid))
    out = exact_revenue_curve(protocol, gamma=gamma, cutoff=cutoff,
                              alphas=alphas, horizon=horizon,
                              stop_delta=stop_delta, native=native,
                              k=k, mesh=mesh, full=True)
    rev = out["revenue"]
    excess = [r / a - 1.0 for r, a in zip(rev, alphas)]

    def wrap(alpha):
        if full:
            return dict(alpha=float(alpha), cached=out["cached"],
                        fingerprint=out["fingerprint"])
        return float(alpha)

    if excess[0] > 0:
        return wrap(lo)
    if excess[-1] < 0:
        return wrap(hi)
    for i in range(1, len(alphas)):
        if excess[i] > 0:
            a0, a1 = alphas[i - 1], alphas[i]
            e0, e1 = excess[i - 1], excess[i]
            if e1 == e0:
                return wrap(0.5 * (a0 + a1))
            return wrap(a0 + (a1 - a0) * (0.0 - e0) / (e1 - e0))
    return wrap(hi)
