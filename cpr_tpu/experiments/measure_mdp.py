"""MDP solve-time measurement sweep.

Reference counterpart: mdp/sprint-0-explicit-mdps/measure-ours.py and
measure-multicore.py — compile a battery of attack models, solve each
with value iteration, and record sizes + wall-times (the reference
filters to models under 1M transitions; same default here).

One row per (model, alpha, gamma): state/transition counts, compile and
solve wall-times, optimal revenue.  Feeds write_tsv like every other
sweep.
"""

from __future__ import annotations

from cpr_tpu.mdp import Compiler, ptmdp
from cpr_tpu.telemetry import now
from cpr_tpu.mdp.explicit import MDP
from cpr_tpu.mdp.generic import SingleAgent, get_protocol
from cpr_tpu.mdp.models import Aft20BitcoinSM, Fc16BitcoinSM


def model_battery(alphas=(0.25, 0.33, 0.4), gamma=0.5, *, native=True,
                  generic_cutoff=7):
    """(name, factory) pairs covering the literature + generic models.

    Factories may return an implicit model (compiled through the Python
    BFS) or a ready MDP; with `native=True` the generic entries use the
    C++ compiler, which reaches cutoffs the Python BFS cannot (the
    capstone sweep runs generic_cutoff=8 at ~3.8M transitions)."""
    battery = []
    for a in alphas:
        battery.append((f"fc16-{a}", lambda a=a: Fc16BitcoinSM(
            alpha=a, gamma=gamma, maximum_fork_length=20)))
        battery.append((f"aft20-{a}", lambda a=a: Aft20BitcoinSM(
            alpha=a, gamma=gamma, maximum_fork_length=20)))
        for proto, kw in (("bitcoin", {}), ("ghostdag", {"k": 2})):
            if native:
                def fac(a=a, proto=proto, kw=kw):
                    from cpr_tpu.mdp.generic.native import compile_native
                    return compile_native(
                        proto, k=kw.get("k", 0), alpha=a, gamma=gamma,
                        collect_garbage="simple",
                        dag_size_cutoff=generic_cutoff)
            else:
                def fac(a=a, proto=proto, kw=kw):
                    return SingleAgent(
                        get_protocol(proto, **kw), alpha=a, gamma=gamma,
                        collect_garbage="simple", merge_isomorphic=True,
                        truncate_common_chain=True,
                        dag_size_cutoff=generic_cutoff)
            battery.append((f"generic-{proto}-{a}", fac))
    return battery


def measure_rows(battery=None, *, horizon=100, stop_delta=1e-6,
                 max_transitions=1_000_000, mesh=None):
    """Compile + solve each model; skip those over `max_transitions`
    (measure-ours.py:14-21 filter)."""
    rows = []
    if battery is None:
        battery = model_battery()
    for name, factory in battery:
        t0 = now()
        made = factory()
        table = made if isinstance(made, MDP) else Compiler(made).mdp()
        mdp = ptmdp(table, horizon=horizon)
        compile_s = now() - t0
        row = {"model": name, "n_states": mdp.n_states,
               "n_transitions": mdp.n_transitions,
               "compile_s": compile_s}
        if mdp.n_transitions > max_transitions:
            row["skipped"] = "transition cap"
            rows.append(row)
            continue
        tm = mdp.tensor()
        t0 = now()
        if mesh is not None:
            from cpr_tpu.parallel import sharded_value_iteration
            vi = sharded_value_iteration(tm, mesh, stop_delta=stop_delta)
        else:
            vi = tm.value_iteration(stop_delta=stop_delta)
        row["vi_s"] = now() - t0
        row["vi_iter"] = int(vi["vi_iter"])
        prog = tm.start_value(vi["vi_progress"])
        row["revenue"] = (float(tm.start_value(vi["vi_value"]) / prog)
                          if prog else 0.0)
        rows.append(row)
    return rows


def battery_groups(*, native=True, generic_cutoff=7, mfl=20):
    """The model_battery regrouped by (protocol, cutoff): each group
    shares one transition structure across every (alpha, gamma) point,
    so ONE parametric compile + ONE grid solve covers what the serial
    battery re-compiles and re-solves per point.  Entries are
    (protocol, cutoff, kwargs-for-compile_protocol, serial-name-stem);
    the stems reproduce measure_rows' `model` labels ("fc16-{alpha}",
    "generic-bitcoin-{alpha}", ...)."""
    return [
        ("fc16", mfl, {}, "fc16"),
        ("aft20", mfl, {}, "aft20"),
        ("bitcoin", generic_cutoff, {"native": native},
         "generic-bitcoin"),
        ("ghostdag", generic_cutoff, {"native": native, "k": 2},
         "generic-ghostdag"),
    ]


def measure_rows_grid(groups=None, *, alphas=(0.25, 0.33, 0.4),
                      gamma=0.5, horizon=100, stop_delta=1e-6,
                      max_transitions=1_000_000, mesh=None):
    """Grid-batched twin of measure_rows: per (protocol, cutoff) group,
    one parametric compile + one vmapped/sharded grid solve over every
    alpha (cpr_tpu.mdp.grid), instead of a compile+solve loop per
    point.  Emits the same per-point row schema (`model` matches the
    serial battery's labels; compile_s/vi_s are the group totals
    amortized over its points, with the raw group totals alongside) so
    existing TSV consumers diff cleanly against measure_rows.  The
    per-point fixpoints — and hence revenue — are those of a solo
    chunked solve of the same revalued tensor, bit-for-bit."""
    from cpr_tpu.mdp.grid import (compile_protocol, grid_value_iteration,
                                  param_ptmdp)

    if groups is None:
        groups = battery_groups()
    rows = []
    gammas = (gamma,)
    for protocol, cutoff, kw, stem in groups:
        t0 = now()
        pm = param_ptmdp(compile_protocol(protocol, cutoff=cutoff, **kw),
                         horizon=horizon)
        compile_s = now() - t0
        shared = {"n_states": pm.n_states,
                  "n_transitions": pm.n_transitions}
        if pm.n_transitions > max_transitions:
            rows.extend([dict(model=f"{stem}-{a}", compile_s=compile_s,
                              skipped="transition cap", **shared)
                         for a in alphas])
            continue
        vi = grid_value_iteration(pm, alphas, gammas,
                                  stop_delta=stop_delta, mesh=mesh,
                                  protocol=protocol, cutoff=cutoff)
        n = len(vi["grid_points"])
        for i, (a, _) in enumerate(vi["grid_points"]):
            rows.append(dict(
                model=f"{stem}-{a}", compile_s=compile_s / n,
                vi_s=vi["vi_time"] / n, vi_iter=int(vi["grid_iter"][i]),
                revenue=float(vi["grid_revenue"][i]),
                group_compile_s=compile_s,
                group_vi_s=vi["vi_time"], group_points=n, **shared))
    return rows
