"""The learner half of the always-on loop: PPO over fed experience.

`python -m cpr_tpu.learn.learner` runs a standalone process that

  1. accepts `learn.feed` frames (serve/protocol.py framing) carrying
     consolidated experience batches the serve fleet's sampler lanes
     recorded (learn/buffer.py -> engine.drain_experience ->
     feed.ExperienceFeeder);
  2. pools the per-lane windows and, whenever cfg.n_envs full windows
     are banked, runs one jitted PPO update
     (train/ppo.py make_experience_update — the update phase of the
     trainer, rollout half replaced by the fleet; the decoupled
     sampler/learner shape of arXiv:1803.02811);
  3. publishes serving snapshots every `--publish-every` updates via
     the sealed checkpoint plumbing (driver.export_policy_snapshot:
     msgpack + checksummed meta sidecar), then points an atomic
     `latest.json` at the newest one — the file serve/server.py
     watches to hot-swap without draining.

The snapshot fingerprint is the sha256 of the serialized params —
byte-identical to the sidecar's `payload_sha256` — so the learner's
`publish` events, the server's `swap` events and heartbeats, and the
engine's no-op-swap detection all correlate on one id.

Updates run inline in the feed handler: the learner may stall its own
socket during an update, but the serve tick loop never feels it — the
feeder thread owns the wait and sheds batches drop-oldest.  Every
batch is validated against the learner's fixed window length
(cfg.n_steps), so one compiled update program serves the whole run.

Lifecycle mirrors the serve child: supervisor heartbeat, ready-file
with the bound port, SIGTERM via resilience.preemption_guard -> final
publish + drain, exit 0.
"""

from __future__ import annotations

import asyncio
import hashlib
import os
from collections import deque
from datetime import datetime, timezone

import numpy as np

from cpr_tpu import resilience, telemetry
from cpr_tpu.learn import learn_event
from cpr_tpu.learn.feed import decode_batch
from cpr_tpu.serve import protocol as wire

LATEST = "latest.json"


def params_fingerprint(net_params) -> str:
    """sha256 of the serialized params — identical to the snapshot
    sidecar's `payload_sha256` for the same params, so fingerprints
    compare across the learner, the wire, and the integrity plane."""
    from flax import serialization

    return hashlib.sha256(serialization.to_bytes(net_params)).hexdigest()


# per-lane window fields pooled between updates ([C, ...] each)
_WINDOW_FIELDS = ("obs", "action", "reward", "done", "era", "erd")


class Learner:
    """Pool fed experience windows, update PPO, publish snapshots."""

    def __init__(self, env, cfg, *, protocol: str, publish_dir: str,
                 publish_every: int = 1, seed: int = 0,
                 reward_transform="relative"):
        from cpr_tpu.train.ppo import (make_experience_update,
                                       relative_reward_on_done)

        self.env = env
        self.cfg = cfg
        self.protocol = protocol
        self.publish_dir = publish_dir
        self.publish_every = max(1, int(publish_every))
        rt = relative_reward_on_done if reward_transform == "relative" \
            else reward_transform
        self.net, init_fn, self._update, self._mspec = \
            make_experience_update(env.n_actions, env.observation_length,
                                   cfg, reward_transform=rt)
        import jax

        init_key, self._key = jax.random.split(jax.random.PRNGKey(seed))
        self.ts = init_fn(init_key)
        # per-lane windows awaiting an update: each entry is a dict of
        # [n_steps, ...] arrays plus its bootstrap last_obs [obs_dim]
        self.pool: deque = deque()
        self.batches = 0
        self.samples = 0
        self.updates = 0
        self.publishes = 0
        self.last_metrics: dict = {}
        self.fingerprint = params_fingerprint(self.ts.params)
        # update counter at the last publish: the drain-time final
        # publish fires only when progress is stranded past it
        self.published_at_update = -1

    # -- feed -------------------------------------------------------------

    def ingest(self, batch: dict) -> dict:
        """Pool one consolidated batch; run every update it unlocks.
        Returns the reply block for the feed acknowledgement."""
        n_lanes = int(np.asarray(batch["lanes"]).shape[0])
        if n_lanes:
            window = int(np.asarray(batch["obs"]).shape[1])
            if window != self.cfg.n_steps:
                raise ValueError(
                    f"fed window length {window} != learner n_steps "
                    f"{self.cfg.n_steps}; align the serve burst with "
                    f"the learner's --n-steps")
        for i in range(n_lanes):
            win = {f: np.asarray(batch[f])[i] for f in _WINDOW_FIELDS}
            win["last_obs"] = np.asarray(batch["last_obs"])[i]
            self.pool.append(win)
        self.batches += 1
        self.samples += int(batch.get("steps", 0))
        updated = 0
        while len(self.pool) >= self.cfg.n_envs:
            self._update_once()
            updated += 1
        return dict(pool=len(self.pool), updates=self.updates,
                    updated=updated, publishes=self.publishes,
                    fingerprint=self.fingerprint)

    def _update_once(self):
        """One jitted PPO update over cfg.n_envs pooled windows,
        stacked time-major ([T, N, ...]) so the compiled program's
        shapes never change across the run."""
        import jax.numpy as jnp

        wins = [self.pool.popleft() for _ in range(self.cfg.n_envs)]
        b = {f: jnp.asarray(np.stack([w[f] for w in wins], axis=1))
             for f in _WINDOW_FIELDS}
        b["last_obs"] = jnp.asarray(
            np.stack([w["last_obs"] for w in wins], axis=0))
        t0 = telemetry.now()
        self.ts, self._key, metrics = self._update(self.ts, b, self._key)
        self.updates += 1
        self.fingerprint = params_fingerprint(self.ts.params)
        self.last_metrics = {
            k: float(v) for k, v in metrics.items()
            if np.ndim(v) == 0 and k != "device"}
        learn_event("update", steps=self.cfg.n_steps * self.cfg.n_envs,
                    batches=1, fingerprint=self.fingerprint,
                    staleness_s=None, update=self.updates,
                    update_s=telemetry.now() - t0,
                    pg_loss=self.last_metrics.get("pg_loss"))
        if self.updates % self.publish_every == 0:
            self.publish()

    # -- publish ----------------------------------------------------------

    def publish(self) -> dict:
        """Export the current params as a sealed serving snapshot and
        atomically repoint `latest.json` at it.  Readers (the serve
        watch loop) always see either the previous pointer or the new
        one — never a torn write, never a pointer to a half-written
        snapshot (the snapshot lands first)."""
        from cpr_tpu.train.driver import export_policy_snapshot

        seq = self.publishes
        path = os.path.join(self.publish_dir,
                            f"snapshot-{seq:06d}.msgpack")
        export_policy_snapshot(
            path, self.ts.params, protocol=self.protocol,
            n_actions=int(self.env.n_actions),
            observation_length=int(self.env.observation_length),
            hidden=list(self.cfg.hidden), seq=seq,
            updates=self.updates, samples=self.samples)
        resilience.atomic_write_json(
            os.path.join(self.publish_dir, LATEST),
            dict(seq=seq, path=path, fingerprint=self.fingerprint,
                 updates=self.updates, samples=self.samples,
                 time_utc=datetime.now(timezone.utc).isoformat(
                     timespec="seconds")))
        self.publishes += 1
        self.published_at_update = self.updates
        learn_event("publish", steps=self.samples, batches=self.batches,
                    fingerprint=self.fingerprint, staleness_s=None,
                    seq=seq, path=path, updates=self.updates)
        return dict(seq=seq, path=path, fingerprint=self.fingerprint)

    def stats(self) -> dict:
        return dict(batches=self.batches, samples=self.samples,
                    updates=self.updates, publishes=self.publishes,
                    pool=len(self.pool), fingerprint=self.fingerprint,
                    metrics=dict(self.last_metrics))


class LearnerServer:
    """TCP front-end: learn.feed / hello / stats / drain over the
    serve wire protocol."""

    def __init__(self, learner: Learner, *, host: str = "127.0.0.1",
                 port: int = 0):
        self.learner = learner
        self.host = host
        self.port = port  # replaced by the bound port after start()
        self._server = None
        self._drain_reason = None

    async def start(self):
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    def request_drain(self, reason: str):
        self._drain_reason = self._drain_reason or reason

    async def serve_until_drained(self, poll_s: float = 0.05):
        while True:
            if resilience.preempt_requested():
                self.request_drain(
                    f"preempt:{resilience.preempt_reason()}")
            if self._drain_reason is not None:
                break
            await asyncio.sleep(poll_s)
        # final publish so a drain never strands unpublished progress
        # (skipped when nothing changed since the last pointer move)
        lr = self.learner
        if lr.updates > lr.published_at_update:
            lr.publish()
        self._server.close()
        await self._server.wait_closed()

    async def _handle(self, reader, writer):
        try:
            while True:
                req = await wire.read_frame(reader)
                if req is None:
                    break
                try:
                    resp = self._dispatch(req)
                except Exception as e:  # noqa: BLE001 — per-request wall
                    resp = dict(ok=False,
                                error=f"{type(e).__name__}: {e}")
                await wire.write_frame(writer, resp)
        except (wire.ProtocolError, ConnectionError,
                asyncio.CancelledError):
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        lr = self.learner
        if op == "hello":
            return dict(ok=True, role="learner",
                        schema=telemetry.SCHEMA_VERSION,
                        run=telemetry.run_id(),
                        n_steps=lr.cfg.n_steps, n_envs=lr.cfg.n_envs,
                        fingerprint=lr.fingerprint)
        if op == "learn.feed":
            if self._drain_reason is not None:
                return dict(ok=False, error="draining", draining=True)
            return dict(ok=True, **lr.ingest(decode_batch(req)))
        if op == "stats":
            return dict(ok=True, **lr.stats())
        if op == "drain":
            self.request_drain(str(req.get("reason", "client")))
            return dict(ok=True, draining=True)
        return dict(ok=False, error=f"unknown op {op!r}")


# -- child entry point ----------------------------------------------------


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description="cpr_tpu learner child (see docs/LEARNING.md)")
    p.add_argument("--protocol", default="nakamoto")
    p.add_argument("--max-steps", type=int, default=256)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--publish-dir", required=True,
                   help="snapshot directory; latest.json in here is "
                        "the hot-swap pointer serve/server.py watches")
    p.add_argument("--ready-file", default=None,
                   help="atomic JSON {host,port,pid} once accepting")
    p.add_argument("--hidden", type=int, nargs="+", default=[64, 64])
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--n-envs", type=int, default=16,
                   help="windows per update (fixed jit batch width)")
    p.add_argument("--n-steps", type=int, default=64,
                   help="window length; must equal the serve burst")
    p.add_argument("--update-epochs", type=int, default=4)
    p.add_argument("--n-minibatches", type=int, default=4)
    p.add_argument("--publish-every", type=int, default=1,
                   help="publish a snapshot every N updates")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    from cpr_tpu import supervisor

    supervisor.maybe_start_heartbeat()
    with supervisor.child_phase("learn:init"):
        from cpr_tpu.envs.registry import get_sized
        from cpr_tpu.train.ppo import PPOConfig

        env = get_sized(args.protocol, args.max_steps)
        cfg = PPOConfig(n_envs=args.n_envs, n_steps=args.n_steps,
                        lr=args.lr, update_epochs=args.update_epochs,
                        n_minibatches=args.n_minibatches,
                        hidden=tuple(args.hidden))
        os.makedirs(args.publish_dir, exist_ok=True)
        learner = Learner(env, cfg, protocol=args.protocol,
                          publish_dir=args.publish_dir,
                          publish_every=args.publish_every,
                          seed=args.seed)
    telemetry.current().manifest(config=dict(
        entry="learn", protocol=args.protocol, n_envs=args.n_envs,
        n_steps=args.n_steps, lr=args.lr, hidden=list(args.hidden),
        publish_every=args.publish_every, max_steps=args.max_steps))
    # seq-0 publish before accepting: the server always has a swap
    # target, and the smoke's "revenue improves across swaps" baseline
    # is the untrained net
    with supervisor.child_phase("learn:publish0"):
        learner.publish()

    async def amain():
        server = LearnerServer(learner, host=args.host, port=args.port)
        await server.start()
        if args.ready_file:
            resilience.atomic_write_json(
                args.ready_file,
                dict(host=args.host, port=server.port, pid=os.getpid()))
        await server.serve_until_drained()

    with supervisor.child_phase("learn:run"), resilience.preemption_guard():
        asyncio.run(amain())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
