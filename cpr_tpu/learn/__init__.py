"""cpr_tpu.learn — always-on learning over the serve fleet.

The subsystem that closes the serve→train loop (ROADMAP item 2, after
arXiv:1803.02811's sampler/learner decoupling): the resident serve
lanes double as the sampler, recording transitions into device-side
ring buffers alongside the burst scan (`buffer`), a feeder thread
ships consolidated windows over the wire protocol to a separate
learner process (`feed`), the learner runs the PPO update phase of
train/ppo.py on the fed experience and publishes sealed snapshots
(`learner`), and the server hot-swaps the serving weights at the next
burst boundary without draining a single session
(serve/engine.py `swap_policy`).  docs/LEARNING.md is the contract.

Everything the loop does travels as ONE typed telemetry event family
(`learn`, schema v17) so the whole sampler→feed→update→publish→swap
cycle can be read off a validated trace; `learn_event` below is the
only emitter.
"""

from __future__ import annotations

from cpr_tpu import telemetry

# the five roles of the learning loop, in causal order
ROLES = ("sample", "feed", "update", "publish", "swap")


def learn_event(role: str, *, steps=None, batches=None,
                fingerprint=None, staleness_s=None, **extra):
    """Emit one typed v17 `learn` event (the only emitter — every leg
    of the loop funnels through here so the smoke can match sampled
    steps against fed, learned, and swapped ones 1:1 on the trace).

    role         -- one of ROLES.
    steps        -- env steps this leg moved (None when not step-shaped).
    batches      -- consolidated windows/batches this leg moved.
    fingerprint  -- snapshot payload_sha256 the leg acted under/on
                    (None before the first publish).
    staleness_s  -- age of the serving weights at this leg (swap: age
                    of the weights being replaced), None where the
                    emitting process cannot know it.
    """
    telemetry.current().event("learn", role=role, steps=steps,
                              batches=batches, fingerprint=fingerprint,
                              staleness_s=staleness_s, **extra)
