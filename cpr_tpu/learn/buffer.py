"""Device-side experience plane: per-lane ring buffers for the
sampler half of the always-on learning loop.

The buffers ride INSIDE the serve burst (serve/engine.py): `record` is
inlined into the burst scan body, so transitions accumulate with the
donated carry, in-graph, with no per-step host sync.  Two disciplines
from the source material shape the layout:

  * never pad to the slowest lane (arXiv:2406.01939): lanes are
    heterogeneous — some idle, some mid-episode, some freshly
    admitted — so each lane owns its ring and a write cursor, and a
    step is recorded with one masked scatter: lanes that are not live
    this step write to the out-of-range drop slot (`mode="drop"`), so
    ragged episode boundaries and idle lanes cost nothing and never
    block the batch;
  * sampler/learner decoupling (arXiv:1803.02811): `consolidate` (host
    side, one `device_get` per burst boundary) packs only lanes whose
    window filled into a dense [K, capacity] batch for the feed —
    partial lanes are counted, not padded.

Key streams: each lane's action-sampling stream is derived with
`fold_in` from the lane's admission key (`experience_stream`), so the
sampler side can never alias the key sequence the legacy training
rollout consumes via `split` — and the per-step key folds a monotone
counter `t` that survives drains (the write cursor resets, `t` never
does), so no step key is ever reused either.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# the fold_in stream tag separating sampler-side keys from every other
# consumer of a lane key ("EXP"); train/ppo.py re-exports it as the
# canonical name for the training side of the contract
EXPERIENCE_STREAM = 0x455850

# per-step ring fields, all lane-major [n_lanes, capacity, ...]
FIELDS = ("obs", "action", "reward", "done", "era", "erd", "policy")


def experience_stream(key):
    """The sampler-side stream of a lane key: fold_in with the stream
    tag, never `split` — a lane admitted with PRNGKey(S) spends its
    own stream on env dynamics, so the action-sampling stream must be
    a sibling derivation that cannot collide with it."""
    return jax.random.fold_in(key, EXPERIENCE_STREAM)


def init_buffer(keys, capacity: int, obs_dim: int) -> dict:
    """Fresh rings: `keys` is the [n_lanes, ...] per-lane sampler key
    block (already experience_stream-derived), `capacity` the ring
    length in steps (the serve layer uses the burst length so a
    drain-per-burst cadence yields dense full windows)."""
    n_lanes = keys.shape[0]
    cap = int(capacity)
    return dict(
        obs=jnp.zeros((n_lanes, cap, int(obs_dim)), jnp.float32),
        action=jnp.zeros((n_lanes, cap), jnp.int32),
        reward=jnp.zeros((n_lanes, cap), jnp.float32),
        done=jnp.zeros((n_lanes, cap), bool),
        # episode aggregates at the recorded step — what the learner's
        # reward transform (relative_reward_on_done) needs at done rows
        era=jnp.zeros((n_lanes, cap), jnp.float32),
        erd=jnp.zeros((n_lanes, cap), jnp.float32),
        policy=jnp.zeros((n_lanes, cap), jnp.int32),
        cursor=jnp.zeros((n_lanes,), jnp.int32),
        t=jnp.zeros((n_lanes,), jnp.int32),
        key=keys,
    )


def step_keys(exp: dict):
    """Per-lane action keys for this step: the lane stream folded by
    its monotone step counter.  `t` never resets (unlike the drain-
    reset write cursor), so a key is never reused across drains."""
    return jax.vmap(jax.random.fold_in)(exp["key"], exp["t"])


def record(exp: dict, live, obs, action, reward, done, info,
           policy_ids) -> dict:
    """Record one burst step for every live lane — one masked scatter
    per field.  Non-live lanes target index `capacity`, which is out
    of range and dropped (`mode="drop"`): the ragged-lane mask costs a
    clamp, not a pad.  Runs inside the burst scan body; inputs are the
    scan's own values, nothing is fetched from host."""
    cap = exp["action"].shape[1]
    lanes = jnp.arange(exp["cursor"].shape[0])
    idx = jnp.where(live, exp["cursor"] % cap, cap)
    live_i = live.astype(jnp.int32)

    def put(buf, val):
        return buf.at[lanes, idx].set(val, mode="drop")

    return dict(
        exp,
        obs=put(exp["obs"], obs.astype(jnp.float32)),
        action=put(exp["action"], action.astype(jnp.int32)),
        reward=put(exp["reward"], reward.astype(jnp.float32)),
        done=put(exp["done"], done),
        era=put(exp["era"],
                info["episode_reward_attacker"].astype(jnp.float32)),
        erd=put(exp["erd"],
                info["episode_reward_defender"].astype(jnp.float32)),
        policy=put(exp["policy"], policy_ids),
        cursor=exp["cursor"] + live_i,
        t=exp["t"] + live_i,
    )


def consolidate(host: dict, last_obs: np.ndarray) -> dict:
    """Pack host-fetched rings into a dense feed batch.

    Only lanes whose window filled (cursor >= capacity) are packed; a
    wrapped ring is unrolled oldest-first so each window is in time
    order.  Partial lanes are DROPPED AND COUNTED (`partial`,
    `dropped_steps`) — never padded to the slowest lane.  `last_obs`
    is the [n_lanes, obs_dim] current lane observation (the carry's),
    i.e. the bootstrap observation following each full window.

    Returns {lanes, obs, action, reward, done, era, erd, policy,
    last_obs, steps, partial, dropped_steps} with leading axis K =
    number of full lanes (arrays empty when K == 0).
    """
    cursor = np.asarray(host["cursor"])
    cap = host["action"].shape[1]
    full = [int(lane) for lane in np.nonzero(cursor >= cap)[0]]
    part = cursor[(cursor > 0) & (cursor < cap)]
    out = {k: [] for k in FIELDS}
    for lane in full:
        order = (np.arange(cap) + cursor[lane]) % cap
        for k in FIELDS:
            out[k].append(np.asarray(host[k])[lane][order])
    batch = {k: (np.stack(v) if v
                 else np.zeros((0, cap) + np.asarray(host[k]).shape[2:],
                               np.asarray(host[k]).dtype))
             for k, v in out.items()}
    batch["lanes"] = np.asarray(full, np.int32)
    batch["last_obs"] = (np.asarray(last_obs)[full] if full
                         else np.zeros((0,) + np.asarray(last_obs).shape[1:],
                                       np.float32))
    batch["steps"] = len(full) * cap
    batch["partial"] = int(part.size)
    batch["dropped_steps"] = int(part.sum())
    return batch
