"""Experience feed: ships consolidated sampler batches to the learner.

The serve tick loop drains the device rings at burst boundaries
(ResidentEngine.drain_experience) and hands the numpy batch to an
`ExperienceFeeder` — a daemon thread with a small bounded queue that
serializes and sends `learn.feed` frames over the serve wire protocol
(serve/protocol.py framing, same 4-byte-BE + JSON contract as every
other op).  The decoupling rules:

  * the tick loop NEVER blocks on the learner: `submit` is
    drop-oldest — a slow or dead learner costs experience, not serve
    latency (the drops are counted and ride the feed events);
  * the learner NEVER blocks the feeder forever: requests run on the
    feeder thread with the client's socket timeout, and errors tear
    down the connection for a lazy reconnect on the next batch.

`encode_batch`/`decode_batch` are the wire codec for a consolidated
batch (learn/buffer.py `consolidate` output): arrays travel as nested
JSON lists with the geometry fields (`lanes`, `steps`) alongside, and
the decoder rebuilds the exact dtypes, so a feed round-trip is
lossless up to float32.
"""

from __future__ import annotations

import queue
import threading

import numpy as np

from cpr_tpu.learn import learn_event
from cpr_tpu.serve.protocol import ServeClient

# batch fields that travel as arrays, with their wire dtypes
_ARRAY_FIELDS = (
    ("obs", np.float32), ("action", np.int32), ("reward", np.float32),
    ("done", bool), ("era", np.float32), ("erd", np.float32),
    ("policy", np.int32), ("last_obs", np.float32),
    ("lanes", np.int32),
)
_SCALAR_FIELDS = ("steps", "partial", "dropped_steps")

_STOP = object()


def encode_batch(batch: dict) -> dict:
    """Consolidated batch -> JSON-serializable feed payload."""
    out = {k: np.asarray(batch[k]).tolist() for k, _ in _ARRAY_FIELDS}
    for k in _SCALAR_FIELDS:
        out[k] = int(batch.get(k, 0))
    return out


def decode_batch(msg: dict) -> dict:
    """Feed payload -> consolidated batch (numpy, exact dtypes)."""
    out = {k: np.asarray(msg[k], dt) for k, dt in _ARRAY_FIELDS}
    for k in _SCALAR_FIELDS:
        out[k] = int(msg.get(k, 0))
    return out


class ExperienceFeeder:
    """Background shipper of experience batches to one learner."""

    def __init__(self, host: str, port: int, *, maxlen: int = 8,
                 timeout_s: float = 60.0, fingerprint=None):
        self._host, self._port = host, int(port)
        self._timeout_s = float(timeout_s)
        self._q: queue.Queue = queue.Queue(maxsize=int(maxlen))
        self._client: ServeClient | None = None
        # the serving snapshot fingerprint, stamped on feed events so
        # the learner trace says which policy generated the samples;
        # the server refreshes it after every swap
        self.fingerprint = fingerprint
        self.batches_fed = 0
        self.samples_fed = 0
        self.dropped = 0
        self.errors = 0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="experience-feeder")
        self._thread.start()

    def submit(self, batch: dict):
        """Enqueue a consolidated batch; drop-oldest on a full queue
        (the tick loop must never wait on the learner)."""
        while True:
            try:
                self._q.put_nowait(batch)
                return
            except queue.Full:
                try:
                    self._q.get_nowait()
                    self.dropped += 1
                except queue.Empty:
                    pass

    def stats(self) -> dict:
        return dict(batches_fed=self.batches_fed,
                    samples_fed=self.samples_fed,
                    dropped=self.dropped, errors=self.errors,
                    queued=self._q.qsize())

    def close(self, timeout_s: float = 10.0):
        """Flush-free shutdown: stop after the in-flight send."""
        self._q.put(_STOP)
        self._thread.join(timeout_s)
        if self._client is not None:
            try:
                self._client.close()
            except OSError:
                pass
            self._client = None

    # -- feeder thread ----------------------------------------------------

    def _send(self, batch: dict) -> dict:
        if self._client is None:
            self._client = ServeClient(self._host, self._port,
                                       timeout=self._timeout_s)
        return self._client.request(
            "learn.feed", fingerprint=self.fingerprint,
            **encode_batch(batch))

    def _run(self):
        while True:
            batch = self._q.get()
            if batch is _STOP:
                return
            try:
                reply = self._send(batch)
            except Exception:
                # connection-level failure: drop this batch, count it,
                # and reconnect lazily on the next one — experience is
                # cheap, serve availability is not
                self.errors += 1
                if self._client is not None:
                    try:
                        self._client.close()
                    except OSError:
                        pass
                    self._client = None
                continue
            if not (isinstance(reply, dict) and reply.get("ok")):
                self.errors += 1
                continue
            self.batches_fed += 1
            self.samples_fed += int(batch.get("steps", 0))
            learn_event("feed", steps=int(batch.get("steps", 0)),
                        batches=1, fingerprint=self.fingerprint,
                        staleness_s=None, dropped=self.dropped,
                        pool=reply.get("pool"))
