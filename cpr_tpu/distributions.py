"""Distribution samplers + string round-trip.

Reference counterpart: simulator/lib/distributions.ml — constant /
uniform / exponential / geometric samplers, the Vose alias method for
weighted discrete draws (:12-98), and the string grammar used by
GraphML-driven network configs (`constant 1`, `uniform 0 2`,
`exponential 1.2`; :100-153).

Two faces per distribution: `sample(rng)` for host-side simulation
(the C++ oracle and the network sims), and `sample_jax(key)` for use
inside jitted kernels — the same declaration drives both engines.
"""

from __future__ import annotations

import random
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

# Shared tail clamp for the geometric inverse-CDF on both faces: the
# host sampler used 1e-300 while the JAX face used 1e-12, so the two
# engines had different support ceilings for the same declaration
# (ceil(log u / log(1-p)) at the clamp).  One constant keeps
# sample(rng) and sample_jax(key) — and netsim's dense delay sampler —
# on the same bound; tests/test_distributions.py asserts the faces
# agree on support and mean for every kind.
GEOM_TAIL_CLAMP = 1e-12


@dataclass(frozen=True)
class Distribution:
    kind: str  # constant | uniform | exponential | geometric | discrete
    params: tuple

    def sample(self, rng: random.Random) -> float:
        k, p = self.kind, self.params
        if k == "constant":
            return p[0]
        if k == "uniform":
            return rng.uniform(p[0], p[1])
        if k == "exponential":
            return rng.expovariate(1.0 / p[0])  # p[0] = expected value
        if k == "geometric":
            # trials until first success at probability p[0]; >= 1
            if p[0] >= 1.0:
                return 1.0
            return max(1.0, float(int(np.ceil(
                np.log(max(rng.random(), GEOM_TAIL_CLAMP))
                / np.log(1.0 - p[0])))))
        if k == "discrete":
            return float(rng.choices(range(len(p)), weights=p)[0])
        raise ValueError(k)

    def sample_jax(self, key):
        k, p = self.kind, self.params
        if k == "constant":
            return jnp.float32(p[0])
        if k == "uniform":
            return jax.random.uniform(key, minval=p[0], maxval=p[1])
        if k == "exponential":
            return jax.random.exponential(key) * p[0]
        if k == "geometric":
            if p[0] >= 1.0:
                return jnp.float32(1.0)
            u = jax.random.uniform(key, minval=GEOM_TAIL_CLAMP,
                                   maxval=1.0)
            return jnp.maximum(
                jnp.ceil(jnp.log(u) / jnp.log(1.0 - p[0])), 1.0)
        if k == "discrete":
            # alias-free categorical; XLA computes the gumbel trick
            w = jnp.asarray(p, jnp.float32)
            return jax.random.categorical(key, jnp.log(w)).astype(
                jnp.float32)
        raise ValueError(k)

    @property
    def ev(self) -> float:
        """Expected value (the R generator's `distance` semantics:
        every delay distribution is parameterized so its mean is the
        link distance, create-networks.R:20-33)."""
        k, p = self.kind, self.params
        if k == "constant":
            return float(p[0])
        if k == "uniform":
            return (p[0] + p[1]) / 2.0
        if k == "exponential":
            return float(p[0])
        if k == "geometric":
            return 1.0 / p[0] if p[0] > 0 else float("inf")
        if k == "discrete":
            t = sum(p)
            return sum(i * w for i, w in enumerate(p)) / t if t else 0.0
        raise ValueError(k)

    def to_string(self) -> str:
        fmt = " ".join(_fmt_float(x) for x in self.params)
        return f"{self.kind} {fmt}"


def _fmt_float(x: float) -> str:
    return str(int(x)) if float(x).is_integer() else repr(float(x))


def constant(value: float) -> Distribution:
    return Distribution("constant", (float(value),))


def uniform(lower: float, upper: float) -> Distribution:
    assert lower <= upper
    return Distribution("uniform", (float(lower), float(upper)))


def exponential(ev: float) -> Distribution:
    assert ev > 0
    return Distribution("exponential", (float(ev),))


def geometric(p: float) -> Distribution:
    assert 0.0 < p <= 1.0
    return Distribution("geometric", (float(p),))


def discrete(weights) -> Distribution:
    ws = tuple(float(w) for w in weights)
    assert ws and all(w >= 0 for w in ws) and sum(ws) > 0
    return Distribution("discrete", ws)


def of_string(s: str) -> Distribution:
    """Parse the reference grammar (distributions.ml:100-141):
    `constant X`, `uniform LO HI`, `exponential EV`, plus `geometric P`
    and `discrete W...`; round-trips with to_string."""
    parts = s.split()
    if not parts:
        raise ValueError("empty distribution string")
    kind, args = parts[0], parts[1:]
    try:
        vals = [float(a) for a in args]
    except ValueError:
        raise ValueError(f"cannot parse distribution '{s}'")
    arity = {"constant": 1, "uniform": 2, "exponential": 1,
             "geometric": 1}
    if kind == "discrete":
        if not vals:
            raise ValueError(f"cannot parse distribution '{s}'")
        return discrete(vals)
    if kind not in arity:
        raise ValueError(f"unknown distribution '{kind}'")
    if len(vals) != arity[kind]:
        raise ValueError(
            f"'{kind}' takes {arity[kind]} parameter(s), got {len(vals)}")
    return {"constant": constant, "uniform": lambda a, b: uniform(a, b),
            "exponential": exponential,
            "geometric": geometric}[kind](*vals)
