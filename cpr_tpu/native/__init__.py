"""Native (C++) oracle engine: build-on-demand + ctypes bindings.

Reference counterpart: the OCaml runtime compiled into cpr_gym_engine.so
and loaded via PyDLL (gym/ocaml/cpr_gym/__init__.py:38-58).  pybind11 is
not available in this environment, so the library exposes a plain C API
driven through ctypes; the source lives in cpr_tpu/native/src/oracle.cpp
and is compiled with g++ on first use (cached next to the source).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "oracle.cpp")
_SO = os.path.join(_HERE, "liboracle.so")
_LOCK = threading.Lock()
_LIB = None


def build_lib(src: str, so: str, opt: str = "-O2") -> None:
    """g++-compile `src` into shared library `so` (skipped when fresh).

    Freshness = the sidecar stamp (`so`.cmd) records the compile
    command (basenames, so relocation into site-packages keeps a
    wheel-prebuilt .so fresh — mtimes don't survive wheel round-trips)
    plus a content hash of the source (so editing the .cpp rebuilds,
    and an -O2 artifact is never served for an -O3 request)."""
    import hashlib

    cmd = ["g++", opt, "-std=c++17", "-shared", "-fPIC", src, "-o", so]
    stamp = so + ".cmd"
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()
    stamp_line = " ".join(["g++", opt, "-std=c++17", "-shared", "-fPIC",
                           os.path.basename(src), "-o",
                           os.path.basename(so), "#", digest])
    if os.path.exists(so):
        try:
            with open(stamp) as f:
                if f.read() == stamp_line:
                    return
        except OSError:
            pass  # no/unreadable stamp: rebuild
    r = subprocess.run(cmd, capture_output=True, text=True)
    if r.returncode != 0:
        raise RuntimeError(
            f"native build failed ({' '.join(cmd)}):\n{r.stderr}")
    # atomic: a torn stamp would silently serve a stale .so forever
    # (lazy import; this module imports nothing from cpr_tpu at the top
    # so the C++ oracle stays loadable mid-package-init)
    from cpr_tpu.resilience import atomic_write_text

    atomic_write_text(stamp, stamp_line)


_LOADED: dict = {}


def load_lib(src: str, so: str, opt: str = "-O2") -> ctypes.CDLL:
    """Lock-guarded memoized build+load; callers attach ctypes
    signatures to the returned CDLL once (idempotent)."""
    with _LOCK:
        L = _LOADED.get(so)
        if L is None:
            build_lib(src, so, opt)
            L = _LOADED[so] = ctypes.CDLL(so)
        return L


def _build():
    build_lib(_SRC, _SO)


def lib() -> ctypes.CDLL:
    """Load (building if stale) the oracle shared library."""
    global _LIB
    with _LOCK:
        if _LIB is not None:
            return _LIB
        _build()  # build_lib early-returns when fresh (mtime + stamp)
        L = ctypes.CDLL(_SO)
        L.cpr_oracle_create.restype = ctypes.c_void_p
        L.cpr_oracle_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,  # proto,k,scheme
            ctypes.c_char_p, ctypes.c_int,  # topology, n_nodes
            ctypes.c_double, ctypes.c_double, ctypes.c_int,  # alpha,gamma,def
            ctypes.c_double, ctypes.c_double,  # activation, propagation
            ctypes.c_char_p, ctypes.c_uint64,  # attacker policy, seed
        ]
        L.cpr_oracle_run.restype = ctypes.c_long
        L.cpr_oracle_run.argtypes = [ctypes.c_void_p, ctypes.c_long]
        L.cpr_oracle_metric.restype = ctypes.c_double
        L.cpr_oracle_metric.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                        ctypes.c_int]
        L.cpr_oracle_destroy.restype = None
        L.cpr_oracle_destroy.argtypes = [ctypes.c_void_p]
        _LIB = L
        return L


_METRICS = {"reward_of": 0, "progress": 1, "sim_time": 2, "n_blocks": 3,
            "head_height": 4, "on_chain": 5, "head_time": 6,
            "pref_height": 7, "trace_truncated": 8, "activations_of": 9,
            "stuck_count": 10, "stuck_first": 11}


class OracleSim:
    """One discrete-event simulation on the C++ engine.

    Protocols: nakamoto, ethereum-whitepaper, ethereum-byzantium,
    bk (with k + scheme constant|block).
    Topologies: clique (n_nodes equal miners), two_agents (alpha split),
    selfish_mining (attacker + defender cloud, gamma via message delays,
    network.ml:61-105).
    attacker_policy (selfish_mining/two_agents topologies):
      nakamoto — none, honest, eyal-sirer-2014, sapirshtein-2016-sm1;
      ethereum-* — none, honest, fn19, fn19pkel (uncle-bearing
      withholding with per-step uncle-mining rules);
      bk — none, honest, get-ahead (vote withholding with private
      quorum proposals);
      spar — none, honest, selfish;
      stree/sdag — none, honest, minor-delay, avoid-loss;
      tailstorm — none, honest, minor-delay, get-ahead, avoid-loss
      (ParAgent: shared SSZ release scan over withheld descendants,
      cpr_protocols.ml:478-657's policy battery counterpart).
    """

    def __init__(self, protocol: str = "nakamoto", *, k: int = 0,
                 scheme: str = "", topology: str = "clique",
                 n_nodes: int = 7, alpha: float = 0.25,
                 gamma: float = 0.5, defenders: int | None = None,
                 activation_delay: float = 1.0,
                 propagation_delay: float = 1e-9,
                 attacker_policy: str = "none", seed: int = 0):
        import math

        if defenders is None:
            defenders = max(2, int(math.ceil(1.0 / (1.0 - gamma)))) \
                if gamma < 1.0 else 2
        self._lib = lib()
        self._h = self._lib.cpr_oracle_create(
            protocol.encode(), k, scheme.encode(), topology.encode(),
            n_nodes, alpha, gamma, defenders, activation_delay,
            propagation_delay, attacker_policy.encode(), seed)
        if not self._h:
            raise ValueError(
                f"oracle rejected configuration: protocol={protocol} "
                f"topology={topology} attacker_policy={attacker_policy}")

    def run(self, activations: int) -> int:
        return self._lib.cpr_oracle_run(self._h, activations)

    def metric(self, name: str, arg: int = 0) -> float:
        return self._lib.cpr_oracle_metric(self._h, _METRICS[name], arg)

    def rewards(self, n: int) -> list[float]:
        return [self.metric("reward_of", i) for i in range(n)]

    def activations(self, n: int) -> list[int]:
        """Per-node PoW success counts (csv_runner.ml:77's array)."""
        return [int(self.metric("activations_of", i)) for i in range(n)]

    def close(self):
        if self._h:
            self._lib.cpr_oracle_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


__all__ = ["OracleSim", "lib"]
