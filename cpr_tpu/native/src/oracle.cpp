// Multi-node discrete-event consensus simulator — the CPU oracle engine.
//
// Reference counterpart: the OCaml core runtime (simulator/lib/simulator.ml
// event loop :421-533, network.ml topologies :29-105, dag.ml views) and the
// honest protocol modules (nakamoto.ml, ethereum.ml, bk.ml) plus the
// nakamoto_ssz.ml withholding agent (:156-350).  The reference compiles this
// machinery into cpr_gym_engine.so; this framework's equivalent is a C
// shared library driven through ctypes (cpr_tpu/native/__init__.py).
//
// Role in the TPU framework: the general multi-node simulator is host-side
// by nature (pointer-chasing DAGs, data-dependent event queues) and serves
// as the equivalence oracle for the collapsed 2-party JAX environments and
// as the engine for honest-network topology sweeps.  The hot RL path runs
// on TPU; this code validates its semantics.
//
// Clean-room implementation: structures and algorithms re-derived from the
// reference's documented behavior, not translated.

#include <algorithm>
#include <array>
#include <cassert>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <queue>
#include <random>
#include <string>
#include <vector>

namespace {

// ---------------------------------------------------------------- blocks

struct Block {
  std::vector<int> parents;  // parents[0] = chain predecessor
  std::vector<int> children;
  int miner = -1;            // -1: root
  int height = 0;
  int work = 0;              // ethereum: cumulative work; bk votes: unused
  bool is_vote = false;      // bk
  int vote_id = -1;          // bk vote: voter id; bk block: signer (leader)
  double pow_hash = 2.0;     // < 2.0 iff proof-of-work block
  double time = 0.0;         // append time
};

struct Dag {
  std::vector<Block> blocks;

  int add(Block b) {
    int id = (int)blocks.size();
    for (int p : b.parents) blocks[p].children.push_back(id);
    blocks.push_back(std::move(b));
    return id;
  }
};

// ------------------------------------------------------------- protocols

struct Sim;  // fwd

// A protocol defines drafts (what an honest node mines on), preference
// updates, optional non-PoW proposals, progress, and rewards.
struct Protocol {
  virtual ~Protocol() = default;
  virtual Block genesis() const = 0;
  // honest mining draft given the node's preferred tip
  virtual Block draft(Sim& s, int node, int preferred) = 0;
  // preference after learning `b` (visibility-filtered view belongs to
  // the caller; protocols only compare chain data)
  virtual int prefer(Sim& s, int node, int old, int b) = 0;
  // non-PoW block the node would append after learning `b` (bk proposal);
  // return empty vector if none
  virtual std::vector<Block> proposals(Sim& s, int node, int b) {
    (void)s; (void)node; (void)b;
    return {};
  }
  virtual double progress(const Dag& d, int head) const = 0;
  // attacker-share bookkeeping: per-miner rewards along head's history
  virtual void rewards(const Dag& d, int head,
                       std::vector<double>& per_miner) const = 0;
  // chain membership for orphan statistics: number of blocks that count
  virtual long on_chain(const Dag& d, int head) const = 0;
  // winner among node preferences (referee `winner`)
  virtual int winner(Sim& s, const std::vector<int>& prefs) = 0;
  // protocols whose votes reference the block they confirm in
  // `vote_id` opt into the Sim's confirmers index (bk overloads
  // vote_id with the voter/signer id, so the index must stay off)
  virtual bool votes_confirm_blocks() const { return false; }
};

// ------------------------------------------------------------ event loop

struct Event {
  double time;
  long seq;  // FIFO tie-break
  int type;  // 0 = activation, 1 = receive(node, block)
  int node = -1;
  int block = -1;
  bool operator<(const Event& o) const {  // min-heap via greater
    if (time != o.time) return time > o.time;
    return seq > o.seq;
  }
};

struct Sim;

// Withholding attacker on node 0 (optional): tracks a private tip and
// its model of the defenders' preferred block, and decides per event
// what to share.  One subclass per attack-space family.
struct Agent {
  int policy = 0;
  int priv = 0, pub = 0;
  // released by us but possibly still in flight; vote-family agents
  // count these as public in their defender models (`pending` messages
  // in the SSZ spaces are visible-on-release, ssz_tools.ml visibility)
  std::vector<char> sent;
  virtual ~Agent() {}
  void init(int g) { priv = pub = g; }
  virtual std::vector<int> handle(Sim& s, int b, bool is_pow) = 0;
  void mark_sent(int b, size_t dag_size) {
    if ((int)sent.size() <= b) sent.resize(dag_size, 0);
    sent[b] = 1;
  }
  // released-or-delivered from the defenders' point of view (defined
  // after Sim, which is incomplete here)
  bool is_public(Sim& s, int b) const;
  // called for EVERY block the release machinery actually sends —
  // including withheld ancestors shared implicitly — so agents that
  // track in-flight releases see the full set
  virtual void note_sent(Sim& s, int b) { (void)s; (void)b; }
  // in-flight releases: the release machinery treats these as public
  // already, so a block is not re-sent on every event between its
  // release and its (delayed) delivery
  virtual bool sent_already(int b) const { (void)b; return false; }
  // chain-parent common ancestor (heights along parents[0] are
  // sequential, so height-stepping both sides converges)
  template <typename D>
  static int common_anc(const D& d, int a, int b) {
    while (a != b) {
      if (d.blocks[a].height >= d.blocks[b].height)
        a = d.blocks[a].parents[0];
      else
        b = d.blocks[b].parents[0];
    }
    return a;
  }
};

struct Sim {
  Dag dag;
  std::unique_ptr<Protocol> proto;
  std::mt19937_64 rng;

  int n_nodes = 0;
  std::vector<double> compute;          // mining weight per node
  double activation_delay = 1.0;
  // link delays: delay_matrix[src][dst]; -1 = uniform attacker delay
  std::vector<std::vector<double>> delay;
  double attacker_delay_upper = 0.0;    // uniform upper bound for src 0
  // optional general link distributions (custom topologies):
  // kind 0 constant(p0), 1 uniform(p0,p1), 2 exponential(ev=p0)
  bool custom_links = false;
  std::vector<int> lkind;
  std::vector<double> lp0, lp1;
  // flooding dissemination (simulator.ml:494-507): re-share received
  // blocks on all links, so multi-hop topologies converge
  bool flooding = false;

  std::vector<std::vector<char>> visible;   // [node][block]
  std::vector<std::vector<char>> known;     // received but maybe buffered
  // when the node first saw each block (visible_since in the reference
  // views, simulator.ml:2-10) — the altruistic quorum sorts by it
  std::vector<std::vector<double>> visible_at;
  // confirmers[b] = ids of votes with vote_id == b, append order —
  // replaces O(|dag|) scans in the parallel family's confirming-vote
  // lookups (kept empty unless proto->votes_confirm_blocks())
  std::vector<std::vector<int>> confirmers;
  std::vector<int> preferred;               // per node
  std::priority_queue<Event> queue;
  long seq = 0;
  double now = 0.0;
  long activations = 0;

  std::unique_ptr<Agent> agent;             // node 0, optional
  // attacker uncle-mining rule (set per step by EthAgent; the ethereum
  // draft for node 0 filters uncle candidates through it)
  bool atk_mine_own = true, atk_mine_foreign = true;
  // parallel-family Prolong mining filter (spar_ssz.ml:180-189): when
  // set, node 0's drafts count only its own votes (`Exclusive);
  // Proceed clears it back to the inclusive node-0 visibility
  bool atk_vote_own_only = false;

  // bk proposal dedup (simulator.ml:138-158): key -> block id
  std::map<std::string, int> dedup;

  // atomic-release graft (decomposition tooling, not reference
  // behavior): deliver a whole release batch to a node BEFORE running
  // its honest handler once — the JAX envs' collapse applies a release
  // atomically and lets the defender cloud attempt ONE proposal per
  // delivery batch, while the event loop runs the handler per item
  // (a defender can propose mid-release on a partial vote set).
  // Enabled by the *-atomicrel agent policies.
  bool atomic_release = false;
  std::vector<char> in_batch;               // block id -> current batch
  std::vector<int> batch_pending;           // per node
  std::vector<std::vector<int>> batch_items;  // per node, arrival order

  // structured causal trace (log.ml:1-26): (time, kind, node, block);
  // kinds: 0 append, 1 share, 2 receive, 3 learn.  Bounded so long runs
  // don't exhaust memory; `trace_truncated` reports the overflow.
  static constexpr size_t kTraceCap = 1 << 20;
  std::vector<std::array<double, 4>> trace;
  bool trace_truncated = false;

  void record(int kind, int node, int block) {
    if (trace.size() >= kTraceCap) {
      trace_truncated = true;
      return;
    }
    trace.push_back(
        std::array<double, 4>{now, (double)kind, (double)node,
                              (double)block});
  }

  double rand_u() { return std::uniform_real_distribution<>(0, 1)(rng); }

  void push(double t, int type, int node, int block) {
    queue.push(Event{t, seq++, type, node, block});
  }

  void init() {
    int g = dag.add(proto->genesis());
    visible.assign(n_nodes, {});
    known.assign(n_nodes, {});
    visible_at.assign(n_nodes, {});
    preferred.assign(n_nodes, g);
    for (int i = 0; i < n_nodes; i++) mark_visible(i, g);
    schedule_activation();
  }

  void mark_visible(int node, int b) {
    auto& v = visible[node];
    auto& k = known[node];
    auto& t = visible_at[node];
    if ((int)v.size() <= b) v.resize(dag.blocks.size(), 0);
    if ((int)k.size() <= b) k.resize(dag.blocks.size(), 0);
    if ((int)t.size() <= b) t.resize(dag.blocks.size(), 0.0);
    if (!v[b]) t[b] = now;
    v[b] = 1;
    k[b] = 1;
  }

  double seen_at(int node, int b) const {
    const auto& t = visible_at[node];
    return b < (int)t.size() ? t[b] : 0.0;
  }

  bool is_visible(int node, int b) const {
    return b < (int)visible[node].size() && visible[node][b];
  }

  bool parents_visible(int node, int b) const {
    for (int p : dag.blocks[b].parents)
      if (!is_visible(node, p)) return false;
    return true;
  }

  void schedule_activation() {
    double dt = std::exponential_distribution<>(1.0 / activation_delay)(rng);
    push(now + dt, 0, -1, -1);
  }

  int sample_miner() {
    double total = 0;
    for (double c : compute) total += c;
    double r = rand_u() * total, acc = 0;
    for (int i = 0; i < n_nodes; i++) {
      acc += compute[i];
      if (r <= acc) return i;
    }
    return n_nodes - 1;
  }

  // negative = no link (caller must skip the send)
  double link_delay(int src, int dst) {
    if (custom_links) {
      int i = src * n_nodes + dst;
      if (lkind[i] < 0) return -1.0;
      switch (lkind[i]) {
        case 1:
          return lp0[i] + rand_u() * (lp1[i] - lp0[i]);
        case 2:
          return -std::log(std::max(rand_u(), 1e-300)) * lp0[i];
        default:
          return lp0[i];
      }
    }
    double d = delay[src][dst];
    if (d < 0) d = rand_u() * attacker_delay_upper;
    return d;
  }

  void send(int src, int b) {  // share a block on all links
    static const bool dbg = getenv("CPR_ORACLE_DEBUG") != nullptr;
    if (dbg)
      fprintf(stderr, "send src=%d b=%d miner=%d vote=%d h=%d t=%.2f\n",
              src, b, dag.blocks[b].miner, (int)dag.blocks[b].is_vote,
              dag.blocks[b].height, now);
    record(1, src, b);
    for (int dst = 0; dst < n_nodes; dst++) {
      if (dst == src) continue;
      double d = link_delay(src, dst);
      if (d < 0) continue;  // no link
      push(now + d, 1, dst, b);
    }
  }

  // deliver b (parents-visible) to node, then its unlocked descendants
  void deliver(int node, int b);
  void flush_batch(int node);
  bool batch_complete(int node) const;
  void unlock_children(int node, int b);
  void handle_honest(int node, int b);
  void handle_agent(int b, bool is_pow);

  void index_confirmer(int id) {
    const Block& b = dag.blocks[id];
    if (!b.is_vote || !proto->votes_confirm_blocks()) return;
    if (b.vote_id < 0 || b.vote_id >= id) return;
    if ((int)confirmers.size() < (int)dag.blocks.size())
      confirmers.resize(dag.blocks.size());
    confirmers[b.vote_id].push_back(id);
  }

  int append_pow(int miner, Block b) {
    b.miner = miner;
    b.pow_hash = rand_u();
    b.time = now;
    int id = dag.add(std::move(b));
    index_confirmer(id);
    return id;
  }

  // append-or-dedup for non-PoW proposals
  int append_plain(int miner, Block b) {
    std::string key;
    key.reserve(b.parents.size() * 4 + 16);
    for (int p : b.parents) key += std::to_string(p) + ",";
    key += "|" + std::to_string(b.vote_id) + "|" + std::to_string(b.height);
    auto it = dedup.find(key);
    if (it != dedup.end()) return it->second;
    b.miner = miner;
    b.time = now;
    int id = dag.add(std::move(b));
    record(0, miner, id);
    dedup[key] = id;
    index_confirmer(id);
    return id;
  }

  void step_event();
  void run(long n_activations);
};

bool Agent::is_public(Sim& s, int b) const {
  if (b < (int)sent.size() && sent[b]) return true;
  for (int n = 1; n < s.n_nodes; n++)
    if (s.is_visible(n, b)) return true;
  return false;
}

// ------------------------------------------------------------- nakamoto

struct Nakamoto final : Protocol {
  Block genesis() const override { return Block{}; }

  Block draft(Sim&, int, int preferred) override {
    Block b;
    b.parents = {preferred};
    return b;  // height set by caller context
  }

  int prefer(Sim& s, int, int old, int b) override {
    return s.dag.blocks[b].height > s.dag.blocks[old].height ? b : old;
  }

  double progress(const Dag& d, int head) const override {
    return d.blocks[head].height;
  }

  void rewards(const Dag& d, int head,
               std::vector<double>& per_miner) const override {
    for (int b = head; d.blocks[b].miner >= 0; b = d.blocks[b].parents[0])
      per_miner[d.blocks[b].miner] += 1.0;
  }

  long on_chain(const Dag& d, int head) const override {
    return d.blocks[head].height;
  }

  int winner(Sim& s, const std::vector<int>& prefs) override {
    int best = prefs[0];
    for (int p : prefs)
      if (s.dag.blocks[p].height > s.dag.blocks[best].height) best = p;
    return best;
  }
};

// ------------------------------------------------------------- ethereum

struct Ethereum final : Protocol {
  // ethereum.ml preset semantics (ethereum.ml:12-24,74-83): the
  // whitepaper preset prefers by cumulative work and progresses by
  // height; byzantium prefers by height, progresses by work, caps
  // uncles at 2 and discounts uncle rewards.
  bool byzantium;
  explicit Ethereum(bool byz) : byzantium(byz) {}

  int pref_key(const Dag& d, int b) const {
    return byzantium ? d.blocks[b].height : d.blocks[b].work;
  }

  Block genesis() const override { return Block{}; }

  // non-uncle ancestors of `tip` up to 6 generations + in-chain set
  void chain_window(const Dag& d, int tip, std::vector<int>& ancestors,
                    std::vector<int>& in_chain) const {
    ancestors.clear();
    in_chain.clear();
    in_chain.push_back(tip);
    int b = tip;
    for (int gen = 0; gen < 6 && !d.blocks[b].parents.empty(); gen++) {
      const auto& ps = d.blocks[b].parents;
      ancestors.push_back(ps[0]);
      for (int p : ps) in_chain.push_back(p);
      b = ps[0];
    }
  }

  Block draft(Sim& s, int node, int preferred) override {
    const Dag& d = s.dag;
    std::vector<int> anc, chain;
    chain_window(d, preferred, anc, chain);
    std::vector<int> uncles;
    for (int a : anc) {
      for (int c : d.blocks[a].children) {
        if (!s.is_visible(node, c)) continue;
        if (std::find(chain.begin(), chain.end(), c) != chain.end())
          continue;
        if (d.blocks[c].parents.empty()) continue;
        int cp = d.blocks[c].parents[0];
        if (std::find(anc.begin(), anc.end(), cp) == anc.end()) continue;
        uncles.push_back(c);
      }
    }
    // the withholding agent steers which uncles its drafts reference
    // (the uncle-mining rule of the attack space)
    if (node == 0 && s.agent) {
      uncles.erase(std::remove_if(uncles.begin(), uncles.end(), [&](int u) {
        bool own = d.blocks[u].miner == 0;
        return own ? !s.atk_mine_own : !s.atk_mine_foreign;
      }), uncles.end());
    }
    // own uncles first, older (lower preference key) first
    std::stable_sort(uncles.begin(), uncles.end(), [&](int a, int b) {
      bool am = d.blocks[a].miner == node, bm = d.blocks[b].miner == node;
      if (am != bm) return am;
      return pref_key(d, a) < pref_key(d, b);
    });
    if (byzantium && uncles.size() > 2) uncles.resize(2);
    Block b;
    b.parents = {preferred};
    b.parents.insert(b.parents.end(), uncles.begin(), uncles.end());
    b.height = d.blocks[preferred].height + 1;
    b.work = d.blocks[preferred].work + 1 + (int)uncles.size();
    return b;
  }

  int prefer(Sim& s, int, int old, int b) override {
    return pref_key(s.dag, b) > pref_key(s.dag, old) ? b : old;
  }

  double progress(const Dag& d, int head) const override {
    return byzantium ? d.blocks[head].work : d.blocks[head].height;
  }

  void rewards(const Dag& d, int head,
               std::vector<double>& per_miner) const override {
    for (int b = head; d.blocks[b].miner >= 0; b = d.blocks[b].parents[0]) {
      const auto& blk = d.blocks[b];
      int nu = (int)blk.parents.size() - 1;
      per_miner[blk.miner] += 1.0 + nu * 0.03125;
      for (size_t i = 1; i < blk.parents.size(); i++) {
        const auto& u = d.blocks[blk.parents[i]];
        if (u.miner < 0) continue;
        double amt = byzantium
            ? (8.0 - (blk.height - u.height)) / 8.0  // discount
            : 0.9375;                                // constant
        per_miner[u.miner] += amt;
      }
    }
  }

  long on_chain(const Dag& d, int head) const override {
    long n = 0;
    for (int b = head; d.blocks[b].miner >= 0; b = d.blocks[b].parents[0])
      n += (long)d.blocks[b].parents.size();  // block + its uncles
    return n;
  }

  int winner(Sim& s, const std::vector<int>& prefs) override {
    int best = prefs[0];
    for (int p : prefs)
      if (pref_key(s.dag, p) > pref_key(s.dag, best)) best = p;
    return best;
  }
};

// ------------------------------------------------------------------- bk

struct Bk final : Protocol {
  int k;
  bool reward_block;  // `Block scheme: signer gets k; `Constant: voters 1
  Bk(int k_, bool rb) : k(k_), reward_block(rb) {}

  Block genesis() const override { return Block{}; }

  static int last_block(const Dag& d, int x) {
    return d.blocks[x].is_vote ? d.blocks[x].parents[0] : x;
  }

  double leader_hash(const Dag& d, int blk) const {
    // leader vote is parents[1] (parents[0] = predecessor block)
    if (d.blocks[blk].parents.size() >= 2)
      return d.blocks[d.blocks[blk].parents[1]].pow_hash;
    return 2.0;  // genesis: max
  }

  Block draft(Sim& s, int node, int preferred) override {
    Block b;  // a vote on the preferred block
    b.parents = {preferred};
    b.is_vote = true;
    b.vote_id = node;
    b.height = s.dag.blocks[preferred].height;
    return b;
  }

  // (height, confirming votes, -leader hash) lexicographic preference
  bool better(Sim& s, int node, int a, int b) const {
    const Dag& d = s.dag;
    if (d.blocks[a].height != d.blocks[b].height)
      return d.blocks[a].height > d.blocks[b].height;
    int va = 0, vb = 0;
    for (int c : d.blocks[a].children)
      if (d.blocks[c].is_vote && s.is_visible(node, c)) va++;
    for (int c : d.blocks[b].children)
      if (d.blocks[c].is_vote && s.is_visible(node, c)) vb++;
    if (va != vb) return va > vb;
    return leader_hash(d, a) < leader_hash(d, b);
  }

  int prefer(Sim& s, int node, int old, int x) override {
    int b = last_block(s.dag, x);
    return better(s, node, b, old) ? b : old;
  }

  std::vector<Block> proposals(Sim& s, int node, int x) override {
    const Dag& d = s.dag;
    int b = last_block(d, x);
    // visible confirming votes, split mine/theirs (bk.ml quorum :233-279)
    double my_hash = 2.0, replace_hash = 2.0;
    std::vector<int> mine, theirs;
    for (int c : d.blocks[b].children) {
      if (!s.is_visible(node, c)) continue;
      if (d.blocks[c].is_vote) {
        if (d.blocks[c].vote_id == node) {
          mine.push_back(c);
          my_hash = std::min(my_hash, d.blocks[c].pow_hash);
        } else {
          theirs.push_back(c);
        }
      } else {
        replace_hash = std::min(replace_hash, leader_hash(d, c));
      }
    }
    if (replace_hash <= my_hash ||
        (int)(mine.size() + theirs.size()) < k)
      return {};
    std::vector<int> q;
    auto by_hash = [&](int a, int c) {
      return d.blocks[a].pow_hash < d.blocks[c].pow_hash;
    };
    if ((int)mine.size() >= k) {
      std::sort(mine.begin(), mine.end(), by_hash);
      q.assign(mine.begin(), mine.begin() + k);
    } else {
      // theirs with hash above my best, earliest-seen first
      std::vector<int> cand;
      for (int t : theirs)
        if (d.blocks[t].pow_hash > my_hash) cand.push_back(t);
      if ((int)(mine.size() + cand.size()) < k) return {};
      std::stable_sort(cand.begin(), cand.end(), [&](int a, int c) {
        return d.blocks[a].time < d.blocks[c].time;
      });
      cand.resize(k - mine.size());
      q = mine;
      q.insert(q.end(), cand.begin(), cand.end());
      std::sort(q.begin(), q.end(), by_hash);
    }
    Block prop;
    prop.parents = {b};
    prop.parents.insert(prop.parents.end(), q.begin(), q.end());
    prop.height = d.blocks[b].height + 1;
    prop.vote_id = d.blocks[q[0]].vote_id;  // leader signs
    return {prop};
  }

  double progress(const Dag& d, int head) const override {
    return (double)d.blocks[head].height * k;
  }

  void rewards(const Dag& d, int head,
               std::vector<double>& per_miner) const override {
    for (int b = head; !d.blocks[b].parents.empty();
         b = d.blocks[b].parents[0]) {
      const auto& blk = d.blocks[b];
      if (reward_block) {
        if (blk.vote_id >= 0) per_miner[blk.vote_id] += (double)k;
      } else {
        for (size_t i = 1; i < blk.parents.size(); i++) {
          const auto& v = d.blocks[blk.parents[i]];
          if (v.is_vote && v.vote_id >= 0) per_miner[v.vote_id] += 1.0;
        }
      }
    }
  }

  long on_chain(const Dag& d, int head) const override {
    // each chain block carries k votes + itself (genesis excluded)
    return (long)d.blocks[head].height * (k + 1);
  }

  int winner(Sim& s, const std::vector<int>& prefs) override {
    // referee compare: (height, confirming votes) over full visibility
    const Dag& d = s.dag;
    auto votes_all = [&](int b) {
      int n = 0;
      for (int c : d.blocks[b].children)
        if (d.blocks[c].is_vote) n++;
      return n;
    };
    int best = prefs[0];
    for (int p : prefs) {
      if (d.blocks[p].height > d.blocks[best].height ||
          (d.blocks[p].height == d.blocks[best].height &&
           votes_all(p) > votes_all(best)))
        best = p;
    }
    return best;
  }
};


// ---------------------------------------------- parallel-PoW family
//
// Spar (spar.ml), Stree (stree.ml), Sdag (sdag.ml), Tailstorm
// (tailstorm.ml): k proofs-of-work per chain block.  Votes record the
// block/summary they confirm in `vote_id` (set at draft time, so
// confirming-vote lookups are linear scans, no walks) and their tree
// depth / vote number in `work`.

struct ParallelBase : Protocol {
  int k;
  // sub-block selection: 0 heuristic, 1 altruistic, 2 optimal
  // (tailstorm.ml:271-313 / :329-380 / :418-506; parsed from the
  // scheme string's ":selector" suffix in cpr_oracle_create)
  int selector = 0;
  explicit ParallelBase(int k_) : k(k_) {}

  // selector dispatch shared by stree drafts and tailstorm proposals;
  // the optimal scorer needs the scheme knobs (see optimal_quorum)
  std::vector<int> select_quorum(Sim& s, const Dag& d,
                                 const std::vector<int>& cands, int node,
                                 int q, bool discount, bool punish,
                                 int depth_plus, int miner_share);

  bool votes_confirm_blocks() const override { return true; }

  static int last_block(const Dag& d, int x) {
    while (d.blocks[x].is_vote) x = d.blocks[x].vote_id;
    return x;
  }

  // the agent's Prolong/Proceed mining filter (spar_ssz.ml:180-189)
  // narrows node 0's draft-time vote view to its own votes
  static bool vote_counts(Sim& s, int node, int i) {
    return node != 0 || !s.atk_vote_own_only ||
           s.dag.blocks[i].miner == 0;
  }

  // ids of votes confirming b (append order) via the Sim's index
  static const std::vector<int>& confirmer_ids(Sim& s, int b) {
    static const std::vector<int> empty;
    if (b < (int)s.confirmers.size()) return s.confirmers[b];
    return empty;
  }

  // visible votes confirming block/summary b, ascending id
  std::vector<int> confirming(Sim& s, int node, int b) const {
    std::vector<int> out;
    for (int i : confirmer_ids(s, b)) {
      if (s.is_visible(node, i) && vote_counts(s, node, i))
        out.push_back(i);
    }
    return out;
  }

  int count_confirming(Sim& s, int node, int b) const {
    int n = 0;
    for (int i : confirmer_ids(s, b))
      if (s.is_visible(node, i) && vote_counts(s, node, i))
        n++;
    return n;
  }

  // preference: (height, confirming votes, -first-seen) — the shared
  // shape of spar.ml:185-196 / stree.ml:516-528 / tailstorm.ml:183-194
  int prefer(Sim& s, int node, int old, int x) override {
    int b = last_block(s.dag, x);
    int ob = last_block(s.dag, old);
    if (b == ob) return old;
    const Dag& d = s.dag;
    if (d.blocks[b].height != d.blocks[ob].height)
      return d.blocks[b].height > d.blocks[ob].height ? b : old;
    int nb = count_confirming(s, node, b);
    int no = count_confirming(s, node, ob);
    if (nb != no) return nb > no ? b : old;
    return old;  // earlier-seen (the incumbent) wins ties
  }

  double progress(const Dag& d, int head) const override {
    return (double)d.blocks[last_block(d, head)].height * k;
  }

  long on_chain(const Dag& d, int head) const override {
    return (long)d.blocks[last_block(d, head)].height * k;
  }

  int winner(Sim& s, const std::vector<int>& prefs) override {
    const Dag& d = s.dag;
    auto votes_all = [&](int b) {
      return (int)confirmer_ids(s, b).size();
    };
    int best = last_block(d, prefs[0]);
    for (int p : prefs) {
      int b = last_block(d, p);
      if (d.blocks[b].height > d.blocks[best].height ||
          (d.blocks[b].height == d.blocks[best].height &&
           votes_all(b) > votes_all(best)))
        best = b;
    }
    return best;
  }
};

struct Spar final : ParallelBase {
  bool reward_block;
  Spar(int k_, bool rb) : ParallelBase(k_), reward_block(rb) {}

  Block genesis() const override { return Block{}; }

  Block draft(Sim& s, int node, int preferred) override {
    const Dag& d = s.dag;
    int pref = last_block(d, preferred);
    std::vector<int> votes = confirming(s, node, pref);
    if ((int)votes.size() >= k - 1) {
      // own votes first, then earliest-seen (spar.ml:205-213)
      std::stable_sort(votes.begin(), votes.end(), [&](int a, int b) {
        bool am = d.blocks[a].miner == node, bm = d.blocks[b].miner == node;
        if (am != bm) return am;
        return d.blocks[a].time < d.blocks[b].time;
      });
      Block blk;
      blk.parents = {pref};
      blk.parents.insert(blk.parents.end(), votes.begin(),
                         votes.begin() + (k - 1));
      blk.height = d.blocks[pref].height + 1;
      return blk;
    }
    Block v;
    v.parents = {pref};
    v.is_vote = true;
    v.vote_id = pref;
    v.height = d.blocks[pref].height;
    return v;
  }

  void rewards(const Dag& d, int head,
               std::vector<double>& per_miner) const override {
    for (int b = last_block(d, head); d.blocks[b].miner >= 0;
         b = last_block(d, d.blocks[b].parents[0])) {
      if (reward_block) {
        per_miner[d.blocks[b].miner] += (double)k;
      } else {
        per_miner[d.blocks[b].miner] += 1.0;
        for (size_t i = 1; i < d.blocks[b].parents.size(); i++) {
          const auto& v = d.blocks[d.blocks[b].parents[i]];
          if (v.miner >= 0) per_miner[v.miner] += 1.0;
        }
      }
    }
  }
};

// tree / path closure helper: the vote-ancestor closure of `x` down to
// (excluding) its block, following vote parents only
static std::vector<int> vote_closure(const Dag& d, int x) {
  std::vector<int> out;
  std::vector<int> stack = {x};
  while (!stack.empty()) {
    int v = stack.back();
    stack.pop_back();
    if (!d.blocks[v].is_vote) continue;
    if (std::find(out.begin(), out.end(), v) != out.end()) continue;
    out.push_back(v);
    for (int p : d.blocks[v].parents) stack.push_back(p);
  }
  return out;
}

// own-reward-first greedy quorum of `q` votes from `cands`
// (stree.ml:280-344 / tailstorm.ml:329-380 heuristic): each round adds
// the candidate whose fresh closure maximizes (own, total) while still
// fitting.  Returns the selected set or empty when infeasible.
static std::vector<int> heuristic_quorum(const Dag& d,
                                         const std::vector<int>& cands,
                                         int me, int q) {
  std::vector<int> sel;
  auto in_sel = [&](int v) {
    return std::find(sel.begin(), sel.end(), v) != sel.end();
  };
  int n = 0;
  while (n < q) {
    int best = -1, best_own = -1, best_all = -1;
    for (int c : cands) {
      if (in_sel(c)) continue;
      int own = 0, all = 0;
      for (int v : vote_closure(d, c)) {
        if (in_sel(v)) continue;
        all++;
        if (d.blocks[v].miner == me) own++;
      }
      if (all < 1 || n + all > q) continue;
      if (own > best_own || (own == best_own && all > best_all)) {
        best = c;
        best_own = own;
        best_all = all;
      }
    }
    if (best < 0) return {};
    for (int v : vote_closure(d, best))
      if (!in_sel(v)) sel.push_back(v);
    n = (int)sel.size();
  }
  return sel;
}

// leaves of a selected vote set: members no other member descends from
static std::vector<int> quorum_leaves(const Dag& d, std::vector<int> sel) {
  std::vector<int> leaves;
  for (int v : sel) {
    bool has_child = false;
    for (int w : sel) {
      if (w == v) continue;
      auto cl = vote_closure(d, w);
      if (std::find(cl.begin(), cl.end(), v) != cl.end() && w != v) {
        has_child = true;
        break;
      }
    }
    if (!has_child) leaves.push_back(v);
  }
  // (depth desc, pow asc) — compare_votes_in_block
  std::sort(leaves.begin(), leaves.end(), [&](int a, int b) {
    if (d.blocks[a].work != d.blocks[b].work)
      return d.blocks[a].work > d.blocks[b].work;
    return d.blocks[a].pow_hash < d.blocks[b].pow_hash;
  });
  return leaves;
}

// longest-branch-first quorum (tailstorm.ml:271-313 altruistic_quorum):
// candidates sorted by (depth desc, own first, first-seen asc), each
// candidate's fresh closure joins iff the quorum still fits; succeeds
// only when exactly q votes assemble (and >= q candidates existed).
static std::vector<int> altruistic_quorum(Sim& s, const Dag& d,
                                          const std::vector<int>& cands,
                                          int me, int q) {
  if ((int)cands.size() < q) return {};
  std::vector<int> sorted = cands;
  std::sort(sorted.begin(), sorted.end(), [&](int a, int b) {
    if (d.blocks[a].work != d.blocks[b].work)
      return d.blocks[a].work > d.blocks[b].work;  // depth desc
    bool ma = d.blocks[a].miner == me, mb = d.blocks[b].miner == me;
    if (ma != mb) return ma;  // own first
    return s.seen_at(me, a) < s.seen_at(me, b);  // earlier-seen first
  });
  std::vector<int> sel;
  auto in_sel = [&](int v) {
    return std::find(sel.begin(), sel.end(), v) != sel.end();
  };
  int n = 0;
  for (int hd : sorted) {
    if (n == q) break;
    std::vector<int> fresh;
    for (int v : vote_closure(d, hd))
      if (!in_sel(v)) fresh.push_back(v);
    if (fresh.empty() || n + (int)fresh.size() > q) continue;
    for (int v : fresh) sel.push_back(v);
    n = (int)sel.size();
  }
  if (n != q) return {};
  return sel;
}

static long n_choose_k_capped(long n, long k, long cap) {
  if (k > n) return 0;
  long r = 1;
  for (long i = 1; i <= k; i++) {
    r = r * (n - k + i) / i;
    if (r > cap) return cap + 1;
  }
  return r;
}

// exhaustive reward-optimal quorum (tailstorm.ml:418-506): enumerate
// every size-q choice of the confirming votes in ascending id (= DAG
// partial) order, keep the closure-closed ones, score the draft's own
// reward under the incentive scheme, first maximum wins.  More than
// `max_options` combinations sets *fallback (the reference's 100-cap
// heuristic fallback, tailstorm.ml:426-428).  depth_plus/miner_share
// mirror the env scorer (cpr_tpu/envs/quorum.py quorum_optimal):
// tailstorm pays votes only with r = depth/k; stree pays (depth+1)/k
// and includes the block itself.
//
// Documented deviation: score TIES resolve in ascending-lexicographic
// combination order over the id-sorted candidate list (first maximum
// wins below), whereas the reference enumerates via
// Combinatorics.iter_n_choose_k, whose emission order follows the
// candidates' list order (visibility/insertion order).  When several
// quorums share the maximal reward the two engines can pick different
// (equally optimal) vote SETS, which later diverges tiebreak-sensitive
// trajectories; reward totals are unaffected.  The env-side scorer
// (quorum_optimal's static combo table) shares this tie order, so
// oracle-vs-env A/B runs stay aligned.
static std::vector<int> optimal_quorum(const Dag& d,
                                       const std::vector<int>& cands_in,
                                       int me, int q, bool discount,
                                       bool punish, int depth_plus,
                                       int miner_share, int k,
                                       bool* fallback) {
  *fallback = false;
  std::vector<int> cands = cands_in;
  std::sort(cands.begin(), cands.end());
  int n = (int)cands.size();
  if (n_choose_k_capped(n, q, 100) > 100) {
    *fallback = true;
    return {};
  }
  if (n < q || q < 1) return {};
  std::vector<int> idx(q);
  for (int i = 0; i < q; i++) idx[i] = i;
  std::vector<int> best;
  double best_score = -1.0;
  while (true) {
    // connectivity: every chosen vote's vote-parents must be chosen
    std::vector<char> chosen(n, 0);
    for (int i : idx) chosen[i] = 1;
    auto pos = [&](int v) {
      auto it = std::lower_bound(cands.begin(), cands.end(), v);
      return it != cands.end() && *it == v ? (int)(it - cands.begin())
                                           : -1;
    };
    bool ok = true;
    for (int i : idx) {
      for (int p : d.blocks[cands[i]].parents) {
        if (!d.blocks[p].is_vote) continue;
        int j = pos(p);
        if (j < 0 || !chosen[j]) {
          ok = false;
          break;
        }
      }
      if (!ok) break;
    }
    if (ok) {
      std::vector<int> sel;
      for (int i : idx) sel.push_back(cands[i]);
      std::vector<int> leaves = quorum_leaves(d, sel);
      int depth_first = leaves.empty() ? 0 : d.blocks[leaves[0]].work;
      double r = discount ? (double)(depth_first + depth_plus) / k : 1.0;
      std::vector<int> paid =
          punish && !leaves.empty() ? vote_closure(d, leaves[0]) : sel;
      int own = miner_share;
      for (int v : paid)
        if (d.blocks[v].miner == me) own++;
      double score = r * own;
      if (score > best_score) {
        best_score = score;
        best = sel;
      }
    }
    // next combination (lexicographic ascending)
    int i = q - 1;
    while (i >= 0 && idx[i] == n - q + i) i--;
    if (i < 0) break;
    idx[i]++;
    for (int j = i + 1; j < q; j++) idx[j] = idx[j - 1] + 1;
  }
  return best;
}

std::vector<int> ParallelBase::select_quorum(Sim& s, const Dag& d,
                                             const std::vector<int>& cands,
                                             int node, int q,
                                             bool discount, bool punish,
                                             int depth_plus,
                                             int miner_share) {
  if (selector == 1) return altruistic_quorum(s, d, cands, node, q);
  if (selector == 2) {
    bool fb = false;
    std::vector<int> sel =
        optimal_quorum(d, cands, node, q, discount, punish, depth_plus,
                       miner_share, k, &fb);
    if (!fb) return sel;
    // over the option cap: the reference falls back to the heuristic
  }
  return heuristic_quorum(d, cands, node, q);
}

struct Stree final : ParallelBase {
  // 0 constant, 1 discount, 2 punish, 3 hybrid, 4 block.
  // `block` is Tailstorm/ll June's extra scheme (the whole k to the
  // summary's miner, tailstorm_june.ml:177 constant_block) — the June
  // variant IS Stree's structure (PoW summaries carrying k-1
  // depth-labelled votes), kept by the reference to reproduce W&B run
  // 257 (tailstorm_june.ml:3-9); protocol key "tailstormjune" maps
  // here with the scheme menu extended.
  int scheme;
  Stree(int k_, int sch) : ParallelBase(k_), scheme(sch) {}

  Block genesis() const override { return Block{}; }

  Block draft(Sim& s, int node, int preferred) override {
    const Dag& d = s.dag;
    int pref = last_block(d, preferred);
    std::vector<int> cands = confirming(s, node, pref);
    std::vector<int> sel = select_quorum(
        s, d, cands, node, k - 1, scheme == 1 || scheme == 3,
        scheme == 2 || scheme == 3, /*depth_plus=*/1, /*miner_share=*/1);
    if (!sel.empty() || k == 1) {
      std::vector<int> leaves = quorum_leaves(d, sel);
      Block blk;
      blk.parents = {pref};
      blk.parents.insert(blk.parents.end(), leaves.begin(), leaves.end());
      blk.height = d.blocks[pref].height + 1;
      return blk;
    }
    // extend the deepest branch (stree.ml:497-511)
    int parent = pref, pd = 0;
    for (int c : cands) {
      if (d.blocks[c].work > pd ||
          (d.blocks[c].work == pd && parent != pref &&
           d.blocks[c].pow_hash < d.blocks[parent].pow_hash)) {
        parent = c;
        pd = d.blocks[c].work;
      }
    }
    Block v;
    v.parents = {parent};
    v.is_vote = true;
    v.vote_id = pref;
    v.work = pd + 1;  // depth
    v.height = d.blocks[pref].height;
    return v;
  }

  void rewards(const Dag& d, int head,
               std::vector<double>& per_miner) const override {
    if (scheme == 4) {  // june `Block: summary miner collects k
      for (int b = last_block(d, head); d.blocks[b].miner >= 0;
           b = last_block(d, d.blocks[b].parents[0]))
        per_miner[d.blocks[b].miner] += (double)k;
      return;
    }
    bool discount = scheme == 1 || scheme == 3;
    bool punish = scheme == 2 || scheme == 3;
    for (int b = last_block(d, head); d.blocks[b].miner >= 0;
         b = last_block(d, d.blocks[b].parents[0])) {
      const auto& blk = d.blocks[b];
      if (blk.parents.size() < 2) {  // k == 1: block only
        per_miner[blk.miner] += 1.0;
        continue;
      }
      int depth_first = d.blocks[blk.parents[1]].work;
      double r = discount ? (double)(depth_first + 1) / k : 1.0;
      per_miner[blk.miner] += r;
      std::vector<int> paid;
      if (punish) {
        paid = vote_closure(d, blk.parents[1]);
      } else {
        for (size_t i = 1; i < blk.parents.size(); i++)
          for (int v : vote_closure(d, blk.parents[i]))
            if (std::find(paid.begin(), paid.end(), v) == paid.end())
              paid.push_back(v);
      }
      for (int v : paid)
        if (d.blocks[v].miner >= 0) per_miner[d.blocks[v].miner] += r;
    }
  }
};

struct Tailstorm final : ParallelBase {
  int scheme;  // 0 constant, 1 discount, 2 punish, 3 hybrid
  Tailstorm(int k_, int sch) : ParallelBase(k_), scheme(sch) {}

  Block genesis() const override { return Block{}; }

  // every PoW is a vote on the deepest visible branch of the preferred
  // summary (tailstorm.ml puzzle_payload)
  Block draft(Sim& s, int node, int preferred) override {
    const Dag& d = s.dag;
    int pref = last_block(d, preferred);
    std::vector<int> cands = confirming(s, node, pref);
    int parent = pref, pd = 0;
    for (int c : cands) {
      if (d.blocks[c].work > pd ||
          (d.blocks[c].work == pd && parent != pref &&
           d.blocks[c].pow_hash < d.blocks[parent].pow_hash)) {
        parent = c;
        pd = d.blocks[c].work;
      }
    }
    Block v;
    v.parents = {parent};
    v.is_vote = true;
    v.vote_id = pref;
    v.work = pd + 1;
    v.height = d.blocks[pref].height;
    return v;
  }

  // learning a vote may enable the next summary (non-PoW append with
  // dedup, tailstorm.ml:565-608)
  std::vector<Block> proposals(Sim& s, int node, int x) override {
    const Dag& d = s.dag;
    if (!d.blocks[x].is_vote) return {};
    int summ = d.blocks[x].vote_id;
    int pref = last_block(d, s.preferred[node]);
    // only worthwhile when it can become the preferred tip
    if (d.blocks[summ].height + 1 < d.blocks[pref].height) return {};
    std::vector<int> cands = confirming(s, node, summ);
    std::vector<int> sel = select_quorum(
        s, d, cands, node, k, scheme == 1 || scheme == 3,
        scheme == 2 || scheme == 3, /*depth_plus=*/0, /*miner_share=*/0);
    if (sel.empty() && k > 0) return {};
    std::vector<int> leaves = quorum_leaves(d, sel);
    Block blk;
    blk.parents = leaves;  // summaries carry only their quorum leaves
    blk.height = d.blocks[summ].height + 1;
    blk.vote_id = -1;
    return {blk};
  }

  void rewards(const Dag& d, int head,
               std::vector<double>& per_miner) const override {
    bool discount = scheme == 1 || scheme == 3;
    bool punish = scheme == 2 || scheme == 3;
    for (int b = last_block(d, head);
         !d.blocks[b].parents.empty();
         b = last_block(d, d.blocks[b].parents[0])) {
      const auto& blk = d.blocks[b];
      int depth_first = d.blocks[blk.parents[0]].work;
      double r = discount ? (double)depth_first / k : 1.0;
      std::vector<int> paid;
      if (punish) {
        paid = vote_closure(d, blk.parents[0]);
      } else {
        for (int leaf : blk.parents)
          for (int v : vote_closure(d, leaf))
            if (std::find(paid.begin(), paid.end(), v) == paid.end())
              paid.push_back(v);
      }
      for (int v : paid)
        if (d.blocks[v].miner >= 0) per_miner[d.blocks[v].miner] += r;
    }
  }
};

struct Sdag final : ParallelBase {
  bool discount;
  Sdag(int k_, bool disc) : ParallelBase(k_), discount(disc) {}

  Block genesis() const override { return Block{}; }

  Block draft(Sim& s, int node, int preferred) override {
    const Dag& d = s.dag;
    int pref = last_block(d, preferred);
    std::vector<int> cands = confirming(s, node, pref);
    std::vector<int> sel = heuristic_quorum(d, cands, node, k - 1);
    if (!sel.empty() || k == 1) {
      std::vector<int> leaves = quorum_leaves(d, sel);
      Block blk;
      blk.parents = {pref};
      blk.parents.insert(blk.parents.end(), leaves.begin(), leaves.end());
      blk.height = d.blocks[pref].height + 1;
      return blk;
    }
    // another vote referencing the leaves of everything seen
    // (sdag.ml:366-396 `Partial)
    std::vector<int> leaves = quorum_leaves(d, cands);
    Block v;
    v.is_vote = true;
    v.vote_id = pref;
    v.work = (int)cands.size() + 1;  // vote number
    v.height = d.blocks[pref].height;
    if (leaves.empty())
      v.parents = {pref};
    else
      v.parents = leaves;
    return v;
  }

  void rewards(const Dag& d, int head,
               std::vector<double>& per_miner) const override {
    for (int b = last_block(d, head); d.blocks[b].miner >= 0;
         b = last_block(d, d.blocks[b].parents[0])) {
      const auto& blk = d.blocks[b];
      per_miner[blk.miner] += 1.0;  // block share c = 1 (sdag.ml reward')
      std::vector<int> cv;
      for (size_t i = 1; i < blk.parents.size(); i++)
        for (int v : vote_closure(d, blk.parents[i]))
          if (std::find(cv.begin(), cv.end(), v) == cv.end())
            cv.push_back(v);
      for (int v : cv) {
        double r = 1.0;
        if (discount) {
          // fwd + bwd connectivity within the confirmed set
          // (sdag.ml reward': fwd counts descendants + the next block,
          // bwd counts ancestors)
          int bwd = 0, fwd = 0;
          auto anc = vote_closure(d, v);
          for (int w : cv) {
            if (w == v) continue;
            auto wanc = vote_closure(d, w);
            bool v_in_w = std::find(wanc.begin(), wanc.end(), v) != wanc.end();
            bool w_in_v = std::find(anc.begin(), anc.end(), w) != anc.end();
            if (v_in_w) fwd++;
            if (w_in_v) bwd++;
          }
          fwd += 1;  // the hypothetical next block
          r = (double)(fwd + bwd) / (k - 1);
        }
        if (d.blocks[v].miner >= 0) per_miner[d.blocks[v].miner] += r;
      }
    }
  }
};

// ------------------------------------------- nakamoto withholding agent

// Clean-room SSZ'16 state machine (nakamoto_ssz.ml:156-350): the attacker
// (node 0) tracks a private tip and a simulated defender ("public") view;
// a policy maps {public_blocks, private_blocks, diff_blocks, event} to
// Adopt/Override/Match/Wait.
struct NakAgent final : Agent {
  // policy: 0 honest, 1 eyal-sirer-2014, 2 sapirshtein-2016-sm1


  int act(int pub_blocks, int priv_blocks, bool pow_event) const {
    (void)pow_event;
    enum { ADOPT, OVERRIDE, MATCH, WAIT };
    int h = pub_blocks, a = priv_blocks;
    switch (policy) {
      case 0:  // honest
        return a > h ? OVERRIDE : (a < h ? ADOPT : WAIT);
      case 1:  // ES'14 (nakamoto_ssz.ml:295-320)
        if (a < h) return ADOPT;
        if (h == 0 && a == 1) return WAIT;
        if (h == 1 && a == 1) return MATCH;
        if (h == 1 && a == 2) return OVERRIDE;
        if (h > 0) return (a - h == 1) ? OVERRIDE : MATCH;
        return WAIT;
      default:  // SM1 (nakamoto_ssz.ml:325-341)
        if (h > a) return ADOPT;
        if (h == 1 && a == 1) return MATCH;
        if (h == a - 1 && h >= 1) return OVERRIDE;
        return WAIT;
    }
  }

  // returns blocks to share; updates priv/pub
  std::vector<int> handle(Sim& s, int b, bool is_pow) override {
    Dag& d = s.dag;
    if (is_pow)
      priv = b;  // mined on private chain
    else if (d.blocks[b].height > d.blocks[pub].height)
      pub = b;  // simulated defender follows longest chain
    int ca = d.blocks[common_anc(d, pub, priv)].height;
    int pub_blocks = d.blocks[pub].height - ca;
    int priv_blocks = d.blocks[priv].height - ca;
    enum { ADOPT, OVERRIDE, MATCH, WAIT };
    int a = act(pub_blocks, priv_blocks, is_pow);
    std::vector<int> share;
    if (a == ADOPT) {
      priv = pub;
    } else if (a == OVERRIDE || a == MATCH) {
      int target = d.blocks[pub].height + (a == OVERRIDE ? 1 : 0);
      int x = priv;
      while (d.blocks[x].height > target) x = d.blocks[x].parents[0];
      share.push_back(x);
      // releasing updates the simulated defender model at next event via
      // pending messages; model it immediately like prepare() would
      if (d.blocks[x].height > d.blocks[pub].height) pub = x;
    }
    return share;
  }
};

// ------------------------------------------- ethereum withholding agent

// Clean-room FN'19-style state machine (ethereum_ssz.ml:172-221 actions,
// :444-538 policies; same semantics as cpr_tpu/envs/ethereum.py): the
// attacker withholds a private uncle-bearing chain, adopts / overrides /
// matches by the preset's preference key, and steers which uncles its
// own drafts include (the Sim::atk_mine_* hook).
struct EthAgent final : Agent {
  // policy: 0 honest, 1 fn19 (adopt-discard, all uncles),
  //         2 fn19pkel (adopt-release, own uncles only)
  bool byzantium = true;  // preference: byzantium height, whitepaper work

  int pkey(const Dag& d, int b) const {
    return byzantium ? d.blocks[b].height : d.blocks[b].work;
  }

  std::vector<int> handle(Sim& s, int b, bool is_pow) override {
    Dag& d = s.dag;
    if (is_pow)
      priv = b;
    else if (pkey(d, b) > pkey(d, pub))
      pub = b;  // defenders follow strict preference improvement
    int ca = common_anc(d, pub, priv);
    int ph = d.blocks[pub].height - d.blocks[ca].height;
    int ah = d.blocks[priv].height - d.blocks[ca].height;

    enum { ADOPT_DISCARD, ADOPT_RELEASE, OVERRIDE, MATCH, RELEASE1, WAIT };
    int act;
    bool own = true, foreign = true;
    if (policy == 0) {  // honest: behind on work -> adopt, else release
      int pw = d.blocks[pub].work - d.blocks[ca].work;
      act = pw > 0 ? ADOPT_RELEASE : OVERRIDE;
    } else {  // fn19 / fn19pkel (ethereum_ssz.ml:505-538)
      int adopt = policy == 1 ? ADOPT_DISCARD : ADOPT_RELEASE;
      if (policy == 2) foreign = false;  // OWN_ONLY uncle rule
      if (is_pow)
        act = (ah == 2 && ph == 1) ? OVERRIDE : WAIT;
      else if (ah < ph)
        act = adopt;
      else if (ah == ph)
        act = MATCH;
      else if (ah == ph + 1)
        act = OVERRIDE;
      else
        act = RELEASE1;
    }
    s.atk_mine_own = own;
    s.atk_mine_foreign = foreign;

    std::vector<int> share;
    if (act == ADOPT_DISCARD) {
      priv = pub;
    } else if (act == ADOPT_RELEASE) {
      if (priv != pub) share.push_back(priv);
      priv = pub;
    } else if (act == OVERRIDE || act == MATCH || act == RELEASE1) {
      // release_upto: first block back from priv with pref <= target
      // (ethereum_ssz.ml:404-412).  Under the work-keyed whitepaper
      // preference the walk can step BELOW the target (work jumps by
      // 1+uncles) and release an already-public block — a deliberate
      // no-op with exactly the reference's stop rule; the JAX env
      // documents the same behavior (envs/ethereum.py _release_upto)
      int target = act == OVERRIDE ? pkey(d, pub) + 1
                   : act == MATCH  ? pkey(d, pub)
                                   : pkey(d, ca) + 1;
      int x = priv;
      while (pkey(d, x) > target && d.blocks[x].miner >= 0)
        x = d.blocks[x].parents[0];
      share.push_back(x);
      if (pkey(d, x) > pkey(d, pub)) pub = x;
    }
    return share;
  }
};

// ------------------------------------------------- bk withholding agent

// Vote-withholding attacker for the Bk family (bk_ssz.ml:265-331 apply,
// :346-404 policies; same semantics as cpr_tpu/envs/bk.py, Proceed
// variants): the attacker mines votes on a private chain, assembles
// private proposals through the protocol's own quorum logic, and on
// Override releases the private block at the target height plus just
// enough withheld votes to flip the defenders' preference.
struct BkAgent final : Agent {
  // policy: 0 honest, 1 get-ahead,
  //         2 get-ahead + gym-style Append interactions (the agent
  //           re-runs its action logic right after appending a
  //           proposal, at unchanged simulation time — the reference
  //           gym engine's `Append` event granularity)
  int k = 1;

  // the release machinery shares withheld ancestors implicitly (quorum
  // votes inside a released proposal); count them in-flight too
  void note_sent(Sim& s, int b) override {
    mark_sent(b, s.dag.blocks.size());
  }
  // no sent_already override: this agent pre-marks its share list so
  // pub_better() sees just-released votes as public; the prune would
  // then cancel the send itself.  Harmless duplicate re-sends are
  // deduped by the receivers' `known` set.

  int public_votes_on(Sim& s, int b) {
    int n = 0;
    for (int c : s.dag.blocks[b].children)
      if (s.dag.blocks[c].is_vote && is_public(s, c)) n++;
    return n;
  }

  // defender-eye preference (height, public votes, -leader hash)
  bool pub_better(Sim& s, int a, int b) {
    const Dag& d = s.dag;
    if (d.blocks[a].height != d.blocks[b].height)
      return d.blocks[a].height > d.blocks[b].height;
    int va = public_votes_on(s, a), vb = public_votes_on(s, b);
    if (va != vb) return va > vb;
    auto lh = [&](int blk) {
      if (d.blocks[blk].parents.size() >= 2)
        return d.blocks[d.blocks[blk].parents[1]].pow_hash;
      return 2.0;
    };
    return lh(a) < lh(b);
  }

  std::vector<int> handle(Sim& s, int b, bool is_pow) override {
    Dag& d = s.dag;
    if (!is_pow) {
      int cand = d.blocks[b].is_vote ? d.blocks[b].parents[0] : b;
      if (pub_better(s, cand, pub)) pub = cand;
      // defender proposals can also beat the private tip outright
      if (d.blocks[cand].height > d.blocks[priv].height) priv = cand;
    }

    std::vector<int> share;
    // policy 2 re-runs the action after appending its own proposal —
    // the gym engine's `Append` interaction at unchanged sim time; 1+k
    // bounds the cascade (one proposal can complete per quorum height)
    int rounds = policy == 2 ? 1 + k : 1;
    for (int round = 0; round < rounds; round++) {
      int ca = common_anc(d, pub, priv);
      int pub_b = d.blocks[pub].height - d.blocks[ca].height;
      int priv_b = d.blocks[priv].height - d.blocks[ca].height;

      enum { ADOPT, OVERRIDE, WAIT };
      int act;
      if (policy == 0)  // honest (bk_ssz.ml:349-352)
        act = pub_b > priv_b ? ADOPT : OVERRIDE;
      else  // get-ahead (bk_ssz.ml:354-360)
        act = pub_b > priv_b ? ADOPT : (pub_b < priv_b ? OVERRIDE : WAIT);

      if (act == ADOPT) {
        priv = pub;
      } else if (act == OVERRIDE) {
        // release targeting (bk_ssz.ml:271-283)
        int nv_pub = public_votes_on(s, pub);
        int tgt_h = d.blocks[pub].height + (nv_pub >= k ? 1 : 0);
        int tgt_v = nv_pub >= k ? 0 : nv_pub + 1;
        int blk = priv;
        while (d.blocks[blk].height > tgt_h && d.blocks[blk].miner >= 0)
          blk = d.blocks[blk].parents[0];
        int rel = blk;
        if (tgt_v >= k) {  // prefer an existing proposal child
          for (int c : d.blocks[blk].children)
            if (!d.blocks[c].is_vote) {
              rel = c;
              tgt_v = 0;
              break;
            }
        }
        share.push_back(rel);
        // + earliest-seen withheld votes on the released block
        std::vector<int> held;
        for (int c : d.blocks[rel].children)
          if (d.blocks[c].is_vote && !is_public(s, c)) held.push_back(c);
        std::stable_sort(held.begin(), held.end(), [&](int a, int c) {
          return d.blocks[a].time < d.blocks[c].time;
        });
        int public_already = public_votes_on(s, rel);
        int taken = 0;
        for (int i = 0; i < (int)held.size() && public_already + taken < tgt_v;
             i++, taken++)
          share.push_back(held[i]);
        for (int y : share) mark_sent(y, d.blocks.size());
        if (pub_better(s, rel, pub)) pub = rel;
      }
      // one attacker proposal attempt per interaction on the
      // (post-action) private tip, like the env's append_proposal at the
      // end of _apply — a defender vote can complete an attacker-led
      // quorum, so this must run on every event, not just own PoW
      // (Proceed's inclusive vote filter == node-0 visibility)
      bool appended = false;
      for (Block& prop : s.proto->proposals(s, 0, priv)) {
        int id = s.append_plain(0, std::move(prop));
        if (!s.is_visible(0, id)) {
          s.mark_visible(0, id);
          s.unlock_children(0, id);
        }
        if (d.blocks[id].height > d.blocks[priv].height) {
          priv = id;
          appended = true;
        }
      }
      if (!appended) break;
    }
    return share;
  }
};

// ---------------------------------- parallel-family withholding agent

// One agent for the whole parallel-PoW family (spar/stree/tailstorm/
// sdag).  Clean-room port of the shared SSZ attack-space shape: the
// spar-specialized release targeting of spar_ssz.ml:255-295 is a
// special case of the generic release used by the tree/DAG variants
// (stree_ssz.ml:272-295, tailstorm_ssz.ml:292-315, sdag_ssz.ml:252-275)
// — scan the withheld descendants of the common ancestor in append
// order, accumulating until the simulated defender head (vote filter =
// public ∪ released-so-far) flips to the attacker's chain: Override
// releases just enough to flip, Match one item short of flipping, and
// if nothing flips, release everything.  Policies mirror
// cpr_tpu/envs/{spar,stree,sdag,tailstorm}.py's jittable policy sets.
struct ParAgent final : Agent {
  // policy: 0 honest, 1 selfish (spar_ssz.ml:340-351),
  //         2 minor-delay (stree_ssz.ml:377-384 shape, shared by
  //           stree/sdag/tailstorm), 3 get-ahead (tailstorm_ssz.ml),
  //         4 honest-tailstorm (adopt only when strictly behind),
  //         5 avoid-loss (confirmed-work compare + Match race)
  int k = 2;

  void note_sent(Sim& s, int b) override {
    mark_sent(b, s.dag.blocks.size());
  }
  bool sent_already(int b) const override {
    return b < (int)sent.size() && sent[b];
  }

  static int last_block(const Dag& d, int x) {
    return ParallelBase::last_block(d, x);  // shared chain-walk invariant
  }
  // chain predecessor of a block; handles tailstorm summaries whose
  // parents are quorum-leaf votes rather than the previous summary
  static int pred(const Dag& d, int b) {
    if (d.blocks[b].parents.empty()) return b;  // genesis
    return last_block(d, d.blocks[b].parents[0]);
  }
  static int block_common_anc(const Dag& d, int a, int b) {
    while (a != b) {
      if (d.blocks[a].parents.empty() || d.blocks[b].parents.empty())
        return 0;  // genesis
      if (d.blocks[a].height >= d.blocks[b].height)
        a = pred(d, a);
      else
        b = pred(d, b);
    }
    return a;
  }
  // does x's chain run through ca?
  static bool on_chain_of(const Dag& d, int x, int ca) {
    int b = last_block(d, x);
    while (d.blocks[b].height > d.blocks[ca].height) b = pred(d, b);
    return b == ca;
  }

  // votes confirming `b` that pass `filt` (public ∪ released set)
  int filtered_votes(Sim& s, int b, const std::vector<char>& in_rel) {
    int n = 0;
    for (int i : ParallelBase::confirmer_ids(s, b))
      if (is_public(s, i) || (i < (int)in_rel.size() && in_rel[i]))
        n++;
    return n;
  }
  // defenders' update_head under the filter: strictly better by
  // (height, confirming votes); the incumbent wins ties
  bool flips(Sim& s, int cand, const std::vector<char>& in_rel) {
    const Dag& d = s.dag;
    if (cand == pub) return false;
    if (d.blocks[cand].height != d.blocks[pub].height)
      return d.blocks[cand].height > d.blocks[pub].height;
    return filtered_votes(s, cand, in_rel) >
           filtered_votes(s, pub, in_rel);
  }

  // generic release scan (see header comment); kind 0 Match, 1 Override
  std::vector<int> release(Sim& s, int ca, int kind) {
    const Dag& d = s.dag;
    std::vector<int> rel;
    std::vector<char> in_rel(d.blocks.size(), 0);
    // ids are topological, so everything descending from ca was
    // appended after it — skip the public prefix (verified: a debug
    // audit over long runs finds no releasable id <= ca)
    for (int x = ca + 1; x < (int)d.blocks.size(); x++) {
      if (d.blocks[x].miner < 0 || is_public(s, x)) continue;
      if (!s.is_visible(0, x)) continue;  // not ours / not seen yet
      if (!on_chain_of(d, x, ca)) continue;
      rel.push_back(x);
      in_rel[x] = 1;
      int cand = last_block(d, x);
      if (flips(s, cand, in_rel)) {
        if (kind == 0) {  // Match: maximal non-flipping prefix
          rel.pop_back();
          return rel;
        }
        pub = cand;  // Override lands at the next prepare; model it now
        return rel;
      }
    }
    return rel;  // nothing flips: release everything (the SSZ fallback)
  }

  std::vector<int> handle(Sim& s, int b, bool is_pow) override {
    Dag& d = s.dag;
    if (is_pow) {
      // prepare on ProofOfWork: work on the private chain
      // (spar_ssz.ml:210-214) — a freshly mined block advances the
      // private tip; a vote confirms it and leaves the tip in place
      priv = last_block(d, b);
    } else {
      // prepare on Network: simulate the defenders' update_head over
      // the public view
      int cand = last_block(d, b);
      std::vector<char> none;
      if (flips(s, cand, none)) pub = cand;
    }
    int ca = block_common_anc(d, pub, priv);
    int pub_b = d.blocks[pub].height - d.blocks[ca].height;
    int priv_b = d.blocks[priv].height - d.blocks[ca].height;
    // observation vote counts (spar_ssz.ml:226-239): public votes on
    // the defender tip; node-0-visible (inclusive) votes on the private
    // tip
    std::vector<char> none;
    int pub_v = filtered_votes(s, pub, none);
    int priv_vi = 0;
    for (int i : ParallelBase::confirmer_ids(s, priv))
      if (s.is_visible(0, i))
        priv_vi++;

    enum { ADOPT, OVERRIDE, MATCH, WAIT };
    int act;
    bool prolong = false;
    switch (policy) {
      case 1:  // spar selfish (spar_ssz.ml:340-351)
        if (priv_b < pub_b) act = ADOPT;
        else if (priv_b == 0 && pub_b == 0) { act = WAIT; prolong = true; }
        else if (pub_b == 0) act = WAIT;
        else act = OVERRIDE;
        break;
      case 2:  // minor-delay (stree/sdag/tailstorm)
        if (pub_b > priv_b) act = ADOPT;
        else if (pub_b == 0) act = WAIT;
        else act = OVERRIDE;
        break;
      case 3:  // tailstorm get-ahead
        if (pub_b > priv_b) act = ADOPT;
        else if (pub_b < priv_b) act = OVERRIDE;
        else act = WAIT;
        break;
      case 4:  // tailstorm honest: adopt only when strictly behind
        act = pub_b > priv_b ? ADOPT : OVERRIDE;
        break;
      case 5: {  // avoid-loss (stree/sdag/tailstorm envs): compare
        // total confirmed work, Match the defender head on a one-block
        // tie to arm the gamma race
        int hp = pub_b * k + pub_v, ap = priv_b * k + priv_vi;
        if (pub_b == 0) act = WAIT;
        else if (pub_b == 1 && hp == ap) act = MATCH;
        else if (hp > ap) act = ADOPT;
        else if (hp == ap - 1) act = OVERRIDE;
        else if (pub_b < priv_b - 10) act = OVERRIDE;
        else act = WAIT;
        break;
      }
      default:  // honest (spar/stree/sdag): adopt any public progress
        act = pub_b > 0 ? ADOPT : OVERRIDE;
        break;
    }
    s.atk_vote_own_only = prolong;

    std::vector<int> share;
    if (act == ADOPT) {
      priv = pub;
    } else if (act == OVERRIDE || act == MATCH) {
      // the release machinery's note_sent marks each item as it is
      // actually sent — don't pre-mark, or sent_already() would prune
      // the send itself
      share = release(s, ca, act == OVERRIDE ? 1 : 0);
    }
    // private summary assembly (tailstorm only: proposals are non-PoW
    // appends the attacker keeps to itself until released; the quorum
    // uses node-0 visibility like the env's inclusive Proceed filter)
    s.preferred[0] = priv;
    for (Block& prop : s.proto->proposals(s, 0, b)) {
      int id = s.append_plain(0, std::move(prop));
      if (!s.is_visible(0, id)) {
        s.mark_visible(0, id);
        s.unlock_children(0, id);
      }
      if (d.blocks[id].height > d.blocks[priv].height) priv = id;
    }
    return share;
  }
};

// -------------------------------------------------------- sim internals

void Sim::flush_batch(int node) {
  // apply the buffered preference updates in arrival order, then run
  // the honest handler ONCE (the env collapse's
  // one-proposal-per-delivery-batch semantics).  Items that became
  // visible through the proposal-dedup path were handled at dedup
  // time and are not buffered here.
  if (node >= (int)batch_pending.size() || batch_pending[node] <= 0)
    return;
  batch_pending[node] = 0;
  if (batch_items[node].empty()) return;
  int last = batch_items[node].back();
  for (int x : batch_items[node])
    preferred[node] = proto->prefer(*this, node, preferred[node], x);
  batch_items[node].clear();
  handle_honest(node, last);
}

bool Sim::batch_complete(int node) const {
  // completeness by VISIBILITY, not by a delivery counter: a batch
  // block can become visible through the proposal-dedup path
  // (unlock_children's re-derivation scenario), whose queued delivery
  // event then early-returns without ever decrementing a counter
  for (int y = 0; y < (int)in_batch.size(); y++)
    if (in_batch[y] && !is_visible(node, y)) return false;
  return true;
}

void Sim::deliver(int node, int b) {
  if (is_visible(node, b)) {
    // a deduped batch item's queued delivery still advances the batch
    if (atomic_release && node != 0 && batch_complete(node))
      flush_batch(node);
    return;
  }
  mark_visible(node, b);
  record(3, node, b);
  if (flooding && dag.blocks[b].miner != node) send(node, b);
  if (node == 0 && agent) {
    handle_agent(b, false);
  } else if (atomic_release && node < (int)batch_pending.size()
             && batch_pending[node] > 0 && b < (int)in_batch.size()
             && in_batch[b]) {
    batch_items[node].push_back(b);
    if (batch_complete(node)) flush_batch(node);
  } else {
    handle_honest(node, b);
  }
  unlock_children(node, b);
}

// unlock buffered children (dependency-ordered delivery,
// simulator.ml:424-450); snapshot the child list first — recursive
// delivery can append proposal blocks, growing dag.blocks and the
// children vector under a live iterator.  Called wherever a block
// becomes visible: normal delivery AND the proposal-dedup path, where a
// node independently assembles a block it had only buffered children of
// (an attacker's withheld summary re-derived by a defender).
void Sim::unlock_children(int node, int b) {
  std::vector<int> kids = dag.blocks[b].children;
  for (int c : kids) {
    if (c < (int)known[node].size() && known[node][c] &&
        !is_visible(node, c) && parents_visible(node, c))
      deliver(node, c);
  }
}

void Sim::handle_honest(int node, int b) {
  preferred[node] = proto->prefer(*this, node, preferred[node], b);
  for (Block& prop : proto->proposals(*this, node, b)) {
    int id = append_plain(node, std::move(prop));
    if (!is_visible(node, id)) {
      mark_visible(node, id);
      send(node, id);
      preferred[node] = proto->prefer(*this, node, preferred[node], id);
      unlock_children(node, id);
    }
  }
}

void Sim::handle_agent(int b, bool is_pow) {
  for (int x : agent->handle(*this, b, is_pow)) {
    // release x and its withheld ancestry over ALL parent slots —
    // uncle references too, or defenders would buffer the released
    // block forever (recursive share of withheld ancestors,
    // simulator.ml:401-419); a non-withheld block's ancestry is
    // already public, so the walk prunes there
    std::vector<int> stack{x}, rel;
    while (!stack.empty()) {
      int y = stack.back();
      stack.pop_back();
      if (y < 0 || dag.blocks[y].miner < 0) continue;
      bool withheld = false;
      for (int n = 1; n < n_nodes; n++)
        if (!is_visible(n, y)) withheld = true;
      if (!withheld || agent->sent_already(y)) continue;
      if (std::find(rel.begin(), rel.end(), y) != rel.end()) continue;
      rel.push_back(y);
      for (int p : dag.blocks[y].parents) stack.push_back(p);
    }
    std::sort(rel.begin(), rel.end());  // ids are topological
    if (atomic_release && !rel.empty()) {
      // a new release while a previous batch is still in flight
      // (delayed topologies) must not drop buffered handling — flush
      // each node's old batch first
      for (int n = 1; n < n_nodes; n++) flush_batch(n);
      // register the batch before the sends: per node, the honest
      // handler waits until every batch item is visible
      in_batch.assign(dag.blocks.size(), 0);
      for (int y : rel) in_batch[y] = 1;
      batch_pending.assign(n_nodes, 0);
      batch_items.assign(n_nodes, {});
      for (int n = 1; n < n_nodes; n++)
        for (int y : rel)
          if (!is_visible(n, y)) batch_pending[n]++;
    }
    for (int y : rel) {
      agent->note_sent(*this, y);
      send(0, y);
    }
  }
  preferred[0] = agent->priv;
}

void Sim::step_event() {
  Event e = queue.top();
  queue.pop();
  now = e.time;
  if (e.type == 0) {  // activation
    activations++;
    int m = sample_miner();
    int pref = (m == 0 && agent) ? agent->priv : preferred[m];
    Block d = proto->draft(*this, m, pref);
    if (!d.is_vote && d.height == 0)
      d.height = dag.blocks[d.parents[0]].height + 1;  // nakamoto fill-in
    int id = append_pow(m, std::move(d));
    record(0, m, id);
    mark_visible(m, id);
    if (m == 0 && agent) {
      handle_agent(id, true);  // agent decides whether to share
    } else {
      handle_honest(m, id);
      send(m, id);  // honest nodes share their blocks immediately
    }
    schedule_activation();
  } else {  // receive
    int node = e.node, b = e.block;
    if ((int)known[node].size() <= b)
      known[node].resize(dag.blocks.size(), 0);
    if (known[node][b]) return;  // duplicate receipt
    known[node][b] = 1;
    record(2, node, b);
    if (parents_visible(node, b))
      deliver(node, b);
    // else: buffered; unlocked when parents become visible
  }
}

void Sim::run(long n_activations) {
  long target = activations + n_activations;
  while (activations < target && !queue.empty()) step_event();
  // drain in-flight messages so final metrics see a settled network
  while (!queue.empty()) {
    if (queue.top().type == 0) break;
    step_event();
  }
}

}  // namespace

// ------------------------------------------------------------- C API

extern "C" {

struct Handle {
  Sim sim;
};

void* cpr_oracle_create(const char* protocol, int k, const char* scheme,
                        const char* topology, int n_nodes, double alpha,
                        double gamma, int defenders,
                        double activation_delay, double propagation_delay,
                        const char* attacker_policy, uint64_t seed) {
  auto* h = new Handle();
  Sim& s = h->sim;
  s.rng.seed(seed);
  s.activation_delay = activation_delay;

  std::string proto(protocol), topo(topology), sch(scheme ? scheme : "");
  // the scheme string may carry a sub-block selector suffix
  // ("discount:optimal"); default heuristic (oracle parity with the
  // env registry's tailstorm/stree selector option)
  int selector = 0;
  {
    auto pos = sch.find(':');
    if (pos != std::string::npos) {
      std::string sel = sch.substr(pos + 1);
      sch = sch.substr(0, pos);
      selector = sel == "altruistic" ? 1 : sel == "optimal" ? 2 : 0;
    }
  }
  if (proto == "nakamoto") {
    s.proto.reset(new Nakamoto());
  } else if (proto == "ethereum-whitepaper") {
    s.proto.reset(new Ethereum(false));
  } else if (proto == "ethereum-byzantium") {
    s.proto.reset(new Ethereum(true));
  } else if (proto == "bk") {
    s.proto.reset(new Bk(k, sch == "block"));
  } else if (proto == "spar") {
    s.proto.reset(new Spar(k, sch == "block"));
  } else if (proto == "stree" || proto == "tailstorm" ||
             proto == "tailstormjune") {
    int scheme = sch == "discount" ? 1 : sch == "punish" ? 2
                 : sch == "hybrid" ? 3
                 : sch == "block" ? 4 : 0;
    ParallelBase* p;
    if (proto == "tailstorm")
      p = new Tailstorm(k, scheme);
    else  // stree; tailstormjune IS stree's structure + the block
          // scheme (tailstorm_june.ml:3-9, see Stree::scheme)
      p = new Stree(k, scheme);
    p->selector = selector;
    s.proto.reset(p);
  } else if (proto == "sdag") {
    s.proto.reset(new Sdag(k, sch == "discount"));
  } else {
    delete h;
    return nullptr;
  }

  if (topo == "clique") {
    s.n_nodes = n_nodes;
    s.compute.assign(n_nodes, 1.0 / n_nodes);
    s.delay.assign(n_nodes, std::vector<double>(n_nodes,
                                                propagation_delay));
  } else if (topo == "two_agents") {
    s.n_nodes = 2;
    s.compute = {alpha, 1.0 - alpha};
    s.delay.assign(2, std::vector<double>(2, 0.0));
  } else if (topo == "selfish_mining") {
    // network.ml:61-105: attacker node 0; defenders split 1-alpha;
    // attacker->defender delays uniform in [0, (d-1)/d * prop/gamma]
    // emulate gamma; defender->attacker is instant.
    int d = defenders >= 2 ? defenders : 2;
    s.n_nodes = d + 1;
    s.compute.assign(d + 1, (1.0 - alpha) / d);
    s.compute[0] = alpha;
    s.delay.assign(d + 1, std::vector<double>(d + 1, propagation_delay));
    // gamma = 0 exactly would make the delay bound infinite, so that even
    // Override releases never arrive — a degenerate corner of the
    // delay-based emulation (the SSZ'16 model it emulates has overrides
    // succeed at any gamma; gamma only decides Match races).  Flooring
    // gamma keeps match races ~always lost while overrides still deliver.
    double g = gamma > 1e-6 ? gamma : 1e-6;
    s.attacker_delay_upper = (double)(d - 1) / d * propagation_delay / g;
    for (int j = 0; j <= d; j++) {
      s.delay[0][j] = -1.0;  // sentinel: sample uniform
      s.delay[j][0] = 0.0;
    }
  } else {
    delete h;
    return nullptr;
  }

  std::string pol(attacker_policy ? attacker_policy : "");
  if (!pol.empty() && pol != "none") {
    if (proto == "nakamoto") {
      s.agent.reset(new NakAgent());
      s.agent->policy = pol == "honest" ? 0
                        : pol == "eyal-sirer-2014" ? 1
                        : pol == "sapirshtein-2016-sm1" ? 2 : -1;
    } else if (proto == "ethereum-whitepaper" ||
               proto == "ethereum-byzantium") {
      auto* a = new EthAgent();
      a->byzantium = proto == "ethereum-byzantium";
      s.agent.reset(a);
      s.agent->policy = pol == "honest" ? 0
                        : pol == "fn19" ? 1
                        : pol == "fn19pkel" ? 2 : -1;
    } else if (proto == "bk") {
      auto* a = new BkAgent();
      a->k = k;
      s.agent.reset(a);
      // "-appendint": gym-engine interaction granularity — the agent
      // re-acts immediately after appending its own proposal (the
      // engine's `Append` interaction, engine.ml:97-273), instead of
      // waiting for the next simulation event.  Used by the
      // gym-vs-simulator deviation decomposition
      // (tools/bk_gap_decompose.py), not a reference behavior.
      s.agent->policy = pol == "honest"              ? 0
                        : pol == "get-ahead"         ? 1
                        : pol == "get-ahead-appendint" ? 2
                        : pol == "get-ahead-atomicrel" ? 3
                                                     : -1;
      // the atomic-release graft (see Sim::atomic_release): policy 3
      // is get-ahead with env-collapse delivery-batch semantics
      if (s.agent->policy == 3) s.atomic_release = true;
    } else if (proto == "spar" || proto == "stree" ||
               proto == "tailstorm" || proto == "sdag" ||
               proto == "tailstormjune") {
      auto* a = new ParAgent();
      a->k = k;
      s.agent.reset(a);
      if (proto == "spar")
        s.agent->policy = pol == "honest" ? 0 : pol == "selfish" ? 1 : -1;
      else if (proto == "tailstorm")
        s.agent->policy = pol == "honest" ? 4
                          : pol == "minor-delay" ? 2
                          : pol == "get-ahead" ? 3
                          : pol == "avoid-loss" ? 5 : -1;
      else  // stree, sdag
        s.agent->policy = pol == "honest" ? 0
                          : pol == "minor-delay" ? 2
                          : pol == "avoid-loss" ? 5 : -1;
    } else {
      delete h;
      return nullptr;  // no withholding agent for this protocol
    }
    if (s.agent->policy < 0) {
      delete h;
      return nullptr;  // unknown policy name for this protocol
    }
  }

  s.init();
  if (s.agent) s.agent->init(0);
  return h;
}

long cpr_oracle_run(void* hp, long activations) {
  auto* h = static_cast<Handle*>(hp);
  h->sim.run(activations);
  return h->sim.activations;
}

// metrics: 0 reward_of(arg) | 1 progress | 2 sim_time | 3 n_blocks |
// 4 head_height | 5 on_chain | 6 head_time
double cpr_oracle_metric(void* hp, int what, int arg) {
  auto* h = static_cast<Handle*>(hp);
  Sim& s = h->sim;
  int head = s.proto->winner(s, s.preferred);
  switch (what) {
    case 0: {
      std::vector<double> per(s.n_nodes, 0.0);
      s.proto->rewards(s.dag, head, per);
      return (arg >= 0 && arg < s.n_nodes) ? per[arg] : 0.0;
    }
    case 1:
      return s.proto->progress(s.dag, head);
    case 2:
      return s.now;
    case 3:
      return (double)s.dag.blocks.size() - 1;  // exclude genesis
    case 4:
      return (double)s.dag.blocks[head].height;
    case 5:
      return (double)s.proto->on_chain(s.dag, head);
    case 6:
      return s.dag.blocks[head].time;
    case 7: {  // preferred height of node `arg` (diagnostics)
      if (arg < 0 || arg >= s.n_nodes) return std::nan("");
      int p = (arg == 0 && s.agent) ? s.agent->priv : s.preferred[arg];
      return (double)s.dag.blocks[p].height;
    }
    case 8:  // causal trace hit its cap; exported traces are incomplete
      return s.trace_truncated ? 1.0 : 0.0;
    case 10: {  // diagnostics: blocks node `arg` knows but can't deliver
      if (arg < 0 || arg >= s.n_nodes) return std::nan("");
      long n = 0;
      for (int b = 0; b < (int)s.dag.blocks.size(); b++)
        if (b < (int)s.known[arg].size() && s.known[arg][b] &&
            !s.is_visible(arg, b))
          n++;
      return (double)n;
    }
    case 11: {  // diagnostics: lowest such stuck block id (-1: none)
      if (arg < 0 || arg >= s.n_nodes) return std::nan("");
      for (int b = 0; b < (int)s.dag.blocks.size(); b++)
        if (b < (int)s.known[arg].size() && s.known[arg][b] &&
            !s.is_visible(arg, b))
          return (double)b;
      return -1.0;
    }
    case 9: {  // activations_of(arg): PoW successes won by node `arg`
      // (csv_runner.ml:77 exports sim.activations per node; every
      // activation mints exactly one pow block, so counting mined pow
      // blocks reproduces that array without extra sim state)
      long n = 0;
      for (const auto& b : s.dag.blocks)
        if (b.miner == arg && b.pow_hash < 2.0) n++;
      return (double)n;
    }
    default:
      return std::nan("");
  }
}

// custom topology: per-node compute weights and per-link delay
// distributions (kind 0 constant, 1 uniform, 2 exponential), row-major
// n*n arrays.  Protocol/k/scheme as in cpr_oracle_create.
void* cpr_oracle_create_custom(const char* protocol, int k,
                               const char* scheme, int n_nodes,
                               const double* compute, const int* dkind,
                               const double* dp0, const double* dp1,
                               double activation_delay, int flooding,
                               uint64_t seed) {
  auto* h = static_cast<Handle*>(cpr_oracle_create(
      protocol, k, scheme, "clique", n_nodes, 0.0, 0.0, 2,
      activation_delay, 0.0, "none", seed));
  if (!h) return nullptr;
  Sim& s = h->sim;
  s.compute.assign(compute, compute + n_nodes);
  s.custom_links = true;
  s.flooding = flooding != 0;
  s.lkind.assign(dkind, dkind + n_nodes * n_nodes);
  s.lp0.assign(dp0, dp0 + n_nodes * n_nodes);
  s.lp1.assign(dp1, dp1 + n_nodes * n_nodes);
  return h;
}

long cpr_oracle_trace_len(void* hp) {
  return (long)static_cast<Handle*>(hp)->sim.trace.size();
}

// out4 = [time, kind, node, block]; kinds: 0 append, 1 share,
// 2 receive, 3 learn
void cpr_oracle_trace_get(void* hp, long i, double* out4) {
  auto& tr = static_cast<Handle*>(hp)->sim.trace;
  if (i < 0 || i >= (long)tr.size()) return;
  for (int j = 0; j < 4; j++) out4[j] = tr[i][j];
}

// out = [miner, height, is_vote, vote_id, time, n_parents]
void cpr_oracle_block(void* hp, int i, double* out6) {
  auto& d = static_cast<Handle*>(hp)->sim.dag;
  if (i < 0 || i >= (int)d.blocks.size()) return;
  const auto& b = d.blocks[i];
  out6[0] = b.miner;
  out6[1] = b.height;
  out6[2] = b.is_vote ? 1.0 : 0.0;
  out6[3] = b.vote_id;
  out6[4] = b.time;
  out6[5] = (double)b.parents.size();
}

int cpr_oracle_block_parent(void* hp, int i, int j) {
  auto& d = static_cast<Handle*>(hp)->sim.dag;
  if (i < 0 || i >= (int)d.blocks.size()) return -1;
  if (j < 0 || j >= (int)d.blocks[i].parents.size()) return -1;
  return d.blocks[i].parents[j];
}

void cpr_oracle_destroy(void* hp) { delete static_cast<Handle*>(hp); }

}  // extern "C"
