// Unit test for the oracle's three sub-block selectors
// (tailstorm.ml:271-313 altruistic, :329-380 heuristic, :418-506
// optimal): build crafted vote forests where the selections MUST
// differ, and check the own-reward ordering optimal >= heuristic >=
// altruistic on randomized forests — the property a silently
// suboptimal search would break.
//
// Build+run (tests/test_native_selectors.py drives this):
//   g++ -O1 -std=c++17 test_selectors.cpp -o test_selectors && ./test_selectors

#include "oracle.cpp"

#include <cstdio>

using std::vector;

namespace {

// a minimal Sim with per-node seen times for altruistic's sort
Sim make_sim(int n_nodes) {
  Sim s;
  s.n_nodes = n_nodes;
  s.visible.assign(n_nodes, {});
  s.known.assign(n_nodes, {});
  s.visible_at.assign(n_nodes, {});
  return s;
}

int add_vote(Sim& s, int parent, int depth, int miner, double hash,
             double t) {
  Block v;
  v.parents = {parent};
  v.is_vote = true;
  v.vote_id = 0;  // confirms the genesis summary
  v.work = depth;
  v.miner = miner;
  v.pow_hash = hash;
  s.now = t;
  int id = s.dag.add(v);
  for (int n = 0; n < s.n_nodes; n++) s.mark_visible(n, id);
  return id;
}

double own_reward(const Dag& d, const vector<int>& sel, int me,
                  bool discount, bool punish, int depth_plus,
                  int miner_share, int k) {
  if (sel.empty()) return -1.0;
  vector<int> leaves = quorum_leaves(d, sel);
  int depth_first = leaves.empty() ? 0 : d.blocks[leaves[0]].work;
  double r = discount ? (double)(depth_first + depth_plus) / k : 1.0;
  vector<int> paid =
      punish && !leaves.empty() ? vote_closure(d, leaves[0]) : sel;
  int own = miner_share;
  for (int v : paid)
    if (d.blocks[v].miner == me) own++;
  return r * own;
}

int failures = 0;

void expect(bool ok, const char* what) {
  if (!ok) {
    std::printf("FAIL: %s\n", what);
    failures++;
  }
}

// Crafted forest, k=3, me=0: branch A = three foreign votes (depth
// 1-2-3), branch B = two own votes (depth 1-2), lone own vote C
// (depth 1).  Altruistic (longest first) must take A; heuristic and
// optimal (own-reward first) must take B+C.
void test_crafted() {
  Sim s = make_sim(2);
  s.dag.add(Block{});  // genesis summary, id 0
  int a1 = add_vote(s, 0, 1, 1, 0.10, 1.0);
  int a2 = add_vote(s, a1, 2, 1, 0.11, 2.0);
  int a3 = add_vote(s, a2, 3, 1, 0.12, 3.0);
  int b1 = add_vote(s, 0, 1, 0, 0.20, 4.0);
  int b2 = add_vote(s, b1, 2, 0, 0.21, 5.0);
  int c1 = add_vote(s, 0, 1, 0, 0.30, 6.0);
  vector<int> cands = {a1, a2, a3, b1, b2, c1};
  const int q = 3, k = 3, me = 0;

  vector<int> alt = altruistic_quorum(s, s.dag, cands, me, q);
  vector<int> heu = heuristic_quorum(s.dag, cands, me, q);
  bool fb = false;
  vector<int> opt = optimal_quorum(s.dag, cands, me, q, false, false, 0,
                                   0, k, &fb);
  expect(!fb, "crafted: optimal under option cap");
  auto has = [](const vector<int>& v, int x) {
    return std::find(v.begin(), v.end(), x) != v.end();
  };
  expect(alt.size() == 3 && has(alt, a3), "altruistic takes the deepest branch");
  expect(heu.size() == 3 && has(heu, b2) && has(heu, c1),
         "heuristic takes the own branches");
  expect(opt.size() == 3 && has(opt, b2) && has(opt, c1),
         "optimal takes the own branches");
  double ra = own_reward(s.dag, alt, me, false, false, 0, 0, k);
  double rh = own_reward(s.dag, heu, me, false, false, 0, 0, k);
  double ro = own_reward(s.dag, opt, me, false, false, 0, 0, k);
  expect(ra == 0.0 && rh == 3.0 && ro == 3.0, "crafted own rewards");
}

// Discount tiebreak: optimal may prefer a DEEPER quorum with fewer own
// votes when the discount factor pays for it; the heuristic (constant-
// reward assumption, tailstorm.ml:329-335) cannot see that.
void test_discount_sensitivity() {
  Sim s = make_sim(2);
  s.dag.add(Block{});
  // branch A: foreign d1 -> own d2 -> own d3 (depth 3, own 2)
  int a1 = add_vote(s, 0, 1, 1, 0.10, 1.0);
  int a2 = add_vote(s, a1, 2, 0, 0.11, 2.0);
  int a3 = add_vote(s, a2, 3, 0, 0.12, 3.0);
  // three lone own votes (depth 1, own 3)
  int b = add_vote(s, 0, 1, 0, 0.20, 4.0);
  int c = add_vote(s, 0, 1, 0, 0.30, 5.0);
  int e = add_vote(s, 0, 1, 0, 0.40, 6.0);
  vector<int> cands = {a1, a2, a3, b, c, e};
  const int q = 3, k = 3, me = 0;
  bool fb = false;
  // constant: lone own votes win (3 x 1 > 2 x 1)
  vector<int> opt_c = optimal_quorum(s.dag, cands, me, q, false, false,
                                     0, 0, k, &fb);
  // discount: deep branch wins (3/3 * 2 = 2 > 1/3 * 3 = 1)
  vector<int> opt_d = optimal_quorum(s.dag, cands, me, q, true, false,
                                     0, 0, k, &fb);
  double rc = own_reward(s.dag, opt_c, me, false, false, 0, 0, k);
  double rd = own_reward(s.dag, opt_d, me, true, false, 0, 0, k);
  expect(rc == 3.0, "optimal/constant picks lone own votes");
  auto has = [](const vector<int>& v, int x) {
    return std::find(v.begin(), v.end(), x) != v.end();
  };
  expect(has(opt_d, a3) && rd == 2.0,
         "optimal/discount pays for the deep branch");
}

// Randomized forests: optimal's own reward must dominate both other
// selectors under every scheme combination (the ordering property a
// silently suboptimal enumeration would break), and every selector
// must return a closed, correctly sized set.
void test_reward_ordering() {
  std::mt19937_64 rng(42);
  for (int trial = 0; trial < 300; trial++) {
    Sim s = make_sim(2);
    s.dag.add(Block{});
    int q = 2 + (int)(rng() % 3);  // 2..4
    int k = q;
    int n = q + (int)(rng() % 5);  // q .. q+4
    vector<int> ids;
    for (int i = 0; i < n; i++) {
      // parent: genesis or any earlier vote (keeps depths consistent)
      int parent = 0, depth = 1;
      if (!ids.empty() && rng() % 2) {
        parent = ids[rng() % ids.size()];
        depth = s.dag.blocks[parent].work + 1;
      }
      int miner = (int)(rng() % 2);
      double hash = (double)(rng() % 1000) / 1000.0;
      ids.push_back(add_vote(s, parent, depth, miner, hash, (double)i));
    }
    for (int scheme = 0; scheme < 4; scheme++) {
      bool discount = scheme == 1 || scheme == 3;
      bool punish = scheme == 2 || scheme == 3;
      vector<int> alt = altruistic_quorum(s, s.dag, ids, 0, q);
      vector<int> heu = heuristic_quorum(s.dag, ids, 0, q);
      bool fb = false;
      vector<int> opt = optimal_quorum(s.dag, ids, 0, q, discount,
                                       punish, 0, 0, k, &fb);
      if (fb) continue;
      double ro = own_reward(s.dag, opt, 0, discount, punish, 0, 0, k);
      double rh = own_reward(s.dag, heu, 0, discount, punish, 0, 0, k);
      double ra = own_reward(s.dag, alt, 0, discount, punish, 0, 0, k);
      // feasibility must agree: all three find a quorum or none does
      // (any q-subset that is closed exists independently of selector)
      if (!opt.empty()) {
        expect((int)opt.size() == q, "optimal size == q");
        expect(ro + 1e-9 >= rh, "optimal >= heuristic own reward");
        expect(ro + 1e-9 >= ra, "optimal >= altruistic own reward");
      }
      for (const vector<int>& sel : {alt, heu, opt}) {
        // closure-closed: every member's vote parents are members
        for (int v : sel)
          for (int p : s.dag.blocks[v].parents)
            if (s.dag.blocks[p].is_vote)
              expect(std::find(sel.begin(), sel.end(), p) != sel.end(),
                     "selection is closure-closed");
      }
    }
  }
}

}  // namespace

int main() {
  test_crafted();
  test_discount_sensitivity();
  test_reward_ordering();
  if (failures) {
    std::printf("%d failures\n", failures);
    return 1;
  }
  std::printf("selectors ok\n");
  return 0;
}
